"""`stencil` — run single-device solver code on every block of the grid.

This is the TPU carrier of the reference's core promise: a solver written for
one local array becomes a distributed one.  In the reference that works
because each MPI process executes the same Julia code on its local array; here
the same effect is `jax.shard_map` over the grid mesh — the decorated function
is traced once with *local block* arguments and compiled SPMD across the
slice, and `update_halo` calls inside it inline into the same XLA program
(fusing communication with compute).

Field arguments (arrays whose per-dimension sizes are divisible by the mesh
``dims``) are sharded one block per device; anything else is replicated.
Override with explicit ``in_specs``/``out_specs`` when the heuristic is wrong
(e.g. a parameter vector whose length happens to be divisible by ``dims[0]``).
"""

from __future__ import annotations

import weakref
from typing import Any

import numpy as np

from ..parallel import grid as _grid
from ..parallel.topology import AXIS_NAMES

# Live stencil objects, so finalize_global_grid can evict their compiled
# executables (each pins the old mesh and program memory).
_instances: "weakref.WeakSet[_Stencil]" = weakref.WeakSet()


def _clear_caches() -> None:
    for s in list(_instances):
        s._cache.clear()


def _infer_spec(leaf, gg):
    from jax.sharding import PartitionSpec as P

    ndim = np.ndim(leaf)
    if ndim == 0:
        return P()
    shape = np.shape(leaf)
    if all(shape[d] % gg.dims[d] == 0 and shape[d] > 0 for d in range(min(ndim, 3))):
        return P(*AXIS_NAMES[:ndim])
    return P()


def stencil(fn=None, *, in_specs=None, out_specs=None, donate_argnums=()):
    """Decorate a per-block step function; returns a jit-compiled SPMD callable.

    Example::

        @igg.stencil
        def step(T, Cp):          # T, Cp are the LOCAL (nx,ny,nz) blocks here
            ...
            T = igg.update_halo(T)
            return T

        T = step(T, Cp)           # called with global-block fields
    """
    if fn is None:
        return lambda f: stencil(
            f, in_specs=in_specs, out_specs=out_specs, donate_argnums=donate_argnums
        )
    return _Stencil(fn, in_specs, out_specs, donate_argnums)


class _Stencil:
    def __init__(self, fn, in_specs, out_specs, donate_argnums):
        self._fn = fn
        self._in_specs = in_specs
        self._out_specs = out_specs
        self._donate = tuple(donate_argnums) if donate_argnums else ()
        self._cache: dict[Any, Any] = {}
        self.__wrapped__ = fn
        self.__doc__ = fn.__doc__
        _instances.add(self)

    def __call__(self, *args):
        import jax

        _grid.check_initialized()
        gg = _grid.global_grid()
        leaves, treedef = jax.tree.flatten(args)
        sig = (
            gg.epoch,
            treedef,
            tuple((np.shape(l), getattr(l, "dtype", type(l))) for l in leaves),
        )
        compiled = self._cache.get(sig)
        if compiled is None:
            compiled = self._build(gg, args, treedef)
            self._cache[sig] = compiled
        return compiled(*args)

    def _build(self, gg, args, treedef):
        import jax

        if gg.nprocs == 1 and not gg.force_spmd:
            # Degenerate 1-device grid: shard_map adds nothing semantically
            # (every mesh axis has size 1) but routes execution through the
            # SPMD path, which measurably caps throughput on some runtimes.
            # Plain jit — unless the function really uses mesh axis names
            # (e.g. a custom psum), detected with a cheap abstract trace.
            try:
                jax.eval_shape(self._fn, *args)
            except Exception:
                pass  # needs the axis environment: fall through to shard_map
            else:
                return jax.jit(self._fn, donate_argnums=self._donate)

        if self._in_specs is not None:
            in_specs = self._in_specs
        else:
            in_specs = jax.tree.map(lambda l: _infer_spec(l, gg), args)

        if self._out_specs is not None:
            out_specs = self._out_specs
        else:
            out_specs = self._infer_out_specs(gg, in_specs, args)

        from ..utils.compat import shard_map

        mapped = shard_map(
            self._fn,
            mesh=gg.mesh,
            in_specs=tuple(in_specs),
            out_specs=out_specs,
            check_vma=False,
        )
        return jax.jit(mapped, donate_argnums=self._donate)

    def _infer_out_specs(self, gg, in_specs, args):
        """Output specs, symmetric with the input heuristic: per-block
        (device-varying) outputs are sharded one block per device; outputs
        the function made replicated (e.g. a `psum` over the mesh axes) KEEP
        their local shape instead of being concatenated into dims-many
        copies.

        Mechanics: a rank-probe (out_specs=P(), never executed) recovers the
        output tree with the axis environment in place; then ONE
        `check_vma=True` trace of the shard_map exposes each output's
        varying-manual-axes set on the inner jaxpr's outvars — an empty set
        is statically-proven replication.  If that introspection is
        unavailable (jax version drift), per-output shape-probes test
        whether `P()` is provable instead (shard_map raises a clear
        ValueError exactly in the varying case); functions whose bodies do
        not trace under vma checking at all fall back to rank-based sharding
        for every output — the pre-round-3 behavior.
        """
        import jax
        from jax.sharding import PartitionSpec as P

        from ..utils.compat import shard_map

        probe = shard_map(
            self._fn,
            mesh=gg.mesh,
            in_specs=tuple(in_specs),
            out_specs=P(),
            check_vma=False,
        )
        out_shape = jax.eval_shape(probe, *args)
        shape_leaves, treedef = jax.tree.flatten(out_shape)
        rank_specs = [_infer_spec_from_ndim(len(l.shape)) for l in shape_leaves]

        def vma_mapped(specs):
            return shard_map(
                self._fn,
                mesh=gg.mesh,
                in_specs=tuple(in_specs),
                out_specs=treedef.unflatten(specs),
                check_vma=True,
            )

        try:
            jaxpr = jax.make_jaxpr(vma_mapped(rank_specs))(*args)
        except Exception:
            return treedef.unflatten(rank_specs)  # not vma-traceable: status quo
        try:
            (sm_eqn,) = [e for e in jaxpr.eqns if e.primitive.name == "shard_map"]
            inner = sm_eqn.params["jaxpr"]
            producer = {id(ov): e for e in inner.eqns for ov in e.outvars}

            def effective_vma(v):
                # shard_map widens a replicated value to the rank-based
                # out_spec with a `pvary` cast; the pre-cast vma is the
                # function's own — unwrap it.
                for _ in range(8):
                    e = producer.get(id(v))
                    if e is None or e.primitive.name != "pvary":
                        break
                    v = e.invars[0]
                return getattr(v.aval, "vma", None)

            vmas = [effective_vma(v) for v in inner.outvars]
            if len(vmas) == len(shape_leaves) and all(
                isinstance(v, frozenset) for v in vmas
            ):
                return treedef.unflatten(
                    [P() if not v else r for v, r in zip(vmas, rank_specs)]
                )
        except Exception:
            pass
        # Introspection shape changed: N per-output probes (slower, same result).
        specs = list(rank_specs)
        for i, leaf in enumerate(shape_leaves):
            if len(leaf.shape) == 0:
                continue  # scalars are already P()
            try:
                jax.eval_shape(
                    vma_mapped(rank_specs[:i] + [P()] + rank_specs[i + 1:]), *args
                )
            except Exception:
                # Device-varying (shard_map's replication ValueError) — or any
                # drifted-jax failure mode: keep the per-block sharding, the
                # safe pre-round-3 behavior.
                continue
            specs[i] = P()
        return treedef.unflatten(specs)


def _infer_spec_from_ndim(ndim: int):
    from jax.sharding import PartitionSpec as P

    return P(*AXIS_NAMES[:ndim])
