"""`stencil` — run single-device solver code on every block of the grid.

This is the TPU carrier of the reference's core promise: a solver written for
one local array becomes a distributed one.  In the reference that works
because each MPI process executes the same Julia code on its local array; here
the same effect is `jax.shard_map` over the grid mesh — the decorated function
is traced once with *local block* arguments and compiled SPMD across the
slice, and `update_halo` calls inside it inline into the same XLA program
(fusing communication with compute).

Field arguments (arrays whose per-dimension sizes are divisible by the mesh
``dims``) are sharded one block per device; anything else is replicated.
Override with explicit ``in_specs``/``out_specs`` when the heuristic is wrong
(e.g. a parameter vector whose length happens to be divisible by ``dims[0]``).
"""

from __future__ import annotations

import weakref
from typing import Any

import numpy as np

from ..parallel import grid as _grid
from ..parallel.topology import AXIS_NAMES

# Live stencil objects, so finalize_global_grid can evict their compiled
# executables (each pins the old mesh and program memory).
_instances: "weakref.WeakSet[_Stencil]" = weakref.WeakSet()


def _clear_caches() -> None:
    for s in list(_instances):
        s._cache.clear()


def _infer_spec(leaf, gg):
    from jax.sharding import PartitionSpec as P

    ndim = np.ndim(leaf)
    if ndim == 0:
        return P()
    shape = np.shape(leaf)
    if all(shape[d] % gg.dims[d] == 0 and shape[d] > 0 for d in range(min(ndim, 3))):
        return P(*AXIS_NAMES[:ndim])
    return P()


def stencil(fn=None, *, in_specs=None, out_specs=None, donate_argnums=()):
    """Decorate a per-block step function; returns a jit-compiled SPMD callable.

    Example::

        @igg.stencil
        def step(T, Cp):          # T, Cp are the LOCAL (nx,ny,nz) blocks here
            ...
            T = igg.update_halo(T)
            return T

        T = step(T, Cp)           # called with global-block fields
    """
    if fn is None:
        return lambda f: stencil(
            f, in_specs=in_specs, out_specs=out_specs, donate_argnums=donate_argnums
        )
    return _Stencil(fn, in_specs, out_specs, donate_argnums)


class _Stencil:
    def __init__(self, fn, in_specs, out_specs, donate_argnums):
        self._fn = fn
        self._in_specs = in_specs
        self._out_specs = out_specs
        self._donate = tuple(donate_argnums) if donate_argnums else ()
        self._cache: dict[Any, Any] = {}
        self.__wrapped__ = fn
        self.__doc__ = fn.__doc__
        _instances.add(self)

    def __call__(self, *args):
        import jax

        _grid.check_initialized()
        gg = _grid.global_grid()
        leaves, treedef = jax.tree.flatten(args)
        sig = (
            gg.epoch,
            treedef,
            tuple((np.shape(l), getattr(l, "dtype", type(l))) for l in leaves),
        )
        compiled = self._cache.get(sig)
        if compiled is None:
            compiled = self._build(gg, args, treedef)
            self._cache[sig] = compiled
        return compiled(*args)

    def _build(self, gg, args, treedef):
        import jax

        if gg.nprocs == 1 and not gg.force_spmd:
            # Degenerate 1-device grid: shard_map adds nothing semantically
            # (every mesh axis has size 1) but routes execution through the
            # SPMD path, which measurably caps throughput on some runtimes.
            # Plain jit — unless the function really uses mesh axis names
            # (e.g. a custom psum), detected with a cheap abstract trace.
            try:
                jax.eval_shape(self._fn, *args)
            except Exception:
                pass  # needs the axis environment: fall through to shard_map
            else:
                return jax.jit(self._fn, donate_argnums=self._donate)

        if self._in_specs is not None:
            in_specs = self._in_specs
        else:
            in_specs = jax.tree.map(lambda l: _infer_spec(l, gg), args)

        if self._out_specs is not None:
            out_specs = self._out_specs
        else:
            # Infer output specs with a probe trace: out_specs=P() preserves
            # every output's rank (replication promise, never executed), and
            # eval_shape of the shard_map gives the output tree with the axis
            # environment in place (so collectives inside `fn` trace fine).
            from jax.sharding import PartitionSpec as P

            probe = jax.shard_map(
                self._fn,
                mesh=gg.mesh,
                in_specs=tuple(in_specs),
                out_specs=P(),
                check_vma=False,
            )
            out_shape = jax.eval_shape(probe, *args)
            out_specs = jax.tree.map(
                lambda l: _infer_spec_from_ndim(len(l.shape)), out_shape
            )

        mapped = jax.shard_map(
            self._fn,
            mesh=gg.mesh,
            in_specs=tuple(in_specs),
            out_specs=out_specs,
            check_vma=False,
        )
        return jax.jit(mapped, donate_argnums=self._donate)


def _infer_spec_from_ndim(ndim: int):
    from jax.sharding import PartitionSpec as P

    return P(*AXIS_NAMES[:ndim])
