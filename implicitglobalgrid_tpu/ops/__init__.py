"""Halo exchange, gather, stencil mapping and comm/compute overlap."""
