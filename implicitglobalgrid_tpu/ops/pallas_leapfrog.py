"""Pallas TPU kernel: temporally-blocked fused staggered leapfrog steps.

The staggered sibling of `ops/pallas_stencil.py` (same custom-kernel lever as
the reference's pack kernels, `/root/reference/src/update_halo.jl:599-649`):
advance ``k`` velocity–pressure leapfrog steps of the acoustic model
(`models/acoustic3d.py`) in ONE HBM round trip per field.  The XLA acoustic
path is at its streaming roofline (12 real passes/step, see
`docs/performance.md`); temporal blocking cuts that to ~``(8.6·R + 4)/k``
passes/step (R = halo-recompute redundancy), the only remaining lever.

**Why this works where the naive staggered tile faulted.**  A face field of
shape ``n+1`` sliced directly gives DMA extents of odd size in the
second-minor or minor dimension — probed on hardware to crash the TPU worker
(odd-extent second-minor DMA).  The fix is an *even-extent padded layout*:
each velocity field is carried in an array padded to ``n+8`` along its own
staggered axis (``pad_faces``), holding all ``n+1`` real faces plus 7 junk
planes.  Every window fetch then has 8-aligned offsets and
multiple-of-8-extents in the second-minor dimension (x-axis padding is
unconstrained — it is the major dimension), and every minor-dimension copy
moves the full minor extent.  No odd-extent slice exists anywhere in the
kernel:

* ``P``  (cells)   window ``(SX,   SY,   n2)``    at ``(sx, sy)``
* ``Vx`` (x-faces) window ``(SX+8, SY,   n2)``    at ``(sx, sy)`` — local
  face ``j`` is global face ``sx+j``; the +8 rows cover the window's top
  face ``SX`` (real data or the global frozen face) plus junk.
* ``Vy`` (y-faces) window ``(SX,   SY+8, n2)``
* ``Vz`` (z-faces) window ``(SX,   SY,   n2+128)`` — full minor extent
  (z is the minor dimension, where Mosaic requires lane-tile-aligned
  extents, so the z pad is 128, not 8).

Output DMAs write only each tile's *owned* ``(bx, by)`` block of cells and
faces ``[i·b, i·b + b)`` — an exact partition of faces ``0..n-1``; the top
global face ``n`` is frozen (rigid wall / exchange-refreshed rind) and never
updated, so it needs no odd-extent store either: ``Vz``'s top face rides
every tile's full-minor out-DMA, and the ``Vx``/``Vy`` top slabs (the real
frozen face plane + 7 junk planes) are carried input→output by two small
aligned fix-up DMAs (major-dim slab for ``Vx``; 8-aligned second-minor slab
for ``Vy``).  The outputs are separate buffers (NOT aliased to the inputs:
a later tile's halo fetch overlaps earlier tiles' owned blocks, so in-place
writes would feed k-step-advanced values into neighbors' windows).

**Semantics** (matches `models/acoustic3d.py` update for update region and
frozen set, bit-near-exactly — same constant folding, different FMA
contraction):

* V update at global-interior faces with global-interior transverse index
  (the XLA model's ``jnp.pad(dV, 1)`` form); all other faces frozen.
* P update at ALL cells — including global boundary cells, whose divergence
  reads the frozen boundary faces (true values, present in the window).
  Tiles clamped to a global edge therefore compute the physical boundary
  exactly; for interior tiles validity shrinks one ring per step and owned
  cells sit ``>= k`` from the window edge (same trapezoid argument as the
  diffusion kernel).

Structure (flat tile `fori_loop`, double-buffered input DMAs, k-step
VMEM ping-pong, out-DMA fencing) is inherited from `ops/pallas_stencil.py`
— see its docstring for the scheduling rationale.

Multi-device: between halo exchanges only ``k=1`` is valid on standard
``overlap=2`` grids; ``fused_k=k`` in `models.acoustic3d.make_multi_step`
pairs k kernel steps with one width-``k`` slab exchange of all four fields
on a deep-halo (``overlap >= 2k``) grid.
"""

from __future__ import annotations

import functools

from . import _fused_envelope as _envelope
from .halo import Z_CZ_BAND

#: Tile candidates for auto-selection, fastest first (shared heuristics with
#: the diffusion kernel; the 4-field working set is ~2.4x larger, so the
#: VMEM check prunes earlier — the intermediate rungs matter here most:
#: 512^3 rejects (32,64) and round 3 degraded straight to (16,32) at 959
#: GB/s; the (32,32) rung measures 1409 there (vs (16,64) 1296), hence its
#: rank (VERDICT r3 #6).
_TILE_CANDIDATES = ((32, 64), (32, 32), (16, 64), (16, 32), (8, 16))

#: See `ops.pallas_stencil._VMEM_BUDGET_BYTES` (v5e-tuned estimate bound).
#: Each kernel's budget encodes ITS probed Mosaic scoped-stack overshoot
#: over the `_tile_bytes` estimate: ~18% for this 4-field set (probed:
#: (32,128) k=6 estimated 92 MiB, Mosaic wanted 109 MiB) vs ~85% for the
#: diffusion kernel's 5-buffer ping-pong — hence 85 MiB here against
#: diffusion's 59.5.  The envelope rejects configs before they reach a
#: Mosaic stack OOM.
_VMEM_BUDGET_BYTES = 85 * 1024 * 1024


def _tile_bytes(n1, n2, k, bx, by, itemsize, zsets: int = 0):
    """VMEM bytes for one full ping-pong set (4 fields x (2 slots + scratch)).

    ``n1`` is unused (this kernel has no full-y mode — envelope signature).
    ``zsets``: how many four-field double-buffered 128-lane window sets to
    add (1 = the z-patch input windows, 2 = + the z-export staging slots)."""
    H = _envelope.aligned_halo(k)
    SX, SY = bx + 2 * k, by + 2 * H
    per_set = (
        SX * SY * n2          # P
        + (SX + 8) * SY * n2  # Vx
        + SX * (SY + 8) * n2  # Vy
        + SX * SY * (n2 + 128)  # Vz (minor pad is a full lane tile)
    )
    total = 3 * per_set
    # Three z-window arrays per set since round 5: the cell and z-face
    # fields share one merged array (lane bands — see `Z_CZ_BAND`).
    total += zsets * 2 * 128 * (
        SX * SY + (SX + 8) * SY + SX * (SY + 8)
    )
    return total * itemsize


_tile_error = _envelope.make_tile_error(
    _tile_bytes, _VMEM_BUDGET_BYTES, "12 haloed staggered tiles spanning z"
)
_tile_error_zpatch = _envelope.make_tile_error(
    lambda n1, n2, k, bx, by, itemsize: _tile_bytes(n1, n2, k, bx, by, itemsize, 1),
    _VMEM_BUDGET_BYTES,
    "12 haloed staggered tiles spanning z + 6 z-patch windows",
)
_tile_error_zexport = _envelope.make_tile_error(
    lambda n1, n2, k, bx, by, itemsize: _tile_bytes(n1, n2, k, bx, by, itemsize, 2),
    _VMEM_BUDGET_BYTES,
    "12 haloed staggered tiles spanning z + 6 z windows + 6 export stagings",
)


def default_tile(shape, k: int, itemsize: int = 4, zpatch: bool = False,
                 zexport: bool | None = None):
    """First tuned tile candidate valid for cell ``shape``, or None.

    ``zexport`` defaults to ``zpatch`` (the production z-slab cadence always
    exports); pass ``zexport=False`` for a patch-only call."""
    return _envelope.default_tile(
        shape, k, itemsize,
        tile_error=_envelope.pick_tile_error(
            _tile_error, _tile_error_zpatch, _tile_error_zexport,
            zpatch, zexport,
        ),
        candidates=_TILE_CANDIDATES,
    )


def fused_support_error(shape, k: int, itemsize: int = 4,
                        bx: int | None = None, by: int | None = None,
                        zpatch: bool = False,
                        zexport: bool | None = None) -> str | None:
    """Why the fused leapfrog kernel cannot run this cell shape, or None.

    Single source of truth for the kernel envelope — used eagerly by
    `fused_leapfrog_steps` (raise) and by `models.acoustic3d.make_multi_step`
    (warn once + fall back to the XLA cadence, the reference's
    runtime-path-selection precedent, `/root/reference/src/update_halo.jl:755-784`).
    Kernel-independent checks live in `ops/_fused_envelope.py`, shared with
    the diffusion kernel; only `_tile_error`'s 12-buffer VMEM accounting is
    specific.  ``zpatch`` accounts for the in-kernel z-exchange variant's
    extra patch windows.
    """
    return _envelope.support_error(
        shape, k, itemsize, bx, by,
        tile_error=_envelope.pick_tile_error(
            _tile_error, _tile_error_zpatch, _tile_error_zexport,
            zpatch, zexport,
        ),
        candidates=_TILE_CANDIDATES,
    )


#: Padded-axis extents of the `pad_faces` layout, relative to the CELL size:
#: a padded face array spans ``cell + PADS[axis]`` along its own staggered
#: axis (``n+1`` real faces + junk planes).  x/y need sublane alignment (8);
#: z is the minor axis, where Mosaic requires lane-tile alignment (128).
#: The single source of truth for every pad_faces shape check
#: (`fused_leapfrog_steps`, `ops.pallas_pt.fused_pt_iterations`,
#: `ops.halo.update_halo_padded_faces`).
PADS = (8, 8, 128)


def padded_face_shapes(cell_shape):
    """The three `pad_faces` array shapes for a given cell shape."""
    n0, n1, n2 = cell_shape
    return (
        (n0 + PADS[0], n1, n2),
        (n0, n1 + PADS[1], n2),
        (n0, n1, n2 + PADS[2]),
    )


def pad_faces(Vx, Vy, Vz):
    """Face fields ``(n+1 staggered)`` -> even-extent padded kernel layout.

    Pads each field's own staggered axis with zeros: ``n+1 -> n+8`` for the
    x/y (major/second-minor) axes, ``n+1 -> n+128`` for z (the minor axis,
    where Mosaic requires lane-tile-aligned extents).  The extra planes are
    junk by contract — never read by the kernel's compute, never written
    back into the real faces.  One HBM pass per field; amortized over a
    whole fused chunk by the model wrapper.
    """
    import jax.numpy as jnp

    return (
        jnp.pad(Vx, ((0, PADS[0] - 1), (0, 0), (0, 0))),
        jnp.pad(Vy, ((0, 0), (0, PADS[1] - 1), (0, 0))),
        jnp.pad(Vz, ((0, 0), (0, 0), (0, PADS[2] - 1))),
    )


def unpad_faces(Vxp, Vyp, Vzp):
    """Inverse of `pad_faces`: slice the ``n+1`` real faces back out."""
    return (
        Vxp[: 1 - PADS[0]],
        Vyp[:, : 1 - PADS[1]],
        Vzp[:, :, : 1 - PADS[2]],
    )


def z_patch_shapes(cell_shape):
    """The three packed z-patch array shapes (`ops.halo.z_slab_patches`):
    merged cell+z-face (bands at lanes 0 / `ops.halo.Z_CZ_BAND`), x-face,
    y-face."""
    n0, n1, n2 = cell_shape
    return (
        (n0, n1, 128),
        (n0 + PADS[0], n1, 128),
        (n0, n1 + PADS[1], 128),
    )


def fused_leapfrog_steps(P, Vxp, Vyp, Vzp, k: int,
                         cax: float, cay: float, caz: float,
                         b: float, idx: float, idy: float, idz: float,
                         *, bx: int | None = None, by: int | None = None,
                         z_patches=None, z_export: bool = False,
                         z_overlap: int | None = None,
                         tile_sel: str = "all", carry_in=None):
    """Advance ``k`` (even) leapfrog steps in one HBM pass per field.

    ``P`` is the cell-centered pressure ``(n0, n1, n2)``; ``Vxp/Vyp/Vzp`` are
    the `pad_faces` layouts of the three staggered velocity fields.
    Coefficients: ``cax = dt/(rho*dx)`` (likewise y, z); ``b = dt*K``;
    ``idx = 1/dx`` (likewise y, z) — the same folds as the XLA model so the
    two paths differ only by FMA contraction.

    ``z_patches``: packed z-exchange patches (`ops.halo.z_slab_patches`,
    width ``k``) applied to each tile in VMEM before stepping — the
    in-kernel z-slab application that avoids whole-array relayouts at the
    kernel boundary (see the exchanged-dimension anisotropy note in
    docs/performance.md).  Lanes ``[0, k)`` overwrite each field's z planes
    ``[0, k)``, lanes ``[k, 2k)`` its planes ``[n_z - k, n_z)``.

    ``z_export`` (requires ``z_patches`` + the grid z-overlap ``z_overlap``):
    additionally return the three packed z-slab exports (shapes
    `z_patch_shapes`; P and Vz share the merged first array's lane bands)
    for the NEXT group's patches — the extraction half of
    the z-anisotropy fix (see `ops.pallas_stencil.fused_diffusion_steps`).
    Lane layout per field f with logical z size ``n_f`` and overlap ``o_f``
    (``o_f = o+1`` for Vz, shape-aware): ``[0,k)`` = planes
    ``[n_f-o_f, n_f-o_f+k)``, ``[k,2k)`` = planes ``[o_f-k, o_f)``,
    ``[2k,4k)`` = current boundary planes.  The Vx row ``n0`` / Vy column
    ``n1`` (frozen top-face) slabs are NOT exported by the tiles (their
    owned-block partition excludes them) — the model cadence fixes them up
    from the output arrays (`ops.halo.fix_topface_z_exports`), and on
    x/y-active grids the exports' own x/y slab exchange refreshes them
    anyway.

    ``tile_sel``/``carry_in``: tile-subset launch for the pipelined group
    schedule, exactly as on `ops.pallas_stencil.fused_diffusion_steps` — a
    ``"mid*"`` launch aliases the matching ``"ring*"`` launch's outputs
    (``carry_in``, all 4 or 7 of them) so the combined result needs no
    copy.  The frozen top-face fix-up DMAs run in the ring pass only (the
    alias carries their planes through the mid pass).
    """
    n0, n1, n2 = P.shape
    if (Vxp.shape, Vyp.shape, Vzp.shape) != padded_face_shapes(P.shape):
        raise ValueError(
            f"V fields must be in pad_faces layout for P{P.shape}: got "
            f"{Vxp.shape}, {Vyp.shape}, {Vzp.shape}"
        )
    if not (P.dtype == Vxp.dtype == Vyp.dtype == Vzp.dtype):
        raise ValueError("P and V fields must share a dtype")
    zp = z_patches is not None
    if zp:
        if tuple(a.shape for a in z_patches) != z_patch_shapes(P.shape):
            raise ValueError(
                f"z_patches must have shapes {z_patch_shapes(P.shape)}: got "
                f"{tuple(a.shape for a in z_patches)}"
            )
        if any(a.dtype != P.dtype for a in z_patches):
            raise ValueError("z_patches must share the fields' dtype")
    if z_export:
        if not zp:
            raise ValueError("z_export requires z_patches (the z-slab cadence)")
        if z_overlap is None or not (2 * k <= z_overlap <= n2 // 2):
            raise ValueError(
                f"z_export needs the grid z-overlap with 2k <= o <= n2/2: "
                f"got o={z_overlap}, k={k}, n2={n2}"
            )
        if 4 * k > 128 - Z_CZ_BAND:
            # Each merged-band half holds 4k lanes (see `ops.halo.Z_CZ_BAND`).
            raise ValueError(
                f"z_export packs 4k lanes per merged-band half; k={k} > "
                f"{(128 - Z_CZ_BAND) // 4} unsupported"
            )
    err = fused_support_error(
        (n0, n1, n2), k, P.dtype.itemsize, bx, by, zpatch=zp, zexport=z_export
    )
    if err is not None:
        raise ValueError(err)
    if bx is None:
        bx, by = default_tile(
            (n0, n1, n2), k, P.dtype.itemsize, zpatch=zp, zexport=z_export
        )
    carry_in = _envelope.check_tile_subset(
        tile_sel, carry_in, (n0, n1), (bx, by), nouts=7 if z_export else 4
    )
    from ..utils.compat import pallas_interpret_active

    fn = _build(n0, n1, n2, str(P.dtype), int(k),
                float(cax), float(cay), float(caz),
                float(b), float(idx), float(idy), float(idz),
                int(bx), int(by), zp,
                bool(z_export), int(z_overlap) if z_export else 0,
                str(tile_sel), carry_in is not None,
                pallas_interpret_active())
    args = (P, Vxp, Vyp, Vzp) + (tuple(z_patches) if zp else ())
    if carry_in is not None:
        args += tuple(carry_in)
    return fn(*args)


@functools.lru_cache(maxsize=64)
def _build(n0, n1, n2, dtype, k, cax, cay, caz, b, idx, idy, idz, bx, by,
           zp: bool = False, zx: bool = False, o: int = 0,
           tile_sel: str = "all", carry: bool = False, interp: bool = False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from ..utils.compat import pallas_compiler_params
    from .overlap import tile_subset_count, tile_subset_map

    H = _envelope.aligned_halo(k)
    SX, SY = bx + 2 * k, by + 2 * H
    SZ = n2
    ncx, ncy = n0 // bx, n1 // by
    ntiles = ncx * ncy
    # Tile-subset launch (see ops/pallas_stencil.py): the loop runs over the
    # subset's index space; per-tile work is unchanged.  The frozen top-face
    # fix-up DMAs belong to the ring pass (the mid pass's aliased outputs
    # already carry those planes).
    nrun = tile_subset_count(tile_sel, ncx, ncy)
    t_of = tile_subset_map(tile_sel, ncx, ncy)
    fixup = not tile_sel.startswith("mid")
    dt_ = jnp.dtype(dtype)

    def sx_of(ix):
        return jnp.clip(ix * bx - k, 0, n0 - SX)

    def sy_of(iy):
        # Always a multiple of 8 (by, H, n1-SY all are); assert it for Mosaic.
        return pl.multiple_of(jnp.clip(iy * by - H, 0, n1 - SY), 8)

    # Frozen-region (ring) copies: the complement of each field's update
    # region, copied once into the scratch buffer (the in-slot buffer holds
    # it from the DMA; frozen values never change across the k steps).
    def ring_vx(dst, s):
        # update region: [1:SX, 1:SY-1, 1:SZ-1]
        dst[0:1] = s[0:1]
        dst[SX : SX + 8] = s[SX : SX + 8]
        dst[1:SX, 0:1] = s[1:SX, 0:1]
        dst[1:SX, SY - 1 : SY] = s[1:SX, SY - 1 : SY]
        dst[1:SX, 1 : SY - 1, 0:1] = s[1:SX, 1 : SY - 1, 0:1]
        dst[1:SX, 1 : SY - 1, SZ - 1 : SZ] = s[1:SX, 1 : SY - 1, SZ - 1 : SZ]

    def ring_vy(dst, s):
        # update region: [1:SX-1, 1:SY, 1:SZ-1]
        dst[:, 0:1] = s[:, 0:1]
        dst[:, SY : SY + 8] = s[:, SY : SY + 8]
        dst[0:1, 1:SY] = s[0:1, 1:SY]
        dst[SX - 1 : SX, 1:SY] = s[SX - 1 : SX, 1:SY]
        dst[1 : SX - 1, 1:SY, 0:1] = s[1 : SX - 1, 1:SY, 0:1]
        dst[1 : SX - 1, 1:SY, SZ - 1 : SZ] = s[1 : SX - 1, 1:SY, SZ - 1 : SZ]

    def ring_vz(dst, s):
        # update region: [1:SX-1, 1:SY-1, 1:SZ]
        dst[:, :, 0:1] = s[:, :, 0:1]
        dst[:, :, SZ : SZ + 128] = s[:, :, SZ : SZ + 128]
        dst[0:1, :, 1:SZ] = s[0:1, :, 1:SZ]
        dst[SX - 1 : SX, :, 1:SZ] = s[SX - 1 : SX, :, 1:SZ]
        dst[1 : SX - 1, 0:1, 1:SZ] = s[1 : SX - 1, 0:1, 1:SZ]
        dst[1 : SX - 1, SY - 1 : SY, 1:SZ] = s[1 : SX - 1, SY - 1 : SY, 1:SZ]

    def step_into(dp, dvx, dvy, dvz, sp, svx, svy, svz, ring: bool):
        """One leapfrog step: (sp, sv*) buffer values -> (dp, dv*) buffers.

        V first (global-interior faces, from old P), then P at ALL window
        cells from the NEW V — the divergence reads the dst V buffers just
        written, plus their frozen rows (input values, present via DMA for
        the in-slot buffers and via the one-time ring copy for scratch).
        """
        if ring:
            ring_vx(dvx, svx)
            ring_vy(dvy, svy)
            ring_vz(dvz, svz)
        P = sp[:]
        dvx[1:SX, 1 : SY - 1, 1 : SZ - 1] = svx[1:SX, 1 : SY - 1, 1 : SZ - 1] - cax * (
            P[1:SX, 1 : SY - 1, 1 : SZ - 1] - P[0 : SX - 1, 1 : SY - 1, 1 : SZ - 1]
        )
        dvy[1 : SX - 1, 1:SY, 1 : SZ - 1] = svy[1 : SX - 1, 1:SY, 1 : SZ - 1] - cay * (
            P[1 : SX - 1, 1:SY, 1 : SZ - 1] - P[1 : SX - 1, 0 : SY - 1, 1 : SZ - 1]
        )
        dvz[1 : SX - 1, 1 : SY - 1, 1:SZ] = svz[1 : SX - 1, 1 : SY - 1, 1:SZ] - caz * (
            P[1 : SX - 1, 1 : SY - 1, 1:SZ] - P[1 : SX - 1, 1 : SY - 1, 0 : SZ - 1]
        )
        nvx = dvx[0 : SX + 1]
        nvy = dvy[:, 0 : SY + 1]
        nvz = dvz[:, :, 0 : SZ + 1]
        div = (
            (nvx[1:] - nvx[:-1]) * idx
            + (nvy[:, 1:] - nvy[:, :-1]) * idy
            + (nvz[:, :, 1:] - nvz[:, :, :-1]) * idz
        )
        dp[:] = P - b * div

    def kernel(*refs):
        ZXcz = ZXx = ZXy = None
        Pin, Vxin, Vyin, Vzin = refs[:4]
        ZPcz, ZPx, ZPy = refs[4:7] if zp else (None, None, None)
        nin = 7 if zp else 4
        # A carry launch receives the ring pass's outputs as aliased inputs
        # between the real inputs and the outputs; never read here.
        outs = refs[nin + ((7 if zx else 4) if carry else 0):]
        if zx:
            Pout, Vxout, Vyout, Vzout, ZXcz, ZXx, ZXy = outs
        else:
            Pout, Vxout, Vyout, Vzout = outs

        def body(p, vx, vy, vz, sp, svx, svy, svz,
                 p_is, vx_is, vy_is, vz_is, p_os, vx_os, vy_os, vz_os, fix_s,
                 zpcz=None, zpx=None, zpy=None, zp_is=None,
                 zxcz=None, zxx=None, zxy=None, zx_os=None):
            def ixy(t):
                return t // ncy, t % ncy

            def in_dmas(t, slot):
                ix, iy = ixy(t)
                sx, sy = sx_of(ix), sy_of(iy)
                return (
                    pltpu.make_async_copy(
                        Pin.at[pl.ds(sx, SX), pl.ds(sy, SY)], p.at[slot], p_is.at[slot]
                    ),
                    pltpu.make_async_copy(
                        Vxin.at[pl.ds(sx, SX + 8), pl.ds(sy, SY)],
                        vx.at[slot], vx_is.at[slot],
                    ),
                    pltpu.make_async_copy(
                        Vyin.at[pl.ds(sx, SX), pl.ds(sy, SY + 8)],
                        vy.at[slot], vy_is.at[slot],
                    ),
                    pltpu.make_async_copy(
                        Vzin.at[pl.ds(sx, SX), pl.ds(sy, SY)],
                        vz.at[slot], vz_is.at[slot],
                    ),
                ) + ((
                    # z-patch windows (full-minor 128-lane fetch, the only
                    # lane-aligned way to move a thin z slab per tile);
                    # P and Vz ride ONE merged window (lane bands).
                    pltpu.make_async_copy(
                        ZPcz.at[pl.ds(sx, SX), pl.ds(sy, SY)],
                        zpcz.at[slot], zp_is.at[0, slot],
                    ),
                    pltpu.make_async_copy(
                        ZPx.at[pl.ds(sx, SX + 8), pl.ds(sy, SY)],
                        zpx.at[slot], zp_is.at[1, slot],
                    ),
                    pltpu.make_async_copy(
                        ZPy.at[pl.ds(sx, SX), pl.ds(sy, SY + 8)],
                        zpy.at[slot], zp_is.at[2, slot],
                    ),
                ) if zp else ())

            def out_dmas(t, slot):
                ix, iy = ixy(t)
                ox = ix * bx - sx_of(ix)
                oy = pl.multiple_of(iy * by - sy_of(iy), 8)
                gx, gy = ix * bx, iy * by
                return (
                    pltpu.make_async_copy(
                        p.at[slot, pl.ds(ox, bx), pl.ds(oy, by)],
                        Pout.at[pl.ds(gx, bx), pl.ds(gy, by)], p_os.at[slot],
                    ),
                    pltpu.make_async_copy(
                        vx.at[slot, pl.ds(ox, bx), pl.ds(oy, by)],
                        Vxout.at[pl.ds(gx, bx), pl.ds(gy, by)], vx_os.at[slot],
                    ),
                    pltpu.make_async_copy(
                        vy.at[slot, pl.ds(ox, bx), pl.ds(oy, by)],
                        Vyout.at[pl.ds(gx, bx), pl.ds(gy, by)], vy_os.at[slot],
                    ),
                    pltpu.make_async_copy(
                        vz.at[slot, pl.ds(ox, bx), pl.ds(oy, by)],
                        Vzout.at[pl.ds(gx, bx), pl.ds(gy, by)], vz_os.at[slot],
                    ),
                )

            def zex_dmas(t, slot):
                ix, iy = ixy(t)
                ox = ix * bx - sx_of(ix)
                oy = pl.multiple_of(iy * by - sy_of(iy), 8)
                gx, gy = ix * bx, iy * by
                return (
                    pltpu.make_async_copy(
                        zxcz.at[slot, pl.ds(ox, bx), pl.ds(oy, by)],
                        ZXcz.at[pl.ds(gx, bx), pl.ds(gy, by)], zx_os.at[0, slot],
                    ),
                    pltpu.make_async_copy(
                        zxx.at[slot, pl.ds(ox, bx), pl.ds(oy, by)],
                        ZXx.at[pl.ds(gx, bx), pl.ds(gy, by)], zx_os.at[1, slot],
                    ),
                    pltpu.make_async_copy(
                        zxy.at[slot, pl.ds(ox, bx), pl.ds(oy, by)],
                        ZXy.at[pl.ds(gx, bx), pl.ds(gy, by)], zx_os.at[2, slot],
                    ),
                )

            def start_in(t, slot):
                for d in in_dmas(t, slot):
                    d.start()

            def wait_in(t, slot):
                for d in in_dmas(t, slot):
                    d.wait()

            def start_out(t, slot):
                for d in out_dmas(t, slot):
                    d.start()
                if zx:
                    for d in zex_dmas(t, slot):
                        d.start()

            def wait_out(t, slot):
                for d in out_dmas(t, slot):
                    d.wait()
                if zx:
                    for d in zex_dmas(t, slot):
                        d.wait()

            # Top-slab fix-up: the frozen Vx row-n0 / Vy col-n1 face planes
            # (plus their 7 junk planes) are outside every tile's owned
            # block — carry them input→output once.  Vz's top face is
            # covered by the tiles' full-minor out-DMAs.
            fix_vx = pltpu.make_async_copy(
                Vxin.at[pl.ds(n0, 8)], Vxout.at[pl.ds(n0, 8)], fix_s.at[0]
            )
            fix_vy = pltpu.make_async_copy(
                Vyin.at[pl.ds(0, n0), pl.ds(n1, 8)],
                Vyout.at[pl.ds(0, n0), pl.ds(n1, 8)],
                fix_s.at[1],
            )
            if fixup:
                fix_vx.start()
                fix_vy.start()
            start_in(t_of(0), 0)

            def tile(i, _):
                t = t_of(i)
                slot = jax.lax.rem(i, 2)
                nslot = 1 - slot

                @pl.when(i + 1 < nrun)
                def _():
                    @pl.when(i >= 1)
                    def _():
                        # nslot still holds the previous tile's output;
                        # fence its out-DMAs before prefetching into it.
                        wait_out(t_of(i - 1), nslot)

                    start_in(t_of(i + 1), nslot)

                wait_in(t, slot)
                if zp:
                    # Apply the z-exchange patches to this tile in VMEM
                    # (minor-dim plane surgery is free here, unlike the
                    # whole-array relayout a z-DUS costs at the kernel
                    # boundary): lanes [0,k) -> planes [0,k), lanes [k,2k)
                    # -> the top k planes of each field's REAL z extent.
                    p[slot, :, :, 0:k] = zpcz[slot, :, :, 0:k]
                    p[slot, :, :, SZ - k : SZ] = zpcz[slot, :, :, k : 2 * k]
                    vx[slot, :, :, 0:k] = zpx[slot, :, :, 0:k]
                    vx[slot, :, :, SZ - k : SZ] = zpx[slot, :, :, k : 2 * k]
                    vy[slot, :, :, 0:k] = zpy[slot, :, :, 0:k]
                    vy[slot, :, :, SZ - k : SZ] = zpy[slot, :, :, k : 2 * k]
                    vz[slot, :, :, 0:k] = zpcz[slot, :, :, Z_CZ_BAND : Z_CZ_BAND + k]
                    vz[slot, :, :, SZ + 1 - k : SZ + 1] = zpcz[
                        slot, :, :, Z_CZ_BAND + k : Z_CZ_BAND + 2 * k
                    ]
                # k-step ping-pong between the in-slot set and the scratch
                # set; k even, so the final state lands back in the slot.
                for j in range(k):
                    if j % 2 == 0:
                        step_into(
                            sp, svx, svy, svz,
                            p.at[slot], vx.at[slot], vy.at[slot], vz.at[slot],
                            ring=(j == 0),
                        )
                    else:
                        step_into(
                            p.at[slot], vx.at[slot], vy.at[slot], vz.at[slot],
                            sp, svx, svy, svz,
                            ring=False,
                        )
                if zx:
                    # z-slab export for the NEXT group's patches (VMEM
                    # extraction — see the diffusion kernel).  Vz uses its
                    # logical n_f = SZ+1, o_f = o+1 (staggered z face).
                    zxcz[slot, :, :, 0:k] = p[slot, :, :, SZ - o : SZ - o + k]
                    zxcz[slot, :, :, k : 2 * k] = p[slot, :, :, o - k : o]
                    zxcz[slot, :, :, 2 * k : 3 * k] = p[slot, :, :, 0:k]
                    zxcz[slot, :, :, 3 * k : 4 * k] = p[slot, :, :, SZ - k : SZ]
                    zxx[slot, :, :, 0:k] = vx[slot, :, :, SZ - o : SZ - o + k]
                    zxx[slot, :, :, k : 2 * k] = vx[slot, :, :, o - k : o]
                    zxx[slot, :, :, 2 * k : 3 * k] = vx[slot, :, :, 0:k]
                    zxx[slot, :, :, 3 * k : 4 * k] = vx[slot, :, :, SZ - k : SZ]
                    zxy[slot, :, :, 0:k] = vy[slot, :, :, SZ - o : SZ - o + k]
                    zxy[slot, :, :, k : 2 * k] = vy[slot, :, :, o - k : o]
                    zxy[slot, :, :, 2 * k : 3 * k] = vy[slot, :, :, 0:k]
                    zxy[slot, :, :, 3 * k : 4 * k] = vy[slot, :, :, SZ - k : SZ]
                    zxcz[slot, :, :, Z_CZ_BAND : Z_CZ_BAND + k] = vz[slot, :, :, SZ - o : SZ - o + k]
                    zxcz[slot, :, :, Z_CZ_BAND + k : Z_CZ_BAND + 2 * k] = vz[
                        slot, :, :, o + 1 - k : o + 1
                    ]
                    zxcz[slot, :, :, Z_CZ_BAND + 2 * k : Z_CZ_BAND + 3 * k] = vz[
                        slot, :, :, 0:k
                    ]
                    zxcz[slot, :, :, Z_CZ_BAND + 3 * k : Z_CZ_BAND + 4 * k] = vz[
                        slot, :, :, SZ + 1 - k : SZ + 1
                    ]
                start_out(t, slot)
                return 0

            jax.lax.fori_loop(0, nrun, tile, 0)
            # Drain the two in-flight out-DMA sets (every launch runs >= 2
            # tiles by validation; distinct slots).
            wait_out(t_of(nrun - 2), (nrun - 2) % 2)
            wait_out(t_of(nrun - 1), (nrun - 1) % 2)
            if fixup:
                fix_vx.wait()
                fix_vy.wait()

        scopes = dict(
            p=pltpu.VMEM((2, SX, SY, SZ), dt_),
            vx=pltpu.VMEM((2, SX + 8, SY, SZ), dt_),
            vy=pltpu.VMEM((2, SX, SY + 8, SZ), dt_),
            vz=pltpu.VMEM((2, SX, SY, SZ + 128), dt_),
            sp=pltpu.VMEM((SX, SY, SZ), dt_),
            svx=pltpu.VMEM((SX + 8, SY, SZ), dt_),
            svy=pltpu.VMEM((SX, SY + 8, SZ), dt_),
            svz=pltpu.VMEM((SX, SY, SZ + 128), dt_),
            p_is=pltpu.SemaphoreType.DMA((2,)),
            vx_is=pltpu.SemaphoreType.DMA((2,)),
            vy_is=pltpu.SemaphoreType.DMA((2,)),
            vz_is=pltpu.SemaphoreType.DMA((2,)),
            p_os=pltpu.SemaphoreType.DMA((2,)),
            vx_os=pltpu.SemaphoreType.DMA((2,)),
            vy_os=pltpu.SemaphoreType.DMA((2,)),
            vz_os=pltpu.SemaphoreType.DMA((2,)),
            fix_s=pltpu.SemaphoreType.DMA((2,)),
        )
        if zp:
            scopes.update(
                zpcz=pltpu.VMEM((2, SX, SY, 128), dt_),
                zpx=pltpu.VMEM((2, SX + 8, SY, 128), dt_),
                zpy=pltpu.VMEM((2, SX, SY + 8, 128), dt_),
                zp_is=pltpu.SemaphoreType.DMA((3, 2)),
            )
        if zx:
            scopes.update(
                zxcz=pltpu.VMEM((2, SX, SY, 128), dt_),
                zxx=pltpu.VMEM((2, SX + 8, SY, 128), dt_),
                zxy=pltpu.VMEM((2, SX, SY + 8, 128), dt_),
                zx_os=pltpu.SemaphoreType.DMA((3, 2)),
            )
        pl.run_scoped(body, **scopes)

    vmem_bytes = _tile_bytes(n1, n2, k, bx, by, dt_.itemsize, (2 if zx else 1) if zp else 0)
    out_shape = [
        jax.ShapeDtypeStruct((n0, n1, n2), dt_),
        jax.ShapeDtypeStruct((n0 + 8, n1, n2), dt_),
        jax.ShapeDtypeStruct((n0, n1 + 8, n2), dt_),
        jax.ShapeDtypeStruct((n0, n1, n2 + 128), dt_),
    ]
    if zx:
        out_shape += [
            jax.ShapeDtypeStruct(s, dt_) for s in z_patch_shapes((n0, n1, n2))
        ]
    nbase = 7 if zp else 4
    nouts = len(out_shape)
    call = pl.pallas_call(
        kernel,
        out_shape=tuple(out_shape),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)]
        * (nbase + (nouts if carry else 0)),
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * nouts,
        input_output_aliases=(
            {nbase + j: j for j in range(nouts)} if carry else {}
        ),
        interpret=interp,
        compiler_params=pallas_compiler_params(
            vmem_limit_bytes=_envelope.vmem_limit(vmem_bytes)
        ),
    )
    return jax.jit(call)
