"""Halo exchange — the hot path, re-designed TPU-first.

The reference implements `update_halo!` as ~670 LoC of explicit buffer
management, pack/unpack kernels, pinned host staging and `MPI_Isend/Irecv`
(`/root/reference/src/update_halo.jl`).  On TPU all of that collapses into a
single compiled XLA program: per dimension, the boundary planes are sliced,
moved HBM→HBM over ICI by `lax.ppermute` (XLA `collective-permute`), and
written into the opposite halo planes.  XLA owns scheduling, so the
reference's streams/tasks/waits have no equivalent — dependencies alone
enforce the required ordering.

Semantics ported exactly (with 0-based indices):

* One plane per side per dimension is exchanged: send plane ``ol-1`` goes to
  the lower neighbor's plane ``n-1``; send plane ``n-ol`` goes to the upper
  neighbor's plane ``0`` (reference ``sendranges``/``recvranges``,
  `/root/reference/src/update_halo.jl:544-563`).  ``update_halo(...,
  width=w)`` generalizes the plane to a ``w``-plane slab on deep-halo grids
  (``overlap >= 2w``) — the TPU-first extension that lets ``w`` fused
  stencil steps ride on one collective (temporal blocking; see
  `ops/pallas_stencil.py` and `models/diffusion3d.py:make_multi_step`).
* Dimensions are processed sequentially — the dim-``k`` exchange must see the
  dim-``k-1``-updated halos for corner correctness
  (`/root/reference/src/update_halo.jl:40`).  Here the sequencing is carried
  by data dependencies inside the one XLA program.
* Per-field overlap is shape-aware: ``ol(d, A) = overlaps[d] + (size(A,d) -
  nxyz[d])`` (`/root/reference/src/shared.jl:94`), which makes staggered
  fields (e.g. ``nx+1``) exchange the right planes.  A dimension with
  ``ol < 2`` has no halo and is skipped
  (`/root/reference/src/update_halo.jl:369`).
* Non-periodic edge blocks keep their boundary planes untouched (the
  reference's ``PROC_NULL`` neighbors): `ppermute` delivers zeros where a
  block has no source, so the received plane is masked against the old one
  with the block's mesh coordinate.
* Periodic with a single block in a dimension is a pure local copy — the
  reference's self-neighbor fast path
  (`/root/reference/src/update_halo.jl:57-63`).

`update_halo` works in two calling contexts:

1. **Global arrays** (outside any `shard_map`): the fields are global-block
   `jax.Array`s; a cached ``jit(shard_map(...))`` wrapper with donated inputs
   performs the exchange "in place".
2. **Inside `shard_map`/`stencil`** (fields are tracers of local blocks): the
   exchange is inlined into the caller's program so it fuses with the
   surrounding stencil computation — the analogue of the reference's advice to
   group halo updates for pipelining
   (`/root/reference/src/update_halo.jl:13-14`).

Batched/ensemble contract (ISSUE 8, `models._batched`): every traced-context
path in this module — `exchange_dims_multi`, `update_halo_padded_faces`,
`begin_slab_exchange`/`finish_slab_exchange`, the z-patch family — batches
under `jax.vmap` over a leading ensemble axis with the SAME collective count
at any B: the `lax.ppermute` batching rule carries the batch dimension
inside the one hop (payload ×B, never B hops), and the coalesced packer's
flatten/concat act on the per-member view so the width-group packing simply
grows a batch axis.  This is pinned as a tier-1 lint
(`analysis.budget.batched_budget_findings` — per-dimension ppermute counts
at B=1 vs B=4 must be equal) and in the compiled-HLO cost baseline
(`exchange/porous[coalesce=True,batch=4]`).  Code here must stay
vmap-transparent: any new transport that branches on concrete batch state
or issues per-member collectives breaks the B-for-the-price-of-1 invariant
and the lint will fail it.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..parallel import grid as _grid
from ..parallel.topology import AXIS_NAMES, NDIMS

_jit_cache: dict = {}

#: Integrity-enabled exchange programs — (fn, TransportCollector) per key.
#: Separate from `_jit_cache` on purpose: the plain path's cache keys (and
#: the ``IGG_INTEGRITY=0`` zero-overhead pin) stay byte-for-byte unchanged.
_integrity_jit_cache: dict = {}

#: Armed ``bit_flip:…:transport`` target rank, consumed by the next
#: integrity-enabled global exchange (`utils.resilience` arms it with the
#: same arm-on-step / fire-on-next-collective idiom as ``net_delay``).
_transport_flip: int | None = None


def arm_transport_flip(proc: int) -> None:
    """Arm a one-shot in-flight payload-word flip on rank ``proc``'s next
    checksummed transport (the ``bit_flip`` chaos kind's transport
    placement).  No-op unless ``IGG_INTEGRITY=1`` routes the next global
    exchange through the checksummed build — the flip is baked into that
    program's wire buffers, after the checksum fold."""
    global _transport_flip
    _transport_flip = int(proc)


def _take_transport_flip() -> int | None:
    global _transport_flip
    proc, _transport_flip = _transport_flip, None
    return proc

# Guard/fault hook point: called on the OUTPUT tuple of every global-array
# `update_halo` (the host-side boundary where concrete fields exist — traced
# contexts inline into the caller's program and cannot run host hooks).  Two
# users: the fault-injection harness corrupts exchanged fields here
# (`utils.resilience.install_halo_fault_hook`), and debugging sessions can
# install a `check_fields` probe to localize which exchange first saw a NaN.
_post_exchange_hook = None


def set_post_exchange_hook(fn):
    """Install ``fn(fields_tuple) -> fields_tuple`` (or None to remove).
    Returns the previously installed hook."""
    global _post_exchange_hook
    prev = _post_exchange_hook
    _post_exchange_hook = fn
    return prev


def _clear_caches() -> None:
    _jit_cache.clear()
    _integrity_jit_cache.clear()


def _is_tracer(x) -> bool:
    import jax

    return isinstance(x, jax.core.Tracer)


def local_shape(A, gg=None) -> tuple[int, ...]:
    """Per-block (local) shape of a field.

    Tracers inside `shard_map` already have local shapes; concrete global-block
    arrays are ``dims``-times larger per sharded dimension.
    """
    if gg is None:
        gg = _grid.global_grid()
    if _is_tracer(A):
        return tuple(A.shape)
    shp = []
    for d in range(A.ndim):
        s, m = divmod(A.shape[d], gg.dims[d])
        if m != 0:
            raise ValueError(
                f"Field with global shape {tuple(A.shape)} is not divisible into "
                f"{gg.dims} blocks along dimension {d}; global-block fields must "
                f"have shape dims*local_shape (create them with the igg field "
                f"constructors)."
            )
        shp.append(s)
    return tuple(shp)


def ol(dim: int, A=None, shape: Sequence[int] | None = None, gg=None) -> int:
    """Shape-aware overlap of a field in ``dim`` (reference: src/shared.jl:93-94)."""
    if gg is None:
        gg = _grid.global_grid()
    if shape is None:
        shape = local_shape(A, gg)
    size_d = shape[dim] if dim < len(shape) else 1
    return gg.overlaps[dim] + (size_d - gg.nxyz[dim])


def halosize(dim: int, A, gg=None) -> tuple[int, ...]:
    """Shape of one halo plane of ``A`` in ``dim`` (reference: src/update_halo.jl:84)."""
    shp = local_shape(A, gg)
    if len(shp) > 1:
        return tuple(s for i, s in enumerate(shp) if i != dim)
    return (1,)


def _validate_fields(fields, gg) -> None:
    """Input validation ported from `/root/reference/src/update_halo.jl:804-834`.

    The reference's third check (identical concrete types) exists only because
    its communication buffers are reinterpreted across element types; there
    are no buffers here, so mixed-dtype calls are valid and the check is
    intentionally not ported.
    """
    shapes = [local_shape(A, gg) for A in fields]
    no_halo = [
        i
        for i, (A, shp) in enumerate(zip(fields, shapes))
        if all(ol(d, shape=shp, gg=gg) < 2 for d in range(len(shp)))
    ]
    if len(no_halo) > 1:
        pos = ", ".join(str(i + 1) for i in no_halo[:-1]) + f" and {no_halo[-1] + 1}"
        raise ValueError(f"The fields at positions {pos} have no halo; remove them from the call.")
    elif no_halo:
        raise ValueError(
            f"The field at position {no_halo[0] + 1} has no halo; remove it from the call."
        )
    dup = [
        (i, j)
        for i in range(len(fields))
        for j in range(i + 1, len(fields))
        if fields[i] is fields[j]
    ]
    if dup:
        i, j = dup[0]
        raise ValueError(
            f"The field at position {j + 1} is a duplicate of the one at the "
            f"position {i + 1}; remove the duplicate from the call."
        )


def dim_has_halo_activity(gg, d: int) -> bool:
    """Whether dimension ``d`` exchanges anything at all on this grid.

    Periodic dimensions always have partners (possibly self via the
    Cart_shift wrap); non-periodic ones only when the distance-``disp``
    shift stays on the grid for some block — ``abs(disp) >= dims[d]`` makes
    every partner PROC_NULL (and ``disp == 0`` a self-partner).
    """
    if gg.periods[d]:
        return True
    return abs(int(gg.disp)) < gg.dims[d]


def require_deep_halo(w: int, gg=None, *, what: str = "exchange_every") -> None:
    """Validate that every dimension with halo activity has ``overlap >= 2w``.

    Shared precondition of the temporal-blocking cadences
    (`update_halo(width=w)` once per ``w`` steps — the fused-kernel and
    XLA-only variants in the models): the sent slab planes must lie at
    distance >= ``w`` from the block edge, where ``w`` stencil steps are
    still exact.  Raises ``ValueError`` naming the shallow dimensions.

    This is a *grid-level* precheck against ``gg.overlaps`` for an early,
    caller-facing error at build time; the authoritative per-field check is
    the shape-aware ``ol`` validation inside `_exchange_dim`, which a field
    whose own ``ol`` is below the grid overlap (e.g. an ``n-1``-sized axis)
    still hits at trace time.
    """
    if gg is None:
        gg = _grid.global_grid()
    shallow = [
        d
        for d in range(NDIMS)
        if dim_has_halo_activity(gg, d) and gg.overlaps[d] < 2 * w
    ]
    if shallow:
        raise ValueError(
            f"{what}={w} on a communicating grid needs a deep halo: overlap >= "
            f"{2 * w} in every dimension with halo activity, but dims {shallow} "
            f"have overlaps {[gg.overlaps[d] for d in shallow]} (grid dims="
            f"{gg.dims}, periods={gg.periods}). Re-init with overlap"
            f"{'/'.join('xyz'[d] for d in shallow)}={2 * w}, or use the "
            "per-step exchange."
        )


def _set_plane(A, plane, index: int, dim: int):
    import jax.numpy as jnp
    from jax import lax

    return lax.dynamic_update_slice_in_dim(A, plane.astype(A.dtype), index, axis=dim)


def _get_plane(A, index: int, dim: int, width: int = 1):
    from jax import lax

    return lax.slice_in_dim(A, index, index + width, axis=dim)


def _exchange_dim(A, d: int, gg, width: int = 1, logical=None, axis=None) -> "jax.Array":
    """Exchange the two halo slabs (``width`` planes each) of block ``A``
    along dimension ``d``.

    ``width=1`` is the reference's exchange.  ``width=w>1`` is the deep-halo
    generalization for temporal blocking: my planes ``[o-w, o)`` refresh the
    lower neighbor's ``[n-w, n)`` and ``[n-o, n-o+w)`` refresh the upper
    neighbor's ``[0, w)`` — one collective per ``w`` steps instead of ``w``
    collectives, so the latency of a `collective_permute` hop amortizes over
    ``w`` fused steps.  Valid iff ``ol >= 2*width`` (the sent planes must lie
    at distance >= width from my own edge, where a width-deep stencil sweep
    still has exact values).

    ``logical``: the field's REAL local shape when ``A`` carries it in a
    larger padded layout (`ops.pallas_leapfrog.pad_faces`) — slab indices
    and the shape-aware ``ol`` are computed from it, and since every real
    plane index is within the padded array the slicing needs no change.
    The pad tail is junk by the layout's contract, so exchanging junk
    planes along *other* dimensions (full-extent slabs include the tail)
    is harmless.

    ``axis``: the ARRAY axis holding grid dimension ``d``'s data when the
    two differ (transposed patch layouts store y on axis 2); slab indices
    still come from ``logical[d]``, the field's real size in grid dim ``d``.
    """
    vals = _slab_recv_values(A, d, gg, width, logical, axis=axis)
    if vals is None:
        return A
    return _apply_recv(A, d, vals, width, logical=logical, axis=axis)


def _apply_recv(A, d: int, vals, width: int, logical=None, axis=None):
    """Write a dim-``d`` exchange's received ``(lo_vals, hi_vals)`` slabs
    into ``A``'s halo planes — the write half of `_exchange_dim`, shared
    with the multi-field exchange paths."""
    lo_vals, hi_vals = vals
    shp = logical if logical is not None else tuple(A.shape)
    ax = d if axis is None else axis
    A = _set_plane(A, hi_vals, shp[d] - width, ax)
    A = _set_plane(A, lo_vals, 0, ax)
    return A


def _patch_slab(slab, d: int, start: int, width: int, received, shp):
    """Overwrite a dim-``d`` slab's earlier-dim halo strips with received
    values — the sequential-dimension corner carry-over
    (`/root/reference/src/update_halo.jl:40`) applied at slab granularity.

    ``slab`` was sliced from the field at plane range ``[start,
    start+width)`` along ``d``; ``received`` maps each already-exchanged
    dim ``d2 < d`` to its ``(lo, hi)`` receive slabs (full field extent
    along ``d``); ``shp`` is the field's logical shape (the hi-strip
    offset, like `_set_plane`'s in `_exchange_dim`).  This makes
    `begin_slab_exchange`'s sends bit-identical to slicing the
    sequentially-updated array.
    """
    from jax import lax

    for d2, (lo2, hi2) in received.items():
        if d2 >= slab.ndim:
            continue
        w2 = lo2.shape[d2]
        strip = lax.slice_in_dim(lo2, start, start + width, axis=d)
        off = [0] * slab.ndim
        slab = lax.dynamic_update_slice(slab, strip.astype(slab.dtype), off)
        strip = lax.slice_in_dim(hi2, start, start + width, axis=d)
        off[d2] = shp[d2] - w2
        slab = lax.dynamic_update_slice(slab, strip.astype(slab.dtype), off)
    return slab


def _slab_parts(A, d: int, gg, width: int = 1, logical=None, axis=None,
                received=None):
    """The slabs a ``d``-exchange of ``A`` involves, without communicating.

    Returns ``None`` when the dimension exchanges nothing for this field,
    ``("self", lo_vals, hi_vals)`` on the self-partner fast path (a pure
    local copy needs no transport), or ``("permute", send_lo, send_hi,
    keep_lo, keep_hi)`` — the two eager send slabs plus the PROC_NULL
    keep-old slabs as thunks (built only when a non-periodic edge needs
    masking).  The communication half lives in `_permute_slabs`
    (per-field) and `_coalesced_permute` (packed multi-field).
    """
    shp = logical if logical is not None else tuple(A.shape)  # local block shape
    ax = d if axis is None else axis  # array axis carrying grid dim d's data
    if d >= len(shp):
        # A dimension beyond the field's rank can only ever be exchanged with a
        # self/absent neighbor (grid validation forces dims[d]==1, period 0).
        return None
    o = ol(d, shape=shp, gg=gg)
    if o < 2:
        return None  # no halo in this dimension (reference: update_halo.jl:369)
    n = shp[d]
    if not dim_has_halo_activity(gg, d):
        # No partners at all: dims==1 non-periodic, or every distance-disp
        # shift falls off the grid (all partners PROC_NULL).
        return None
    if o < 2 * width:
        # Only dimensions that actually exchange need the deep halo.
        raise ValueError(
            f"update_halo(width={width}) needs overlap >= {2 * width} in "
            f"dimension {d}; this field has ol={o}. Re-init the grid with "
            f"overlap{'xyz'[d]}={2 * width} (deep halo) or use width=1."
        )
    # Exchange partners sit at Cartesian distance ``disp`` — the semantics of
    # the reference's ``MPI_Cart_shift(dim, disp)`` neighbor table
    # (`/root/reference/src/init_global_grid.jl:89-92`), which its
    # `update_halo!` sends to (`/root/reference/src/update_halo.jl:713-735`).
    # The ppermute pairs (see `_permute_slabs`) realize exactly
    # `GlobalGrid.neighbors` (`parallel/topology.py:neighbors_table`):
    # send_lo goes to ``neighbors[0, d]`` (coordinate - disp), send_hi to
    # ``neighbors[1, d]``.
    def slab(start):
        s = _get_plane(A, start, ax, width)
        if received:
            s = _patch_slab(s, ax, start, width, received, shp)
        return s

    if _partner_self(gg, d):
        # Every block is its own partner (periodic wrap disp%nd==0, the
        # reference's self-neighbor fast path generalized, or disp==0):
        # pure local copy (reference: update_halo.jl:57-63).
        return (
            "self",
            slab(n - o),      # -> planes [0, width)
            slab(o - width),  # -> planes [n-width, n)
        )

    # Slabs go to the lower partner's top ``width`` planes / the upper
    # partner's bottom ``width`` planes (reference sendranges/recvranges,
    # generalized from one plane to a slab).
    return (
        "permute",
        slab(o - width),
        slab(n - o),
        lambda: slab(0),
        lambda: slab(n - width),
    )


def _slab_recv_values(A, d: int, gg, width: int = 1, logical=None, axis=None,
                      received=None):
    """The two slabs a ``d``-exchange of ``A`` would write, without writing.

    Returns ``(lo_vals, hi_vals)`` — the values destined for planes
    ``[0, width)`` and ``[n-width, n)`` (``n`` from ``logical`` when given)
    — or ``None`` when the dimension exchanges nothing for this field.
    `_exchange_dim` is get-values + two `_set_plane`s; the fused kernels'
    z-patch path (`z_slab_patches`) uses the values directly, applying them
    in VMEM where the minor-dim plane surgery is free (see
    docs/performance.md's exchanged-dimension anisotropy note).

    ``received`` (the `begin_slab_exchange` path): earlier dims' receive
    slabs, patched into this dim's send/keep slabs via `_patch_slab` so the
    sends equal those sliced from a sequentially-updated array.
    """
    p = _slab_parts(A, d, gg, width, logical, axis, received)
    if p is None:
        return None
    if p[0] == "self":
        return p[1], p[2]
    _, send_lo, send_hi, keep_lo, keep_hi = p
    return _permute_slabs(
        gg, d, send_lo=send_lo, send_hi=send_hi, keep_lo=keep_lo,
        keep_hi=keep_hi,
    )


def _partner_self(gg, d: int) -> bool:
    """Every block its own distance-``disp`` partner along ``d``?"""
    nd = gg.dims[d]
    disp = int(gg.disp)
    return (disp % nd == 0) if bool(gg.periods[d]) else (disp == 0)


def _permute_slabs(gg, d: int, *, send_lo, send_hi, keep_lo, keep_hi):
    """ppermute two send slabs to the distance-``disp`` partners along ``d``.

    The ONE implementation of the neighbor communication used by both the
    full-field exchange (`_slab_recv_values`) and the packed z-export path
    (`z_patch_from_export`) — partner permutation, periodic wrap, and
    PROC_NULL keep-old masking must never drift between the two.  Returns
    ``(lo_vals, hi_vals)`` destined for planes ``[0,w)`` / ``[n-w,n)``;
    ``keep_lo``/``keep_hi`` are thunks producing the current boundary slabs
    for blocks whose shift falls off a non-periodic grid (the reference's
    PROC_NULL neighbors do nothing).  Self-partner configs never reach
    here (both callers take their own fast path).
    """
    import jax.numpy as jnp
    from jax import lax

    nd = gg.dims[d]
    periodic = bool(gg.periods[d])
    disp = int(gg.disp)
    axis = AXIS_NAMES[d]
    if periodic:
        perm_down = [(i, (i - disp) % nd) for i in range(nd)]
        perm_up = [(i, (i + disp) % nd) for i in range(nd)]
    else:
        perm_down = [(i, i - disp) for i in range(nd) if 0 <= i - disp < nd]
        perm_up = [(i, i + disp) for i in range(nd) if 0 <= i + disp < nd]
    try:
        recv_hi = lax.ppermute(send_lo, axis, perm_down)  # from my upper partner
        recv_lo = lax.ppermute(send_hi, axis, perm_up)  # from my lower partner
    except NameError as e:
        raise RuntimeError(
            "update_halo was called on traced (non-concrete) fields outside of an "
            "igg.stencil/shard_map context over the global grid's mesh. Either call "
            "it on global-block arrays, or inside a function wrapped with "
            "igg.stencil (or jax.shard_map over igg's mesh axes 'x','y','z')."
        ) from e
    if periodic:
        return recv_lo, recv_hi
    # ppermute delivered zeros to blocks with no source partner; keep the
    # old boundary slab there.
    idx = lax.axis_index(axis)
    has_upper = (idx + disp >= 0) & (idx + disp < nd)
    has_lower = (idx - disp >= 0) & (idx - disp < nd)
    return (
        jnp.where(has_lower, recv_lo, keep_lo()),
        jnp.where(has_upper, recv_hi, keep_hi()),
    )


# --- Coalesced multi-field transport (message combining) ---------------------
#
# One `collective-permute` pair per (dimension, dtype byte width) instead of
# one per field: every participating field's send slab is flattened to its
# same-width unsigned-int words (the chunked gather's byte-exact transport,
# `ops.gather._block_fetch_fn` — f32/bf16/-0.0/NaN payloads survive because
# bitcasting is arithmetic-free), the flat words concatenate into one buffer
# per byte width, the packed buffers ride `_permute_slabs` (same partner
# permutation, same PROC_NULL whole-word masking), and the received buffer
# splits/bitcasts back into per-field slabs.  Fewer, fatter hops: the
# per-hop latency of a collective amortizes over every field of the step —
# the reference's own pipelining advice taken one level further
# (`/root/reference/src/update_halo.jl:13-14`).


def _word_width(dtype) -> int:
    """Transport word size in bytes (complex splits into two float words)."""
    dt = np.dtype(dtype)
    return dt.itemsize // 2 if dt.kind == "c" else dt.itemsize


def _flat_words(x):
    """Flatten ``x`` to its same-width unsigned-int words, byte-exactly.

    bool cannot `bitcast_convert_type`; its {0,1} values convert to uint8
    exactly (and back), which is just as byte-faithful for a transport.
    """
    import jax.numpy as jnp
    from jax import lax

    from .gather import _word_dtype

    if x.dtype == jnp.bool_.dtype:
        return x.reshape(-1).astype(jnp.uint8)
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        x = jnp.stack((x.real, x.imag), axis=-1)
    return lax.bitcast_convert_type(x, _word_dtype(x.dtype)).reshape(-1)


def _from_words(buf, shape, dtype):
    """Invert `_flat_words`: words back to an array of ``shape``/``dtype``."""
    import jax.numpy as jnp
    from jax import lax

    dt = jnp.dtype(dtype)
    if dt == jnp.bool_.dtype:
        return buf.reshape(tuple(shape)).astype(dt)
    if jnp.issubdtype(dt, jnp.complexfloating):
        ft = jnp.finfo(dt).dtype
        comp = lax.bitcast_convert_type(buf.reshape(tuple(shape) + (2,)), ft)
        return lax.complex(comp[..., 0], comp[..., 1])
    return lax.bitcast_convert_type(buf.reshape(tuple(shape)), dt)


def _coalesced_permute(gg, d: int, parts):
    """`_permute_slabs` for several fields at once: one ppermute pair per
    dtype byte-width group instead of one per field.

    ``parts``: per-field ``(send_lo, send_hi, keep_lo, keep_hi)`` tuples
    (keeps as thunks, `_slab_parts`).  Returns per-field ``(lo_vals,
    hi_vals)`` BIT-identical to the per-field path: the packed buffer moves
    the same words, the PROC_NULL mask picks whole words with the same
    per-dim predicate, and the bitcast round trip is arithmetic-free.  A
    width group with a single member skips the packing (nothing to combine
    — same collectives either way, no relayout paid).

    Autodiff: `lax.bitcast_convert_type` has no tangent, so the packed
    transport carries a custom VJP that differentiates the PER-FIELD
    transport instead (`_packed_transport` — the `fused_with_xla_grad`
    pattern): both move the identical values field-for-field, so the
    per-field path's exact ppermute/where transpose IS the packed path's
    transpose.  Without it, `jax.grad` through a coalesced exchange would
    silently drop every cotangent that crosses a block boundary.
    """
    periodic = bool(gg.periods[d])
    sends_lo = tuple(p[0] for p in parts)
    sends_hi = tuple(p[1] for p in parts)
    if periodic:
        # Keep slabs are only ever read by the PROC_NULL mask of
        # non-periodic dims; do not materialize them elsewhere.
        keeps_lo = keeps_hi = ()
    else:
        keeps_lo = tuple(p[2]() for p in parts)
        keeps_hi = tuple(p[3]() for p in parts)
    los, his = _packed_transport(gg, d)(sends_lo, sends_hi, keeps_lo, keeps_hi)
    return [(lo, hi) for lo, hi in zip(los, his)]


def _keep_thunks(keeps_lo, keeps_hi, j: int):
    """keep_lo/keep_hi thunk kwargs for field ``j`` (dummies on periodic
    dims, where `_permute_slabs` never invokes them)."""
    if not keeps_lo:
        return dict(keep_lo=lambda: None, keep_hi=lambda: None)
    return dict(keep_lo=lambda: keeps_lo[j], keep_hi=lambda: keeps_hi[j])


def _flip_wire_word(buf, proc: int, gg):
    """XOR bit 0 of payload word 0 of rank ``proc``'s wire buffer — the
    armed ``bit_flip:…:transport`` injection.  Applied AFTER the checksum
    fold (in-flight corruption: the sender's fold covered the clean words,
    so the receiver's recompute over the landed payload must disagree)."""
    import jax.numpy as jnp
    from jax import lax

    # row-major linear rank from the mesh coords (topology.rank_of_coords)
    rank = lax.axis_index(AXIS_NAMES[0])
    for dd in range(1, NDIMS):
        rank = rank * gg.dims[dd] + lax.axis_index(AXIS_NAMES[dd])
    flipped = buf.at[0].set(buf[0] ^ jnp.array(1, buf.dtype))
    return jnp.where(rank == proc, flipped, buf)


def _packed_transport(gg, d: int):
    """The width-group packed transport as a differentiable function of the
    per-field send/keep slabs.  Primal: bitcast-pack per byte width, one
    `_permute_slabs` pair per group.  VJP: `jax.vjp` of the per-field
    transport over the same operands (value-identical by the coalescing
    contract, and built from primitives with exact transpose rules).

    With a `integrity.transport.TransportCollector` active (the
    integrity-enabled global exchange, ``IGG_INTEGRITY=1``), every group —
    singletons included — packs to the word view and the wire buffer grows
    ONE checksum word (XOR fold of the payload words, `append_checksum`);
    the receive side recomputes the fold over the landed payload
    (`split_and_verify`) and the traced mismatch flags register on the
    collector.  Same hops, payload +1 word per (group, direction); PROC_NULL
    keep buffers carry their own self-consistent fold, so masked edges can
    never false-trip.  The checksummed build returns the RAW function (no
    `custom_vjp` envelope): the host integrity path never differentiates,
    and flags escaping through a custom-VJP primal would leak tracers.
    """
    import jax
    import jax.numpy as jnp

    from ..integrity import transport as _itransport
    from ..utils import telemetry as _telemetry

    col = _itransport.active_collector()

    def packed(sends_lo, sends_hi, keeps_lo, keeps_hi):
        groups: dict[int, list[int]] = {}
        for j, s in enumerate(sends_lo):
            groups.setdefault(_word_width(s.dtype), []).append(j)
        los: list = [None] * len(sends_lo)
        his: list = [None] * len(sends_lo)
        for wbytes, idxs in sorted(groups.items()):
            if len(idxs) == 1 and col is None:
                (j,) = idxs
                los[j], his[j] = _permute_slabs(
                    gg, d, send_lo=sends_lo[j], send_hi=sends_hi[j],
                    **_keep_thunks(keeps_lo, keeps_hi, j),
                )
                continue
            flats_lo = [_flat_words(sends_lo[j]) for j in idxs]
            flats_hi = [_flat_words(sends_hi[j]) for j in idxs]
            sizes = [int(f.shape[0]) for f in flats_lo]
            buf_lo = jnp.concatenate(flats_lo)
            buf_hi = jnp.concatenate(flats_hi)
            if len(idxs) > 1:
                # Trace-time counters (like `halo.begin_slab_traces`):
                # coalesced exchanges are built into compiled programs, so
                # these count traced collectives and their per-hop payload
                # bytes (docs/observability.md).
                _telemetry.counter("halo.coalesced_collectives").inc(2)
                _telemetry.counter("halo.coalesced_bytes").inc(
                    2 * int(buf_lo.shape[0]) * wbytes
                )
            if col is not None:
                wire_lo = _itransport.append_checksum(buf_lo)
                wire_hi = _itransport.append_checksum(buf_hi)
                flip = col.take_flip()
                if flip is not None:
                    wire_lo = _flip_wire_word(wire_lo, flip, gg)
                    wire_hi = _flip_wire_word(wire_hi, flip, gg)
                recv_lo, recv_hi = _permute_slabs(
                    gg, d,
                    send_lo=wire_lo,
                    send_hi=wire_hi,
                    keep_lo=lambda: _itransport.append_checksum(
                        jnp.concatenate([_flat_words(keeps_lo[j]) for j in idxs])
                    ),
                    keep_hi=lambda: _itransport.append_checksum(
                        jnp.concatenate([_flat_words(keeps_hi[j]) for j in idxs])
                    ),
                )
                recv_lo, bad_lo = _itransport.split_and_verify(recv_lo)
                recv_hi, bad_hi = _itransport.split_and_verify(recv_hi)
                col.record(
                    dim=d, width=wbytes, fields=idxs, bad_lo=bad_lo,
                    bad_hi=bad_hi,
                )
            else:
                recv_lo, recv_hi = _permute_slabs(
                    gg, d,
                    send_lo=buf_lo,
                    send_hi=buf_hi,
                    keep_lo=lambda: jnp.concatenate(
                        [_flat_words(keeps_lo[j]) for j in idxs]
                    ),
                    keep_hi=lambda: jnp.concatenate(
                        [_flat_words(keeps_hi[j]) for j in idxs]
                    ),
                )
            off = 0
            for j, size in zip(idxs, sizes):
                shape, dtype = sends_lo[j].shape, sends_lo[j].dtype
                los[j] = _from_words(recv_lo[off : off + size], shape, dtype)
                his[j] = _from_words(recv_hi[off : off + size], shape, dtype)
                off += size
        return tuple(los), tuple(his)

    def per_field(sends_lo, sends_hi, keeps_lo, keeps_hi):
        outs = [
            _permute_slabs(
                gg, d, send_lo=sends_lo[j], send_hi=sends_hi[j],
                **_keep_thunks(keeps_lo, keeps_hi, j),
            )
            for j in range(len(sends_lo))
        ]
        return tuple(o[0] for o in outs), tuple(o[1] for o in outs)

    if col is not None:
        # Checksummed build: raw function, no custom_vjp (docstring).
        return packed

    f = jax.custom_vjp(packed)

    def fwd(*ops):
        return packed(*ops), ops

    def bwd(ops, g):
        _, vjp = jax.vjp(per_field, *ops)
        return vjp(g)

    f.defvjp(fwd, bwd)
    return f


def _multi_slab_recv_values(fields, d: int, gg, width: int = 1, logicals=None,
                            axes=None, receiveds=None, coalesce: bool = True):
    """Per-field ``(lo_vals, hi_vals)`` of a dim-``d`` exchange of a field
    LIST — `_slab_recv_values` over several fields, with the collectives
    coalesced across fields (`_coalesced_permute`) when ``coalesce`` is on
    and at least two fields actually permute.  Entries are ``None`` where a
    field skips the dimension; ``axes[i]``/``logicals[i]``/``receiveds[i]``
    as in `_slab_recv_values`.

    With a `TransportCollector` active (the integrity-enabled global
    exchange), EVERY permuting field routes through `_coalesced_permute`
    regardless of count or the ``coalesce`` flag — the checksum word rides
    the packed wire form, so per-field hops must pack too (still one
    ppermute pair per width group; the collective census is unchanged)."""
    from ..integrity.transport import active_collector

    n = len(fields)
    logicals = (None,) * n if logicals is None else tuple(logicals)
    axes = (None,) * n if axes is None else tuple(axes)
    receiveds = (None,) * n if receiveds is None else tuple(receiveds)
    out: list = [None] * n
    permuting: list = []
    for i, A in enumerate(fields):
        p = _slab_parts(A, d, gg, width, logicals[i], axes[i], receiveds[i])
        if p is None:
            continue
        if p[0] == "self":
            out[i] = (p[1], p[2])
        else:
            permuting.append((i, p[1:]))
    if permuting and (
        active_collector() is not None or (coalesce and len(permuting) >= 2)
    ):
        vals = _coalesced_permute(gg, d, [p for _, p in permuting])
        for (i, _), v in zip(permuting, vals):
            out[i] = v
    else:
        for i, (send_lo, send_hi, keep_lo, keep_hi) in permuting:
            out[i] = _permute_slabs(
                gg, d, send_lo=send_lo, send_hi=send_hi, keep_lo=keep_lo,
                keep_hi=keep_hi,
            )
    return out


def _default_coalesce() -> bool:
    """``IGG_COALESCE`` env default for the multi-field exchange paths.

    Unset = auto (coalesce whenever >= 2 fields share a dimension's
    exchange — it is bit-identical, so the only reason to stay per-field
    is debugging/attribution); ``0`` restores per-field collectives;
    nonzero forces the auto behavior explicitly.  Read per call/trace,
    like ``IGG_DONATE``.
    """
    from ..utils.config import coalesce_env

    val = coalesce_env()
    return True if val is None else val


def _update_halo_local(fields: tuple, gg, width: int = 1,
                       coalesce: bool | None = None) -> tuple:
    """Per-block exchange of all fields, dimensions strictly in order x→y→z.

    ``coalesce`` (None = `IGG_COALESCE` env, default auto): pack every
    field's send slab into one buffer per dtype byte width and issue ONE
    collective-permute pair per (dimension, width group) instead of one per
    field (`_coalesced_permute`) — bit-identical, fewer/fatter hops.
    """
    from ..utils.compat import named_scope

    if coalesce is None:
        coalesce = _default_coalesce()
    out = list(fields)
    with named_scope("igg_halo_exchange"):
        for d in range(NDIMS):
            vals = _multi_slab_recv_values(out, d, gg, width, coalesce=coalesce)
            for i, v in enumerate(vals):
                if v is not None:
                    out[i] = _apply_recv(out[i], d, v, width)
    return tuple(out)


def _padded_logicals(C, Axp, Ayp, Azp):
    from .pallas_leapfrog import padded_face_shapes

    n0, n1, n2 = C.shape
    if (Axp.shape, Ayp.shape, Azp.shape) != padded_face_shapes(C.shape):
        raise ValueError(
            f"fields must be in pad_faces layout for cell shape {tuple(C.shape)}: "
            f"got {Axp.shape}, {Ayp.shape}, {Azp.shape}"
        )
    return (None, (n0 + 1, n1, n2), (n0, n1 + 1, n2), (n0, n1, n2 + 1))


def _pack_z_patch(lo, hi, width: int):
    """Pack a field's two z slabs into one 128-lane array: lanes ``[0, w)``
    = values for planes ``[0, w)``, lanes ``[w, 2w)`` = values for planes
    ``[n-w, n)``, junk beyond — the layout the fused kernels' z-patch DMA
    windows require (full-minor 128-lane fetches are the only lane-aligned
    way to move a thin z slab; see the exchanged-dimension anisotropy note
    in docs/performance.md)."""
    import jax.numpy as jnp

    packed = jnp.concatenate([lo, hi], axis=2)
    return jnp.pad(packed, ((0, 0), (0, 0), (0, 128 - 2 * width)))


def z_slab_patch(A, *, width: int = 1):
    """Single-field version of `z_slab_patches` (the diffusion kernel's T).

    Returns the packed 128-lane patch for a plain cell field, or None when
    the z dimension exchanges nothing."""
    gg = _grid.global_grid()
    vals = _slab_recv_values(A, 2, gg, width)
    if vals is None:
        return None
    return _pack_z_patch(*vals, width)


def identity_z_patch(A, *, width: int = 1):
    """Single-field `identity_z_patches` (re-writes the current z planes)."""
    n = A.shape[2]
    return _pack_z_patch(
        _get_plane(A, 0, 2, width), _get_plane(A, n - width, 2, width), width
    )


def apply_z_patch(A, patch, *, width: int = 1):
    """Single-field `apply_z_patches` (the chunk-end restoration)."""
    n = A.shape[2]
    A = _set_plane(A, patch[:, :, :width], 0, 2)
    return _set_plane(A, patch[:, :, width : 2 * width], n - width, 2)


# --- Transposed thin-patch layout (round 5) ---------------------------------
#
# The packed 128-lane z-patch layout moves 128 lanes per window for 2k-4k
# real planes — at n2=256 the patch/export windows cost the fused z-split
# cadence ~30% extra HBM traffic (VERDICT r4 missing #3).  The transposed
# layout stores the thin dimension in SUBLANES instead: a patch is
# ``(n0, pad8(planes), n1p)`` with plane p of the field's y-row at
# ``[:, p, :]`` — sublanes are 8-dense, so windows move pad8(2k) planes
# instead of 128 lanes (~16x less patch traffic), and the export write
# shrinks the same way.  The kernel needs FULL-Y tiles (``by == n1``) for
# this layout: the transposed export's out-DMA then has no minor-dim window
# offsets at all (minor-dim slicing would need 128-aligned offsets the
# owned-block geometry cannot provide).  Plane layout along axis 1 is
# identical to the packed layout's lanes: patches [0,w) = values for planes
# [0,w), [w,2w) = the top w planes; exports [0,w) send-hi, [w,2w) send-lo,
# [2w,3w)/[3w,4w) keep-old.  ``n1p`` pads the minor (y) extent to a 128
# multiple (Mosaic lane-tile alignment).

from ._fused_envelope import pad8 as _pad8, pad128 as _pad128


def _pack_z_patch_t(lo, hi, width: int):
    """Pack two z slabs (each ``(n0, n1, width)``) into the transposed patch
    ``(n0, pad8(2w), pad128(n1))``."""
    import jax.numpy as jnp

    packed = jnp.concatenate([lo, hi], axis=2).transpose(0, 2, 1)
    n0, p, n1 = packed.shape
    return jnp.pad(packed, ((0, 0), (0, _pad8(p) - p), (0, _pad128(n1) - n1)))


def identity_z_patch_t(A, *, width: int = 1):
    """Transposed-layout `identity_z_patch` (re-writes the current z planes)."""
    n = A.shape[2]
    return _pack_z_patch_t(
        _get_plane(A, 0, 2, width), _get_plane(A, n - width, 2, width), width
    )


def apply_z_patch_t(A, patch_t, *, width: int = 1):
    """Transposed-layout `apply_z_patch` (the chunk-end restoration)."""
    n0, n1, n = A.shape
    lo = patch_t[:, 0:width, :n1].transpose(0, 2, 1)
    hi = patch_t[:, width : 2 * width, :n1].transpose(0, 2, 1)
    A = _set_plane(A, lo, 0, 2)
    return _set_plane(A, hi, n - width, 2)


#: Array-axis map of the transposed z-patch/export layout: grid dim 0's
#: slabs live on array axis 0 (as usual), grid dim 1's on array axis 2
#: (the ``axes`` override of `exchange_dims_multi`).
_T_AXES = {0: 0, 1: 2}


def exchange_dims_t(E, *, width: int, shape, coalesce=None):
    """x/y-exchange a TRANSPOSED z-patch/export array ``(n0, P, n1p)``.

    Grid dim 0's slabs live on array axis 0 (as usual); grid dim 1's live on
    array axis 2, with slab indices from the field's REAL shape ``shape`` —
    the ``axis`` override of `_exchange_dim`.  Dimension order (x before y)
    carries the sequential-dimension corner semantics exactly like the
    packed layout's `exchange_dims`.
    """
    (E,) = exchange_dims_multi(
        (E,), (0, 1), width=width, logicals=(shape,), axes=(_T_AXES,),
        coalesce=coalesce,
    )
    return E


def z_patch_from_export_t(export_t, *, width: int):
    """Transposed-layout `z_patch_from_export`: the z communication on the
    ``(n0, PE, n1p)`` export's axis-1 plane slabs.  Must run AFTER the x/y
    exchange of the export (`exchange_dims_t`)."""
    import jax.numpy as jnp

    gg = _grid.global_grid()
    w = width
    if _partner_self(gg, 2):
        # Planes [0,2w) are already the patch (send-hi -> planes [0,w),
        # send-lo -> the top w planes); the pad8 tail planes are junk either
        # way, so hand the export straight back when the pads agree.
        if _pad8(2 * w) == export_t.shape[1]:
            return export_t
        return export_t[:, 0 : _pad8(2 * w), :]
    recv_lo, recv_hi = _permute_slabs(
        gg, 2,
        send_lo=export_t[:, w : 2 * w, :],
        send_hi=export_t[:, 0:w, :],
        keep_lo=lambda: export_t[:, 2 * w : 3 * w, :],
        keep_hi=lambda: export_t[:, 3 * w : 4 * w, :],
    )
    packed = jnp.concatenate([recv_lo, recv_hi], axis=1)
    pad = _pad8(2 * w) - 2 * w
    return jnp.pad(packed, ((0, 0), (0, pad), (0, 0)))


def exchange_dims(A, dims, *, width: int = 1, logical=None):
    """Exchange a single field along the given dimensions only (traced
    context; the z-patch cadences exchange x/y here and route z through
    the kernel).  ``logical`` as in `_exchange_dim` (packed z-slab exports
    exchange with their field's REAL x/y slab indices)."""
    gg = _grid.global_grid()
    for d in dims:
        A = _exchange_dim(A, d, gg, width, logical=logical)
    return A


def exchange_dims_multi(fields, dims, *, width: int = 1, logicals=None,
                        axes=None, coalesce: bool | None = None):
    """Exchange SEVERAL fields along the given dimensions in one pass — the
    multi-field `exchange_dims`, with each dimension's collectives coalesced
    across fields (one `collective-permute` pair per (dimension, dtype byte
    width); ``coalesce`` None = the ``IGG_COALESCE`` env default, auto-on).

    ``logicals[i]``: field ``i``'s REAL shape for padded layouts; ``axes[i]``:
    an optional ``{grid dim: array axis}`` map for transposed layouts
    (`exchange_dims_t`'s y-on-axis-2).  Dimensions run strictly in the given
    order, each seeing the previous dims' updated halos — the sequential-
    dimension corner semantics, unchanged.  Traced-context only, like
    `exchange_dims`.
    """
    gg = _grid.global_grid()
    if coalesce is None:
        coalesce = _default_coalesce()
    n = len(fields)
    logicals = (None,) * n if logicals is None else tuple(logicals)
    axes = (None,) * n if axes is None else tuple(axes)
    out = list(fields)
    for d in dims:
        axs = [None if a is None else a.get(d) for a in axes]
        vals = _multi_slab_recv_values(
            out, d, gg, width, logicals, axs, coalesce=coalesce
        )
        for i, v in enumerate(vals):
            if v is not None:
                out[i] = _apply_recv(out[i], d, v, width, logicals[i], axs[i])
    return tuple(out)


# --- Early-dispatch slab exchange (pipelined group schedule) ----------------


def begin_slab_exchange(fields, dims, *, width: int, logicals=None,
                        coalesce=None):
    """Start the slab exchange of ``fields`` along ``dims`` WITHOUT writing
    the received planes back.

    The pipelined group schedule's early-exchange entry: called on the
    boundary pass's outputs — which own every send plane — so the
    `collective-permute`s dispatch with only thin slab slices as
    dependencies and fly while the interior pass computes.  Sequential-
    dimension corner semantics are preserved at slab granularity: each
    dim-``d`` send (and PROC_NULL keep) slab is patched with the dims
    ``< d`` receive strips from THIS call (`_patch_slab`), exactly the
    values a serialized per-dim exchange would have sliced.  Returns one
    ``pend`` list per field — ``[(d, lo_vals, hi_vals), ...]`` over the
    dims that actually exchange — for `finish_slab_exchange`.

    ``finish_slab_exchange(fields', pends)`` on arrays holding the same
    owned values is bit-identical to the serialized exchange
    (`exchange_dims` / `update_halo_padded_faces`) over the same dims.
    ``logicals``: per-field REAL shapes for padded layouts (as in
    `_exchange_dim`).  ``coalesce`` (None = ``IGG_COALESCE``): pack each
    dimension's send slabs across fields into one collective-permute pair
    per dtype byte width — each field's sends depend only on its OWN
    earlier-dim receive strips, so the dim-major packing moves exactly the
    per-field values.  Traced-context only, like `exchange_dims`.
    """
    from ..utils import telemetry as _telemetry
    from ..utils import tracing as _tracing
    from ..utils.compat import named_scope

    gg = _grid.global_grid()
    if logicals is None:
        logicals = (None,) * len(fields)
    if coalesce is None:
        coalesce = _default_coalesce()
    # Trace-time counter: begin/finish calls run while BUILDING a program
    # (the early-dispatch exchange shape), so this counts traced schedules,
    # not runtime executions (docs/observability.md).  The host span below
    # is trace-time too (tagged so a timeline reader cannot mistake it for
    # a runtime exchange).
    _telemetry.counter("halo.begin_slab_traces").inc()
    receiveds: list[dict] = [{} for _ in fields]
    pends: list[list] = [[] for _ in fields]
    with _tracing.trace_span(
        "igg_slab_exchange_begin", phase="trace", fields=len(fields)
    ), named_scope("igg_slab_exchange_begin"):
        for d in dims:
            vals = _multi_slab_recv_values(
                fields, d, gg, width, logicals, receiveds=receiveds,
                coalesce=coalesce,
            )
            for i, v in enumerate(vals):
                if v is None:
                    continue
                receiveds[i][d] = v
                pends[i].append((d, v[0], v[1]))
    return pends


def finish_slab_exchange(fields, pends, *, logicals=None):
    """Apply `begin_slab_exchange`'s received slabs to ``fields``.

    ``fields`` may be later arrays than the ones `begin_slab_exchange` saw
    (the pipelined schedule finishes on the combined boundary+interior
    output) as long as they hold the same owned values.  Returns the
    updated tuple.
    """
    from ..utils import telemetry as _telemetry
    from ..utils import tracing as _tracing
    from ..utils.compat import named_scope

    if logicals is None:
        logicals = (None,) * len(fields)
    _telemetry.counter("halo.finish_slab_traces").inc()
    out = []
    with _tracing.trace_span(
        "igg_slab_exchange_finish", phase="trace", fields=len(fields)
    ), named_scope("igg_slab_exchange_finish"):
        for A, pend, logical in zip(fields, pends, logicals):
            shp = logical if logical is not None else tuple(A.shape)
            for d, lo, hi in pend:
                w = lo.shape[d]
                A = _set_plane(A, hi, shp[d] - w, d)
                A = _set_plane(A, lo, 0, d)
            out.append(A)
    return tuple(out)


def z_patch_from_export(export, *, width: int):
    """The next group's packed z patch from a fused kernel's z-slab export.

    Export lane layout (see `ops.pallas_stencil.fused_diffusion_steps`
    ``z_export``): ``[0,w)`` = send-hi planes ``[n-o, n-o+w)``, ``[w,2w)``
    = send-lo planes ``[o-w, o)``, ``[2w,3w)``/``[3w,4w)`` = the current
    boundary planes (PROC_NULL keep-old values).  This is the z-dimension
    communication of `_slab_recv_values` performed on the packed 128-lane
    array instead of the full field — the kernel already did the
    extraction in VMEM, so no whole-array minor-dim relayout is paid.
    Must run AFTER the x/y exchanges of the export (sequential-dimension
    corner semantics ride the packed array).
    """
    gg = _grid.global_grid()
    w = width
    if _partner_self(gg, 2):
        # Lanes [0,2w) are already the patch (send-hi -> planes [0,w),
        # send-lo -> the top w planes) — the self-neighbor fast path.
        return export
    recv_lo, recv_hi = _permute_slabs(gg, 2, **_z_export_slabs(export, w))
    return _pack_recv_patch(recv_lo, recv_hi, w)


def _z_export_slabs(export, w: int) -> dict:
    """The send/keep slab kwargs of one packed z export's z communication
    (export lane layout: see `z_patch_from_export`)."""
    return dict(
        send_lo=export[:, :, w : 2 * w],
        send_hi=export[:, :, 0:w],
        keep_lo=lambda: export[:, :, 2 * w : 3 * w],
        keep_hi=lambda: export[:, :, 3 * w : 4 * w],
    )


def _pack_recv_patch(recv_lo, recv_hi, w: int):
    """Received z slabs -> the next group's 128-lane patch layout."""
    import jax.numpy as jnp

    packed = jnp.concatenate([recv_lo, recv_hi], axis=2)
    return jnp.pad(packed, ((0, 0), (0, 0), (0, 128 - 2 * w)))


#: Lane offset of the z-face band in the merged cell+z-face patch/export —
#: THE owner of the value (the kernels import it from here).  The cell
#: field (C/P/Pf) and its z-staggered face field (Az/Vz/qDz) share x/y
#: extents AND x/y slab indices (they stagger only in z), so one packed
#: array serves both at lane bands [0, 4w) and [Z_CZ_BAND, Z_CZ_BAND+4w):
#: one kernel window fetch and one export write instead of two (round 5).
Z_CZ_BAND = 64


def _pack_cz(cell_band, z_band):
    """Merge the cell and z-face 128-lane packed arrays into one: the cell
    lanes stay at [0, ...), the z-face lanes move to [Z_CZ_BAND, ...)."""
    import jax.numpy as jnp

    return jnp.concatenate(
        [cell_band[:, :, :Z_CZ_BAND], z_band[:, :, : 128 - Z_CZ_BAND]], axis=2
    )


def fix_topface_z_exports(exports, C, Axp, Ayp, Azp, *, width: int):
    """Fill the frozen top-face slabs of the staggered kernels' z exports.

    The Vx row ``n0`` and Vy column ``n1`` (each field's real top face)
    sit outside every tile's owned block, so the kernels never write their
    export rows — fill them here from the output arrays (a one-row minor
    slice: ~n1*n2 elements, negligible next to the whole-array relayouts
    the export replaces).  Must run BEFORE the exports' x/y exchange: on
    x/y-active grids the exchange then overwrites the rows that belong to
    neighbors, exactly as it does for the fields themselves.
    """
    import jax.numpy as jnp
    from jax import lax

    gg = _grid.global_grid()
    n0, n1, n2 = C.shape
    w = width
    o = ol(2, shape=(n0, n1, n2), gg=gg)
    exp_cz, exp_x, exp_y = exports

    def packed_lanes(row):
        return jnp.concatenate(
            [
                row[..., n2 - o : n2 - o + w],
                row[..., o - w : o],
                row[..., 0:w],
                row[..., n2 - w : n2],
            ],
            axis=2,
        )

    exp_x = lax.dynamic_update_slice(
        exp_x, packed_lanes(Axp[n0 : n0 + 1]), (n0, 0, 0)
    )
    exp_y = lax.dynamic_update_slice(
        exp_y, packed_lanes(Ayp[:, n1 : n1 + 1]), (0, n1, 0)
    )
    return exp_cz, exp_x, exp_y


def z_patches_from_exports(exports, C_shape, *, width: int, coalesce=None):
    """x/y-exchange the three packed z exports (real-shape slab indices via
    ``logical``) and turn each into the next group's patch — the multi-field
    z communication of the staggered z-slab cadence, all on packed arrays.

    The merged cell+z-face export's x/y slab indices are the CELL's (the
    z-face field staggers only in z); its z communication runs per lane
    band in the non-self case, and the self-partner fast path hands the
    whole merged array back untouched.

    Coalesced by default (``IGG_COALESCE``): the three exports' x/y hops
    combine into one permute pair per dimension, and the non-self z hops
    of all four lane bands (cell, z-face, x-face, y-face) pack into ONE
    pair — 2 collectives for the whole staggered family's z exchange
    instead of 8 (the residual VERDICT r5 names behind the porous
    periodic-z gap).
    """
    n0, n1, _ = C_shape
    w = width
    gg = _grid.global_grid()
    if coalesce is None:
        coalesce = _default_coalesce()

    exp_cz, exp_x, exp_y = exchange_dims_multi(
        exports, (0, 1), width=w,
        logicals=(None, (n0 + 1, n1, 128), (n0, n1 + 1, 128)),
        coalesce=coalesce,
    )
    if _partner_self(gg, 2):
        # Bands [L, L+2w) are already the patches (`z_patch_from_export`'s
        # self-partner fast path, applied to all three).
        return exp_cz, exp_x, exp_y
    bands = (
        exp_cz[:, :, :Z_CZ_BAND],
        exp_cz[:, :, Z_CZ_BAND : Z_CZ_BAND + 4 * w],
        exp_x,
        exp_y,
    )
    slabs = [_z_export_slabs(b, w) for b in bands]
    if coalesce:
        vals = _coalesced_permute(
            gg, 2,
            [(s["send_lo"], s["send_hi"], s["keep_lo"], s["keep_hi"])
             for s in slabs],
        )
    else:
        vals = [_permute_slabs(gg, 2, **s) for s in slabs]
    cell, zf, patch_x, patch_y = (
        _pack_recv_patch(lo, hi, w) for lo, hi in vals
    )
    return _pack_cz(cell, zf), patch_x, patch_y


def z_slab_patches(C, Axp, Ayp, Azp, *, width: int = 1, coalesce=None):
    """The z-dimension exchange of the four fields, as packed patch arrays.

    Returns ``(patch_CAz, patch_Ax, patch_Ay)`` (`_pack_z_patch` layout;
    the cell and z-face fields share the first array's lane bands, see
    `Z_CZ_BAND`; extents match each PADDED array's x/y extents so kernel
    tile windows slice them with the same aligned offsets), or ``None``
    when the z dimension exchanges nothing.  Must be called AFTER the x/y
    exchanges (sequential-dimension corner semantics).  The patches are
    consumed by the fused kernels, which apply them to their VMEM tiles
    where minor-dim plane surgery is free — instead of the whole-array
    relayouts a z-`dynamic-update-slice` costs at a kernel boundary.
    """
    gg = _grid.global_grid()
    logicals = _padded_logicals(C, Axp, Ayp, Azp)
    vals = _multi_slab_recv_values(
        (C, Axp, Ayp, Azp), 2, gg, width, logicals,
        coalesce=_default_coalesce() if coalesce is None else coalesce,
    )
    if any(v is None for v in vals):
        return None  # all-or-nothing: z activity is per-grid, not per-field
    packed = [_pack_z_patch(*v, width) for v in vals]
    return (_pack_cz(packed[0], packed[3]), packed[1], packed[2])


def identity_z_patches(C, Axp, Ayp, Azp, *, width: int = 1):
    """Patches that re-write the CURRENT z-halo planes (a no-op application).

    The chunk-entry state has fresh halos (the models' chunk-boundary
    invariant), so the first fused group's patches are the planes already
    in place."""
    logicals = _padded_logicals(C, Axp, Ayp, Azp)
    packed = []
    for A, logical in zip((C, Axp, Ayp, Azp), logicals):
        n = (logical or tuple(A.shape))[2]
        lo = _get_plane(A, 0, 2, width)
        hi = _get_plane(A, n - width, 2, width)
        packed.append(_pack_z_patch(lo, hi, width))
    return (_pack_cz(packed[0], packed[3]), packed[1], packed[2])


def apply_z_patches(C, Axp, Ayp, Azp, patches, *, width: int = 1):
    """Write packed z patches into the arrays (the chunk-end restoration).

    One whole-array `dynamic-update-slice` pass per field — paid once per
    CHUNK (the in-kernel application covers every group in between), so the
    relayout cost amortizes over ``nsteps``."""
    w = width
    patch_cz, patch_x, patch_y = patches
    per_field = (
        patch_cz,
        patch_x,
        patch_y,
        patch_cz[:, :, Z_CZ_BAND : Z_CZ_BAND + 2 * w],
    )
    logicals = _padded_logicals(C, Axp, Ayp, Azp)
    out = []
    for A, logical, patch in zip((C, Axp, Ayp, Azp), logicals, per_field):
        n = (logical or tuple(A.shape))[2]
        A = _set_plane(A, patch[:, :, :w], 0, 2)
        A = _set_plane(A, patch[:, :, w : 2 * w], n - w, 2)
        out.append(A)
    return tuple(out)


def update_halo_padded_faces(C, Axp, Ayp, Azp, *, width: int = 1, dims=None,
                             coalesce=None):
    """Slab-exchange a cell field + three `pad_faces`-layout staggered fields.

    The models' fused deep-halo cadences keep the staggered fields in the
    kernel's padded layout across a whole chunk; exchanging them directly
    (with slab indices computed from the REAL ``n+1`` shapes via the
    ``logical`` override of `_exchange_dim`) removes the two HBM passes per
    field per group an unpad/re-pad pair would cost.  Owned results are
    bitwise identical to unpad→`update_halo`→pad: the same real planes
    move; only the junk tail differs (it receives exchanged junk instead of
    zeros, and the layout's contract already forbids reading it).

    ``dims``: restrict the exchange to these dimensions (default all) — the
    z-patch cadence exchanges x/y here and routes z through `z_slab_patches`
    into the kernel.  ``coalesce``: the four fields' collectives combine
    into one permute pair per (dimension, dtype width) by default
    (`exchange_dims_multi`; ``IGG_COALESCE=0`` restores per-field hops).

    Tracer-context only (inside `stencil`/shard_map — where the fused block
    steps live); the public `update_halo` remains the global-array entry.
    """
    logicals = _padded_logicals(C, Axp, Ayp, Azp)
    return exchange_dims_multi(
        (C, Axp, Ayp, Azp),
        tuple(range(NDIMS)) if dims is None else dims,
        width=width,
        logicals=logicals,
        coalesce=coalesce,
    )


def _exchange_slab_bytes(fields, gg, width: int) -> int:
    """Per-call slab traffic of a global-array exchange, in bytes.

    For every field and every dimension that actually exchanges, two
    ``width``-deep slabs (one per side) are written into the halo planes —
    ``2 * width * plane_bytes`` per field per active dim.  Host-side shape
    math only (no device work); self-copies count (they move the same
    bytes), PROC_NULL keep-old planes of edge blocks are included (the
    per-block census is not knowable host-side without extra collectives),
    so this is the upper-bound slab payload the program was built to move.
    """
    total = 0
    for A in fields:
        shp = local_shape(A, gg)
        itemsize = np.dtype(A.dtype).itemsize
        n = int(np.prod(shp))
        for d in range(min(len(shp), NDIMS)):
            if not dim_has_halo_activity(gg, d):
                continue
            if ol(d, shape=shp, gg=gg) < 2:
                continue
            total += 2 * width * (n // shp[d]) * itemsize
    return total


def _default_donate() -> bool:
    """``IGG_DONATE`` env default for `update_halo`'s global-array entry.

    Donation makes the exchange buffer-in-place like the reference's mutating
    API (no extra allocation) and is the right default on production
    runtimes; some runtimes pay a large runtime-side penalty for donated
    buffers (the tunneled single-chip bench backend measures ~3x,
    docs/performance.md) — ``IGG_DONATE=0`` turns it off globally, the
    per-call ``donate=`` kwarg overrides both.
    """
    from ..utils.config import _int_env

    val = _int_env("IGG_DONATE")
    return True if val is None else val > 0


def _global_update_fn(gg, shapes_dtypes, width: int = 1, donate: bool = True,
                      coalesce: bool = True):
    """Build (and cache) the jitted shard_map wrapper for one field signature."""
    import jax
    from jax.sharding import PartitionSpec as P

    key = (gg.epoch, shapes_dtypes, width, donate, coalesce)
    fn = _jit_cache.get(key)
    if fn is not None:
        return fn
    ndims_per_field = tuple(len(s) for s, _ in shapes_dtypes)
    dn = tuple(range(len(ndims_per_field))) if donate else ()

    def exchange(*fields):
        return _update_halo_local(fields, gg, width, coalesce)

    if gg.nprocs == 1 and not gg.force_spmd:
        # 1-device grid: only self-neighbor local copies remain (no ppermute,
        # no axis environment) — plain jit avoids the SPMD execution path.
        fn = jax.jit(exchange, donate_argnums=dn)
        _jit_cache[key] = fn
        return fn

    from ..utils.compat import shard_map

    specs = tuple(P(*AXIS_NAMES[:nd]) for nd in ndims_per_field)
    mapped = shard_map(
        exchange, mesh=gg.mesh, in_specs=specs, out_specs=specs, check_vma=False
    )
    fn = jax.jit(mapped, donate_argnums=dn)
    _jit_cache[key] = fn
    return fn


def _integrity_update_fn(gg, shapes_dtypes, width: int, donate: bool,
                         coalesce: bool, flip: int | None):
    """The checksummed twin of `_global_update_fn` (``IGG_INTEGRITY=1``).

    The exchange builds under an active `TransportCollector`, so every hop's
    wire buffer carries an XOR-fold checksum word (`_packed_transport`) and
    the per-hop mismatch flags escape as one extra per-block ``(1, 1, 1,
    nhops, 2)`` int32 output, out-spec sharded over the mesh — the host
    entry reads its OWN blocks' verdicts from addressable shards, no extra
    collective.  Cached per (epoch, signature, width, donate, coalesce,
    flip): an armed transport flip bakes a DIFFERENT program, so a chaos
    injection never poisons the clean entry.  Returns ``(fn, collector)``;
    the collector's trace-order records label the flag rows.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from ..integrity import transport as _itransport
    from ..utils.compat import shard_map

    key = (gg.epoch, shapes_dtypes, width, donate, coalesce, flip)
    hit = _integrity_jit_cache.get(key)
    if hit is not None:
        return hit
    ndims_per_field = tuple(len(s) for s, _ in shapes_dtypes)
    dn = tuple(range(len(ndims_per_field))) if donate else ()
    col = _itransport.TransportCollector()

    def exchange(*fields):
        # A retrace rebuilds the records/flags and re-arms the baked flip
        # (trace-time collector state must match the program every time).
        col.records.clear()
        col.flags.clear()
        col.flip_proc = flip
        with _itransport.use_collector(col):
            out = _update_halo_local(fields, gg, width, coalesce)
        return tuple(out) + (col.stacked_flags()[None, None, None],)

    specs = tuple(P(*AXIS_NAMES[:nd]) for nd in ndims_per_field)
    mapped = shard_map(
        exchange, mesh=gg.mesh, in_specs=specs,
        out_specs=specs + (P(*AXIS_NAMES, None, None),), check_vma=False,
    )
    fn = jax.jit(mapped, donate_argnums=dn)
    _integrity_jit_cache[key] = (fn, col)
    return fn, col


def _check_transport_flags(gg, col, flags) -> None:
    """Rank-local verdict of one checksummed exchange.

    Scans the flag blocks THIS process hosts; any nonzero entry names a hop
    whose landed payload contradicts its checksum word.  Escalation is a
    LOCAL raise plus the out-of-band ``reason=sdc`` flight bundle
    implicating the SENDER (the wire buffer is the sender's slab until it
    lands, so a mismatch at the receiver indicts the sending rank/link) —
    never a collective: a rank-local integrity verdict driving a collective
    is the SPMD-divergence class `analysis.collectives` exists to catch.
    """
    from ..integrity.errors import IntegrityError
    from ..parallel import topology
    from ..utils import telemetry as _telemetry
    from ..utils import tracing as _tracing

    for shard in flags.addressable_shards:
        arr = np.asarray(shard.data)
        if not arr.size or not arr.any():
            continue
        coords = tuple(
            int(sl.start or 0) for sl in tuple(shard.index)[:NDIMS]
        )
        nbrs = topology.neighbors_table(
            coords, gg.dims, gg.periods, int(gg.disp)
        )
        hop, side = (
            int(i) for i in np.argwhere(arr.reshape(arr.shape[-2:]))[0]
        )
        rec = col.records[hop] if hop < len(col.records) else {}
        dim = int(rec.get("dim", -1))
        # flag column 0 = the lo receive (sent by my LOWER partner), column
        # 1 = the hi receive (sent by my upper partner) — `_permute_slabs`
        direction = "lo" if side == 0 else "hi"
        sender = int(nbrs[side, dim]) if dim >= 0 else -1
        fields = tuple(rec.get("fields", ()))
        _telemetry.counter("integrity.transport_mismatches").inc()
        _telemetry.event(
            "integrity.transport_mismatch", detector="transport_checksum",
            dim=dim, direction=direction, fields=list(fields),
            block=list(coords), implicated_rank=sender,
        )
        _tracing.dump_flight_recorder(
            "sdc", detector="transport_checksum", implicated_rank=sender,
            dim=dim, direction=direction, fields=list(fields),
            block=list(coords),
        )
        raise IntegrityError(
            f"halo transport checksum mismatch: dim {dim} ({direction} "
            f"receive) at block {coords} — the landed payload contradicts "
            f"its checksum word; implicating sender rank {sender}. A finite "
            f"bit flip in flight passes every NaN guard; quarantine the "
            f"implicated device (docs/robustness.md), do not restart in "
            f"place.",
            detector="transport_checksum", implicated_rank=sender,
            dim=dim, direction=direction, fields=fields,
        )


def update_halo(*fields, width: int = 1, donate: bool | None = None,
                coalesce: bool | None = None):
    """Update the halo planes of the given field(s).

    TPU-native counterpart of `update_halo!` (`/root/reference/src/update_halo.jl:25-78`).
    Functional: returns the updated field(s) — a single array for one argument,
    a tuple for several.  Pass all fields of a time step in one call so XLA
    compiles one fused program (the reference's pipelining advice,
    `/root/reference/src/update_halo.jl:13-14`) — and so their collectives
    COALESCE: by default every field's send slab packs into one flat buffer
    per dtype byte width and each exchanged dimension issues ONE
    `collective-permute` pair per width group instead of one per field
    (message combining; bit-identical — the transport bitcasts to same-width
    unsigned ints, like the chunked gather).  ``coalesce=False`` (or
    ``IGG_COALESCE=0``) restores per-field collectives; ``coalesce=None``
    takes the env default (auto: combine whenever >= 2 fields share a
    dimension's exchange).

    ``width``: halo planes refreshed per side (default 1 = the reference's
    exchange).  ``width=w`` on a deep-halo grid (``overlap >= 2w``) refreshes
    ``w`` planes in one collective, licensing ``w`` stencil steps between
    exchanges (temporal blocking, `make_multi_step(fused_k=w)`): the
    per-hop latency of the exchange amortizes over ``w`` steps.

    ``donate`` (global-array calls only): donate the inputs so the update is
    buffer-in-place like the reference's mutating API.  Default from the
    ``IGG_DONATE`` env var, else True; pass ``donate=False`` (or set
    ``IGG_DONATE=0``) on runtimes where donation is slow — the tunneled
    single-chip bench backend measures ~3x (docs/performance.md) — or when
    the caller reuses the passed-in arrays.  Inside a traced context the
    flag is ignored: buffer lifetime belongs to the enclosing program.
    """
    import jax

    _grid.check_initialized()
    gg = _grid.global_grid()
    if not fields:
        raise ValueError("update_halo requires at least one field.")
    if width < 1:
        raise ValueError(f"width must be >= 1 (got {width})")
    _validate_fields(fields, gg)
    if any(_is_tracer(A) for A in fields):
        if not all(_is_tracer(A) for A in fields):
            # A concrete global-block array mixed into a traced (local-view)
            # call would be exchanged at global indices — always a bug.
            raise ValueError(
                "update_halo inside a stencil/shard_map context requires all "
                "fields to be local-block tracers; pass captured global-block "
                "fields as arguments of the stencil function instead."
            )
        out = _update_halo_local(tuple(fields), gg, width, coalesce)
    else:
        from jax.sharding import NamedSharding, PartitionSpec as P

        arrs = []
        for A in fields:
            if not isinstance(A, jax.Array):
                spec = P(*AXIS_NAMES[: np.ndim(A)])
                A = jax.device_put(np.asarray(A), NamedSharding(gg.mesh, spec))
            arrs.append(A)
        sig = tuple((local_shape(A, gg), str(A.dtype)) for A in arrs)
        if donate is None:
            donate = _default_donate()
        if coalesce is None:
            coalesce = _default_coalesce()
        from ..utils import config as _config
        from ..utils import telemetry as _telemetry
        from ..utils import tracing as _tracing

        # Transport checksums (docs/robustness.md): host-side resolution,
        # like IGG_DONATE/IGG_COALESCE — the traced paths never read the
        # env (knob-binding lint).  Only communicating grids have a wire
        # to checksum.
        integrity = (
            _config.integrity_enabled_env() is True
            and (gg.nprocs > 1 or gg.force_spmd)
        )
        flip = _take_transport_flip() if integrity else None

        if _telemetry.enabled():
            # Runtime counters (the global-array entry runs host-side per
            # call, unlike the traced paths — docs/observability.md).
            nbytes = _exchange_slab_bytes(arrs, gg, width)
            _telemetry.counter("halo.exchanges").inc()
            _telemetry.counter("halo.fields").inc(len(arrs))
            _telemetry.counter("halo.bytes").inc(nbytes)
            _telemetry.histogram("halo.slab_bytes").record(nbytes)
        # Host span named like the device-side annotation
        # (`named_scope("igg_halo_exchange")` inside the compiled program),
        # so the merged trace and a profiler capture correlate by name.
        with _tracing.trace_span(
            "igg_halo_exchange", fields=len(arrs), width=width
        ):
            if integrity:
                fn, col = _integrity_update_fn(
                    gg, sig, width, bool(donate), bool(coalesce), flip
                )
                *out, flags = fn(*arrs)
                _check_transport_flags(gg, col, flags)
            else:
                out = _global_update_fn(
                    gg, sig, width, bool(donate), bool(coalesce)
                )(*arrs)
        if _post_exchange_hook is not None:
            out = tuple(_post_exchange_hook(tuple(out)))
    return out[0] if len(fields) == 1 else tuple(out)
