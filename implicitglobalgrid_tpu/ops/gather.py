"""Root gather of a field for in-situ visualization / monitoring.

TPU-native counterpart of `gather!` (`/root/reference/src/gather.jl:14-66`).
The reference hand-rolls a gather over `MPI_Isend/Irecv` with a persistent
grow-only staging buffer and reassembles rank blocks into ``A_global`` in
Cartesian block order.  Here the field *is already* the block-ordered global
array (one block per device), so:

* single process: gather is a host transfer (`jax.device_get`) — no
  collective at all;
* multi-host: the non-addressable shards are fetched with
  `multihost_utils.process_allgather` (XLA all-gather over DCN/ICI), and only
  the root process returns data.

Like the reference, no halo de-duplication is performed — the result is the
blocks side by side; strip halos first with `block_slice` if needed
(the reference's examples do exactly that on the caller side,
`/root/reference/examples/diffusion3D_multigpu_CuArrays.jl:53-54`).
"""

from __future__ import annotations

import numpy as np

from ..parallel import grid as _grid


def gather(A, A_global=None, *, root: int = 0):
    """Gather field ``A`` to the host on process ``root``.

    Returns the assembled numpy array on the root process and ``None`` on all
    other processes.  If ``A_global`` (a numpy array of matching size and
    dtype) is given, it is filled in place on the root and ``None`` is
    returned — the reference's ``gather!(A, A_global)`` signature.

    Collective: on a multi-process runtime EVERY process must make this call
    (non-roots pass ``A_global=None``), exactly like the reference where
    non-root ranks send (`/root/reference/src/gather.jl:33-36`); a root-only
    call deadlocks in the underlying all-gather.
    """
    import jax

    _grid.check_initialized()
    gg = _grid.global_grid()
    if not (0 <= root < jax.process_count()):
        # Reference tests gather with non-default roots
        # (`/root/reference/test/test_gather.jl:126-137`); an out-of-range
        # root would silently return None everywhere, so fail loudly.
        raise ValueError(
            f"root must be a valid process index in [0, {jax.process_count()}); "
            f"got {root}."
        )

    if isinstance(A, jax.Array) and not A.is_fully_addressable:
        from jax.experimental import multihost_utils

        data = np.asarray(multihost_utils.process_allgather(A, tiled=True))
    else:
        data = np.asarray(jax.device_get(A))

    if jax.process_index() != root:
        return None
    if A_global is not None:
        if A_global.size != data.size:
            # Error contract from /root/reference/src/gather.jl:39 (local length
            # = global length / nprocs in the global-block representation).
            raise ValueError(
                "The input argument A_global must be of length nprocs*length(A)"
            )
        if A_global.dtype != data.dtype:
            raise ValueError(
                f"A_global has dtype {A_global.dtype} but A has dtype {data.dtype}; "
                "they must match."
            )
        np.copyto(A_global.reshape(data.shape), data)
        return None
    return data
