"""Root gather of a field for in-situ visualization / monitoring.

TPU-native counterpart of `gather!` (`/root/reference/src/gather.jl:14-66`).
The reference hand-rolls a gather over `MPI_Isend/Irecv` with a persistent
grow-only staging buffer and reassembles rank blocks into ``A_global`` in
Cartesian block order — its whole design exists so that ONLY the root pays
global-array memory (`/root/reference/src/gather.jl:33-46`: non-roots Isend
their local block; the root assembles block by block).  Here the field *is
already* the block-ordered global array (one block per device), so:

* single process: gather is a host transfer (`jax.device_get`) — no
  collective at all;
* multi-host: blocks are fetched a small BATCH at a time with a compiled
  masked all-reduce (`_block_fetch_fn`; batch size `_gather_batch_size`,
  default 8, env ``IGG_GATHER_BATCH``) and placed into the output
  immediately on the root; non-root processes never fetch anything to the
  host.  Batching amortizes the host-synchronized dispatch of the
  ``prod(dims)`` sequential collectives a pod-scale gather performs.
  Per-process memory bound (matching the reference's root-only design): the
  root holds the assembled global array plus one staged batch of blocks;
  every other process pays ZERO extra host bytes and one transient batch
  per device — never the global array.  The round-4 implementation
  (`process_allgather(tiled=True)`) materialized the full global array on
  EVERY process, which at pod scale (512^3 f32 x 256 chips ~ 137 GB) OOMs
  every host; this path replaces it.

Like the reference, no halo de-duplication is performed by default — the
result is the blocks side by side; strip halos first with `block_slice` if
needed (the reference's examples do exactly that on the caller side,
`/root/reference/examples/diffusion3D_multigpu_CuArrays.jl:53-54`), or pass
``dedup=True`` for the owner-wise de-duplicated ``nxyz_g`` view
(`assemble_dedup` — the same block-assembly rule the elastic checkpoint
restore reshards with).
"""

from __future__ import annotations

import numpy as np

from ..parallel import grid as _grid
from ..parallel.topology import AXIS_NAMES

_fetch_cache: dict = {}

#: Instrumentation for tests (VERDICT r4 #1 done-criterion: prove non-roots
#: never hold the assembled array).  Set by every `gather` call:
#: ``path`` in {"local", "chunked"}, ``host_bytes`` = bytes this process
#: fetched to host memory, ``fetches`` = number of per-block collectives.
#: Compat alias of the telemetry registry (``gather.*`` metrics,
#: docs/observability.md): treat it as a READ-ONLY view of the LAST call —
#: it is reset to ``None`` at the START of every gather, so a failed gather
#: can never leave the previous call's stats lying around.
last_gather_stats: dict | None = None


def _record_stats(stats: dict) -> None:
    """Publish one gather's stats: the compat global + the registry fold."""
    global last_gather_stats
    last_gather_stats = stats
    from ..utils import telemetry as _telemetry

    if not _telemetry.enabled():
        return
    _telemetry.counter("gather.calls").inc()
    _telemetry.counter(f"gather.calls.{stats['path']}").inc()
    _telemetry.counter("gather.fetches").inc(stats.get("fetches", 0))
    _telemetry.counter("gather.host_bytes").inc(stats.get("host_bytes", 0))
    _telemetry.histogram("gather.call_host_bytes").record(
        stats.get("host_bytes", 0)
    )


def _clear_caches() -> None:
    _fetch_cache.clear()


def _telemetry_member_inc() -> None:
    """Fold a member-sliced gather into the registry (``gather.*`` family:
    the member path is the same gather, plus this attribution counter)."""
    from ..utils import telemetry as _telemetry

    if _telemetry.enabled():
        _telemetry.counter("gather.member_calls").inc()


def _block_fetch_fn(gg, ndim: int, block_shape, dtype, nsel: int = 1):
    """Compiled block fetch: replicate blocks ``sels`` onto every device.

    One masked all-reduce: the owning devices contribute their local
    blocks, everyone else zeros, `psum` over the field's mesh axes
    replicates the batch.  This is the memory-scalable primitive behind the
    multi-host gather — device transient = ``nsel`` blocks, host transient
    = ``nsel`` blocks on the root only (vs `process_allgather`'s full
    global array everywhere).  The block indices ``sels`` (an ``(nsel,)``
    vector) are traced, so all batches of one size share one executable;
    ``nsel > 1`` amortizes the per-dispatch host sync of the chunked
    gather over several blocks per collective (`_gather_batch`).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    key = (gg.epoch, ndim, tuple(block_shape), str(dtype), int(nsel))
    fn = _fetch_cache.get(key)
    if fn is not None:
        return fn
    axes = AXIS_NAMES[:ndim]
    dims = gg.dims[:ndim]

    def local(a, sels):
        my = jnp.int32(0)
        for ax, nd in zip(axes, dims):
            my = my * nd + lax.axis_index(ax)
        # Bitcast to unsigned integers around the transport: gather is a
        # byte-copy in the reference (MPI) and must stay byte-exact here,
        # but a float psum maps -0.0 + 0.0 to +0.0.  Integer addition with
        # zeros preserves every bit pattern.  Complex cannot bitcast
        # directly: split into a trailing real/imag float axis first (each
        # component then round-trips bit-exactly).
        cplx = jnp.issubdtype(a.dtype, jnp.complexfloating)
        if cplx:
            a = jnp.stack((a.real, a.imag), axis=-1)
        bits = lax.bitcast_convert_type(a, _word_dtype(a.dtype))
        # One leading batch axis, masked per selected block; a block
        # appears at the batch slot(s) whose sel it owns.
        mask = (sels == my).reshape((nsel,) + (1,) * bits.ndim)
        contrib = jnp.where(mask, bits[None], jnp.zeros_like(bits)[None])
        # psum over the field's own axes only: fields of lower rank than the
        # mesh are replicated over the remaining axes, and summing those
        # would multiply the block by the replica count.
        out = lax.bitcast_convert_type(lax.psum(contrib, axes), a.dtype)
        if cplx:
            out = lax.complex(out[..., 0], out[..., 1]).astype(jnp.dtype(dtype))
        return out

    from ..utils.compat import shard_map

    mapped = shard_map(
        local,
        mesh=gg.mesh,
        in_specs=(P(*axes), P()),
        out_specs=P(*([None] * (ndim + 1))),
        check_vma=False,
    )
    fn = jax.jit(mapped, out_shardings=NamedSharding(gg.mesh, P()))
    _fetch_cache[key] = fn
    return fn


def _word_dtype(dtype):
    """Same-width unsigned integer type for a byte-exact bitcast of
    ``dtype`` (complex never reaches here — `_block_fetch_fn` pre-splits it
    into real/imag float components)."""
    import jax.numpy as jnp

    return jnp.dtype(f"uint{8 * jnp.dtype(dtype).itemsize}")


def _gather_batch_size() -> int:
    """Blocks fetched per compiled dispatch in `_gather_chunked`.

    At pod scale the chunked gather's cost is ``prod(dims)`` sequential
    host-synchronized collectives; batching ``B`` blocks per dispatch
    amortizes the per-dispatch sync ``B``-fold while the root's transient
    grows to ``B`` blocks (still nowhere near the full global array) and
    non-roots keep paying ZERO host bytes.  ``IGG_GATHER_BATCH`` overrides
    (min 1); the default 8 keeps the root transient below one typical
    block-row.
    """
    from ..utils.config import gather_batch_env

    val = gather_batch_env()
    return max(int(val), 1) if val is not None else 8


def collective_plan(dims, batch, *, is_root: bool = False):
    """Ordered collective dispatch schedule of one chunked gather.

    Returns ``[("block_fetch", (sel, ...)), ...]`` — one record per compiled
    fetch dispatch, each carrying the linearized block indices it
    replicates.  This is the single source of `_gather_chunked`'s loop
    shape, extracted so the schedule is a *checkable artifact*: the PR-1
    ~50%-flaky hang was exactly non-root processes running a different
    in-flight collective schedule than the root, and the fix's invariant —
    EVERY process issues the identical dispatch sequence — is now asserted
    statically by ``igg.analysis``'s collective-consistency detector, which
    evaluates this plan for every simulated rank and requires equality.

    ``is_root`` is deliberately accepted AND ignored: the parameter exists
    so the detector can prove the schedule cannot depend on it (root-ness
    may only affect host-side assembly of fetched results, never the
    collective order).  Do not branch on it here.
    """
    del is_root  # the invariant: the plan is rank-independent
    idxs = list(np.ndindex(*tuple(dims))) or [()]
    b = min(max(int(batch), 1), len(idxs))
    plan = []
    for start in range(0, len(idxs), b):
        chunk = idxs[start : start + b]
        plan.append(
            (
                "block_fetch",
                tuple(
                    int(np.ravel_multi_index(idx, dims)) if idx else 0
                    for idx in chunk
                ),
            )
        )
    return plan


def _gather_chunked(A, gg, out: np.ndarray | None, dedup: bool = False):
    """Batched block-by-block multi-host assembly (root-only memory bound).

    Collective: every process iterates the same batch sequence (the
    reference's non-roots likewise all participate by sending,
    `/root/reference/src/gather.jl:33-36`), as pinned by `collective_plan`.
    The root (the one process with ``out is not None``) places each batch's
    blocks as they arrive; the replicated device copy is dropped before the
    next fetch.
    """
    import jax

    ndim = A.ndim
    bshape = _local_shape(A, gg)
    dims = gg.dims[:ndim]
    plan = collective_plan(dims, _gather_batch_size(), is_root=out is not None)
    nblocks = sum(len(sels) for _, sels in plan)
    batch = len(plan[0][1])
    host_bytes = 0
    nfetch = 0
    for _op, sels_t in plan:
        chunk = [
            tuple(int(c) for c in np.unravel_index(s, dims)) if ndim else ()
            for s in sels_t
        ]
        sels = np.asarray(sels_t, np.int32)
        # At most two executables total: the full batch size and one ragged
        # tail size (both cached in `_fetch_cache`).
        fetch = _block_fetch_fn(gg, ndim, bshape, A.dtype, nsel=len(chunk))
        blk = fetch(A, sels)
        # EVERY process completes each fetch before dispatching the next —
        # not just the root (whose host copy syncs implicitly).  Without
        # this, non-roots enqueue all fetches asynchronously: many identical
        # collectives in flight, which (a) starves the single-core CPU
        # mesh's rendezvous and (b) can cross-match on transports without
        # per-op channels (observed as intermittent wrong fill-in-place
        # gathers under the gloo backend — the root's assembled bytes mixed
        # blocks).  One outstanding collective per process is also what the
        # docstring's memory bound promises.
        jax.block_until_ready(blk)
        if out is not None:  # the root, assembling (see `gather`)
            data = np.asarray(blk.addressable_shards[0].data)
            if dedup:
                assemble_dedup(
                    {idx: data[j] for j, idx in enumerate(chunk)},
                    bshape,
                    dims,
                    _field_ols(gg, bshape),
                    gg.periods[:ndim],
                    data.dtype,
                    out=out,
                )
            else:
                for j, idx in enumerate(chunk):
                    out[
                        tuple(slice(c * b, (c + 1) * b) for c, b in zip(idx, bshape))
                    ] = data[j]
            host_bytes += data.nbytes
            del data
        del blk
        nfetch += 1
    _record_stats(
        {
            "path": "chunked",
            "host_bytes": host_bytes,
            "fetches": nfetch,
            "blocks": nblocks,
            "batch": batch,
            "block_bytes": int(np.prod(bshape)) * np.dtype(A.dtype).itemsize,
        }
    )
    return out


def _local_shape(A, gg):
    from .halo import local_shape

    return local_shape(A, gg)


# -- De-duplicated (owner-wise) block assembly --------------------------------
#
# The global-block representation stores overlap cells redundantly (blocks
# side by side, like the reference's per-process local arrays); these helpers
# assemble the DE-DUPLICATED global grid from per-block arrays by giving each
# global cell to exactly one owning block.  Shared by `gather(dedup=True)`
# and the elastic checkpoint restore (`utils.checkpoint.restore_checkpoint`
# resharding a checkpoint onto a different topology) — one ownership rule,
# so the two paths cannot disagree about which copy of an overlap cell wins.


def owned_range(c: int, nblocks: int, size: int, ol: int, periodic: bool) -> tuple[int, int]:
    """Local index range ``[a, b)`` of the cells block ``c`` owns in one dim.

    Adjacent blocks share ``ol`` overlap cells; the midpoint split gives the
    first ``ceil(ol/2)`` to the left block and the rest to the right one —
    the partition that keeps every owned cell as deep inside its block as
    possible (most robust choice when outer halo planes are the stalest
    data in a deep-halo schedule).  Grid-edge cells of a non-periodic dim
    belong to the edge block whole; under periodicity every block has both
    neighbors, and the wrap seam follows the same midpoint rule.
    """
    if ol < 0:
        raise ValueError(
            f"owned_range: negative overlap {ol} — blocks would leave gaps; "
            f"this field does not follow the halo size convention."
        )
    a = 0 if (c == 0 and not periodic) else ol - ol // 2
    b = size if (c == nblocks - 1 and not periodic) else size - ol // 2
    return a, b


def dedup_length(nblocks: int, size: int, ol: int, periodic: bool) -> int:
    """De-duplicated global extent of one dim: ``nblocks*(size-ol)`` plus the
    boundary overlap when the dim is not periodic (the nxyz_g formula,
    applied to an arbitrary per-field local ``size``)."""
    return nblocks * (size - ol) + (0 if periodic else ol)


def dedup_indices(c: int, lo: int, hi: int, size: int, ol: int, glen: int) -> np.ndarray:
    """Global de-dup indices of block ``c``'s local cells ``[lo, hi)`` in one
    dim.  Local cell ``j`` of block ``c`` is global cell ``(c*(size-ol) + j)
    mod glen`` — the modulo realizes the periodic wrap (a halo cell past the
    seam aliases the cell at the far side)."""
    return (c * (size - ol) + np.arange(lo, hi)) % glen


def assemble_dedup(
    blocks, bshape, dims, ols, periods, dtype, out: np.ndarray | None = None
) -> np.ndarray:
    """Assemble the de-duplicated global array from ``{coords: block}``.

    ``blocks`` maps Cartesian block coordinates (tuples of length ndim) to
    per-block numpy arrays of shape ``bshape``; ``dims``/``ols``/``periods``
    are per-dim block counts, overlaps and periodicity flags (each clipped
    to the field's rank by the caller).  Every global cell is written from
    its OWNING block only (`owned_range`), so stale outer halo planes can
    never overwrite an owner's value.
    """
    gshape = tuple(
        dedup_length(d, s, o, bool(p))
        for d, s, o, p in zip(dims, bshape, ols, periods)
    )
    if out is None:
        out = np.empty(gshape, dtype)
    for coords, block in blocks.items():
        sel = []
        idxs = []
        for dim, c in enumerate(coords):
            a, b = owned_range(
                c, dims[dim], bshape[dim], ols[dim], bool(periods[dim])
            )
            sel.append(slice(a, b))
            idxs.append(
                dedup_indices(c, a, b, bshape[dim], ols[dim], gshape[dim])
            )
        out[np.ix_(*idxs)] = block[tuple(sel)]
    return out


def _field_ols(gg, bshape) -> tuple[int, ...]:
    """Per-dim overlap of a field with local shape ``bshape`` (shape-aware:
    staggered ``n+1`` fields carry overlap+1, reference src/shared.jl:93)."""
    from .halo import ol as _ol

    return tuple(
        _ol(d, shape=bshape, gg=gg) for d in range(len(bshape))
    )


def dedup_shape(A, gg=None) -> tuple[int, ...]:
    """De-duplicated global shape of field ``A`` (``nxyz_g`` adjusted for the
    field's own stagger/rank)."""
    if gg is None:
        gg = _grid.global_grid()
    bshape = _local_shape(A, gg)
    ols = _field_ols(gg, bshape)
    return tuple(
        dedup_length(gg.dims[d], bshape[d], ols[d], bool(gg.periods[d]))
        for d in range(len(bshape))
    )


def gather(
    A,
    A_global=None,
    *,
    root: int = 0,
    dedup: bool = False,
    member: int | None = None,
    _force_chunked: bool = False,
):
    from ..utils import tracing as _tracing

    with _tracing.trace_span("igg.gather", root=root, dedup=dedup):
        return _gather(
            A, A_global, root=root, dedup=dedup, member=member,
            _force_chunked=_force_chunked,
        )


def _gather(
    A,
    A_global=None,
    *,
    root: int = 0,
    dedup: bool = False,
    member: int | None = None,
    _force_chunked: bool = False,
):
    """Gather field ``A`` to the host on process ``root``.

    ``member=k`` gathers ONE ensemble member of a BATCHED field (leading
    batch axis, `models._batched`): member ``k`` is sliced on device first
    (`member_field` — a per-device slice, so neither the root nor anyone
    else ever materializes the other B-1 members), then the ordinary
    gather path runs on the 3-D slice, folding its stats into the same
    ``gather.*`` telemetry counters.  A batched field without ``member``
    is rejected: its leading axis would be misread as grid dimension x.

    Returns the assembled numpy array on the root process and ``None`` on all
    other processes.  If ``A_global`` (a numpy array of matching size and
    dtype) is given, it is filled in place on the root and ``None`` is
    returned — the reference's ``gather!(A, A_global)`` signature.

    ``dedup=True`` returns the DE-DUPLICATED global grid (shape
    `dedup_shape(A)`, the ``nxyz_g`` view) instead of the blocks side by
    side: every overlap cell comes from its owning block (`owned_range`) —
    the halo-stripping the reference's examples hand-roll caller-side, and
    the representation in which fields from DIFFERENT topologies of the
    same global problem are comparable (the elastic-restart oracle).

    Collective: on a multi-process runtime EVERY process must make this call
    (non-roots pass ``A_global=None``), exactly like the reference where
    non-root ranks send (`/root/reference/src/gather.jl:33-36`); a root-only
    call deadlocks in the underlying collectives.  A root-side ``A_global``
    argument error is therefore raised only AFTER the root has participated
    in (and discarded) every fetch — non-roots cannot observe the root's
    buffer, so raising before the collectives would leave them blocked in
    the first `psum` forever.

    Memory bound (multi-host): root = global array + one block; non-root =
    no extra host memory, one transient block per device.  See the module
    docstring; ``_force_chunked`` routes even a fully-addressable field
    through the multi-host block path (test hook).
    """
    import jax

    _grid.check_initialized()
    gg = _grid.global_grid()
    from ..parallel.topology import NDIMS as _NDIMS

    if member is not None:
        from ..models._batched import member_field

        if np.ndim(A) <= _NDIMS:
            # gather legitimately accepts rank-1/2/3 fields on the 3-D grid,
            # so a rank <= NDIMS array here is an ORDINARY grid field — with
            # member= it would be silently misread (grid axis x sliced off
            # as the "ensemble"); batched model fields are rank NDIMS+1.
            raise ValueError(
                f"gather(member={member}) needs a batched field (leading "
                f"ensemble axis over grid-rank blocks, i.e. rank > "
                f"{_NDIMS}); got rank {np.ndim(A)} — an unbatched grid "
                f"field: drop member=."
            )
        B = int(np.shape(A)[0])
        if not (0 <= int(member) < B):
            raise ValueError(
                f"member must be in [0, {B}) for this batched field; got "
                f"{member}."
            )
        A = member_field(A, int(member))
        _telemetry_member_inc()
    elif np.ndim(A) > _NDIMS:
        raise ValueError(
            f"gather got a rank-{np.ndim(A)} field but the grid has "
            f"{_NDIMS} dimensions; for a batched ensemble field pass "
            f"member=k to gather one member (the leading axis is the "
            f"ensemble, not grid dimension x)."
        )
    # Reset FIRST: a gather that fails (or deadlocks and is restarted) must
    # not leave the previous call's stats lying around as if they were its
    # own — `last_gather_stats` is only ever the LAST COMPLETED call's view.
    global last_gather_stats
    last_gather_stats = None
    if not (0 <= root < jax.process_count()):
        # Reference tests gather with non-default roots
        # (`/root/reference/test/test_gather.jl:126-137`); an out-of-range
        # root would silently return None everywhere, so fail loudly.
        raise ValueError(
            f"root must be a valid process index in [0, {jax.process_count()}); "
            f"got {root}."
        )

    chunked = _force_chunked or (
        isinstance(A, jax.Array) and not A.is_fully_addressable
    )
    is_root = jax.process_index() == root

    if chunked:
        bshape = _local_shape(A, gg)
        if dedup:
            gshape = tuple(
                dedup_length(d, b, o, bool(p))
                for d, b, o, p in zip(
                    gg.dims[: A.ndim],
                    bshape,
                    _field_ols(gg, bshape),
                    gg.periods[: A.ndim],
                )
            )
        else:
            gshape = tuple(d * b for d, b in zip(gg.dims[: A.ndim], bshape))
        gsize = int(np.prod(gshape))
        # A root-side argument error must not strand non-roots mid-collective
        # (see docstring): on invalid A_global the root still participates in
        # every fetch (assembling nothing) and raises afterwards.
        err = None
        out = None
        if is_root:
            if A_global is not None:
                try:
                    _check_out(A_global, gsize, np.dtype(A.dtype))
                except ValueError as e:
                    err = e
                else:
                    out = A_global.reshape(gshape)
            else:
                out = np.empty(gshape, np.dtype(A.dtype))
        out = _gather_chunked(A, gg, out, dedup=dedup)
        if err is not None:
            raise err
        if not is_root or A_global is not None:
            return None
        return out

    data = np.asarray(jax.device_get(A))
    _record_stats(
        {
            "path": "local",
            "host_bytes": data.nbytes,
            "fetches": 0,
            "block_bytes": data.nbytes,
        }
    )
    if not is_root:
        return None
    if dedup:
        bshape = _local_shape(A, gg)
        dims = gg.dims[: A.ndim]
        blocks = {
            idx: data[
                tuple(slice(c * b, (c + 1) * b) for c, b in zip(idx, bshape))
            ]
            for idx in (list(np.ndindex(*dims)) or [()])
        }
        data = assemble_dedup(
            blocks,
            bshape,
            dims,
            _field_ols(gg, bshape),
            gg.periods[: A.ndim],
            data.dtype,
        )
    if A_global is not None:
        _check_out(A_global, data.size, data.dtype)
        np.copyto(A_global.reshape(data.shape), data)
        return None
    return data


# The public entry wraps the implementation in the ``igg.gather`` host span
# (docs/observability.md); same docstring, same collective contract.
gather.__doc__ = _gather.__doc__


def _check_out(A_global, size: int, dtype) -> None:
    if A_global.size != size:
        # Error contract from /root/reference/src/gather.jl:39 (local length
        # = global length / nprocs in the global-block representation).
        raise ValueError(
            "The input argument A_global must be of length nprocs*length(A)"
        )
    if A_global.dtype != dtype:
        raise ValueError(
            f"A_global has dtype {A_global.dtype} but A has dtype {dtype}; "
            "they must match."
        )
