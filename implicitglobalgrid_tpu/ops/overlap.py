"""Communication/computation overlap — the `@hide_communication` capability.

The reference ships the *mechanism* for overlap (max-priority CUDA streams per
halo plane, `/root/reference/src/update_halo.jl:424`) and its sister package
ParallelStencil supplies the *scheduling* (`@hide_communication`: compute the
boundary slabs first, start the halo exchange, compute the interior while the
exchange is in flight — reference `README.md:10`).

On TPU both live in one compiled XLA program and the scheduler overlaps an
async `collective-permute` with any compute it does not depend on.  The job
here is to give the scheduler that freedom *structurally*: `hide_communication`
wraps a shape-preserving local stencil update so that

1. the boundary slabs of the new state are computed first (small),
2. the halo planes are sliced from those slabs and sent (`ppermute`) —
   depending only on the slab computation,
3. the interior is computed as an independent op (big) that XLA schedules
   concurrently with the in-flight collectives,
4. slabs, interior and received planes are assembled into the final state.

Corner correctness matches `update_halo`'s sequential-dimension semantics
(`/root/reference/src/update_halo.jl:40`): the dim-``d`` send planes are
patched with the strips received in dims ``< d`` before being sent, which is
exactly the data the reference's dim-``d`` pack kernel reads after the
dim-``d-1`` unpack.

Contract for ``update_fn``: a pure, translation-invariant stencil update of
its field arguments (output element ``i`` depends on input elements
``i-radius .. i+radius``), returning the new field(s) with the same shapes.
It is called on cropped windows of the blocks, so it must not hard-code sizes.
"""

from __future__ import annotations

from typing import Sequence

from ..parallel import grid as _grid
from ..parallel.topology import NDIMS
from . import halo as _halo


# --- Boundary/interior tile decomposition (pipelined group schedule) --------
#
# The fused cadences' pipelined schedule splits each group's kernel launch
# into a BOUNDARY pass over the "ring" tiles — the tiles whose owned blocks
# contain the x/y slab-exchange send planes and whose haloed windows read
# the planes the exchange refreshes — and an INTERIOR pass over the "mid"
# tiles, whose k-step outputs provably never touch a refreshed plane.  The
# boundary pass runs first, so the group's `collective-permute`s dispatch
# with only thin slab slices as dependencies and fly while the interior
# pass computes (the same boundary-first scheduling `hide_communication`
# gives the per-step XLA path, lifted to tile granularity).  ONE
# implementation here, shared by the three Pallas kernels (traced index
# maps) and the models' cadence builders (admissibility) so the
# decomposition can never drift between the launch geometry and the
# schedule that relies on it.

#: Valid tile-subset selectors: "all", or ring/mid over the split dims —
#: "0" (x-edge rows), "1" (y-edge columns), "01" (the full ring).
TILE_SELS = ("all", "ring0", "mid0", "ring1", "mid1", "ring01", "mid01")


def tile_subset_count(sel: str, ncx: int, ncy: int) -> int:
    """Number of tiles in subset ``sel`` of an ``(ncx, ncy)`` tile grid."""
    if sel == "all":
        return ncx * ncy
    if sel == "ring0":
        return 2 * ncy
    if sel == "mid0":
        return (ncx - 2) * ncy
    if sel == "ring1":
        return 2 * ncx
    if sel == "mid1":
        return ncx * (ncy - 2)
    if sel == "ring01":
        return 2 * ncy + 2 * (ncx - 2)
    if sel == "mid01":
        return (ncx - 2) * (ncy - 2)
    raise ValueError(f"unknown tile subset {sel!r}; one of {TILE_SELS}")


def tile_subset_map(sel: str, ncx: int, ncy: int):
    """Traced index map for subset ``sel``: ``t_of(i) -> flat tile index``.

    ``i`` iterates ``[0, tile_subset_count(sel, ...))``; the returned flat
    index feeds the kernels' existing ``(t // ncy, t % ncy)`` decomposition.
    Works on both traced int32 scalars and Python ints (the kernels use
    Python ints for the static DMA-drain indices).
    """
    def where(cond, a, b):
        if isinstance(cond, bool):
            return a if cond else b
        import jax.numpy as jnp

        return jnp.where(cond, a, b)

    if sel == "all":
        return lambda i: i
    if sel == "ring0":
        # x-edge rows: ix=0 then ix=ncx-1, all iy.
        return lambda i: where(i < ncy, i, (ncx - 1) * ncy + (i - ncy))
    if sel == "mid0":
        return lambda i: ncy + i
    if sel == "ring1":
        # y-edge columns: alternating iy=0 / iy=ncy-1 per ix.
        return lambda i: (i // 2) * ncy + (i % 2) * (ncy - 1)
    if sel == "mid1":
        return lambda i: (i // (ncy - 2)) * ncy + 1 + i % (ncy - 2)
    if sel == "ring01":
        # the full ring: both x-edge rows, then the two y-edge columns of
        # the interior x range (alternating iy=0 / iy=ncy-1).
        def t_of(i):
            j = i - 2 * ncy
            side = (1 + j // 2) * ncy + (j % 2) * (ncy - 1)
            return where(
                i < ncy,
                i,
                where(i < 2 * ncy, (ncx - 1) * ncy + (i - ncy), side),
            )

        return t_of
    if sel == "mid01":
        return lambda i: (1 + i // (ncy - 2)) * ncy + 1 + i % (ncy - 2)
    raise ValueError(f"unknown tile subset {sel!r}; one of {TILE_SELS}")


def tile_split_error(shape, k: int, width: int, bx: int, by: int, H: int,
                     active_dims, *, ox: int, oy: int) -> str | None:
    """Why the ring/mid tile split cannot pipeline this config, or None.

    ``active_dims``: the x/y grid dimensions with halo activity (subset of
    ``(0, 1)``).  ``ox``/``oy``: the MAXIMUM shape-aware overlap of any
    exchanged field along x/y (grid overlap, +1 for staggered fields).
    Conditions, per active dim:

    * at least 3 tiles (a ring needs two edges plus a non-empty interior);
    * the slab exchange's send and keep planes (indices ``< o`` from
      either edge) must lie inside the ring tiles' owned rows — ``ox <=
      bx`` / ``oy <= by`` — or `begin_slab_exchange` would slice planes
      the boundary pass never wrote (deeper-than-minimum overlaps);
    * the interior tiles' haloed windows (including the staggered kernels'
      one-extra-face read) must stay clear of the ``width`` outermost
      planes — the planes the slab exchange refreshes — which needs
      ``bx >= k + width`` / ``by >= H + width``.

    Both passes also need >= 2 tiles (the kernels' double-buffered DMA
    drain assumes it).
    """
    n0, n1, _ = shape
    if not active_dims:
        return "no x/y halo activity: nothing for the interior pass to overlap"
    ncx, ncy = n0 // bx, n1 // by
    if 0 in active_dims:
        if ncx < 3:
            return f"x split needs >= 3 x-tiles (ncx={ncx} at bx={bx})"
        if ox > bx:
            return (
                f"x send/keep planes reach past the ring tiles: overlap "
                f"{ox} > bx={bx}"
            )
        if bx < k + width:
            return (
                f"interior windows reach the refreshed x planes: bx={bx} < "
                f"k+width={k + width}"
            )
    if 1 in active_dims:
        if ncy < 3:
            return f"y split needs >= 3 y-tiles (ncy={ncy} at by={by})"
        if oy > by:
            return (
                f"y send/keep planes reach past the ring tiles: overlap "
                f"{oy} > by={by}"
            )
        if by < H + width:
            return (
                f"interior windows reach the refreshed y planes: by={by} < "
                f"H+width={H + width}"
            )
    sel = "".join(str(d) for d in sorted(active_dims))
    for kind in ("ring", "mid"):
        if tile_subset_count(kind + sel, ncx, ncy) < 2:
            return f"{kind}{sel} has < 2 tiles (ncx={ncx}, ncy={ncy})"
    return None


def tile_split_sel(active_dims) -> str:
    """The ring/mid selector suffix for the given active x/y dims."""
    return "".join(str(d) for d in sorted(active_dims))


def hide_communication(update_fn=None, *, radius: int = 1, exchange=None):
    """Wrap ``update_fn`` so its halo update overlaps its interior computation.

    Per-block function: use inside `igg.stencil` (or compose:
    ``igg.stencil(igg.hide_communication(step))``).  ``exchange`` optionally
    lists which outputs get a halo update (default: every output that has a
    halo).  Semantically equivalent to ``update_halo(*update_fn(*fields))``.
    """
    if update_fn is None:
        return lambda f: hide_communication(f, radius=radius, exchange=exchange)

    def wrapped(*fields):
        return _overlapped_update(update_fn, fields, radius, exchange)

    wrapped.__wrapped__ = update_fn
    return wrapped


def _halo_dims(shapes, gg) -> list[int]:
    """Dimensions in which any of ``shapes`` exchanges a halo."""
    out = []
    for d in range(NDIMS):
        if not _halo.dim_has_halo_activity(gg, d):
            continue
        if any(
            d < len(s) and _halo.ol(d, shape=s, gg=gg) >= 2 for s in shapes
        ):
            out.append(d)
    return out


def _overlapped_update(update_fn, fields, radius, exchange):
    import jax
    import jax.numpy as jnp
    from jax import lax

    gg = _grid.global_grid()
    fields = tuple(fields)

    out_aval = jax.eval_shape(
        update_fn, *[jax.ShapeDtypeStruct(f.shape, f.dtype) for f in fields]
    )
    single = not isinstance(out_aval, (tuple, list))
    out_avals = (out_aval,) if single else tuple(out_aval)
    out_shapes = [tuple(a.shape) for a in out_avals]
    if exchange is None:
        exchange_idx = [
            i
            for i, s in enumerate(out_shapes)
            if any(_halo.ol(d, shape=s, gg=gg) >= 2 for d in range(len(s)))
        ]
    else:
        exchange_idx = list(exchange)

    hdims = _halo_dims([out_shapes[i] for i in exchange_idx], gg)
    if not hdims:
        out = update_fn(*fields)
        return out

    # Slab width per halo dim: wide enough to contain every exchanged field's
    # send plane (index ol-1 / n-ol).
    W = {
        d: max(
            _halo.ol(d, shape=out_shapes[i], gg=gg)
            for i in exchange_idx
            if d < len(out_shapes[i])
        )
        for d in hdims
    }
    for d, w in W.items():
        for s in out_shapes:
            if d < len(s) and s[d] < 2 * w:
                raise ValueError(
                    f"hide_communication: local size {s[d]} in dimension {d} is too "
                    f"small for boundary-slab width {w}; use plain update_halo."
                )
        if radius > w:
            raise ValueError(
                f"hide_communication: stencil radius {radius} exceeds the boundary-"
                f"slab width {w} in dimension {d}."
            )

    def crop(x, d, lo, hi):
        if d >= x.ndim:
            return x
        return lax.slice_in_dim(x, lo, x.shape[d] - hi, axis=d)

    # -- 1. boundary slabs of the new state (one pair per halo dim) ----------
    # Input windows start at a common index (edge-aligned) and each field's
    # window additionally includes its stagger excess over the smallest field,
    # so cross-field index relations (e.g. Vx[1:] - Vx[:-1] vs P) hold on the
    # windows exactly as on the full blocks.
    slabs = {}  # d -> (lo_outs, hi_outs): tuples over outputs
    for d in hdims:
        w = W[d]
        n_min = min(f.shape[d] for f in fields if d < f.ndim)
        # Fields of lower rank than d (e.g. a 2-D parameter field on a 3-D
        # grid) have no extent in this dimension: pass them through whole.
        lo_in = [
            lax.slice_in_dim(
                f, 0, min(w + radius + (f.shape[d] - n_min), f.shape[d]), axis=d
            )
            if d < f.ndim
            else f
            for f in fields
        ]
        hi_in = [
            lax.slice_in_dim(
                f,
                max(f.shape[d] - (w + radius + (f.shape[d] - n_min)), 0),
                f.shape[d],
                axis=d,
            )
            if d < f.ndim
            else f
            for f in fields
        ]
        lo_out = update_fn(*lo_in)
        hi_out = update_fn(*hi_in)
        lo_out = (lo_out,) if single else tuple(lo_out)
        hi_out = (hi_out,) if single else tuple(hi_out)
        lo_out = tuple(
            lax.slice_in_dim(o, 0, w, axis=d) if d < o.ndim else o for o in lo_out
        )
        hi_out = tuple(
            lax.slice_in_dim(o, o.shape[d] - w, o.shape[d], axis=d) if d < o.ndim else o
            for o in hi_out
        )
        slabs[d] = (lo_out, hi_out)

    # -- 2./3. interior as one big independent op ----------------------------
    int_in = fields
    for d in hdims:
        int_in = [crop(f, d, W[d] - radius, W[d] - radius) for f in int_in]
    int_out = update_fn(*int_in)
    int_out = (int_out,) if single else tuple(int_out)
    int_out = [o for o in int_out]
    for d in hdims:
        int_out = [crop(o, d, radius, radius) for o in int_out]

    # -- 4a. assemble slabs + interior ---------------------------------------
    assembled = []
    for i, aval in enumerate(out_avals):
        nd_out = len(aval.shape)
        my_hdims = [d for d in hdims if d < nd_out]
        base = jnp.zeros(aval.shape, aval.dtype)
        off = [0] * nd_out
        for d in my_hdims:
            off[d] = W[d]
        base = lax.dynamic_update_slice(base, int_out[i].astype(aval.dtype), off)
        for d in my_hdims:
            lo_o, hi_o = slabs[d]
            lo_off = [0] * nd_out
            hi_off = [0] * nd_out
            hi_off[d] = aval.shape[d] - W[d]
            base = lax.dynamic_update_slice(base, lo_o[i].astype(aval.dtype), lo_off)
            base = lax.dynamic_update_slice(base, hi_o[i].astype(aval.dtype), hi_off)
        assembled.append(base)

    # -- 4b. halo exchange, sends sliced from the slabs (not the assembly) ---
    for i in exchange_idx:
        my_slabs = {d: (slabs[d][0][i], slabs[d][1][i]) for d in hdims}
        assembled[i] = _exchange_from_slabs(
            assembled[i], out_shapes[i], my_slabs, hdims, gg
        )

    return assembled[0] if single else tuple(assembled)


def _exchange_from_slabs(A, shape, slabs, hdims, gg):
    """Sequential per-dim exchange whose send planes depend only on the slabs
    (plus strips received in earlier dims), so they are schedulable before the
    interior computation finishes."""
    from jax import lax

    def plane_of(x, idx, d):
        return lax.slice_in_dim(x, idx, idx + 1, axis=d)

    def patch(plane, d, p_idx, received):
        # Overwrite the strips of `plane` (a dim-d plane at index p_idx) that
        # lie in earlier-exchanged dims' halo planes with the received values —
        # the reference's corner carry-over (dim-d pack reads post-dim-(d-1)
        # state, /root/reference/src/update_halo.jl:40).
        for d2, (lo2, hi2) in received.items():
            if d2 >= len(plane.shape):
                continue
            if lo2 is not None:
                strip = plane_of(lo2, p_idx, d)
                off = [0] * plane.ndim
                plane = lax.dynamic_update_slice(plane, strip.astype(plane.dtype), off)
            if hi2 is not None:
                strip = plane_of(hi2, p_idx, d)
                off = [0] * plane.ndim
                off[d2] = plane.shape[d2] - 1
                plane = lax.dynamic_update_slice(plane, strip.astype(plane.dtype), off)
        return plane

    received = {}
    for d in hdims:
        if d >= len(shape):
            continue
        o = _halo.ol(d, shape=shape, gg=gg)
        if o < 2:
            continue
        n = shape[d]
        if not _halo.dim_has_halo_activity(gg, d):
            continue
        lo_slab, hi_slab = slabs[d]
        w = lo_slab.shape[d]
        send_lo = patch(plane_of(lo_slab, o - 1, d), d, o - 1, received)
        send_hi = patch(plane_of(hi_slab, w - o, d), d, n - o, received)
        if _halo._partner_self(gg, d):
            # Every block its own distance-disp partner: pure local copy.
            final_lo, final_hi = send_hi, send_lo
        else:
            # The distance-``disp`` partner permutation, periodic wrap and
            # PROC_NULL keep-old masking are `_permute_slabs` — the ONE
            # implementation shared with the plain exchange, so
            # hide_communication honors `Cart_shift(dim, disp)` for any
            # disp exactly like `update_halo` (VERDICT r4 weak #3).
            try:
                final_lo, final_hi = _halo._permute_slabs(
                    gg, d,
                    send_lo=send_lo,
                    send_hi=send_hi,
                    keep_lo=lambda: patch(plane_of(lo_slab, 0, d), d, 0, received),
                    keep_hi=lambda: patch(
                        plane_of(hi_slab, w - 1, d), d, n - 1, received
                    ),
                )
            except RuntimeError as e:
                raise RuntimeError(
                    "hide_communication must run inside an igg.stencil/shard_map "
                    "context over the grid mesh (wrap it: "
                    "igg.stencil(igg.hide_communication(step)))."
                ) from e
        A = _halo._set_plane(A, final_lo, 0, d)
        A = _halo._set_plane(A, final_hi, n - 1, d)
        received[d] = (final_lo, final_hi)
    return A
