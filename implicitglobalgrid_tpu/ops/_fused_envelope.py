"""Shared envelope control flow for the temporally-blocked Pallas kernels.

`ops/pallas_stencil.py` (cell-centered diffusion) and `ops/pallas_leapfrog.py`
(staggered leapfrog) share every hardware-probed constraint except the VMEM
accounting of their working sets: k even in [2, 8] (k=8 since round 5, with
the H=16 y-halo margin — see `aligned_halo`), minor dim <= 1024
(validated ceiling) and a multiple of 128 (Mosaic requires lane-tile-aligned
minor extents on HBM memref slices — probed at n2=192, round 3), y-size a
multiple of 8 (sublane-aligned second-minor DMA windows), tuned-candidate
auto-selection.  Keeping the control flow here means a newly probed
constraint lands in ONE place — the round-3 lane-alignment find had to be
retrofitted into the diffusion envelope precisely because each kernel
carried its own copy.

Each kernel supplies its own ``tile_error(n0, n1, n2, k, bx, by, itemsize)``
(VMEM budget + divisibility for its specific buffer set) and its candidate
list; this module owns everything kernel-independent.
"""

from __future__ import annotations

import math
import os


def aligned_halo(k: int) -> int:
    """y-halo: sublane-aligned with at least one spare ring beyond ``k`` —
    ``H = 8*ceil((k+1)/8)`` (8 for k <= 6, 16 for k = 8).

    The margin is load-bearing: k=8 with H=8 (halo exactly k, no spare
    ring) corrupted tile-corner cells on this toolchain (probed round 3);
    H=16 at k=8 is hardware-validated BITWISE against 8 XLA steps
    (round 5 probe, acoustic 256^3 (32,64))."""
    return 8 * math.ceil((k + 1) / 8)


def pad8(x: int) -> int:
    """Round up to sublane alignment (the transposed-layout plane pad)."""
    return -(-x // 8) * 8


def pad128(x: int) -> int:
    """Round up to lane-tile alignment (minor-dim extents of HBM arrays)."""
    return -(-x // 128) * 128


#: Per-core VMEM the tuned defaults were probed against (v5e/v5p: 128 MiB).
_TUNED_VMEM_MB = 128


def vmem_budget(default_bytes: int) -> int:
    """The VMEM budget a kernel plans against (VERDICT r3 #6).

    ``IGG_VMEM_MB`` declares the per-core VMEM capacity (MiB; the tuned
    defaults assume v5e's 128).  Each kernel's budget scales
    proportionally, so the per-kernel headroom ratios stay intact (each
    budget encodes that kernel's probed Mosaic scoped-stack overshoot over
    the buffer-byte estimate — ~85% for the diffusion kernel, ~18% for the
    staggered ones; a flat override would erase those margins).  jax's
    public API exposes no
    per-generation VMEM size, so another generation tunes via env instead
    of editing source.  Read per envelope check, not at import, so tests
    and long-running processes can flip it.
    """
    v = os.environ.get("IGG_VMEM_MB")
    if v:
        try:
            cap = int(v)
        except ValueError:
            raise ValueError(f"IGG_VMEM_MB must be an integer (MiB), got {v!r}")
        if cap <= 0:
            raise ValueError(f"IGG_VMEM_MB must be positive, got {v!r}")
        return default_bytes * cap // _TUNED_VMEM_MB
    return default_bytes


def vmem_limit(need_bytes: int) -> int:
    """``CompilerParams.vmem_limit_bytes`` for a kernel needing ``need_bytes``:
    the need plus Mosaic's working margin, capped at the capacity-scaled
    per-core ceiling (110 MiB of the tuned 128 MiB generation)."""
    return min(vmem_budget(110 * 1024 * 1024), need_bytes + 16 * 1024 * 1024)


def pick_tile_error(base, patch, export, zpatch, zexport=None):
    """Select a kernel's ``tile_error`` for the requested z-window mode.

    ``zexport=None`` defaults to ``zpatch`` — the production z-slab cadence
    always exports, so callers that only say "zpatch" budget for the full
    variant; pass ``zexport=False`` for a patch-only kernel call.  One
    definition for all three kernels (this module's contract: shared
    envelope control flow lands in ONE place).
    """
    if zexport is None:
        zexport = zpatch
    if zpatch and zexport:
        return export
    return patch if zpatch else base


def make_tile_error(tile_bytes, budget, desc, full_y_ok=False):
    """Build a kernel's ``tile_error`` from its VMEM accounting.

    ``tile_bytes(n1, n2, k, bx, by, itemsize)`` is the kernel-specific
    working set (``n1`` matters only to the full-y window modes);
    ``budget`` its tuned default budget (env-overridable, see
    `vmem_budget`); ``desc`` names it in the rejection message.  Everything
    else (divisibility, sublane alignment, haloed-tile fit) is
    kernel-independent and lives here once.

    ``full_y_ok``: admit ``by == n1`` full-y tiles (window spans all of y
    with NO y halo — the window edge is the block edge, where the frozen
    ring reproduces the XLA cadence's own frozen boundary, so no recompute
    halo is needed).  Only for kernels whose window math implements the
    mode (round 5: the diffusion kernel); others keep rejecting oversized
    windows.
    """

    def tile_error(n0, n1, n2, k, bx, by, itemsize):
        H = 0 if (full_y_ok and by == n1) else aligned_halo(k)
        vmem_need = tile_bytes(n1, n2, k, bx, by, itemsize)
        live_budget = vmem_budget(budget)
        if vmem_need > live_budget:
            # Name the env knob accurately: "scaled by" only when an override
            # is actually active (advisor r4).
            how = (
                "scaled by IGG_VMEM_MB"
                if os.environ.get("IGG_VMEM_MB")
                else "tunable via IGG_VMEM_MB"
            )
            return (
                f"tile ({bx},{by}) with k={k} needs ~{vmem_need >> 20} MiB of "
                f"VMEM ({desc}; budget {live_budget >> 20} MiB, {how}); "
                "shrink the tile or k"
            )
        if n0 % bx != 0 or n1 % by != 0:
            return f"tile ({bx},{by}) does not divide volume ({n0},{n1})"
        if by % 8 != 0 or n1 % 8 != 0:
            return "by and the y-size must be multiples of 8 (DMA alignment)"
        if bx + 2 * k > n0 or by + 2 * H > n1:
            return f"haloed tile ({bx + 2 * k},{by + 2 * H}) exceeds volume; lower k"
        return None

    return tile_error


def check_tile_subset(tile_sel, carry_in, n01, tile, nouts: int):
    """Validate a tile-subset launch request (shared by the three kernels).

    ``tile_sel``/``carry_in`` as documented on each kernel's public entry;
    ``n01`` = (n0, n1), ``tile`` = (bx, by), ``nouts`` = the launch's output
    count (what a ``mid*`` carry must alias).  Returns ``carry_in``
    normalized to a tuple, or None for non-aliasing launches.
    """
    if tile_sel == "all":
        if carry_in is not None:
            raise ValueError("carry_in is only for 'mid*' tile-subset launches")
        return None
    from .overlap import TILE_SELS, tile_subset_count

    if tile_sel not in TILE_SELS:
        raise ValueError(f"tile_sel {tile_sel!r} must be one of {TILE_SELS}")
    ncx, ncy = n01[0] // tile[0], n01[1] // tile[1]
    n = tile_subset_count(tile_sel, ncx, ncy)
    if n < 2:
        # The kernels' double-buffered DMA drain assumes >= 2 tiles; the
        # models gate admissibility through `ops.overlap.tile_split_error`,
        # so reaching this is a caller bug, not a fall-back condition.
        raise ValueError(
            f"tile subset {tile_sel!r} has {n} tiles on the ({ncx},{ncy}) "
            "tile grid; a subset launch needs >= 2"
        )
    if tile_sel.startswith("mid"):
        if carry_in is None:
            raise ValueError(
                "a 'mid*' launch needs carry_in: the matching 'ring*' "
                "launch's output array(s) to alias the combined result into"
            )
        carry = tuple(carry_in) if isinstance(carry_in, (tuple, list)) else (carry_in,)
        if len(carry) != nouts:
            raise ValueError(
                f"carry_in must hold the ring launch's {nouts} output(s); "
                f"got {len(carry)}"
            )
        return carry
    if carry_in is not None:
        raise ValueError("carry_in is only for 'mid*' tile-subset launches")
    return None


def default_tile(shape, k, itemsize, *, tile_error, candidates):
    """First candidate ``tile_error`` accepts for ``shape``, or None."""
    n0, n1, n2 = shape
    for bx, by in candidates:
        if tile_error(n0, n1, n2, k, bx, by, itemsize) is None:
            return (bx, by)
    return None


def support_error(shape, k, itemsize, bx, by, *, tile_error, candidates):
    """The kernel-independent envelope checks + tile-selection flow.

    Returns the reason the config cannot run, or None if it can — the
    single source of truth behind each kernel's ``fused_support_error``.
    """
    n0, n1, n2 = shape
    if itemsize > 4:
        # TPU hardware has no 8-byte element type: XLA emulates f64 in
        # software but Mosaic kernels cannot — without this check an
        # x64/complex field reaches a Mosaic compile error instead of the
        # warn-once XLA fallback.
        return (
            f"itemsize {itemsize} (f64/complex) is not supported by TPU "
            "Pallas kernels; fall back to the XLA path (XLA emulates x64)"
        )
    if k < 2 or k % 2 != 0 or k > 8:
        return (
            f"k must be even in [2, 8] (got {k}); use the XLA path for k=1. "
            "(k=8 runs with the H=16 y-halo margin — `aligned_halo`; deeper "
            "blocking is unvalidated)"
        )
    if n2 > 1024:
        # Bit-level agreement with the XLA path is validated on hardware up
        # to n2=1024 (an earlier toolchain miscompiled >2-lane-tile tiled
        # DMAs; the current one is clean, with `pl.multiple_of` alignment
        # hints on the dynamic offsets).
        return (
            f"minor dimension {n2} > 1024 not validated on this toolchain; "
            "fall back to the XLA path"
        )
    if n2 % 128 != 0:
        # Mosaic requires HBM memref slices to have lane-tile-aligned minor
        # extents ("Slice shape along dimension 2 must be aligned to tiling
        # (128)") — probed on hardware at n2=192 (round 3); every validated
        # size (256/512/1024) is a multiple of 128.
        return (
            f"minor dimension {n2} is not a multiple of 128 (lane-tile "
            "alignment for HBM slices); fall back to the XLA path"
        )
    if bx is None and by is None:
        picked = default_tile(
            (n0, n1, n2), k, itemsize, tile_error=tile_error, candidates=candidates
        )
        if picked is None:
            if n1 % 8 != 0:
                return (
                    f"y-size {n1} is not a multiple of 8 (DMA sublane "
                    "alignment); no tile can fit — use the XLA path"
                )
            return (
                f"no tuned tile candidate {candidates} fits volume "
                f"({n0},{n1},{n2}) with k={k}; pass bx/by explicitly"
            )
        return None
    if bx is None or by is None:
        return "pass both bx and by, or neither"
    return tile_error(n0, n1, n2, k, bx, by, itemsize)
