"""Pallas TPU kernel: temporally-blocked fused pseudo-transient iterations.

The porous-convection sibling of `ops/pallas_leapfrog.py`: advance ``w``
Darcy flux / fluid pressure relaxation iterations of the PT inner solver
(`models/porous_convection3d.py` — the HydroMech weak-scaling flagship,
BASELINE config 4) in ONE HBM round trip per field.  Structurally the PT
iteration IS a staggered leapfrog — flux update at interior faces (a
relaxation toward ``-grad(Pf)`` plus buoyancy on z-faces), pressure update
at ALL cells from the fresh fluxes — so the even-extent padded face layout
(`pad_faces`), the tile/window geometry, the frozen-top-face fix-up DMAs,
the trapezoid validity argument, and the envelope checks are all inherited
from the leapfrog kernel (see its module docstring; the kernel body is
deliberately mirrored rather than abstracted over — the compute formulas and
buffer sets differ, and the DMA scaffolding is the delicate, hardware-
validated part that benefits from staying literal).

Differences from the leapfrog kernel:

* One extra **read-only** cell-shaped input ``T`` (temperature, frozen
  across the whole PT loop): double-buffered input DMAs like the diffusion
  kernel's ``Cp``, no scratch, no output.  Its window values are exact
  everywhere (no shrinkage), so the buoyancy term reads them at any step.
* Flux update: ``q <- q + th*(f - q)`` with ``f = -dPf*id`` (plus
  ``RaLam * av_z(T)`` on z-faces) instead of the leapfrog increment.
* Pressure update: ``Pf <- Pf - bp*div(q)`` — same all-cells form.

Semantics match `models/porous_convection3d.py`'s `_flux_update` +
`_pressure_update` pair for update regions and frozen sets, to a few f32
ULPs (the kernel multiplies by precomputed ``1/dx`` where the XLA path
divides; same stencil, different rounding).

Multi-device: ``fused_k=w`` in `porous_convection3d.make_multi_step` is the
kernel-accelerated version of its ``exchange_every=w`` deep-halo cadence —
w kernel iterations per width-``w`` all-field slab exchange.
"""

from __future__ import annotations

import functools

from . import _fused_envelope as _envelope
from .halo import Z_CZ_BAND
from .pallas_leapfrog import (  # noqa: F401  (re-export)
    pad_faces,
    padded_face_shapes,
    unpad_faces,
    z_patch_shapes,
)

_TILE_CANDIDATES = ((32, 64), (32, 32), (16, 64), (16, 32), (8, 16))

#: See `ops.pallas_leapfrog._VMEM_BUDGET_BYTES` (Mosaic's scoped stack runs
#: ~18% past the buffer-byte estimate on the staggered sets — the diffusion
#: kernel's overshoot is far larger, hence its smaller budget).
_VMEM_BUDGET_BYTES = 85 * 1024 * 1024


def _tile_bytes(n1, n2, k, bx, by, itemsize, zsets: int = 0):
    """VMEM bytes: 4 ping-pong fields x (2 slots + scratch) + 2 T slots
    plus ``zsets`` four-field double-buffered 128-lane window sets (1 = the
    z-patch input windows, 2 = + the z-export staging slots)."""
    H = _envelope.aligned_halo(k)
    SX, SY = bx + 2 * k, by + 2 * H
    per_set = (
        SX * SY * n2            # Pf
        + (SX + 8) * SY * n2    # qDx
        + SX * (SY + 8) * n2    # qDy
        + SX * SY * (n2 + 128)  # qDz
    )
    total = 3 * per_set + 2 * SX * SY * n2
    # Three z-window arrays per set since round 5 (merged Pf+qDz bands).
    total += zsets * 2 * 128 * (
        SX * SY + (SX + 8) * SY + SX * (SY + 8)
    )
    return total * itemsize


_tile_error = _envelope.make_tile_error(
    _tile_bytes, _VMEM_BUDGET_BYTES, "14 haloed staggered tiles spanning z"
)
_tile_error_zpatch = _envelope.make_tile_error(
    lambda n1, n2, k, bx, by, itemsize: _tile_bytes(n1, n2, k, bx, by, itemsize, 1),
    _VMEM_BUDGET_BYTES,
    "14 haloed staggered tiles spanning z + 6 z-patch windows",
)
_tile_error_zexport = _envelope.make_tile_error(
    lambda n1, n2, k, bx, by, itemsize: _tile_bytes(n1, n2, k, bx, by, itemsize, 2),
    _VMEM_BUDGET_BYTES,
    "14 haloed staggered tiles spanning z + 6 z windows + 6 export stagings",
)


def default_tile(shape, k: int, itemsize: int = 4, zpatch: bool = False,
                 zexport: bool | None = None):
    """First tuned tile candidate valid for cell ``shape``, or None.

    ``zexport`` defaults to ``zpatch`` (the production z-slab cadence always
    exports); pass ``zexport=False`` for a patch-only call."""
    return _envelope.default_tile(
        shape, k, itemsize,
        tile_error=_envelope.pick_tile_error(
            _tile_error, _tile_error_zpatch, _tile_error_zexport,
            zpatch, zexport,
        ),
        candidates=_TILE_CANDIDATES,
    )


def fused_support_error(shape, k: int, itemsize: int = 4,
                        bx: int | None = None, by: int | None = None,
                        zpatch: bool = False,
                        zexport: bool | None = None) -> str | None:
    """Why the fused PT kernel cannot run this cell shape, or None.

    Shared control flow in `ops/_fused_envelope.py`; only `_tile_error`'s
    14-buffer VMEM accounting is specific.  ``zpatch`` accounts for the
    in-kernel z-exchange variant (PT fields only — ``T`` is frozen through
    the PT loop, its halos are refreshed at its own once-per-step exchange,
    so it needs no patches).
    """
    return _envelope.support_error(
        shape, k, itemsize, bx, by,
        tile_error=_envelope.pick_tile_error(
            _tile_error, _tile_error_zpatch, _tile_error_zexport,
            zpatch, zexport,
        ),
        candidates=_TILE_CANDIDATES,
    )


def fused_pt_iterations(T, Pf, qxp, qyp, qzp, k: int,
                        th: float, idx: float, idy: float, idz: float,
                        ralam: float, bp: float,
                        *, bx: int | None = None, by: int | None = None,
                        z_patches=None, z_patch_width: int | None = None,
                        z_export: bool = False, z_export_width: int | None = None,
                        z_overlap: int | None = None,
                        tile_sel: str = "all", carry_in=None):
    """Advance ``k`` (even) PT relaxation iterations in one HBM pass per field.

    ``T``/``Pf`` are cell-centered ``(n0, n1, n2)``; ``qxp/qyp/qzp`` are the
    `pad_faces` layouts of the staggered Darcy fluxes.  Coefficients:
    ``th`` = flux relaxation, ``idx = 1/dx`` (likewise y, z), ``ralam =
    Ra*lam_T`` (buoyancy), ``bp`` = pressure relaxation.  Returns
    ``(Pf, qxp, qyp, qzp)`` — ``T`` is read-only.

    ``z_patches``: packed z-exchange patches for the four PT fields
    (`ops.halo.z_slab_patches`, width ``k``), applied per tile in VMEM —
    see `ops.pallas_leapfrog.fused_leapfrog_steps`.

    ``z_export``/``z_overlap``: additionally return the three packed z-slab
    exports for the NEXT group's patches (Pf and qDz share the merged
    first array's lane bands) — same layout, top-face fix-up obligation,
    and rationale as the leapfrog kernel's ``z_export``
    (`ops.pallas_leapfrog.fused_leapfrog_steps`).

    ``z_patch_width``/``z_export_width`` (default ``k``): widths of the
    patch application and the exported slabs — the ragged-``npt`` cadence
    (`models.porous_convection3d`) keeps both at the schedule's maximum
    chunk ``w`` for every chunk, so a shorter chunk (``k < w``) still heals
    the previous chunk's ``w``-deep stale rind and exports ``w``-deep
    slabs.  Requires ``k <= width`` and ``o >= z_export_width + k`` (the
    exported planes must be exact after ``k`` steps).

    ``tile_sel``/``carry_in``: tile-subset launch for the pipelined group
    schedule, exactly as on `ops.pallas_leapfrog.fused_leapfrog_steps`
    (``T`` is a plain input, not part of the carry).
    """
    n0, n1, n2 = Pf.shape
    if T.shape != Pf.shape:
        raise ValueError(f"T{T.shape} and Pf{Pf.shape} must share the cell shape")
    if (qxp.shape, qyp.shape, qzp.shape) != padded_face_shapes(Pf.shape):
        raise ValueError(
            f"flux fields must be in pad_faces layout for Pf{Pf.shape}: got "
            f"{qxp.shape}, {qyp.shape}, {qzp.shape}"
        )
    if not (T.dtype == Pf.dtype == qxp.dtype == qyp.dtype == qzp.dtype):
        raise ValueError("T, Pf and flux fields must share a dtype")
    zp = z_patches is not None
    if zp:
        if tuple(a.shape for a in z_patches) != z_patch_shapes(Pf.shape):
            raise ValueError(
                f"z_patches must have shapes {z_patch_shapes(Pf.shape)}: got "
                f"{tuple(a.shape for a in z_patches)}"
            )
        if any(a.dtype != Pf.dtype for a in z_patches):
            raise ValueError("z_patches must share the fields' dtype")
    wp = k if z_patch_width is None else int(z_patch_width)
    we = k if z_export_width is None else int(z_export_width)
    if zp and not (k <= wp <= 32):
        # 2*wp lanes per merged-band half (see Z_CZ_BAND).
        raise ValueError(f"z_patch_width must satisfy k <= wp <= 32: got {wp}, k={k}")
    if z_export:
        if not zp:
            raise ValueError("z_export requires z_patches (the z-slab cadence)")
        if z_overlap is None or not (we + k <= z_overlap <= n2 // 2):
            raise ValueError(
                f"z_export needs the grid z-overlap with we+k <= o <= n2/2: "
                f"got o={z_overlap}, k={k}, we={we}, n2={n2}"
            )
        if 4 * we > 64:
            raise ValueError(
                f"z_export packs 4*we lanes per merged-band half; "
                f"z_export_width={we} > 16 unsupported"
            )
    err = fused_support_error(
        (n0, n1, n2), k, Pf.dtype.itemsize, bx, by, zpatch=zp, zexport=z_export
    )
    if err is not None:
        raise ValueError(err)
    if bx is None:
        bx, by = default_tile(
            (n0, n1, n2), k, Pf.dtype.itemsize, zpatch=zp, zexport=z_export
        )
    carry_in = _envelope.check_tile_subset(
        tile_sel, carry_in, (n0, n1), (bx, by), nouts=7 if z_export else 4
    )
    from ..utils.compat import pallas_interpret_active

    fn = _build(n0, n1, n2, str(Pf.dtype), int(k),
                float(th), float(idx), float(idy), float(idz),
                float(ralam), float(bp), int(bx), int(by), zp,
                bool(z_export), int(z_overlap) if z_export else 0,
                wp if zp else 0, we if z_export else 0,
                str(tile_sel), carry_in is not None,
                pallas_interpret_active())
    args = (T, Pf, qxp, qyp, qzp) + (tuple(z_patches) if zp else ())
    if carry_in is not None:
        args += tuple(carry_in)
    return fn(*args)


@functools.lru_cache(maxsize=64)
def _build(n0, n1, n2, dtype, k, th, idx, idy, idz, ralam, bp, bx, by,
           zp: bool = False, zx: bool = False, o: int = 0,
           wp: int = 0, we: int = 0,
           tile_sel: str = "all", carry: bool = False, interp: bool = False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from ..utils.compat import pallas_compiler_params
    from .overlap import tile_subset_count, tile_subset_map

    H = _envelope.aligned_halo(k)
    SX, SY = bx + 2 * k, by + 2 * H
    SZ = n2
    ncx, ncy = n0 // bx, n1 // by
    ntiles = ncx * ncy
    # Tile-subset launch (see ops/pallas_stencil.py); fix-up DMAs belong to
    # the ring pass, like the leapfrog kernel.
    nrun = tile_subset_count(tile_sel, ncx, ncy)
    t_of = tile_subset_map(tile_sel, ncx, ncy)
    fixup = not tile_sel.startswith("mid")
    dt_ = jnp.dtype(dtype)

    def sx_of(ix):
        return jnp.clip(ix * bx - k, 0, n0 - SX)

    def sy_of(iy):
        return pl.multiple_of(jnp.clip(iy * by - H, 0, n1 - SY), 8)

    # Frozen-region copies: identical regions to the leapfrog kernel (the
    # flux update regions match the velocity ones; Pf updates all cells).
    def ring_qx(dst, s):
        dst[0:1] = s[0:1]
        dst[SX : SX + 8] = s[SX : SX + 8]
        dst[1:SX, 0:1] = s[1:SX, 0:1]
        dst[1:SX, SY - 1 : SY] = s[1:SX, SY - 1 : SY]
        dst[1:SX, 1 : SY - 1, 0:1] = s[1:SX, 1 : SY - 1, 0:1]
        dst[1:SX, 1 : SY - 1, SZ - 1 : SZ] = s[1:SX, 1 : SY - 1, SZ - 1 : SZ]

    def ring_qy(dst, s):
        dst[:, 0:1] = s[:, 0:1]
        dst[:, SY : SY + 8] = s[:, SY : SY + 8]
        dst[0:1, 1:SY] = s[0:1, 1:SY]
        dst[SX - 1 : SX, 1:SY] = s[SX - 1 : SX, 1:SY]
        dst[1 : SX - 1, 1:SY, 0:1] = s[1 : SX - 1, 1:SY, 0:1]
        dst[1 : SX - 1, 1:SY, SZ - 1 : SZ] = s[1 : SX - 1, 1:SY, SZ - 1 : SZ]

    def ring_qz(dst, s):
        dst[:, :, 0:1] = s[:, :, 0:1]
        dst[:, :, SZ : SZ + 128] = s[:, :, SZ : SZ + 128]
        dst[0:1, :, 1:SZ] = s[0:1, :, 1:SZ]
        dst[SX - 1 : SX, :, 1:SZ] = s[SX - 1 : SX, :, 1:SZ]
        dst[1 : SX - 1, 0:1, 1:SZ] = s[1 : SX - 1, 0:1, 1:SZ]
        dst[1 : SX - 1, SY - 1 : SY, 1:SZ] = s[1 : SX - 1, SY - 1 : SY, 1:SZ]

    def step_into(dp, dqx, dqy, dqz, sp, sqx, sqy, sqz, tv, ring: bool):
        """One PT iteration: (sp, sq*) buffers -> (dp, dq*) buffers.

        ``tv`` is the tile's (frozen) temperature value.  Fluxes first
        (relaxation toward -grad(Pf), buoyancy on z), then Pf at ALL cells
        from the fresh fluxes.
        """
        if ring:
            ring_qx(dqx, sqx)
            ring_qy(dqy, sqy)
            ring_qz(dqz, sqz)
        P = sp[:]
        fx = -idx * (P[1:SX, 1 : SY - 1, 1 : SZ - 1] - P[0 : SX - 1, 1 : SY - 1, 1 : SZ - 1])
        q = sqx[1:SX, 1 : SY - 1, 1 : SZ - 1]
        dqx[1:SX, 1 : SY - 1, 1 : SZ - 1] = q + th * (fx - q)
        fy = -idy * (P[1 : SX - 1, 1:SY, 1 : SZ - 1] - P[1 : SX - 1, 0 : SY - 1, 1 : SZ - 1])
        q = sqy[1 : SX - 1, 1:SY, 1 : SZ - 1]
        dqy[1 : SX - 1, 1:SY, 1 : SZ - 1] = q + th * (fy - q)
        # z-faces carry buoyancy: Ra*lam_T * (T averaged onto the face).
        tz = 0.5 * (tv[1 : SX - 1, 1 : SY - 1, 1:SZ] + tv[1 : SX - 1, 1 : SY - 1, 0 : SZ - 1])
        fz = (
            -idz * (P[1 : SX - 1, 1 : SY - 1, 1:SZ] - P[1 : SX - 1, 1 : SY - 1, 0 : SZ - 1])
            + ralam * tz
        )
        q = sqz[1 : SX - 1, 1 : SY - 1, 1:SZ]
        dqz[1 : SX - 1, 1 : SY - 1, 1:SZ] = q + th * (fz - q)
        nqx = dqx[0 : SX + 1]
        nqy = dqy[:, 0 : SY + 1]
        nqz = dqz[:, :, 0 : SZ + 1]
        div = (
            (nqx[1:] - nqx[:-1]) * idx
            + (nqy[:, 1:] - nqy[:, :-1]) * idy
            + (nqz[:, :, 1:] - nqz[:, :, :-1]) * idz
        )
        dp[:] = P - bp * div

    def kernel(*refs):
        ZXcz = ZXx = ZXy = None
        Tin, Pfin, Qxin, Qyin, Qzin = refs[:5]
        ZPcz, ZPx, ZPy = refs[5:8] if zp else (None, None, None)
        nin = 8 if zp else 5
        # A carry launch receives the ring pass's outputs as aliased inputs
        # between the real inputs and the outputs; never read here.
        outs = refs[nin + ((7 if zx else 4) if carry else 0):]
        if zx:
            Pfout, Qxout, Qyout, Qzout, ZXcz, ZXx, ZXy = outs
        else:
            Pfout, Qxout, Qyout, Qzout = outs

        def body(t, p, qx, qy, qz, sp, sqx, sqy, sqz,
                 t_is, p_is, qx_is, qy_is, qz_is,
                 p_os, qx_os, qy_os, qz_os, fix_s,
                 zpcz=None, zpx=None, zpy=None, zp_is=None,
                 zxcz=None, zxx=None, zxy=None, zx_os=None):
            def ixy(tt):
                return tt // ncy, tt % ncy

            def in_dmas(tt, slot):
                ix, iy = ixy(tt)
                sx, sy = sx_of(ix), sy_of(iy)
                return (
                    pltpu.make_async_copy(
                        Tin.at[pl.ds(sx, SX), pl.ds(sy, SY)], t.at[slot], t_is.at[slot]
                    ),
                    pltpu.make_async_copy(
                        Pfin.at[pl.ds(sx, SX), pl.ds(sy, SY)], p.at[slot], p_is.at[slot]
                    ),
                    pltpu.make_async_copy(
                        Qxin.at[pl.ds(sx, SX + 8), pl.ds(sy, SY)],
                        qx.at[slot], qx_is.at[slot],
                    ),
                    pltpu.make_async_copy(
                        Qyin.at[pl.ds(sx, SX), pl.ds(sy, SY + 8)],
                        qy.at[slot], qy_is.at[slot],
                    ),
                    pltpu.make_async_copy(
                        Qzin.at[pl.ds(sx, SX), pl.ds(sy, SY)],
                        qz.at[slot], qz_is.at[slot],
                    ),
                ) + ((
                    # Pf and qDz ride ONE merged window (lane bands).
                    pltpu.make_async_copy(
                        ZPcz.at[pl.ds(sx, SX), pl.ds(sy, SY)],
                        zpcz.at[slot], zp_is.at[0, slot],
                    ),
                    pltpu.make_async_copy(
                        ZPx.at[pl.ds(sx, SX + 8), pl.ds(sy, SY)],
                        zpx.at[slot], zp_is.at[1, slot],
                    ),
                    pltpu.make_async_copy(
                        ZPy.at[pl.ds(sx, SX), pl.ds(sy, SY + 8)],
                        zpy.at[slot], zp_is.at[2, slot],
                    ),
                ) if zp else ())

            def out_dmas(tt, slot):
                ix, iy = ixy(tt)
                ox = ix * bx - sx_of(ix)
                oy = pl.multiple_of(iy * by - sy_of(iy), 8)
                gx, gy = ix * bx, iy * by
                return (
                    pltpu.make_async_copy(
                        p.at[slot, pl.ds(ox, bx), pl.ds(oy, by)],
                        Pfout.at[pl.ds(gx, bx), pl.ds(gy, by)], p_os.at[slot],
                    ),
                    pltpu.make_async_copy(
                        qx.at[slot, pl.ds(ox, bx), pl.ds(oy, by)],
                        Qxout.at[pl.ds(gx, bx), pl.ds(gy, by)], qx_os.at[slot],
                    ),
                    pltpu.make_async_copy(
                        qy.at[slot, pl.ds(ox, bx), pl.ds(oy, by)],
                        Qyout.at[pl.ds(gx, bx), pl.ds(gy, by)], qy_os.at[slot],
                    ),
                    pltpu.make_async_copy(
                        qz.at[slot, pl.ds(ox, bx), pl.ds(oy, by)],
                        Qzout.at[pl.ds(gx, bx), pl.ds(gy, by)], qz_os.at[slot],
                    ),
                )

            def zex_dmas(tt, slot):
                ix, iy = ixy(tt)
                ox = ix * bx - sx_of(ix)
                oy = pl.multiple_of(iy * by - sy_of(iy), 8)
                gx, gy = ix * bx, iy * by
                return (
                    pltpu.make_async_copy(
                        zxcz.at[slot, pl.ds(ox, bx), pl.ds(oy, by)],
                        ZXcz.at[pl.ds(gx, bx), pl.ds(gy, by)], zx_os.at[0, slot],
                    ),
                    pltpu.make_async_copy(
                        zxx.at[slot, pl.ds(ox, bx), pl.ds(oy, by)],
                        ZXx.at[pl.ds(gx, bx), pl.ds(gy, by)], zx_os.at[1, slot],
                    ),
                    pltpu.make_async_copy(
                        zxy.at[slot, pl.ds(ox, bx), pl.ds(oy, by)],
                        ZXy.at[pl.ds(gx, bx), pl.ds(gy, by)], zx_os.at[2, slot],
                    ),
                )

            def start_in(tt, slot):
                for d in in_dmas(tt, slot):
                    d.start()

            def wait_in(tt, slot):
                for d in in_dmas(tt, slot):
                    d.wait()

            def start_out(tt, slot):
                for d in out_dmas(tt, slot):
                    d.start()
                if zx:
                    for d in zex_dmas(tt, slot):
                        d.start()

            def wait_out(tt, slot):
                for d in out_dmas(tt, slot):
                    d.wait()
                if zx:
                    for d in zex_dmas(tt, slot):
                        d.wait()

            # Frozen top-slab fix-up (see the leapfrog kernel): Qx row-n0 and
            # Qy col-n1 planes; Qz's top face rides the full-minor out-DMAs.
            fix_qx = pltpu.make_async_copy(
                Qxin.at[pl.ds(n0, 8)], Qxout.at[pl.ds(n0, 8)], fix_s.at[0]
            )
            fix_qy = pltpu.make_async_copy(
                Qyin.at[pl.ds(0, n0), pl.ds(n1, 8)],
                Qyout.at[pl.ds(0, n0), pl.ds(n1, 8)],
                fix_s.at[1],
            )
            if fixup:
                fix_qx.start()
                fix_qy.start()
            start_in(t_of(0), 0)

            def tile(i, _):
                tt = t_of(i)
                slot = jax.lax.rem(i, 2)
                nslot = 1 - slot

                @pl.when(i + 1 < nrun)
                def _():
                    @pl.when(i >= 1)
                    def _():
                        wait_out(t_of(i - 1), nslot)

                    start_in(t_of(i + 1), nslot)

                wait_in(tt, slot)
                if zp:
                    # Apply the z-exchange patches in VMEM (see the
                    # leapfrog kernel): lanes [0,wp) -> planes [0,wp),
                    # lanes [wp,2wp) -> the top wp planes of each field.
                    p[slot, :, :, 0:wp] = zpcz[slot, :, :, 0:wp]
                    p[slot, :, :, SZ - wp : SZ] = zpcz[slot, :, :, wp : 2 * wp]
                    qx[slot, :, :, 0:wp] = zpx[slot, :, :, 0:wp]
                    qx[slot, :, :, SZ - wp : SZ] = zpx[slot, :, :, wp : 2 * wp]
                    qy[slot, :, :, 0:wp] = zpy[slot, :, :, 0:wp]
                    qy[slot, :, :, SZ - wp : SZ] = zpy[slot, :, :, wp : 2 * wp]
                    qz[slot, :, :, 0:wp] = zpcz[slot, :, :, Z_CZ_BAND : Z_CZ_BAND + wp]
                    qz[slot, :, :, SZ + 1 - wp : SZ + 1] = zpcz[
                        slot, :, :, Z_CZ_BAND + wp : Z_CZ_BAND + 2 * wp
                    ]
                tv = t[slot]
                for j in range(k):
                    if j % 2 == 0:
                        step_into(
                            sp, sqx, sqy, sqz,
                            p.at[slot], qx.at[slot], qy.at[slot], qz.at[slot],
                            tv, ring=(j == 0),
                        )
                    else:
                        step_into(
                            p.at[slot], qx.at[slot], qy.at[slot], qz.at[slot],
                            sp, sqx, sqy, sqz,
                            tv, ring=False,
                        )
                if zx:
                    # z-slab export for the NEXT group's patches (VMEM
                    # extraction — see the leapfrog kernel).  Qz uses its
                    # logical n_f = SZ+1, o_f = o+1 (staggered z face).
                    zxcz[slot, :, :, 0:we] = p[slot, :, :, SZ - o : SZ - o + we]
                    zxcz[slot, :, :, we : 2 * we] = p[slot, :, :, o - we : o]
                    zxcz[slot, :, :, 2 * we : 3 * we] = p[slot, :, :, 0:we]
                    zxcz[slot, :, :, 3 * we : 4 * we] = p[slot, :, :, SZ - we : SZ]
                    zxx[slot, :, :, 0:we] = qx[slot, :, :, SZ - o : SZ - o + we]
                    zxx[slot, :, :, we : 2 * we] = qx[slot, :, :, o - we : o]
                    zxx[slot, :, :, 2 * we : 3 * we] = qx[slot, :, :, 0:we]
                    zxx[slot, :, :, 3 * we : 4 * we] = qx[slot, :, :, SZ - we : SZ]
                    zxy[slot, :, :, 0:we] = qy[slot, :, :, SZ - o : SZ - o + we]
                    zxy[slot, :, :, we : 2 * we] = qy[slot, :, :, o - we : o]
                    zxy[slot, :, :, 2 * we : 3 * we] = qy[slot, :, :, 0:we]
                    zxy[slot, :, :, 3 * we : 4 * we] = qy[slot, :, :, SZ - we : SZ]
                    zxcz[slot, :, :, Z_CZ_BAND : Z_CZ_BAND + we] = qz[slot, :, :, SZ - o : SZ - o + we]
                    zxcz[slot, :, :, Z_CZ_BAND + we : Z_CZ_BAND + 2 * we] = qz[
                        slot, :, :, o + 1 - we : o + 1
                    ]
                    zxcz[slot, :, :, Z_CZ_BAND + 2 * we : Z_CZ_BAND + 3 * we] = qz[
                        slot, :, :, 0:we
                    ]
                    zxcz[slot, :, :, Z_CZ_BAND + 3 * we : Z_CZ_BAND + 4 * we] = qz[
                        slot, :, :, SZ + 1 - we : SZ + 1
                    ]
                start_out(tt, slot)
                return 0

            jax.lax.fori_loop(0, nrun, tile, 0)
            wait_out(t_of(nrun - 2), (nrun - 2) % 2)
            wait_out(t_of(nrun - 1), (nrun - 1) % 2)
            if fixup:
                fix_qx.wait()
                fix_qy.wait()

        scopes = dict(
            t=pltpu.VMEM((2, SX, SY, SZ), dt_),
            p=pltpu.VMEM((2, SX, SY, SZ), dt_),
            qx=pltpu.VMEM((2, SX + 8, SY, SZ), dt_),
            qy=pltpu.VMEM((2, SX, SY + 8, SZ), dt_),
            qz=pltpu.VMEM((2, SX, SY, SZ + 128), dt_),
            sp=pltpu.VMEM((SX, SY, SZ), dt_),
            sqx=pltpu.VMEM((SX + 8, SY, SZ), dt_),
            sqy=pltpu.VMEM((SX, SY + 8, SZ), dt_),
            sqz=pltpu.VMEM((SX, SY, SZ + 128), dt_),
            t_is=pltpu.SemaphoreType.DMA((2,)),
            p_is=pltpu.SemaphoreType.DMA((2,)),
            qx_is=pltpu.SemaphoreType.DMA((2,)),
            qy_is=pltpu.SemaphoreType.DMA((2,)),
            qz_is=pltpu.SemaphoreType.DMA((2,)),
            p_os=pltpu.SemaphoreType.DMA((2,)),
            qx_os=pltpu.SemaphoreType.DMA((2,)),
            qy_os=pltpu.SemaphoreType.DMA((2,)),
            qz_os=pltpu.SemaphoreType.DMA((2,)),
            fix_s=pltpu.SemaphoreType.DMA((2,)),
        )
        if zp:
            scopes.update(
                zpcz=pltpu.VMEM((2, SX, SY, 128), dt_),
                zpx=pltpu.VMEM((2, SX + 8, SY, 128), dt_),
                zpy=pltpu.VMEM((2, SX, SY + 8, 128), dt_),
                zp_is=pltpu.SemaphoreType.DMA((3, 2)),
            )
        if zx:
            scopes.update(
                zxcz=pltpu.VMEM((2, SX, SY, 128), dt_),
                zxx=pltpu.VMEM((2, SX + 8, SY, 128), dt_),
                zxy=pltpu.VMEM((2, SX, SY + 8, 128), dt_),
                zx_os=pltpu.SemaphoreType.DMA((3, 2)),
            )
        pl.run_scoped(body, **scopes)

    vmem_bytes = _tile_bytes(n1, n2, k, bx, by, dt_.itemsize, (2 if zx else 1) if zp else 0)
    out_shape = [
        jax.ShapeDtypeStruct((n0, n1, n2), dt_),
        jax.ShapeDtypeStruct((n0 + 8, n1, n2), dt_),
        jax.ShapeDtypeStruct((n0, n1 + 8, n2), dt_),
        jax.ShapeDtypeStruct((n0, n1, n2 + 128), dt_),
    ]
    if zx:
        out_shape += [
            jax.ShapeDtypeStruct(s, dt_) for s in z_patch_shapes((n0, n1, n2))
        ]
    nbase = 8 if zp else 5
    nouts = len(out_shape)
    call = pl.pallas_call(
        kernel,
        out_shape=tuple(out_shape),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)]
        * (nbase + (nouts if carry else 0)),
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * nouts,
        input_output_aliases=(
            {nbase + j: j for j in range(nouts)} if carry else {}
        ),
        interpret=interp,
        compiler_params=pallas_compiler_params(
            vmem_limit_bytes=_envelope.vmem_limit(vmem_bytes)
        ),
    )
    return jax.jit(call)
