"""Lineage digests: a rolling per-field digest chain over checkpoint bytes.

The at-rest detector of the integrity plane.  `utils.checkpoint` already
CRCs every shard *file* — which vouches for the bytes as written, not for
the state that produced them.  A lineage digest closes that gap: at save
time every process hashes each stored block's payload bytes (the dedup-
space uint8 serialization, hashed from the LIVE arrays before the npz
writer touches them) into per-block sha256 digests that ride the CRC
sidecar; rank 0 folds them into per-field digests and chains each against
the previous generation's chain entry::

    digest_f  = sha256( sorted per-block digests of field f )
    chain_f   = sha256( prev_chain_f + digest_f )     (genesis: digest_f)

`verify_checkpoint` recomputes the per-block digests by STREAMING the npz
members in bounded chunks (never materializing a shard — the RSS
satellite) and can now tell two corruption classes apart:

* CRC mismatch, lineage whatever  -> shard damaged ON DISK (bit rot, torn
  write) — the pre-existing class;
* CRC clean, lineage mismatch    -> the written bytes never matched the
  live state: the state was already corrupt (or was corrupted in the
  writer path) WHEN SAVED — a poisoned generation that
  `latest_checkpoint`'s fallback must walk past, because restoring it
  would resurrect the corruption the run just escaped.

Chain entries reset to genesis when the previous generation's lineage is
absent or has a different field count (elastic topology changes re-shard
blocks but preserve fields; a field-set change is a different run).
jax-free on purpose: `utils.checkpoint` imports this at module level.
"""

from __future__ import annotations

import hashlib
import json
import os
import zipfile

import numpy as np

__all__ = [
    "block_digest",
    "field_digests_from_blocks",
    "chain_field_digests",
    "stream_npz_block_digests",
    "lineage_problem",
    "read_prev_chain",
]

#: bounded read size of the streaming verifier (bytes)
STREAM_CHUNK = 1 << 20


def block_digest(payload: np.ndarray) -> str:
    """sha256 hex of one stored block's payload bytes (dedup-space uint8
    serialization).  Zero-copy: hashes the buffer via memoryview."""
    return hashlib.sha256(memoryview(np.ascontiguousarray(payload))).hexdigest()


def field_digests_from_blocks(blocks: dict, nfields: int) -> list[str]:
    """Fold per-block digests into one digest per field.

    ``blocks`` maps payload keys (``f<i>_o<offsets>``) to sha256 hex.  The
    fold is over ``key=digest`` lines sorted by key — deterministic for
    any process count and block assignment, so a 2-proc save and its
    4-proc elastic re-save of the SAME state produce different block maps
    but the same per-field digest only when the serialized bytes agree
    blockwise (block boundaries move with the topology, so cross-topology
    equality is not promised — the chain resets on such transitions).
    """
    per_field = [hashlib.sha256() for _ in range(nfields)]
    for key in sorted(blocks):
        try:
            idx = int(key.split("_", 1)[0][1:])
        except (ValueError, IndexError):
            continue
        if 0 <= idx < nfields:
            per_field[idx].update(f"{key}={blocks[key]}\n".encode())
    return [h.hexdigest() for h in per_field]


def chain_field_digests(field_digests: list[str],
                        prev_chain: list[str] | None) -> list[str]:
    """Roll the per-field digest chain forward one generation."""
    if prev_chain is None or len(prev_chain) != len(field_digests):
        prev_chain = [""] * len(field_digests)  # genesis / topology reset
    return [
        hashlib.sha256((prev + cur).encode()).hexdigest()
        for prev, cur in zip(prev_chain, field_digests)
    ]


def _stream_member_digest(f) -> str:
    """sha256 hex of one npy member's payload bytes, header skipped,
    read in `STREAM_CHUNK` slices (never the whole member at once)."""
    version = np.lib.format.read_magic(f)
    if version == (1, 0):
        np.lib.format.read_array_header_1_0(f)
    else:
        np.lib.format.read_array_header_2_0(f)
    h = hashlib.sha256()
    while True:
        chunk = f.read(STREAM_CHUNK)
        if not chunk:
            break
        h.update(chunk)
    return h.hexdigest()


def stream_npz_block_digests(path: str) -> dict:
    """Per-block payload digests of one shard file, streamed.

    Opens the npz as a zip and pipes each payload member (``f<i>_o…``,
    shape sidecars skipped) through sha256 in `STREAM_CHUNK` reads —
    bounded RSS however large the shard (the integrity-sweep satellite:
    the ``rss_growth`` anomaly rule must not fire on our own verifier).
    """
    out: dict = {}
    with zipfile.ZipFile(path) as zf:
        for name in zf.namelist():
            key = name[:-4] if name.endswith(".npy") else name
            if key.endswith("_shape") or not key.startswith("f"):
                continue
            with zf.open(name) as f:
                out[key] = _stream_member_digest(f)
    return out


def lineage_problem(step_dir: str, meta: dict) -> str | None:
    """Why this generation's stored bytes contradict its lineage, or None.

    Recomputes every shard's per-block digests (streaming) and folds them
    into per-field digests compared against the manifest's ``lineage``
    section.  Only called after the CRC pass succeeded, so a mismatch here
    means the CRC-clean file bytes never matched the live state that was
    being saved — the poisoned-at-save class (module docstring).  Metas
    without a ``lineage`` section (older generations) verify as clean.
    """
    lineage = meta.get("lineage")
    if not lineage:
        return None
    want = [f.get("digest") for f in lineage.get("fields", ())]
    nfields = len(want)
    if not nfields:
        return None
    blocks: dict = {}
    try:
        # ``shards`` maps shard FILENAME -> {"bytes", "crc32"} (the
        # format-2 manifest shape); only the names matter here.
        for fname in meta.get("shards", ()) or ():
            path = os.path.join(step_dir, fname)
            blocks.update(stream_npz_block_digests(path))
    except (OSError, zipfile.BadZipFile, ValueError, KeyError) as e:
        return f"lineage recompute failed: {e}"
    got = field_digests_from_blocks(blocks, nfields)
    bad = [
        i for i, (w, g) in enumerate(zip(want, got)) if w is not None and w != g
    ]
    if bad:
        names = meta.get("fields") or []
        label = ", ".join(
            str(names[i].get("name") or f"field{i}") if i < len(names) and
            isinstance(names[i], dict) else f"field{i}"
            for i in bad
        )
        return (
            f"lineage mismatch (state was already corrupt when saved) in "
            f"{label}: stored bytes do not reproduce the manifest's "
            f"per-field digest chain"
        )
    return None


def read_prev_chain(prev_meta_path: str | None, nfields: int) -> list[str] | None:
    """The previous generation's chain entries, or None (genesis)."""
    if not prev_meta_path or not os.path.exists(prev_meta_path):
        return None
    try:
        with open(prev_meta_path) as f:
            prev = json.load(f)
        chain = [
            f.get("chain", "") for f in prev.get("lineage", {}).get("fields", ())
        ]
    except (OSError, ValueError):
        return None
    if len(chain) != nfields:
        return None
    return chain
