"""Silent-data-corruption integrity plane (docs/robustness.md).

Every pre-existing integrity mechanism in this stack is at-rest or
non-finite-only: checkpoint CRC manifests vouch for bytes as written,
result digests fire at retirement, `check_fields` sees NaN/Inf.  A
*finite* bit flip in a send slab or one wrong FMA from a mercurial core
passes all of them and propagates through every subsequent halo exchange —
at fleet scale (ROADMAP north star) that failure mode is a statistical
certainty, and the reference's whole contract (PAPER.md: every overlap
copy faithful) is void once it happens.  This package is the in-flight
plane that produces the evidence the existing escalation machinery
(supervisor PR 13, fleet controller PR 15) needs to quarantine the liar:

* `transport` — per-hop XOR-fold checksum words riding the coalesced
  packed `ppermute` payload (`ops.halo._packed_transport`); the receiver
  recomputes over the landed slab, a mismatch raises `IntegrityError`
  implicating the SENDER.  Armed by ``IGG_INTEGRITY=1``; no extra
  collective, hop count unchanged.
* `audit` — the shadow-step audit: at ``IGG_INTEGRITY_EVERY`` cadence the
  guarded time loop re-executes the just-committed step from retained
  pre-step state and bit-compares (replicated psum verdict) — catches
  wrong COMPUTE, which no transport checksum can.
* `lineage` — rolling per-field digest chains in the checkpoint manifest:
  `verify_checkpoint` can now tell "shard damaged on disk" (CRC) from
  "state was already corrupt when saved" (CRC clean, lineage mismatch),
  and `latest_checkpoint` walks past poisoned generations.
* `plan` — the rank-uniformity contract the ``collective-consistency``
  analyzer censuses (`analysis.collectives.integrity_plan_censuses`).

Escalation: every detector trip dumps a ``reason=sdc`` flight bundle
naming the implicated rank; `supervisor.classify` maps it to the
``silent_corruption`` class whose policy verdict is QUARANTINE — a lying
core re-lies, so restart-in-place is exactly wrong; `fleet.policy` treats
an SDC-struck pool as a device-subset quarantine candidate.  The
``bit_flip`` fault kind (`utils.resilience`) proves every detector live
by injection.
"""

from .audit import AuditReport, audit_fields
from .errors import IntegrityError
from .lineage import (
    block_digest,
    chain_field_digests,
    field_digests_from_blocks,
    lineage_problem,
    read_prev_chain,
    stream_npz_block_digests,
)
from .plan import integrity_plan
from .transport import (
    TransportCollector,
    active_collector,
    append_checksum,
    fold_words,
    split_and_verify,
    use_collector,
)

__all__ = [
    "AuditReport",
    "IntegrityError",
    "TransportCollector",
    "active_collector",
    "append_checksum",
    "audit_fields",
    "block_digest",
    "chain_field_digests",
    "field_digests_from_blocks",
    "fold_words",
    "integrity_plan",
    "lineage_problem",
    "read_prev_chain",
    "split_and_verify",
    "stream_npz_block_digests",
    "use_collector",
]
