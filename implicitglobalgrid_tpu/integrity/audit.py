"""Shadow-step audit: bit-compare a re-executed step against its commit.

The mercurial-core detector (wrong *compute*, not wrong transport): at the
``IGG_INTEGRITY_EVERY`` cadence, `utils.resilience.guarded_time_loop`
retains a pre-step snapshot, re-executes the just-committed step from it
and bit-compares the two results here.  XLA programs are run-to-run
deterministic on healthy hardware (same executable, same inputs, same
partitioning), so ANY difference — one flipped mantissa bit included — is
a finding; the interpret-mode matrix in ``tests/test_integrity.py`` pins
that healthy re-execution is bit-identical across all three models.

The comparison follows the `utils.resilience._probe_fn` discipline: each
block reduces its field pairs to per-field mismatch flags over the
*bitcast word view* (NaN-proof — NaN != NaN would hide a corrupted NaN
under a float compare), scatters them into a ``dims``-shaped one-hot and
`psum`s over every mesh axis.  The verdict is therefore REPLICATED: every
rank sees the same report, raises (or not) together, and the rank-uniform
cadence + replicated verdict are exactly what
`analysis.collectives.integrity_plan_censuses` pins — a rank-local audit
verdict driving a collective would be the SPMD-divergence class the
analyzer exists to catch.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

__all__ = ["AuditReport", "audit_fields"]


@dataclasses.dataclass(frozen=True)
class AuditReport:
    """Outcome of one shadow-step bit-compare."""

    names: tuple[str, ...]
    #: field name -> block coords whose re-execution differed bitwise
    bad_blocks: dict
    #: ranks owning a differing block (the quarantine targets)
    implicated_ranks: tuple[int, ...]

    @property
    def ok(self) -> bool:
        return not self.bad_blocks

    def summary(self) -> str:
        if self.ok:
            return f"bit-identical re-execution ({', '.join(self.names)})"
        parts = [
            f"{name}: block(s) {', '.join(str(c) for c in coords)}"
            for name, coords in self.bad_blocks.items()
        ]
        return (
            "re-execution differs bitwise in " + "; ".join(parts)
            + f" (implicated rank(s) {list(self.implicated_ranks)})"
        )


_compare_cache: dict = {}


def _clear_caches() -> None:
    _compare_cache.clear()


def _compare_fn(gg, shapes_dtypes):
    """Build (and cache) the jitted bitwise-difference probe.

    One program per (epoch, signature), shaped exactly like
    `utils.resilience._probe_fn`: per-block word-view inequality reduced
    to per-field flags, one-hot scattered at the block's coords, `psum`med
    over all mesh axes into a replicated ``(nfields, *dims)`` int32 array.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..ops.halo import _flat_words
    from ..parallel.topology import AXIS_NAMES, NDIMS
    from ..utils.compat import shard_map

    key = (gg.epoch, shapes_dtypes)
    fn = _compare_cache.get(key)
    if fn is not None:
        return fn

    nfields = len(shapes_dtypes)

    def block_flags(args):
        committed, redone = args[:nfields], args[nfields:]
        flags = []
        for a, b in zip(committed, redone):
            # word-view compare: bit-exact, NaN bit patterns included
            flags.append(
                jnp.any(_flat_words(a) != _flat_words(b)).astype(jnp.int32)
            )
        return jnp.stack(flags)

    if gg.nprocs == 1 and not gg.force_spmd:
        fn = jax.jit(
            lambda *f: block_flags(f).reshape((nfields, 1, 1, 1))
        )
        _compare_cache[key] = fn
        return fn

    def per_block(*args):
        flags = block_flags(args)  # (nfields,)
        onehot = jnp.zeros((nfields, *gg.dims), jnp.int32)
        for i, (shp, _) in enumerate(shapes_dtypes):
            # replicated axes clamp to 0 (the `_probe_fn` discipline: a
            # lower-rank field's replicas must scatter at one coord)
            coords = tuple(
                lax.axis_index(AXIS_NAMES[d])
                if d < len(shp) and gg.dims[d] > 1
                else jnp.int32(0)
                for d in range(NDIMS)
            )
            onehot = lax.dynamic_update_slice(
                onehot, flags[i].reshape((1, 1, 1, 1)), (jnp.int32(i), *coords)
            )
        return lax.psum(onehot, AXIS_NAMES)

    specs = tuple(P(*AXIS_NAMES[: len(s)]) for s, _ in shapes_dtypes)
    mapped = shard_map(
        per_block, mesh=gg.mesh, in_specs=specs + specs, out_specs=P(),
        check_vma=False,
    )
    fn = jax.jit(mapped)
    _compare_cache[key] = fn
    return fn


def audit_fields(committed: tuple, redone: tuple,
                 names: Sequence[str] | None = None) -> AuditReport:
    """Bit-compare a committed state tuple against its re-execution.

    Returns an `AuditReport` naming every field and block whose bits
    differ plus the owning ranks.  Replicated verdict (module docstring):
    multi-host callers all see the same report.
    """
    from ..ops.halo import local_shape
    from ..parallel import grid as _grid
    from ..parallel import topology

    _grid.check_initialized()
    gg = _grid.global_grid()
    if len(committed) != len(redone):
        raise ValueError(
            f"audit_fields: committed has {len(committed)} fields, the "
            f"re-execution {len(redone)}."
        )
    if names is None:
        names = tuple(f"field{i}" for i in range(len(committed)))
    else:
        names = tuple(names)
        if len(names) != len(committed):
            raise ValueError(
                f"names has {len(names)} entries for {len(committed)} fields."
            )
    sig = tuple(
        (local_shape(A, gg), str(A.dtype)) for A in committed
    )
    flags = np.asarray(_compare_fn(gg, sig)(*committed, *redone))
    bad: dict = {}
    ranks: set[int] = set()
    for i, name in enumerate(names):
        coords = tuple(
            tuple(int(c) for c in idx) for idx in np.argwhere(flags[i])
        )
        if coords:
            bad[name] = coords
            for c in coords:
                ranks.add(topology.rank_of_coords(c, gg.dims))
    return AuditReport(
        names=names, bad_blocks=bad, implicated_ranks=tuple(sorted(ranks))
    )
