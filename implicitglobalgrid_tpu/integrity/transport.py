"""Transport checksums for the coalesced packed halo transport.

`ops.halo._packed_transport` concatenates every same-width field slab of
one (dimension, direction) hop into a single unsigned-word buffer and
moves it with ONE `lax.ppermute` pair.  When a `TransportCollector` is
active (installed by `ops.halo.update_halo`'s host entry while
``IGG_INTEGRITY=1``), the sender appends one extra word — an XOR fold of
the payload words — to that same buffer, and the receiver recomputes the
fold over the landed payload and compares it against the landed checksum
word.  SPMD-safe by construction: both sides evaluate the same pure
function of data they already hold, so the hop count is unchanged and the
payload grows by exactly one word per (dimension, width-group, direction).

The fold operates on the *word view* (`ops.halo._flat_words` bitcast), so
``-0.0`` and NaN payload bytes are bitwise-covered — the whole point of an
SDC detector is that a flipped mantissa bit is still a perfectly finite
float.  An XOR fold misses only even-multiplicity identical-position
flips, far below the single-bit-upset model this plane targets.

The collector is trace-time state: `ops.halo._global_update_fn` builds the
integrity-enabled exchange program under `use_collector`, the traced
mismatch flags escape as one extra tiny program output, and the host entry
reads its OWN addressable flag blocks — a rank-local verdict that raises
`IntegrityError` locally (escalation via the ``sdc`` flight bundle) and
never drives a collective.
"""

from __future__ import annotations

import contextlib

from .errors import IntegrityError

__all__ = [
    "IntegrityError",
    "TransportCollector",
    "active_collector",
    "use_collector",
    "fold_words",
    "append_checksum",
    "split_and_verify",
]


class TransportCollector:
    """Trace-time registry of one integrity-enabled exchange build.

    ``records`` — host metadata per checksummed hop, in trace order:
    ``{"dim", "width", "fields"}`` (``fields`` = positional indices of the
    fields packed into that width group).  ``flags`` — the matching traced
    ``(bad_lo, bad_hi)`` mismatch booleans.  The collector lives in the jit
    cache next to its compiled program: the records fill during the first
    (tracing) call and label the flag outputs of every later cached call.

    ``flip_proc`` — an armed ``bit_flip:…:transport`` injection target:
    the first checksummed hop traced after arming XORs one payload word
    bit on that rank's send buffers (AFTER the checksum fold — in-flight
    corruption, exactly what the receiver's recompute must catch).
    """

    def __init__(self, flip_proc: int | None = None):
        self.records: list[dict] = []
        self.flags: list[tuple] = []
        self.flip_proc = flip_proc

    def record(self, *, dim, width, fields, bad_lo, bad_hi) -> None:
        self.records.append(
            {"dim": int(dim), "width": int(width), "fields": tuple(fields)}
        )
        self.flags.append((bad_lo, bad_hi))

    def take_flip(self) -> int | None:
        """Consume the armed in-flight flip target (first hop only)."""
        proc, self.flip_proc = self.flip_proc, None
        return proc

    def stacked_flags(self):
        """The traced flags as one ``(nrecords, 2)`` int32 array."""
        import jax.numpy as jnp

        if not self.flags:
            return jnp.zeros((0, 2), dtype=jnp.int32)
        return jnp.stack(
            [jnp.stack([lo.astype(jnp.int32), hi.astype(jnp.int32)])
             for lo, hi in self.flags]
        )


_active: TransportCollector | None = None


def active_collector() -> TransportCollector | None:
    """The collector of the integrity-enabled exchange being traced, or
    None — the signal `_packed_transport` keys checksum emission on."""
    return _active


@contextlib.contextmanager
def use_collector(col: TransportCollector):
    global _active
    prev = _active
    _active = col
    try:
        yield col
    finally:
        _active = prev


def fold_words(buf):
    """XOR fold of a 1-D unsigned-word buffer to one scalar word."""
    import jax.numpy as jnp
    from jax import lax

    if buf.size == 0:
        return jnp.zeros((), dtype=buf.dtype)
    return lax.reduce(
        buf, jnp.zeros((), dtype=buf.dtype), lax.bitwise_xor, (0,)
    )


def append_checksum(buf):
    """``payload ++ [fold(payload)]`` — the wire form of one hop buffer."""
    import jax.numpy as jnp

    return jnp.concatenate([buf, fold_words(buf)[None]])


def split_and_verify(recv):
    """Landed hop buffer -> ``(payload, mismatch)``.

    ``mismatch`` is a traced boolean: recomputed fold over the landed
    payload words != the landed checksum word.  PROC_NULL edges are safe
    by construction — `ops.halo._permute_slabs` already substituted the
    keep buffer, whose checksum was computed from the same words.
    """
    payload, chk = recv[:-1], recv[-1]
    return payload, fold_words(payload) != chk
