"""Structured error type of the silent-data-corruption integrity plane."""

from __future__ import annotations

__all__ = ["IntegrityError"]


class IntegrityError(RuntimeError):
    """A detector of the integrity plane caught silent data corruption.

    Carries enough structure for the escalation path (``reason=sdc``
    flight bundle → `supervisor.classify` → quarantine verdict) to name
    the implicated rank without re-parsing the message:

    ``detector``         ``"transport_checksum"`` | ``"shadow_audit"`` |
                         ``"lineage_digest"``
    ``implicated_rank``  the rank whose data (or storage) is wrong — for a
                         transport mismatch the SENDER, not the receiver
                         that noticed; None when unattributable
    ``step``             1-based time-loop step (None outside a loop)
    ``dim``              exchange dimension of a transport mismatch
    ``direction``        ``"lo"`` | ``"hi"`` receive direction
    ``fields``           names/indices of the covered fields
    """

    def __init__(self, message, *, detector=None, implicated_rank=None,
                 step=None, dim=None, direction=None, fields=()):
        super().__init__(message)
        self.detector = detector
        self.implicated_rank = implicated_rank
        self.step = step
        self.dim = dim
        self.direction = direction
        self.fields = tuple(fields)
