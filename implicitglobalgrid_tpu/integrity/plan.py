"""The integrity plane's rank-uniformity contract (analyzer census).

`integrity_plan` states, per simulated RANK, the ordered host-transport
collective schedule one integrity-plane observation implies — the input
of `analysis.collectives.integrity_plan_censuses`.  The plane's SPMD
discipline has two halves, and the census pins both:

* the transport checksum adds NO collective: the checksum word rides the
  existing `ppermute` payload, verification is a pure local recompute,
  and a mismatch raises LOCALLY (escalation is the out-of-band ``sdc``
  flight bundle) — so the plan for an exchange is one entry per hop
  whether or not checksums are armed, identical on every rank;
* the shadow audit's one extra collective (the replicated bit-compare
  `psum`) is keyed ONLY on the rank-uniform cadence (`IGG_INTEGRITY_EVERY`
  arrives identically via the environment tier), never on a rank-local
  verdict — a rank-local integrity verdict driving a collective is the
  `_gather_chunked` deadlock class wearing an integrity hat.
"""

from __future__ import annotations

__all__ = ["integrity_plan"]


def integrity_plan(is_root: bool, *, checksums: bool, audit_every: int,
                   step: int, exchange_dims: int = 1) -> tuple:
    """The ordered host-transport schedule of ONE guarded step on one rank.

    ``is_root`` exists precisely so the census can prove the schedule
    ignores rank identity (the `ops.gather.collective_plan` contract).
    ``checksums`` — transport checksums armed (``IGG_INTEGRITY=1``);
    ``audit_every`` — shadow-audit cadence (0 = off); ``step`` — 1-based
    committed step; ``exchange_dims`` — dimensions the step's halo
    exchange permutes.  All four are rank-uniform inputs: the env tier
    delivers the knobs identically, the step counter advances in lockstep.
    """
    del is_root  # rank identity must not shape the schedule
    plan = []
    for d in range(exchange_dims):
        # one ppermute pair per exchanged dimension, checksums or not —
        # the checksum word rides the same hop (payload-only delta)
        plan.append(
            ("ppermute_pair", d, "checksummed" if checksums else "plain")
        )
    if audit_every and step % audit_every == 0:
        # the replicated bit-compare reduction: cadence-keyed, never
        # verdict-keyed
        plan.append(("psum", "audit-compare"))
    return tuple(plan)
