"""implicitglobalgrid_tpu — implicit global grids for stencil computations on TPU.

A brand-new TPU-native framework with the capabilities of ImplicitGlobalGrid.jl
(reference mounted at /root/reference): distributed parallelization of
stencil-based 1/2/3-D Cartesian staggered-grid computations with an *implicit*
global grid — global sizes and coordinates are computed from (local size,
device topology, overlap), never materialized.

Where the reference builds an MPI Cartesian process topology and exchanges
halos via CUDA-aware Isend/Irecv with hand-managed pack kernels and pinned
buffers, this framework is idiomatic JAX/XLA: the topology is a TPU-slice
device `Mesh` aligned to the ICI torus, fields are global-block `jax.Array`s
(one local block per device), halo exchange compiles to `collective_permute`
inside `shard_map`-ed programs, and gather lowers to a host fetch /
all-gather.  The user-facing promise is the reference's three-function recipe
(`README.md:12` of the reference): take a single-device stencil solver, add
`init_global_grid` / `update_halo` / `finalize_global_grid`, and it scales
over a pod.

Public API (reference parity, `/root/reference/src/ImplicitGlobalGrid.jl:10-21`):
`init_global_grid`, `finalize_global_grid`, `update_halo`, `gather`,
`select_device`, `nx_g`, `ny_g`, `nz_g`, `x_g`, `y_g`, `z_g`, `tic`, `toc` —
plus the TPU-native field toolkit: `zeros`/`ones`/`full`/`from_block_fn`,
`coord_fields`, `block_slice`, and the `stencil` decorator that turns
single-block solver code into a pod-wide SPMD program.

Production resilience (docs/robustness.md): guarded multi-host bring-up
(retry/backoff/deadline + `watchdog`), NaN/Inf guards (`check_fields`,
`RunGuard`), and per-process checkpoint/restart (`save_checkpoint` /
`restore_checkpoint` / `latest_checkpoint`) with an `IGG_FAULT_INJECT`
harness proving the recovery paths.

Observability (docs/observability.md): a process-local metrics registry +
per-process JSONL event log (`utils.telemetry`), per-step wall-time /
steps-per-s / ``T_eff`` instrumentation in every model's run loop, named
profiler annotations on the pipelined ring/interior passes and the slab
exchange, and `telemetry_snapshot` / `dump_metrics` (JSON + Prometheus
text) as the public surface.  On top: the cross-rank observability plane
(`utils.tracing`) — host spans (`trace_span`) into a bounded ring,
per-rank trace dumps (`dump_trace`) mergeable into ONE barrier-aligned
Chrome/Perfetto timeline (``scripts/igg_trace.py``), an all-ranks
straggler probe at heartbeat cadence (``skew.*`` gauges), and a crash
flight recorder (``flight_<rank>.json``) dumped on guard trips, watchdog
deadlines and injected crashes.  The LIVE half (`utils.liveplane`,
``IGG_METRICS_PORT``): per-rank HTTP scrape endpoints (``/metrics`` /
``/healthz`` / ``/spans``), rolling SLO windows (``slo.*`` gauges over
``IGG_SLO_WINDOW_S`` windows), an in-flight anomaly-rule engine firing
structured ``alert.*`` events, and ``scripts/igg_top.py`` aggregating any
set of rank endpoints into one cluster view.  ``IGG_TELEMETRY=0``
disables it all on a zero-allocation branch (the server never starts).

Static analysis (docs/static-analysis.md): ``igg.analysis`` — a pass
registry running over the package AST, traced jaxprs of the public entry
points, and optimized HLO; ships a cross-rank collective-consistency
(deadlock) detector, a trace-time knob-binding lint, a Pallas aliasing
lint, and the suite-wide overlap-independence check
(``scripts/igg_lint.py`` is the CLI; the full suite runs in tier-1).
"""

from .parallel.grid import (
    GlobalGrid,
    check_initialized,
    finalize_global_grid,
    get_global_grid,
    global_grid,
    grid_is_initialized,
    init_global_grid,
    profile_trace,
    select_device,
    set_global_grid,
    tic,
    toc,
)
from .parallel.topology import AXIS_NAMES, NDIMS, NNEIGHBORS_PER_DIM, PROC_NULL
from .parallel import distributed
from .ops.halo import halosize, ol, local_shape, update_halo
from .ops.gather import gather
from .ops.stencil import stencil
from .ops.overlap import hide_communication
from .utils.tools import nx_g, ny_g, nz_g, x_g, y_g, z_g
from .utils.fields import (
    block_slice,
    coord_fields,
    from_block_fn,
    full,
    ones,
    zeros,
)
from .utils.resilience import (
    FieldReport,
    GuardError,
    RunGuard,
    check_fields,
    watchdog,
)
from .utils.checkpoint import (
    latest_checkpoint,
    prune_checkpoints,
    restore_checkpoint,
    save_checkpoint,
    verify_checkpoint,
)
from .utils import liveplane
from .utils import profiling
from .utils import telemetry
from .utils import tracing
from .utils.telemetry import dump_metrics, telemetry_snapshot
from .utils.tracing import dump_trace, trace_span
from . import analysis

__version__ = "0.1.0"

__all__ = [
    # reference API parity
    "init_global_grid",
    "finalize_global_grid",
    "update_halo",
    "gather",
    "select_device",
    "nx_g",
    "ny_g",
    "nz_g",
    "x_g",
    "y_g",
    "z_g",
    "tic",
    "toc",
    "profile_trace",
    # grid state
    "GlobalGrid",
    "global_grid",
    "get_global_grid",
    "set_global_grid",
    "grid_is_initialized",
    "check_initialized",
    "AXIS_NAMES",
    "NDIMS",
    "NNEIGHBORS_PER_DIM",
    "PROC_NULL",
    # TPU-native field toolkit
    "zeros",
    "ones",
    "full",
    "from_block_fn",
    "coord_fields",
    "block_slice",
    "stencil",
    "hide_communication",
    "halosize",
    "ol",
    "local_shape",
    "distributed",
    # resilience subsystem (docs/robustness.md)
    "check_fields",
    "FieldReport",
    "GuardError",
    "RunGuard",
    "watchdog",
    "save_checkpoint",
    "restore_checkpoint",
    "latest_checkpoint",
    "verify_checkpoint",
    "prune_checkpoints",
    # observability subsystem (docs/observability.md)
    "telemetry",
    "telemetry_snapshot",
    "dump_metrics",
    "tracing",
    "trace_span",
    "dump_trace",
    "liveplane",
    "profiling",
    # static-analysis subsystem (docs/static-analysis.md)
    "analysis",
    # batched multi-simulation serving (ISSUE 8; docs/api.md)
    "serving",
    # self-healing run supervisor (docs/robustness.md)
    "supervisor",
    # multi-pool fleet tier (ISSUE 16; docs/serving.md)
    "fleet",
]


def __getattr__(name):
    # Lazy: the serving subsystem pulls the model zoo in and the
    # supervisor/fleet tiers are host-orchestration-only; importing igg
    # itself must stay light (mirrors `models.__getattr__`).
    if name in ("serving", "supervisor", "fleet"):
        import importlib

        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
