"""The versioned, schema-checked on-disk winner table of the autotuner.

One JSON file per ``(backend, topology, model, size, dtype, batch[, extra])``
key, written atomically (`utils.telemetry.atomic_write_json` — the same
temp-file + ``os.replace`` publish as ``bench.py``'s round records, so a
crash mid-search can never leave a half-written entry that poisons every
later lookup).  A cache hit is ZERO search cost: no candidate is measured,
no compile beyond the production program itself.

Layers: lookups read the PRIMARY directory (``IGG_TUNE_CACHE`` env, else
``~/.cache/implicitglobalgrid_tpu/tune``) first and fall back to the
committed SEED layer (`SEED_DIR`, shipped in the package) — chip-measured
winners ingested from the ``BENCH_r*.json`` trajectory by ``igg_tune.py
seed``, so environments that cannot re-measure (no chip, CI) still apply
the recorded winners.  Writes always go to the primary layer.

Refusal is the schema's job: a version mismatch, a corrupt file, a key
drift or an unknown config field makes the lookup a MISS (counted by
`tune.cache_miss`), never a crash and never a silently-applied stale
config.  The committed seed layer is additionally gated by the
``tune-cache-valid`` analyzer (`analysis.tunecache`) in tier-1.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import time

#: Bump on any incompatible change to the entry layout; readers REFUSE
#: other versions (a stale-schema entry is a finding, not a config).
SCHEMA_VERSION = 1

#: The committed seed layer, shipped next to this module.
SEED_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "entries")

from .space import CONFIG_FIELDS, K_LADDER, MODELS


def schedule_class(model: str, nsteps: int | None) -> str:
    """The nsteps-derived cadence-admissibility class of a key.

    The winner table deliberately omits ``nsteps`` itself (a winner should
    serve every chunk size that can run it), but the ladder's admissible
    subset ``{w : nsteps % w == 0}`` IS schedule-relevant: keying on the
    CLASS makes two chunk sizes share a winner exactly when they admit the
    same candidates — so a winner searched at one nsteps can never poison,
    thrash, or force re-searching at another.  Porous cadences chunk
    ``npt``, not ``nsteps`` (one class); ``None`` = an nsteps-agnostic key
    (``any`` — matches only other nsteps-agnostic keys).
    """
    if model == "porous_convection3d":
        return "npt"
    if nsteps is None:
        return "any"
    ws = [w for w in K_LADDER if nsteps % w == 0]
    return "w" + ".".join(str(w) for w in ws) if ws else "none"


def default_cache_dir() -> str:
    """``IGG_TUNE_CACHE`` env, else the per-user cache directory."""
    from ..utils.config import tune_cache_env

    env = tune_cache_env()
    if env:
        return env
    return os.path.join(
        os.path.expanduser("~"), ".cache", "implicitglobalgrid_tpu", "tune"
    )


def topology_string(gg) -> str:
    """Canonical topology component of the key — every rank derives the
    identical string from the shared grid geometry (dims, periods,
    overlaps, process count), never from rank identity."""
    return (
        f"dims={'x'.join(str(d) for d in gg.dims)};"
        f"periods={''.join(str(p) for p in gg.periods)};"
        f"overlaps={'x'.join(str(o) for o in gg.overlaps)};"
        f"nprocs={gg.nprocs}"
    )


def make_key(model: str, shape, dtype, *, batch: int = 0, gg=None,
             backend: str | None = None, topology: str | None = None,
             extra: dict | None = None, nsteps: int | None = None) -> dict:
    """The canonical cache key of one tuning point.

    ``batch=0`` = the unbatched cadence; ``>= 1`` = the vmapped ensemble
    cadence (the model hook keys the FLAG as 1 — the collective budget is
    B-invariant, but the vmapped working set tunes separately from the
    unbatched one; a future per-B sweep can key finer without a schema
    change).  ``extra`` carries model-config fields that change NUMERICS
    and therefore key rather than tune (porous ``npt``).  ``nsteps``
    contributes only its cadence-admissibility CLASS (`schedule_class`),
    so chunk sizes with identical ladders share one winner.
    """
    if model not in MODELS:
        raise ValueError(f"unknown model {model!r}; tunable: {sorted(MODELS)}")
    if backend is None:
        import jax

        backend = jax.default_backend()
    if topology is None:
        if gg is None:
            from ..parallel.grid import global_grid

            gg = global_grid()
        topology = topology_string(gg)
    import numpy as np

    return {
        "backend": str(backend),
        "topology": str(topology),
        "model": str(model),
        "size": [int(x) for x in shape],
        "dtype": str(np.dtype(dtype)),
        "batch": int(batch),
        "schedule": schedule_class(model, nsteps),
        "extra": {str(k): extra[k] for k in sorted(extra)} if extra else {},
    }


def key_digest(key: dict) -> str:
    return hashlib.sha1(
        json.dumps(key, sort_keys=True).encode()
    ).hexdigest()[:10]


def entry_filename(key: dict) -> str:
    n0, n1, n2 = key["size"]
    b = f"_b{key['batch']}" if key["batch"] else ""
    return (
        f"{key['model']}_{n0}x{n1}x{n2}_{key['dtype']}{b}_"
        f"{key_digest(key)}.json"
    )


def new_entry(key: dict, config: dict, *, source: str = "search",
              modeled: dict | None = None, measured: dict | None = None,
              tuner: dict | None = None) -> dict:
    """A schema-complete entry (validated before it is returned — a writer
    can never persist what a reader would refuse)."""
    doc = {
        "schema_version": SCHEMA_VERSION,
        "key": key,
        "config": config,
        "modeled": modeled,
        "measured": measured,
        "source": source,
        "created_unix": round(time.time(), 3),
    }
    if tuner is not None:
        doc["tuner"] = tuner
    validate_entry(doc)
    return doc


def validate_entry(doc) -> tuple[dict, dict]:
    """``(key, config)`` of a schema-valid entry; `ValueError` otherwise.

    Validation is strictly structural (version, key fields, config fields
    and types) — whether the config is ADMISSIBLE on the current ladder is
    `admissibility_error`'s question (the analyzer asks both)."""
    if not isinstance(doc, dict):
        raise ValueError("entry is not a JSON object")
    v = doc.get("schema_version")
    if v != SCHEMA_VERSION:
        raise ValueError(
            f"schema version {v!r} is not the supported {SCHEMA_VERSION} — "
            f"refusing the entry (re-run the search or re-seed)"
        )
    key = doc.get("key")
    if not isinstance(key, dict):
        raise ValueError("entry has no key object")
    for field, typ in (("backend", str), ("topology", str), ("model", str),
                       ("dtype", str), ("batch", int), ("schedule", str)):
        if not isinstance(key.get(field), typ):
            raise ValueError(f"key.{field} missing or not a {typ.__name__}")
    size = key.get("size")
    if (
        not isinstance(size, list) or len(size) != 3
        or not all(isinstance(x, int) and x > 0 for x in size)
    ):
        raise ValueError(f"key.size must be 3 positive ints, got {size!r}")
    if key["model"] not in MODELS:
        raise ValueError(f"key.model {key['model']!r} is not a tunable model")
    config = doc.get("config")
    if not isinstance(config, dict):
        raise ValueError("entry has no config object")
    unknown = sorted(set(config) - set(CONFIG_FIELDS))
    if unknown:
        raise ValueError(
            f"config field(s) {unknown} are not tunable kwargs "
            f"{CONFIG_FIELDS} — a tuned config must be a pure substitution "
            f"of existing kwargs"
        )
    k = config.get("fused_k")
    if k is not None and (not isinstance(k, int) or k < 2 or k % 2 or k > 8):
        raise ValueError(f"config.fused_k={k!r} outside the even [2, 8] ladder")
    tile = config.get("fused_tile")
    if tile is not None:
        if (
            not isinstance(tile, (list, tuple)) or len(tile) != 2
            or not all(isinstance(x, int) and x > 0 for x in tile)
        ):
            raise ValueError(f"config.fused_tile={tile!r} must be 2 positive ints")
        if k is None:
            raise ValueError("config.fused_tile without fused_k")
    w = config.get("exchange_every")
    if w is not None and (not isinstance(w, int) or w < 1):
        raise ValueError(f"config.exchange_every={w!r} must be an int >= 1")
    for flag in ("pipelined", "coalesce"):
        if flag in config and not isinstance(config[flag], (bool, type(None))):
            raise ValueError(f"config.{flag}={config[flag]!r} must be bool/None")
    if not (doc.get("source") or "").strip():
        raise ValueError("entry has no source (provenance is mandatory)")
    return key, config


def admissibility_error(key: dict, config: dict) -> str | None:
    """Why the entry's config is not currently admissible, or None.

    The analyzer's second gate: the tile must clear the kernel envelope's
    ``IGG_VMEM_MB`` ladder for the keyed size/dtype, and a porous width
    must be accepted by the kernel builder's PT schedule."""
    import numpy as np

    from . import space as _space

    shape = tuple(key["size"])
    itemsize = int(np.dtype(key["dtype"]).itemsize)
    k = config.get("fused_k")
    if k is not None:
        kmod = _space.kernel_module(key["model"])
        tile = config.get("fused_tile")
        bx, by = tile if tile is not None else (None, None)
        err = kmod.fused_support_error(shape, k, itemsize, bx, by)
        if err is not None:
            return f"fused_k={k} tile={tile}: {err}"
        if key["model"] == "porous_convection3d":
            from ..models.porous_convection3d import _pt_schedule

            npt = key.get("extra", {}).get("npt")
            if npt is None:
                return "porous entry without key.extra.npt (npt keys, not tunes)"
            if not _pt_schedule(int(npt), k)[1]:
                return f"npt={npt} leaves no even kernel chunk at w={k}"
    return None


class TuneCache:
    """The layered winner table (see module docstring).

    ``primary=None`` resolves `default_cache_dir` per call, so a test (or
    rank) flipping ``IGG_TUNE_CACHE`` is honored without rebuilding."""

    def __init__(self, primary: str | None = None, fallbacks=None):
        self._primary = primary
        self.fallbacks = tuple(
            fallbacks if fallbacks is not None else (SEED_DIR,)
        )
        self.last_refusal: str | None = None

    @property
    def primary(self) -> str:
        return self._primary or default_cache_dir()

    def _layers(self):
        return (self.primary,) + self.fallbacks

    def path_for(self, key: dict, layer: str | None = None) -> str:
        return os.path.join(layer or self.primary, entry_filename(key))

    def lookup(self, key: dict) -> dict | None:
        """The entry for ``key`` from the first layer that holds a VALID
        one; None on miss.  Refusals (corrupt file, schema mismatch, key
        drift) are recorded on ``last_refusal`` and fall through to the
        next layer — a bad entry degrades to the default config, never to
        a crash."""
        self.last_refusal = None
        for layer in self._layers():
            path = self.path_for(key, layer)
            if not os.path.exists(path):
                continue
            try:
                with open(path, encoding="utf-8") as f:
                    doc = json.load(f)
            except ValueError as e:
                self.last_refusal = f"{path}: corrupt JSON ({e})"
                continue
            except OSError as e:
                # unreadable (permissions, stale NFS handle, a directory
                # squatting on the name): the never-crash contract says
                # degrade to the next layer / the default config
                self.last_refusal = f"{path}: unreadable ({e})"
                continue
            try:
                got_key, _config = validate_entry(doc)
            except ValueError as e:
                self.last_refusal = f"{path}: {e}"
                continue
            if got_key != key:
                self.last_refusal = (
                    f"{path}: key drift — the file's key is not the "
                    f"looked-up key (digest collision or a hand edit)"
                )
                continue
            return doc
        return None

    def store(self, key: dict, entry: dict) -> str:
        """Atomically publish ``entry`` into the primary layer."""
        validate_entry(entry)
        from ..utils.telemetry import atomic_write_json

        os.makedirs(self.primary, exist_ok=True)
        path = self.path_for(key)
        atomic_write_json(path, entry, indent=1)
        return path

    def entries(self):
        """Every (path, doc-or-None) across the layers, primary first —
        ``None`` doc = unparseable file (the CLI's ``show`` lists both)."""
        out = []
        seen = set()
        for layer in self._layers():
            for path in sorted(glob.glob(os.path.join(layer, "*.json"))):
                name = os.path.basename(path)
                if name in seen:
                    continue  # primary shadows the seed layer
                seen.add(name)
                try:
                    with open(path, encoding="utf-8") as f:
                        out.append((path, json.load(f)))
                except (OSError, ValueError):
                    out.append((path, None))
        return out

    def clear(self) -> int:
        """Delete the PRIMARY layer's entries (the committed seed layer is
        repo content — ``igg_tune.py clear`` never touches it)."""
        n = 0
        for path in glob.glob(os.path.join(self.primary, "*.json")):
            os.remove(path)
            n += 1
        return n


# -- offline seeding from the committed bench trajectory ----------------------

#: Which bench extras seed which keys.  Each row: the dotted extras path of
#: a measured teff, the tuning point the bench ran it at (bench.py is the
#: source of truth for those configs — a 1-chip grid, default overlap 2),
#: and the winner config the measurement belongs to.  Only extras that ran
#: the REAL kernel path (``path == "pallas-fused"``) seed — an XLA-fallback
#: record would seed a config the winner never actually measured.
SEEDABLE = (
    # "nsteps" = the chunk the bench ran (bench.py: chunk=24 for all three
    # fused configs) — it keys only through its admissibility CLASS
    # (`schedule_class`; 24 admits the whole even ladder).
    {"path": "diffusion_pallas_fused4", "model": "diffusion3d",
     "size": (256, 256, 256), "dtype": "float32", "nsteps": 24,
     "config": {"fused_k": 4}, "extra": None},
    {"path": "diffusion_512_pallas_fused4", "model": "diffusion3d",
     "size": (512, 512, 512), "dtype": "float32", "nsteps": 24,
     "config": {"fused_k": 4, "fused_tile": [32, 128]}, "extra": None},
    {"path": "acoustic_256_pallas_fused6", "model": "acoustic3d",
     "size": (256, 256, 256), "dtype": "float32", "nsteps": 24,
     "config": {"fused_k": 6}, "extra": None},
    {"path": "porous_256_pallas_fused.npt12_w6", "model": "porous_convection3d",
     "size": (256, 256, 256), "dtype": "float32",
     "config": {"fused_k": 6}, "extra": {"npt": 12},
     "provenance_from": "porous_256_pallas_fused"},
    {"path": "porous_256_pallas_fused.npt10_w6_ragged",
     "model": "porous_convection3d",
     "size": (256, 256, 256), "dtype": "float32",
     "config": {"fused_k": 6}, "extra": {"npt": 10},
     "provenance_from": "porous_256_pallas_fused"},
)

#: The bench rounds' 1-chip topology (bench.py tears the grid down and
#: re-inits per config with default overlaps and no periodicity).
BENCH_TOPOLOGY = "dims=1x1x1;periods=000;overlaps=2x2x2;nprocs=1"


def _extras_get(extras: dict, dotted: str):
    node = extras
    for part in dotted.split("."):
        if not isinstance(node, dict):
            return None
        node = node.get(part)
    return node


def seed_from_bench(repo_root: str, cache: TuneCache | None = None, *,
                    backend: str = "tpu", write: bool = True):
    """Ingest the committed ``BENCH_r*.json`` rounds into seed entries.

    The NEWEST round carrying each seedable extra wins (the trajectory's
    own convention); provenance (``source: seed:bench_rNN``) is recorded
    per entry so a reader knows the winner is chip-measured history, not a
    local search.  Returns the entry list; ``write=False`` = dry run.
    """
    from ..analysis.perf import load_bench_records

    cache = cache or TuneCache()
    records, _skipped = load_bench_records(repo_root)
    out = []
    for row in SEEDABLE:
        seeded = None
        for round_n, rec in records:  # ascending: the last hit is newest
            node = _extras_get(rec.get("extras", {}), row["path"])
            if not isinstance(node, dict):
                continue
            teff = node.get("teff")
            prov = node
            if "provenance_from" in row:
                prov = _extras_get(rec.get("extras", {}),
                                   row["provenance_from"]) or {}
            if not isinstance(teff, (int, float)):
                continue
            if prov.get("path") != "pallas-fused":
                continue  # fallback-path record: not this config's number
            seeded = (round_n, float(teff), node.get("t_it_ms"))
        if seeded is None:
            continue
        round_n, teff, t_it_ms = seeded
        key = make_key(
            row["model"], row["size"], row["dtype"], batch=0,
            backend=backend, topology=BENCH_TOPOLOGY, extra=row["extra"],
            nsteps=row.get("nsteps"),
        )
        entry = new_entry(
            key, dict(row["config"]),
            source=f"seed:bench_r{round_n:02d}",
            measured={
                "teff_gbs": teff,
                "t_step_s": (t_it_ms / 1e3) if isinstance(
                    t_it_ms, (int, float)) else None,
                "steps": None,
            },
        )
        if write:
            cache.store(key, entry)
        out.append(entry)
    return out
