"""Search orchestration of the autotuner: measure, decide, broadcast, cache.

One ``resolve_tuned_config`` call is the whole lifecycle of a tuning point:

1. **cache decision** — on multi-process grids RANK 0 ALONE consults the
   on-disk table and the decision rides the existing
   `serving.frontdoor.broadcast_control` host transport.  A rank-keyed
   cache lookup (every rank trusting its own disk) is exactly the
   SPMD-divergence class the ``collective-consistency`` analyzer pins: a
   rank whose local cache hit would skip the measurement collectives its
   peers enter, and the fabric hangs.  `control_plan` states the invariant
   (the per-rank collective schedule ignores rank identity and local cache
   state); the analyzer's census provider checks it
   (`analysis.collectives.tuning_plan_censuses`).
2. **search** (miss only) — every rank enumerates the SAME candidate list
   (`space.candidate_space` is a pure function of the shared grid geometry
   and env), prunes it with the static prior (`space.prune`, top
   ``IGG_TUNE_TOPK``), and measures the survivors TOGETHER with short
   compiled runs (the candidate programs are SPMD: measurement itself is
   collective, so the rank-uniform candidate order is load-bearing).
3. **decide + publish** — rank 0's timings pick the winner, the winner
   broadcasts, rank 0 persists it (`cache.TuneCache.store`, atomic).  Every
   rank applies the identical config; the second call at the same key is a
   pure cache hit (no measurement — pinned by the ``tune.cache_hit`` /
   ``tune.candidates_measured`` counters).

Telemetry (no-op under ``IGG_TELEMETRY=0``, docs/observability.md): the
``igg.tune`` span around the whole resolve, ``tune.cache_hit`` /
``tune.cache_miss``, ``tune.candidates_pruned`` /
``tune.candidates_measured``, ``tune.search_seconds``, and a rank-tagged
``tune.winner`` event carrying the chosen config.
"""

from __future__ import annotations

import json
import time

from ..utils import telemetry as _telemetry
from ..utils import tracing as _tracing
from . import cache as _cache
from . import space as _space


def _topk() -> int:
    from ..utils.config import tune_topk_env

    val = tune_topk_env()
    return 4 if val is None else val


def _tune_steps() -> int:
    from ..utils.config import tune_steps_env

    val = tune_steps_env()
    return 3 if val is None else val


# -- the host-transport collective plan (analyzer contract) -------------------


def control_plan(is_root: bool, hit: bool, n_measured: int) -> tuple:
    """The ordered host-transport collective schedule of ONE resolve.

    ``is_root`` exists precisely so the ``collective-consistency`` census
    can prove the schedule ignores it (the `ops.gather.collective_plan`
    contract): every rank issues the cache-decision broadcast, then — on a
    miss with admissible candidates — the identical measurement sequence
    and the winner broadcast.  ``n_measured == 0`` is the DEGENERATE miss
    (nothing admissible beyond the default): no measurement and no winner
    broadcast, a conclusion every rank reaches from the shared enumeration
    alone.  ``hit`` means the broadcast decision was APPLIED: an
    nsteps-incompatible hand-seeded winner (the `resolve_tuned_config`
    belt branch) follows the MISS-shaped schedule — the projection that
    demotes it is a pure function of the broadcast config and the shared
    ``nsteps``, never of rank-local state.  ``hit``/``n_measured`` come
    from the BROADCAST decision and the shared enumeration.
    """
    del is_root  # rank identity must not shape the schedule
    plan = [("broadcast_control", "cache-decision")]
    if not hit and n_measured > 0:
        plan += [("measure_candidate", i) for i in range(int(n_measured))]
        plan.append(("broadcast_control", "winner"))
    return tuple(plan)


# -- measurement --------------------------------------------------------------


def measure_candidate(build_step, make_state, *, steps: int | None = None):
    """Seconds per chunk call of one candidate: compile + warm once, then
    the median of ``steps`` timed calls (short by design — the tuner ranks
    configs; `benchmarks/run.py::_time_steps` owns publication-grade
    timing).  COLLECTIVE on multi-process grids: the compiled step is the
    production SPMD program."""
    import jax

    steps = _tune_steps() if steps is None else steps
    step = build_step()
    state = make_state()
    state = jax.block_until_ready(step(*state))  # compile + warmup
    times = []
    for _ in range(max(1, steps)):
        t0 = time.perf_counter()
        state = jax.block_until_ready(step(*state))
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def _measure_model(module, params, nsteps: int, batch: int, config: dict,
                   base_kwargs: dict | None = None, steps: int | None = None):
    """Measure one candidate through the model's own entry point
    (``autotune=False``: a candidate build must never recurse into the
    search) on a synthetic ones-filled state (`module._tune_state` — linear
    first steps, no NaN risk, correctly sharded global-block fields)."""
    kwargs = dict(base_kwargs or {})
    kwargs.update(config)

    def build_step():
        return module.make_multi_step(
            params, nsteps, donate=False, autotune=False,
            batch=bool(batch), **kwargs,
        )

    def make_state():
        state = module._tune_state(params)
        if batch:
            from ..models._batched import stack_states

            return stack_states([state] * int(batch))
        return state

    return measure_candidate(build_step, make_state, steps=steps)


# -- the resolve --------------------------------------------------------------


def _config_key(config: dict) -> str:
    return json.dumps(config, sort_keys=True)


def resolve_tuned_config(model: str, shape, dtype, *, nsteps: int,
                         batch: int = 0, gg=None, extra: dict | None = None,
                         cache: _cache.TuneCache | None = None,
                         measure=None, allow_search: bool = True) -> dict:
    """The tuned config for one point — cache hit, or search + persist.

    ``measure(config) -> seconds``: injected by `apply_tuned_config` (the
    models) and by tests (stubbed for determinism); must be rank-uniform in
    WHICH collectives it issues.  ``allow_search=False``: cache-only (a
    miss returns the default ``{}`` without measuring — the serving path's
    no-surprise mode).  Returns a dict of ``make_multi_step`` kwargs
    (possibly empty = the default config won or nothing was searched).
    """
    import jax

    if gg is None:
        from ..parallel.grid import global_grid

        gg = global_grid()
    key = _cache.make_key(model, shape, dtype, batch=batch, gg=gg,
                          extra=extra, nsteps=nsteps)
    cache = cache or _cache.TuneCache()
    multi = _telemetry.process_count() > 1
    is_root = jax.process_index() == 0 if multi else True

    with _tracing.trace_span("igg.tune", model=model,
                             size="x".join(str(s) for s in key["size"])):
        t0 = time.perf_counter()
        # -- phase 1: the cache decision (rank 0's alone, broadcast) ------
        entry = cache.lookup(key) if is_root else None
        if multi:
            from ..serving.frontdoor import broadcast_control

            decision = broadcast_control(
                {"tune": {"hit": entry is not None,
                          "config": entry["config"] if entry else None,
                          "source": entry["source"] if entry else None}}
                if is_root else None
            )["tune"]
        else:
            decision = {"hit": entry is not None,
                        "config": entry["config"] if entry else None,
                        "source": entry["source"] if entry else None}
        store_winner = True
        if decision["hit"]:
            config = dict(decision["config"])
            projected = project_config(model, config, nsteps)
            if projected == config:
                _telemetry.counter("tune.cache_hit").inc()
                _telemetry.event("tune.winner", model=model, config=config,
                                 source=decision["source"], cache="hit")
                return config
            # BELT: the key's schedule class makes a resolve-written winner
            # always nsteps-compatible with its hits, so this branch only
            # fires on a hand-written entry whose cadence does not fit.
            # Applying the projected remainder would silently under-tune,
            # so fall through to a fresh search — WITHOUT overwriting the
            # entry (never thrash a hand-seeded winner).  Deterministic on
            # every rank (the decision and nsteps are shared); the
            # schedule is the miss-shaped `control_plan` row.
            store_winner = False
            _telemetry.event("tune.hit_incompatible", model=model,
                             stored=config, nsteps=nsteps)
        _telemetry.counter("tune.cache_miss").inc()
        if cache.last_refusal and is_root:
            # a refused entry (corrupt/stale-schema) degrades to a miss —
            # say so once rather than silently re-searching forever
            _telemetry.event("tune.cache_refused", reason=cache.last_refusal)
        if not allow_search:
            return {}

        # -- phase 2: enumerate + prune (pure, rank-uniform) --------------
        import numpy as np

        itemsize = int(np.dtype(key["dtype"]).itemsize)
        npt = (extra or {}).get("npt")
        candidates, rejected = _space.candidate_space(
            model, key["size"], itemsize, nsteps=nsteps, gg=gg, npt=npt,
        )
        survivors, cut = _space.prune(candidates, _topk())
        _telemetry.counter("tune.candidates_pruned").inc(
            len(rejected) + len(cut)
        )
        if len(survivors) <= 1:
            # Degenerate point: nothing admissible beyond the default —
            # there is nothing to measure and an empty winner is not worth
            # an entry (and on a hand-keyed ``schedule`` mismatch it would
            # shadow a future admissible search).  No measurement, no
            # winner broadcast: every rank reaches this from the shared
            # enumeration alone (`control_plan(n_measured=0)`).
            _telemetry.event("tune.degenerate", model=model,
                             rejected=len(rejected))
            return {}

        # -- phase 3: measure the survivors TOGETHER ----------------------
        if measure is None:
            raise ValueError(
                f"tuning point {key['model']}/{key['size']} missed the "
                f"cache and no measure callable was provided — resolve "
                f"through the model's autotune= entry (or seed the cache)."
            )
        timed = []
        for cand in survivors:
            _telemetry.counter("tune.candidates_measured").inc()
            timed.append((measure(dict(cand["config"])), cand))

        # -- phase 4: rank 0 decides, everyone applies --------------------
        if multi:
            from ..serving.frontdoor import broadcast_control

            winner = broadcast_control(
                {"tune_winner": min(timed, key=lambda tc: tc[0])[1]["config"]}
                if is_root else None
            )["tune_winner"]
            t_by_cfg = {_config_key(c["config"]): t for t, c in timed}
            t_win = t_by_cfg.get(_config_key(winner))
        else:
            t_win, cand = min(timed, key=lambda tc: tc[0])
            winner = cand["config"]
        winner = dict(winner)
        elapsed = time.perf_counter() - t0
        _telemetry.counter("tune.search_seconds").inc(round(elapsed, 4))
        _telemetry.event("tune.winner", model=model, config=winner,
                         source="search", cache="miss",
                         search_seconds=round(elapsed, 3))
        if is_root and store_winner:
            modeled = next(
                (c["modeled"] for c in survivors
                 if _config_key(c["config"]) == _config_key(winner)), None,
            )
            cache.store(key, _cache.new_entry(
                key, winner, source="search", modeled=modeled,
                measured={"t_step_s": (t_win / nsteps)
                          if t_win is not None else None,
                          "teff_gbs": None, "steps": nsteps},
                tuner={"topk": _topk(), "candidates": len(candidates),
                       "pruned": len(rejected) + len(cut),
                       "measured": len(survivors)},
            ))
        return winner


# -- the model entry-point hook -----------------------------------------------

#: ``make_multi_step`` defaults per tunable kwarg: autotune substitutes a
#: field ONLY while the caller left it at this default (explicit kwargs
#: always win — the package's env-vs-kwarg precedence).
_KWARG_DEFAULTS = {"fused_k": None, "fused_tile": None, "exchange_every": 1,
                   "pipelined": None, "coalesce": None}


def autotune_requested(autotune) -> bool:
    """Kwarg > ``IGG_AUTOTUNE`` env > off (default) — resolved HOST-side,
    before any tracing (the knob-binding contract)."""
    if autotune is not None:
        return bool(autotune)
    from ..utils.config import autotune_env

    env = autotune_env()
    return False if env is None else env


def maybe_autotune(model: str, params, nsteps: int, autotune, *,
                   batch: bool = False, **kwargs) -> tuple:
    """The models' ONE-statement ``make_multi_step`` hook: resolve the five
    tunable kwargs through the winner cache when autotuning is requested
    (kwarg > ``IGG_AUTOTUNE`` > off), pass them through untouched otherwise.
    Returns ``(fused_k, fused_tile, exchange_every, pipelined, coalesce)``
    — one definition for the three models, so a new tunable field cannot
    be wired into one entry point and silently dropped from another.
    """
    if autotune_requested(autotune):
        kwargs = apply_tuned_config(
            model, _space.model_module(model), params, nsteps, dict(kwargs),
            batch=batch,
        )
    return tuple(kwargs[k] for k in _KWARG_DEFAULTS)


def apply_tuned_config(model: str, module, params, nsteps: int,
                       kwargs: dict, *, batch: bool = False) -> dict:
    """The ``make_multi_step`` hook: return ``kwargs`` with the tuned
    config substituted in, or unchanged.

    No substitution when the caller pinned ANY tunable kwarg away from its
    default — a half-tuned schedule is neither the caller's config nor the
    measured winner — and none on a ``hide_comm`` run: the overlap-scheduled
    per-step path conflicts with every cadence candidate by construction
    (the builders raise on the combination), so the tuner has nothing
    admissible to search there.  A cached winner whose cadence does not
    divide the live ``nsteps`` triggers a fresh (non-persisted) search
    inside the resolve; the projection below is pure belt — a resolve can
    only return nsteps-compatible configs.
    """
    explicit = [k for k, d in _KWARG_DEFAULTS.items() if kwargs.get(k) != d]
    if explicit:
        _telemetry.event("tune.skipped", model=model,
                         reason=f"explicit kwargs pin {explicit}")
        return kwargs
    if getattr(params, "hide_comm", False):
        _telemetry.event("tune.skipped", model=model,
                         reason="hide_comm schedules the per-step path; "
                                "the cadence candidates conflict with it")
        return kwargs
    from ..parallel.grid import global_grid

    gg = global_grid()
    extra = (
        {"npt": int(params.npt)} if model == "porous_convection3d" else None
    )
    config = resolve_tuned_config(
        model, gg.nxyz, params.dtype, nsteps=nsteps,
        batch=0 if not batch else 1, gg=gg, extra=extra,
        measure=lambda cfg: _measure_model(
            module, params, nsteps, 1 if batch else 0, cfg
        ),
    )
    config = project_config(model, config, nsteps)
    return {**kwargs, **config}


def project_config(model: str, config: dict, nsteps: int) -> dict:
    """Drop cached cadence fields the live ``nsteps`` cannot run (the
    porous cadence chunks ``npt``, not ``nsteps`` — exempt)."""
    out = dict(config)
    if model != "porous_convection3d":
        for field in ("fused_k", "exchange_every"):
            w = out.get(field)
            if isinstance(w, int) and w > 1 and nsteps % w != 0:
                _telemetry.counter("tune.config_projected").inc()
                out.pop(field)
                if field == "fused_k":
                    out.pop("fused_tile", None)
                    out.pop("pipelined", None)
    return out
