"""Autotuned kernel & schedule configs (ISSUE 13, docs/performance.md).

The reference's pitch is "as fast as the hardware allows" via per-device
tuned launch configs (its CUDA/ROCm backends pick kernel geometry per
architecture); this package's translation is a cost-model-pruned search
over the schedule kwargs the models already expose, with a versioned
on-disk winner table so the search runs ONCE per (backend, topology,
model, size, dtype, batch) point:

* `space` — candidate enumeration + the static prior (the PR-7 cost-model
  vocabulary: VMEM ladder via the kernel envelopes, modeled roofline
  bytes, collective counts);
* `cache` — the schema-checked atomic winner table (``IGG_TUNE_CACHE``
  primary layer + the committed chip-measured seed layer
  `cache.SEED_DIR`, ingested from ``BENCH_r*.json`` by ``igg_tune.py
  seed``);
* `search` — measurement, the SPMD-consistent rank-0-decides/broadcast
  resolve, and the ``make_multi_step`` hook behind ``autotune=`` /
  ``IGG_AUTOTUNE``.

CLI: ``scripts/igg_tune.py`` (sweep / show / seed / clear).  Tier-1 gate:
the ``tune-cache-valid`` analyzer (`analysis.tunecache`) over the
committed seed layer, and ``bench.py``'s gated ``tuned_vs_default`` extra.
"""

from .cache import (  # noqa: F401
    SCHEMA_VERSION,
    SEED_DIR,
    TuneCache,
    admissibility_error,
    default_cache_dir,
    entry_filename,
    key_digest,
    make_key,
    new_entry,
    schedule_class,
    seed_from_bench,
    topology_string,
    validate_entry,
)
from .search import (  # noqa: F401
    apply_tuned_config,
    autotune_requested,
    control_plan,
    measure_candidate,
    project_config,
    resolve_tuned_config,
)
from .space import (  # noqa: F401
    CONFIG_FIELDS,
    MODELS,
    candidate_space,
    modeled_cost,
    modeled_seconds,
    prune,
    tile_ladder,
)
