"""Candidate enumeration + the static cost-model prior of the autotuner.

The search space is every performance knob the model entry points already
expose as kwargs — ``fused_k`` (with tile ladder candidates from each
kernel module's `default_tile` neighborhood), ``exchange_every``,
``pipelined`` (where the ring/interior split is admissible), ``coalesce``
(multi-field cadences only) — enumerated at one ``(model, local size,
dtype, topology, batch)`` point.  A tuned config is therefore a PURE
SUBSTITUTION of existing kwargs: it changes the *schedule* of a run, never
its results (the bit-exactness contract `tests/test_tuning.py` pins on the
oracle matrix).  Model-config parameters that change numerics — the porous
``npt`` — are part of the cache KEY, never of the searched space.

The prior is the PR-7 static cost model's vocabulary applied per candidate
(`analysis.costmodel` gates the same quantities on the compiled matrix):

* **buffer peaks vs the VMEM ladder** — each kernel module's
  ``fused_support_error`` (backed by its ``_tile_bytes`` accounting and
  `ops._fused_envelope.vmem_budget`, the ``IGG_VMEM_MB`` ladder) rejects a
  candidate whose working set exceeds the per-core budget BEFORE it can
  reach measurement;
* **modeled ``bytes_accessed``** — the roofline HBM traffic per step
  (streamed fields, divided by the temporal-blocking depth, multiplied by
  the tile's halo-recompute redundancy) ranks the survivors;
* **collective count** — hops per step (amortized by the slab cadence,
  combined by coalescing) breaks ties with a nominal per-hop latency.

The nominal constants (`RANK_BW_BYTES_PER_S`, `RANK_HOP_SECONDS`) only
ORDER candidates — the measured short runs decide the winner — so their
absolute calibration is deliberately unimportant.
"""

from __future__ import annotations

import importlib

#: Nominal ranking constants (v5e-flavored; ordering-only, see module doc).
RANK_BW_BYTES_PER_S = 819e9
RANK_HOP_SECONDS = 1e-6

#: The only fields a tuned config may carry — each one an existing
#: ``make_multi_step`` kwarg on all three models (pure substitution).
CONFIG_FIELDS = ("fused_k", "fused_tile", "exchange_every", "pipelined",
                 "coalesce")

#: Per-model enumeration facts: the kernel module behind ``fused_k``, the
#: streamed-field census of the roofline model (fields read+written / read
#: only per unit step), whether the cadence exchanges >= 2 fields (the
#: ``coalesce`` knob is definitionally multi-field), and the tile-split
#: stagger of the pipelined gate.
MODELS = {
    "diffusion3d": dict(
        kernel="implicitglobalgrid_tpu.ops.pallas_stencil",
        module="implicitglobalgrid_tpu.models.diffusion3d",
        fields_rw=1, fields_ro=1, exchanged_fields=1, stagger=0,
    ),
    "acoustic3d": dict(
        kernel="implicitglobalgrid_tpu.ops.pallas_leapfrog",
        module="implicitglobalgrid_tpu.models.acoustic3d",
        fields_rw=4, fields_ro=0, exchanged_fields=4, stagger=1,
    ),
    "porous_convection3d": dict(
        kernel="implicitglobalgrid_tpu.ops.pallas_pt",
        module="implicitglobalgrid_tpu.models.porous_convection3d",
        fields_rw=4, fields_ro=1, exchanged_fields=4, stagger=1,
    ),
}

#: Temporal-blocking depths probed per point (the kernels' envelope admits
#: even k in [2, 8]; ``exchange_every`` reuses the shallow rungs).
K_LADDER = (2, 4, 6, 8)
EXCHANGE_LADDER = (2, 4)

#: Explicit tiles enumerated per k beyond the auto pick (`default_tile`):
#: the ladder is the module's own candidate neighborhood, deduplicated
#: against the auto pick, capped to keep the space measurable.
TILES_PER_K = 2


def kernel_module(model: str):
    return importlib.import_module(MODELS[model]["kernel"])


def model_module(model: str):
    return importlib.import_module(MODELS[model]["module"])


def _active_dims(gg, shape):
    from ..ops.halo import dim_has_halo_activity

    if gg is None:
        return ()
    return tuple(d for d in range(3) if dim_has_halo_activity(gg, d))


def _deep_halo_ok(w: int, gg, active) -> bool:
    return all(gg.overlaps[d] >= 2 * w for d in active)


def tile_ladder(model: str, shape, k: int, itemsize: int):
    """Explicit tile candidates around the kernel's auto pick: the module's
    own candidate neighborhood (``_candidates``/``_TILE_CANDIDATES``),
    admissibility-filtered, auto-pick deduplicated, first `TILES_PER_K`."""
    mod = kernel_module(model)
    auto = mod.default_tile(shape, k, itemsize)
    if hasattr(mod, "_candidates"):
        cands = mod._candidates(shape, k)
    else:
        cands = mod._TILE_CANDIDATES
    out = []
    for t in cands:
        if tuple(t) == auto or t in out:
            continue
        if mod.fused_support_error(shape, k, itemsize, t[0], t[1]) is None:
            out.append(tuple(t))
        if len(out) >= TILES_PER_K:
            break
    return auto, out


def modeled_cost(model: str, shape, itemsize: int, config: dict, *,
                 gg=None, npt: int | None = None) -> dict:
    """The static prior of one candidate: modeled HBM ``bytes_per_step``
    (roofline traffic, per time step — per PT iteration for porous, times
    ``npt``), the kernel working set ``vmem_bytes`` (0 for XLA-cadence
    candidates: XLA manages its own VMEM), and ``collectives_per_step``."""
    from ..ops._fused_envelope import aligned_halo

    facts = MODELS[model]
    n0, n1, n2 = shape
    vol = n0 * n1 * n2
    rw, ro = facts["fields_rw"], facts["fields_ro"]
    # npt scales the porous traffic linearly; it is constant across the
    # candidates of one point, so ranking survives an unknown (None) npt
    iters = (int(npt) if model == "porous_convection3d" and npt is not None
             else 1)
    k = config.get("fused_k")
    w = k or config.get("exchange_every", 1) or 1
    vmem = 0
    if k:
        mod = kernel_module(model)
        tile = config.get("fused_tile")
        if tile is None:
            tile = mod.default_tile(shape, k, itemsize)
        bx, by = tile
        H = 0 if by == n1 else aligned_halo(k)
        redundancy = ((bx + 2 * k) * (by + 2 * H)) / float(bx * by)
        # One haloed read + one owned write per field per k steps.
        bytes_per = (rw * (1 + redundancy) + ro * redundancy) * vol * itemsize / k
        vmem = int(mod._tile_bytes(n1, n2, k, bx, by, itemsize))
    else:
        bytes_per = (2 * rw + ro) * vol * itemsize
    active = _active_dims(gg, shape)
    if active:
        nf = facts["exchanged_fields"]
        per_exchange = 2 * len(active) * (
            nf if config.get("coalesce") is False or nf < 2 else 1
        )
        coll = per_exchange / float(w)
    else:
        coll = 0.0
    return {
        "bytes_per_step": round(bytes_per * iters, 2),
        "vmem_bytes": vmem,
        "collectives_per_step": round(coll * iters, 4),
    }


def modeled_seconds(modeled: dict) -> float:
    """The ranking proxy (ordering-only, see module doc)."""
    return (
        modeled["bytes_per_step"] / RANK_BW_BYTES_PER_S
        + modeled["collectives_per_step"] * RANK_HOP_SECONDS
    )


def candidate_space(model: str, shape, itemsize: int, *, nsteps: int,
                    gg=None, npt: int | None = None):
    """``(candidates, rejected)`` for one tuning point, deterministic order.

    ``candidates``: admissible ``{"config", "modeled"}`` dicts, the default
    (empty) config always FIRST — it is always measured, so the winner can
    never be worse than what the caller would have run untuned.
    ``rejected``: configs the prior refused with the reason (VMEM ladder,
    divisibility, deep-halo precondition) — the dry-run table's left half
    and the ``tune.candidates_pruned`` census.
    """
    if model not in MODELS:
        raise ValueError(f"unknown model {model!r}; tunable: {sorted(MODELS)}")
    shape = tuple(int(x) for x in shape)
    facts = MODELS[model]
    active = _active_dims(gg, shape)
    porous = model == "porous_convection3d"
    kmod = kernel_module(model)

    bases: list[dict] = [{}]
    rejected: list[dict] = []

    def _steps_ok(w: int) -> str | None:
        if porous:
            # the PT cadence chunks npt, not nsteps (`_pt_schedule`)
            if npt is not None and w > int(npt):
                return f"w={w} exceeds npt={npt}: no PT chunk to amortize"
            return None
        if nsteps % w != 0:
            return f"nsteps={nsteps} is not a multiple of {w}"
        return None

    # -- exchange_every rungs (slab cadence without the kernel) -----------
    for w in EXCHANGE_LADDER:
        cfg = {"exchange_every": w}
        if not active:
            rejected.append({"config": cfg, "error": "no halo activity: "
                             "nothing to amortize"})
            continue
        err = _steps_ok(w)
        if err is None and not _deep_halo_ok(w, gg, active):
            err = f"deep-halo precondition overlap >= {2 * w} not met"
        if err:
            rejected.append({"config": cfg, "error": err})
            continue
        bases.append(cfg)

    # -- fused_k x tile ladder x pipelined --------------------------------
    for k in K_LADDER:
        err = _steps_ok(k)
        if err is None and porous and npt is not None:
            from ..models.porous_convection3d import _pt_schedule

            if not _pt_schedule(int(npt), k)[1]:
                err = f"npt={npt} leaves no even kernel chunk at w={k}"
        if err is None and active and not _deep_halo_ok(k, gg, active):
            err = f"deep-halo precondition overlap >= {2 * k} not met"
        if err is None:
            # the envelope gate: VMEM ladder (IGG_VMEM_MB), alignment,
            # divisibility — the same check the model's fallback uses
            err = kmod.fused_support_error(shape, k, itemsize, None, None)
        if err:
            rejected.append({"config": {"fused_k": k}, "error": err})
            continue
        auto, tiles = tile_ladder(model, shape, k, itemsize)
        for tile in [None] + tiles:
            cfg = {"fused_k": k}
            if tile is not None:
                cfg["fused_tile"] = tile
            bx, by = tile if tile is not None else (None, None)
            split_err = _split_error(model, shape, k, itemsize, bx, by, gg,
                                     npt=npt)
            if split_err is None:
                bases.append({**cfg, "pipelined": False})
                bases.append({**cfg, "pipelined": True})
            else:
                bases.append(cfg)

    # -- coalesce twins (multi-field cadences on communicating grids) -----
    out = list(bases)
    if facts["exchanged_fields"] >= 2 and active:
        out += [{**cfg, "coalesce": False} for cfg in bases]

    candidates = [
        {"config": cfg,
         "modeled": modeled_cost(model, shape, itemsize, cfg, gg=gg, npt=npt)}
        for cfg in out
    ]
    return candidates, rejected


def _split_error(model, shape, k, itemsize, bx, by, gg, npt=None):
    """Why the ring/interior pipelined split cannot run, or None — the
    model's own gate (`models.*.pipelined_support_error`)."""
    mod = model_module(model)
    kw = {"npt": npt} if model == "porous_convection3d" else {}
    try:
        return mod.pipelined_support_error(shape, k, itemsize, bx, by,
                                           gg=gg, **kw)
    except Exception as e:  # a gate crash must reject, not sink the sweep
        return f"{type(e).__name__}: {e}"


def prune(candidates, topk: int, *, vmem_budget_bytes: int | None = None):
    """Cost-model pruning: ``(survivors, cut)``.

    The default config (index 0) ALWAYS survives; the rest rank by
    `modeled_seconds` and the best ``topk - 1`` join it.  An explicit
    ``vmem_budget_bytes`` additionally refuses candidates whose modeled
    working set exceeds it — the enumeration's envelope gate already
    enforces the ``IGG_VMEM_MB`` ladder, this parameter lets callers (and
    the pruning-correctness test) tighten it on injected candidates.
    ``cut`` lists the refused candidates with reasons: a candidate over the
    VMEM ladder must NEVER reach measurement.
    """
    if not candidates:
        return [], []
    if topk < 1:
        raise ValueError(f"topk must be >= 1 (got {topk})")
    default, rest = candidates[0], candidates[1:]
    cut = []
    kept = []
    for c in rest:
        if (
            vmem_budget_bytes is not None
            and c["modeled"].get("vmem_bytes", 0) > vmem_budget_bytes
        ):
            cut.append({**c, "error": (
                f"modeled VMEM {c['modeled']['vmem_bytes']} B exceeds the "
                f"budget {vmem_budget_bytes} B")})
        else:
            kept.append(c)
    ranked = sorted(kept, key=lambda c: modeled_seconds(c["modeled"]))
    survivors = [default] + ranked[: max(0, topk - 1)]
    cut += [{**c, "error": "ranked below topk by the modeled prior"}
            for c in ranked[max(0, topk - 1):]]
    return survivors, cut
