"""Batched (ensemble) execution helpers — B simulations for the price of 1.

The north star is millions of users each running a small-to-medium
*independent* simulation; per-chip throughput for that workload comes from
batching B members into one program.  The mechanism is deliberately thin:
every model's per-block step is a pure function of local-block fields, so a
leading ensemble axis is just ``jax.vmap`` over it — and the collective
structure is *provably* invariant in B, because the batching rule of
`lax.ppermute` carries the batch dimension inside the SAME collective (one
fatter hop, not B hops).  The coalesced multi-field packer (`ops.halo`)
composes with this for free: under vmap its flatten/concatenate operate on
the per-member view, so the packed buffer simply grows a batch axis and the
one-permute-pair-per-(dimension, width group) budget holds at any B.  The
``collective-budget`` analyzer pins this as a static invariant
(`analysis.budget.batched_budget_findings`), and the compiled-HLO census
cross-checks it (``bench.py batch``).

Layout: a batched field is ``(B, *local_block)`` per device — global shape
``(B, dims[0]*nx, dims[1]*ny, dims[2]*nz)`` sharded ``P(None, 'x', 'y',
'z')`` (the ensemble axis is replicated-rank: every device holds all B
members of ITS block).  Members are independent problems on the SAME grid
topology; per-member physics parameters stay per-member fields (each member
carries its own Cp/state), scalar `Params` are shared.

Bit-exactness contract: a batched step is bit-identical, member for member,
to B independent unbatched steps (vmap of pure array code plus the batched
collectives moves exactly the per-member values; pinned across the oracle
matrix in ``tests/test_batched_serving.py`` and across a real 2-process
boundary in ``tests/_distributed_worker.py``).
"""

from __future__ import annotations

import numpy as np

from ..parallel import grid as _grid
from ..parallel.topology import AXIS_NAMES

_jit_cache: dict = {}


def _clear_caches() -> None:
    _jit_cache.clear()


def batch_size(state) -> int:
    """The ensemble size B of a batched state tuple (leading-axis extent)."""
    leaf = state[0] if isinstance(state, (tuple, list)) else state
    return int(np.shape(leaf)[0])


def _batched_spec(ndim: int):
    """PartitionSpec of one batched field: replicated ensemble axis, block-
    sharded grid axes (``P(None, 'x', 'y', 'z')`` for the usual 1+3 rank)."""
    from jax.sharding import PartitionSpec as P

    return P(None, *AXIS_NAMES[: ndim - 1])


def batched_stencil(block_step, nfields: int, *, donate_argnums=()):
    """`igg.stencil` for a vmapped per-block step over ``nfields`` batched
    fields.

    The single-member ``block_step`` is vmapped over the leading ensemble
    axis and wrapped with EXPLICIT specs (`_batched_spec`): the stencil
    heuristic maps array axis ``d`` to grid axis ``d`` and would shard the
    ensemble axis over ``'x'``.  Donation semantics match the unbatched
    wrapper.
    """
    import jax

    from ..ops.stencil import stencil

    specs = (_batched_spec(4),) * nfields
    return stencil(
        jax.vmap(block_step),
        in_specs=specs,
        out_specs=specs,
        donate_argnums=donate_argnums,
    )


def _stack_fn(gg, ndims: tuple[int, ...]):
    """Jitted shard_map stacking per-member global-block fields into one
    batched field — local per-device stacking, no host transfer (multi-host
    safe: each process stacks only its own shards)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..utils.compat import shard_map

    key = ("stack", gg.epoch, tuple(ndims))
    fn = _jit_cache.get(key)
    if fn is not None:
        return fn
    if gg.nprocs == 1 and not gg.force_spmd:
        fn = jax.jit(lambda *fs: jnp.stack(fs))
        _jit_cache[key] = fn
        return fn
    nd = ndims[0]
    mapped = shard_map(
        lambda *fs: jnp.stack(fs),
        mesh=gg.mesh,
        in_specs=(P(*AXIS_NAMES[:nd]),) * len(ndims),
        out_specs=_batched_spec(nd + 1),
        check_vma=False,
    )
    fn = jax.jit(mapped)
    _jit_cache[key] = fn
    return fn


def stack_fields(*fields):
    """Stack B same-shaped global-block fields into one batched field
    ``(B, ...)`` (device-side; the inverse of `member_field`)."""
    _grid.check_initialized()
    gg = _grid.global_grid()
    if not fields:
        raise ValueError("stack_fields requires at least one field.")
    ndims = tuple(np.ndim(f) for f in fields)
    if len(set(ndims)) != 1:
        raise ValueError(f"stack_fields: mixed ranks {ndims}")
    return _stack_fn(gg, ndims)(*fields)


def stack_states(states):
    """Stack B state tuples (one per member) into one batched state tuple."""
    states = [tuple(s) for s in states]
    nf = len(states[0])
    if any(len(s) != nf for s in states):
        raise ValueError("stack_states: members have different field counts")
    return tuple(
        stack_fields(*(s[i] for s in states)) for i in range(nf)
    )


def _member_fn(gg, ndim: int):
    """Jitted shard_map slicing member ``k`` out of a batched field.  ``k``
    is a traced operand, so every member shares one executable."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..utils.compat import shard_map

    key = ("member", gg.epoch, ndim)
    fn = _jit_cache.get(key)
    if fn is not None:
        return fn

    def take(A, k):
        return lax.dynamic_index_in_dim(A, k, 0, keepdims=False)

    if gg.nprocs == 1 and not gg.force_spmd:
        fn = jax.jit(take)
        _jit_cache[key] = fn
        return fn
    mapped = shard_map(
        take,
        mesh=gg.mesh,
        in_specs=(_batched_spec(ndim), P()),
        out_specs=P(*AXIS_NAMES[: ndim - 1]),
        check_vma=False,
    )
    fn = jax.jit(mapped)
    _jit_cache[key] = fn
    return fn


def member_field(A, k: int):
    """Member ``k``'s global-block field out of a batched field — a device
    slice, never materializing the other members anywhere new."""
    import jax.numpy as jnp

    _grid.check_initialized()
    gg = _grid.global_grid()
    if np.ndim(A) < 2:
        raise ValueError(f"member_field needs a batched field, got rank {np.ndim(A)}")
    return _member_fn(gg, np.ndim(A))(A, jnp.int32(k))


def member_state(state, k: int):
    """Member ``k``'s state tuple out of a batched state tuple."""
    return tuple(member_field(A, k) for A in state)


def _set_member_fn(gg, ndim: int):
    """Jitted shard_map writing one member's fields into a batched field at
    slot ``k`` (the serving loop's admit/rollback primitive)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..utils.compat import shard_map

    key = ("set_member", gg.epoch, ndim)
    fn = _jit_cache.get(key)
    if fn is not None:
        return fn

    def put(B, A, k):
        return lax.dynamic_update_index_in_dim(B, A.astype(B.dtype), k, 0)

    if gg.nprocs == 1 and not gg.force_spmd:
        fn = jax.jit(put, donate_argnums=(0,))
        _jit_cache[key] = fn
        return fn
    mapped = shard_map(
        put,
        mesh=gg.mesh,
        in_specs=(_batched_spec(ndim), P(*AXIS_NAMES[: ndim - 1]), P()),
        out_specs=_batched_spec(ndim),
        check_vma=False,
    )
    fn = jax.jit(mapped, donate_argnums=(0,))
    _jit_cache[key] = fn
    return fn


def set_member_state(batched, state, k: int):
    """Write single-member ``state`` into slot ``k`` of ``batched`` (donating
    the old batched buffers — the slot pool's in-place admit)."""
    import jax.numpy as jnp

    _grid.check_initialized()
    gg = _grid.global_grid()
    kk = jnp.int32(k)
    return tuple(
        _set_member_fn(gg, np.ndim(B))(B, A, kk)
        for B, A in zip(batched, state)
    )


def _member_finite_fn(gg, sig):
    """Jitted per-member finite probe over a batched state: one ``(B,)``
    int32 flag vector, 1 where the member holds any non-finite value in any
    field — replicated across devices/processes (psum over the mesh), so
    every rank takes the same serving decision for member k and only member
    k (the batched sibling of `utils.resilience.check_fields`)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..utils.compat import shard_map

    key = ("finite", gg.epoch, sig)
    fn = _jit_cache.get(key)
    if fn is not None:
        return fn

    def flags(*fields):
        bad = None
        for A in fields:
            if jnp.issubdtype(A.dtype, jnp.inexact):
                f = jnp.any(
                    ~jnp.isfinite(A), axis=tuple(range(1, A.ndim))
                ).astype(jnp.int32)
            else:
                f = jnp.zeros((A.shape[0],), jnp.int32)
            bad = f if bad is None else jnp.maximum(bad, f)
        return bad

    if gg.nprocs == 1 and not gg.force_spmd:
        fn = jax.jit(flags)
        _jit_cache[key] = fn
        return fn

    def per_block(*fields):
        return lax.psum(flags(*fields), AXIS_NAMES)

    mapped = shard_map(
        per_block,
        mesh=gg.mesh,
        in_specs=tuple(_batched_spec(len(s) + 1) for s, _ in sig),
        out_specs=P(),
        check_vma=False,
    )
    fn = jax.jit(mapped)
    _jit_cache[key] = fn
    return fn


def _batched_local_shape(A, gg) -> tuple[int, ...]:
    """Per-block shape of a batched field's GRID axes (the leading ensemble
    axis is replicated, never divided by the mesh; `ops.halo.local_shape`
    only knows grid-rank fields)."""
    shp = np.shape(A)
    out = []
    for d, s in enumerate(shp[1:]):
        nd = gg.dims[d] if d < len(gg.dims) else 1
        q, m = divmod(s, nd)
        if m != 0:
            raise ValueError(
                f"batched field with global shape {tuple(shp)} is not "
                f"divisible into {gg.dims} blocks along grid dimension {d}."
            )
        out.append(q)
    return tuple(out)


def check_members_finite(state) -> np.ndarray:
    """Per-member NaN/Inf probe of a batched state: boolean ``(B,)`` array,
    True where the member is bad.  One compiled all-reduce — member k's
    fault never taints the verdict on member j."""
    _grid.check_initialized()
    gg = _grid.global_grid()
    sig = tuple(
        (_batched_local_shape(A, gg), str(A.dtype)) for A in state
    )
    flags = np.asarray(_member_finite_fn(gg, sig)(*state))
    return flags > 0


def _select_fn(gg, sig):
    """Jitted per-member select: ``where(mask[b], new[b], old[b])`` per
    field — the serving loop's convergence/idle masking (a masked member's
    state is BIT-frozen, not merely numerically close).  Donates both state
    tuples (the loop keeps only the result)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..utils.compat import shard_map

    key = ("select", gg.epoch, sig)
    fn = _jit_cache.get(key)
    if fn is not None:
        return fn
    n = len(sig)

    def sel(mask, *fields):
        news, olds = fields[:n], fields[n:]
        return tuple(
            jnp.where(mask.reshape((-1,) + (1,) * (N.ndim - 1)), N, O)
            for N, O in zip(news, olds)
        )

    dn = tuple(range(1, 2 * n + 1))
    if gg.nprocs == 1 and not gg.force_spmd:
        fn = jax.jit(sel, donate_argnums=dn)
        _jit_cache[key] = fn
        return fn
    specs = tuple(_batched_spec(len(s) + 1) for s, _ in sig)
    mapped = shard_map(
        sel,
        mesh=gg.mesh,
        in_specs=(P(),) + specs + specs,
        out_specs=specs,
        check_vma=False,
    )
    fn = jax.jit(mapped, donate_argnums=dn)
    _jit_cache[key] = fn
    return fn


def select_members(mask, new_state, old_state):
    """Per-member select over batched state tuples: member ``b`` takes
    ``new_state`` where ``mask[b]`` is True, else keeps ``old_state``
    bit-for-bit.  ``mask`` is a length-B boolean array (host or device).
    Donates both inputs."""
    import jax.numpy as jnp

    _grid.check_initialized()
    gg = _grid.global_grid()
    sig = tuple(
        (_batched_local_shape(A, gg), str(A.dtype)) for A in new_state
    )
    m = jnp.asarray(np.asarray(mask), jnp.bool_)
    return _select_fn(gg, sig)(m, *new_state, *old_state)


def batched_setup(model, nx: int, ny: int, nz: int, *, batch: int,
                  ic_scales=None, init_grid: bool = True, **kw):
    """Grid + B-member batched initial state for one model module.

    ``model`` is one of `models.diffusion3d` / `acoustic3d` /
    `porous_convection3d` (any module with ``setup(..., ic_scale=...)``).
    Member ``b`` gets the model's standard initial condition with its
    perturbation scaled by ``ic_scales[b]`` (default ``1 + b/(8*batch)`` —
    distinct members, same smooth physics), so a batched run is directly
    comparable to B independent runs of ``setup(..., ic_scale=s_b)``.
    Returns ``(batched_state, params)``; `Params` are shared (same grid,
    same dt) by construction.
    """
    if batch < 1:
        raise ValueError(f"batch must be >= 1 (got {batch})")
    if ic_scales is None:
        ic_scales = [1.0 + b / (8.0 * batch) for b in range(batch)]
    if len(ic_scales) != batch:
        raise ValueError(
            f"ic_scales has {len(ic_scales)} entries for batch={batch}"
        )
    states = []
    params = None
    for b, scale in enumerate(ic_scales):
        state, params = model.setup(
            nx, ny, nz,
            ic_scale=float(scale),
            init_grid=(init_grid and b == 0),
            **kw,
        )
        states.append(state)
    return stack_states(states), params
