"""3-D heat diffusion — the flagship model (reference `examples/diffusion3D_*.jl`).

The reference's headline application: heat diffusion with spatially variable
heat capacity and two Gaussian anomalies, solved with a conservative
finite-difference stencil on the implicit global grid
(`/root/reference/examples/diffusion3D_multigpu_CuArrays_novis.jl:11-50`).
The reference allocates explicit flux arrays (``qx, qy, qz, dTedt``) and runs
five broadcast kernels plus `update_halo!` per step; here the whole time step
is ONE fused XLA program per block — fluxes never hit HBM, and the halo
exchange (`collective_permute`) is scheduled by XLA inside the same program.
With ``hide_comm=True`` the boundary slabs are computed first so the exchange
overlaps the interior update (the `@hide_communication` capability,
reference `README.md:10`).

Physics (reference lines :41-46):

    q      = -lam * grad(T)              (Fourier's law, on the staggered flux grid)
    dT/dt  = -(1/Cp) * div(q)            (conservation of energy)
    T     += dt * dT/dt                  (explicit Euler, interior points only)

Usage::

    import implicitglobalgrid_tpu.models.diffusion3d as m
    state, params = m.setup(nx=128, ny=128, nz=128)
    step = m.make_step(params)
    for _ in range(nt):
        state = step(state)
    T = m.temperature(state)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import numpy as np

from .. import (
    coord_fields,
    finalize_global_grid,
    init_global_grid,
    nx_g,
    ny_g,
    nz_g,
    stencil,
    update_halo,
    zeros,
)
from ..ops.overlap import hide_communication


@dataclasses.dataclass(frozen=True)
class Params:
    """Physics + numerics of the run (reference lines :13-23,:39)."""

    lam: float = 1.0  # thermal conductivity
    cp_min: float = 1.0  # minimal heat capacity
    lx: float = 10.0
    ly: float = 10.0
    lz: float = 10.0
    dx: float = 0.0
    dy: float = 0.0
    dz: float = 0.0
    dt: float = 0.0
    dtype: Any = None
    hide_comm: bool = False


def _inn(A):
    return A[1:-1, 1:-1, 1:-1]


from ._fused import warn_fused_fallback as _warn_fused_fallback  # shared w/ acoustic


def _gaussians(X, Y, Z, params: Params, jnp):
    """The reference's two pairs of Gaussian anomalies (lines :34-37)."""
    lx, ly, lz = params.lx, params.ly, params.lz
    cp = params.cp_min + (
        5 * jnp.exp(-((X - lx / 1.5) ** 2) - (Y - ly / 2) ** 2 - (Z - lz / 1.5) ** 2)
        + 5 * jnp.exp(-((X - lx / 3.0) ** 2) - (Y - ly / 2) ** 2 - (Z - lz / 1.5) ** 2)
    )
    t = 100 * jnp.exp(
        -(((X - lx / 2) / 2) ** 2) - ((Y - ly / 2) / 2) ** 2 - ((Z - lz / 3.0) / 2) ** 2
    ) + 50 * jnp.exp(
        -(((X - lx / 2) / 2) ** 2) - ((Y - ly / 2) / 2) ** 2 - ((Z - lz / 1.5) / 2) ** 2
    )
    return cp, t


def setup(
    nx: int = 128,
    ny: int = 128,
    nz: int = 128,
    *,
    lam: float = 1.0,
    cp_min: float = 1.0,
    lx: float = 10.0,
    ly: float = 10.0,
    lz: float = 10.0,
    dtype=None,
    hide_comm: bool = False,
    init_grid: bool = True,
    ic_scale: float = 1.0,
    **grid_kwargs,
):
    """Initialize the global grid (unless ``init_grid=False``) and the fields.

    Returns ``(state, params)`` where ``state = (T, Cp)`` are global-block
    fields with the reference's initial conditions (lines :34-37).
    ``ic_scale`` scales the initial temperature anomaly — the ensemble
    lever: `models._batched.batched_setup` gives each member a distinct
    scale so batched members are distinct problems on one grid.
    """
    import jax
    import jax.numpy as jnp

    if init_grid:
        init_global_grid(nx, ny, nz, **grid_kwargs)
    if dtype is None:
        dtype = jax.dtypes.canonicalize_dtype(float)
    dx = lx / (nx_g() - 1)  # reference line :21-23
    dy = ly / (ny_g() - 1)
    dz = lz / (nz_g() - 1)
    dt = min(dx * dx, dy * dy, dz * dz) * cp_min / lam / 8.1  # reference line :39
    params = Params(
        lam=lam, cp_min=cp_min, lx=lx, ly=ly, lz=lz,
        dx=dx, dy=dy, dz=dz, dt=dt, dtype=dtype, hide_comm=hide_comm,
    )
    T = zeros((nx, ny, nz), dtype)
    X, Y, Z = coord_fields(T, (dx, dy, dz), dtype=dtype)

    @stencil
    def init_ic(X, Y, Z):
        cp, t = _gaussians(X, Y, Z, params, jnp)
        return cp.astype(dtype), (ic_scale * t).astype(dtype)

    Cp, T = init_ic(X, Y, Z)
    return (T, Cp), params


def _diffusion_update(params: Params):
    """Per-block, pure T update (no exchange): the reference's five broadcast
    kernels (lines :41-45) fused into one expression.

    Formulation chosen by measurement on TPU: with scalar conductivity the
    flux divergence is the Laplacian, computed from interior slices and added
    back as ``T + pad(delta, 1)`` — ~1.5x faster than the literal
    flux-arrays + scatter-update translation (`.at[1:-1,...].set` lowers to an
    unaligned dynamic-update-slice against the (8,128)-tiled layout, and the
    intermediate flux arrays cost extra HBM passes).  The padded-delta form
    also freezes the outermost ring (width 1 = stencil radius), exactly the
    reference's boundary behavior.
    """
    import jax.numpy as jnp

    lam, dt = params.lam, params.dt
    dx, dy, dz = params.dx, params.dy, params.dz

    def update(T, Cp):
        lap = (
            (T[2:, 1:-1, 1:-1] - 2 * _inn(T) + T[:-2, 1:-1, 1:-1]) / (dx * dx)
            + (T[1:-1, 2:, 1:-1] - 2 * _inn(T) + T[1:-1, :-2, 1:-1]) / (dy * dy)
            + (T[1:-1, 1:-1, 2:] - 2 * _inn(T) + T[1:-1, 1:-1, :-2]) / (dz * dz)
        )
        delta = (dt * lam) / _inn(Cp) * lap
        return T + jnp.pad(delta, 1)

    return update


def make_step(params: Params, *, donate: bool = True, batch: bool = False):
    """Build the jitted SPMD time step: ``(T, Cp) -> (T, Cp)``.

    One call = one fused XLA program: stencil update + halo exchange
    (+ overlap scheduling when ``params.hide_comm``).

    ``batch=True``: the ensemble step over ``(B, nx, ny, nz)`` batched
    fields (`models._batched`) — `jax.vmap` of the same per-block step, so
    B members advance bit-identically to B independent calls while every
    exchanged dimension still issues ONE collective pair (the ppermute
    batching rule carries the ensemble axis inside the same hop).
    """
    update = _diffusion_update(params)

    if params.hide_comm:
        overlapped = hide_communication(update, radius=1)

        def block_step(T, Cp):
            return overlapped(T, Cp), Cp

    else:

        def block_step(T, Cp):
            T = update(T, Cp)
            T = update_halo(T)
            return T, Cp

    if batch:
        from ._batched import batched_stencil

        return batched_stencil(
            block_step, 2, donate_argnums=(0,) if donate else ()
        )
    return stencil(block_step, donate_argnums=(0,) if donate else ())


def pipelined_support_error(shape, k, itemsize: int = 4, bx=None, by=None,
                            gg=None) -> str | None:
    """Why the pipelined group schedule cannot split this config, or None.

    The same decision the ``pipelined`` knob's auto mode makes at trace
    time (`models._fused.pipelined_support_error` over the diffusion
    kernel's envelope) — exported for benchmark provenance.
    """
    from ..ops import pallas_stencil
    from ._fused import pipelined_support_error as _generic

    return _generic(pallas_stencil, shape, k, itemsize, bx, by, gg, stagger=0)


def _tune_state(params: Params):
    """Synthetic ones-filled state for autotuner candidate measurement
    (`tuning.search`): the first steps are linear on ones (lap(1) = 0 — no
    NaN risk) and the fields are real global-block sharded arrays, so a
    measured candidate runs the production SPMD program."""
    from .. import ones
    from ..parallel.grid import global_grid

    shape = tuple(global_grid().nxyz)
    return ones(shape, params.dtype), ones(shape, params.dtype)


def make_multi_step(
    params: Params,
    nsteps: int,
    *,
    donate: bool = True,
    fused_k: int | None = None,
    fused_tile: tuple[int, int] | None = None,
    exchange_every: int = 1,
    pipelined: bool | None = None,
    batch: bool = False,
    coalesce: bool | None = None,
    autotune: bool | None = None,
):
    """Like `make_step` but advances ``nsteps`` steps per call via `lax.fori_loop`.

    TPU-first: the whole loop is one XLA program, so per-call dispatch
    overhead amortizes away and the compiler schedules across iterations —
    use this for production runs and benchmarks.

    ``exchange_every=w`` (XLA path): on a deep-halo grid (``overlap >= 2w``
    in every dimension with halo activity) run ``w`` stencil steps between
    halo exchanges and exchange a width-``w`` slab — one collective per
    ``w`` steps, results at group boundaries identical up to compiler
    fusion rounding (bitwise on the CPU mesh; few f32 ULPs on TPU, where
    differently-fused programs contract FMAs differently) — the w-deep
    stale rind each block accumulates is exactly the slab the exchange
    replaces with the neighbor's still-exact planes.  The latency-amortization half
    of the deep-halo story without the Pallas kernel; combine with
    ``fused_k=w`` to also amortize HBM traffic.

    ``fused_k``: advance ``fused_k`` steps per HBM pass with the
    temporally-blocked Pallas kernel (`ops/pallas_stencil.py`) — the analogue
    of the reference's custom-kernel-when-generic-is-slow move
    (`/root/reference/src/update_halo.jl:430`), here lifting T_eff past the
    streaming bound.  On a grid with no halo activity (single block,
    non-periodic) the kernel runs alone.  On a communicating grid the block
    needs a **deep halo**: every dimension with halo activity must have
    ``overlap >= 2*fused_k`` (``init_global_grid(..., overlapx=2*k, ...)``);
    the chunk then alternates ``fused_k`` kernel steps with ONE slab
    exchange (`update_halo(T, width=fused_k)`) — k steps per HBM pass *and*
    per collective, so both the memory and the latency cost amortize.
    Requires ``nsteps % fused_k == 0`` and TPU-compatible shapes (see
    `fused_diffusion_steps`).

    ``pipelined`` (default auto): run the fused groups on the
    boundary-first pipelined schedule
    (`models._fused.run_pipelined_group_schedule`) — each group's kernel
    launch splits into a ring pass that feeds the slab exchange early and
    an interior pass XLA schedules across the in-flight
    `collective-permute`s.  Bit-identical to the serialized schedule
    (`pipelined=False`); auto turns it on whenever the grid communicates
    in x/y and the tile split is admissible
    (`pipelined_support_error`).  ``pipelined=True`` also applies the
    early-dispatch exchange shape to the XLA cadences (the fused fallback
    and ``exchange_every``).

    ``batch``: vmap the whole cadence over a leading ensemble axis (see
    `make_step`).  Every path — fused Pallas chunks included (the
    `pallas_call` batching rule adds the ensemble as an outer grid
    dimension), slab exchanges, pipelined begin/finish — batches through
    the same vmap, and the per-(dimension, width group) collective budget
    is B-invariant (pinned by `analysis.budget.batched_budget_findings`).

    ``coalesce`` (None = the ``IGG_COALESCE`` env default, auto): the
    cadence's multi-field exchanges pass it through to `ops.halo`
    (bit-identical either way; the diffusion cadence exchanges a single
    field except on the z-patch path, so the knob mostly matters to the
    acoustic/porous siblings — it exists here so a tuned config is one
    vocabulary across the three models).

    ``autotune`` (None = ``IGG_AUTOTUNE`` env, default off): substitute the
    cached winner config of this (backend, topology, model, local size,
    dtype, batch) point into the kwargs above — searching (cost-model
    pruned, short measured runs) and persisting it on first use
    (`implicitglobalgrid_tpu.tuning`, docs/performance.md).  A pure
    schedule substitution: results stay bit-identical to the default
    config.  Explicitly-set kwargs always win — autotune only fills fields
    left at their defaults.
    """
    from jax import lax

    from ..tuning.search import maybe_autotune

    fused_k, fused_tile, exchange_every, pipelined, coalesce = maybe_autotune(
        "diffusion3d", params, nsteps, autotune, batch=batch,
        fused_k=fused_k, fused_tile=fused_tile, exchange_every=exchange_every,
        pipelined=pipelined, coalesce=coalesce,
    )

    def _wrap(block_fn):
        dn = (0,) if donate else ()
        if batch:
            from ._batched import batched_stencil

            return batched_stencil(block_fn, 2, donate_argnums=dn)
        return stencil(block_fn, donate_argnums=dn)

    if fused_k:
        from ..parallel.grid import global_grid
        from ..ops.pallas_stencil import fused_diffusion_steps, fused_support_error

        gg = global_grid()
        if params.hide_comm:
            raise ValueError(
                "fused_k and hide_comm are mutually exclusive: the fused "
                "kernel's slab exchange is already amortized over k steps; "
                "overlap scheduling applies to the per-step XLA path."
            )
        if nsteps % fused_k != 0:
            raise ValueError(f"nsteps={nsteps} must be a multiple of fused_k={fused_k}")
        if exchange_every not in (1, fused_k):
            raise ValueError(
                f"fused_k={fused_k} already exchanges every fused_k steps; "
                f"exchange_every={exchange_every} conflicts."
            )
        import jax

        from ..ops.halo import require_deep_halo

        require_deep_halo(fused_k, gg, what="fused_k")
        from ..ops.halo import dim_has_halo_activity

        active = [d for d in range(3) if dim_has_halo_activity(gg, d)]
        update = _diffusion_update(params)
        cx = params.dt * params.lam / (params.dx * params.dx)
        cy = params.dt * params.lam / (params.dy * params.dy)
        cz = params.dt * params.lam / (params.dz * params.dz)
        bx, by = fused_tile if fused_tile is not None else (None, None)
        if (bx is None) != (by is None):
            # A half-specified tile is a caller error, not a shape the kernel
            # cannot run — raise eagerly rather than warn-and-fall-back.
            raise ValueError(f"fused_tile={fused_tile}: pass both bx and by, or neither")

        z_active = dim_has_halo_activity(gg, 2)

        # Shapes are only known at trace time, so the kernel-vs-fallback
        # choice happens there: a local block the kernel's envelope rejects
        # warns once and runs the XLA path at the SAME exchange cadence
        # (w steps per width-w slab exchange — the deep halo is already
        # validated above), the reference's runtime-path-selection move
        # (`/root/reference/src/update_halo.jl:755-784`).
        from ._fused import fused_with_xla_grad, resolve_pipelined, split_selector

        active01 = tuple(d for d in (0, 1) if d in active)

        def _split(shape, itemsize, zpatch):
            """(ring/mid selector suffix, admissibility error) for the
            resolved tile — the shared trace-time gate (`split_selector`)."""
            from ..ops import pallas_stencil

            return split_selector(
                pallas_stencil, shape, fused_k, fused_k, itemsize, bx, by,
                active01, zpatch, stagger=0, gg=gg,
            )

        def fused_or_fallback(T, Cp, fused_body, xla_body, zpatch_body=None,
                              pipelined_bodies=None):
            # Kernel paths are wrapped with `fused_with_xla_grad`: the
            # primal runs the Pallas chunk, jax.grad differentiates the
            # XLA-cadence twin (the kernels have no VJP).
            shape = tuple(T.shape)
            pb = pipelined_bodies or {}
            if (
                zpatch_body is not None
                and z_active
                and fused_support_error(
                    shape, fused_k, T.dtype.itemsize, bx, by, zpatch=True
                ) is None
            ):
                # In-kernel z-slab application (docs/performance.md's
                # exchanged-dimension anisotropy note).
                body = zpatch_body
                if "zpatch" in pb and resolve_pipelined(
                    pipelined, _split(shape, T.dtype.itemsize, True)[1],
                    shape, fused_k, "diffusion",
                ):
                    body = pb["zpatch"]
                return fused_with_xla_grad(body, xla_body)(T, Cp)
            err = fused_support_error(shape, fused_k, T.dtype.itemsize, bx, by)
            if err is None:
                body = fused_body
                # The non-zpatch pipelined split only exists on z-inactive
                # grids (a z-DUS exchange spans every tile's rows).
                if "plain" in pb and not z_active and resolve_pipelined(
                    pipelined, _split(shape, T.dtype.itemsize, False)[1],
                    shape, fused_k, "diffusion",
                ):
                    body = pb["plain"]
                return fused_with_xla_grad(body, xla_body)(T, Cp)
            _warn_fused_fallback(tuple(T.shape), fused_k, err)
            if pipelined and "xla" in pb:
                # Explicit request: the XLA cadence with the early-dispatch
                # exchange shape (begin/finish; bit-identical values).
                return pb["xla"](T, Cp)
            return xla_body(T, Cp)

        from ._fused import run_group_schedule

        groups = [fused_k] * (nsteps // fused_k)

        if not active:
            if pipelined:
                from ._fused import warn_pipelined_fallback

                warn_pipelined_fallback(
                    None, fused_k, "no halo activity: nothing to overlap"
                )

            def fused_chunk(T, Cp):
                T = run_group_schedule(
                    groups,
                    lambda ki, T: fused_diffusion_steps(
                        T, Cp, ki, cx, cy, cz, bx=bx, by=by
                    ),
                    T,
                )
                return T, Cp

            def xla_chunk(T, Cp):
                # No halo activity: the exchange is a no-op, plain steps.
                return lax.fori_loop(0, nsteps, lambda i, T: update(T, Cp), T), Cp

            # No halo activity means no collectives: skip the shard_map
            # wrapper and jit directly (fields are committed to the grid's
            # single device).
            body = lambda T, Cp: fused_or_fallback(T, Cp, fused_chunk, xla_chunk)
            if batch:
                body = jax.vmap(body)
            return jax.jit(body, donate_argnums=(0,) if donate else ())

        def fused_block_step(T, Cp):
            def body(ki, T):
                T = fused_diffusion_steps(T, Cp, ki, cx, cy, cz, bx=bx, by=by)
                # One slab exchange licenses the next fused_k steps: the
                # kernel's k-deep contaminated rind is exactly the region
                # the width-k exchange refreshes, and the sent planes
                # [ol-k, ol) sit at distance >= k from the block edge,
                # where k kernel steps are still exact.
                return update_halo(T, width=fused_k, coalesce=coalesce)

            return run_group_schedule(groups, body, T), Cp

        def fused_zpatch_step(T, Cp):
            from ..ops.halo import (
                _T_AXES,
                apply_z_patch,
                apply_z_patch_t,
                exchange_dims_multi,
                identity_z_patch,
                identity_z_patch_t,
                ol,
                z_patch_from_export,
                z_patch_from_export_t,
            )
            from ..ops.pallas_stencil import zpatch_transposed

            shape = tuple(T.shape)
            o_z = ol(2, shape=shape, gg=gg)
            # Patch layout follows the kernel's tile choice: full-y tiles
            # take the transposed thin-plane layout (round 5 — ~16x less
            # patch/export window traffic), others the packed 128-lane one.
            tr = zpatch_transposed(shape, fused_k, T.dtype.itemsize, bx, by)

            def group(ki, carry):
                T, patch = carry
                # The kernel applies the z patch per tile in VMEM AND
                # exports the next group's send slabs (round 4: extraction
                # outside the kernel paid whole-array relayouts per group);
                # x/y slabs exchange outside (cheap DUS) for both T and the
                # packed export IN ONE COALESCED PASS (one permute pair per
                # dim for the pair of fields; corner semantics preserved),
                # then the z communication runs on the packed array alone.
                T, zex = fused_diffusion_steps(
                    T, Cp, fused_k, cx, cy, cz, bx=bx, by=by, z_patch=patch,
                    z_export=True, z_overlap=o_z,
                )
                if tr:
                    T, zex = exchange_dims_multi(
                        (T, zex), (0, 1), width=fused_k,
                        logicals=(None, shape), axes=(None, _T_AXES),
                        coalesce=coalesce,
                    )
                    return T, z_patch_from_export_t(zex, width=fused_k)
                T, zex = exchange_dims_multi(
                    (T, zex), (0, 1), width=fused_k, coalesce=coalesce
                )
                return T, z_patch_from_export(zex, width=fused_k)

            mk_ident = identity_z_patch_t if tr else identity_z_patch
            T, patch = run_group_schedule(
                groups, group, (T, mk_ident(T, width=fused_k))
            )
            mk_apply = apply_z_patch_t if tr else apply_z_patch
            return mk_apply(T, patch, width=fused_k), Cp

        def fused_pipelined_block_step(T, Cp):
            # Boundary-first split of `fused_block_step` (z-inactive grids):
            # the ring pass feeds the x/y slab exchange early, the interior
            # pass runs across the in-flight collectives, the received
            # slabs land on the aliased combined output.
            from ..ops.halo import begin_slab_exchange, finish_slab_exchange
            from ._fused import run_pipelined_group_schedule

            sel, _, _ = _split(tuple(T.shape), T.dtype.itemsize, False)

            def boundary(ki, T):
                Tb = fused_diffusion_steps(
                    T, Cp, ki, cx, cy, cz, bx=bx, by=by, tile_sel="ring" + sel
                )
                return (Tb,), begin_slab_exchange(
                    (Tb,), (0, 1), width=fused_k, coalesce=coalesce
                )

            def interior(ki, T, b_out, pend):
                T2 = fused_diffusion_steps(
                    T, Cp, ki, cx, cy, cz, bx=bx, by=by,
                    tile_sel="mid" + sel, carry_in=b_out,
                )
                (T2,) = finish_slab_exchange((T2,), pend)
                return T2

            return run_pipelined_group_schedule(groups, boundary, interior, T), Cp

        def fused_zpatch_pipelined_step(T, Cp):
            # Boundary-first split of `fused_zpatch_step`: x/y slabs of T
            # exchange early off the ring pass; the packed z export (which
            # every tile feeds) completes with the interior pass and its
            # thin communication stays on the serialized tail of the group.
            from ..ops.halo import (
                apply_z_patch,
                apply_z_patch_t,
                begin_slab_exchange,
                exchange_dims,
                exchange_dims_t,
                finish_slab_exchange,
                identity_z_patch,
                identity_z_patch_t,
                ol,
                z_patch_from_export,
                z_patch_from_export_t,
            )
            from ..ops.pallas_stencil import zpatch_transposed
            from ._fused import run_pipelined_group_schedule

            shape = tuple(T.shape)
            o_z = ol(2, shape=shape, gg=gg)
            tr = zpatch_transposed(shape, fused_k, T.dtype.itemsize, bx, by)
            sel, _, _ = _split(shape, T.dtype.itemsize, True)

            def boundary(ki, carry):
                T, patch = carry
                b_out = fused_diffusion_steps(
                    T, Cp, fused_k, cx, cy, cz, bx=bx, by=by, z_patch=patch,
                    z_export=True, z_overlap=o_z, tile_sel="ring" + sel,
                )
                pend = begin_slab_exchange(
                    b_out[:1], (0, 1), width=fused_k, coalesce=coalesce
                )
                return b_out, pend

            def interior(ki, carry, b_out, pend):
                T, patch = carry
                T2, zex = fused_diffusion_steps(
                    T, Cp, fused_k, cx, cy, cz, bx=bx, by=by, z_patch=patch,
                    z_export=True, z_overlap=o_z,
                    tile_sel="mid" + sel, carry_in=b_out,
                )
                (T2,) = finish_slab_exchange((T2,), pend)
                if tr:
                    zex = exchange_dims_t(
                        zex, width=fused_k, shape=shape, coalesce=coalesce
                    )
                    return T2, z_patch_from_export_t(zex, width=fused_k)
                zex = exchange_dims(zex, (0, 1), width=fused_k)
                return T2, z_patch_from_export(zex, width=fused_k)

            mk_ident = identity_z_patch_t if tr else identity_z_patch
            T, patch = run_pipelined_group_schedule(
                groups, boundary, interior, (T, mk_ident(T, width=fused_k))
            )
            mk_apply = apply_z_patch_t if tr else apply_z_patch
            return mk_apply(T, patch, width=fused_k), Cp

        def xla_cadence_step(T, Cp):
            def group(i, T):
                T = lax.fori_loop(0, fused_k, lambda j, T: update(T, Cp), T)
                return update_halo(T, width=fused_k, coalesce=coalesce)

            return lax.fori_loop(0, nsteps // fused_k, group, T), Cp

        def xla_pipelined_cadence_step(T, Cp):
            # The XLA fallback with the early-dispatch exchange shape: the
            # group's permutes depend on slab slices only (begin), the
            # received planes land lazily (finish).  Values bit-identical
            # to `xla_cadence_step`; there is no tile split to ride, so
            # only `pipelined=True` selects it.
            from ..ops.halo import begin_slab_exchange, finish_slab_exchange

            def group(i, T):
                T = lax.fori_loop(0, fused_k, lambda j, T: update(T, Cp), T)
                pend = begin_slab_exchange(
                    (T,), (0, 1, 2), width=fused_k, coalesce=coalesce
                )
                (T,) = finish_slab_exchange((T,), pend)
                return T

            return lax.fori_loop(0, nsteps // fused_k, group, T), Cp

        return _wrap(
            lambda T, Cp: fused_or_fallback(
                T, Cp, fused_block_step, xla_cadence_step, fused_zpatch_step,
                pipelined_bodies={
                    "plain": fused_pipelined_block_step,
                    "zpatch": fused_zpatch_pipelined_step,
                    "xla": xla_pipelined_cadence_step,
                },
            )
        )

    update = _diffusion_update(params)

    if exchange_every < 1:
        raise ValueError(f"exchange_every must be >= 1 (got {exchange_every})")
    if exchange_every > 1:
        from ..ops.halo import require_deep_halo

        if params.hide_comm:
            raise ValueError(
                "exchange_every and hide_comm are mutually exclusive: overlap "
                "scheduling hides the per-step exchange; a slab cadence "
                "replaces it."
            )
        if nsteps % exchange_every != 0:
            raise ValueError(
                f"nsteps={nsteps} must be a multiple of exchange_every={exchange_every}"
            )
        require_deep_halo(exchange_every)
        w = exchange_every

        def block_step(T, Cp):
            def group(i, T):
                T = lax.fori_loop(0, w, lambda j, T: update(T, Cp), T)
                if pipelined:
                    # Early-dispatch exchange shape (bit-identical values);
                    # see the ``pipelined`` docstring note.
                    from ..ops.halo import (
                        begin_slab_exchange,
                        finish_slab_exchange,
                    )

                    pend = begin_slab_exchange(
                        (T,), (0, 1, 2), width=w, coalesce=coalesce
                    )
                    (T,) = finish_slab_exchange((T,), pend)
                    return T
                return update_halo(T, width=w, coalesce=coalesce)

            T = lax.fori_loop(0, nsteps // w, group, T)
            return T, Cp

        return _wrap(block_step)

    if pipelined:
        raise ValueError(
            "pipelined applies to the group cadences (fused_k or "
            "exchange_every > 1); the per-step path has no group schedule."
        )

    if params.hide_comm:
        overlapped = hide_communication(update, radius=1)

        def one(T, Cp):
            return overlapped(T, Cp)

    else:

        def one(T, Cp):
            return update_halo(update(T, Cp), coalesce=coalesce)

    def block_step(T, Cp):
        T = lax.fori_loop(0, nsteps, lambda i, T: one(T, Cp), T)
        return T, Cp

    return _wrap(block_step)


def run(
    nt: int,
    nx: int = 128,
    ny: int = 128,
    nz: int = 128,
    *,
    finalize: bool = True,
    guard_every: int | None = None,
    guard_policy: str | None = None,
    checkpoint_every: int | None = None,
    checkpoint_dir: str | None = None,
    checkpoint_keep: int | None = None,
    integrity_every: int | None = None,
    **setup_kwargs,
):
    """End-to-end run (the reference's ``diffusion3D()`` without visualization).

    Returns the final global-block temperature field.

    Resilience hooks (kwarg > ``IGG_*`` env > off; docs/robustness.md):
    ``guard_every=N`` runs the `igg.check_fields` NaN/Inf probe every ``N``
    steps under ``guard_policy`` (``raise`` | ``warn`` | ``rollback``);
    ``checkpoint_every=N`` writes restartable checkpoints to
    ``checkpoint_dir`` — a rerun pointing at the same directory resumes
    from the latest VALID one, even on a different admissible topology
    (elastic restart: re-init with any ``dims``/local sizes implying the
    same global grid).  ``checkpoint_keep=N`` (``IGG_CHECKPOINT_KEEP``)
    prunes to the newest N generations after each save, never deleting the
    only integrity-verified one.  ``integrity_every=N``
    (``IGG_INTEGRITY_EVERY``) arms the shadow-step audit: every Nth step
    is re-executed from retained pre-step state and bit-compared — a
    finite silent corruption the NaN/Inf guard can never see raises
    `integrity.IntegrityError` naming the corrupting rank.
    """
    import jax

    from ..parallel.grid import global_grid, grid_is_initialized
    from ..utils.resilience import RunGuard, guarded_time_loop

    caller_owns_grid = grid_is_initialized()  # init_grid=False with a live grid
    try:
        from ..utils import tracing as _tracing

        # Live plane up BEFORE the long bring-up/compile phase (no-op
        # unless IGG_METRICS_PORT is set): an operator can scrape /healthz
        # while the program is still building (docs/observability.md).
        from ..utils import liveplane as _liveplane

        _liveplane.ensure_server()
        # Setup span: grid bring-up + field allocation, distinct from the
        # per-step `igg.step` spans the loop records (docs/observability.md).
        with _tracing.trace_span("igg.run.setup", model="diffusion3d"):
            state, params = setup(nx, ny, nz, **setup_kwargs)
            step = make_step(params)
        guard = RunGuard(
            guard_every=guard_every,
            policy=guard_policy,
            checkpoint_every=checkpoint_every,
            checkpoint_dir=checkpoint_dir,
            checkpoint_keep=checkpoint_keep,
            integrity_every=integrity_every,
            names=("T", "Cp"),
        )
        # On the virtual CPU mesh, XLA's in-process collectives deadlock if
        # too many asynchronously dispatched programs pile up; syncing each
        # step costs nothing there and is skipped on real accelerators.
        sync_every_step = global_grid().mesh.devices.flat[0].platform == "cpu"
        # Telemetry bytes model (docs/observability.md): the diffusion step
        # MUST stream T once in and once out; Cp is a read-only parameter
        # field and does not count (the reference T_eff convention).
        from ..utils.telemetry import teff_bytes

        state = guarded_time_loop(
            step, state, nt, guard=guard, sync_every_step=sync_every_step,
            model="diffusion3d", bytes_per_step=teff_bytes(state[:1]),
        )
        T = jax.block_until_ready(state[0])
    except BaseException:
        # A failed run must not poison the next init_global_grid in this
        # process (the singleton would report "already initialized") — but
        # never tear down a grid the caller set up themselves.
        if not caller_owns_grid and grid_is_initialized():
            finalize_global_grid()
        raise
    if finalize:
        finalize_global_grid()
    return T


def temperature(state):
    return state[0]
