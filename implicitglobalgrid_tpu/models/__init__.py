"""Model zoo: the reference's example applications, rebuilt TPU-first.

Each model is a module with the same shape: ``setup`` (grid + fields + initial
conditions), ``make_step`` (one fused SPMD time step), ``run`` (end-to-end).
They correspond to the benchmark configs in `BASELINE.md`:

* `diffusion3d` — 3-D heat diffusion (the reference's flagship example,
  `/root/reference/examples/diffusion3D_*.jl`).
* `acoustic3d` — 3-D acoustic wave on a staggered grid with comm/compute
  overlap (BASELINE config 3).
* `porous_convection3d` — pseudo-transient porous convection, the HydroMech3D
  weak-scaling analogue (BASELINE config 4).

Modules import lazily via ``__getattr__`` so ``import implicitglobalgrid_tpu``
stays light.
"""

import importlib

_MODELS = ("diffusion3d", "acoustic3d", "porous_convection3d")

__all__ = list(_MODELS)


def __getattr__(name):
    if name in _MODELS:
        try:
            return importlib.import_module(f".{name}", __name__)
        except ModuleNotFoundError as e:
            raise AttributeError(f"module {__name__!r} has no attribute {name!r}") from e
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
