"""Example physics models built on the framework."""
