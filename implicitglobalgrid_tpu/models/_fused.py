"""Shared fused-kernel fallback warning (diffusion + acoustic + porous).

The reference's precedent is runtime path selection by threshold
(`/root/reference/src/update_halo.jl:755-784`); here the selection happens at
trace time against the kernel envelope (`fused_support_error`), warning once
per (shape, k, reason) so production loops are not spammed.
"""

from __future__ import annotations

_warned: set = set()


def warn_fused_fallback(shape, k, err, model: str = "diffusion") -> None:
    """Warn once per (model, shape, k, reason) that fused_k fell back to XLA.

    ``model`` keys the registry per kernel: the diffusion and leapfrog
    envelopes share reason strings, and one model's fallback must not
    silence another's first warning.
    """
    import warnings

    key = (model, shape, k, err)
    if key in _warned:
        return
    _warned.add(key)
    warnings.warn(
        f"fused_k={k} is unsupported for {model}'s local block shape {shape} "
        f"({err}); falling back to the XLA path at the same exchange cadence.",
        RuntimeWarning,
        stacklevel=3,
    )
