"""Shared fused-kernel helpers (diffusion + acoustic + porous).

Fallback warning: the reference's precedent is runtime path selection by
threshold (`/root/reference/src/update_halo.jl:755-784`); here the selection
happens at trace time against the kernel envelope (`fused_support_error`),
warning once per (shape, k, reason) so production loops are not spammed.

Autodiff: `fused_with_xla_grad` makes ``jax.grad`` work through the fused
Pallas chunks (which have no VJP of their own) by differentiating the
equivalent XLA cadence in the backward pass.
"""

from __future__ import annotations

_warned: set = set()


def run_group_schedule(chunks, body, carry, *, unroll_limit=8,
                       fori_excess_only=True):
    """Run ``carry = body(ki, carry)`` for each ``ki`` in ``chunks``.

    The one loop shape behind every fused cadence's group sequence: up to
    ``unroll_limit`` groups are Python-unrolled — one Pallas call per group
    is tiny HLO, and the unrolled form measured ~15-30% faster than a
    fori_loop over groups (XLA pipelines DMAs across group boundaries;
    probed on v5e: porous npt=12 fused6 788 -> 1017 GB/s/PT-iter, acoustic
    256^3 fused6 1117 -> 1564).  A leading run of equal chunks longer than
    the limit routes only its EXCESS through ONE `lax.fori_loop` (bounds
    compile size for long schedules) and still unrolls ``unroll_limit``
    groups in total — a 12-group production schedule keeps the pipelining
    win on 8 of them (advisor r4: the old shape sent such schedules
    entirely through the fori_loop).

    ``fori_excess_only=False`` restores the all-or-nothing shape: a uniform
    run longer than the limit goes ENTIRELY through the fori_loop (the
    ragged tail still unrolls).  The porous XLA cadence needs this — its
    group bodies are large unrolled XLA programs whose bit-identity across
    cadences relies on the fori boundary as a fusion barrier (unrolling the
    last group lets XLA contract FMAs differently per surrounding context);
    the Pallas paths are immune (fusion cannot reach inside a kernel).
    """
    prefix = 0
    while prefix < len(chunks) and chunks[prefix] == chunks[0]:
        prefix += 1
    if fori_excess_only:
        keep = max(unroll_limit - (len(chunks) - prefix), 0)
    else:
        keep = prefix if prefix <= unroll_limit else 0
    if prefix > keep:
        from jax import lax

        k0 = chunks[0]
        nloop = prefix - keep
        carry = lax.fori_loop(0, nloop, lambda i, c: body(k0, c), carry)
        chunks = chunks[nloop:]
    for ki in chunks:
        carry = body(ki, carry)
    return carry


def fused_with_xla_grad(fused_body, xla_body):
    """Make a fused Pallas chunk differentiable via its XLA-cadence twin.

    The temporally-blocked Pallas kernels have no VJP; their XLA cadences
    (same steps, same slab exchanges, pure jnp/lax ops) match them to a few
    float ULPs — so the primal runs ``fused_body`` (full kernel speed) and
    the backward pass recomputes + differentiates ``xla_body`` via
    ``jax.vjp``.  Residuals are just the chunk inputs (rematerialization:
    one extra XLA-cadence forward per backward, nothing saved across the
    k-step loop).  Without this wrapper ``jax.grad`` over a fused multi-step
    dies inside `pallas_call` with no actionable message; with it the fused
    production path and the autodiff story (`tests/test_autodiff.py`)
    compose.  TPU-first capability — no reference analogue (the reference
    has no autodiff, SURVEY.md §0).
    """
    import jax

    f = jax.custom_vjp(fused_body)

    def fwd(*args):
        return fused_body(*args), args

    def bwd(args, g):
        _, vjp = jax.vjp(xla_body, *args)
        return vjp(g)

    f.defvjp(fwd, bwd)
    return f


def warn_fused_fallback(shape, k, err, model: str = "diffusion") -> None:
    """Warn once per (model, shape, k, reason) that fused_k fell back to XLA.

    ``model`` keys the registry per kernel: the diffusion and leapfrog
    envelopes share reason strings, and one model's fallback must not
    silence another's first warning.
    """
    import warnings

    key = (model, shape, k, err)
    if key in _warned:
        return
    _warned.add(key)
    warnings.warn(
        f"fused_k={k} is unsupported for {model}'s local block shape {shape} "
        f"({err}); falling back to the XLA path at the same exchange cadence.",
        RuntimeWarning,
        stacklevel=3,
    )
