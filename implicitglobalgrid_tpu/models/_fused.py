"""Shared fused-kernel helpers (diffusion + acoustic + porous).

Fallback warning: the reference's precedent is runtime path selection by
threshold (`/root/reference/src/update_halo.jl:755-784`); here the selection
happens at trace time against the kernel envelope (`fused_support_error`),
warning once per (shape, k, reason) so production loops are not spammed.

Autodiff: `fused_with_xla_grad` makes ``jax.grad`` work through the fused
Pallas chunks (which have no VJP of their own) by differentiating the
equivalent XLA cadence in the backward pass.
"""

from __future__ import annotations

_warned: set = set()


def run_group_schedule(chunks, body, carry, *, unroll_limit=8,
                       fori_excess_only=True):
    """Run ``carry = body(ki, carry)`` for each ``ki`` in ``chunks``.

    The one loop shape behind every fused cadence's group sequence: up to
    ``unroll_limit`` groups are Python-unrolled — one Pallas call per group
    is tiny HLO, and the unrolled form measured ~15-30% faster than a
    fori_loop over groups (XLA pipelines DMAs across group boundaries;
    probed on v5e: porous npt=12 fused6 788 -> 1017 GB/s/PT-iter, acoustic
    256^3 fused6 1117 -> 1564).  A leading run of equal chunks longer than
    the limit routes only its EXCESS through ONE `lax.fori_loop` (bounds
    compile size for long schedules) and still unrolls ``unroll_limit``
    groups in total — a 12-group production schedule keeps the pipelining
    win on 8 of them (advisor r4: the old shape sent such schedules
    entirely through the fori_loop).

    ``fori_excess_only=False`` restores the all-or-nothing shape: a uniform
    run longer than the limit goes ENTIRELY through the fori_loop (the
    ragged tail still unrolls).  The porous XLA cadence needs this — its
    group bodies are large unrolled XLA programs whose bit-identity across
    cadences relies on the fori boundary as a fusion barrier (unrolling the
    last group lets XLA contract FMAs differently per surrounding context);
    the Pallas paths are immune (fusion cannot reach inside a kernel).
    """
    prefix = 0
    while prefix < len(chunks) and chunks[prefix] == chunks[0]:
        prefix += 1
    if fori_excess_only:
        keep = max(unroll_limit - (len(chunks) - prefix), 0)
    else:
        keep = prefix if prefix <= unroll_limit else 0
    if prefix > keep:
        from jax import lax

        k0 = chunks[0]
        nloop = prefix - keep
        carry = lax.fori_loop(0, nloop, lambda i, c: body(k0, c), carry)
        chunks = chunks[nloop:]
    for ki in chunks:
        carry = body(ki, carry)
    return carry


def run_pipelined_group_schedule(chunks, boundary, interior, carry, *,
                                 unroll_limit=8, fori_excess_only=True):
    """Boundary-first pipelined sibling of `run_group_schedule`.

    Each group's kernel launch is split in two (the `@hide_communication`
    scheduling of the per-step path — `ops/overlap.py` — lifted to tile
    granularity inside the fused group schedule):

    * ``boundary(ki, carry) -> (b_out, pend)`` runs the RING tiles — the
      tiles owning every x/y slab-exchange send plane — and dispatches the
      group's exchange early (`ops.halo.begin_slab_exchange`): the
      `collective-permute`s depend only on thin slices of the ring outputs.
    * ``interior(ki, carry, b_out, pend) -> carry`` runs the MID tiles as
      an op independent of the in-flight collectives (the ring outputs ride
      an input/output alias into the interior launch, so XLA schedules the
      permutes across it), then applies the received slabs
      (`ops.halo.finish_slab_exchange`) and the group's z-patch carry.

    The split-launch carry threaded through each group keeps per-group
    results bit-identical to the serialized schedule: ring+mid partition
    the same tiles tile-for-tile, and the early exchange moves exactly the
    slabs the serialized exchange would (corner strips patched in,
    `ops.halo._patch_slab`).  The loop shaping (unrolled prefix, fori
    excess) is `run_group_schedule`'s.
    """

    from ..utils.compat import named_scope

    def group(ki, c):
        # Named profiler scopes (docs/observability.md): the ring pass (and
        # the early slab-exchange dispatch it feeds) vs the interior pass
        # show up as distinctly named op groups in a `profile_trace`
        # capture — the runtime evidence that the collectives overlap the
        # interior launch, by name in Perfetto.
        with named_scope("igg_ring_pass"):
            b_out, pend = boundary(ki, c)
        with named_scope("igg_interior_pass"):
            return interior(ki, c, b_out, pend)

    return run_group_schedule(
        chunks, group, carry,
        unroll_limit=unroll_limit, fori_excess_only=fori_excess_only,
    )


def resolve_pipelined(pipelined, split_err, shape, k, model: str) -> bool:
    """Resolve a cadence's ``pipelined`` knob against split admissibility.

    ``pipelined`` is the user knob (None = auto); ``split_err`` is
    `ops.overlap.tile_split_error`'s verdict (None = admissible) for the
    traced local block.  Auto turns the pipelined schedule ON whenever the
    split is admissible (it is bit-identical to the serialized schedule,
    so the only reason to stay serialized is an inadmissible split);
    ``pipelined=True`` on an inadmissible config warns once and runs the
    serialized schedule — the same warn-once fallback contract as the
    kernel envelope (`warn_fused_fallback`).
    """
    if pipelined is False:
        return False
    if split_err is None:
        return True
    if pipelined:
        warn_pipelined_fallback(shape, k, split_err, model)
    return False


def split_selector(kernel_mod, shape, k, width, itemsize, bx, by, active01,
                   zpatch, stagger: int = 0, gg=None):
    """(selector suffix, admissibility error, resolved tile) for a cadence.

    THE one trace-time gate behind every model's pipelined path (and the
    benchmark-provenance wrappers): resolves a missing/half tile through
    the kernel's own ladder (mirroring the kernels' ``bx is None or by is
    None`` handling), derives the y-halo H for the resolved tile, and
    checks `ops.overlap.tile_split_error` with the per-field maximum
    overlaps (``stagger=1`` for the staggered models, whose face fields'
    shape-aware ``ol`` is one deeper than the grid overlap).  The
    RESOLVED tile is returned so ragged schedules can pin every chunk's
    launch to the geometry this gate actually validated (a shorter chunk
    re-resolving its own ladder default could otherwise launch an
    unvalidated — or subset-incapable — tile; the validated tile stays
    legal for any ``ki <= k``: smaller halo, same divisibility).
    """
    from ..ops._fused_envelope import aligned_halo
    from ..ops.overlap import tile_split_error, tile_split_sel
    from ..parallel.grid import global_grid

    if gg is None:
        gg = global_grid()
    shape = tuple(shape)
    if bx is None or by is None:
        t = kernel_mod.default_tile(shape, k, itemsize, zpatch=zpatch)
        if t is None:
            return None, "no valid kernel tile for this shape", None
        bx, by = t
    H = 0 if by == shape[1] else aligned_halo(k)
    err = tile_split_error(
        shape, k, width, bx, by, H, active01,
        ox=gg.overlaps[0] + stagger, oy=gg.overlaps[1] + stagger,
    )
    return tile_split_sel(active01), err, (bx, by)


def pipelined_support_error(kernel_mod, shape, k, itemsize: int = 4,
                            bx=None, by=None, gg=None,
                            stagger: int = 0) -> str | None:
    """Why the pipelined group schedule cannot split this config, or None.

    Mirrors the cadence builders' trace-time decision: on z-active grids
    the z-patch kernel variant must be admissible (the pipelined schedule
    routes z through the in-kernel patches; a z-DUS cadence stays
    serialized), then the split must clear `split_selector`.  One
    implementation for the three models (``kernel_mod`` = the model's
    Pallas kernel module); the per-model wrappers
    (`models.*.pipelined_support_error`) exist for benchmark provenance.
    """
    from ..ops.halo import dim_has_halo_activity
    from ..parallel.grid import global_grid

    if gg is None:
        gg = global_grid()
    shape = tuple(shape)
    active = tuple(d for d in (0, 1) if dim_has_halo_activity(gg, d))
    z_active = dim_has_halo_activity(gg, 2)
    zp = z_active and kernel_mod.fused_support_error(
        shape, k, itemsize, bx, by, zpatch=True
    ) is None
    if z_active and not zp:
        return "z-active grid without the z-patch kernel: serialized z-DUS cadence"
    if not zp:
        # The split only exists on a kernel path: a config the plain
        # envelope rejects runs the XLA cadence, and labeling it
        # "pipelined" would corrupt the A/B provenance.
        kerr = kernel_mod.fused_support_error(shape, k, itemsize, bx, by)
        if kerr is not None:
            return f"kernel envelope rejects this config ({kerr}): XLA cadence"
    _, err, _ = split_selector(
        kernel_mod, shape, k, k, itemsize, bx, by, active, zp, stagger, gg
    )
    return err


def warn_pipelined_fallback(shape, k, reason, model: str = "diffusion") -> None:
    """Warn once per (model, shape, k, reason) that pipelined=True fell back
    to the serialized group schedule."""
    import warnings

    key = ("pipelined", model, shape, k, reason)
    if key in _warned:
        return
    _warned.add(key)
    where = (
        f"{model}'s local block shape {shape}" if shape is not None
        else f"the {model} cadence"  # grid-level rejection, no shape to cite
    )
    warnings.warn(
        f"pipelined=True is not admissible for {where} at k={k} ({reason}); "
        "running the serialized group schedule.",
        RuntimeWarning,
        stacklevel=3,
    )


def fused_with_xla_grad(fused_body, xla_body):
    """Make a fused Pallas chunk differentiable via its XLA-cadence twin.

    The temporally-blocked Pallas kernels have no VJP; their XLA cadences
    (same steps, same slab exchanges, pure jnp/lax ops) match them to a few
    float ULPs — so the primal runs ``fused_body`` (full kernel speed) and
    the backward pass recomputes + differentiates ``xla_body`` via
    ``jax.vjp``.  Residuals are just the chunk inputs (rematerialization:
    one extra XLA-cadence forward per backward, nothing saved across the
    k-step loop).  Without this wrapper ``jax.grad`` over a fused multi-step
    dies inside `pallas_call` with no actionable message; with it the fused
    production path and the autodiff story (`tests/test_autodiff.py`)
    compose.  TPU-first capability — no reference analogue (the reference
    has no autodiff, SURVEY.md §0).

    Composes with `jax.vmap` (the ensemble batch axis, `models._batched`):
    custom_vjp has a batching rule, the Pallas chunk batches through the
    `pallas_call` rule (batch as an outer grid dimension), and the XLA twin
    vmaps like any jnp code — so `make_multi_step(batch=True)` keeps both
    the fused primal and the differentiable story at any B
    (`tests/test_batched_serving.py` pins the fused bit-identity per
    member).
    """
    import jax

    f = jax.custom_vjp(fused_body)

    def fwd(*args):
        return fused_body(*args), args

    def bwd(args, g):
        _, vjp = jax.vjp(xla_body, *args)
        return vjp(g)

    f.defvjp(fwd, bwd)
    return f


def warn_fused_fallback(shape, k, err, model: str = "diffusion") -> None:
    """Warn once per (model, shape, k, reason) that fused_k fell back to XLA.

    ``model`` keys the registry per kernel: the diffusion and leapfrog
    envelopes share reason strings, and one model's fallback must not
    silence another's first warning.
    """
    import warnings

    key = (model, shape, k, err)
    if key in _warned:
        return
    _warned.add(key)
    warnings.warn(
        f"fused_k={k} is unsupported for {model}'s local block shape {shape} "
        f"({err}); falling back to the XLA path at the same exchange cadence.",
        RuntimeWarning,
        stacklevel=3,
    )
