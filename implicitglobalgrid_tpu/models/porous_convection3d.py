"""3-D porous convection — the HydroMech3D weak-scaling analogue.

BASELINE config 4.  The reference's headline scaling result is a multi-physics
hydro-mechanical solver built on its grid (`/root/reference/README.md:6-8`);
the publicly documented miniapp of that family is pseudo-transient porous
convection (Darcy flow + temperature advection-diffusion, the
PorousConvection3D miniapp of the reference's ecosystem).  This module builds
it TPU-first:

* **Pseudo-transient pressure solve**: each time step runs ``npt`` relaxation
  iterations of the Darcy flux / fluid pressure pair inside `lax.fori_loop` —
  the whole inner solver is ONE XLA program with a halo exchange per
  iteration, the communication pattern that dominates the reference's
  weak-scaling benchmark.
* **Comm-lean exchange**: the per-iteration exchange is the PRESSURE (one
  cell field), not the three staggered fluxes — the fluxes at every interior
  face are recomputable from post-exchange ``Pf`` (plus their own local
  relaxation history, which never crosses the block edge), so one plane per
  side per dimension crosses the wire instead of three.  3x less
  communication volume per PT iteration than the flux-exchange formulation
  on the same grid; a single 3-field flux exchange at the end of the PT loop
  restores the all-duplicated-cells-agree invariant for the frozen face
  rings (gather/visualization contract).
* **Staggered fields**: Darcy fluxes live on cell faces (``n+1`` shapes).
* **Buoyancy** (Boussinesq): ``qD = -k/eta * (grad(Pf) - Ra_hat * T * e_z)``.
* **Temperature**: explicit upwind advection + diffusion, interior update +
  halo exchange; frozen boundary planes give Dirichlet walls in z (hot
  bottom / cold top) and fixed side walls.

State: ``(T, Pf, qDx, qDy, qDz)``, all global-block fields.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from .. import (
    coord_fields,
    finalize_global_grid,
    init_global_grid,
    stencil,
    update_halo,
    zeros,
)
from ..ops.overlap import hide_communication


@dataclasses.dataclass(frozen=True)
class Params:
    Ra: float = 1000.0  # Rayleigh number
    lx: float = 2.0
    ly: float = 1.0
    lz: float = 1.0
    dT: float = 1.0  # temperature contrast bottom-top
    phi: float = 0.1  # porosity
    lam_T: float = 1.0 / 1000.0  # effective thermal diffusivity (lam/rhoCp = 1/Ra)
    dx: float = 0.0
    dy: float = 0.0
    dz: float = 0.0
    dt: float = 0.0
    theta_q: float = 0.5  # PT relaxation for fluxes
    beta_p: float = 0.0  # PT relaxation for pressure (set in setup, see bound below)
    npt: int = 20  # PT iterations per time step
    dtype: Any = None
    hide_comm: bool = False


def _inn(A):
    return A[1:-1, 1:-1, 1:-1]


def setup(
    nx: int = 32,
    ny: int = 32,
    nz: int = 32,
    *,
    Ra: float = 1000.0,
    lx: float = 2.0,
    ly: float = 1.0,
    lz: float = 1.0,
    dT: float = 1.0,
    npt: int = 20,
    dtype=None,
    hide_comm: bool = False,
    init_grid: bool = True,
    ic_scale: float = 1.0,
    **grid_kwargs,
):
    """Grid + fields: linear conductive T profile with a central Gaussian
    perturbation (the standard porous-convection initial condition), zero
    pressure and fluxes.  Returns ``(state, params)``.  ``ic_scale`` scales
    the Gaussian perturbation (the ensemble lever,
    `models._batched.batched_setup`)."""
    import jax
    import jax.numpy as jnp

    from ..utils import tools

    if init_grid:
        init_global_grid(nx, ny, nz, **grid_kwargs)
    if dtype is None:
        dtype = jax.dtypes.canonicalize_dtype(float)
    dx = lx / (tools.nx_g() - 1)
    dy = ly / (tools.ny_g() - 1)
    dz = lz / (tools.nz_g() - 1)
    lam_T = 1.0 / Ra
    dmin = min(dx, dy, dz)
    # Fixed dt bounded by both explicit limits (miniapp simplification of the
    # adaptive dt used in the reference ecosystem): diffusive dmin^2/lam/8.1,
    # and advective phi*dmin/(3*q_scale) with the buoyancy-limited flux scale
    # q_scale = Ra*lam_T*dT.
    phi = 0.1
    q_scale = Ra * lam_T * dT
    dt = min(dmin**2 / lam_T / 8.1, phi * dmin / (3.0 * q_scale))
    # Pressure relaxation: von Neumann bound for the (theta, beta) PT pair is
    # beta*theta*k^2 <= 2 with the 3-D staggered-Laplacian spectral bound
    # k^2 <= 4*(1/dx^2 + 1/dy^2 + 1/dz^2).
    theta_q = 0.5
    k2_max = 4.0 * (1.0 / dx**2 + 1.0 / dy**2 + 1.0 / dz**2)
    beta_p = 0.9 * 2.0 / (theta_q * k2_max)
    params = Params(
        Ra=Ra, lx=lx, ly=ly, lz=lz, dT=dT, phi=phi, lam_T=lam_T,
        dx=dx, dy=dy, dz=dz, dt=dt, theta_q=theta_q, beta_p=beta_p,
        npt=int(npt), dtype=dtype, hide_comm=hide_comm,
    )

    T0 = zeros((nx, ny, nz), dtype)
    X, Y, Z = coord_fields(T0, (dx, dy, dz), dtype=dtype)

    @stencil
    def init_ic(X, Y, Z):
        # Conductive profile: +dT/2 at z=0 (hot bottom) to -dT/2 at z=lz.
        prof = dT / 2 - dT * Z / lz
        pert = (
            0.1
            * dT
            * jnp.exp(
                -(((X - lx / 2) / 0.1) ** 2)
                - ((Y - ly / 2) / 0.1) ** 2
                - ((Z - lz / 2) / 0.1) ** 2
            )
        )
        return (prof + ic_scale * pert).astype(dtype)

    T = init_ic(X, Y, Z)
    Pf = zeros((nx, ny, nz), dtype)
    qDx = zeros((nx + 1, ny, nz), dtype)
    qDy = zeros((nx, ny + 1, nz), dtype)
    qDz = zeros((nx, ny, nz + 1), dtype)
    return (T, Pf, qDx, qDy, qDz), params


def _flux_update(params: Params):
    """Pure per-block Darcy flux relaxation (no exchange): interior faces only
    (padded-delta form — boundary faces frozen, the no-flow walls)."""
    import jax.numpy as jnp

    th = params.theta_q
    dx, dy, dz = params.dx, params.dy, params.dz

    def av_z_to_face(T):
        # T averaged onto interior z-faces: (nx-2, ny-2, nz-1)
        return 0.5 * (T[1:-1, 1:-1, 1:] + T[1:-1, 1:-1, :-1])

    def update(T, Pf, qDx, qDy, qDz):
        # Darcy flux relaxation toward -grad(Pf) + Ra*T e_z (interior faces).
        fx = -jnp.diff(Pf[:, 1:-1, 1:-1], axis=0) / dx
        fy = -jnp.diff(Pf[1:-1, :, 1:-1], axis=1) / dy
        fz = -jnp.diff(Pf[1:-1, 1:-1, :], axis=2) / dz + params.Ra * params.lam_T * av_z_to_face(T)
        qDx = qDx + jnp.pad(th * (fx - _inn(qDx)), 1)
        qDy = qDy + jnp.pad(th * (fy - _inn(qDy)), 1)
        qDz = qDz + jnp.pad(th * (fz - _inn(qDz)), 1)
        return qDx, qDy, qDz

    return update


def _pressure_update(params: Params):
    """Pure per-block pressure relaxation: all cells, from fresh fluxes.

    At global walls the frozen boundary faces carry flux 0, so the outermost
    cells evolve under the physical no-flow condition; at block-internal
    edges the same expression writes garbage into the halo cells (stale
    frozen faces), which the Pf exchange overwrites with the neighbor's
    interior values — the standard recompute-then-exchange pattern."""
    import jax.numpy as jnp

    bp = params.beta_p
    dx, dy, dz = params.dx, params.dy, params.dz

    def update(Pf, qDx, qDy, qDz):
        div = (
            jnp.diff(qDx, axis=0) / dx
            + jnp.diff(qDy, axis=1) / dy
            + jnp.diff(qDz, axis=2) / dz
        )
        return Pf - bp * div

    return update


def _pt_iteration(params: Params):
    """One pseudo-transient Darcy relaxation: flux update (+buoyancy) on
    interior faces, pressure update at all cells, halo exchange of ``Pf``
    (ONE field — see the module docstring's comm-lean design note; the
    reference's analogue exchanges every relaxed field per iteration,
    `/root/reference/src/update_halo.jl:25-78` applied in its miniapp loops).
    The fluxes need no per-iteration exchange: their interior faces are
    recomputed each iteration from post-exchange ``Pf`` halos and their own
    (purely local) relaxation history.  With ``params.hide_comm`` the ``Pf``
    exchange overlaps the interior pressure update (`hide_communication`)."""
    flux_update = _flux_update(params)
    p_update = _pressure_update(params)

    if params.hide_comm:
        overlapped_p = hide_communication(p_update, radius=1)

        def pressure_exchanged(Pf, qDx, qDy, qDz):
            return overlapped_p(Pf, qDx, qDy, qDz)

    else:

        def pressure_exchanged(Pf, qDx, qDy, qDz):
            return update_halo(p_update(Pf, qDx, qDy, qDz))

    def iteration(T, Pf, qDx, qDy, qDz):
        qDx, qDy, qDz = flux_update(T, Pf, qDx, qDy, qDz)
        Pf = pressure_exchanged(Pf, qDx, qDy, qDz)
        return Pf, qDx, qDy, qDz

    return iteration


def _temperature_update(params: Params):
    """Explicit upwind advection + diffusion of T (interior), frozen walls."""
    import jax.numpy as jnp

    dx, dy, dz = params.dx, params.dy, params.dz
    lam = params.lam_T
    iphi = 1.0 / params.phi
    dt = params.dt

    def update(T, qDx, qDy, qDz):
        lap = (
            (T[2:, 1:-1, 1:-1] - 2 * _inn(T) + T[:-2, 1:-1, 1:-1]) / (dx * dx)
            + (T[1:-1, 2:, 1:-1] - 2 * _inn(T) + T[1:-1, :-2, 1:-1]) / (dy * dy)
            + (T[1:-1, 1:-1, 2:] - 2 * _inn(T) + T[1:-1, 1:-1, :-2]) / (dz * dz)
        )
        # Upwind advective derivatives at interior cells from face fluxes.
        qxm = qDx[1:-2, 1:-1, 1:-1]  # face below cell (x), interior cells
        qxp = qDx[2:-1, 1:-1, 1:-1]  # face above cell (x)
        qym = qDy[1:-1, 1:-2, 1:-1]
        qyp = qDy[1:-1, 2:-1, 1:-1]
        qzm = qDz[1:-1, 1:-1, 1:-2]
        qzp = qDz[1:-1, 1:-1, 2:-1]
        dTm_x = (_inn(T) - T[:-2, 1:-1, 1:-1]) / dx
        dTp_x = (T[2:, 1:-1, 1:-1] - _inn(T)) / dx
        dTm_y = (_inn(T) - T[1:-1, :-2, 1:-1]) / dy
        dTp_y = (T[1:-1, 2:, 1:-1] - _inn(T)) / dy
        dTm_z = (_inn(T) - T[1:-1, 1:-1, :-2]) / dz
        dTp_z = (T[1:-1, 1:-1, 2:] - _inn(T)) / dz
        adv = (
            jnp.maximum(qxm, 0.0) * dTm_x
            + jnp.minimum(qxp, 0.0) * dTp_x
            + jnp.maximum(qym, 0.0) * dTm_y
            + jnp.minimum(qyp, 0.0) * dTp_y
            + jnp.maximum(qzm, 0.0) * dTm_z
            + jnp.minimum(qzp, 0.0) * dTp_z
        )
        dTdt = lam * lap - iphi * adv
        return T + jnp.pad(dt * dTdt, 1)

    return update


def _build_block_step(params: Params, coalesce: bool | None = None):
    """One whole time step (per-iteration exchange cadence), shared verbatim
    by `make_step` and `make_multi_step(exchange_every=1)` so the physics can
    never diverge between the two entry points: ``npt`` PT iterations
    (fori_loop, per-iteration ``Pf`` exchange), the once-per-step 3-field
    flux exchange (refreshing only the frozen face rings — the interior
    faces are already exact — to restore the duplicated-cells-agree
    invariant for gather/visualization), then the T update + exchange.
    ``coalesce``: forwarded to the multi-field flux exchange
    (`make_multi_step`'s knob; None = the ``IGG_COALESCE`` default)."""
    from jax import lax

    pt_iter = _pt_iteration(params)
    t_update = _temperature_update(params)
    npt = params.npt

    def block_step(T, Pf, qDx, qDy, qDz):
        def body(i, s):
            Pf, qDx, qDy, qDz = s
            return pt_iter(T, Pf, qDx, qDy, qDz)

        Pf, qDx, qDy, qDz = lax.fori_loop(0, npt, body, (Pf, qDx, qDy, qDz))
        qDx, qDy, qDz = update_halo(qDx, qDy, qDz, coalesce=coalesce)
        T = t_update(T, qDx, qDy, qDz)
        T = update_halo(T)
        return T, Pf, qDx, qDy, qDz

    return block_step


def _tune_state(params: Params):
    """Synthetic ones-filled state for autotuner candidate measurement
    (`tuning.search`): finite on ones (linear relaxations), real
    global-block sharded fields — a measured candidate runs the production
    SPMD program.  ``npt`` is part of the cache KEY (it changes numerics),
    so the state carries no tuned physics."""
    from .. import ones
    from ..parallel.grid import global_grid

    nx, ny, nz = global_grid().nxyz
    dt = params.dtype
    return (
        ones((nx, ny, nz), dt), ones((nx, ny, nz), dt),
        ones((nx + 1, ny, nz), dt), ones((nx, ny + 1, nz), dt),
        ones((nx, ny, nz + 1), dt),
    )


def make_step(params: Params, *, donate: bool = True, batch: bool = False):
    """One time step: ``npt`` PT pressure iterations (fori_loop) + T update,
    compiled into one XLA program per block (see `_build_block_step`).

    ``batch=True``: the ensemble step over ``(B, ...)`` batched fields —
    `jax.vmap` of the same per-block step; bit-identical per member, one
    collective pair per exchanged dimension at any B (see
    `models.diffusion3d.make_step`).
    """
    donate_argnums = tuple(range(5)) if donate else ()
    if batch:
        from ._batched import batched_stencil

        return batched_stencil(
            _build_block_step(params), 5, donate_argnums=donate_argnums
        )
    return stencil(_build_block_step(params), donate_argnums=donate_argnums)


def _pt_schedule(npt: int, w: int, *, even: bool = True):
    """Chunk ``npt`` PT iterations into groups of at most ``w``: ``(lead,
    chunks)``.

    ``even=True`` (the fused cadence — Pallas kernels need even k): ``lead``
    (0 or 1) per-iteration-exchanged XLA iterations for odd ``npt``, then
    greedy even chunks; ``w < 2`` admits no kernel chunk at all (the caller
    falls back to the XLA cadence).  ``even=False`` (the pure-XLA
    ``exchange_every`` cadence, which has no parity constraint): plain
    greedy chunks, so ``npt % w == 0`` reproduces the uniform round-3
    schedule exactly.  Ragged schedules still exchange/patch at width
    ``w`` after every chunk (VERDICT r3 #5).
    """
    if even and w < 2:
        return npt, []
    lead = npt % 2 if even else 0
    rem = npt - lead
    chunks = []
    while rem > 0:
        ki = min(w, rem)
        if even and ki % 2:
            ki -= 1
        chunks.append(ki)
        rem -= ki
    return lead, chunks


def pipelined_support_error(shape, k, itemsize: int = 4, bx=None, by=None,
                            gg=None, npt=None) -> str | None:
    """Why the pipelined group schedule cannot split this config, or None
    (benchmark provenance; see `models._fused.pipelined_support_error`).

    ``npt``: when given, also require the PT schedule to admit a kernel
    chunk at all (``npt=1`` leaves none, and the cadence then runs the XLA
    path regardless of the split)."""
    from ..ops import pallas_pt
    from ._fused import pipelined_support_error as _generic

    if npt is not None and not _pt_schedule(int(npt), k)[1]:
        return f"npt={npt} leaves no even kernel chunk: XLA cadence"
    # stagger=1: the flux fields' shape-aware ol is one deeper than the
    # grid overlap, and their send planes must fit the ring tiles too.
    return _generic(pallas_pt, shape, k, itemsize, bx, by, gg, stagger=1)


def make_multi_step(
    params: Params,
    nsteps: int,
    *,
    donate: bool = True,
    exchange_every: int = 1,
    fused_k: int | None = None,
    fused_tile: tuple[int, int] | None = None,
    pipelined: bool | None = None,
    batch: bool = False,
    coalesce: bool | None = None,
    autotune: bool | None = None,
):
    """Advance ``nsteps`` time steps per call in ONE XLA program
    (`lax.fori_loop` over whole time steps) — the production path: per-call
    dispatch amortizes over ``nsteps * npt`` PT iterations, the
    communication pattern of the reference's weak-scaling headline
    (`/root/reference/README.md:6-8`).

    ``exchange_every=w`` (deep-halo grids, ``overlap >= 2w``): the PT inner
    loop runs ``w`` relaxation iterations between exchanges and then
    slab-exchanges ALL FOUR PT fields (``Pf`` + fluxes, width ``w``) in one
    collective call — unlike the per-iteration path, the fluxes' relaxation
    history goes stale in the rind between exchanges (each unexchanged
    iteration contaminates one more ring of both ``Pf`` and ``q``), so the
    slab must replace the fluxes' stale rind too, exactly like the acoustic
    cadence exchanges its incrementally-updated ``P``.  One collective per
    ``w`` PT iterations; owned-cell results bitwise identical to the
    per-iteration path on the CPU mesh (few f32 ULPs on TPU, where
    differently-fused programs round differently).  Requires
    ``npt % w == 0``.

    ``fused_k=w``: run the ``w`` PT iterations between slab exchanges inside
    the temporally-blocked Pallas kernel (`ops/pallas_pt.py`) — one HBM pass
    per field per ``w`` iterations instead of ``w`` read/write sweeps, the
    porous sibling of the diffusion/acoustic ``fused_k`` levers.  Same
    cadence semantics as ``exchange_every=w`` (deep halo ``overlap >= 2w``,
    all-four-field width-``w`` slab exchange per group, ``npt % w == 0``);
    local blocks the kernel envelope rejects warn once and run the XLA
    cadence instead.  On grids with no halo activity the fluxes stay in the
    kernel's padded layout across the whole PT loop (pad/unpad once per time
    step); on communicating grids each group pays one pad/unpad of the three
    flux fields around the slab exchange.

    Loop structure chosen by measurement on v5e (160^3 f32, npt=10): the
    per-step PT loop stays a `lax.fori_loop`, the outer time-step loop is
    unrolled in Python INSIDE the one program — nesting it as a second
    `fori_loop` costs ~35% (225 vs 357 GB/s), while fully unrolling the PT
    loop also loses (~210 GB/s, fusion blow-up).  ``nsteps`` is a small
    production chunk, so the unroll is cheap to compile.

    ``pipelined`` (default auto): boundary-first pipelined group schedule
    for the fused PT groups — ring/interior split launches with the
    all-field slab exchange dispatched off the ring pass, exactly as on
    `models.diffusion3d.make_multi_step` (bit-identical to the serialized
    schedule; auto when admissible, see `pipelined_support_error`).
    ``pipelined=True`` also applies the early-dispatch exchange shape to
    the XLA cadences' group exchange.

    ``coalesce`` (None = ``IGG_COALESCE``, auto): passed through to every
    multi-field exchange of the cadence (`ops.halo`; bit-identical either
    way — the per-field-attribution/A/B knob, tunable per config).
    ``autotune`` (None = ``IGG_AUTOTUNE``, default off): substitute this
    point's cached winner schedule into the kwargs above
    (`implicitglobalgrid_tpu.tuning`; pure substitution — explicit kwargs
    always win, results bit-identical).  ``npt`` is part of the tuning KEY,
    never tuned: it changes the numerics, and tuning changes schedule only.
    """
    from jax import lax

    from ._fused import run_group_schedule
    from ..tuning.search import maybe_autotune

    fused_k, fused_tile, exchange_every, pipelined, coalesce = maybe_autotune(
        "porous_convection3d", params, nsteps, autotune, batch=batch,
        fused_k=fused_k, fused_tile=fused_tile, exchange_every=exchange_every,
        pipelined=pipelined, coalesce=coalesce,
    )

    t_update = _temperature_update(params)
    flux_update = _flux_update(params)
    p_update = _pressure_update(params)
    npt = params.npt

    def pt_iterate(T, s):
        Pf, qDx, qDy, qDz = s
        qDx, qDy, qDz = flux_update(T, Pf, qDx, qDy, qDz)
        Pf = p_update(Pf, qDx, qDy, qDz)
        return Pf, qDx, qDy, qDz

    def cadence_block_step(w, lead=0, chunks=None, early_exchange=False):
        """One time step at the w-iterations-per-slab-exchange cadence — the
        ONE definition behind both ``exchange_every=w`` and the ``fused_k``
        branch's XLA fallback, so the fallback's bit-identical-to-cadence
        contract can never drift.  The exchanges are no-ops on dimensions
        without halo activity, so the same body serves 1-device grids.

        ``lead``/``chunks``: the ragged schedule for ``npt % w != 0``
        (`_pt_schedule`) — ``lead`` per-iteration-exchanged XLA iterations,
        then Python-unrolled chunks of ``ki <= w`` iterations, each followed
        by a width-``w`` slab exchange (width ``w`` for EVERY chunk: it
        heals any chunk's stale rind and keeps the exchange geometry
        uniform; the sent planes sit ``o - w >= w >= ki`` from the edge, so
        they are exact)."""
        sched = ([w] * (npt // w)) if chunks is None else list(chunks)

        def block_step(T, Pf, qDx, qDy, qDz):
            s = (Pf, qDx, qDy, qDz)
            for _ in range(lead):
                s = update_halo(*pt_iterate(T, s), coalesce=coalesce)

            # The small ki-iteration body is unrolled inside each group (a
            # nested fori_loop is the measured-slow shape); the group
            # sequence runs through `run_group_schedule` with unroll_limit=1
            # and the all-or-nothing shape — unlike the one-Pallas-call
            # fused groups, each XLA group is a large unrolled body, so any
            # uniform run longer than one group stays a fori_loop to bound
            # HLO size AND to keep the fori fusion barrier that makes this
            # cadence bit-identical to the per-iteration path.
            def group(ki, s):
                for _ in range(ki):
                    s = pt_iterate(T, s)
                if early_exchange:
                    # pipelined=True: the early-dispatch exchange shape
                    # (begin/finish; bit-identical values).
                    from ..ops.halo import (
                        begin_slab_exchange,
                        finish_slab_exchange,
                    )

                    pend = begin_slab_exchange(
                        s, (0, 1, 2), width=w, coalesce=coalesce
                    )
                    return finish_slab_exchange(s, pend)
                return update_halo(*s, width=w, coalesce=coalesce)

            s = run_group_schedule(
                sched, group, s, unroll_limit=1, fori_excess_only=False
            )
            Pf, qDx, qDy, qDz = s
            T = t_update(T, qDx, qDy, qDz)
            T = update_halo(T)
            return T, Pf, qDx, qDy, qDz

        return block_step

    if fused_k:
        import jax

        from ..ops.halo import dim_has_halo_activity, require_deep_halo
        from ..ops.pallas_pt import (
            fused_pt_iterations,
            fused_support_error,
            pad_faces,
            unpad_faces,
        )
        from ..parallel.grid import global_grid
        from ._fused import run_group_schedule, warn_fused_fallback

        gg = global_grid()
        if params.hide_comm:
            raise ValueError(
                "fused_k and hide_comm are mutually exclusive: the fused "
                "kernel's slab exchange is already amortized over k "
                "iterations; overlap scheduling applies to the per-iteration "
                "XLA path."
            )
        if exchange_every not in (1, fused_k):
            raise ValueError(
                f"fused_k={fused_k} already exchanges every fused_k PT "
                f"iterations; exchange_every={exchange_every} conflicts."
            )
        require_deep_halo(fused_k, gg, what="fused_k")
        active = [d for d in range(3) if dim_has_halo_activity(gg, d)]
        w = fused_k
        # Ragged schedule (VERDICT r3 #5: ``w | npt`` made the kernel benefit
        # depend on a numerics parameter — npt=10 admitted only w=2): chunk
        # npt into even kernel chunks of at most w iterations, preceded by
        # one per-iteration-exchanged XLA iteration when npt is odd.
        lead, chunks = _pt_schedule(npt, w)
        th = params.theta_q
        idx, idy, idz = 1.0 / params.dx, 1.0 / params.dy, 1.0 / params.dz
        ralam = params.Ra * params.lam_T
        bp = params.beta_p
        bx, by = fused_tile if fused_tile is not None else (None, None)
        if (bx is None) != (by is None):
            raise ValueError(f"fused_tile={fused_tile}: pass both bx and by, or neither")

        def kernel_iters(ki, T, Pf, qxp, qyp, qzp, z_patches=None, tile=None,
                         **zkw):
            # ``tile``: the pipelined paths pin every chunk to the tile the
            # split gate validated at k=w (a shorter ragged chunk would
            # otherwise re-resolve its own ladder default — a geometry the
            # ring/mid admissibility check never saw).  Serialized paths
            # keep the per-chunk ladder resolution.
            tbx, tby = tile if tile is not None else (bx, by)
            return fused_pt_iterations(
                T, Pf, qxp, qyp, qzp, ki, th, idx, idy, idz, ralam, bp,
                bx=tbx, by=tby, z_patches=z_patches, **zkw,
            )

        if not active:
            if pipelined:
                from ._fused import warn_pipelined_fallback

                warn_pipelined_fallback(
                    None, w,
                    "no halo activity: nothing to overlap", model="porous",
                )

            def fused_block_step(T, Pf, qDx, qDy, qDz):
                # Fluxes stay padded across the whole PT loop (no exchange
                # to serve); the no-op update_halo calls are skipped too.
                for _ in range(lead):
                    Pf, qDx, qDy, qDz = pt_iterate(T, (Pf, qDx, qDy, qDz))
                qxp, qyp, qzp = pad_faces(qDx, qDy, qDz)
                Pf, qxp, qyp, qzp = run_group_schedule(
                    chunks, lambda ki, s: kernel_iters(ki, T, *s),
                    (Pf, qxp, qyp, qzp),
                )
                qDx, qDy, qDz = unpad_faces(qxp, qyp, qzp)
                T = t_update(T, qDx, qDy, qDz)
                return T, Pf, qDx, qDy, qDz

        else:

            def fused_block_step(T, Pf, qDx, qDy, qDz):
                from ..ops.halo import update_halo_padded_faces

                for _ in range(lead):
                    Pf, qDx, qDy, qDz = update_halo(
                        *pt_iterate(T, (Pf, qDx, qDy, qDz)),
                        coalesce=coalesce,
                    )

                def group(ki, s):
                    out = kernel_iters(ki, T, *s)
                    # All four PT fields slab-exchange (the fluxes' rind
                    # relaxation history is stale — see exchange_every) —
                    # directly on the padded layout: one pad/unpad per
                    # whole PT loop instead of one per group.  Width w for
                    # every chunk: heals any chunk's stale rind; sent
                    # planes sit o-w >= w >= ki from the edge, so they are
                    # exact after ki iterations.
                    return update_halo_padded_faces(
                        *out, width=w, coalesce=coalesce
                    )

                Pf, qxp, qyp, qzp = run_group_schedule(
                    chunks, group, (Pf, *pad_faces(qDx, qDy, qDz))
                )
                qDx, qDy, qDz = unpad_faces(qxp, qyp, qzp)
                T = t_update(T, qDx, qDy, qDz)
                T = update_halo(T)
                return T, Pf, qDx, qDy, qDz

            def fused_zpatch_step(T, Pf, qDx, qDy, qDz):
                from ..ops.halo import (
                    apply_z_patches,
                    fix_topface_z_exports,
                    identity_z_patches,
                    ol,
                    update_halo_padded_faces,
                    z_patches_from_exports,
                )

                for _ in range(lead):
                    Pf, qDx, qDy, qDz = update_halo(
                        *pt_iterate(T, (Pf, qDx, qDy, qDz)),
                        coalesce=coalesce,
                    )
                s0 = (Pf, *pad_faces(qDx, qDy, qDz))
                o_z = ol(2, shape=tuple(Pf.shape), gg=gg)
                patches0 = identity_z_patches(*s0, width=w)

                def group_k(ki, carry):
                    s, patches = carry
                    # In-kernel z-slab application + in-kernel export of
                    # the next group's send slabs (round 4); x/y exchange
                    # outside for fields and packed exports alike — see
                    # acoustic3d's fused_zpatch_step.  Patch application
                    # and export both at width w regardless of ki (ragged
                    # schedule: heals the previous chunk's w-deep rind).
                    out = kernel_iters(
                        ki, T, *s, z_patches=patches, z_patch_width=w,
                        z_export=True, z_export_width=w, z_overlap=o_z,
                    )
                    s, exports = out[:4], out[4:]
                    exports = fix_topface_z_exports(exports, *s, width=w)
                    s = update_halo_padded_faces(
                        *s, width=w, dims=(0, 1), coalesce=coalesce
                    )
                    patches = z_patches_from_exports(
                        exports, tuple(s[0].shape), width=w
                    )
                    return s, patches

                s, patches = run_group_schedule(chunks, group_k, (s0, patches0))
                Pf, qxp, qyp, qzp = apply_z_patches(*s, patches, width=w)
                qDx, qDy, qDz = unpad_faces(qxp, qyp, qzp)
                T = t_update(T, qDx, qDy, qDz)
                T = update_halo(T)
                return T, Pf, qDx, qDy, qDz

            def fused_pipelined_block_step(T, Pf, qDx, qDy, qDz):
                # Boundary-first split of `fused_block_step` (z-inactive):
                # ring pass feeds the all-field slab exchange early,
                # interior pass runs across the in-flight collectives.
                from ..ops.halo import (
                    _padded_logicals,
                    begin_slab_exchange,
                    finish_slab_exchange,
                )
                from ._fused import run_pipelined_group_schedule

                for _ in range(lead):
                    Pf, qDx, qDy, qDz = update_halo(
                        *pt_iterate(T, (Pf, qDx, qDy, qDz)),
                        coalesce=coalesce,
                    )
                sel, _, ptile = _split(tuple(Pf.shape), Pf.dtype.itemsize, False)
                s0 = (Pf, *pad_faces(qDx, qDy, qDz))
                logicals = _padded_logicals(*s0)

                def boundary(ki, s):
                    out_b = kernel_iters(ki, T, *s, tile=ptile, tile_sel="ring" + sel)
                    pend = begin_slab_exchange(
                        out_b, (0, 1), width=w, logicals=logicals,
                        coalesce=coalesce,
                    )
                    return out_b, pend

                def interior(ki, s, out_b, pend):
                    out = kernel_iters(
                        ki, T, *s, tile=ptile, tile_sel="mid" + sel,
                        carry_in=out_b,
                    )
                    return finish_slab_exchange(out, pend, logicals=logicals)

                # Same loop shaping as the serialized Pallas cadence (the
                # unrolled-group pipelining win; only the XLA cadence needs
                # the all-or-nothing fori shape).
                Pf, qxp, qyp, qzp = run_pipelined_group_schedule(
                    chunks, boundary, interior, s0
                )
                qDx, qDy, qDz = unpad_faces(qxp, qyp, qzp)
                T = t_update(T, qDx, qDy, qDz)
                T = update_halo(T)
                return T, Pf, qDx, qDy, qDz

            def fused_zpatch_pipelined_step(T, Pf, qDx, qDy, qDz):
                # Boundary-first split of `fused_zpatch_step`: the PT
                # fields' x/y slabs exchange early off the ring pass; the
                # packed z exports complete with the interior pass.
                from ..ops.halo import (
                    _padded_logicals,
                    apply_z_patches,
                    begin_slab_exchange,
                    finish_slab_exchange,
                    fix_topface_z_exports,
                    identity_z_patches,
                    ol,
                    z_patches_from_exports,
                )
                from ._fused import run_pipelined_group_schedule

                for _ in range(lead):
                    Pf, qDx, qDy, qDz = update_halo(
                        *pt_iterate(T, (Pf, qDx, qDy, qDz)),
                        coalesce=coalesce,
                    )
                s0 = (Pf, *pad_faces(qDx, qDy, qDz))
                o_z = ol(2, shape=tuple(Pf.shape), gg=gg)
                patches0 = identity_z_patches(*s0, width=w)
                sel, _, ptile = _split(tuple(Pf.shape), Pf.dtype.itemsize, True)
                logicals = _padded_logicals(*s0)

                def boundary(ki, carry):
                    s, patches = carry
                    out_b = kernel_iters(
                        ki, T, *s, z_patches=patches, z_patch_width=w,
                        z_export=True, z_export_width=w, z_overlap=o_z,
                        tile=ptile, tile_sel="ring" + sel,
                    )
                    pend = begin_slab_exchange(
                        out_b[:4], (0, 1), width=w, logicals=logicals,
                        coalesce=coalesce,
                    )
                    return out_b, pend

                def interior(ki, carry, out_b, pend):
                    s, patches = carry
                    out = kernel_iters(
                        ki, T, *s, z_patches=patches, z_patch_width=w,
                        z_export=True, z_export_width=w, z_overlap=o_z,
                        tile=ptile, tile_sel="mid" + sel, carry_in=out_b,
                    )
                    s2, exports = out[:4], out[4:]
                    exports = fix_topface_z_exports(exports, *s2, width=w)
                    s2 = finish_slab_exchange(s2, pend, logicals=logicals)
                    patches2 = z_patches_from_exports(
                        exports, tuple(s2[0].shape), width=w
                    )
                    return s2, patches2

                # Serialized-cadence loop shaping (see above).
                s, patches = run_pipelined_group_schedule(
                    chunks, boundary, interior, (s0, patches0)
                )
                Pf, qxp, qyp, qzp = apply_z_patches(*s, patches, width=w)
                qDx, qDy, qDz = unpad_faces(qxp, qyp, qzp)
                T = t_update(T, qDx, qDy, qDz)
                T = update_halo(T)
                return T, Pf, qDx, qDy, qDz

        xla_block_step = cadence_block_step(w, lead, chunks)
        z_active = dim_has_halo_activity(gg, 2)
        from ._fused import fused_with_xla_grad, resolve_pipelined, split_selector

        active01 = tuple(d for d in (0, 1) if d in active)

        def _split(shape, itemsize, zpatch):
            """(ring/mid selector suffix, admissibility error) — the shared
            trace-time gate (`split_selector`; stagger=1 for the flux
            fields).  The ragged schedule keeps patch/export widths at
            ``w`` for every chunk, so the split is gated at the worst case
            ``ki = w`` too."""
            from ..ops import pallas_pt

            return split_selector(
                pallas_pt, shape, w, w, itemsize, bx, by,
                active01, zpatch, stagger=1, gg=gg,
            )

        def block_step(T, Pf, qDx, qDy, qDz):
            # Shapes are only known at trace time, so the kernel-vs-fallback
            # choice happens there (the reference's runtime-path-selection
            # move, `/root/reference/src/update_halo.jl:755-784`).  Kernel
            # paths are wrapped with `fused_with_xla_grad`: primal runs the
            # Pallas chunk, jax.grad differentiates the XLA cadence.
            shape = tuple(Pf.shape)
            if (
                chunks
                and active
                and z_active
                and fused_support_error(
                    shape, w, Pf.dtype.itemsize, bx, by, zpatch=True
                ) is None
            ):
                # In-kernel z-slab application (see docs/performance.md).
                body = fused_zpatch_step
                if resolve_pipelined(
                    pipelined, _split(shape, Pf.dtype.itemsize, True)[1],
                    shape, w, "porous",
                ):
                    body = fused_zpatch_pipelined_step
                return fused_with_xla_grad(body, xla_block_step)(
                    T, Pf, qDx, qDy, qDz
                )
            err = fused_support_error(shape, w, Pf.dtype.itemsize, bx, by)
            if err is None and not chunks:
                err = f"npt={npt} leaves no even kernel chunk"
            if err is None:
                body = fused_block_step
                if active and not z_active and resolve_pipelined(
                    pipelined, _split(shape, Pf.dtype.itemsize, False)[1],
                    shape, w, "porous",
                ):
                    body = fused_pipelined_block_step
                return fused_with_xla_grad(body, xla_block_step)(
                    T, Pf, qDx, qDy, qDz
                )
            warn_fused_fallback(tuple(Pf.shape), w, err, model="porous")
            if pipelined:
                return cadence_block_step(w, lead, chunks, early_exchange=True)(
                    T, Pf, qDx, qDy, qDz
                )
            return xla_block_step(T, Pf, qDx, qDy, qDz)

    elif exchange_every < 1:
        raise ValueError(f"exchange_every must be >= 1 (got {exchange_every})")
    elif exchange_every > 1:
        from ..ops.halo import require_deep_halo

        if params.hide_comm:
            raise ValueError(
                "exchange_every and hide_comm are mutually exclusive: overlap "
                "scheduling hides the per-iteration exchange; a slab cadence "
                "replaces it."
            )
        require_deep_halo(exchange_every)
        block_step = cadence_block_step(
            exchange_every, *_pt_schedule(npt, exchange_every, even=False),
            early_exchange=bool(pipelined),
        )

    else:
        if pipelined:
            raise ValueError(
                "pipelined applies to the group cadences (fused_k or "
                "exchange_every > 1); the per-iteration path has no group "
                "schedule."
            )
        block_step = _build_block_step(params, coalesce=coalesce)

    # The Python unroll is only cheap for production-sized chunks; past this
    # the trace/HLO grows linearly (each step carries npt PT iterations) and
    # compile time explodes long before any dispatch saving pays back.
    # Callers wanting more steps per sync should call the chunk repeatedly.
    if nsteps > 64:
        raise ValueError(
            f"nsteps={nsteps} would unroll {nsteps} whole time steps into one "
            "program (the outer loop is unrolled by measurement — a nested "
            "fori_loop costs ~35% on v5e); keep chunks <= 64 and call the "
            "step function repeatedly instead"
        )

    def multi(*s):
        for _ in range(nsteps):  # unrolled: see the loop-structure note above
            s = block_step(*s)
        return s

    donate_argnums = tuple(range(5)) if donate else ()
    if batch:
        # Ensemble cadence: vmap over the leading member axis — every path
        # (PT fori_loop, slab exchanges, fused PT kernels via the
        # pallas_call batching rule) batches with a B-invariant collective
        # budget (see `models.diffusion3d.make_multi_step`).
        from ._batched import batched_stencil

        return batched_stencil(multi, 5, donate_argnums=donate_argnums)
    return stencil(multi, donate_argnums=donate_argnums)


def run(
    nt: int,
    nx: int = 32,
    ny: int = 32,
    nz: int = 32,
    *,
    finalize: bool = True,
    guard_every: int | None = None,
    guard_policy: str | None = None,
    checkpoint_every: int | None = None,
    checkpoint_dir: str | None = None,
    checkpoint_keep: int | None = None,
    integrity_every: int | None = None,
    **kw,
):
    """End-to-end run; returns the final global-block temperature field.

    Resilience hooks as in `models.diffusion3d.run` (``guard_every`` /
    ``guard_policy`` / ``checkpoint_every`` / ``checkpoint_dir`` /
    ``checkpoint_keep`` / ``integrity_every``; resume is
    topology-elastic)."""
    import jax

    from ..parallel.grid import global_grid, grid_is_initialized
    from ..utils.resilience import RunGuard, guarded_time_loop

    caller_owns_grid = grid_is_initialized()  # init_grid=False with a live grid
    try:
        from ..utils import liveplane as _liveplane
        from ..utils import tracing as _tracing

        # Live plane up BEFORE the long bring-up/compile phase (no-op
        # unless IGG_METRICS_PORT is set; docs/observability.md).
        _liveplane.ensure_server()
        with _tracing.trace_span("igg.run.setup", model="porous_convection3d"):
            state, params = setup(nx, ny, nz, **kw)
            step = make_step(params)
        guard = RunGuard(
            guard_every=guard_every,
            policy=guard_policy,
            checkpoint_every=checkpoint_every,
            checkpoint_dir=checkpoint_dir,
            checkpoint_keep=checkpoint_keep,
            integrity_every=integrity_every,
            names=("T", "Pf", "qDx", "qDy", "qDz"),
        )
        sync_every_step = global_grid().mesh.devices.flat[0].platform == "cpu"
        # Telemetry bytes model: the whole evolving state (T, Pf, qDx, qDy,
        # qDz) streams per time step; the inner PT iterations move more on
        # top, so the recorded T_eff stays a lower bound (docs convention).
        from ..utils.telemetry import teff_bytes

        state = guarded_time_loop(
            step, state, nt, guard=guard, sync_every_step=sync_every_step,
            model="porous_convection3d", bytes_per_step=teff_bytes(state),
        )
        T = jax.block_until_ready(state[0])
    except BaseException:
        # A failed run must not poison the next init_global_grid in this
        # process (the singleton would report "already initialized") — but
        # never tear down a grid the caller set up themselves.
        if not caller_owns_grid and grid_is_initialized():
            finalize_global_grid()
        raise
    if finalize:
        finalize_global_grid()
    return T


def temperature(state):
    return state[0]


def _pt_residual_block(params: Params):
    """Per-block PT defect: ``max |div(qD)|`` over interior cells — the
    pressure equation's residual, the criterion the PT relaxation drives to
    zero.  Interior cells only: the outermost ring evolves under frozen
    boundary faces (physical walls / halo planes) and its defect is not
    driven by the local relaxation."""
    import jax.numpy as jnp

    dx, dy, dz = params.dx, params.dy, params.dz

    def residual(T, Pf, qDx, qDy, qDz):
        div = (
            jnp.diff(qDx, axis=0) / dx
            + jnp.diff(qDy, axis=1) / dy
            + jnp.diff(qDz, axis=2) / dz
        )
        return jnp.max(jnp.abs(_inn(div)))

    return residual


def make_batched_residual(params: Params):
    """Jitted per-member PT residual of a BATCHED state: ``(B,)`` array.

    The serving loop's convergence criterion (ISSUE 8): member ``b``'s
    residual is the global max of its block defects (`_pt_residual_block`
    + `lax.pmax` over the mesh), replicated on every process so all ranks
    mask the same members.  One cheap fused reduction — no collective
    beyond the final scalar pmax per member batch.
    """
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..parallel.grid import global_grid
    from ..parallel.topology import AXIS_NAMES
    from ..utils.compat import shard_map
    from ._batched import _batched_spec

    block = _pt_residual_block(params)
    gg = global_grid()
    if gg.nprocs == 1 and not gg.force_spmd:
        return jax.jit(lambda *s: jax.vmap(block)(*s))

    def body(*state):
        return lax.pmax(jax.vmap(block)(*state), AXIS_NAMES)

    mapped = shard_map(
        body,
        mesh=gg.mesh,
        in_specs=(_batched_spec(4),) * 5,
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(mapped)
