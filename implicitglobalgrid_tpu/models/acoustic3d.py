"""3-D acoustic wave on a staggered grid with comm/compute overlap.

BASELINE config 3: velocity–pressure acoustic FDTD, the canonical *staggered*
application of the reference's grid machinery (staggered fields of shape
``n+1`` on one topology are the reference's test-pinned feature,
`/root/reference/test/test_update_halo.jl:828-937`; the solver structure
follows the acoustic miniapp of the reference's sister package
ParallelStencil, referenced at `/root/reference/README.md:10`).

Grid layout (one cell = one pressure point):

* ``P``  at cell centers, local shape ``(nx,   ny,   nz)``
* ``Vx`` on x-faces,      local shape ``(nx+1, ny,   nz)``
* ``Vy`` on y-faces,      local shape ``(nx,   ny+1, nz)``
* ``Vz`` on z-faces,      local shape ``(nx,   ny,   nz+1)``

Update (explicit leapfrog):

    V  -= dt/rho * grad(P)      (interior face points)
    P  -= dt*K   * div(V)       (all cell centers)

On the per-step path only the velocity fields exchange halos: ``P`` is
recomputed everywhere from post-exchange velocities, so its boundary planes
are always fresh — one 3-field `update_halo` per step instead of four.  (The
``exchange_every`` slab cadence in `make_multi_step` is the exception: there
``P``'s rind goes stale between exchanges and all FOUR fields are
slab-exchanged.)  With ``hide_comm=True`` the exchange of the velocity slabs
overlaps the interior velocity update (`hide_communication`), the
reference's `@hide_communication` capability.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from .. import (
    coord_fields,
    finalize_global_grid,
    init_global_grid,
    stencil,
    update_halo,
    zeros,
)
from ..ops.overlap import hide_communication


@dataclasses.dataclass(frozen=True)
class Params:
    K: float = 1.0  # bulk modulus
    rho: float = 1.0  # density
    lx: float = 10.0
    ly: float = 10.0
    lz: float = 10.0
    dx: float = 0.0
    dy: float = 0.0
    dz: float = 0.0
    dt: float = 0.0
    dtype: Any = None
    hide_comm: bool = False


def _inn(A):
    return A[1:-1, 1:-1, 1:-1]


def setup(
    nx: int = 64,
    ny: int = 64,
    nz: int = 64,
    *,
    K: float = 1.0,
    rho: float = 1.0,
    lx: float = 10.0,
    ly: float = 10.0,
    lz: float = 10.0,
    dtype=None,
    hide_comm: bool = False,
    init_grid: bool = True,
    ic_scale: float = 1.0,
    **grid_kwargs,
):
    """Initialize grid + fields; a Gaussian pressure pulse at the domain center.

    Returns ``(state, params)`` with ``state = (P, Vx, Vy, Vz)``.
    ``ic_scale`` scales the initial pressure pulse (the ensemble lever,
    `models._batched.batched_setup`).
    """
    import jax
    import jax.numpy as jnp

    from ..utils import tools

    if init_grid:
        init_global_grid(nx, ny, nz, **grid_kwargs)
    if dtype is None:
        dtype = jax.dtypes.canonicalize_dtype(float)
    dx = lx / (tools.nx_g() - 1)
    dy = ly / (tools.ny_g() - 1)
    dz = lz / (tools.nz_g() - 1)
    c = (K / rho) ** 0.5
    dt = min(dx, dy, dz) / c / 2.0  # CFL (3-D bound is 1/sqrt(3); 1/2 for margin)
    params = Params(
        K=K, rho=rho, lx=lx, ly=ly, lz=lz, dx=dx, dy=dy, dz=dz, dt=dt,
        dtype=dtype, hide_comm=hide_comm,
    )

    P = zeros((nx, ny, nz), dtype)
    X, Y, Z = coord_fields(P, (dx, dy, dz), dtype=dtype)

    @stencil
    def init_ic(X, Y, Z):
        p0 = 100 * jnp.exp(
            -(((X - lx / 2) / 1.0) ** 2)
            - ((Y - ly / 2) / 1.0) ** 2
            - ((Z - lz / 2) / 1.0) ** 2
        )
        return (ic_scale * p0).astype(dtype)

    P = init_ic(X, Y, Z)
    Vx = zeros((nx + 1, ny, nz), dtype)
    Vy = zeros((nx, ny + 1, nz), dtype)
    Vz = zeros((nx, ny, nz + 1), dtype)
    return (P, Vx, Vy, Vz), params


def _velocity_update(params: Params):
    """Pure per-block velocity update (no exchange): interior face points only
    (padded-delta form — boundary faces frozen, the rigid-wall condition)."""
    import jax.numpy as jnp

    a = params.dt / params.rho

    def update(P, Vx, Vy, Vz):
        dVx = -(a / params.dx) * jnp.diff(P[:, 1:-1, 1:-1], axis=0)  # (nx-1,ny-2,nz-2)
        dVy = -(a / params.dy) * jnp.diff(P[1:-1, :, 1:-1], axis=1)
        dVz = -(a / params.dz) * jnp.diff(P[1:-1, 1:-1, :], axis=2)
        Vx = Vx + jnp.pad(dVx, 1)  # interior of (nx+1,ny,nz)
        Vy = Vy + jnp.pad(dVy, 1)
        Vz = Vz + jnp.pad(dVz, 1)
        return Vx, Vy, Vz

    return update


def _pressure_update(params: Params):
    """Pure per-block pressure update: all centers, from fresh velocities."""
    import jax.numpy as jnp

    b = params.dt * params.K

    def update(P, Vx, Vy, Vz):
        div = (
            jnp.diff(Vx, axis=0) / params.dx
            + jnp.diff(Vy, axis=1) / params.dy
            + jnp.diff(Vz, axis=2) / params.dz
        )
        return P - b * div

    return update


def make_step(params: Params, *, donate: bool = True, batch: bool = False):
    """One fused SPMD leapfrog step: ``(P, Vx, Vy, Vz) -> (P, Vx, Vy, Vz)``.

    ``batch=True``: the ensemble step over ``(B, ...)`` batched fields —
    `jax.vmap` of the same per-block step; bit-identical per member, one
    collective pair per exchanged dimension at any B (see
    `models.diffusion3d.make_step`).
    """
    v_update = _velocity_update(params)
    p_update = _pressure_update(params)

    if params.hide_comm:
        overlapped = hide_communication(v_update, radius=1)

        def block_step(P, Vx, Vy, Vz):
            Vx, Vy, Vz = overlapped(P, Vx, Vy, Vz)
            P = p_update(P, Vx, Vy, Vz)
            return P, Vx, Vy, Vz

    else:

        def block_step(P, Vx, Vy, Vz):
            Vx, Vy, Vz = v_update(P, Vx, Vy, Vz)
            Vx, Vy, Vz = update_halo(Vx, Vy, Vz)
            P = p_update(P, Vx, Vy, Vz)
            return P, Vx, Vy, Vz

    donate_argnums = tuple(range(4)) if donate else ()
    if batch:
        from ._batched import batched_stencil

        return batched_stencil(block_step, 4, donate_argnums=donate_argnums)
    return stencil(block_step, donate_argnums=donate_argnums)


def pipelined_support_error(shape, k, itemsize: int = 4, bx=None, by=None,
                            gg=None) -> str | None:
    """Why the pipelined group schedule cannot split this config, or None
    (benchmark provenance; see `models._fused.pipelined_support_error`)."""
    from ..ops import pallas_leapfrog
    from ._fused import pipelined_support_error as _generic

    # stagger=1: the face fields' shape-aware ol is one deeper than the
    # grid overlap, and their send planes must fit the ring tiles too.
    return _generic(pallas_leapfrog, shape, k, itemsize, bx, by, gg, stagger=1)


def _tune_state(params: Params):
    """Synthetic ones-filled state for autotuner candidate measurement
    (`tuning.search`): linear updates on ones stay finite, and the fields
    are real global-block sharded arrays (staggered ``n+1`` faces), so a
    measured candidate runs the production SPMD program."""
    from .. import ones
    from ..parallel.grid import global_grid

    nx, ny, nz = global_grid().nxyz
    dt = params.dtype
    return (
        ones((nx, ny, nz), dt), ones((nx + 1, ny, nz), dt),
        ones((nx, ny + 1, nz), dt), ones((nx, ny, nz + 1), dt),
    )


def make_multi_step(
    params: Params, nsteps: int, *, donate: bool = True, exchange_every: int = 1,
    fused_k: int | None = None, fused_tile: tuple[int, int] | None = None,
    pipelined: bool | None = None, batch: bool = False,
    coalesce: bool | None = None, autotune: bool | None = None,
):
    """``nsteps`` leapfrog steps per call in one XLA program (`lax.fori_loop`).

    ``exchange_every=w``: on a deep-halo grid (``overlap >= 2w``) run ``w``
    leapfrog steps between exchanges and then exchange width-``w`` slabs of
    ALL four fields in one collective call — unlike the per-step path, the
    incrementally-updated ``P`` must be exchanged too (its stale rind is
    never recomputed from fresh velocities, so the slab replaces it with the
    neighbor's still-exact planes).  One collective per ``w`` steps; states
    at group boundaries identical up to compiler fusion rounding (bitwise on
    the CPU mesh; few f32 ULPs on TPU).

    ``fused_k``: advance ``fused_k`` leapfrog steps per HBM pass with the
    temporally-blocked staggered Pallas kernel (`ops/pallas_leapfrog.py`) —
    the staggered sibling of the diffusion model's ``fused_k``, made possible
    by the even-extent padded face layout (`pad_faces`).  On a grid with no
    halo activity the kernel runs alone (pad once per chunk).  On a
    communicating grid every dimension with halo activity needs
    ``overlap >= 2*fused_k``; the chunk then alternates ``fused_k`` kernel
    steps with ONE width-``fused_k`` slab exchange of all four fields (the
    same all-field slab as ``exchange_every`` — the kernel's k-deep
    contaminated rind is exactly the slab the exchange refreshes).  Local
    blocks the kernel envelope rejects warn once and run the XLA path at the
    same cadence (`fused_support_error` is the single source of truth).
    Requires ``nsteps % fused_k == 0``.

    ``pipelined`` (default auto): boundary-first pipelined group schedule —
    ring/interior split launches with the all-field slab exchange
    dispatched off the ring pass, exactly as on
    `models.diffusion3d.make_multi_step` (bit-identical to the serialized
    schedule; auto when admissible, see `pipelined_support_error`).

    ``batch``: vmap the whole cadence over a leading ensemble axis — every
    path batches through the same vmap with a B-invariant collective
    budget (see `models.diffusion3d.make_multi_step`).

    ``coalesce`` (None = ``IGG_COALESCE``, auto): passed through to the
    cadence's all-field exchanges (`ops.halo`; bit-identical either way —
    the A/B-measurement knob, tunable per config).  ``autotune`` (None =
    ``IGG_AUTOTUNE``, default off): substitute this point's cached winner
    schedule into the kwargs above (`implicitglobalgrid_tpu.tuning`; pure
    substitution — explicit kwargs always win, results bit-identical).
    """
    from jax import lax

    from ..tuning.search import maybe_autotune

    fused_k, fused_tile, exchange_every, pipelined, coalesce = maybe_autotune(
        "acoustic3d", params, nsteps, autotune, batch=batch,
        fused_k=fused_k, fused_tile=fused_tile, exchange_every=exchange_every,
        pipelined=pipelined, coalesce=coalesce,
    )

    def _wrap(block_fn):
        dn = tuple(range(4)) if donate else ()
        if batch:
            from ._batched import batched_stencil

            return batched_stencil(block_fn, 4, donate_argnums=dn)
        return stencil(block_fn, donate_argnums=dn)

    v_update = _velocity_update(params)
    p_update = _pressure_update(params)

    if fused_k:
        import jax

        from ..ops.halo import dim_has_halo_activity, require_deep_halo
        from ..ops.pallas_leapfrog import (
            fused_leapfrog_steps,
            fused_support_error,
            pad_faces,
            unpad_faces,
        )
        from ..parallel.grid import global_grid
        from ._fused import warn_fused_fallback

        gg = global_grid()
        if params.hide_comm:
            raise ValueError(
                "fused_k and hide_comm are mutually exclusive: the fused "
                "kernel's slab exchange is already amortized over k steps; "
                "overlap scheduling applies to the per-step XLA path."
            )
        if nsteps % fused_k != 0:
            raise ValueError(f"nsteps={nsteps} must be a multiple of fused_k={fused_k}")
        if exchange_every not in (1, fused_k):
            raise ValueError(
                f"fused_k={fused_k} already exchanges every fused_k steps; "
                f"exchange_every={exchange_every} conflicts."
            )
        require_deep_halo(fused_k, gg, what="fused_k")
        active = [d for d in range(3) if dim_has_halo_activity(gg, d)]
        cax = params.dt / params.rho / params.dx
        cay = params.dt / params.rho / params.dy
        caz = params.dt / params.rho / params.dz
        b = params.dt * params.K
        idx, idy, idz = 1.0 / params.dx, 1.0 / params.dy, 1.0 / params.dz
        bx, by = fused_tile if fused_tile is not None else (None, None)
        if (bx is None) != (by is None):
            raise ValueError(f"fused_tile={fused_tile}: pass both bx and by, or neither")

        def kernel_steps(P, Vxp, Vyp, Vzp, z_patches=None, **zkw):
            return fused_leapfrog_steps(
                P, Vxp, Vyp, Vzp, fused_k, cax, cay, caz, b, idx, idy, idz,
                bx=bx, by=by, z_patches=z_patches, **zkw,
            )

        def xla_step(s):
            P, Vx, Vy, Vz = s
            Vx, Vy, Vz = v_update(P, Vx, Vy, Vz)
            return p_update(P, Vx, Vy, Vz), Vx, Vy, Vz

        z_active = dim_has_halo_activity(gg, 2)
        from ._fused import (
            fused_with_xla_grad,
            resolve_pipelined,
            run_group_schedule,
            split_selector,
        )

        groups = [fused_k] * (nsteps // fused_k)
        active01 = tuple(d for d in (0, 1) if d in active)

        def _split(shape, itemsize, zpatch):
            """(ring/mid selector suffix, admissibility error) for the
            resolved tile — the shared trace-time gate (`split_selector`;
            stagger=1: the face fields' ol is one deeper)."""
            from ..ops import pallas_leapfrog

            return split_selector(
                pallas_leapfrog, shape, fused_k, fused_k, itemsize, bx, by,
                active01, zpatch, stagger=1, gg=gg,
            )

        def fused_or_fallback(P, Vx, Vy, Vz, fused_body, xla_body,
                              zpatch_body=None, pipelined_bodies=None):
            # Kernel paths wrapped with `fused_with_xla_grad`: primal runs
            # the Pallas chunk, jax.grad differentiates the XLA cadence.
            shape = tuple(P.shape)
            pb = pipelined_bodies or {}
            if (
                zpatch_body is not None
                and z_active
                and fused_support_error(
                    shape, fused_k, P.dtype.itemsize, bx, by, zpatch=True
                ) is None
            ):
                # The in-kernel z-slab application: avoids the whole-array
                # relayouts a z-dim DUS costs at the kernel boundary (the
                # exchanged-dimension anisotropy, docs/performance.md).
                body = zpatch_body
                if "zpatch" in pb and resolve_pipelined(
                    pipelined, _split(shape, P.dtype.itemsize, True)[1],
                    shape, fused_k, "acoustic",
                ):
                    body = pb["zpatch"]
                return fused_with_xla_grad(body, xla_body)(P, Vx, Vy, Vz)
            err = fused_support_error(shape, fused_k, P.dtype.itemsize, bx, by)
            if err is None:
                body = fused_body
                if "plain" in pb and not z_active and resolve_pipelined(
                    pipelined, _split(shape, P.dtype.itemsize, False)[1],
                    shape, fused_k, "acoustic",
                ):
                    body = pb["plain"]
                return fused_with_xla_grad(body, xla_body)(P, Vx, Vy, Vz)
            warn_fused_fallback(shape, fused_k, err, model="acoustic")
            if pipelined and "xla" in pb:
                return pb["xla"](P, Vx, Vy, Vz)
            return xla_body(P, Vx, Vy, Vz)

        if not active:
            if pipelined:
                from ._fused import warn_pipelined_fallback

                warn_pipelined_fallback(
                    None, fused_k,
                    "no halo activity: nothing to overlap", model="acoustic",
                )

            def fused_chunk(P, Vx, Vy, Vz):
                # Pad once per chunk; the kernel keeps the padded layout
                # across all groups (no exchange to serve).
                P, Vxp, Vyp, Vzp = run_group_schedule(
                    groups, lambda ki, s: kernel_steps(*s),
                    (P, *pad_faces(Vx, Vy, Vz)),
                )
                return (P, *unpad_faces(Vxp, Vyp, Vzp))

            def xla_chunk(P, Vx, Vy, Vz):
                return lax.fori_loop(
                    0, nsteps, lambda i, s: xla_step(s), (P, Vx, Vy, Vz)
                )

            # No halo activity = no collectives: plain jit on the grid's
            # single device (same rationale as the diffusion fused path).
            body = lambda *s: fused_or_fallback(*s, fused_chunk, xla_chunk)
            if batch:
                body = jax.vmap(body)
            return jax.jit(
                body, donate_argnums=tuple(range(4)) if donate else ()
            )

        def fused_block_step(P, Vx, Vy, Vz):
            from ..ops.halo import update_halo_padded_faces

            def group(ki, s):
                s = kernel_steps(*s)
                # One all-field slab exchange licenses the next fused_k
                # steps (see the exchange_every docstring for why P's slab
                # must ride along) — directly on the padded layout, so the
                # chunk pays ONE pad/unpad instead of one per group.
                return update_halo_padded_faces(
                    *s, width=fused_k, coalesce=coalesce
                )

            P, Vxp, Vyp, Vzp = run_group_schedule(
                groups, group, (P, *pad_faces(Vx, Vy, Vz))
            )
            return (P, *unpad_faces(Vxp, Vyp, Vzp))

        def fused_zpatch_step(P, Vx, Vy, Vz):
            from ..ops.halo import (
                apply_z_patches,
                fix_topface_z_exports,
                identity_z_patches,
                ol,
                update_halo_padded_faces,
                z_patches_from_exports,
            )

            s0 = (P, *pad_faces(Vx, Vy, Vz))
            o_z = ol(2, shape=tuple(P.shape), gg=gg)
            # Chunk entry has fresh halos, so the first group's z patches
            # re-write the planes already in place.
            patches0 = identity_z_patches(*s0, width=fused_k)

            def group(ki, carry):
                s, patches = carry
                # The kernel applies the z patches tile-by-tile in VMEM AND
                # exports the next group's send slabs (round 4: extraction
                # outside paid whole-array relayouts per group); x/y slabs
                # exchange outside for the fields and the packed exports
                # alike (sequential-dimension corner semantics), then the z
                # communication runs on the packed arrays alone.
                out = kernel_steps(
                    *s, z_patches=patches, z_export=True, z_overlap=o_z
                )
                s, exports = out[:4], out[4:]
                exports = fix_topface_z_exports(exports, *s, width=fused_k)
                s = update_halo_padded_faces(
                    *s, width=fused_k, dims=(0, 1), coalesce=coalesce
                )
                patches = z_patches_from_exports(
                    exports, tuple(s[0].shape), width=fused_k
                )
                return s, patches

            s, patches = run_group_schedule(groups, group, (s0, patches0))
            # One whole-array application restores the chunk-boundary
            # fresh-halo invariant (amortized over the whole chunk).
            P, Vxp, Vyp, Vzp = apply_z_patches(*s, patches, width=fused_k)
            return (P, *unpad_faces(Vxp, Vyp, Vzp))

        def fused_pipelined_block_step(P, Vx, Vy, Vz):
            # Boundary-first split of `fused_block_step` (z-inactive):
            # ring pass feeds the all-field slab exchange early, interior
            # pass runs across the in-flight collectives.
            from ..ops.halo import (
                _padded_logicals,
                begin_slab_exchange,
                finish_slab_exchange,
            )
            from ._fused import run_pipelined_group_schedule

            sel, _, _ = _split(tuple(P.shape), P.dtype.itemsize, False)
            s0 = (P, *pad_faces(Vx, Vy, Vz))
            logicals = _padded_logicals(*s0)

            def boundary(ki, s):
                out_b = kernel_steps(*s, tile_sel="ring" + sel)
                pend = begin_slab_exchange(
                    out_b, (0, 1), width=fused_k, logicals=logicals,
                    coalesce=coalesce,
                )
                return out_b, pend

            def interior(ki, s, out_b, pend):
                out = kernel_steps(*s, tile_sel="mid" + sel, carry_in=out_b)
                return finish_slab_exchange(out, pend, logicals=logicals)

            P, Vxp, Vyp, Vzp = run_pipelined_group_schedule(
                groups, boundary, interior, s0
            )
            return (P, *unpad_faces(Vxp, Vyp, Vzp))

        def fused_zpatch_pipelined_step(P, Vx, Vy, Vz):
            # Boundary-first split of `fused_zpatch_step`: the four fields'
            # x/y slabs exchange early off the ring pass; the packed z
            # exports (which every tile feeds) complete with the interior
            # pass, and their thin communication stays on the group's
            # serialized tail.
            from ..ops.halo import (
                _padded_logicals,
                apply_z_patches,
                begin_slab_exchange,
                finish_slab_exchange,
                fix_topface_z_exports,
                identity_z_patches,
                ol,
                z_patches_from_exports,
            )
            from ._fused import run_pipelined_group_schedule

            s0 = (P, *pad_faces(Vx, Vy, Vz))
            o_z = ol(2, shape=tuple(P.shape), gg=gg)
            patches0 = identity_z_patches(*s0, width=fused_k)
            sel, _, _ = _split(tuple(P.shape), P.dtype.itemsize, True)
            logicals = _padded_logicals(*s0)

            def boundary(ki, carry):
                s, patches = carry
                out_b = kernel_steps(
                    *s, z_patches=patches, z_export=True, z_overlap=o_z,
                    tile_sel="ring" + sel,
                )
                pend = begin_slab_exchange(
                    out_b[:4], (0, 1), width=fused_k, logicals=logicals,
                    coalesce=coalesce,
                )
                return out_b, pend

            def interior(ki, carry, out_b, pend):
                s, patches = carry
                out = kernel_steps(
                    *s, z_patches=patches, z_export=True, z_overlap=o_z,
                    tile_sel="mid" + sel, carry_in=out_b,
                )
                s2, exports = out[:4], out[4:]
                # Top-face fix-up reads the PRE-exchange outputs, exactly
                # like the serialized cadence's ordering.
                exports = fix_topface_z_exports(exports, *s2, width=fused_k)
                s2 = finish_slab_exchange(s2, pend, logicals=logicals)
                patches2 = z_patches_from_exports(
                    exports, tuple(s2[0].shape), width=fused_k
                )
                return s2, patches2

            s, patches = run_pipelined_group_schedule(
                groups, boundary, interior, (s0, patches0)
            )
            P, Vxp, Vyp, Vzp = apply_z_patches(*s, patches, width=fused_k)
            return (P, *unpad_faces(Vxp, Vyp, Vzp))

        def xla_cadence_step(P, Vx, Vy, Vz):
            def group(i, s):
                s = lax.fori_loop(0, fused_k, lambda j, s: xla_step(s), s)
                return update_halo(*s, width=fused_k, coalesce=coalesce)

            return lax.fori_loop(0, nsteps // fused_k, group, (P, Vx, Vy, Vz))

        def xla_pipelined_cadence_step(P, Vx, Vy, Vz):
            # The XLA fallback with the early-dispatch exchange shape
            # (begin/finish; bit-identical values) — only pipelined=True
            # selects it (no tile split to ride).
            from ..ops.halo import begin_slab_exchange, finish_slab_exchange

            def group(i, s):
                s = lax.fori_loop(0, fused_k, lambda j, s: xla_step(s), s)
                pend = begin_slab_exchange(
                    s, (0, 1, 2), width=fused_k, coalesce=coalesce
                )
                return finish_slab_exchange(s, pend)

            return lax.fori_loop(0, nsteps // fused_k, group, (P, Vx, Vy, Vz))

        return _wrap(
            lambda *s: fused_or_fallback(
                *s, fused_block_step, xla_cadence_step, fused_zpatch_step,
                pipelined_bodies={
                    "plain": fused_pipelined_block_step,
                    "zpatch": fused_zpatch_pipelined_step,
                    "xla": xla_pipelined_cadence_step,
                },
            )
        )

    if exchange_every < 1:
        raise ValueError(f"exchange_every must be >= 1 (got {exchange_every})")
    if exchange_every > 1:
        from ..ops.halo import require_deep_halo

        if params.hide_comm:
            raise ValueError(
                "exchange_every and hide_comm are mutually exclusive: overlap "
                "scheduling hides the per-step exchange; a slab cadence "
                "replaces it."
            )
        if nsteps % exchange_every != 0:
            raise ValueError(
                f"nsteps={nsteps} must be a multiple of exchange_every={exchange_every}"
            )
        require_deep_halo(exchange_every)
        w = exchange_every

        def block_step(P, Vx, Vy, Vz):
            def group(i, s):
                def body(j, s):
                    P, Vx, Vy, Vz = s
                    Vx, Vy, Vz = v_update(P, Vx, Vy, Vz)
                    P = p_update(P, Vx, Vy, Vz)
                    return (P, Vx, Vy, Vz)

                s = lax.fori_loop(0, w, body, s)
                if pipelined:
                    from ..ops.halo import (
                        begin_slab_exchange,
                        finish_slab_exchange,
                    )

                    pend = begin_slab_exchange(
                        s, (0, 1, 2), width=w, coalesce=coalesce
                    )
                    return finish_slab_exchange(s, pend)
                return update_halo(*s, width=w, coalesce=coalesce)

            return lax.fori_loop(0, nsteps // w, group, (P, Vx, Vy, Vz))

        return _wrap(block_step)

    if pipelined:
        raise ValueError(
            "pipelined applies to the group cadences (fused_k or "
            "exchange_every > 1); the per-step path has no group schedule."
        )

    if params.hide_comm:
        v_exchange = hide_communication(v_update, radius=1)
    else:

        def v_exchange(P, Vx, Vy, Vz):
            return update_halo(*v_update(P, Vx, Vy, Vz), coalesce=coalesce)

    def block_step(P, Vx, Vy, Vz):
        def body(i, s):
            P, Vx, Vy, Vz = s
            Vx, Vy, Vz = v_exchange(P, Vx, Vy, Vz)
            P = p_update(P, Vx, Vy, Vz)
            return (P, Vx, Vy, Vz)

        return lax.fori_loop(0, nsteps, body, (P, Vx, Vy, Vz))

    return _wrap(block_step)


def run(
    nt: int,
    nx: int = 64,
    ny: int = 64,
    nz: int = 64,
    *,
    finalize: bool = True,
    guard_every: int | None = None,
    guard_policy: str | None = None,
    checkpoint_every: int | None = None,
    checkpoint_dir: str | None = None,
    checkpoint_keep: int | None = None,
    integrity_every: int | None = None,
    **kw,
):
    """End-to-end run; returns the final global-block pressure field.

    Resilience hooks as in `models.diffusion3d.run` (``guard_every`` /
    ``guard_policy`` / ``checkpoint_every`` / ``checkpoint_dir`` /
    ``checkpoint_keep`` / ``integrity_every``; resume is
    topology-elastic)."""
    import jax

    from ..parallel.grid import global_grid

    from ..parallel.grid import grid_is_initialized
    from ..utils.resilience import RunGuard, guarded_time_loop

    caller_owns_grid = grid_is_initialized()  # init_grid=False with a live grid
    try:
        from ..utils import liveplane as _liveplane
        from ..utils import tracing as _tracing

        # Live plane up BEFORE the long bring-up/compile phase (no-op
        # unless IGG_METRICS_PORT is set; docs/observability.md).
        _liveplane.ensure_server()
        with _tracing.trace_span("igg.run.setup", model="acoustic3d"):
            state, params = setup(nx, ny, nz, **kw)
            step = make_step(params)
        guard = RunGuard(
            guard_every=guard_every,
            policy=guard_policy,
            checkpoint_every=checkpoint_every,
            checkpoint_dir=checkpoint_dir,
            checkpoint_keep=checkpoint_keep,
            integrity_every=integrity_every,
            names=("P", "Vx", "Vy", "Vz"),
        )
        sync_every_step = global_grid().mesh.devices.flat[0].platform == "cpu"
        # Telemetry bytes model: all four leapfrog fields (P, Vx, Vy, Vz)
        # evolve, so each must stream once in and once out per step.
        from ..utils.telemetry import teff_bytes

        state = guarded_time_loop(
            step, state, nt, guard=guard, sync_every_step=sync_every_step,
            model="acoustic3d", bytes_per_step=teff_bytes(state),
        )
        P = jax.block_until_ready(state[0])
    except BaseException:
        # A failed run must not poison the next init_global_grid in this
        # process (the singleton would report "already initialized") — but
        # never tear down a grid the caller set up themselves.
        if not caller_owns_grid and grid_is_initialized():
            finalize_global_grid()
        raise
    if finalize:
        finalize_global_grid()
    return P


def pressure(state):
    return state[0]
