#!/usr/bin/env python
"""refresh_cost_baseline — re-emit ``analysis/cost_baseline.json``.

Runs the ``hlo-cost`` census (compiles the production config matrix on the
8-device XLA:CPU mesh) and rewrites the committed baseline.  The audit
contract mirrors ``analysis/baseline.json``: every CHANGED metric must be
justified, so the baseline records WHY each number moved, never just that
it did::

    refresh_cost_baseline.py --dry-run
        # show what changed vs the committed baseline, write nothing
    refresh_cost_baseline.py \\
        --justify "cadence/porous[pipelined=True]::fusions=PR 8 splits the \\
PT update into ragged chunks (bench shows +12%)" \\
        --justify-all "toolchain bump to jaxlib X.Y re-fused the cadences"
        # per-metric notes win over the catch-all

``--justify`` keys are ``program::metric`` (repeatable); ``--justify-all``
covers any remaining changes.  Unchanged metrics keep their existing
justification.  Exit 0 = written (or clean dry run), 1 = changed metrics
lack justification, 2 = census failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _ensure_devices() -> None:
    """One shared mesh-staging recipe: `analysis.core.ensure_cpu_devices`
    (the census must compile on the SAME mesh igg_lint gates on)."""
    sys.path.insert(0, REPO)
    from implicitglobalgrid_tpu.analysis.core import ensure_cpu_devices

    ensure_cpu_devices()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="refresh_cost_baseline",
                                description=__doc__)
    p.add_argument("--justify", action="append", default=[],
                   metavar="PROGRAM::METRIC=NOTE",
                   help="justification for one changed metric (repeatable)")
    p.add_argument("--justify-all", default=None, metavar="NOTE",
                   help="justification for every otherwise-unjustified "
                        "changed metric")
    p.add_argument("--dry-run", action="store_true",
                   help="report changes, write nothing")
    p.add_argument("--out", default=None,
                   help="output path (default: the committed baseline)")
    args = p.parse_args(argv)

    notes = {}
    for spec in args.justify:
        key, sep, note = spec.partition("=")
        if not sep or not note.strip() or "::" not in key:
            p.error(f"--justify must be PROGRAM::METRIC=NOTE, got {spec!r}")
        notes[key.strip()] = note.strip()

    sys.path.insert(0, REPO)
    _ensure_devices()
    from implicitglobalgrid_tpu.analysis import costmodel
    from implicitglobalgrid_tpu.analysis.core import Context

    try:
        census = costmodel.cost_census(Context())
    except Exception as e:  # noqa: BLE001 — CLI surface
        print(f"refresh_cost_baseline: census failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2

    path = args.out or costmodel.COST_BASELINE
    old = {"programs": {}, "tolerances": dict(costmodel.TOLERANCES)}
    if os.path.exists(path):
        old = costmodel.load_baseline(path)

    changed, missing_notes = [], []
    removal_notes = {}
    programs = {}
    for name in sorted(census):
        metrics = {
            m: (int(v) if float(v).is_integer() else round(float(v), 2))
            for m, v in sorted(census[name].items())
        }
        old_prog = old.get("programs", {}).get(name, {})
        old_metrics = old_prog.get("metrics", {})
        old_just = old_prog.get("justifications", {})
        justifications = {}
        for m, v in metrics.items():
            key = f"{name}::{m}"
            if m in old_metrics and old_metrics[m] == v:
                justifications[m] = old_just.get(
                    m, notes.get(key, args.justify_all or "")
                )
            else:
                was = old_metrics.get(m, "<absent>")
                changed.append(f"{key}: {was} -> {v}")
                note = notes.get(key, args.justify_all)
                if not note:
                    missing_notes.append(key)
                justifications[m] = note or ""
        for m in sorted(set(old_metrics) - set(metrics)):
            # A baselined metric the census stopped producing is the gate
            # LOSING a blind-spot check — dropping it must be as audited
            # as changing it (the costmodel pass reports the same absence
            # as `metric-lost` until the baseline is refreshed).
            key = f"{name}::{m}"
            changed.append(f"{key}: {old_metrics[m]} -> <removed>")
            note = notes.get(key, args.justify_all)
            if note:
                removal_notes[key] = note
            else:
                missing_notes.append(key)
        programs[name] = {"metrics": metrics,
                          "justifications": justifications}
    for name in sorted(set(old.get("programs", {})) - set(census)):
        # A whole program leaving the matrix drops EVERY one of its gated
        # metrics — the audit bar is the same as for a single metric
        # (justify as `PROGRAM::*`).
        changed.append(f"{name}: removed (no longer in the compiled matrix)")
        note = notes.get(f"{name}::*", args.justify_all)
        if note:
            removal_notes[f"{name}::*"] = note
        else:
            missing_notes.append(f"{name}::*")

    for line in changed:
        print(f"changed  {line}")
    if not changed:
        print("refresh_cost_baseline: census matches the committed "
              "baseline — nothing to refresh")
    if args.dry_run:
        return 0
    if missing_notes:
        print("refresh_cost_baseline: FAIL — changed metric(s) without a "
              "--justify note:", file=sys.stderr)
        for key in missing_notes:
            print(f"  --justify \"{key}=<why>\"", file=sys.stderr)
        return 1

    data = {
        "version": 1,
        "tolerances": old.get("tolerances",
                              dict(costmodel.TOLERANCES)),
        "programs": programs,
    }
    # removals are an APPEND-ONLY audit log: the note explaining why a
    # gated metric/program left the baseline must outlive the entry itself
    removals = {**old.get("removals", {}), **removal_notes}
    if removals:
        data["removals"] = removals
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"refresh_cost_baseline: wrote {path} "
          f"({len(programs)} program(s), {len(changed)} change(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
