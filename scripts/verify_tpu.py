"""One-command hardware validation on a real TPU chip.

Runs the end-to-end drives that the CPU test suite cannot: compiled (not
interpreted) kernels and exchanges on the attached chip, via the public API
only.  Complements `python -m pytest tests/` (virtual 8-device CPU mesh) and
`python bench.py` (performance).

    python scripts/verify_tpu.py

Checks:
 1. periodic self-neighbor halo restoration on the chip,
 2. fused Pallas kernel vs the XLA path (few-ULP, ring bit-exact),
 3. deep-halo temporal blocking (fused + width-k slab exchange) vs the
    per-step XLA path on a communicating (periodic) grid,
 4. the XLA-only slab cadence (`exchange_every`) matching per-step to
    few f32 ULPs (per-program FMA contraction),
 5. example `diffusion3d_tpu_fused` end-to-end.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def sync(x):
    """Honest completion sync: fetch one element (block_until_ready can
    return early on tunneled backends — see docs/performance.md)."""
    shard = x.addressable_shards[0].data
    float(shard[(0,) * shard.ndim])
    return x


def check_self_neighbor():
    import jax
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    state, params = diffusion3d.setup(
        32, 32, 32, periodx=1, quiet=True, dtype=jax.numpy.float32
    )
    step = diffusion3d.make_step(params)
    for _ in range(3):
        state = step(*state)
    T = np.asarray(sync(state[0]))
    o = igg.get_global_grid().overlaps[0]
    assert np.array_equal(T[-1], T[o - 1]), "self-neighbor hi plane"
    assert np.array_equal(T[0], T[-o]), "self-neighbor lo plane"
    igg.finalize_global_grid()
    print("1. periodic self-neighbor halo: OK")


def check_fused_vs_xla():
    import jax
    import jax.numpy as jnp
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    state, params = diffusion3d.setup(64, 128, 256, quiet=True, dtype=jnp.float32)
    xla = diffusion3d.make_multi_step(params, 4, donate=False)
    fused = diffusion3d.make_multi_step(params, 4, donate=False, fused_k=4)
    ref = np.asarray(sync(xla(*state)[0]))
    got = np.asarray(sync(fused(*state)[0]))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    T0 = np.asarray(state[0])
    for ax in range(3):
        assert np.array_equal(np.take(got, 0, axis=ax), np.take(T0, 0, axis=ax))
    igg.finalize_global_grid()
    print(f"2. fused kernel vs XLA (compiled): OK, max|d|={np.max(np.abs(got - ref)):.2e}")


def check_deep_halo_slab():
    import jax.numpy as jnp
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    kw = dict(periodz=1, overlapz=4, quiet=True, dtype=jnp.float32)
    state, params = diffusion3d.setup(64, 64, 256, **kw)
    sx = diffusion3d.make_multi_step(params, 4, donate=False)
    sf = diffusion3d.make_multi_step(params, 4, donate=False, fused_k=2)
    ref = np.asarray(sync(sx(*state)[0]))
    got = np.asarray(sync(sf(*state)[0]))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    igg.finalize_global_grid()
    print(
        "3. deep-halo temporal blocking (fused + width-2 slab exchange): OK, "
        f"max|d|={np.max(np.abs(got - ref)):.2e}"
    )


def check_cadence():
    import jax.numpy as jnp
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    kw = dict(periodz=1, overlapz=4, quiet=True, dtype=jnp.float32)
    state, params = diffusion3d.setup(64, 64, 256, **kw)
    sx = diffusion3d.make_multi_step(params, 4, donate=False)
    sc = diffusion3d.make_multi_step(params, 4, donate=False, exchange_every=2)
    ref = np.asarray(sync(sx(*state)[0]))
    got = np.asarray(sync(sc(*state)[0]))
    # Few-ULP, not bitwise: the two programs fuse differently and XLA's FMA
    # contraction rounds differently per program on TPU (measured ~5e-7 on
    # O(100) values; the CPU-mesh test is bitwise because codegen matches).
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    igg.finalize_global_grid()
    print(
        "4. XLA slab cadence (exchange_every=2) matches per-step: OK, "
        f"max|d|={np.max(np.abs(got - ref)):.2e}"
    )


def check_example():
    import importlib.util

    import numpy as np

    ex = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "examples",
        "diffusion3d_tpu_fused.py",
    )
    spec = importlib.util.spec_from_file_location("dtf", ex)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    T = mod.diffusion3d_fused(nx=128, nt=40, k=2, quiet=True)
    assert np.isfinite(np.asarray(T)).all()
    print("5. fused example end-to-end: OK")


if __name__ == "__main__":
    import jax

    print("device:", jax.devices()[0].device_kind)
    check_self_neighbor()
    check_fused_vs_xla()
    check_deep_halo_slab()
    check_cadence()
    check_example()
    print("ALL TPU CHECKS PASSED")
