"""One-command hardware validation on a real TPU chip.

Runs the end-to-end drives that the CPU test suite cannot: compiled (not
interpreted) kernels and exchanges on the attached chip, via the public API
only.  Complements `python -m pytest tests/` (virtual 8-device CPU mesh) and
`python bench.py` (performance).

    python scripts/verify_tpu.py

Checks:
 1. periodic self-neighbor halo restoration on the chip,
 2. fused Pallas kernel vs the XLA path (few-ULP, ring bit-exact),
 3. deep-halo temporal blocking (fused + width-k slab exchange) vs the
    per-step XLA path on a communicating (periodic) grid,
 4. the XLA-only slab cadence (`exchange_every`) matching per-step to
    few f32 ULPs (per-program FMA contraction),
 5. example `diffusion3d_tpu_fused` end-to-end,
 6. the hide_communication overlap schedule in the TPU backend's compiled
    multi-chip program: async collective-permute-start/-done pairs present,
    and no exchange waiting on the interior fusion (AOT topology compile;
    skipped with a pointer to the CPU-mesh dataflow test when the runtime
    cannot compile for a multi-chip topology),
 7. the staggered fused leapfrog kernel (even-extent padded layout) vs the
    XLA acoustic path — compiled, the config the round-2 infeasibility note
    said could not run (reversed in round 3, see docs/performance.md),
 8. the fused PT-iteration kernel vs the per-iteration XLA porous path —
    compiled, scale-relative tolerance (flux magnitudes scale as
    |grad Pf|/dx, so absolute ULP size scales with them),
 9. the multi-chip staggered fused program AOT-compiled for an 8-chip TPU
    topology: acoustic fused_k chunk (Mosaic kernel + width-k all-field
    slab exchange) lowered over a 2x2x2 mesh — the Pallas custom call and
    the collective-permute exchanges coexist in one compiled program,
10. the round-4 z-patch export cadence AOT-compiled for the same 8-chip
    topology with a REAL z split: one fused group (in-kernel patch apply +
    z-slab export) + x/y exchanges of field and packed export + the packed
    z communication (`z_patch_from_export`) in one program,
11. the same production cadence scaled to a 16-chip (4,2,2) topology with
    TWO pipelined kernel groups — the weak-scaling compile proxy.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def sync(x):
    """Honest completion sync: fetch one element (block_until_ready can
    return early on tunneled backends — see docs/performance.md)."""
    shard = x.addressable_shards[0].data
    float(shard[(0,) * shard.ndim])
    return x


def check_self_neighbor():
    import jax
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    state, params = diffusion3d.setup(
        32, 32, 32, periodx=1, quiet=True, dtype=jax.numpy.float32
    )
    step = diffusion3d.make_step(params)
    for _ in range(3):
        state = step(*state)
    T = np.asarray(sync(state[0]))
    o = igg.get_global_grid().overlaps[0]
    assert np.array_equal(T[-1], T[o - 1]), "self-neighbor hi plane"
    assert np.array_equal(T[0], T[-o]), "self-neighbor lo plane"
    igg.finalize_global_grid()
    print("1. periodic self-neighbor halo: OK")


def check_fused_vs_xla():
    import jax
    import jax.numpy as jnp
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    state, params = diffusion3d.setup(64, 128, 256, quiet=True, dtype=jnp.float32)
    xla = diffusion3d.make_multi_step(params, 4, donate=False)
    fused = diffusion3d.make_multi_step(params, 4, donate=False, fused_k=4)
    ref = np.asarray(sync(xla(*state)[0]))
    got = np.asarray(sync(fused(*state)[0]))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    T0 = np.asarray(state[0])
    for ax in range(3):
        assert np.array_equal(np.take(got, 0, axis=ax), np.take(T0, 0, axis=ax))
    igg.finalize_global_grid()
    print(f"2. fused kernel vs XLA (compiled): OK, max|d|={np.max(np.abs(got - ref)):.2e}")


def check_deep_halo_slab():
    import jax.numpy as jnp
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    kw = dict(periodz=1, overlapz=4, quiet=True, dtype=jnp.float32)
    state, params = diffusion3d.setup(64, 64, 256, **kw)
    sx = diffusion3d.make_multi_step(params, 4, donate=False)
    sf = diffusion3d.make_multi_step(params, 4, donate=False, fused_k=2)
    ref = np.asarray(sync(sx(*state)[0]))
    got = np.asarray(sync(sf(*state)[0]))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    igg.finalize_global_grid()
    print(
        "3. deep-halo temporal blocking (fused + width-2 slab exchange): OK, "
        f"max|d|={np.max(np.abs(got - ref)):.2e}"
    )


def check_cadence():
    import jax.numpy as jnp
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    kw = dict(periodz=1, overlapz=4, quiet=True, dtype=jnp.float32)
    state, params = diffusion3d.setup(64, 64, 256, **kw)
    sx = diffusion3d.make_multi_step(params, 4, donate=False)
    sc = diffusion3d.make_multi_step(params, 4, donate=False, exchange_every=2)
    ref = np.asarray(sync(sx(*state)[0]))
    got = np.asarray(sync(sc(*state)[0]))
    # Few-ULP, not bitwise: the two programs fuse differently and XLA's FMA
    # contraction rounds differently per program on TPU (measured ~5e-7 on
    # O(100) values; the CPU-mesh test is bitwise because codegen matches).
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    igg.finalize_global_grid()
    print(
        "4. XLA slab cadence (exchange_every=2) matches per-step: OK, "
        f"max|d|={np.max(np.abs(got - ref)):.2e}"
    )


def check_example():
    import importlib.util

    import numpy as np

    ex = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "examples",
        "diffusion3d_tpu_fused.py",
    )
    spec = importlib.util.spec_from_file_location("dtf", ex)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    T = mod.diffusion3d_fused(nx=128, nt=40, k=2, quiet=True)
    assert np.isfinite(np.asarray(T)).all()
    print("5. fused example end-to-end: OK")


def _aot_hide_comm_hlo():
    """Compile the hide_comm step for an 8-chip TPU topology AOT (no second
    chip needed); returns the optimized HLO text, or raises when the runtime
    cannot compile for a multi-chip topology (the only legitimate skip)."""
    import numpy as np

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.ops.overlap import hide_communication
    from implicitglobalgrid_tpu.utils.aot import synthetic_topology_grid

    # Build the per-block program against the AOT mesh via the shared
    # synthetic-GlobalGrid scaffold (the public init path binds to the
    # attached client's devices, which is exactly what AOT avoids).
    with synthetic_topology_grid((2, 2, 2), (16, 16, 16)) as (gg, mesh):
        params = diffusion3d.Params(
            dx=0.1, dy=0.1, dz=0.1, dt=1e-4, dtype=np.float32, hide_comm=True
        )
        update = diffusion3d._diffusion_update(params)
        overlapped = hide_communication(update, radius=1)

        def block_step(T, Cp):
            return overlapped(T, Cp), Cp

        mapped = jax.jit(
            jax.shard_map(
                block_step, mesh=mesh,
                in_specs=(P("x", "y", "z"),) * 2,
                out_specs=(P("x", "y", "z"),) * 2,
                check_vma=False,
            )
        )
        aval = jax.ShapeDtypeStruct(
            (32, 32, 32), np.float32, sharding=NamedSharding(mesh, P("x", "y", "z"))
        )
        return mapped.lower(aval, aval).compile().as_text()


def check_overlap_schedule():
    """Pin the overlap claim on the real backend's compiled program: async
    collective-permute-start/-done pairs + no exchange waiting on the
    interior fusion.  Only the AOT compile itself may skip; a failed
    ASSERTION on the obtained program fails the whole script."""
    from implicitglobalgrid_tpu.utils.hlo_analysis import collective_waits

    try:
        txt = _aot_hide_comm_hlo()
    except Exception as e:  # noqa: BLE001 — report and point at the CPU pin
        print(
            f"6. overlap schedule: SKIPPED ({type(e).__name__}: {e}) — the "
            "dataflow property is pinned by tests/test_stencil_overlap.py::"
            "test_hide_comm_collectives_do_not_wait_on_interior on the "
            "8-device CPU mesh"
        )
        return
    n_cp, waits, n_async = collective_waits(txt, 16 * 16 * 16)
    assert n_cp >= 6, f"expected >= 6 exchanges in the AOT program, got {n_cp}"
    assert n_async > 0, "TPU program has no async collective-permute-start"
    assert "collective-permute-done" in txt
    assert not any(waits), f"exchange waits on the interior fusion: {waits}"
    print(
        f"6. overlap schedule (AOT 2x2x2): OK — {n_async} async "
        "collective-permute-start/-done pairs, none waiting on the interior"
    )


def check_staggered_fused():
    import jax.numpy as jnp
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import acoustic3d

    state, params = acoustic3d.setup(64, 128, 256, quiet=True, dtype=jnp.float32)
    xla = acoustic3d.make_multi_step(params, 6, donate=False)
    fused = acoustic3d.make_multi_step(params, 6, donate=False, fused_k=6)
    ref = [np.asarray(A) for A in xla(*state)]
    sync(state[0])
    got = fused(*state)
    sync(got[0])
    got = [np.asarray(A) for A in got]
    for name, g, r in zip(("P", "Vx", "Vy", "Vz"), got, ref):
        np.testing.assert_allclose(g, r, rtol=1e-5, atol=1e-5, err_msg=name)
    # Frozen velocity boundary faces stay bit-exact; P's boundary evolves.
    Vx0 = np.asarray(state[1])
    assert np.array_equal(got[1][0], Vx0[0]) and np.array_equal(got[1][-1], Vx0[-1])
    assert not np.array_equal(got[0][0], np.asarray(state[0])[0])
    igg.finalize_global_grid()
    print(
        "7. staggered fused leapfrog kernel vs XLA (compiled): OK, "
        f"max|dP|={np.max(np.abs(got[0] - ref[0])):.2e}"
    )


def _aot_staggered_fused_hlo():
    """AOT-compile the acoustic fused_k chunk for an 8-chip topology.

    Same synthetic-GlobalGrid technique as `_aot_hide_comm_hlo`; the mesh is
    2x2x2 with deep halos in every dimension, local blocks (16, 32, 128)
    with the (8, 16) tile, so the kernel envelope accepts the block and the
    program contains BOTH the Mosaic kernel custom-call and the width-2
    slab exchanges."""
    import numpy as np

    import jax

    from implicitglobalgrid_tpu.utils.aot import synthetic_topology_grid

    with synthetic_topology_grid(
        (2, 2, 2), (16, 32, 128), (4, 4, 4)
    ) as (gg, mesh):
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from implicitglobalgrid_tpu.ops.pallas_leapfrog import (
            fused_leapfrog_steps,
            pad_faces,
            unpad_faces,
        )

        # The fused chunk body of acoustic3d.make_multi_step's deep-halo
        # branch, shard_mapped by hand (the `stencil` wrapper builds from
        # concrete args, which AOT avals cannot provide).
        c = 1e-3 / 0.1

        from implicitglobalgrid_tpu.ops.halo import update_halo_padded_faces

        def block_step(Pf, Vx, Vy, Vz):
            def group(i, s):
                s = fused_leapfrog_steps(
                    *s, 2, c, c, c, 1e-3, 10.0, 10.0, 10.0, bx=8, by=16
                )
                return update_halo_padded_faces(*s, width=2)

            Pf, Vxp, Vyp, Vzp = lax.fori_loop(
                0, 2, group, (Pf, *pad_faces(Vx, Vy, Vz))
            )
            return (Pf, *unpad_faces(Vxp, Vyp, Vzp))

        mapped = jax.jit(
            jax.shard_map(
                block_step, mesh=mesh,
                in_specs=(P("x", "y", "z"),) * 4,
                out_specs=(P("x", "y", "z"),) * 4,
                check_vma=False,
            )
        )
        spec = NamedSharding(mesh, P("x", "y", "z"))
        avals = tuple(
            jax.ShapeDtypeStruct(s, np.float32, sharding=spec)
            for s in ((32, 64, 256), (34, 64, 256), (32, 66, 256), (32, 64, 258))
        )
        return mapped.lower(*avals).compile().as_text()


def check_multichip_fused_aot():
    """Pin the multi-chip staggered fused path on the real backend's AOT
    compiler: kernel custom-call + collective-permute exchanges in one
    program.  Only the AOT compile itself may skip (same rule as check 6)."""
    try:
        txt = _aot_staggered_fused_hlo()
    except Exception as e:  # noqa: BLE001 — report and point at the CPU pin
        print(
            f"9. multi-chip staggered fused AOT: SKIPPED ({type(e).__name__}: "
            f"{e}) — the path is pinned by tests/test_models_acoustic.py::"
            "test_fused_deep_halo_matches_xla_multiblock on the CPU mesh"
        )
        return
    assert "tpu_custom_call" in txt, "no Mosaic kernel custom-call in the AOT program"
    n_cp = txt.count("collective-permute-start(") + txt.count("collective-permute(")
    assert n_cp >= 6, f"expected >= 6 slab exchanges in the AOT program, got {n_cp}"
    print(
        f"9. multi-chip staggered fused AOT (2x2x2): OK — Mosaic kernel + "
        f"{n_cp} collective-permute exchanges in one program"
    )


def _aot_zpatch_fused_hlo(dims=(2, 2, 2), k=2, groups=1):
    """AOT-compile ``groups`` diffusion z-patch-export group(s) over a mesh.

    Same synthetic-GlobalGrid technique as `_aot_staggered_fused_hlo`, but
    the mesh has a real z split, so the compiled program must contain the
    Mosaic kernel (with its z-export output), the x/y collective-permute
    slab exchanges of BOTH the field and the packed export, and the packed
    z communication of `z_patch_from_export`.  ``dims=(4,2,2)`` with
    ``groups=2`` is the 16-chip production-shape variant (check 11)."""
    import numpy as np

    import jax

    from implicitglobalgrid_tpu.utils.aot import synthetic_topology_grid

    o = 2 * k
    with synthetic_topology_grid(dims, (16, 32, 128), (o, o, o)) as (gg, mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from implicitglobalgrid_tpu.ops.halo import (
            apply_z_patch,
            exchange_dims,
            identity_z_patch,
            z_patch_from_export,
        )
        from implicitglobalgrid_tpu.ops.pallas_stencil import fused_diffusion_steps

        c = 1e-3 / 0.01

        def block_step(T, Cp):
            patch = identity_z_patch(T, width=k)
            for _ in range(groups):
                T, zex = fused_diffusion_steps(
                    T, Cp, k, c, c, c, bx=8, by=16, z_patch=patch,
                    z_export=True, z_overlap=o,
                )
                T = exchange_dims(T, (0, 1), width=k)
                zex = exchange_dims(zex, (0, 1), width=k)
                patch = z_patch_from_export(zex, width=k)
            return apply_z_patch(T, patch, width=k)

        mapped = jax.jit(
            jax.shard_map(
                block_step, mesh=mesh,
                in_specs=(P("x", "y", "z"),) * 2,
                out_specs=P("x", "y", "z"),
                check_vma=False,
            )
        )
        spec = NamedSharding(mesh, P("x", "y", "z"))
        gshape = (16 * dims[0], 32 * dims[1], 128 * dims[2])
        avals = tuple(
            jax.ShapeDtypeStruct(gshape, np.float32, sharding=spec)
            for _ in range(2)
        )
        return mapped.lower(*avals).compile().as_text()


def check_zpatch_export_aot():
    """Pin the round-4 z-split production cadence on the TPU AOT compiler."""
    try:
        txt = _aot_zpatch_fused_hlo()
    except Exception as e:  # noqa: BLE001 — report and point at the CPU pin
        print(
            f"10. z-patch export cadence AOT: SKIPPED ({type(e).__name__}: "
            f"{e}) — the path is pinned by tests/test_models_diffusion.py::"
            "test_fused_zpatch_random_topology_invariance on the CPU mesh"
        )
        return
    assert "tpu_custom_call" in txt, "no Mosaic kernel custom-call in the AOT program"
    n_cp = txt.count("collective-permute-start(") + txt.count("collective-permute(")
    # x/y exchanges of T (4) + of the packed export (4) + the packed z
    # communication's two ppermutes = >= 10 collective-permutes.
    assert n_cp >= 10, f"expected >= 10 collective-permutes, got {n_cp}"
    # The z hop must move packed (n0, n1, k) slabs, NOT full arrays — the
    # point of the export design.  Local block (16,32,128), k=2: count the
    # thin-slab permute OPS (start/sync forms only — an async op's matching
    # -done line would double-count the same hop).
    thin = sum(
        1
        for line in txt.splitlines()
        if ("collective-permute-start(" in line or "collective-permute(" in line)
        and "f32[16,32,2]" in line
    )
    assert thin >= 2, (
        f"expected >= 2 packed (16,32,2) z-slab collective-permutes, got {thin}"
    )
    print(
        f"10. z-patch export cadence AOT (2x2x2, z split): OK — Mosaic kernel "
        f"+ {n_cp} collective-permutes ({thin} packed (16,32,2) z hops; no "
        "full-array z exchange) in one program"
    )


def check_zpatch_export_aot_16chip():
    """Scale the production cadence compile to 16 chips, two groups — the
    weak-scaling compile proxy at (4,2,2): the program must pipeline two
    kernel groups with packed z hops between them."""
    try:
        txt = _aot_zpatch_fused_hlo(dims=(4, 2, 2), k=4, groups=2)
    except Exception as e:  # noqa: BLE001
        print(
            f"11. 16-chip production cadence AOT: SKIPPED ({type(e).__name__}: {e})"
        )
        return
    assert "tpu_custom_call" in txt, "no Mosaic kernel custom-call"
    n_cp = txt.count("collective-permute-start(") + txt.count("collective-permute(")
    thin = sum(
        1
        for line in txt.splitlines()
        if ("collective-permute-start(" in line or "collective-permute(" in line)
        and "f32[16,32,4]" in line
    )
    assert n_cp >= 20, f"expected >= 20 collective-permutes (2 groups), got {n_cp}"
    assert thin >= 4, f"expected >= 4 packed z hops (2 groups x 2), got {thin}"
    print(
        f"11. 16-chip (4,2,2) production cadence AOT: OK — 2 pipelined kernel "
        f"groups, {n_cp} collective-permutes, {thin} packed (16,32,4) z hops"
    )


def check_pt_fused():
    import jax.numpy as jnp
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import porous_convection3d as pc

    state, params = pc.setup(64, 128, 256, npt=4, quiet=True, dtype=jnp.float32)
    xla = pc.make_multi_step(params, 2, donate=False)
    fused = pc.make_multi_step(params, 2, donate=False, fused_k=2)
    ref = [np.asarray(A) for A in xla(*state)]
    sync(state[0])
    got = fused(*state)
    sync(got[0])
    got = [np.asarray(A) for A in got]
    worst = 0.0
    for name, g, r in zip(("T", "Pf", "qDx", "qDy", "qDz"), got, ref):
        scale = max(float(np.abs(r).max()), 1.0)
        rel = float(np.abs(g - r).max()) / scale
        worst = max(worst, rel)
        assert rel < 1e-5, (name, rel)
    igg.finalize_global_grid()
    print(f"8. fused PT-iteration kernel vs XLA (compiled): OK, worst rel={worst:.2e}")




def check_transposed_zpatch_aot():
    """Round 5: AOT-pin the TRANSPOSED z-patch cadence's hop structure on a
    2x2x2 topology — the full-y tile (by == n1) routes the diffusion cadence
    through the transposed thin-patch machinery (`ops.halo.*_t`), and the
    compiled program's collective-permutes must all move slab-sized
    payloads (never a full block).  The transposed routing itself is pinned
    structurally: the export's y exchange slices axis 2 (an
    `exchange_dims_t`-only shape), so its (n0, PE, w) hop can only exist if
    the cadence really built transposed patches."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from implicitglobalgrid_tpu.utils.aot import synthetic_topology_grid
    from implicitglobalgrid_tpu.utils.hlo_analysis import collective_payloads

    k = 2
    try:
        ctx = synthetic_topology_grid((2, 2, 2), (16, 32, 128), (4, 4, 4))
        ctx.__enter__()
    except Exception as e:  # noqa: BLE001 — AOT topology is the only skip
        print(
            f"12. transposed z-patch cadence AOT: SKIPPED ({type(e).__name__}: "
            f"{e}) — the layout equivalence is pinned by tests/test_update_halo"
            ".py::test_transposed_z_patch_communication_matches_packed on the "
            "CPU mesh"
        )
        return
    try:
        gg = None
        from implicitglobalgrid_tpu.parallel.grid import global_grid

        gg = global_grid()
        mesh = gg.mesh
        from implicitglobalgrid_tpu.models import diffusion3d

        params = diffusion3d.Params(
            dx=0.1, dy=0.1, dz=0.1, dt=1e-4, dtype=jax.numpy.float32
        )
        step = diffusion3d.make_multi_step(
            params, 2 * k, donate=False, fused_k=k, fused_tile=(8, 32)
        )
        shapes = tuple(
            jax.ShapeDtypeStruct(
                (32, 64, 256), jax.numpy.float32,
                sharding=NamedSharding(mesh, P("x", "y", "z")),
            )
            for _ in range(2)
        )
        fn = step._build(gg, shapes, jax.tree.flatten(shapes)[1])
        txt = fn.lower(*shapes).compile().as_text()
    finally:
        ctx.__exit__(None, None, None)
    assert "tpu_custom_call" in txt, "no Mosaic kernel custom-call in the AOT program"
    hops = collective_payloads(txt)
    assert len(hops) >= 10, f"expected >= 10 hops, got {len(hops)}"
    block_bytes = 16 * 32 * 128 * 4
    biggest = max(h["bytes"] for h in hops)
    assert biggest < block_bytes // 4, (
        f"a collective moves {biggest} bytes — slab exchanges should be far "
        f"below the {block_bytes}-byte block (full-array z exchange regression?)"
    )
    # The transposed-routing signature: the export's axis-2 y-slab hop,
    # shape (n0, pad8(4k), w) = (16, 8, 2).
    assert any(h["shape"] == "f32[16,8,2]" for h in hops), (
        "no (16,8,2) export y-slab hop — the cadence did not route through "
        f"the transposed patch machinery (hops: {[h['shape'] for h in hops]})"
    )
    print(
        f"12. transposed z-patch cadence AOT (2x2x2, full-y tile): OK — "
        f"{len(hops)} slab hops incl. the (16,8,2) transposed-export y hop, "
        f"largest {biggest} B << {block_bytes} B block"
    )


if __name__ == "__main__":
    import jax

    print("device:", jax.devices()[0].device_kind)
    check_self_neighbor()
    check_fused_vs_xla()
    check_deep_halo_slab()
    check_cadence()
    check_example()
    check_overlap_schedule()
    check_staggered_fused()
    check_pt_fused()
    check_multichip_fused_aot()
    check_zpatch_export_aot()
    check_zpatch_export_aot_16chip()
    check_transposed_zpatch_aot()
    print("ALL TPU CHECKS PASSED")
