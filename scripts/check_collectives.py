#!/usr/bin/env python
"""Collective-budget lint: no exchanged dimension may out-spend its hops.

Thin CLI wrapper over the ``collective-budget`` analyzer of ``igg.analysis``
(`implicitglobalgrid_tpu/analysis/budget.py` — the pass-registry home of
the census since ISSUE 6; run the whole suite with ``scripts/igg_lint.py``).
The exit-code contract is unchanged: 0 = every model within <= 2
collective-permutes per exchanged (dimension, dtype width group), nonzero =
violations listed on stdout.  The tier-1 test
``tests/test_collective_budget.py`` calls `violations` directly.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _ensure_devices() -> None:
    """Standalone entry: stage the 8-device CPU mesh before first jax use
    (the tier-1 test inherits conftest's identical staging)."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass


from implicitglobalgrid_tpu.analysis.budget import (  # noqa: E402
    BUDGET_PAIRS,
    violation_strings,
    _count_ppermutes,  # re-exported: tests/test_coalesced_halo.py counts
    # with the lint's own census so the two counters cannot drift
)


def violations(n: int = 8) -> list[str]:
    """Human-readable budget violations (empty = clean).

    Grid: dims (2,2,2), periodic z — every dimension exchanges, both
    PROC_NULL and periodic transports in one config.
    """
    return violation_strings(n, BUDGET_PAIRS)


def main() -> int:
    _ensure_devices()
    probs = violations()
    if probs:
        print("check_collectives: FAIL")
        for p in probs:
            print(f"  - {p}")
        return 1
    print(
        "check_collectives: OK (all models within "
        "<= 2 collective-permutes per exchanged (dim, width-group))"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
