#!/usr/bin/env python
"""Collective-budget lint: no exchanged dimension may out-spend its hops.

The coalesced exchange's whole value is structural — one collective-permute
pair per (dimension, dtype width group) regardless of field count — and it
is provable below the compiler: trace each model's production exchange set
on the virtual 8-device mesh and count the ppermute equations per exchanged
dimension.  The budget table pins the allowed pairs; a regression that
silently re-serializes the exchange into per-field collectives (or emits
extras) fails the suite, exactly like an undocumented knob fails
`check_knobs.py`.

Run standalone (exits nonzero listing violations) or via the tier-1 test
``tests/test_collective_budget.py``.
"""

from __future__ import annotations

import os
import sys


def _ensure_devices() -> None:
    """Standalone entry: stage the 8-device CPU mesh before first jax use
    (the tier-1 test inherits conftest's identical staging)."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass


#: Allowed collective-permute PAIRS per exchanged dimension for each model's
#: production exchange set (all fields f32 => ONE dtype width group each).
#: The per-field counts these budgets forbid are len(fields) pairs per dim.
BUDGET_PAIRS = {
    "diffusion": 1,  # T
    "acoustic": 1,   # P, Vx, Vy, Vz — 4 fields, one pair
    "porous": 1,     # Pf, qDx, qDy, qDz, T — the 5-field step, one pair
}


def _model_fields(model: str, n: int):
    """The model's exchanged field set as traced shapes (staggered ``n+1``
    faces like the real states; f32 like the production configs)."""
    import jax
    import jax.numpy as jnp

    def s(shape):
        return jax.ShapeDtypeStruct(shape, jnp.float32)

    cell = (n, n, n)
    faces = [tuple(n + (1 if d == ax else 0) for d in range(3)) for ax in range(3)]
    if model == "diffusion":
        return (s(cell),)
    if model == "acoustic":
        return (s(cell), *map(s, faces))
    if model == "porous":
        return (s(cell), *map(s, faces), s(cell))
    raise ValueError(model)


def _count_ppermutes(jaxpr) -> int:
    n = 0
    for e in jaxpr.eqns:
        if e.primitive.name == "ppermute":
            n += 1
        for v in e.params.values():
            if hasattr(v, "jaxpr"):
                n += _count_ppermutes(v.jaxpr)
            elif hasattr(v, "eqns"):
                n += _count_ppermutes(v)
    return n


def _traced_dim_ppermutes(fields, d: int, coalesce) -> int:
    """ppermute equations in the traced dim-``d`` exchange of ``fields``."""
    import jax
    from jax.sharding import PartitionSpec as P

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.ops.halo import exchange_dims_multi
    from implicitglobalgrid_tpu.utils.compat import shard_map

    gg = igg.get_global_grid()

    def body(*fs):
        return exchange_dims_multi(fs, (d,), width=1, coalesce=coalesce)

    specs = tuple(P(*igg.AXIS_NAMES[: f.ndim]) for f in fields)
    mapped = shard_map(
        body, mesh=gg.mesh, in_specs=specs, out_specs=specs, check_vma=False
    )
    # Local-block shapes scale to global for the shard_map entry.
    gargs = tuple(
        jax.ShapeDtypeStruct(
            tuple(s * gg.dims[i] for i, s in enumerate(f.shape)), f.dtype
        )
        for f in fields
    )
    return _count_ppermutes(jax.make_jaxpr(mapped)(*gargs).jaxpr)


def violations(n: int = 8) -> list[str]:
    """Human-readable budget violations (empty = clean).

    Grid: dims (2,2,2), periodic z — every dimension exchanges, both
    PROC_NULL and periodic transports in one config.
    """
    import implicitglobalgrid_tpu as igg

    out = []
    igg.init_global_grid(n, n, n, dimx=2, dimy=2, dimz=2, periodz=1,
                         quiet=True)
    try:
        for model, pairs in BUDGET_PAIRS.items():
            fields = _model_fields(model, n)
            for d in range(3):
                got = _traced_dim_ppermutes(fields, d, coalesce=None)
                if got > 2 * pairs:
                    out.append(
                        f"{model}: dimension {d} emits {got} collective-"
                        f"permutes for {len(fields)} fields — budget is "
                        f"{2 * pairs} ({pairs} pair(s); the coalesced "
                        f"exchange regressed to per-field collectives?)"
                    )
            # The lint itself must be alive: the per-field control has to
            # exceed the budget for every multi-field model, or the counter
            # is not seeing the collectives at all.
            if len(fields) > 1:
                ctrl = _traced_dim_ppermutes(fields, 0, coalesce=False)
                if ctrl != 2 * len(fields):
                    out.append(
                        f"{model}: per-field control counted {ctrl} "
                        f"collectives in dim 0, expected {2 * len(fields)} — "
                        f"the ppermute census is broken"
                    )
    finally:
        igg.finalize_global_grid()
    return out


def main() -> int:
    _ensure_devices()
    probs = violations()
    if probs:
        print("check_collectives: FAIL")
        for p in probs:
            print(f"  - {p}")
        return 1
    print(
        "check_collectives: OK (all models within "
        "<= 2 collective-permutes per exchanged (dim, width-group))"
    )
    return 0


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    sys.exit(main())
