#!/usr/bin/env python
"""Cluster view over per-rank live-plane endpoints (docs/observability.md).

Each rank's ``IGG_METRICS_PORT`` server exposes ``/metrics`` (Prometheus
text) and ``/healthz`` (JSON).  This tool scrapes any set of them into ONE
cluster view: a merged exposition with ``rank`` labels, and a terminal
summary table (per-rank step p50/p99, T_eff, skew, last-step age, alerts)
— the live answer to "which rank is slow" without waiting for a trace
merge::

    python scripts/igg_top.py host0:9100 host1:9100
    python scripts/igg_top.py --dir $IGG_TELEMETRY_DIR       # liveplane.p*.json
    python scripts/igg_top.py --endpoints-file endpoints.txt # one host:port/line
    python scripts/igg_top.py --dir RUN --watch 2            # refresh every 2s
    python scripts/igg_top.py --dir RUN --prom merged.prom   # merged exposition

``--dir`` reads the ``liveplane.p<rank>.json`` endpoint files each rank
writes into ``IGG_TELEMETRY_DIR`` when it binds an ephemeral port — the
discovery channel for port-0 runs (the soak ``live_plane`` scenario uses
exactly this).  A scrape retries with exponential backoff (``--retries``,
default ``IGG_FLEET_SCRAPE_RETRIES`` or 2 — one transient accept-queue
hiccup on a busy rank must not paint it dead) before the rank is declared
``UNREACHABLE``; unreachable ranks get an explicit table row, not just a
stderr line, so a fleet operator sees the hole in the screen they are
actually watching.  Exit codes: 0 all endpoints scraped, 1 any endpoint
unreachable, 2 bad usage.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time
import urllib.request

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

SCRAPE_TIMEOUT_S = 3.0
DEFAULT_RETRIES = 2
RETRY_BACKOFF_S = 0.25
UNREACHABLE = "UNREACHABLE"

_SAMPLE = re.compile(r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")


# ---------------------------------------------------------------------------
# endpoint discovery
# ---------------------------------------------------------------------------


def discover_endpoints(args) -> list[str]:
    """``host:port`` list from positional args / --endpoints-file / --dir."""
    endpoints = list(args.endpoints)
    if args.endpoints_file:
        with open(args.endpoints_file) as f:
            endpoints += [
                line.strip()
                for line in f
                if line.strip() and not line.startswith("#")
            ]
    if args.dir:
        files = sorted(glob.glob(os.path.join(args.dir, "liveplane.p*.json")))
        if not files:
            raise FileNotFoundError(
                f"{args.dir}: no liveplane.p*.json endpoint files (is the "
                f"run up with IGG_METRICS_PORT and IGG_TELEMETRY_DIR set?)"
            )
        for path in files:
            with open(path) as f:
                doc = json.load(f)
            endpoints.append(f"{doc['host']}:{doc['port']}")
    if not endpoints:
        raise ValueError(
            "no endpoints: pass host:port arguments, --endpoints-file or "
            "--dir"
        )
    return endpoints


def scrape(endpoint: str, *, retries: int | None = None,
           backoff_s: float = RETRY_BACKOFF_S) -> dict:
    """One rank's ``{health, metrics}``.

    Retries ``retries`` times with exponential backoff (``backoff_s``,
    ``2*backoff_s``, ...) before re-raising — a rank mid-GC or with a
    momentarily full accept queue is busy, not dead.  ``retries=None``
    reads ``IGG_FLEET_SCRAPE_RETRIES`` (shared with the fleet router's
    health scraper) and falls back to ``DEFAULT_RETRIES``.
    """
    if retries is None:
        raw = os.environ.get("IGG_FLEET_SCRAPE_RETRIES", "")
        retries = int(raw) if raw.strip() else DEFAULT_RETRIES
    last: Exception | None = None
    for attempt in range(max(0, retries) + 1):
        if attempt:
            time.sleep(backoff_s * (2 ** (attempt - 1)))
        try:
            with urllib.request.urlopen(
                f"http://{endpoint}/healthz", timeout=SCRAPE_TIMEOUT_S
            ) as r:
                health = json.load(r)
            with urllib.request.urlopen(
                f"http://{endpoint}/metrics", timeout=SCRAPE_TIMEOUT_S
            ) as r:
                metrics = r.read().decode()
            return {"endpoint": endpoint, "health": health,
                    "metrics": metrics}
        except Exception as e:  # noqa: BLE001 — any failure is retryable
            last = e
    raise last


# ---------------------------------------------------------------------------
# merged exposition
# ---------------------------------------------------------------------------


def merge_expositions(per_rank: dict[int, str]) -> str:
    """Join per-rank Prometheus text into one exposition with rank labels.

    Sample lines gain (or extend) a label set with ``rank="N"``; each
    metric's ``# TYPE`` header is emitted once.  The output stays valid
    text format 0.0.4, so one igg_top scrape can stand in for N direct
    scrapes in any collector.
    """
    types: dict[str, str] = {}
    samples: list[tuple[str, str]] = []
    for rank in sorted(per_rank):
        for line in per_rank[rank].splitlines():
            line = line.strip()
            if not line:
                continue
            if line.startswith("# TYPE "):
                parts = line.split()
                if len(parts) == 4:
                    types.setdefault(parts[2], parts[3])
                continue
            if line.startswith("#"):
                continue
            m = _SAMPLE.match(line)
            if not m:
                continue
            name, labels, value = m.groups()
            inner = labels[1:-1] if labels else ""
            inner = f'rank="{rank}"' + (f",{inner}" if inner else "")
            samples.append((name, f"{name}{{{inner}}} {value}"))
    out: list[str] = []
    emitted: set[str] = set()
    for name, line in samples:
        if name not in emitted:
            emitted.add(name)
            t = types.get(name)
            if t:
                out.append(f"# TYPE {name} {t}")
        out.append(line)
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# summary table
# ---------------------------------------------------------------------------


def _fmt(v, scale=1.0, suffix="", nd=1) -> str:
    if v is None:
        return "-"
    return f"{v * scale:.{nd}f}{suffix}"


def _reject_rate(frontdoor: dict) -> str | None:
    """``"NN%(tenant)"`` — overall reject share, tagged with the worst
    tenant by reject count (the per-tenant quota/backpressure attribution
    the front door's capped counters carry)."""
    admitted = frontdoor.get("admitted_total") or 0
    rejected = frontdoor.get("rejected_total") or 0
    total = admitted + rejected
    if not total:
        return None
    pct = f"{100.0 * rejected / total:.0f}%"
    tenants = frontdoor.get("tenants") or {}
    worst = max(
        tenants, key=lambda t: tenants[t].get("rejected", 0), default=None
    )
    if worst is not None and tenants[worst].get("rejected", 0) > 0:
        return f"{pct}({worst})"
    return pct


def summary_rows(healths: dict[int, dict]) -> list[dict]:
    """One summary row per rank from its ``/healthz`` document (incl. the
    ``serving``/``frontdoor`` SLO columns — queue depth, pool occupancy,
    round p50/p99, per-tenant reject rate — so one screen answers
    "is serving healthy" across ranks, ISSUE 12)."""
    rows = []
    for rank in sorted(healths):
        h = healths[rank]
        slo = h.get("slo", {})
        step = next(
            (s for n, s in slo.items() if n.endswith("step_seconds")), {}
        )
        teff = next(
            (s for n, s in slo.items() if n.endswith("t_eff_gbs")), {}
        )
        rnd = next(
            (s for n, s in slo.items() if n.endswith("round_seconds")), {}
        )
        serving = h.get("serving") or {}
        frontdoor = h.get("frontdoor") or {}
        active = h.get("alerts", {}).get("active", [])
        occupancy = None
        if serving.get("active_members") is not None:
            cap = serving.get("capacity")
            occupancy = (
                f"{serving['active_members']:.0f}/{cap:.0f}"
                if cap is not None else f"{serving['active_members']:.0f}"
            )
        rows.append(
            {
                "rank": rank,
                "ok": h.get("ok"),
                "coords": h.get("coords"),
                "step": h.get("last_step", {}).get("step"),
                "age_s": h.get("last_step", {}).get("age_s"),
                "p50_ms": (step.get("p50") or 0) * 1e3 if step else None,
                "p99_ms": (step.get("p99") or 0) * 1e3 if step else None,
                "teff_gbs": teff.get("p50") if teff else None,
                "skew": h.get("skew", {}).get("step_seconds_max_over_min"),
                "queue": serving.get("queue_depth"),
                "members": occupancy,
                "oldest_s": serving.get("oldest_request_age_s"),
                "rnd_p50_ms": (rnd.get("p50") or 0) * 1e3 if rnd else None,
                "rnd_p99_ms": (rnd.get("p99") or 0) * 1e3 if rnd else None,
                "reject": _reject_rate(frontdoor),
                "alerts": ",".join(
                    f"{a['rule']}({a['severity']})" for a in active
                ) or "-",
            }
        )
    return rows


def render_table(rows: list[dict]) -> str:
    head = (
        f"{'rank':>4} {'ok':>4} {'step':>8} {'age':>8} {'p50':>9} "
        f"{'p99':>9} {'T_eff':>9} {'skew':>6} {'queue':>6} {'mem':>7} "
        f"{'oldest':>8} {'rnd50':>8} {'rnd99':>8} {'rej':>10}  alerts"
    )
    lines = [head, "-" * len(head)]
    for r in rows:
        if r["ok"] == UNREACHABLE:
            # explicit row state: the hole in the fleet stays on the
            # screen the operator is watching, aligned with its rank
            lines.append(
                f"{r['rank']:>4} {'DOWN':>4} "
                + " ".join(["-".rjust(w) for w in (8, 8, 9, 9, 9, 6, 6,
                                                   7, 8, 8, 8, 10)])
                + f"  {UNREACHABLE} {r['alerts']}"
            )
            continue
        lines.append(
            f"{r['rank']:>4} {('ok' if r['ok'] else 'ALRT'):>4} "
            f"{r['step'] if r['step'] is not None else '-':>8} "
            f"{_fmt(r['age_s'], suffix='s'):>8} "
            f"{_fmt(r['p50_ms'], suffix='ms'):>9} "
            f"{_fmt(r['p99_ms'], suffix='ms'):>9} "
            f"{_fmt(r['teff_gbs'], suffix='GB', nd=2):>9} "
            f"{_fmt(r['skew'], nd=2):>6} "
            f"{_fmt(r.get('queue'), nd=0):>6} "
            f"{r.get('members') or '-':>7} "
            f"{_fmt(r.get('oldest_s'), suffix='s'):>8} "
            f"{_fmt(r.get('rnd_p50_ms'), suffix='ms'):>8} "
            f"{_fmt(r.get('rnd_p99_ms'), suffix='ms'):>8} "
            f"{r.get('reject') or '-':>10}  {r['alerts']}"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def scrape_cluster(endpoints: list[str], *,
                   retries: int | None = None) -> tuple[dict, list[str]]:
    """``({rank: scrape result}, [unreachable endpoint messages])``."""
    by_rank: dict[int, dict] = {}
    errors: list[str] = []
    for i, ep in enumerate(endpoints):
        try:
            res = scrape(ep, retries=retries)
        except Exception as e:
            errors.append(f"{ep}: {type(e).__name__}: {e}")
            continue
        rank = res["health"].get("rank", i)
        by_rank[rank] = res
    return by_rank, errors


def one_view(args, endpoints: list[str]) -> int:
    by_rank, errors = scrape_cluster(
        endpoints, retries=getattr(args, "retries", None)
    )
    healths = {r: res["health"] for r, res in by_rank.items()}
    rows = summary_rows(healths)
    for msg in errors:
        rows.append({
            "rank": "?", "ok": UNREACHABLE, "coords": None, "step": None,
            "age_s": None, "p50_ms": None, "p99_ms": None, "teff_gbs": None,
            "skew": None, "queue": None, "members": None, "oldest_s": None,
            "rnd_p50_ms": None, "rnd_p99_ms": None, "reject": None,
            "alerts": msg,
        })
    print(
        f"igg_top — {len(by_rank)}/{len(endpoints)} rank(s) at "
        f"{time.strftime('%H:%M:%S')}"
    )
    print(render_table(rows))
    for msg in errors:
        print(f"igg_top: UNREACHABLE {msg}", file=sys.stderr)
    if args.prom:
        merged = merge_expositions(
            {r: res["metrics"] for r, res in by_rank.items()}
        )
        with open(args.prom, "w", encoding="utf-8") as f:
            f.write(merged)
        print(f"igg_top: wrote merged exposition {args.prom}", file=sys.stderr)
    if args.json:
        print(json.dumps({"ranks": healths, "errors": errors}, default=str))
    return 1 if errors else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="igg_top.py",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("endpoints", nargs="*", help="host:port endpoints")
    ap.add_argument("--endpoints-file", help="file of host:port lines")
    ap.add_argument("--dir", help="telemetry dir holding liveplane.p*.json")
    ap.add_argument("--watch", type=float, metavar="SECONDS",
                    help="refresh the view every SECONDS until interrupted")
    ap.add_argument("--retries", type=int, default=None, metavar="N",
                    help="scrape retries with exponential backoff before an "
                         "endpoint is declared UNREACHABLE (default: "
                         "IGG_FLEET_SCRAPE_RETRIES or 2)")
    ap.add_argument("--prom", help="write the merged rank-labeled exposition")
    ap.add_argument("--json", action="store_true",
                    help="also print the cluster health view as one JSON line")
    args = ap.parse_args(argv)
    try:
        endpoints = discover_endpoints(args)
    except (OSError, ValueError) as e:
        print(f"igg_top: {e}", file=sys.stderr)
        return 2
    if not args.watch:
        return one_view(args, endpoints)
    try:
        while True:
            print("\x1b[2J\x1b[H", end="")  # clear screen, home cursor
            one_view(args, endpoints)
            time.sleep(args.watch)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
