#!/usr/bin/env python
"""Static lint: every ``IGG_*`` knob must be declared and documented.

Thin CLI wrapper over the ``knob-decl`` analyzer of ``igg.analysis``
(`implicitglobalgrid_tpu/analysis/knobs.py` — the pass-registry home of
the scan since ISSUE 6; run the whole suite with ``scripts/igg_lint.py``).
The contract is unchanged: an env var read anywhere in the package that
appears in neither `utils/config.py` nor `docs/usage.md` is a knob nobody
can find (exactly how ``IGG_GATHER_BATCH`` went undocumented for two
rounds) and exits nonzero.  The tier-1 test ``tests/test_knob_lint.py``
calls `violations`/`referenced_knobs` directly and monkeypatches the path
globals below.
"""

from __future__ import annotations

import importlib.util
import os
import sys
import types

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
PACKAGE = os.path.join(REPO, "implicitglobalgrid_tpu")
CONFIG = os.path.join(PACKAGE, "utils", "config.py")
USAGE = os.path.join(REPO, "docs", "usage.md")


def _load_knobs_standalone():
    """Load `analysis/knobs.py` (+ its `core` dependency) WITHOUT importing
    the package: this lint must keep working — and stay a millisecond text
    scan — even when the package or its jax env is broken, which is exactly
    when a standalone knob audit is most useful.  The modules are stitched
    into a synthetic package so their relative imports resolve; both are
    stdlib-only by design (analysis/core.py's layering contract)."""
    name = "_igg_analysis_standalone"
    if name in sys.modules:
        return sys.modules[f"{name}.knobs"]
    adir = os.path.join(PACKAGE, "analysis")
    pkg = types.ModuleType(name)
    pkg.__path__ = [adir]
    sys.modules[name] = pkg
    for mod in ("core", "knobs"):
        spec = importlib.util.spec_from_file_location(
            f"{name}.{mod}", os.path.join(adir, f"{mod}.py")
        )
        m = importlib.util.module_from_spec(spec)
        sys.modules[f"{name}.{mod}"] = m
        spec.loader.exec_module(m)
    return sys.modules[f"{name}.knobs"]


_knobs = _load_knobs_standalone()


def referenced_knobs() -> dict[str, list[str]]:
    """``knob -> [repo-relative files referencing it]`` over the package,
    excluding the declaration site (utils/config.py)."""
    return _knobs.referenced_knobs(REPO, PACKAGE, CONFIG)


def violations() -> list[str]:
    """Human-readable lint failures (empty = clean)."""
    return [
        f"{f.message} — {f.fix_hint}"
        for f in _knobs.knob_decl_findings(REPO, PACKAGE, CONFIG, USAGE)
    ]


def main() -> int:
    probs = violations()
    if probs:
        print("check_knobs: FAIL")
        for p in probs:
            print(f"  - {p}")
        return 1
    nrefs = len(referenced_knobs())
    print(f"check_knobs: OK ({nrefs} IGG_* knob(s) declared + documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
