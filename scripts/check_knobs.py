#!/usr/bin/env python
"""Static lint: every ``IGG_*`` knob must be declared and documented.

The configuration tier's whole value is discoverability — an env var read
deep inside a hot path that appears in neither `utils/config.py` nor
`docs/usage.md` is a knob nobody can find (exactly how ``IGG_GATHER_BATCH``
went undocumented for two rounds).  This lint closes the loop:

* scan every ``.py`` under ``implicitglobalgrid_tpu/`` (excluding
  ``utils/config.py`` itself — the declaration site) for ``IGG_[A-Z0-9_]+``
  tokens;
* each referenced knob must appear in ``utils/config.py`` (docstring table
  or accessor) AND in ``docs/usage.md``.

Run standalone (exits nonzero listing violations) or via the tier-1 test
``tests/test_knob_lint.py`` — an undocumented knob fails the suite.
"""

from __future__ import annotations

import os
import re
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
PACKAGE = os.path.join(REPO, "implicitglobalgrid_tpu")
CONFIG = os.path.join(PACKAGE, "utils", "config.py")
USAGE = os.path.join(REPO, "docs", "usage.md")

_KNOB = re.compile(r"IGG_[A-Z0-9_]+")


def _read(path: str) -> str:
    with open(path, encoding="utf-8") as f:
        return f.read()


def referenced_knobs() -> dict[str, list[str]]:
    """``knob -> [repo-relative files referencing it]`` over the package,
    excluding the declaration site (utils/config.py)."""
    refs: dict[str, list[str]] = {}
    for dirpath, dirnames, filenames in os.walk(PACKAGE):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            if os.path.samefile(path, CONFIG):
                continue
            rel = os.path.relpath(path, REPO)
            for knob in set(_KNOB.findall(_read(path))):
                refs.setdefault(knob, []).append(rel)
    return {k: sorted(v) for k, v in sorted(refs.items())}


def violations() -> list[str]:
    """Human-readable lint failures (empty = clean)."""
    declared = set(_KNOB.findall(_read(CONFIG)))
    documented = set(_KNOB.findall(_read(USAGE)))
    out = []
    for knob, files in referenced_knobs().items():
        where = ", ".join(files)
        if knob not in declared:
            out.append(
                f"{knob} (referenced in {where}) is not declared in "
                f"implicitglobalgrid_tpu/utils/config.py — add it to the "
                f"knob table (and an accessor if it is read per call)"
            )
        if knob not in documented:
            out.append(
                f"{knob} (referenced in {where}) is not documented in "
                f"docs/usage.md — add a row to the env-var table"
            )
    return out


def main() -> int:
    probs = violations()
    if probs:
        print("check_knobs: FAIL")
        for p in probs:
            print(f"  - {p}")
        return 1
    nrefs = len(referenced_knobs())
    print(f"check_knobs: OK ({nrefs} IGG_* knob(s) declared + documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
