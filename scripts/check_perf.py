#!/usr/bin/env python
"""check_perf — the perf-regression gate over the BENCH trajectory.

Compares a candidate bench record against a reference within per-metric
tolerance bands (ROADMAP item 5: "a perf regression fails a PR the way a
collective-count regression already does").  Gated metrics are the
headline plus every nested `teff`/`teff_grad`/`members_per_s` under
``extras`` (`analysis.perf.GATED_KEYS` — the last is `bench.py batch`'s
batched-serving members/s/chip sweep).  Defaults compare the two
newest parseable committed rounds — the self-consistency check the
``bench-regression`` tier-1 pass also runs; pass ``--candidate`` to gate a
FRESH ``bench.py`` record before committing it.

Examples::

    check_perf.py                                # newest round vs previous
    check_perf.py --candidate /tmp/bench.json    # fresh record vs newest
    python bench.py | tail -1 > /tmp/b.json && check_perf.py -c /tmp/b.json
    check_perf.py --tol 0.10 --json              # tighter band, machine out

Exit code: 0 = within tolerance (waived regressions listed), 1 = at least
one unwaived metric dropped beyond tolerance, 2 = the comparison is
impossible (missing/unparseable records).  Waivers live in
``implicitglobalgrid_tpu/analysis/perf_waivers.json`` — every entry
requires a justification; ``--strict-waivers`` also fails on STALE waivers
(entries that matched nothing — the tree moved on).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="check_perf", description=__doc__)
    p.add_argument("-c", "--candidate", default=None,
                   help="candidate record (BENCH wrapper or raw bench.py "
                        "JSON; default: the newest committed round)")
    p.add_argument("--against", default=None,
                   help="reference record file (default: the newest "
                        "committed round below the candidate)")
    p.add_argument("--tol", type=float, default=None,
                   help="allowed fractional drop per metric "
                        "(default 0.15)")
    p.add_argument("--waivers", default=None,
                   help="waiver file (default: the package waiver file)")
    p.add_argument("--json", action="store_true", help="JSON verdict")
    p.add_argument("--strict-waivers", action="store_true",
                   help="stale waivers (matching nothing) also fail")
    args = p.parse_args(argv)

    sys.path.insert(0, REPO)
    from implicitglobalgrid_tpu.analysis import perf

    tol = perf.DEFAULT_TOL if args.tol is None else args.tol
    # exit 2 = "comparison impossible" covers setup failures too: a typo'd
    # path or malformed waiver file must not read as a perf regression (1)
    try:
        waivers = perf.load_waivers(args.waivers or perf.PERF_WAIVERS)
    except (OSError, ValueError) as e:
        print(f"check_perf: cannot load waivers: {e}", file=sys.stderr)
        return 2

    records, skipped = perf.load_bench_records(REPO)
    cand_round = None
    if args.candidate:
        try:
            cand = perf.parse_bench_file(args.candidate)
        except OSError as e:
            print(f"check_perf: cannot read {args.candidate}: {e}",
                  file=sys.stderr)
            return 2
        if cand is None:
            print(f"check_perf: {args.candidate} holds no parseable bench "
                  f"record", file=sys.stderr)
            return 2
    elif records:
        cand_round, cand = records[-1]
        records = records[:-1]
    else:
        print("check_perf: no parseable committed BENCH records",
              file=sys.stderr)
        return 2

    if args.against:
        try:
            ref = perf.parse_bench_file(args.against)
        except OSError as e:
            print(f"check_perf: cannot read {args.against}: {e}",
                  file=sys.stderr)
            return 2
        ref_label = args.against
        if ref is None:
            print(f"check_perf: {args.against} holds no parseable bench "
                  f"record", file=sys.stderr)
            return 2
    elif records:
        ref_round, ref = records[-1]
        ref_label = f"BENCH_r{ref_round:02d}.json"
    else:
        print("check_perf: no committed reference record to compare "
              "against", file=sys.stderr)
        return 2

    cmp = perf.compare_metrics(
        perf.gate_metrics(cand), perf.gate_metrics(ref),
        tol=tol, waivers=waivers, candidate_round=cand_round,
    )
    used = {w["waiver_index"] for w in cmp["waived"]}
    stale = [w for i, w in enumerate(waivers) if i not in used]
    verdict = {
        "ok": not cmp["regressions"]
        and not (args.strict_waivers and stale),
        "reference": ref_label,
        "tol": tol,
        **cmp,
        "stale_waivers": [w["metric"] for w in stale],
        "skipped_records": skipped,
    }
    if args.json:
        print(json.dumps(verdict, indent=2, sort_keys=True))
    else:
        for reg in cmp["regressions"]:
            print(f"REGRESSION {reg['metric']}: {reg['reference']:.2f} -> "
                  f"{reg['candidate']:.2f} GB/s ({reg['drop']:.1%} drop, "
                  f"tolerance {tol:.0%})")
        for w in cmp["waived"]:
            print(f"waived     {w['metric']}: {w['drop']:.1%} drop — "
                  f"{w['justification']}")
        for m in cmp["missing"]:
            print(f"note       {m}: present in reference, absent from "
                  f"candidate (config retired?)")
        for w in stale:
            print(f"stale      waiver for {w['metric']} matched nothing — "
                  f"remove it")
        for s in skipped:
            print(f"note       {s}: unparseable record, skipped")
        state = "FAIL" if not verdict["ok"] else "OK"
        print(f"check_perf: {state} ({cmp['checked']} metric(s) vs "
              f"{ref_label}, tol {tol:.0%})")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
