#!/usr/bin/env python
"""igg-lint — run the `igg.analysis` static-analysis suite.

Examples::

    igg_lint.py --list                      # what passes exist
    igg_lint.py --all                       # full suite (tier-1 runs this
                                            #   in-process, test_lint_suite)
    igg_lint.py knob-binding knob-decl      # a subset
    igg_lint.py --all --changed-only        # fast mode: only analyzers
                                            #   whose declared paths
                                            #   intersect `git status`
    igg_lint.py --all --json                # machine-readable report

Exit code: 0 = clean (or WARNING-only), 1 = CRITICAL/ERROR findings
(WARNINGs too under ``--strict``), 2 = an analyzer crashed.  Findings are
suppressed through the baseline file (justified suppressions only —
docs/static-analysis.md describes the workflow); ``--no-baseline`` shows
the raw findings.
"""

from __future__ import annotations

import argparse
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _ensure_devices() -> None:
    """Stage the 8-device CPU mesh before first jax use (the tier-1 test
    inherits conftest's identical staging; the traced-IR analyzers need
    a multi-device mesh to exist)."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="igg_lint", description=__doc__)
    p.add_argument("analyzers", nargs="*", help="analyzer names (see --list)")
    p.add_argument("--all", action="store_true", help="run every analyzer")
    p.add_argument("--list", action="store_true", dest="list_passes",
                   help="list available analyzers and exit")
    p.add_argument("--json", action="store_true", help="JSON report")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: the package baseline)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (show raw findings)")
    p.add_argument("--changed-only", action="store_true",
                   help="run only analyzers relevant to `git status` paths")
    p.add_argument("--strict", action="store_true",
                   help="WARNINGs also fail the run")
    args = p.parse_args(argv)

    from implicitglobalgrid_tpu import analysis

    if args.list_passes:
        from implicitglobalgrid_tpu.analysis.core import REGISTRY

        for name, spec in REGISTRY.items():
            print(f"{name:24s} [{spec.cost}]  {spec.title}")
        return 0

    if not args.all and not args.analyzers:
        p.error("name analyzers to run, or pass --all (see --list)")
    names = None if args.all else args.analyzers

    needs_trace = True
    if names is not None:
        from implicitglobalgrid_tpu.analysis.core import REGISTRY

        unknown = [n for n in names if n not in REGISTRY]
        if unknown:
            p.error(f"unknown analyzer(s): {unknown}")
        needs_trace = any(REGISTRY[n].cost == "trace" for n in names)
    if needs_trace:
        _ensure_devices()

    baseline = (
        None
        if args.no_baseline
        else (args.baseline or analysis.DEFAULT_BASELINE)
    )
    changed = analysis.changed_files(REPO) if args.changed_only else None
    report = analysis.run(
        names,
        baseline=baseline,
        changed_paths=changed,
        keep_going=True,
    )
    print(report.to_json() if args.json else report.human())
    return report.exit_code(strict=args.strict)


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    sys.exit(main())
