#!/usr/bin/env python
"""igg-lint — run the `igg.analysis` static-analysis suite.

Examples::

    igg_lint.py --list                      # what passes exist
    igg_lint.py --all                       # full suite (tier-1 runs this
                                            #   in-process, test_lint_suite)
    igg_lint.py knob-binding knob-decl      # a subset
    igg_lint.py --all --changed-only        # fast mode: only analyzers
                                            #   whose declared paths
                                            #   intersect `git status`
    igg_lint.py --all --changed-only=main   # CI mode: diff against the
                                            #   merge-base with `main` (a
                                            #   clean checkout has no
                                            #   status paths)
    igg_lint.py --all --json                # machine-readable report
    igg_lint.py --all --sarif out.sarif     # SARIF 2.1.0 for CI diff
                                            #   annotation (code scanning)

Exit code: 0 = clean (or WARNING-only), 1 = CRITICAL/ERROR findings
(WARNINGs too under ``--strict``), 2 = an analyzer crashed.  Findings are
suppressed through the baseline file (justified suppressions only —
docs/static-analysis.md describes the workflow); ``--no-baseline`` shows
the raw findings.
"""

from __future__ import annotations

import argparse
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _ensure_devices() -> None:
    """Stage the 8-device CPU mesh before first jax use (the traced-IR
    analyzers need a multi-device mesh; one shared recipe,
    `analysis.core.ensure_cpu_devices`)."""
    from implicitglobalgrid_tpu.analysis.core import ensure_cpu_devices

    ensure_cpu_devices()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="igg_lint", description=__doc__)
    p.add_argument("analyzers", nargs="*", help="analyzer names (see --list)")
    p.add_argument("--all", action="store_true", help="run every analyzer")
    p.add_argument("--list", action="store_true", dest="list_passes",
                   help="list available analyzers and exit")
    p.add_argument("--json", action="store_true", help="JSON report")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: the package baseline)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (show raw findings)")
    p.add_argument("--changed-only", nargs="?", const=True, default=None,
                   metavar="REF",
                   help="run only analyzers relevant to changed paths: "
                        "bare = `git status` (local fast mode); =REF adds "
                        "the merge-base diff against REF (CI mode, where a "
                        "clean checkout has no status paths)")
    p.add_argument("--sarif", default=None, metavar="PATH",
                   help="also write the report as SARIF 2.1.0 to PATH "
                        "('-' = stdout) for CI diff annotation")
    p.add_argument("--strict", action="store_true",
                   help="WARNINGs also fail the run")
    args = p.parse_args(argv)

    from implicitglobalgrid_tpu import analysis

    if args.list_passes:
        from implicitglobalgrid_tpu.analysis.core import REGISTRY

        for name, spec in REGISTRY.items():
            print(f"{name:24s} [{spec.cost}]  {spec.title}")
        return 0

    if not args.all and not args.analyzers:
        p.error("name analyzers to run, or pass --all (see --list)")
    names = None if args.all else args.analyzers

    needs_mesh = True
    if names is not None:
        from implicitglobalgrid_tpu.analysis.core import REGISTRY

        unknown = [n for n in names if n not in REGISTRY]
        if unknown:
            p.error(f"unknown analyzer(s): {unknown}")
        needs_mesh = any(
            REGISTRY[n].cost in ("trace", "compile") for n in names
        )
    if needs_mesh:
        try:
            _ensure_devices()
        except RuntimeError as e:
            # an environment/setup failure is a crash (2), never to be
            # read as "lint findings" (1) by an exit-code-driven consumer
            print(f"igg-lint: {e}", file=sys.stderr)
            return 2

    baseline = (
        None
        if args.no_baseline
        else (args.baseline or analysis.DEFAULT_BASELINE)
    )
    changed = None
    if args.changed_only is not None:
        ref = None if args.changed_only is True else args.changed_only
        from implicitglobalgrid_tpu.analysis.core import REGISTRY

        raw = sys.argv[1:] if argv is None else list(argv)
        explicit_ref = any(a.startswith("--changed-only=") for a in raw)
        if ref is not None and ref in REGISTRY and not explicit_ref:
            # `--changed-only knob-binding` used to mean "fast mode, run
            # knob-binding"; with the optional REF argparse would silently
            # eat the analyzer name as a git ref.  Refuse the ambiguity —
            # the literal `=` spelling (checked against the raw argv,
            # argparse normalizes both forms) stays available for a
            # branch that genuinely shares an analyzer's name.
            p.error(
                f"'--changed-only {ref}' parsed {ref!r} as a git ref, but "
                f"it names an analyzer — write `--changed-only={ref}` for "
                f"a ref of that name, or put analyzer names BEFORE the "
                f"bare --changed-only flag"
            )
        try:
            changed = analysis.changed_files(REPO, ref=ref)
        except RuntimeError as e:
            print(f"igg-lint: {e}", file=sys.stderr)
            return 2
    report = analysis.run(
        names,
        baseline=baseline,
        changed_paths=changed,
        keep_going=True,
    )
    if args.sarif:
        import json as _json

        from implicitglobalgrid_tpu.analysis.sarif import report_to_sarif

        sarif_text = _json.dumps(report_to_sarif(report), indent=2,
                                 sort_keys=True) + "\n"
        if args.sarif == "-":
            sys.stdout.write(sarif_text)
            # stdout IS the artifact now: the report must not corrupt it
            print(report.to_json() if args.json else report.human(),
                  file=sys.stderr)
            return report.exit_code(strict=args.strict)
        with open(args.sarif, "w", encoding="utf-8") as f:
            f.write(sarif_text)
    print(report.to_json() if args.json else report.human())
    return report.exit_code(strict=args.strict)


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    sys.exit(main())
