#!/usr/bin/env python
"""Fault-injection soak: diffusion3d under every IGG_FAULT_INJECT fault type.

Orchestrates child runs of the flagship model while cycling through the
fault-injection knobs (docs/robustness.md) and verifies that every fault is
*recovered* — the final field of each scenario must be bit-identical to the
fault-free baseline.  Exits nonzero on any unrecovered failure, so it can
gate a CI lane or soak a new runtime build:

    python scripts/soak.py                 # all scenarios, defaults
    python scripts/soak.py --steps 24 --scenarios halo_corrupt worker_crash

Scenarios:

* ``baseline``     — no fault; produces the reference field.
* ``init_flake``   — the first 2 `init_distributed` attempts fail
  (simulated coordinator race); ``IGG_INIT_RETRIES=3`` must bring the
  runtime up anyway.
* ``halo_corrupt`` — a NaN is injected into one block mid-run; the
  ``guard_every=1`` probe must trip and ``policy=rollback`` must finish
  the run finite and bit-identical.
* ``worker_crash`` — the process hard-exits (status 17) right after a
  checkpoint; the orchestrator restarts it against the same checkpoint
  directory and the resumed run must complete bit-identical.

Each scenario runs in a fresh child process (a crash must not take the
orchestrator down, and init faults need a pristine runtime).
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

CRASH_STATUS = 17  # FaultInjector.CRASH_STATUS
SCENARIOS = ("init_flake", "halo_corrupt", "worker_crash")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# child: one guarded diffusion run
# ---------------------------------------------------------------------------


def child_main(args) -> int:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.devices}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    from implicitglobalgrid_tpu.models import diffusion3d

    grid_kwargs = {}
    if args.distributed:
        # Single-process coordinator bring-up: the init_flake scenario
        # exercises the real retry path of jax.distributed.initialize.
        grid_kwargs = dict(
            init_distributed=True,
            distributed_kwargs=dict(
                coordinator_address=f"127.0.0.1:{args.port}",
                num_processes=1,
                process_id=0,
            ),
        )
    T = diffusion3d.run(
        args.steps,
        args.nx,
        args.nx,
        args.nx,
        quiet=True,
        guard_every=1,
        guard_policy="rollback",
        checkpoint_every=2,
        checkpoint_dir=args.ckpt_dir,
        **grid_kwargs,
    )
    arr = np.asarray(T)
    if not np.isfinite(arr).all():
        print("SOAK CHILD: non-finite final field", file=sys.stderr)
        return 1
    np.save(args.out, arr)
    print("SOAK CHILD OK", flush=True)
    return 0


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------


class _Timeout:
    """Stand-in result for a child that outlived --timeout: nonzero rc plus
    whatever output the child produced, so the scenario reports FAIL with
    diagnostics instead of crashing the orchestrator."""

    returncode = -1

    def __init__(self, e: subprocess.TimeoutExpired):
        self.stdout = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        self.stderr = (
            (e.stderr or b"").decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        ) + f"\n[soak] child timed out after {e.timeout}s and was killed"


def _run_child(cmd, env, timeout):
    try:
        return subprocess.run(
            cmd, env=env, capture_output=True, text=True, timeout=timeout
        )
    except subprocess.TimeoutExpired as e:
        return _Timeout(e)


def _spawn_child(args, scenario: str, workdir: str, env_extra: dict, *, ckpt: str | None = None) -> tuple:
    import shutil

    out = os.path.join(workdir, f"{scenario}.npy")
    if ckpt is None:
        ckpt = os.path.join(workdir, f"ckpt_{scenario}")
        # A fresh scenario must not auto-resume from a previous soak's
        # checkpoints (RunGuard.start picks up anything in the dir); the
        # worker_crash RESTART leg passes its dir explicitly to reuse it.
        shutil.rmtree(ckpt, ignore_errors=True)
    env = dict(os.environ)
    env.pop("IGG_FAULT_INJECT", None)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (REPO, env.get("PYTHONPATH")) if p
    )
    env.update(env_extra)
    cmd = [
        sys.executable, os.path.abspath(__file__), "--child",
        "--steps", str(args.steps), "--nx", str(args.nx),
        "--devices", str(args.devices),
        "--ckpt-dir", ckpt, "--out", out,
    ]
    if env_extra.get("_distributed"):
        cmd += ["--distributed", "--port", str(_free_port())]
        env.pop("_distributed")
    return _run_child(cmd, env, args.timeout), out, ckpt


def _report(name: str, ok: bool, detail: str = "") -> bool:
    print(f"[soak] {name:14s} {'PASS' if ok else 'FAIL'}  {detail}".rstrip())
    return ok


def orchestrate(args) -> int:
    import numpy as np

    os.makedirs(args.workdir, exist_ok=True)
    failures = 0

    proc, base_out, _ = _spawn_child(args, "baseline", args.workdir, {})
    if proc.returncode != 0:
        print(proc.stdout, proc.stderr, sep="\n", file=sys.stderr)
        _report("baseline", False, f"rc={proc.returncode}")
        return 1
    baseline = np.load(base_out)
    _report("baseline", True, f"steps={args.steps} nx={args.nx}")

    for scenario in args.scenarios:
        if scenario == "init_flake":
            env = {
                "IGG_FAULT_INJECT": "init_flake:2",
                "IGG_INIT_RETRIES": "3",
                "IGG_INIT_BACKOFF_S": "0.05",
                "_distributed": "1",
            }
            proc, out, _ = _spawn_child(args, scenario, args.workdir, env)
            ok = proc.returncode == 0 and np.array_equal(
                np.load(out), baseline
            )
            if not _report(scenario, ok, f"rc={proc.returncode}"):
                print(proc.stdout, proc.stderr, sep="\n", file=sys.stderr)
                failures += 1

        elif scenario == "halo_corrupt":
            mid = max(1, args.steps // 2)
            env = {"IGG_FAULT_INJECT": f"halo_corrupt:step{mid}"}
            proc, out, _ = _spawn_child(args, scenario, args.workdir, env)
            ok = (
                proc.returncode == 0
                and "rolling back" in (proc.stdout + proc.stderr)
                and np.array_equal(np.load(out), baseline)
            )
            if not _report(
                scenario, ok, f"rc={proc.returncode} (guard tripped + rollback)"
            ):
                print(proc.stdout, proc.stderr, sep="\n", file=sys.stderr)
                failures += 1

        elif scenario == "worker_crash":
            mid = max(2, (args.steps // 2) // 2 * 2)  # a checkpointed step
            env = {"IGG_FAULT_INJECT": f"worker_crash:step{mid}:proc0"}
            proc, out, ckpt = _spawn_child(args, scenario, args.workdir, env)
            if proc.returncode != CRASH_STATUS:
                _report(scenario, False, f"expected crash rc={CRASH_STATUS}, got {proc.returncode}")
                print(proc.stdout, proc.stderr, sep="\n", file=sys.stderr)
                failures += 1
                continue
            # restart against the same checkpoint dir: must resume + finish
            proc2, out, _ = _spawn_child(args, scenario, args.workdir, {}, ckpt=ckpt)
            ok = (
                proc2.returncode == 0
                and "resumed from checkpoint" in (proc2.stdout + proc2.stderr)
                and np.array_equal(np.load(out), baseline)
            )
            if not _report(
                scenario, ok, f"crash rc={proc.returncode} -> restart rc={proc2.returncode}"
            ):
                print(proc2.stdout, proc2.stderr, sep="\n", file=sys.stderr)
                failures += 1

        else:
            _report(scenario, False, "unknown scenario")
            failures += 1

    print(f"[soak] {'ALL RECOVERED' if failures == 0 else f'{failures} UNRECOVERED FAILURE(S)'}")
    return 1 if failures else 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--nx", type=int, default=8)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--workdir", default=os.path.join(REPO, ".soak"))
    ap.add_argument("--scenarios", nargs="+", default=list(SCENARIOS),
                    choices=list(SCENARIOS))
    ap.add_argument("--timeout", type=int, default=600)
    # child-mode flags
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--ckpt-dir", help=argparse.SUPPRESS)
    ap.add_argument("--out", help=argparse.SUPPRESS)
    ap.add_argument("--distributed", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--port", type=int, default=0, help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.child:
        return child_main(args)
    return orchestrate(args)


if __name__ == "__main__":
    sys.exit(main())
