#!/usr/bin/env python
"""Fault-injection soak: diffusion3d under every IGG_FAULT_INJECT fault type.

Orchestrates child runs of the flagship model while cycling through the
fault-injection knobs (docs/robustness.md) and verifies that every fault is
*recovered* — the final field of each scenario must be bit-identical to the
fault-free baseline.  Exits nonzero on any unrecovered failure, so it can
gate a CI lane or soak a new runtime build:

    python scripts/soak.py                 # all scenarios, defaults
    python scripts/soak.py --steps 24 --scenarios halo_corrupt worker_crash

Scenarios:

* ``baseline``     — no fault; produces the reference field.
* ``init_flake``   — the first 2 `init_distributed` attempts fail
  (simulated coordinator race); ``IGG_INIT_RETRIES=3`` must bring the
  runtime up anyway.
* ``halo_corrupt`` — a NaN is injected into one block mid-run; the
  ``guard_every=1`` probe must trip and ``policy=rollback`` must finish
  the run finite and bit-identical.
* ``worker_crash`` — the process hard-exits (status 17) right after a
  checkpoint; the orchestrator restarts it against the same checkpoint
  directory and the resumed run must complete bit-identical.
* ``elastic_failover`` — the SUPERVISED failover drill: a real 2-process
  gloo pair runs the job (dims (2,1,1)); process 1 is crash-injected right
  after the mid-run checkpoint AND that newest generation is corrupt-injected
  (``worker_crash:stepM:proc1,ckpt_corrupt:stepM``).  The supervisor detects
  the crash, relaunches on a SHRUNK 1-process topology (same implicit global
  grid, adjusted local size) against the same checkpoint directory — the
  restart must fall back past the damaged generation to the newest valid one,
  reshard the 2-process shards elastically, and finish matching a
  never-crashed oracle in de-duplicated (nxyz_g) space.

Each scenario runs in a fresh child process (a crash must not take the
orchestrator down, and init faults need a pristine runtime).

The elastic drill runs with telemetry armed (``IGG_TELEMETRY_DIR``,
docs/observability.md): the supervisor verifies the per-rank
``events.jsonl`` timeline contains the crash, the checkpoint fallback past
the damaged generation, the elastic reshard and the recovery IN ORDER, and
that the restarted child's `igg.dump_metrics` output is valid JSON +
Prometheus text with per-step ``T_eff`` recorded — the soak consumes the
telemetry snapshot instead of private tallies.

* ``serving`` — the batched-serving smoke (ISSUE 8): a 2-slot
  `serving.ServingLoop` pool takes 4 requests, so members admit and retire
  MID-FLIGHT; one member converges on the porous PT residual mask, one
  retires on its step budget, a NaN-poisoned member is evicted without
  touching its batch-mates, and the late member runs in the freed slot.
  The orchestrator re-verifies the ``serving.*`` event schema
  (docs/observability.md) from the JSONL log.

* ``live_plane`` — the live-telemetry drill (ISSUE 11): a real 2-process
  gloo pair runs with ``IGG_METRICS_PORT=0`` (ephemeral per-rank scrape
  servers, discovered via the ``liveplane.p*.json`` endpoint files) and a
  ``stall:stepN:proc1`` fault armed.  The orchestrator scrapes BOTH
  ranks' ``/metrics`` + ``/healthz`` mid-run, renders one
  ``scripts/igg_top.py`` cluster view (merged rank-labeled exposition +
  per-rank summary), and verifies the injected stall fires a rank-tagged
  ``alert.step_stall`` on the stalled rank — visible in the scraped
  health view WHILE the loop is wedged (the scrape-time rule evaluation)
  and in that rank's event log afterwards.

* ``frontdoor`` — the network-facing serving drill (ISSUE 12,
  docs/serving.md): bursty multi-tenant load is driven through the REAL
  HTTP front door (`serving.FrontDoor`, ephemeral ``IGG_SERVE_PORT=0``)
  of a diffusion serving pool.  The supervisor proves, in one run:
  (a) admission control is LIVE — an injected serving-thread stall
  (``stall:step1``) flips the door into SLO backpressure within one
  rule-engine tick, observed as real 429s with ``reason="slo"`` AND as
  ``igg_frontdoor_rejected_slo`` in a mid-stall ``/metrics`` scrape;
  (b) elastic scale-UP under traffic — the queue burst drives the
  `serving.autoscale.Autoscaler` to checkpoint and exit with
  ``RESIZE_STATUS``; the supervisor relaunches as a 2-process gloo pair
  whose `FrontDoor.elastic_resume` reshards the batched pool, re-adopts
  every live member mid-budget and rebuilds the queued ones, while new
  requests keep arriving at the resized door; (c) graceful scale-DOWN —
  once the queue drains the autoscaler drains the retiring slots and
  resizes back to one process, live members crossing topologies again;
  (d) ZERO members dropped and every request's final field BIT-IDENTICAL
  to an undisturbed fixed-topology oracle (per-field sha256 digests of
  the de-duplicated global state); (e) p50/p99 submit→result latency and
  rounds/s recorded (``frontdoor_soak.json``) — the same metric names
  ``bench.py``'s ``frontdoor_serving`` extra gates.

* ``chaos`` — the self-healing drill (docs/robustness.md): a SEEDED
  randomized multi-fault storm (``IGG_FAULT_INJECT=chaos:seed=N:rate=R``,
  sampling crash + stall + ckpt_corrupt + net_delay) over a real
  2-process gloo pair owned end to end by `igg.supervisor.RunSupervisor`.
  The supervisor polls liveness + the per-rank liveplane ``/healthz``
  endpoints, classifies every failure, restarts in place (one strike),
  then shrinks elastically to 1 process once the strikes are spent —
  pruning fired faults from each relaunch and fencing every superseded
  generation.  Acceptance: both recovery legs exercised, the final
  gathered dedup-space field BIT-IDENTICAL to an undisturbed oracle, and
  the detect → classify → recover event ORDER verified from the per-rank
  ``events.jsonl`` timeline.

* ``sdc`` — the silent-data-corruption drill (ISSUE 18,
  docs/robustness.md): a deterministic ``bit_flip`` storm through every
  tier of the integrity plane (``IGG_INTEGRITY=1``,
  ``IGG_INTEGRITY_EVERY=1``) over a supervised 2-process gloo pair
  running the HOST-path step the transport checksums cover.  One flip
  per placement, each caught by exactly its intended detector:
  (a) a transport-placement flip on rank 0's wire trips the RECEIVER's
  checksum check on rank 1, whose ``reason="sdc"`` flight bundle
  implicates the SENDER — the supervisor classifies
  ``silent_corruption`` and quarantines rank 0 on the FIRST offense;
  (b) a state-placement flip in the shrunk restart is caught by the
  shadow-step audit BEFORE the corrupt state reaches a checkpoint —
  second quarantine; (c) a checkpoint-placement flip (CRC-clean, flipped
  AFTER the lineage digests) poisons a generation silently, a crash
  follows, and the relaunch's lineage verification convicts the poisoned
  generation and falls back past it (``checkpoint.fallback``).
  Acceptance: the detector → classify → quarantine chain in order for
  both in-flight detectors, the final de-duplicated field BIT-IDENTICAL
  to an undisturbed oracle, and the oracle doubling as the clean leg —
  the whole plane armed, ZERO false positives (audits > 0, mismatch
  counters pinned at 0 in its `igg.dump_metrics` record).

The ``elastic_failover``, ``frontdoor``, ``chaos`` and ``sdc`` scenarios
are thin wrappers over `igg.supervisor` — the spawn/watch/classify/
relaunch logic lives in the package, the drills keep only their load
generators and acceptance checks.

``--quick`` runs the ``elastic_failover`` drill, the ``serving`` smoke,
the ``live_plane`` drill, the ``frontdoor`` drill, the ``chaos`` storm,
the ``fleet`` drill (multi-pool failure domains behind one
health-routed door + SLO-gated canary rollout, ISSUE 16) and the ``sdc``
drill (bit-flip storm through the integrity plane, ISSUE 18) at small
size — the fast smoke path (registered next to the tier-1 command in
docs/testing.md).  Scenarios can also be named positionally:
``python scripts/soak.py chaos --quick`` runs just the chaos drill at
quick sizing; ``--list`` prints every scenario with a one-line
description.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

CRASH_STATUS = 17   # FaultInjector.CRASH_STATUS
RESIZE_STATUS = 19  # serving.frontdoor.RESIZE_STATUS
SCENARIOS = ("init_flake", "halo_corrupt", "worker_crash",
             "elastic_failover", "serving", "live_plane", "frontdoor",
             "chaos", "fleet", "sdc")
SCENARIO_DESCRIPTIONS = {
    "init_flake": "transient init failure -> bounded retry, result == baseline",
    "halo_corrupt": "injected halo corruption -> guard trip + checkpoint rollback",
    "worker_crash": "mid-run crash -> restart resumes from checkpoint, bit-identical",
    "elastic_failover": "supervised crash -> corrupt-generation fallback -> shrunk-topology restart",
    "serving": "batched serving loop smoke: mid-flight admit/retire, convergence masking",
    "live_plane": "mid-run endpoint scrape + stall alert through the live plane",
    "frontdoor": "HTTP load + stall backpressure + elastic scale-up/down, digests == oracle",
    "chaos": "seeded multi-fault storm through the self-healing supervisor",
    "fleet": "chaos-killed pool re-routed behind one door + SLO-gated canary rollout",
    "sdc": "bit-flip storm: every integrity detector trips, liars quarantined, "
           "poisoned generation skipped, clean leg pins zero false positives",
}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# child: one guarded diffusion run
# ---------------------------------------------------------------------------


def child_main(args) -> int:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.devices}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    from implicitglobalgrid_tpu.models import diffusion3d

    grid_kwargs = {}
    if args.distributed:
        # Single-process coordinator bring-up: the init_flake scenario
        # exercises the real retry path of jax.distributed.initialize.
        grid_kwargs = dict(
            init_distributed=True,
            distributed_kwargs=dict(
                coordinator_address=f"127.0.0.1:{args.port}",
                num_processes=1,
                process_id=0,
            ),
        )
    T = diffusion3d.run(
        args.steps,
        args.nx,
        args.nx,
        args.nx,
        quiet=True,
        guard_every=1,
        guard_policy="rollback",
        checkpoint_every=2,
        checkpoint_dir=args.ckpt_dir,
        **grid_kwargs,
    )
    arr = np.asarray(T)
    if not np.isfinite(arr).all():
        print("SOAK CHILD: non-finite final field", file=sys.stderr)
        return 1
    np.save(args.out, arr)
    print("SOAK CHILD OK", flush=True)
    return 0


def child_elastic_main(args) -> int:
    """One worker of the elastic-failover drill.

    ``--nproc 2`` = one member of the gloo pair (dims (2,1,1), local
    ``nx^3``); ``--nproc 1`` = the single-process topology spanning the SAME
    implicit global grid (local ``(2*nx-2, nx, nx)``) — the oracle run, or
    the shrunk restart when ``--ckpt-dir`` points at the pair's directory.
    """
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=1"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    pid = args.pair_id
    if args.nproc > 1:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.utils import resilience

    resilience.arm_watchdog(max(30, args.timeout - 40), exit=True)
    if args.nproc > 1:
        nxyz = (args.nx, args.nx, args.nx)
        grid_kwargs = dict(
            init_distributed=True,
            distributed_kwargs=dict(
                coordinator_address=f"127.0.0.1:{args.port}",
                num_processes=args.nproc,
                process_id=pid,
            ),
        )
    else:
        # same nxyz_g as the pair's (2,1,1) decomposition: 2*(nx-2)+2
        nxyz = (2 * args.nx - 2, args.nx, args.nx)
        grid_kwargs = {}
    igg.init_global_grid(*nxyz, quiet=(pid != 0), **grid_kwargs)

    if args.expect_resume_step >= 0:
        latest = igg.latest_checkpoint(args.ckpt_dir)
        want = f"step_{args.expect_resume_step:08d}"
        assert latest is not None and latest.endswith(want), (
            f"expected the restart to fall back to the valid {want} "
            f"generation, found {latest!r}"
        )

    state, params = diffusion3d.setup(*nxyz, init_grid=False)
    step = diffusion3d.make_step(params)
    guard = resilience.RunGuard(
        checkpoint_every=2 if args.ckpt_dir else 0,
        checkpoint_dir=args.ckpt_dir,
        names=("T", "Cp"),
    )
    from implicitglobalgrid_tpu.utils.telemetry import teff_bytes

    state = resilience.guarded_time_loop(
        step, state, args.steps, guard=guard, sync_every_step=True,
        model="diffusion3d", bytes_per_step=teff_bytes(state[:1]),
    )
    T = diffusion3d.temperature(state)
    dd = igg.gather(T, dedup=True, root=0)
    if jax.process_index() == 0:
        assert dd is not None and np.isfinite(dd).all()
        np.save(args.out, dd)
        # The machine-readable run record (docs/observability.md): registry
        # snapshot as JSON + Prometheus text next to the field.
        igg.dump_metrics(args.out + ".metrics")
    # Per-rank span file into IGG_TELEMETRY_DIR (no-op when unarmed): the
    # orchestrator merges and validates the Chrome trace (--quick gate).
    igg.dump_trace()
    igg.finalize_global_grid()
    print("SOAK CHILD OK", flush=True)
    return 0


def child_sdc_main(args) -> int:
    """One worker of the sdc drill: a guarded diffusion-like run whose
    exchange goes through the HOST-path `igg.update_halo` entry — the
    surface the transport checksums cover (the models' fused steps trace
    the exchange inside the jitted program, where the in-program variant
    carries no checksum words).  ``--nproc 2`` = one member of the gloo
    pair (dims (2,1,1), local ``nx^3``); ``--nproc 1`` = the
    single-process topology spanning the SAME implicit global grid — the
    oracle/clean leg, or the shrunk quarantine restart."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=1"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    pid = args.pair_id
    if args.nproc > 1:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.utils import resilience

    resilience.arm_watchdog(max(30, args.timeout - 40), exit=True)
    if args.nproc > 1:
        nxyz = (args.nx, args.nx, args.nx)
        grid_kwargs = dict(
            init_distributed=True,
            distributed_kwargs=dict(
                coordinator_address=f"127.0.0.1:{args.port}",
                num_processes=args.nproc,
                process_id=pid,
            ),
        )
    else:
        # same nxyz_g as the pair's (2,1,1) decomposition: 2*(nx-2)+2
        nxyz = (2 * args.nx - 2, args.nx, args.nx)
        grid_kwargs = {}
    igg.init_global_grid(*nxyz, quiet=(pid != 0), **grid_kwargs)

    # The diffusion model's initial condition under a hand-rolled step:
    # jitted per-block interior update (`igg.stencil`), then the
    # checksummed global exchange on the committed fields.  The update is
    # functional on the PRE-step values and the halos entering step k hold
    # step k-1's committed neighbor planes, so the 2-process and 1-process
    # topologies stay bit-identical in dedup space — the cross-topology
    # resume the quarantine ladder depends on.
    state, _params = diffusion3d.setup(*nxyz, init_grid=False)

    @igg.stencil
    def interior(T, Cp):
        avg = (
            T[:-2, 1:-1, 1:-1] + T[2:, 1:-1, 1:-1]
            + T[1:-1, :-2, 1:-1] + T[1:-1, 2:, 1:-1]
            + T[1:-1, 1:-1, :-2] + T[1:-1, 1:-1, 2:]
        ) / 6.0
        mid = T[1:-1, 1:-1, 1:-1]
        T = T.at[1:-1, 1:-1, 1:-1].set(
            mid + 0.1 * Cp[1:-1, 1:-1, 1:-1] * (avg - mid)
        )
        return T, Cp

    def step(T, Cp):
        T, Cp = interior(T, Cp)
        return igg.update_halo(T, Cp)  # HOST path: the checksummed plane

    guard = resilience.RunGuard(
        checkpoint_every=2 if args.ckpt_dir else 0,
        checkpoint_dir=args.ckpt_dir,
        names=("T", "Cp"),
    )
    from implicitglobalgrid_tpu.utils.telemetry import teff_bytes

    state = resilience.guarded_time_loop(
        step, state, args.steps, guard=guard, sync_every_step=True,
        model="diffusion3d", bytes_per_step=teff_bytes(state[:1]),
    )
    T = diffusion3d.temperature(state)
    dd = igg.gather(T, dedup=True, root=0)
    if jax.process_index() == 0:
        assert dd is not None and np.isfinite(dd).all()
        np.save(args.out, dd)
        # counters the orchestrator's clean-leg acceptance reads:
        # integrity.audits > 0, *_mismatches == 0
        igg.dump_metrics(args.out + ".metrics")
    igg.finalize_global_grid()
    print("SOAK CHILD OK", flush=True)
    return 0


def child_serving_main(args) -> int:
    """The batched-serving smoke (ISSUE 8): a `serving.ServingLoop` slot
    pool must admit and retire members MID-FLIGHT — more requests than
    slots, per-member convergence masking (porous PT residual), a NaN
    member evicted without touching its batch-mates — with the event
    timeline proving the order.  Asserts in-child; the orchestrator
    re-verifies the event schema from the JSONL log."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.devices}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import porous_convection3d as porous
    from implicitglobalgrid_tpu.serving import Request, ServingLoop

    nx = args.nx
    igg.init_global_grid(nx, nx, nx, quiet=True)
    _, params = porous.setup(nx, nx, nx, init_grid=False, npt=3)
    loop = ServingLoop(porous, params, capacity=2, steps_per_round=1)

    def member(scale):
        s, _ = porous.setup(nx, nx, nx, init_grid=False, npt=3,
                            ic_scale=scale)
        return s

    # 4 requests through 2 slots: member 0 converges on a loose residual
    # tolerance, member 1 retires on its step budget, member 2 is poisoned
    # (evicted), member 3 is only admitted once a slot frees MID-FLIGHT.
    m_conv = loop.submit(Request(state=member(1.0), max_steps=50, tol=1.0,
                                 tenant="conv"))
    m_budget = loop.submit(Request(state=member(0.7), max_steps=2,
                                   tenant="budget"))
    bad = member(0.5)
    bad_T = np.asarray(bad[0]).copy()
    bad_T[(1,) * bad_T.ndim] = np.nan
    from jax.sharding import NamedSharding, PartitionSpec as P

    gg = igg.get_global_grid()
    badt = jax.device_put(
        bad_T, NamedSharding(gg.mesh, P(*igg.AXIS_NAMES[: bad_T.ndim]))
    )
    m_bad = loop.submit(Request(state=(badt,) + tuple(bad[1:]), max_steps=9,
                                tenant="bad"))
    m_late = loop.submit(Request(state=member(0.9), max_steps=2,
                                 tenant="late"))
    results = loop.run(max_rounds=60)
    assert results[m_conv].status == "converged", results[m_conv]
    assert results[m_budget].status == "completed", results[m_budget]
    assert results[m_bad].status == "evicted", results[m_bad]
    assert results[m_late].status == "completed", results[m_late]
    # Mid-flight admission: the late member entered a slot AFTER the pool
    # had already retired someone (queue > capacity forces it).
    assert loop.rounds > 1 and len(results) == 4
    for m, r in results.items():
        if r.state is not None:
            assert all(np.isfinite(np.asarray(A)).all() for A in r.state), m
    snap = igg.telemetry_snapshot()
    c = snap["counters"]
    assert c.get("serving.admitted_total") == 4, c
    assert c.get("serving.retired_total") == 4, c
    assert c.get("serving.evicted_total") == 1, c
    assert c.get("serving.converged_total") == 1, c
    igg.finalize_global_grid()
    print("SOAK SERVING OK", flush=True)
    return 0


def child_live_main(args) -> int:
    """One worker of the live-plane drill: a 2-process gloo member running
    instrumented diffusion with the scrape server on an ephemeral port.
    The orchestrator injects the stall (``IGG_FAULT_INJECT``), scrapes the
    endpoints mid-run and does all verification; this child just runs."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=1"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.utils import resilience
    from implicitglobalgrid_tpu.utils.telemetry import teff_bytes

    pid = args.pair_id
    resilience.arm_watchdog(max(30, args.timeout - 40), exit=True)
    igg.init_global_grid(
        args.nx, args.nx, args.nx, quiet=(pid != 0),
        init_distributed=True,
        distributed_kwargs=dict(
            coordinator_address=f"127.0.0.1:{args.port}",
            num_processes=2,
            process_id=pid,
        ),
    )
    state, params = diffusion3d.setup(args.nx, args.nx, args.nx,
                                      init_grid=False)
    step = diffusion3d.make_step(params)
    # No guard cadence needed: the armed stall injector alone enables the
    # per-step pipeline (RunGuard.enabled), and the live plane rides the
    # telemetry hooks.
    guard = resilience.RunGuard(names=("T", "Cp"))
    state = resilience.guarded_time_loop(
        step, state, args.steps, guard=guard, sync_every_step=True,
        model="diffusion3d", bytes_per_step=teff_bytes(state[:1]),
    )
    # this rank's shards only: the global array spans both processes
    for shard in state[0].addressable_shards:
        assert np.isfinite(np.asarray(shard.data)).all()
    igg.finalize_global_grid()
    print("SOAK CHILD OK", flush=True)
    return 0


def _frontdoor_grid_args(args):
    """(nxyz, grid_kwargs) for one frontdoor worker at ``args.nproc`` —
    the same implied global grid at every rung (the elastic contract):
    2-proc dims (2,1,1) local ``nx^3``; 1-proc local ``(2*nx-2, nx, nx)``."""
    if args.nproc > 1:
        return (args.nx, args.nx, args.nx), dict(
            init_distributed=True,
            distributed_kwargs=dict(
                coordinator_address=f"127.0.0.1:{args.port}",
                num_processes=args.nproc,
                process_id=args.pair_id,
            ),
        )
    return (2 * args.nx - 2, args.nx, args.nx), {}


def child_frontdoor_main(args) -> int:
    """One serving process of the frontdoor drill: pool + front door at the
    given rung, optionally elastically resumed from the resize checkpoint.
    Exits 0 on a broadcast shutdown, RESIZE_STATUS after writing a resize
    plan — the supervisor relaunches at the plan's topology."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=1"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    if args.nproc > 1:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.serving import (
        RESIZE_STATUS as _RS, AutoscalePolicy, Autoscaler, FrontDoor, Rung,
        ServingLoop,
    )
    from implicitglobalgrid_tpu.utils import resilience

    pid = args.pair_id
    resilience.arm_watchdog(max(30, args.timeout - 40), exit=True)
    nxyz, grid_kwargs = _frontdoor_grid_args(args)
    igg.init_global_grid(*nxyz, quiet=(pid != 0), **grid_kwargs)
    _, params = diffusion3d.setup(*nxyz, init_grid=False)
    ladder = [
        Rung(*(int(x) for x in rung.split(":")))
        for rung in args.ladder.split(",")
    ]
    loop = ServingLoop(diffusion3d, params, capacity=args.capacity,
                       steps_per_round=1)
    policy = AutoscalePolicy.from_env(ladder)
    fd = FrontDoor(
        loop,
        checkpoint_dir=args.ckpt_dir,
        autoscaler=Autoscaler(policy, rung=args.rung),
    )
    if args.resume:
        assert fd.elastic_resume(), "resume requested but no checkpoint found"
    outcome = fd.serve_rounds(idle_sleep=0.05)
    fd.close()
    igg.dump_trace()
    igg.finalize_global_grid()
    print(f"SOAK FRONTDOOR CHILD {outcome}", flush=True)
    return _RS if outcome == "resize" else 0


def child_frontdoor_oracle(args) -> int:
    """The undisturbed fixed-topology oracle: run every distinct request
    spec through a plain 1-process `ServingLoop` (no HTTP, no resizes) and
    dump each final field's digest — the bit-identity reference."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=1"
    ).strip()
    import json as _json

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.serving import Request, ServingLoop
    from implicitglobalgrid_tpu.serving.frontdoor import state_digest

    with open(args.specs) as f:
        specs = _json.load(f)  # [[ic_scale, max_steps], ...]
    nxyz = (2 * args.nx - 2, args.nx, args.nx)
    igg.init_global_grid(*nxyz, quiet=True)
    _, params = diffusion3d.setup(*nxyz, init_grid=False)
    loop = ServingLoop(diffusion3d, params, capacity=max(2, len(specs)),
                       steps_per_round=1)
    members = {}
    for ic, ms in specs:
        state, _ = diffusion3d.setup(*nxyz, init_grid=False, ic_scale=ic)
        members[f"{ic}:{ms}"] = loop.submit(
            Request(state=state, max_steps=int(ms))
        )
    loop.run(max_rounds=10 * max(ms for _, ms in specs))
    digests = {}
    for key, m in members.items():
        res = loop.results[m]
        assert res.status == "completed", (key, res.status)
        digests[key] = state_digest(res.state)["fields"]
    with open(args.out, "w") as f:
        _json.dump(digests, f)
    igg.finalize_global_grid()
    print("SOAK FRONTDOOR ORACLE OK", flush=True)
    return 0


def child_fleet_pool_main(args) -> int:
    """One fleet pool: a single-process `ServingLoop` behind its own
    `FrontDoor`, spawned/fenced/killed by the fleet controller (the
    ``fleet`` drill).  ``--round-sleep S`` doctors every serving round S
    seconds slower INSIDE the measured section, so the rolling
    ``serving.round_seconds`` p99 honestly reports the slowness — the
    canary-rollback leg's "bad config".  Exits 0 on the broadcast
    shutdown; the controller's SIGKILL is the other way out."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=1"
    ).strip()
    import time as _time

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.serving import FrontDoor, ServingLoop
    from implicitglobalgrid_tpu.utils import resilience

    resilience.arm_watchdog(max(30, args.timeout - 40), exit=True)
    # the same grid as the oracle child: digest bit-identity is the point
    nxyz = (2 * args.nx - 2, args.nx, args.nx)
    igg.init_global_grid(*nxyz, quiet=True)
    _, params = diffusion3d.setup(*nxyz, init_grid=False)
    loop = ServingLoop(diffusion3d, params, capacity=args.capacity,
                      steps_per_round=1)
    if args.round_sleep > 0:
        step = loop._step

        def doctored(*state):
            _time.sleep(args.round_sleep)
            return step(*state)

        loop._step = doctored
    # Periodic trace dumps (ISSUE 19): the chaos SIGKILL takes this
    # process's span ring with it, so the request-tree reconstruction
    # reads the last atomically-published trace.g<gen>.p0.json instead.
    import threading as _threading

    stop_dumper = _threading.Event()

    def _trace_dumper():
        while not stop_dumper.wait(0.25):
            try:
                igg.dump_trace()
            except Exception:  # noqa: BLE001 — a dump must never kill serving
                pass

    _threading.Thread(
        target=_trace_dumper, name="igg-trace-dumper", daemon=True
    ).start()
    fd = FrontDoor(loop)
    outcome = fd.serve_rounds(idle_sleep=0.02)
    stop_dumper.set()
    igg.dump_trace()  # final flush: the shutdown path's spans
    fd.close()
    igg.finalize_global_grid()
    print(f"SOAK FLEET POOL {outcome}", flush=True)
    return 0


class _DoorClient:
    """Tiny HTTP client for the drill: submit with 429-aware retries, poll
    results, scrape metrics — everything deadline-bounded."""

    def __init__(self, endpoint: str):
        self.endpoint = endpoint

    def _url(self, path):
        return f"http://{self.endpoint}{path}"

    def post(self, path, doc):
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            self._url(path), data=__import__("json").dumps(doc).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=5) as r:
                return r.status, __import__("json").load(r)
        except urllib.error.HTTPError as e:
            try:
                return e.code, __import__("json").load(e)
            except ValueError:
                return e.code, {}
        except OSError:
            # door down (mid-resize restart): report unreachable, let the
            # caller retry against the next phase's endpoint
            return 0, {}

    def get(self, path):
        import urllib.request

        with urllib.request.urlopen(self._url(path), timeout=5) as r:
            body = r.read()
        try:
            return __import__("json").loads(body)
        except ValueError:
            return body.decode()

    def metrics_text(self) -> str:
        import urllib.request

        with urllib.request.urlopen(self._url("/metrics"), timeout=5) as r:
            return r.read().decode()


def supervise_frontdoor(args) -> bool:
    """The frontdoor drill (module docstring): three phases across two
    elastic resizes — now a thin wrapper over
    `igg.supervisor.RunSupervisor`: the subsystem owns spawn/liveness/
    resize-plan handling/relaunch (a ``resize`` classification maps onto
    the ladder through ``on_resize``), while this wrapper keeps only the
    drill-specific load generator, the stall-driven backpressure check and
    the digest acceptance, injected per incarnation via the ``drive``
    hook."""
    import json as _json
    import shutil
    import time as _time

    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from implicitglobalgrid_tpu import supervisor as sup

    workdir = args.workdir
    ckpt = os.path.join(workdir, "ckpt_frontdoor")
    run_dir = os.path.join(workdir, "frontdoor_run")
    tele_dir = os.path.join(workdir, "telemetry_frontdoor")
    shutil.rmtree(ckpt, ignore_errors=True)
    shutil.rmtree(run_dir, ignore_errors=True)
    shutil.rmtree(tele_dir, ignore_errors=True)
    steps = max(4, args.steps)
    cap1, cap2 = 2, 4
    ladder = f"1:{cap1},2:{cap2}"
    # request catalog: (tenant, ic_scale, max_steps).  The burst outruns
    # cap1 so the queue drives the scale-up; the two long members are
    # still LIVE when the queue later drains, so the scale-down must
    # carry them across topologies mid-budget.
    burst = [("tA", 1.0, steps), ("tB", 1.05, steps), ("tA", 1.1, steps),
             ("tC", 1.15, steps), ("tB", 1.2, steps), ("tC", 1.25, steps)]
    long_jobs = [("tA", 1.3, 3 * steps), ("tB", 1.35, 3 * steps)]
    mid_traffic = [("t2proc", 1.4, steps)]  # submitted WHILE 2-proc
    probe = ("probe", 1.0, 1)               # the stall-window hammer
    all_specs = sorted({(ic, ms) for _, ic, ms in
                        burst + long_jobs + mid_traffic + [probe]})

    # (0) the undisturbed oracle's digests
    specs_path = os.path.join(workdir, "frontdoor_specs.json")
    oracle_out = os.path.join(workdir, "frontdoor_oracle.json")
    with open(specs_path, "w") as f:
        _json.dump([list(s) for s in all_specs], f)
    proc = _run_child(
        [sys.executable, os.path.abspath(__file__), "--frontdoor-oracle",
         "--nx", str(args.nx), "--specs", specs_path, "--out", oracle_out],
        _elastic_env({}), args.timeout,
    )
    if proc.returncode != 0:
        print(proc.stdout, proc.stderr, sep="\n", file=sys.stderr)
        return _report("frontdoor", False, f"oracle rc={proc.returncode}")
    with open(oracle_out) as f:
        oracle = _json.load(f)

    endpoint_file = os.path.join(tele_dir, "frontdoor.p0.json")
    accepted: dict[str, dict] = {}  # rid -> {tenant, ic, ms, t}
    done: dict[str, dict] = {}
    to_submit: list[tuple] = []     # load not yet 202-accepted; survives
    resize_plans: list[dict] = []   # phase transitions (a resize may land
    slo_429 = None                  # mid-burst — leftovers hit the next door)
    slo_metrics_seen = False
    shutdown_sent = False
    final_status = None
    # launch parameters the supervisor's command_for/on_resize drive: the
    # autoscale rung/capacity ride the workload's own resize plans
    fdstate = {"phase": 0, "capacity": cap1, "as_rung": 0, "resume": False,
               "port": 0, "gen": None}

    def command_for(rank, nranks, rung, gen):
        if fdstate["gen"] != gen:
            fdstate["gen"] = gen
            fdstate["port"] = _free_port() if nranks > 1 else 0
        return [
            sys.executable, os.path.abspath(__file__), "--frontdoor-child",
            "--nx", str(args.nx), "--steps", str(steps),
            "--nproc", str(nranks), "--pair-id", str(rank),
            "--port", str(fdstate["port"]), "--ckpt-dir", ckpt,
            "--capacity", str(fdstate["capacity"]),
            "--rung", str(fdstate["as_rung"]),
            "--resume", str(int(fdstate["resume"])), "--ladder", ladder,
            "--timeout", str(args.timeout),
        ]

    def on_resize(plan):
        resize_plans.append({k: plan[k] for k in
                             ("nproc", "capacity", "rung", "reason")
                             if k in plan})
        fdstate["capacity"] = int(plan["capacity"])
        fdstate["as_rung"] = int(plan["rung"])
        fdstate["resume"] = True
        # manager ladder: rung 0 = the 2-process (preferred) topology,
        # rung 1 = the 1-process one (the drill STARTS there)
        return 0 if int(plan["nproc"]) == 2 else 1

    def _try_submit(client, tenant, ic, ms, phase_no) -> bool:
        """ONE submit attempt; True iff 202-accepted (429/unreachable =
        not yet — the caller keeps the spec queued)."""
        code, body = client.post("/v1/submit", {
            "tenant": tenant,
            "model": "diffusion3d",
            "params": {"ic_scale": ic, "max_steps": ms},
        })
        if code == 202:
            accepted[body["request_id"]] = {
                "tenant": tenant, "ic": ic, "ms": ms,
                "t": _time.monotonic(), "phase": phase_no,
            }
            return True
        return False

    def _poll_done(client):
        for rid in list(accepted):
            if rid in done:
                continue
            try:
                view = client.get(f"/v1/result/{rid}")
            except OSError:
                return
            if isinstance(view, dict) and view.get("status") == "done":
                view["t_done"] = _time.monotonic()
                done[rid] = view

    t_drill0 = _time.monotonic()

    def drive(inc):
        """One incarnation's client work (raises RuntimeError on a drill
        failure; the supervisor reaps and reports).  Runs until every
        child of the incarnation exited — resize exits included."""
        nonlocal slo_429, slo_metrics_seen, shutdown_sent, final_status
        fdstate["phase"] += 1
        phase_no = fdstate["phase"]

        # endpoint discovery (rank 0 publishes frontdoor.p0.json; the ts
        # check skips a stale file from the previous incarnation)
        deadline = _time.monotonic() + args.timeout
        client = None
        while _time.monotonic() < deadline:
            if any(q.poll() is not None for q in inc.procs):
                raise RuntimeError(
                    f"phase {phase_no}: a child exited before opening the "
                    f"front door"
                )
            if os.path.isfile(endpoint_file):
                try:
                    with open(endpoint_file) as f:
                        doc = _json.load(f)
                    if float(doc.get("ts") or 0) >= inc.t0:
                        client = _DoorClient(f"{doc['host']}:{doc['port']}")
                        client.get("/v1/status")
                        break
                    client = None
                except (OSError, ValueError):
                    client = None
            _time.sleep(0.1)
        if client is None:
            raise RuntimeError(
                f"phase {phase_no}: front-door endpoint never became "
                f"reachable"
            )

        # phase-specific load
        if phase_no == 1:
            # two requests arm the pool (the stall fires after round 1)...
            armed = 0
            while armed < 2 and _time.monotonic() < deadline:
                if _try_submit(client, *burst[armed], phase_no):
                    armed += 1
                else:
                    _time.sleep(0.1)
            if armed < 2:
                raise RuntimeError(
                    f"phase {phase_no}: initial submissions never accepted"
                )
            # ...wait for round 1 (the stall wedges right after it) so the
            # probes below cannot pile up as pending QUEUE load and trip
            # the autoscaler before the stall leg has run...
            while _time.monotonic() < deadline:
                try:
                    if (client.get("/v1/status").get("rounds") or 0) >= 1:
                        break
                except OSError:
                    pass
                _time.sleep(0.05)
            # ...then hammer the door until the wedged serving thread shows
            # up as a LIVE 429 reason="slo" + the counter in /metrics.  The
            # wedge outlasts any resize decision (the serving thread IS the
            # decision loop), so this completes before phase 1 can end.
            while _time.monotonic() < deadline and slo_429 is None:
                if any(q.poll() is not None for q in inc.procs):
                    raise RuntimeError(
                        f"phase {phase_no}: children exited before the "
                        f"stall produced a 429"
                    )
                code, body = client.post("/v1/submit", {
                    "tenant": probe[0], "model": "diffusion3d",
                    "params": {"ic_scale": probe[1], "max_steps": probe[2]},
                })
                if code == 202:
                    accepted[body["request_id"]] = {
                        "tenant": probe[0], "ic": probe[1], "ms": probe[2],
                        "t": _time.monotonic(), "phase": phase_no,
                    }
                elif code == 429 and body.get("reason") == "slo":
                    slo_429 = body
                    if "igg_frontdoor_rejected_slo" in client.metrics_text():
                        slo_metrics_seen = True
                _time.sleep(0.1)
            if slo_429 is None:
                raise RuntimeError(
                    f"phase {phase_no}: injected stall never produced a "
                    f"429 reason=slo"
                )
            # the burst that outruns cap1 and drives the scale-up, plus the
            # two long members the scale-down must later carry live (a
            # resize may land mid-burst; leftovers hit the next door)
            to_submit.extend(burst[2:] + long_jobs)
        elif inc.nranks > 1:
            # traffic THROUGH the resized (2-process) door
            to_submit.extend(mid_traffic)

        # drive until the phase ends (resize exit or everything done)
        while _time.monotonic() < deadline:
            if to_submit and _try_submit(client, *to_submit[0], phase_no):
                to_submit.pop(0)
            _poll_done(client)
            if not inc.alive():
                break
            if (
                not shutdown_sent
                and phase_no >= 3
                and not to_submit
                and len(done) == len(accepted)
            ):
                try:
                    status = client.get("/v1/status")
                    # a resumed door answers /v1/status BEFORE
                    # elastic_resume restores the round counter: wait for
                    # the restored figure so the rounds/s record is real
                    if status.get("rounds"):
                        final_status = status
                        client.post("/v1/shutdown", {})
                        shutdown_sent = True
                except OSError:
                    pass
            _time.sleep(0.1)
        if inc.alive():
            raise RuntimeError(f"phase {phase_no}: children did not exit")

    rsup = sup.RunSupervisor(
        command_for,
        ladder=[2, 1],       # rung 0 = the 2-proc topology, rung 1 = 1-proc
        initial_rung=1,      # the drill starts small and scales up
        preferred_rung=0,
        workdir=run_dir,
        telemetry_dir=tele_dir,
        policy=sup.RecoveryPolicy(max_restarts=0, backoff_s=0.2),
        # the SLO-breach leg: wedge the serving thread after round 1 (the
        # supervisor prunes the fired stall from every later incarnation)
        fault_spec="stall:step1",
        env={
            "PYTHONPATH": _elastic_env({})["PYTHONPATH"],
            "IGG_TELEMETRY": "1", "IGG_HEARTBEAT_EVERY": "1",
            "IGG_SERVE_PORT": "0",
            "IGG_AUTOSCALE_QUEUE_HIGH": "3", "IGG_AUTOSCALE_SUSTAIN": "1",
            "IGG_FRONTDOOR_QUEUE_MAX": "64",
        },
        drive=drive,
        on_resize=on_resize,
        resize_plan_path=os.path.join(ckpt, "resize.json"),
        grace_s=30.0,
        poll_s=0.3,
        name="frontdoor",
    )
    report = rsup.run(timeout=args.timeout + 60, max_incarnations=6)
    if not report.ok:
        _dump_run_logs(run_dir)
        return _report("frontdoor", False, f"supervisor: {report.summary()}")
    bad_kinds = [i["kind"] for i in report.incidents
                 if i["kind"] not in ("healthy", "resize")]
    if bad_kinds:
        _dump_run_logs(run_dir)
        return _report("frontdoor", False,
                       f"unexpected incident kind(s) {bad_kinds}")
    if not shutdown_sent:
        return _report("frontdoor", False,
                       "the drill never reached the clean-shutdown phase")

    # -- acceptance ----------------------------------------------------------
    ups = [p for p in resize_plans if p["reason"] == "up"]
    downs = [p for p in resize_plans if "down" in p["reason"]]
    if not (ups and ups[0]["nproc"] == 2):
        return _report("frontdoor", False,
                       f"no scale-UP to 2 processes (plans: {resize_plans})")
    if not (downs and downs[0]["nproc"] == 1):
        return _report("frontdoor", False,
                       f"no scale-DOWN back to 1 process (plans: {resize_plans})")
    if not slo_metrics_seen:
        return _report("frontdoor", False,
                       "frontdoor.rejected.slo never visible in /metrics")
    missing = [rid for rid in accepted if rid not in done]
    if missing:
        return _report("frontdoor", False,
                       f"{len(missing)} accepted request(s) never completed "
                       f"(dropped members?): {missing[:5]}")
    bad = []
    for rid, meta in accepted.items():
        digest = (done[rid].get("digest") or {}).get("fields")
        want = oracle.get(f"{meta['ic']}:{meta['ms']}")
        if digest != want:
            bad.append(rid)
    if bad:
        return _report("frontdoor", False,
                       f"digest mismatch vs the undisturbed oracle: {bad}")
    if not any(m["phase"] == 2 for m in accepted.values()):
        return _report("frontdoor", False,
                       "no request was accepted during the 2-process phase")

    lat = sorted(done[rid]["t_done"] - accepted[rid]["t"] for rid in accepted)
    p50 = lat[len(lat) // 2]
    p99 = lat[min(len(lat) - 1, int(0.99 * (len(lat) - 1)))]
    rounds = (final_status or {}).get("rounds") or 0
    rps = rounds / max(1e-9, _time.monotonic() - t_drill0)
    record = {
        "requests": len(accepted),
        "submit_to_result_p50_s": round(p50, 4),
        "submit_to_result_p99_s": round(p99, 4),
        "rounds": rounds,
        "rounds_per_s": round(rps, 3),
        "resizes": len(resize_plans),
        "plans": resize_plans,
        "incidents": report.incidents,
    }
    with open(os.path.join(workdir, "frontdoor_soak.json"), "w") as f:
        _json.dump(record, f, indent=1)
    return _report(
        "frontdoor", True,
        f"{len(accepted)} requests across {len(report.incidents)} "
        f"supervised phases (up@2proc + drain/down@1proc), all digests == "
        f"oracle; stall -> 429 reason=slo (+/metrics counter); "
        f"p50 {p50:.2f}s p99 {p99:.2f}s {rps:.2f} rounds/s",
    )


def supervise_live_plane(args) -> bool:
    """The live-plane drill (module docstring): spawn the pair, discover
    the ephemeral endpoints, scrape mid-run, catch the stall alert live,
    render the igg_top cluster view, then verify the event logs."""
    import shutil
    import time as _time
    import urllib.request

    if HERE not in sys.path:
        sys.path.insert(0, HERE)
    import igg_top

    workdir = args.workdir
    tele_dir = os.path.join(workdir, "telemetry_live")
    shutil.rmtree(tele_dir, ignore_errors=True)
    mid = max(2, args.steps // 2)
    port = _free_port()
    env = _elastic_env(
        {
            "IGG_TELEMETRY": "1",
            "IGG_TELEMETRY_DIR": tele_dir,
            "IGG_METRICS_PORT": "0",
            "IGG_HEARTBEAT_EVERY": "2",
            "IGG_FAULT_INJECT": f"stall:step{mid}:proc1",
        }
    )
    logs = [
        open(os.path.join(workdir, f"live_pair{pid}.log"), "w+")
        for pid in range(2)
    ]
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--live-child",
             "--steps", str(args.steps), "--nx", str(args.nx),
             "--pair-id", str(pid), "--port", str(port),
             "--timeout", str(args.timeout)],
            env=env, stdout=logs[pid], stderr=subprocess.STDOUT, text=True,
        )
        for pid in range(2)
    ]

    def _fail(detail: str) -> bool:
        for q in procs:
            q.kill()
        for f in logs:
            f.flush()
            f.seek(0)
            print(f.read(), file=sys.stderr)
            f.close()
        return _report("live_plane", False, detail)

    # (1) endpoint discovery: both ranks publish liveplane.p<rank>.json
    # once their loops (and scrape servers) are up.
    deadline = _time.monotonic() + args.timeout
    endpoints = None
    while _time.monotonic() < deadline:
        try:
            endpoints = igg_top.discover_endpoints(
                argparse.Namespace(endpoints=[], endpoints_file=None,
                                   dir=tele_dir)
            )
            if len(endpoints) == 2:
                break
        except (OSError, ValueError):
            pass
        if any(q.poll() is not None for q in procs):
            return _fail("a child exited before publishing its endpoint")
        _time.sleep(0.1)
    if not endpoints or len(endpoints) != 2:
        return _fail(f"endpoint discovery timed out ({endpoints})")

    # (2) scrape both ranks mid-run until the injected stall's alert shows
    # in the STALLED rank's live health view (the scrape-time rule firing
    # while the loop is wedged), collecting /metrics along the way.
    metrics_ok = {0: False, 1: False}
    stall_seen = None
    cluster = None
    while _time.monotonic() < deadline:
        by_rank, _errors = igg_top.scrape_cluster(endpoints)
        for rank, res in by_rank.items():
            if "igg_diffusion3d_steps_total" in res["metrics"]:
                metrics_ok[rank] = True
            alerts = res["health"].get("alerts", {})
            for a in alerts.get("active", []) + alerts.get("recent", []):
                if a.get("rule") == "step_stall" and rank == 1:
                    stall_seen = a
                    cluster = by_rank
        if stall_seen and all(metrics_ok.values()):
            break
        if all(q.poll() is not None for q in procs):
            break
        _time.sleep(0.1)
    for q in procs:
        try:
            q.wait(timeout=args.timeout)
        except subprocess.TimeoutExpired:
            return _fail("pair did not finish after the stall")
    if any(q.returncode != 0 for q in procs):
        return _fail(f"child rc={[q.returncode for q in procs]}")
    for f in logs:
        f.close()
    if not all(metrics_ok.values()):
        return _report("live_plane", False,
                       f"/metrics never scraped from both ranks {metrics_ok}")
    if stall_seen is None:
        return _report(
            "live_plane", False,
            "alert.step_stall never appeared in rank 1's scraped /healthz "
            "during the injected stall",
        )

    # (3) ONE igg_top cluster view from the mid-run scrape: the merged
    # exposition must carry BOTH ranks' samples under rank labels, and the
    # summary table one row per rank.
    merged = igg_top.merge_expositions(
        {r: res["metrics"] for r, res in cluster.items()}
    )
    if 'rank="0"' not in merged or 'rank="1"' not in merged:
        return _report("live_plane", False,
                       "merged exposition lacks per-rank labels")
    rows = igg_top.summary_rows(
        {r: res["health"] for r, res in cluster.items()}
    )
    if len(rows) != 2:
        return _report("live_plane", False, f"cluster view rows: {rows}")

    # (4) the event-log acceptance: the stall fired a rank-tagged
    # alert.step_stall on the RIGHT rank (the event log is the durable
    # record the scraped view previewed), next to the fault marker.
    from implicitglobalgrid_tpu.utils.telemetry import read_events

    p1 = os.path.join(tele_dir, "events.p1.jsonl")
    if not os.path.isfile(p1):
        return _report("live_plane", False, f"no {p1}")
    events = read_events(p1)
    fault = [e for e in events if e.get("type") == "fault.stall"]
    alerts = [e for e in events if e.get("type") == "alert.step_stall"]
    if not fault:
        return _report("live_plane", False, "no fault.stall event on rank 1")
    if not any(e.get("rank") == 1 for e in alerts):
        return _report(
            "live_plane", False,
            f"no rank-1-tagged alert.step_stall event (saw "
            f"{[(e.get('type'), e.get('rank')) for e in events][:20]})",
        )
    return _report(
        "live_plane", True,
        f"2 ranks scraped live; stall at step {mid} -> alert.step_stall on "
        f"rank 1 (age {stall_seen['evidence'].get('age_s')}s > deadline "
        f"{stall_seen['evidence'].get('deadline_s')}s) seen in /healthz "
        f"mid-stall AND in events.p1.jsonl; igg_top merged view spans both "
        f"ranks",
    )


def _verify_serving_events(tele_dir: str) -> tuple[bool, str]:
    """Orchestrator-side check of the serving event schema
    (docs/observability.md): all four event types present, every one
    tagged with member/slot/tenant, and at least one admit AFTER the
    first retirement (the mid-flight slot reuse)."""
    import glob

    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from implicitglobalgrid_tpu.utils.telemetry import read_events

    files = sorted(glob.glob(os.path.join(tele_dir, "events*.jsonl")))
    if not files:
        return False, f"no events*.jsonl under {tele_dir}"
    events = [e for f in files for e in read_events(f)]
    serving = [e for e in events if str(e.get("type", "")).startswith("serving.")]
    kinds = {e["type"] for e in serving}
    need = {"serving.admit", "serving.retire", "serving.converged",
            "serving.evict"}
    if not need <= kinds:
        return False, f"missing event type(s) {sorted(need - kinds)}"
    for e in serving:
        if any(k not in e for k in ("member", "slot", "tenant")):
            return False, f"event {e['type']} missing member/slot/tenant tags"
    serving.sort(key=lambda e: e["ts"])
    first_retire = next(
        i for i, e in enumerate(serving) if e["type"] != "serving.admit"
    )
    if not any(
        e["type"] == "serving.admit" for e in serving[first_retire:]
    ):
        return False, "no mid-flight admission (admit after a retirement)"
    return True, (
        f"{len(serving)} serving events: admit/retire/converged/evict all "
        f"present, mid-flight admission confirmed"
    )


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------


class _Timeout:
    """Stand-in result for a child that outlived --timeout: nonzero rc plus
    whatever output the child produced, so the scenario reports FAIL with
    diagnostics instead of crashing the orchestrator."""

    returncode = -1

    def __init__(self, e: subprocess.TimeoutExpired):
        self.stdout = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        self.stderr = (
            (e.stderr or b"").decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        ) + f"\n[soak] child timed out after {e.timeout}s and was killed"


def _run_child(cmd, env, timeout):
    try:
        return subprocess.run(
            cmd, env=env, capture_output=True, text=True, timeout=timeout
        )
    except subprocess.TimeoutExpired as e:
        return _Timeout(e)


def _spawn_child(args, scenario: str, workdir: str, env_extra: dict, *, ckpt: str | None = None) -> tuple:
    import shutil

    out = os.path.join(workdir, f"{scenario}.npy")
    if ckpt is None:
        ckpt = os.path.join(workdir, f"ckpt_{scenario}")
        # A fresh scenario must not auto-resume from a previous soak's
        # checkpoints (RunGuard.start picks up anything in the dir); the
        # worker_crash RESTART leg passes its dir explicitly to reuse it.
        shutil.rmtree(ckpt, ignore_errors=True)
    env = dict(os.environ)
    env.pop("IGG_FAULT_INJECT", None)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (REPO, env.get("PYTHONPATH")) if p
    )
    env.update(env_extra)
    cmd = [
        sys.executable, os.path.abspath(__file__), "--child",
        "--steps", str(args.steps), "--nx", str(args.nx),
        "--devices", str(args.devices),
        "--ckpt-dir", ckpt, "--out", out,
    ]
    if env_extra.get("_distributed"):
        cmd += ["--distributed", "--port", str(_free_port())]
        env.pop("_distributed")
    return _run_child(cmd, env, args.timeout), out, ckpt


def _report(name: str, ok: bool, detail: str = "") -> bool:
    print(f"[soak] {name:14s} {'PASS' if ok else 'FAIL'}  {detail}".rstrip())
    return ok


def _elastic_cmd(args, *, nproc, pair_id, port, ckpt, out, expect_resume=-1):
    return [
        sys.executable, os.path.abspath(__file__), "--elastic-child",
        "--steps", str(args.steps), "--nx", str(args.nx),
        "--nproc", str(nproc), "--pair-id", str(pair_id),
        "--port", str(port), "--timeout", str(args.timeout),
        "--ckpt-dir", ckpt or "", "--out", out or "",
        "--expect-resume-step", str(expect_resume),
    ]


def _sdc_cmd(args, *, nproc, pair_id, port, ckpt, out):
    return [
        sys.executable, os.path.abspath(__file__), "--sdc-child",
        "--steps", str(args.steps), "--nx", str(args.nx),
        "--nproc", str(nproc), "--pair-id", str(pair_id),
        "--port", str(port), "--timeout", str(args.timeout),
        "--ckpt-dir", ckpt or "", "--out", out or "",
    ]


def _elastic_env(env_extra: dict) -> dict:
    env = dict(os.environ)
    env.pop("IGG_FAULT_INJECT", None)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (REPO, env.get("PYTHONPATH")) if p
    )
    env.update(env_extra)
    return env


def _verify_elastic_telemetry(tele_dir: str, got_out: str) -> tuple[bool, str]:
    """The drill's machine-readable acceptance (docs/observability.md).

    The per-rank ``events.jsonl`` files must contain the crash, the
    checkpoint fallback past the damaged generation, the elastic reshard
    and the recovery IN ORDER (absolute timestamps make the cross-process
    timeline sortable), and the restart's `igg.dump_metrics` output must be
    valid JSON + Prometheus text with per-step ``T_eff`` recorded.
    """
    import glob
    import json

    if REPO not in sys.path:  # the orchestrator runs from anywhere
        sys.path.insert(0, REPO)
    from implicitglobalgrid_tpu.utils.telemetry import read_events

    files = sorted(glob.glob(os.path.join(tele_dir, "events*.jsonl")))
    if not files:
        return False, f"no events*.jsonl under {tele_dir}"
    events = [e for f in files for e in read_events(f)]
    # Tag check BEFORE the ts sort: a malformed line must yield this report,
    # not a KeyError/TypeError out of sorted().
    if any(
        "rank" not in e or not isinstance(e.get("ts"), (int, float))
        for e in events
    ):
        return False, "event lines missing rank/ts tags"
    events.sort(key=lambda e: e["ts"])
    milestones = (
        ("crash", lambda e: e["type"] == "fault.worker_crash"),
        ("fallback", lambda e: e["type"] == "checkpoint.fallback"),
        ("reshard", lambda e: e["type"] == "checkpoint.restore"
         and e.get("mode") == "elastic"),
        ("recovery", lambda e: e["type"] == "run.complete"),
    )
    i = 0
    for name, pred in milestones:
        while i < len(events) and not pred(events[i]):
            i += 1
        if i >= len(events):
            seen = sorted({e["type"] for e in events})
            return False, (
                f"event timeline missing '{name}' (in order); saw {seen}"
            )
        i += 1
    ranks = {e["rank"] for e in events}
    if not {0, 1} <= ranks:
        return False, f"expected rank-tagged events from both ranks, got {ranks}"

    json_path, prom_path = got_out + ".metrics.json", got_out + ".metrics.prom"
    try:
        with open(json_path) as f:
            snap = json.load(f)
    except (OSError, ValueError) as e:
        return False, f"metrics JSON unreadable ({e!r})"
    teff = snap.get("histograms", {}).get("diffusion3d.t_eff_gbs", {})
    if not teff.get("count"):
        return False, f"no per-step T_eff recorded in {json_path}"
    try:
        with open(prom_path) as f:
            prom = f.read()
    except OSError as e:
        return False, f"Prometheus dump unreadable ({e!r})"
    for line in prom.splitlines():
        if not line or line.startswith("#"):
            continue
        parts = line.rsplit(None, 1)
        if len(parts) != 2:
            return False, f"malformed Prometheus line {line!r}"
        try:
            float(parts[1])
        except ValueError:
            return False, f"non-numeric Prometheus sample {line!r}"
    if "igg_diffusion3d_t_eff_gbs" not in prom:
        return False, "T_eff summary missing from the Prometheus exposition"

    # Flight recorder (docs/observability.md): the injected crash on proc 1
    # must have left a bundle with the span ring, metrics snapshot and
    # active config BEFORE its hard exit.
    from implicitglobalgrid_tpu.utils import tracing

    flight = os.path.join(tele_dir, tracing.flight_filename(1))
    if not os.path.isfile(flight):
        return False, f"no flight-recorder bundle {flight} from the crash"
    bundles = tracing.read_flight_bundles(flight)
    crash_bundles = [
        b for b in bundles if b.get("reason") == "fault.worker_crash"
    ]
    if not crash_bundles:
        return False, (
            f"{flight}: no fault.worker_crash bundle "
            f"(reasons: {[b.get('reason') for b in bundles]})"
        )
    bundle = crash_bundles[-1]
    missing = [k for k in ("metrics", "config", "spans") if k not in bundle]
    if missing:
        return False, f"flight bundle missing section(s) {missing}"
    if bundle.get("rank") != 1:
        return False, f"flight bundle rank {bundle.get('rank')} != 1"

    # Merged-trace validation: the restart's span dump must merge into a
    # valid Chrome trace carrying the instrumented spans.
    tfiles = sorted(glob.glob(os.path.join(tele_dir, "trace.p*.json")))
    if not tfiles:
        return False, f"no trace.p*.json span dumps under {tele_dir}"
    try:
        doc = tracing.merge_trace_files(tfiles)
    except (OSError, ValueError) as e:
        return False, f"trace merge failed ({e!r})"
    problems = tracing.validate_chrome_trace(doc)
    if problems:
        return False, f"merged trace invalid: {problems[:3]}"
    span_names = {
        e["name"] for e in doc["traceEvents"] if e["ph"] == "X"
    }
    for need_span in ("igg.step", "igg.checkpoint.restore"):
        if need_span not in span_names:
            return False, (
                f"merged trace lacks '{need_span}' spans "
                f"(saw {sorted(span_names)})"
            )
    return True, (
        f"{len(events)} events across {len(files)} rank file(s): "
        f"crash -> fallback -> elastic reshard -> recovery in order; "
        f"T_eff over {teff['count']} step(s); crash flight bundle ok; "
        f"merged trace valid ({len(span_names)} span name(s))"
    )


def _dump_run_logs(run_dir: str) -> None:
    import glob as _glob_mod

    for path in sorted(_glob_mod.glob(os.path.join(run_dir, "*.log"))):
        print(f"----- {path}", file=sys.stderr)
        with open(path) as f:
            print(f.read(), file=sys.stderr)


def supervise_elastic_failover(args) -> bool:
    """The supervised-failover drill, now a thin wrapper over
    `igg.supervisor.RunSupervisor` (the subsystem this scenario used to
    hand-roll): the supervisor launches the 2-process pair with the
    crash + corrupt-newest-generation faults armed, detects the crash,
    classifies it, and — with ``max_restarts=0`` — its policy engine
    drops straight to the shrunk 1-process rung, relaunching from the
    latest VALID checkpoint.  Verification against the never-crashed
    oracle (and the telemetry/flight/trace acceptance) is unchanged."""
    import shutil

    import numpy as np

    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from implicitglobalgrid_tpu import supervisor as sup

    workdir = args.workdir
    ckpt = os.path.join(workdir, "ckpt_elastic")
    run_dir = os.path.join(workdir, "elastic_run")
    shutil.rmtree(ckpt, ignore_errors=True)
    shutil.rmtree(run_dir, ignore_errors=True)
    # Telemetry armed for the pair AND the restart (same directory): the
    # drill must yield one machine-readable cross-process timeline.  The
    # oracle leg stays un-armed — its events would pollute the timeline.
    tele_dir = os.path.join(workdir, "telemetry_elastic")
    shutil.rmtree(tele_dir, ignore_errors=True)
    if args.steps < 6:
        return _report(
            "elastic", False,
            f"--steps {args.steps} too small: the drill needs a valid "
            f"generation BEFORE the corrupted crash checkpoint (>= 6 steps)",
        )
    # a checkpointed step with at least one earlier generation to fall
    # back to once the crash-step generation is corrupted
    mid = max(4, (args.steps // 2) // 2 * 2)

    # (1) never-crashed oracle on the single-process topology
    oracle_out = os.path.join(workdir, "elastic_oracle.npy")
    proc = _run_child(
        _elastic_cmd(args, nproc=1, pair_id=0, port=0, ckpt=None, out=oracle_out),
        _elastic_env({}), args.timeout,
    )
    if proc.returncode != 0:
        print(proc.stdout, proc.stderr, sep="\n", file=sys.stderr)
        return _report("elastic", False, f"oracle rc={proc.returncode}")

    # (2) the supervised run: `RunSupervisor` owns the pair end to end —
    # spawn with the faults armed, detect/classify the crash, shrink
    # (max_restarts=0: the first strike walks the ladder), relaunch
    # against the same checkpoint directory with the fired faults pruned.
    got_out = os.path.join(workdir, "elastic_resumed.npy")
    launch = {"gen": None, "port": 0}

    def command_for(rank, nranks, rung, gen):
        if launch["gen"] != gen:
            launch["gen"] = gen
            launch["port"] = _free_port()
        return _elastic_cmd(
            args, nproc=nranks, pair_id=rank, port=launch["port"], ckpt=ckpt,
            out=got_out, expect_resume=(mid - 2) if nranks == 1 else -1,
        )

    rsup = sup.RunSupervisor(
        command_for,
        ladder=[2, 1],
        workdir=run_dir,
        telemetry_dir=tele_dir,
        policy=sup.RecoveryPolicy(max_restarts=0, backoff_s=0.2),
        fault_spec=f"worker_crash:step{mid}:proc1,ckpt_corrupt:step{mid}",
        env={"PYTHONPATH": _elastic_env({})["PYTHONPATH"],
             "IGG_TELEMETRY": "1"},
        grace_s=30.0,
        poll_s=0.3,
        name="elastic",
    )
    report = rsup.run(timeout=args.timeout)
    if not report.ok:
        _dump_run_logs(run_dir)
        return _report("elastic", False, f"supervisor: {report.summary()}")
    kinds = [i["kind"] for i in report.incidents]
    actions = [i["decision"]["action"] for i in report.incidents]
    if "shrink" not in actions:
        return _report(
            "elastic", False,
            f"supervisor never took the shrink leg (kinds {kinds}, "
            f"actions {actions})",
        )
    crash_inc = report.incidents[0]
    if CRASH_STATUS not in crash_inc["rcs"]:
        _dump_run_logs(run_dir)
        return _report(
            "elastic", False,
            f"expected crash rc={CRASH_STATUS} in the first incident, got "
            f"{crash_inc['rcs']}",
        )
    oracle = np.load(oracle_out)
    got = np.load(got_out)
    ok = got.shape == oracle.shape and np.allclose(
        got, oracle, rtol=1e-13, atol=1e-13
    )
    # (3) the observability acceptance: rank-tagged event timeline in order
    # + a valid metrics dump with per-step T_eff (docs/observability.md).
    tele_ok, tele_detail = _verify_elastic_telemetry(tele_dir, got_out)
    if not tele_ok:
        return _report("elastic", False, f"telemetry: {tele_detail}")
    return _report(
        "elastic", ok,
        f"supervised: {' -> '.join(f'{k}/{a}' for k, a in zip(kinds, actions))} "
        f"across {report.generations + 1} generation(s) "
        f"(max |err| {np.max(np.abs(got - oracle)) if got.shape == oracle.shape else 'shape mismatch'}); "
        f"telemetry: {tele_detail}",
    )


#: fault kinds the chaos drill samples (the storm the acceptance names:
#: crash + stall + ckpt_corrupt + net_delay)
CHAOS_DRILL_KINDS = ("worker_crash", "stall", "net_delay", "ckpt_corrupt")
CHAOS_DRILL_RATE = 0.8


def _chaos_pick_seed(steps: int) -> tuple[int, list[str]]:
    """First seed whose deterministic `chaos_schedule` expansion is a
    qualifying storm: exactly TWO crashes (so the supervisor exercises the
    restart-in-place leg AND the strikes-exhausted shrink leg), at least
    one stall and one net_delay, and a ckpt_corrupt at an even
    (checkpointed) step with a crash at that step or the next — the
    configuration that leaves the NEWEST generation damaged when the
    restart reads the directory, so the integrity fallback runs inside
    the storm.  The scan is deterministic: every invocation (and any
    debugging rerun) derives the same seed from the same ``steps``."""
    from implicitglobalgrid_tpu.utils.resilience import chaos_schedule

    for seed in range(100000):
        specs = chaos_schedule(
            seed, CHAOS_DRILL_RATE, steps=steps, kinds=CHAOS_DRILL_KINDS
        )
        by_kind: dict[str, list[int]] = {}
        for s in specs:
            kind, step = s.split(":")
            by_kind.setdefault(kind, []).append(int(step[len("step"):]))
        crashes = sorted(by_kind.get("worker_crash", []))
        if len(crashes) != 2:
            continue
        if not by_kind.get("stall") or not by_kind.get("net_delay"):
            continue
        # exactly ONE ckpt_corrupt, at an even (checkpointed) step >= 4:
        # the step-(c-2) generation is valid and on disk by the time step
        # c's save is damaged, so the shrink leg's fallback lands on a
        # real generation and the 2->1-process ELASTIC reshard runs —
        # damaging the only generation would make the "recovery" a silent
        # from-scratch rerun instead
        corrupts = by_kind.get("ckpt_corrupt", [])
        if len(corrupts) != 1 or corrupts[0] % 2 or corrupts[0] < 4:
            continue
        # ...and the SECOND crash (the strikes-exhausted shrink trigger)
        # lands at the damaged step or the next, so that generation is the
        # newest when the shrunk incarnation reads the directory
        if not corrupts[0] <= crashes[1] <= corrupts[0] + 1:
            continue
        return seed, specs
    raise RuntimeError(
        f"no chaos seed under 100000 satisfies the storm predicate at "
        f"steps={steps}"
    )


def _verify_chaos_events(tele_dir: str) -> tuple[bool, str]:
    """The chaos drill's machine-readable acceptance: every storm kind
    fired, the wedged loop was caught live (``alert.step_stall``), and the
    supervisor's detect → classify → recover transitions bracket both
    recovery legs IN ORDER on the merged per-rank timeline."""
    import glob

    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from implicitglobalgrid_tpu.utils.telemetry import read_events

    files = sorted(glob.glob(os.path.join(tele_dir, "events*.jsonl")))
    if not files:
        return False, f"no events*.jsonl under {tele_dir}"
    events = [e for f in files for e in read_events(f)]
    if any(
        "rank" not in e or not isinstance(e.get("ts"), (int, float))
        for e in events
    ):
        return False, "event lines missing rank/ts tags"
    events.sort(key=lambda e: e["ts"])
    types = [str(e.get("type")) for e in events]
    missing_kinds = [
        k for k in CHAOS_DRILL_KINDS if f"fault.{k}" not in types
    ]
    if missing_kinds:
        return False, f"storm kind(s) never fired: {missing_kinds}"
    if "alert.step_stall" not in types:
        return False, (
            "the injected stall never surfaced as a live alert.step_stall "
            "(scrape-time rule) on any rank"
        )
    milestones = (
        ("crash #1", lambda e: e["type"] == "fault.worker_crash"),
        ("detect #1", lambda e: e["type"] == "supervisor.detect"),
        ("classify #1", lambda e: e["type"] == "supervisor.classify"),
        ("recover/restart", lambda e: e["type"] == "supervisor.recover"
         and e.get("action") == "restart"),
        ("crash #2", lambda e: e["type"] == "fault.worker_crash"),
        ("detect #2", lambda e: e["type"] == "supervisor.detect"),
        ("classify #2", lambda e: e["type"] == "supervisor.classify"),
        ("recover/shrink", lambda e: e["type"] == "supervisor.recover"
         and e.get("action") == "shrink"),
        ("elastic reshard", lambda e: e["type"] == "checkpoint.restore"
         and e.get("mode") == "elastic"),
        ("recovery", lambda e: e["type"] == "run.complete"),
    )
    i = 0
    for name, pred in milestones:
        while i < len(events) and not pred(events[i]):
            i += 1
        if i >= len(events):
            seen = sorted(set(types))
            return False, (
                f"chaos timeline missing '{name}' (in order); saw {seen}"
            )
        i += 1
    fallbacks = types.count("checkpoint.fallback")
    gens = sorted({e.get("gen") for e in events if e.get("gen") is not None})
    return True, (
        f"{len(events)} events across {len(files)} file(s): all "
        f"{len(CHAOS_DRILL_KINDS)} storm kinds fired, stall caught live, "
        f"detect->classify->recover in order through restart AND shrink, "
        f"{fallbacks} integrity fallback(s), generations {gens}"
    )


def supervise_chaos(args) -> bool:
    """The chaos drill (module docstring): a seeded randomized multi-fault
    storm over a REAL 2-process gloo pair, owned end to end by
    `igg.supervisor.RunSupervisor` — the supervisor detects each failure
    (process liveness + live ``/healthz`` scrapes), classifies it, restarts
    in place, then shrinks elastically once the strikes are spent, and the
    final de-duplicated field must be BIT-IDENTICAL to an undisturbed
    oracle."""
    import shutil

    import numpy as np

    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from implicitglobalgrid_tpu import supervisor as sup

    workdir = args.workdir
    ckpt = os.path.join(workdir, "ckpt_chaos")
    run_dir = os.path.join(workdir, "chaos_run")
    tele_dir = os.path.join(workdir, "telemetry_chaos")
    for d in (ckpt, run_dir, tele_dir):
        shutil.rmtree(d, ignore_errors=True)
    steps = max(6, args.steps)
    seed, storm = _chaos_pick_seed(steps)
    print(f"[soak] chaos storm (seed {seed}): {', '.join(storm)}")

    # (1) the undisturbed oracle (1-process topology, no faults, no
    # telemetry — its events would pollute the storm timeline)
    oracle_out = os.path.join(workdir, "chaos_oracle.npy")
    oargs = argparse.Namespace(**vars(args))
    oargs.steps = steps
    proc = _run_child(
        _elastic_cmd(oargs, nproc=1, pair_id=0, port=0, ckpt=None,
                     out=oracle_out),
        _elastic_env({}), args.timeout,
    )
    if proc.returncode != 0:
        print(proc.stdout, proc.stderr, sep="\n", file=sys.stderr)
        return _report("chaos", False, f"oracle rc={proc.returncode}")

    # (2) the supervised storm
    got_out = os.path.join(workdir, "chaos_resumed.npy")
    launch = {"gen": None, "port": 0}

    def command_for(rank, nranks, rung, gen):
        if launch["gen"] != gen:
            launch["gen"] = gen
            launch["port"] = _free_port()
        return _elastic_cmd(
            oargs, nproc=nranks, pair_id=rank, port=launch["port"],
            ckpt=ckpt, out=got_out,
        )

    rsup = sup.RunSupervisor(
        command_for,
        ladder=[2, 1],
        workdir=run_dir,
        telemetry_dir=tele_dir,
        policy=sup.RecoveryPolicy(max_restarts=1, backoff_s=0.2, seed=seed),
        fault_spec=(
            f"chaos:seed={seed}:rate={CHAOS_DRILL_RATE}:steps={steps}"
            f":kinds={'+'.join(CHAOS_DRILL_KINDS)}"
        ),
        env={
            "PYTHONPATH": _elastic_env({})["PYTHONPATH"],
            "IGG_TELEMETRY": "1",
            # the live plane the supervisor polls: per-rank ephemeral
            # scrape servers + heartbeat-cadence rule evaluation
            "IGG_METRICS_PORT": "0",
            "IGG_HEARTBEAT_EVERY": "1",
        },
        grace_s=30.0,
        poll_s=0.3,
        name="chaos",
    )
    report = rsup.run(timeout=args.timeout, max_incarnations=6)
    if not report.ok:
        _dump_run_logs(run_dir)
        return _report("chaos", False, f"supervisor: {report.summary()}")
    actions = [i["decision"]["action"] for i in report.incidents]
    kinds = [i["kind"] for i in report.incidents]
    if "restart" not in actions or "shrink" not in actions:
        return _report(
            "chaos", False,
            f"storm did not exercise both recovery legs (kinds {kinds}, "
            f"actions {actions})",
        )

    # (3) bit-identity in dedup space vs the undisturbed oracle
    oracle = np.load(oracle_out)
    got = np.load(got_out)
    if got.shape != oracle.shape or not np.array_equal(got, oracle):
        detail = (
            "shape mismatch" if got.shape != oracle.shape
            else f"max |err| {np.max(np.abs(got - oracle))}"
        )
        return _report(
            "chaos", False,
            f"final dedup field differs from the oracle ({detail})",
        )

    # (4) the event-order acceptance
    ev_ok, ev_detail = _verify_chaos_events(tele_dir)
    if not ev_ok:
        return _report("chaos", False, f"events: {ev_detail}")
    return _report(
        "chaos", True,
        f"seed {seed}: {len(storm)} faults -> "
        f"{' -> '.join(f'{k}/{a}' for k, a in zip(kinds, actions))} across "
        f"{report.generations + 1} generation(s), final field bit-identical "
        f"to the oracle; {ev_detail}",
    )


def _verify_sdc_events(tele_dir: str) -> tuple[bool, str]:
    """The sdc drill's machine-readable acceptance: each bit_flip
    placement surfaced through exactly its intended detector, in order —
    the transport trip (emitted by the RECEIVER, implicating the sender),
    the shadow-audit trip, the lineage fallback past the poisoned
    generation — and the recovered run completed."""
    import glob

    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from implicitglobalgrid_tpu.utils.telemetry import read_events

    files = sorted(glob.glob(os.path.join(tele_dir, "events*.jsonl")))
    if not files:
        return False, f"no events*.jsonl under {tele_dir}"
    events = [e for f in files for e in read_events(f)]
    if any(
        "rank" not in e or not isinstance(e.get("ts"), (int, float))
        for e in events
    ):
        return False, "event lines missing rank/ts tags"
    events.sort(key=lambda e: e["ts"])
    types = [str(e.get("type")) for e in events]
    placements = sorted({
        str(e.get("placement")) for e in events if e["type"] == "fault.bit_flip"
    })
    if placements != ["ckpt", "state", "transport"]:
        return False, f"expected all three bit_flip placements, saw {placements}"
    transport = [e for e in events if e["type"] == "integrity.transport_mismatch"]
    if not transport:
        return False, "the transport flip never tripped a receiver checksum"
    if any(e.get("implicated_rank") != 0 for e in transport):
        return False, (
            f"transport trip implicated "
            f"{sorted({e.get('implicated_rank') for e in transport})}, "
            f"expected the armed sender rank 0"
        )
    if all(e.get("rank") != 1 for e in transport):
        return False, "no transport trip was emitted by the RECEIVER rank 1"
    milestones = (
        ("transport trip", lambda e: e["type"] == "integrity.transport_mismatch"),
        ("quarantine #1", lambda e: e["type"] == "supervisor.recover"
         and e.get("action") == "quarantine"),
        ("audit trip", lambda e: e["type"] == "integrity.audit_mismatch"),
        ("quarantine #2", lambda e: e["type"] == "supervisor.recover"
         and e.get("action") == "quarantine"),
        ("ckpt flip", lambda e: e["type"] == "fault.bit_flip"
         and e.get("placement") == "ckpt"),
        ("crash", lambda e: e["type"] == "fault.worker_crash"),
        ("lineage fallback", lambda e: e["type"] == "checkpoint.fallback"),
        ("recovery", lambda e: e["type"] == "run.complete"),
    )
    i = 0
    for name, pred in milestones:
        while i < len(events) and not pred(events[i]):
            i += 1
        if i >= len(events):
            seen = sorted(set(types))
            return False, f"sdc timeline missing '{name}' (in order); saw {seen}"
        i += 1
    return True, (
        f"{len(events)} events across {len(files)} file(s): transport trip "
        f"(receiver rank 1 implicating sender rank 0) -> quarantine -> "
        f"audit trip -> quarantine -> poisoned generation skipped by "
        f"lineage fallback -> recovery"
    )


def supervise_sdc(args) -> bool:
    """The silent-data-corruption drill (module docstring): one bit_flip
    per integrity-plane placement over a supervised gloo pair running the
    HOST-path step, each caught by exactly its intended detector, the
    implicated rank quarantined on the first offense, the poisoned
    checkpoint generation skipped on relaunch — and the final field
    BIT-IDENTICAL to an undisturbed oracle that doubles as the clean leg
    (whole plane armed, zero false positives)."""
    import json
    import shutil

    import numpy as np

    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from implicitglobalgrid_tpu import supervisor as sup

    workdir = args.workdir
    ckpt = os.path.join(workdir, "ckpt_sdc")
    run_dir = os.path.join(workdir, "sdc_run")
    tele_dir = os.path.join(workdir, "telemetry_sdc")
    tele_clean = os.path.join(workdir, "telemetry_sdc_clean")
    for d in (ckpt, run_dir, tele_dir, tele_clean):
        shutil.rmtree(d, ignore_errors=True)
    # the placements need distinct steps (cross-incarnation pruning is
    # keyed on (kind, step)) and the ckpt flip needs a generation after
    # the audit trip's resume point
    steps = max(8, args.steps)
    oargs = argparse.Namespace(**vars(args))
    oargs.steps = steps
    integrity = {"IGG_INTEGRITY": "1", "IGG_INTEGRITY_EVERY": "1"}

    # (1) the undisturbed oracle IS the clean leg: 1-process topology,
    # the WHOLE plane armed, its own telemetry dir — zero false positives
    # is part of the acceptance (transport checksums + per-step audits
    # must never trip on honest data)
    oracle_out = os.path.join(workdir, "sdc_oracle.npy")
    proc = _run_child(
        _sdc_cmd(oargs, nproc=1, pair_id=0, port=0, ckpt=None,
                 out=oracle_out),
        _elastic_env({**integrity, "IGG_TELEMETRY": "1",
                      "IGG_TELEMETRY_DIR": tele_clean}),
        args.timeout,
    )
    if proc.returncode != 0:
        print(proc.stdout, proc.stderr, sep="\n", file=sys.stderr)
        return _report("sdc", False, f"clean leg rc={proc.returncode}")
    try:
        with open(oracle_out + ".metrics.json") as f:
            counters = json.load(f).get("counters", {})
    except (OSError, ValueError) as e:
        return _report("sdc", False, f"clean-leg metrics unreadable ({e!r})")
    false_pos = {
        k: counters.get(k, 0)
        for k in ("integrity.audit_mismatches",
                  "integrity.transport_mismatches")
        if counters.get(k, 0)
    }
    if not counters.get("integrity.audits"):
        return _report("sdc", False, "clean leg ran zero shadow audits")
    if false_pos:
        return _report("sdc", False, f"clean-leg FALSE POSITIVES: {false_pos}")

    # (2) the supervised bit-flip storm: transport flip on rank 0's wire
    # (step 2 arm -> step 3 trip on rank 1), state flip at step 4 (fires
    # only in the shrunk restart: the stranded sender is reaped while
    # blocked in its step-3 audit replay), ckpt flip poisoning the
    # step-6 generation, then a crash so the relaunch must walk past it
    got_out = os.path.join(workdir, "sdc_resumed.npy")
    launch = {"gen": None, "port": 0}

    def command_for(rank, nranks, rung, gen):
        if launch["gen"] != gen:
            launch["gen"] = gen
            launch["port"] = _free_port()
        return _sdc_cmd(
            oargs, nproc=nranks, pair_id=rank, port=launch["port"],
            ckpt=ckpt, out=got_out,
        )

    rsup = sup.RunSupervisor(
        command_for,
        ladder=[2, 1, 1],  # two quarantine shrinks must not exhaust it
        workdir=run_dir,
        telemetry_dir=tele_dir,
        policy=sup.RecoveryPolicy(max_restarts=1, backoff_s=0.2),
        fault_spec=(
            "bit_flip:step2:transport:proc0,bit_flip:step4:T,"
            "bit_flip:step6:ckpt,worker_crash:step7:proc0"
        ),
        env={
            "PYTHONPATH": _elastic_env({})["PYTHONPATH"],
            **integrity,
            "IGG_TELEMETRY": "1",
            "IGG_METRICS_PORT": "0",
            "IGG_HEARTBEAT_EVERY": "1",
        },
        grace_s=15.0,
        poll_s=0.3,
        name="sdc",
    )
    report = rsup.run(timeout=args.timeout, max_incarnations=6)
    if not report.ok:
        _dump_run_logs(run_dir)
        return _report("sdc", False, f"supervisor: {report.summary()}")

    # (3) the escalation chain: detector -> silent_corruption ->
    # first-offense quarantine, for BOTH in-flight detectors
    sdc_inc = [i for i in report.incidents if i["kind"] == "silent_corruption"]
    detectors = [i["detail"].get("detector") for i in sdc_inc]
    if detectors != ["transport_checksum", "shadow_audit"]:
        return _report(
            "sdc", False,
            f"expected transport_checksum then shadow_audit convictions, "
            f"got {detectors} (kinds "
            f"{[i['kind'] for i in report.incidents]})",
        )
    if any(i["decision"]["action"] != "quarantine" for i in sdc_inc):
        return _report(
            "sdc", False,
            f"silent_corruption must quarantine on the FIRST offense, got "
            f"{[i['decision']['action'] for i in sdc_inc]}",
        )
    transport_inc = sdc_inc[0]
    if (transport_inc["detail"].get("implicated_rank") != 0
            or transport_inc["detail"].get("bundle_rank") != 1):
        return _report(
            "sdc", False,
            f"transport conviction must come from the RECEIVER's bundle "
            f"(rank 1) and implicate the SENDER (rank 0), got detail "
            f"{transport_inc['detail']}",
        )
    if 0 not in report.quarantined:
        return _report(
            "sdc", False, f"rank 0 not quarantined ({report.quarantined})"
        )
    crash_actions = [
        i["decision"]["action"] for i in report.incidents
        if i["kind"] == "crash"
    ]
    if crash_actions != ["restart"]:
        return _report(
            "sdc", False,
            f"the post-poisoning crash should restart in place, got "
            f"{crash_actions}",
        )

    # (4) bit-identity in dedup space vs the undisturbed oracle
    oracle = np.load(oracle_out)
    got = np.load(got_out)
    if got.shape != oracle.shape or not np.array_equal(got, oracle):
        detail = (
            "shape mismatch" if got.shape != oracle.shape
            else f"max |err| {np.max(np.abs(got - oracle))}"
        )
        return _report(
            "sdc", False,
            f"final dedup field differs from the oracle ({detail})",
        )

    # (5) the event-order acceptance
    ev_ok, ev_detail = _verify_sdc_events(tele_dir)
    if not ev_ok:
        return _report("sdc", False, f"events: {ev_detail}")
    kinds = [i["kind"] for i in report.incidents]
    actions = [i["decision"]["action"] for i in report.incidents]
    return _report(
        "sdc", True,
        f"{' -> '.join(f'{k}/{a}' for k, a in zip(kinds, actions))} across "
        f"{report.generations + 1} generation(s), clean leg pinned zero "
        f"false positives, final field bit-identical to the oracle; "
        f"{ev_detail}",
    )


def _dump_fleet_logs(fleet_dir: str) -> None:
    import glob as _glob

    for path in sorted(_glob.glob(os.path.join(fleet_dir, "*", "*.log"))):
        try:
            with open(path) as f:
                tail = f.read()[-2000:]
        except OSError:
            continue
        print(f"---- {path} ----\n{tail}", file=sys.stderr)


def supervise_fleet(args) -> bool:
    """The fleet drill (ISSUE 16, docs/serving.md "The fleet tier"): two
    live single-process pools behind ONE `FleetRouter`, owned by a
    `FleetController` in THIS process.  Legs:

    1. bursty multi-tenant traffic; one pool chaos-SIGKILLed with a long
       job in flight — every request (including submits fired during the
       outage) completes with digests bit-identical to the undisturbed
       oracle, zero failed requests, the ``fleet.detect`` →
       ``fleet.reroute`` → ``fleet.recovered`` order verified from the
       orchestrator's events.jsonl and the respawned pool's per-pool log
       carrying the BUMPED generation; additionally (ISSUE 19) every
       admitted request's causal tree reconstructs from the pools'
       periodic trace dumps + the orchestrator's dump — door→result
       spans present, re-routed requests carrying the detect→reroute
       hop, both generations of the chaos-killed pool contributing
       spans, and the OTLP/Chrome exports schema-clean;
    2. a healthy canary serving real traffic promotes after the streak
       and its config overlay spreads to the seed specs;
    3. a doctored-slow canary (``--round-sleep``) breaches the round-p99
       SLO bar and rolls back through quarantine — the bad overlay never
       spreads.
    """
    import glob as _glob
    import json as _json
    import shutil
    import time as _time

    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from implicitglobalgrid_tpu import fleet as flt
    from implicitglobalgrid_tpu.utils import telemetry as tele
    from implicitglobalgrid_tpu.utils import tracing as trc

    workdir = args.workdir
    fleet_dir = os.path.join(workdir, "fleet_run")
    tele_dir = os.path.join(workdir, "telemetry_fleet")
    shutil.rmtree(fleet_dir, ignore_errors=True)
    shutil.rmtree(tele_dir, ignore_errors=True)
    os.makedirs(tele_dir)

    steps = max(4, args.steps)
    # request catalog: (tenant, ic_scale, max_steps).  The long job is the
    # chaos victim's in-flight work — rerouted mid-run, replayed whole on
    # the survivor; the during-outage pair proves the door never closes.
    traffic = [("tA", 1.0, steps), ("tB", 1.05, steps), ("tA", 1.1, steps),
               ("tC", 1.15, steps), ("tB", 1.2, steps)]
    long_job = ("tA", 1.3, 40 * steps)
    during_outage = [("tC", 1.05, steps), ("tB", 1.1, steps)]
    canary_job = ("tA", 1.0, steps)
    all_specs = sorted({(ic, ms) for _, ic, ms in
                        traffic + during_outage + [long_job, canary_job]})

    # (0) the undisturbed oracle's digests (fixed 1-process topology)
    specs_path = os.path.join(workdir, "fleet_specs.json")
    oracle_out = os.path.join(workdir, "fleet_oracle.json")
    with open(specs_path, "w") as f:
        _json.dump([list(s) for s in all_specs], f)
    proc = _run_child(
        [sys.executable, os.path.abspath(__file__), "--frontdoor-oracle",
         "--nx", str(args.nx), "--specs", specs_path, "--out", oracle_out],
        _elastic_env({}), args.timeout,
    )
    if proc.returncode != 0:
        print(proc.stdout, proc.stderr, sep="\n", file=sys.stderr)
        return _report("fleet", False, f"oracle rc={proc.returncode}")
    with open(oracle_out) as f:
        oracle = _json.load(f)

    # fleet.* events land in the orchestrator's OWN event log
    saved_env = {k: os.environ.get(k)
                 for k in ("IGG_TELEMETRY", "IGG_TELEMETRY_DIR")}
    os.environ["IGG_TELEMETRY"] = "1"
    os.environ["IGG_TELEMETRY_DIR"] = tele_dir

    pool_env = {"PYTHONPATH": _elastic_env({})["PYTHONPATH"],
                "IGG_SERVE_PORT": "0"}

    def pool_spec(name, round_sleep=0.0, env_extra=None):
        cmd = [sys.executable, os.path.abspath(__file__),
               "--fleet-pool-child", "--nx", str(args.nx),
               "--capacity", "2", "--timeout", str(args.timeout),
               "--round-sleep", str(round_sleep)]
        return flt.PoolSpec(
            name=name,
            command_for=lambda spec, gen: cmd,
            workdir=os.path.join(fleet_dir, name),
            telemetry_dir=os.path.join(fleet_dir, name, "telemetry"),
            key={"model": "diffusion3d"},
            devices=f"soak-dev-{name}",
            env={**pool_env, **(env_extra or {})},
        )

    router = flt.FleetRouter(port=0)
    fc = flt.FleetController(
        [pool_spec("a"), pool_spec("b")],
        router=router,
        policy=flt.FleetPolicy(respawn_limit=2, canary_streak=2,
                               canary_p99_s=0.25),
        poll_s=0.2,
    )
    accepted: dict[str, dict] = {}
    done: dict[str, dict] = {}
    failed: list = []
    client = None

    def _submit(tenant, ic, ms):
        code, body = client.post("/v1/submit", {
            "tenant": tenant, "model": "diffusion3d",
            "params": {"ic_scale": ic, "max_steps": ms},
        })
        if code != 202:
            failed.append((tenant, ic, ms, code, body))
            return None
        route = router.routes.get(body["request_id"]) or {}
        accepted[body["request_id"]] = {
            "tenant": tenant, "ic": ic, "ms": ms, "pool": body["pool"],
            "trace_id": (route.get("trace") or {}).get("trace_id"),
        }
        return body

    def _poll_done():
        for fid in list(accepted):
            if fid in done:
                continue
            try:
                view = client.get(f"/v1/result/{fid}")
            except OSError:
                return
            if isinstance(view, dict) and view.get("status") == "done":
                done[fid] = view

    def _fail(msg):
        _dump_fleet_logs(fleet_dir)
        return _report("fleet", False, msg)

    try:
        # -- leg 1: traffic + chaos-killed pool -------------------------
        fc.launch(wait_s=min(60.0, args.timeout))
        if sorted(router.pools) != ["a", "b"]:
            return _fail(f"pools never registered: {sorted(router.pools)}")
        client = _DoorClient(f"127.0.0.1:{router.port}")
        for t in traffic:
            if _submit(*t) is None:
                return _fail(f"submit refused: {failed}")
        body = _submit(*long_job)
        if body is None:
            return _fail(f"submit refused: {failed}")
        victim = body["pool"]
        long_tid = accepted[body["request_id"]]["trace_id"]
        # Hold the chaos kill until the victim's periodic trace dump has
        # published a span of the long job: the SIGKILL erases the ring,
        # so the tree reconstruction reads the pool's LAST dump — which
        # must already carry the request's gen-0 spans (ISSUE 19).
        victim_tele = os.path.join(fleet_dir, victim, "telemetry")
        dump_deadline = _time.monotonic() + min(30.0, args.timeout)
        seen = long_tid is None  # tracing off: skip the hold
        while not seen and _time.monotonic() < dump_deadline:
            for p in _glob.glob(
                os.path.join(victim_tele, "trace.g*.p*.json")
            ):
                try:
                    with open(p) as f:
                        pool_doc = _json.load(f)
                except (OSError, ValueError):
                    continue
                if any(trc._trace_match(s.get("args"), long_tid)[0]
                       for s in pool_doc.get("spans", ())):
                    seen = True
                    break
            if not seen:
                _time.sleep(0.1)
        if not seen:
            return _fail("the victim pool never published a trace dump "
                         "carrying the long job's spans")
        fc.handles[victim].kill()  # chaos: SIGKILL one whole failure domain
        # the door stays open THROUGH the outage (failover, not 5xx)
        for t in during_outage:
            if _submit(*t) is None:
                return _fail(f"submit failed during the outage: {failed}")
        deadline = _time.monotonic() + args.timeout
        while _time.monotonic() < deadline:
            fc.poll_once()
            _poll_done()
            if len(done) == len(accepted):
                break
            _time.sleep(0.1)
        missing = [f for f in accepted if f not in done]
        if missing:
            return _fail(f"{len(missing)} accepted request(s) never "
                         f"completed after the chaos kill: {missing}")
        if failed:
            return _fail(f"failed request(s): {failed}")
        bad = [fid for fid, meta in accepted.items()
               if (done[fid].get("digest") or {}).get("fields")
               != oracle.get(f"{meta['ic']}:{meta['ms']}")]
        if bad:
            return _fail(f"digest mismatch vs the undisturbed oracle: {bad}")
        events = tele.read_events(os.path.join(tele_dir, "events.jsonl"))
        def _first(etype):
            for i, e in enumerate(events):
                if e["type"] == etype and e.get("pool") == victim:
                    return i
            return None
        i_det, i_rr, i_rec = (_first("fleet.detect"),
                              _first("fleet.reroute"),
                              _first("fleet.recovered"))
        if not (i_det is not None and i_rr is not None and i_rec is not None
                and i_det < i_rr < i_rec):
            return _fail(f"detect->reroute->recovered order broken: "
                         f"({i_det}, {i_rr}, {i_rec})")
        # the respawned incarnation's per-pool log carries the BUMPED gen
        pool_events = tele.read_events(
            os.path.join(fleet_dir, victim, "telemetry", "events.jsonl")
        )
        gens = {e.get("gen") for e in pool_events if e.get("gen") is not None}
        if not {0, 1} <= gens:
            return _fail(f"victim pool log gens {sorted(gens)}: the bumped "
                         f"generation never reached the per-pool log")

        # -- ISSUE 19: request-tree reconstruction ----------------------
        # Every admitted request must reconstruct into ONE causal tree
        # from the pools' periodic dumps + the orchestrator's own dump:
        # door→result spans present, re-routed requests carrying the
        # detect→reroute hop, and the victim pool contributing spans from
        # BOTH its generations (gen 0 pre-kill serving; gen 1 via a
        # post-recovery request routed onto the respawned incarnation).
        post_rid = None
        deadline = _time.monotonic() + args.timeout
        for _attempt in range(8):
            body = _submit("tA", 1.0, steps)
            if body is None:
                return _fail(f"post-recovery submit refused: {failed}")
            if body["pool"] == victim:
                post_rid = body["request_id"]
                break
            _poll_done()
            _time.sleep(0.2)
        if post_rid is None:
            return _fail(f"no post-recovery submit ever routed onto the "
                         f"respawned pool {victim!r} (least-loaded routing "
                         f"kept avoiding it)")
        while _time.monotonic() < deadline:
            fc.poll_once()
            _poll_done()
            if len(done) == len(accepted):
                break
            _time.sleep(0.1)
        if len(done) != len(accepted):
            return _fail("post-recovery request(s) never completed")
        rerouted = sorted({
            tid for s in trc.span_records()
            if s["name"] == "igg.fleet.detect"
            for tid in (s.get("args") or {}).get("trace_ids", ())
        })
        if not rerouted:
            return _fail("the fleet.detect span carries no trace ids: the "
                         "in-flight victim requests left no causal link")
        trc.dump_trace(tele_dir)  # route/detect/reroute spans live HERE

        def _load_dumps(d):
            docs = []
            for pat in ("trace.p*.json", "trace.g*.p*.json"):
                for p in sorted(_glob.glob(os.path.join(d, pat))):
                    try:
                        docs.append(trc._load_rank_trace(p))
                    except (OSError, ValueError):
                        pass  # a dump mid-publish: the retry loop re-reads
            return docs

        def _span_names(tree):
            names = set()

            def walk(ns):
                for nd in ns:
                    names.add(nd["name"])
                    walk(nd["children"])

            walk(tree["roots"])
            return names

        # the pools dump every ~0.25 s: poll until the final round spans
        # land on disk (bounded — a persistent hole is a real failure)
        problems = ["dumps not read yet"]
        all_docs: list = []
        check_deadline = _time.monotonic() + 20.0
        while problems and _time.monotonic() < check_deadline:
            problems = []
            victim_docs = _load_dumps(
                os.path.join(fleet_dir, victim, "telemetry")
            )
            all_docs = list(victim_docs)
            for pname in ("a", "b"):
                if pname != victim:
                    all_docs += _load_dumps(
                        os.path.join(fleet_dir, pname, "telemetry")
                    )
            all_docs += _load_dumps(tele_dir)
            victim_gens: set = set()
            for fid, meta in accepted.items():
                tid = meta.get("trace_id")
                if not tid:
                    problems.append(f"{fid}: no trace context on its route")
                    continue
                tree = trc.request_tree(all_docs, tid)
                names = _span_names(tree)
                if (not tree["spans"]
                        or "igg.frontdoor.request" not in names
                        or "igg.fleet.route" not in names):
                    problems.append(
                        f"{fid}: tree incomplete "
                        f"(spans={tree['spans']}, names={sorted(names)})"
                    )
                if tid in rerouted and not (
                    {"igg.fleet.detect", "igg.fleet.reroute"} <= names
                ):
                    problems.append(
                        f"{fid}: re-routed but its tree lacks the "
                        f"detect→reroute hop ({sorted(names)})"
                    )
                victim_gens |= set(
                    trc.request_tree(victim_docs, tid)["gens"]
                )
            if not {0, 1} <= victim_gens:
                problems.append(
                    f"victim-pool generations in the trees: "
                    f"{sorted(victim_gens)} — both generations of the "
                    f"chaos-killed pool must contribute spans"
                )
            if problems:
                _time.sleep(0.5)
        if problems:
            return _fail("request-tree check: " + "; ".join(problems[:4]))
        # the same dumps must ship schema-clean (what igg_trace.py
        # request/export would emit for these requests)
        otlp_problems = trc.validate_otlp(trc.otlp_trace(all_docs))
        if otlp_problems:
            return _fail(f"OTLP export not schema-clean: "
                         f"{otlp_problems[:3]}")
        view = trc.request_chrome_trace(
            trc.request_tree(all_docs, rerouted[0])
        )
        view_problems = trc.validate_chrome_trace(view)
        if view_problems:
            return _fail(f"request Chrome view invalid: "
                         f"{view_problems[:3]}")

        # -- canary legs ------------------------------------------------
        from implicitglobalgrid_tpu.fleet.router import pool_health_view

        def _bake_canary(name, tenant, ic, ms):
            """Drive one canary bake honestly: wait for the pool's door,
            put REAL traffic through it, wait until its rolling round
            p99 is a measurement (not an idle pool's silence), and only
            then let the controller's gate observe.  Returns the rid, or
            None if the pool never served."""
            deadline = _time.monotonic() + args.timeout
            ep = None
            while _time.monotonic() < deadline and ep is None:
                if fc.handles[name].poll() is not None:
                    return None
                ep = fc.discover_endpoint(name)
                _time.sleep(0.1)
            if ep is None:
                return None
            rid = None
            while _time.monotonic() < deadline and rid is None:
                code, b = _DoorClient(ep).post("/v1/submit", {
                    "tenant": tenant, "model": "diffusion3d",
                    "params": {"ic_scale": ic, "max_steps": ms},
                })
                if code == 202:
                    rid = b["request_id"]
                else:
                    _time.sleep(0.2)
            while _time.monotonic() < deadline:
                view = pool_health_view(flt.scrape_health(ep))
                if view.get("round_p99_s"):
                    break
                _time.sleep(0.2)
            while (_time.monotonic() < deadline
                   and fc.canary.state == "baking"):
                fc.poll_once()
                _time.sleep(0.2)
            return rid

        # -- leg 2: healthy canary promotes -----------------------------
        fc.start_canary(
            pool_spec("canary-good",
                      env_extra={"SOAK_CANARY_OVERLAY": "good"}),
            {"overlay": "good"},
        )
        if _bake_canary("canary-good", *canary_job) is None:
            return _fail("the healthy canary pool never served")
        if fc.canary.state != "promoted":
            return _fail(f"healthy canary never promoted "
                         f"(state={fc.canary.state}, "
                         f"breach={fc.canary.breach})")
        if fc.specs["a"].env.get("SOAK_CANARY_OVERLAY") != "good":
            return _fail("promoted overlay never spread to the seed specs")
        with open(os.path.join(fleet_dir, "canary-good",
                               "canary.json")) as f:
            doc = _json.load(f)
        if doc["state"] != "promoted":
            return _fail(f"canary.json says {doc['state']!r}, not promoted")

        # -- leg 3: doctored-slow canary rolls back ---------------------
        fc.start_canary(
            pool_spec("canary-bad", round_sleep=0.6,
                      env_extra={"SOAK_CANARY_OVERLAY": "bad"}),
            {"overlay": "doctored-slow"},
        )
        # the doctored round only SHOWS in the p99 once it runs, so the
        # helper holds the gate until the slowness is a measurement
        if _bake_canary("canary-bad", "tCanary", 1.0, steps) is None:
            return _fail("the doctored canary pool never served")
        if fc.canary.state != "rolled_back":
            return _fail(f"doctored canary never rolled back "
                         f"(state={fc.canary.state})")
        if (fc.canary.breach or {}).get("kind") != "slo":
            return _fail(f"expected an slo breach, got {fc.canary.breach}")
        if not router.pools["canary-bad"]["quarantined"]:
            return _fail("rolled-back canary not quarantined")
        if fc.specs["a"].env.get("SOAK_CANARY_OVERLAY") != "good":
            return _fail("the bad overlay reached the seed specs")
        with open(os.path.join(fleet_dir, "canary-bad", "canary.json")) as f:
            doc = _json.load(f)
        if doc["state"] != "rolled_back" or doc["breach"]["kind"] != "slo":
            return _fail(f"canary.json verdict wrong: {doc}")

        # the fleet.canary.* order, per pool, from the orchestrator log
        events = tele.read_events(os.path.join(tele_dir, "events.jsonl"))
        def _order(pool, *etypes):
            idx = []
            for et in etypes:
                found = [i for i, e in enumerate(events)
                         if e["type"] == et and e.get("pool") == pool]
                if not found:
                    return f"{pool}: no {et}"
                idx.append(found[0])
            if idx != sorted(idx):
                return f"{pool}: {list(zip(etypes, idx))} out of order"
            return None
        for problem in (
            _order("canary-good", "fleet.canary.start",
                   "fleet.canary.observe", "fleet.canary.promote"),
            _order("canary-bad", "fleet.canary.start",
                   "fleet.canary.rollback", "fleet.quarantine"),
        ):
            if problem:
                return _fail(f"canary event order: {problem}")
    finally:
        try:
            fc.shutdown()
        except Exception as e:  # noqa: BLE001 — teardown must not mask
            print(f"[soak] fleet shutdown: {e}", file=sys.stderr)
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    record = {
        "requests": len(accepted),
        "rerouted_pool": victim,
        "traced_requests": sum(
            1 for m in accepted.values() if m.get("trace_id")
        ),
        "canary": {"promoted": "canary-good", "rolled_back": "canary-bad"},
    }
    with open(os.path.join(workdir, "fleet_soak.json"), "w") as f:
        _json.dump(record, f, indent=1)
    return _report(
        "fleet", True,
        f"{len(accepted)} requests, pool {victim!r} chaos-killed -> "
        f"detect/reroute/recovered with zero failed requests, all digests "
        f"== oracle; every request's causal tree reconstructed across "
        f"pools/generations (detect→reroute hop + both victim gens); "
        f"canary promote + doctored-slow rollback (breach=slo)",
    )


def orchestrate(args) -> int:
    import numpy as np

    os.makedirs(args.workdir, exist_ok=True)
    failures = 0

    # The elastic drill carries its own oracle (a different topology); the
    # shared 8-device baseline is only needed by the other scenarios.
    baseline = None
    if any(
        s not in ("elastic_failover", "serving", "live_plane", "frontdoor",
                  "chaos", "fleet", "sdc")
        for s in args.scenarios
    ):
        proc, base_out, _ = _spawn_child(args, "baseline", args.workdir, {})
        if proc.returncode != 0:
            print(proc.stdout, proc.stderr, sep="\n", file=sys.stderr)
            _report("baseline", False, f"rc={proc.returncode}")
            return 1
        baseline = np.load(base_out)
        _report("baseline", True, f"steps={args.steps} nx={args.nx}")

    for scenario in args.scenarios:
        if scenario == "elastic_failover":
            if not supervise_elastic_failover(args):
                failures += 1
            continue
        if scenario == "live_plane":
            if not supervise_live_plane(args):
                failures += 1
            continue
        if scenario == "chaos":
            if not supervise_chaos(args):
                failures += 1
            continue
        if scenario == "frontdoor":
            if not supervise_frontdoor(args):
                failures += 1
            continue
        if scenario == "fleet":
            if not supervise_fleet(args):
                failures += 1
            continue
        if scenario == "sdc":
            if not supervise_sdc(args):
                failures += 1
            continue
        if scenario == "serving":
            import shutil

            tele_dir = os.path.join(args.workdir, "telemetry_serving")
            shutil.rmtree(tele_dir, ignore_errors=True)
            env = _elastic_env(
                {"IGG_TELEMETRY": "1", "IGG_TELEMETRY_DIR": tele_dir}
            )
            proc = _run_child(
                [sys.executable, os.path.abspath(__file__),
                 "--serving-child", "--nx", str(args.nx),
                 "--devices", str(args.devices)],
                env, args.timeout,
            )
            ok = proc.returncode == 0
            detail = f"rc={proc.returncode}"
            if ok:
                ok, detail = _verify_serving_events(tele_dir)
            if not _report("serving", ok, detail):
                print(proc.stdout, proc.stderr, sep="\n", file=sys.stderr)
                failures += 1
            continue
        if scenario == "init_flake":
            env = {
                "IGG_FAULT_INJECT": "init_flake:2",
                "IGG_INIT_RETRIES": "3",
                "IGG_INIT_BACKOFF_S": "0.05",
                "_distributed": "1",
            }
            proc, out, _ = _spawn_child(args, scenario, args.workdir, env)
            ok = proc.returncode == 0 and np.array_equal(
                np.load(out), baseline
            )
            if not _report(scenario, ok, f"rc={proc.returncode}"):
                print(proc.stdout, proc.stderr, sep="\n", file=sys.stderr)
                failures += 1

        elif scenario == "halo_corrupt":
            mid = max(1, args.steps // 2)
            env = {"IGG_FAULT_INJECT": f"halo_corrupt:step{mid}"}
            proc, out, _ = _spawn_child(args, scenario, args.workdir, env)
            ok = (
                proc.returncode == 0
                and "rolling back" in (proc.stdout + proc.stderr)
                and np.array_equal(np.load(out), baseline)
            )
            if not _report(
                scenario, ok, f"rc={proc.returncode} (guard tripped + rollback)"
            ):
                print(proc.stdout, proc.stderr, sep="\n", file=sys.stderr)
                failures += 1

        elif scenario == "worker_crash":
            mid = max(2, (args.steps // 2) // 2 * 2)  # a checkpointed step
            env = {"IGG_FAULT_INJECT": f"worker_crash:step{mid}:proc0"}
            proc, out, ckpt = _spawn_child(args, scenario, args.workdir, env)
            if proc.returncode != CRASH_STATUS:
                _report(scenario, False, f"expected crash rc={CRASH_STATUS}, got {proc.returncode}")
                print(proc.stdout, proc.stderr, sep="\n", file=sys.stderr)
                failures += 1
                continue
            # restart against the same checkpoint dir: must resume + finish
            proc2, out, _ = _spawn_child(args, scenario, args.workdir, {}, ckpt=ckpt)
            ok = (
                proc2.returncode == 0
                and "resumed from checkpoint" in (proc2.stdout + proc2.stderr)
                and np.array_equal(np.load(out), baseline)
            )
            if not _report(
                scenario, ok, f"crash rc={proc.returncode} -> restart rc={proc2.returncode}"
            ):
                print(proc2.stdout, proc2.stderr, sep="\n", file=sys.stderr)
                failures += 1

        else:
            _report(scenario, False, "unknown scenario")
            failures += 1

    print(f"[soak] {'ALL RECOVERED' if failures == 0 else f'{failures} UNRECOVERED FAILURE(S)'}")
    return 1 if failures else 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument(
        "scenario", nargs="*", choices=[[], *SCENARIOS],
        help="scenario(s) to run positionally (e.g. `soak.py chaos "
        "--quick`); default: --scenarios (or every scenario)",
    )
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--nx", type=int, default=8)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--workdir", default=os.path.join(REPO, ".soak"))
    ap.add_argument("--scenarios", nargs="+", default=list(SCENARIOS),
                    choices=list(SCENARIOS))
    ap.add_argument("--timeout", type=int, default=600)
    ap.add_argument(
        "--quick", action="store_true",
        help="fast smoke path: the elastic_failover drill (crash -> "
        "fallback past the corrupt generation -> shrunk-topology restart), "
        "the batched-serving loop smoke (mid-flight admit/retire, "
        "per-member convergence masking), the live_plane drill "
        "(mid-run endpoint scrape + stall alert) and the frontdoor drill "
        "(HTTP load + stall backpressure + elastic scale-up/down), the "
        "fleet drill (chaos-killed pool re-routed + canary rollout) and "
        "the sdc drill (bit-flip storm through the integrity plane) at "
        "small size — the CI lane registered in docs/testing.md",
    )
    ap.add_argument(
        "--list", action="store_true",
        help="list every scenario with a one-line description and exit",
    )
    # child-mode flags
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--elastic-child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--sdc-child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--serving-child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--live-child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--frontdoor-child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--frontdoor-oracle", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--fleet-pool-child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--round-sleep", type=float, default=0.0, help=argparse.SUPPRESS)
    ap.add_argument("--capacity", type=int, default=2, help=argparse.SUPPRESS)
    ap.add_argument("--rung", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--resume", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--ladder", default="1:2,2:4", help=argparse.SUPPRESS)
    ap.add_argument("--specs", help=argparse.SUPPRESS)
    ap.add_argument("--ckpt-dir", help=argparse.SUPPRESS)
    ap.add_argument("--out", help=argparse.SUPPRESS)
    ap.add_argument("--distributed", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--port", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--pair-id", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--nproc", type=int, default=1, help=argparse.SUPPRESS)
    ap.add_argument("--expect-resume-step", type=int, default=-1,
                    help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.list:
        for name in SCENARIOS:
            print(f"{name:<18} {SCENARIO_DESCRIPTIONS[name]}")
        return 0
    if args.elastic_child:
        return child_elastic_main(args)
    if args.sdc_child:
        return child_sdc_main(args)
    if args.serving_child:
        return child_serving_main(args)
    if args.live_child:
        return child_live_main(args)
    if args.frontdoor_child:
        return child_frontdoor_main(args)
    if args.frontdoor_oracle:
        return child_frontdoor_oracle(args)
    if args.fleet_pool_child:
        return child_fleet_pool_main(args)
    if args.child:
        return child_main(args)
    if args.scenario:
        # positional selection wins (and composes with --quick's sizing):
        # `python scripts/soak.py chaos --quick` is the CI registration
        args.scenarios = list(args.scenario)
        if args.quick:
            args.steps = min(args.steps, 6)
            args.timeout = min(args.timeout, 300)
    elif args.quick:
        args.scenarios = ["elastic_failover", "serving", "live_plane",
                          "frontdoor", "chaos", "fleet", "sdc"]
        args.steps = min(args.steps, 6)
        args.timeout = min(args.timeout, 300)
    return orchestrate(args)


if __name__ == "__main__":
    sys.exit(main())
