#!/usr/bin/env python
"""Merge / validate per-rank igg trace files (docs/observability.md).

``igg.dump_trace(dir)`` leaves one ``trace.p<rank>.json`` per process;
this tool joins any set of them into ONE Chrome-trace/Perfetto JSON on the
shared barrier-aligned clock (one track per rank, alignment offsets and
their honesty bound in ``otherData.clock_alignment``)::

    python scripts/igg_trace.py merge RUN_DIR -o merged.json
    python scripts/igg_trace.py merge RUN_DIR --device -o merged.json
    python scripts/igg_trace.py merge RUN_DIR --per-epoch -o m.json
    python scripts/igg_trace.py merge trace.p0.json trace.p1.json -o m.json
    python scripts/igg_trace.py validate merged.json
    python scripts/igg_trace.py summarize RUN_DIR
    python scripts/igg_trace.py request TRACE_ID RUN_DIR [-o req.json]
    python scripts/igg_trace.py export RUN_DIR --otlp -o spans.otlp.json

``--device`` additionally joins each rank's profiler capture
(``profile.p<rank>.json`` capture metas written by the ``IGG_PROFILE``
windowed capture, `implicitglobalgrid_tpu.utils.profiling`) as device-op
tracks on the same per-rank pids — host spans and device ops side by side
in ONE valid Chrome trace, aligned through the shared `named_scope`
namespace with the anchor uncertainty recorded in
``otherData.device_alignment``.

``summarize`` prints a per-span-name aggregate table (count, total,
p50/p99, max) over one or more per-rank dumps — the quick look that no
longer requires loading Perfetto.  Load ``merged.json`` at
https://ui.perfetto.dev (or chrome://tracing).

``request TRACE_ID`` reconstructs ONE request's causal tree from any set
of dumps — across pools, generations and re-routes (supervised restarts
leave ``trace.g<gen>.p<rank>.json`` dumps; directories pick those up
too) — printing the tree and its critical-path latency attribution,
writing the request-highlighted Chrome view with ``-o`` and the OTLP/JSON
slice with ``--otlp``.  A loud ``INCOMPLETE`` banner fires when any
contributing ring dropped spans.  ``export --otlp`` ships every closed
span as byte-stable OTLP/JSON (the Jaeger/Tempo ingest shape).
``merge --per-epoch`` merges a multi-generation dump dir as one trace
(one pid band per (generation, epoch) group) instead of refusing it.
Exit codes: 0 ok, 1 invalid trace, 2 bad input/usage.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _expand(inputs: list[str]) -> list[str]:
    """Trace files from a mix of files and directories (a directory means
    every ``trace.p*.json`` in it, plus the generation-suffixed
    ``trace.g<gen>.p*.json`` dumps a supervised restart leaves)."""
    paths: list[str] = []
    for item in inputs:
        if os.path.isdir(item):
            found = sorted(
                glob.glob(os.path.join(item, "trace.p*.json"))
                + glob.glob(os.path.join(item, "trace.g*.p*.json"))
            )
            if not found:
                raise FileNotFoundError(
                    f"{item}: no trace.p*.json files (run with "
                    f"IGG_TELEMETRY_DIR set and call igg.dump_trace)."
                )
            paths.extend(found)
        else:
            paths.append(item)
    return paths


def cmd_merge(args) -> int:
    from implicitglobalgrid_tpu.utils import tracing

    try:
        paths = _expand(args.inputs)
        doc = tracing.merge_trace_files(paths, per_epoch=args.per_epoch)
        if args.device:
            from implicitglobalgrid_tpu.utils import profiling

            # capture metas live next to the trace files: search directory
            # inputs AND the parent dirs of explicit trace.pN.json inputs
            # (the stale-refusal remedy says "merge the current run's
            # files explicitly" — --device must work in that form too)
            dirs: list[str] = []
            for item in args.inputs:
                d = item if os.path.isdir(item) else os.path.dirname(
                    os.path.abspath(item)
                )
                if d not in dirs:
                    dirs.append(d)
            metas: list[str] = []
            for d in dirs:
                metas.extend(profiling.find_capture_metas(d))
            if not metas:
                raise ValueError(
                    "--device: no profile.p*.json capture metas next to "
                    "the trace files (run with IGG_PROFILE=steps:A-B so "
                    "each rank captures a device window)."
                )
            profiling.attach_device_tracks(doc, metas)
    except (OSError, ValueError) as e:
        print(f"igg_trace: {e}", file=sys.stderr)
        return 2
    problems = tracing.validate_chrome_trace(doc)
    if problems:
        for p in problems:
            print(f"igg_trace: INVALID merged trace: {p}", file=sys.stderr)
        return 1
    out = json.dumps(doc)
    if args.output == "-":
        print(out)
    else:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(out)
        nspans = sum(1 for e in doc["traceEvents"] if e["ph"] == "X")
        ranks = sorted({e["pid"] for e in doc["traceEvents"]})
        print(
            f"igg_trace: wrote {args.output}: {nspans} span(s) across "
            f"rank(s) {ranks} — load it at https://ui.perfetto.dev",
            file=sys.stderr,
        )
    return 0


def render_span_table(stats: dict) -> str:
    """Fixed-width aggregate table (golden-pinned by tests/test_tracing.py:
    change the format deliberately and update the golden)."""
    head = (
        f"{'span':<32} {'count':>7} {'total_ms':>10} {'mean_ms':>9} "
        f"{'p50_ms':>9} {'p99_ms':>9} {'max_ms':>9}"
    )
    lines = [head, "-" * len(head)]
    for name, s in stats.items():
        lines.append(
            f"{name:<32} {s['count']:>7} {s['total_s'] * 1e3:>10.3f} "
            f"{s['mean_s'] * 1e3:>9.3f} {s['p50_s'] * 1e3:>9.3f} "
            f"{s['p99_s'] * 1e3:>9.3f} {s['max_s'] * 1e3:>9.3f}"
        )
    return "\n".join(lines)


def cmd_summarize(args) -> int:
    from implicitglobalgrid_tpu.utils import tracing

    try:
        paths = _expand(args.inputs)
        docs = [tracing._load_rank_trace(os.fspath(p)) for p in paths]
    except (OSError, ValueError) as e:
        print(f"igg_trace: {e}", file=sys.stderr)
        return 2
    stats = tracing.span_stats([d["spans"] for d in docs])
    if args.json:
        print(json.dumps(stats))
        return 0
    ranks = sorted(d["rank"] for d in docs)
    nspans = sum(len(d["spans"]) for d in docs)
    print(f"# {nspans} span(s) across rank(s) {ranks}")
    print(render_span_table(stats))
    return 0


def render_request_tree(tree: dict) -> str:
    """Indented causal-tree text: one line per span with rank/gen
    provenance and duration — the terminal view of `tracing.request_tree`
    (golden-shaped by tests/test_request_tracing.py)."""
    lines = [
        f"trace {tree['trace_id']}: {tree['spans']} span(s), "
        f"rank(s) {tree['ranks']}, gen(s) {tree['gens'] or '-'}"
    ]

    def _walk(nodes, depth):
        for n in nodes:
            where = f"rank {n['rank']}"
            if n.get("gen") is not None:
                where += f" gen {n['gen']}"
            lines.append(
                f"{'  ' * depth}- {n['name']}  [{where}]  "
                f"{n['dur_s'] * 1e3:.3f}ms"
            )
            _walk(n["children"], depth + 1)

    _walk(tree.get("roots", ()), 1)
    return "\n".join(lines)


def render_critical_path(cp: dict) -> str:
    """Latency-attribution table over `tracing.critical_path` output."""
    lines = [f"critical path: total {cp['total_s'] * 1e3:.3f}ms"]
    for seg, v in cp["segments"].items():
        lines.append(
            f"  {seg:<12} {v['s'] * 1e3:>10.3f}ms {v['share'] * 100:>6.1f}%"
        )
    return "\n".join(lines)


def cmd_request(args) -> int:
    from implicitglobalgrid_tpu.utils import tracing

    try:
        paths = _expand(args.inputs)
        docs = [tracing._load_rank_trace(os.fspath(p)) for p in paths]
    except (OSError, ValueError) as e:
        print(f"igg_trace: {e}", file=sys.stderr)
        return 2
    tree = tracing.request_tree(docs, args.trace_id)
    if not tree["spans"]:
        print(
            f"igg_trace: no spans for trace {args.trace_id} in "
            f"{len(docs)} dump(s).",
            file=sys.stderr,
        )
        return 2
    if tree["incomplete"]:
        # the ring evicted spans somewhere: the tree below is silently
        # partial and the reader must know before trusting it
        print(
            f"igg_trace: INCOMPLETE — contributing dump(s) dropped "
            f"{tree['dropped']} span(s) to ring overflow; raise "
            f"IGG_TRACE_RING and re-run for a full tree.",
            file=sys.stderr,
        )
    print(render_request_tree(tree))
    print(render_critical_path(tracing.critical_path(tree)))
    if args.output:
        view = tracing.request_chrome_trace(tree)
        problems = tracing.validate_chrome_trace(view)
        if problems:
            for p in problems:
                print(f"igg_trace: INVALID request view: {p}",
                      file=sys.stderr)
            return 1
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(json.dumps(view))
        print(
            f"igg_trace: wrote {args.output} (request-highlighted Chrome "
            f"view) — load it at https://ui.perfetto.dev",
            file=sys.stderr,
        )
    if args.otlp:
        out = tracing.otlp_trace(docs, trace_id=args.trace_id)
        problems = tracing.validate_otlp(out)
        if problems:
            for p in problems:
                print(f"igg_trace: INVALID OTLP export: {p}",
                      file=sys.stderr)
            return 1
        with open(args.otlp, "w", encoding="utf-8") as f:
            f.write(json.dumps(out, sort_keys=True, separators=(",", ":")))
        print(f"igg_trace: wrote {args.otlp} (OTLP/JSON)", file=sys.stderr)
    return 0


def cmd_export(args) -> int:
    from implicitglobalgrid_tpu.utils import tracing

    try:
        paths = _expand(args.inputs)
        docs = [tracing._load_rank_trace(os.fspath(p)) for p in paths]
    except (OSError, ValueError) as e:
        print(f"igg_trace: {e}", file=sys.stderr)
        return 2
    out = tracing.otlp_trace(docs, trace_id=args.trace_id)
    problems = tracing.validate_otlp(out)
    if problems:
        for p in problems:
            print(f"igg_trace: INVALID OTLP export: {p}", file=sys.stderr)
        return 1
    # byte-stable serialization: same dumps, same bytes (the golden-pin
    # contract — a collector diff means the data changed, not the tool)
    body = json.dumps(out, sort_keys=True, separators=(",", ":"))
    if args.output == "-":
        print(body)
    else:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(body)
        nspans = sum(
            len(ss["spans"])
            for rs in out["resourceSpans"]
            for ss in rs["scopeSpans"]
        )
        print(
            f"igg_trace: wrote {args.output}: {nspans} OTLP span(s) from "
            f"{len(docs)} dump(s)",
            file=sys.stderr,
        )
    return 0


def cmd_validate(args) -> int:
    from implicitglobalgrid_tpu.utils import tracing

    try:
        with open(args.trace, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"igg_trace: {args.trace}: {e}", file=sys.stderr)
        return 2
    problems = tracing.validate_chrome_trace(doc)
    for p in problems:
        print(f"igg_trace: {args.trace}: {p}", file=sys.stderr)
    if not problems:
        print(f"igg_trace: {args.trace}: valid", file=sys.stderr)
    return 1 if problems else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="igg_trace.py",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser("merge", help="join per-rank trace files")
    mp.add_argument("inputs", nargs="+",
                    help="trace.pN.json files and/or directories")
    mp.add_argument("-o", "--output", default="-",
                    help="merged trace path ('-' = stdout)")
    mp.add_argument("--device", action="store_true",
                    help="join each rank's IGG_PROFILE capture "
                         "(profile.p*.json metas in the input dirs) as "
                         "device-op tracks on the rank pids")
    mp.add_argument("--per-epoch", action="store_true",
                    help="merge a multi-generation dump dir (supervised "
                         "restarts) as one trace: one pid band per "
                         "(generation, epoch) group instead of refusing "
                         "the set")
    vp = sub.add_parser("validate", help="check a merged Chrome trace")
    vp.add_argument("trace")
    sp = sub.add_parser(
        "summarize", help="per-span-name aggregate table over rank dumps"
    )
    sp.add_argument("inputs", nargs="+",
                    help="trace.pN.json files and/or directories")
    sp.add_argument("--json", action="store_true",
                    help="machine-readable stats instead of the table")
    rp = sub.add_parser(
        "request",
        help="reconstruct one request's causal tree across dumps",
    )
    rp.add_argument("trace_id", help="the request's 32-hex trace id")
    rp.add_argument("inputs", nargs="+",
                    help="trace.pN.json files and/or directories (any mix "
                         "of pools/generations)")
    rp.add_argument("-o", "--output", default=None,
                    help="write the request-highlighted Chrome view here")
    rp.add_argument("--otlp", default=None, metavar="PATH",
                    help="write the request's OTLP/JSON slice here")
    ep = sub.add_parser(
        "export", help="OTLP/JSON export of every closed span"
    )
    ep.add_argument("inputs", nargs="+",
                    help="trace.pN.json files and/or directories")
    ep.add_argument("-o", "--output", default="-",
                    help="OTLP/JSON path ('-' = stdout)")
    ep.add_argument("--otlp", action="store_true",
                    help="accepted for symmetry; OTLP/JSON is the only "
                         "export format")
    ep.add_argument("--trace-id", default=None,
                    help="restrict the export to one request's spans")
    args = ap.parse_args(argv)
    if args.cmd == "merge":
        return cmd_merge(args)
    if args.cmd == "summarize":
        return cmd_summarize(args)
    if args.cmd == "request":
        return cmd_request(args)
    if args.cmd == "export":
        return cmd_export(args)
    return cmd_validate(args)


if __name__ == "__main__":
    raise SystemExit(main())
