#!/usr/bin/env python
"""Attribute / diff device-timeline profiler captures (docs/observability.md).

``IGG_PROFILE=steps:A-B`` (or `igg.profile_trace`) leaves a profiler
capture per rank; this tool turns the raw Chrome/Perfetto JSON into the
per-scope device-time attribution and the measured comm/compute overlap
fraction — the numbers the "cadence glue" gap (docs/performance.md) and
ROADMAP item 1's overlap acceptance are stated in::

    python scripts/igg_prof.py attribute RUN_DIR            # capture meta dir
    python scripts/igg_prof.py attribute trace.json.gz      # one trace file
    python scripts/igg_prof.py attribute PROFILER_LOGDIR    # jax.profiler dir
    python scripts/igg_prof.py diff RUN_A RUN_B             # cross-run drift

``attribute`` accepts a telemetry/run directory (newest
``profile.p<rank>.json`` capture meta per rank), a profiler log dir, or a
``*.trace.json[.gz]`` file, and prints the scope table + overlap fraction
(``--json`` for the machine-readable record).  ``diff`` attributes BOTH
inputs and names the scope that ate the regression (positive delta = B
spends more).  A malformed/truncated trace is a structured finding on
stdout and exit 1 — never a traceback.
Exit codes: 0 ok, 1 structured finding (bad trace), 2 bad input/usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _attribute_target(target: str) -> dict:
    """Attribution record for one CLI target (file / profiler logdir /
    run dir with capture metas).  Raises ValueError with the finding text
    on malformed input."""
    from implicitglobalgrid_tpu.utils import profiling

    if os.path.isfile(target):
        return profiling.attribute_trace(target)
    if not os.path.isdir(target):
        raise FileNotFoundError(f"{target}: no such file or directory")
    metas = profiling.find_capture_metas(target)
    if metas:
        # A run dir: attribute every rank's capture, roll ranks up.
        ranks = {}
        merged_ops: list = []
        for path in metas:
            with open(path, encoding="utf-8") as f:
                meta = json.load(f)
            # resolve relative to the meta's own dir too, so archived /
            # copied run dirs (cross-round diffing) stay attributable
            trace = profiling.resolve_trace_path(
                meta, os.path.dirname(os.path.abspath(path))
            )
            if not trace:
                ranks[str(meta.get("rank"))] = {
                    "error": "capture recorded no trace file"
                }
                continue
            doc = profiling.load_trace(trace)
            ops = profiling.device_ops(doc)
            # distinct pids per rank keep the overlap measure per-track
            for op in ops:
                op["pid"] = (meta.get("rank"), op["pid"])
            merged_ops.extend(ops)
            ranks[str(meta.get("rank"))] = profiling.attribute_ops(ops)
        rec = profiling.attribute_ops(merged_ops)
        rec["per_rank"] = ranks
        rec["trace"] = target
        return rec
    return profiling.attribute_capture(target)


def _finding(kind: str, target: str, error: Exception) -> int:
    print(
        json.dumps(
            {
                "finding": kind,
                "target": target,
                "error": f"{type(error).__name__}: {error}",
            }
        )
    )
    return 1


def cmd_attribute(args) -> int:
    from implicitglobalgrid_tpu.utils import profiling

    try:
        rec = _attribute_target(args.target)
    except FileNotFoundError as e:
        print(f"igg_prof: {e}", file=sys.stderr)
        return 2
    except (OSError, ValueError) as e:
        return _finding("profile.parse_failed", args.target, e)
    if args.json:
        print(json.dumps(rec))
    else:
        print(profiling.render_attribution_table(rec))
    return 0


def cmd_diff(args) -> int:
    from implicitglobalgrid_tpu.utils import profiling

    recs = []
    for target in (args.a, args.b):
        try:
            recs.append(_attribute_target(target))
        except FileNotFoundError as e:
            print(f"igg_prof: {e}", file=sys.stderr)
            return 2
        except (OSError, ValueError) as e:
            return _finding("profile.parse_failed", target, e)
    delta = profiling.attribution_delta(*recs)
    if args.json:
        print(json.dumps(delta))
    else:
        print(profiling.render_delta_table(delta))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="igg_prof.py",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    at = sub.add_parser(
        "attribute", help="per-scope device-time attribution of a capture"
    )
    at.add_argument("target",
                    help="trace file, profiler logdir, or run dir with "
                         "profile.p*.json capture metas")
    at.add_argument("--json", action="store_true",
                    help="machine-readable record instead of the table")
    df = sub.add_parser(
        "diff", help="attribute a drift between two runs/rounds"
    )
    df.add_argument("a", help="reference capture (file/logdir/run dir)")
    df.add_argument("b", help="candidate capture (file/logdir/run dir)")
    df.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    if args.cmd == "attribute":
        return cmd_attribute(args)
    return cmd_diff(args)


if __name__ == "__main__":
    raise SystemExit(main())
