#!/usr/bin/env python
"""igg_tune — the autotuner's operator CLI (docs/performance.md, Autotuning).

Subcommands over the versioned winner table (`implicitglobalgrid_tpu.tuning`):

``sweep``
    Run one search at an explicit (model, size, dtype[, npt]) point on the
    current backend: enumerate the admissible config space, prune it with
    the static cost-model prior, measure the survivors, persist the
    winner.  ``--dry-run`` stops after pruning and prints the candidate
    table (modeled columns only — nothing is compiled or measured);
    without it the table carries the measured column too.

``show``
    List the cache entries across both layers (primary + the committed
    seed layer), with config, provenance and measured numbers.

``seed``
    Ingest the committed ``BENCH_r*.json`` trajectory into seed entries
    (chip-measured winners with ``source: seed:bench_rNN`` provenance) —
    how the committed ``tuning/entries`` layer is produced, and how an
    environment that cannot re-measure gets the recorded winners.

``clear``
    Delete the PRIMARY layer's entries (the committed seed layer is repo
    content and is never touched).

Examples::

    igg_tune.py sweep --model diffusion3d --n 256 --nsteps 24 --dry-run
    igg_tune.py sweep --model porous_convection3d --n 256 --npt 12 --nsteps 2
    igg_tune.py show --json
    igg_tune.py seed --dry-run
    igg_tune.py clear

Exit code: 0 = success, 1 = the requested point produced no admissible
candidate beyond the default, 2 = setup/environment failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _cache(args):
    from implicitglobalgrid_tpu import tuning

    return tuning.TuneCache(primary=args.cache) if args.cache else \
        tuning.TuneCache()


def _fmt_mib(b):
    return f"{b / (1 << 20):.1f}" if b else "-"


def cmd_sweep(args) -> int:
    import jax

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu import tuning
    from implicitglobalgrid_tpu.tuning import search as _search
    from implicitglobalgrid_tpu.tuning import space as _space

    if args.topk:
        os.environ["IGG_TUNE_TOPK"] = str(args.topk)
    n = args.n
    dtype = jax.numpy.dtype(args.dtype)
    model = args.model
    module = _space.model_module(model)
    setup_kw = {"npt": args.npt} if model == "porous_convection3d" else {}
    grid_kw = {}
    if args.overlap:
        grid_kw.update(overlapx=args.overlap, overlapy=args.overlap,
                       overlapz=args.overlap)
    for ax in args.period or "":
        if ax not in "xyz":
            raise ValueError(
                f"--period axes must be from 'xyz', got {args.period!r}")
        grid_kw[f"period{ax}"] = 1
    if igg.grid_is_initialized():
        igg.finalize_global_grid()
    _state, params = module.setup(n, n, n, dtype=dtype, quiet=True,
                                  **setup_kw, **grid_kw)
    try:
        gg = igg.get_global_grid()
        extra = ({"npt": int(args.npt)}
                 if model == "porous_convection3d" else None)
        key = tuning.make_key(model, gg.nxyz, dtype, gg=gg, extra=extra,
                              nsteps=args.nsteps)
        # the table's rows come from the same pure functions the resolve
        # runs; the search itself (measure/decide/persist) goes THROUGH
        # `resolve_tuned_config`, so the CLI can never write an entry the
        # library path would shape differently
        candidates, rejected = _space.candidate_space(
            model, gg.nxyz, dtype.itemsize, nsteps=args.nsteps, gg=gg,
            npt=(extra or {}).get("npt"),
        )
        survivors, cut = _space.prune(candidates, _search._topk())
        measured = {}
        winner = None
        path = None
        if not args.dry_run and len(survivors) > 1:
            cache = _cache(args)

            def measure(cfg):
                t = _search._measure_model(module, params, args.nsteps, 0,
                                           dict(cfg))
                measured[json.dumps(cfg, sort_keys=True)] = t
                return t

            winner = _search.resolve_tuned_config(
                model, gg.nxyz, dtype, nsteps=args.nsteps, gg=gg,
                extra=extra, cache=cache, measure=measure,
            )
            path = cache.path_for(key)
    finally:
        igg.finalize_global_grid()

    rows = []
    for cand in survivors:
        ck = json.dumps(cand["config"], sort_keys=True)
        rows.append({**cand, "status": "measured" if measured else "survivor",
                     "t_chunk_s": measured.get(ck)})
    rows += [{**c, "status": "pruned"} for c in cut]
    rows += [{"config": c["config"], "modeled": None, "status": "rejected",
              "error": c["error"]} for c in rejected]
    doc = {"key": key, "dry_run": bool(args.dry_run), "winner": winner,
           "rows": rows}
    if path is not None:
        doc["cache_path"] = path
    if winner is not None and not measured:
        doc["note"] = ("cache hit: the stored winner was applied without "
                       "re-measuring — `igg_tune.py clear` first to force "
                       "a fresh search")
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        print(f"igg_tune sweep: {model} {key['size']} {key['dtype']} on "
              f"{key['backend']} ({key['topology']})")
        hdr = (f"{'config':40s} {'modeled GB/step':>15s} {'coll/step':>10s} "
               f"{'VMEM MiB':>9s} {'measured s':>11s}  status")
        print(hdr)
        for r in rows:
            m = r.get("modeled") or {}
            t = r.get("t_chunk_s")
            print(f"{json.dumps(r['config']):40s} "
                  f"{(m.get('bytes_per_step', 0) / 1e9):15.3f} "
                  f"{m.get('collectives_per_step', 0):10.2f} "
                  f"{_fmt_mib(m.get('vmem_bytes', 0)):>9s} "
                  f"{('%.4f' % t) if t is not None else '-':>11s}  "
                  f"{r['status']}"
                  + (f" ({r['error']})" if r.get("error") else ""))
        if winner is not None:
            print(f"winner: {json.dumps(winner)} -> {doc['cache_path']}")
            if doc.get("note"):
                print(f"({doc['note']})")
        elif args.dry_run:
            print("(dry run: nothing measured, nothing persisted)")
        else:
            print("(degenerate point: nothing admissible beyond the "
                  "default — nothing measured, nothing persisted)")
    # exit 1 = a degenerate tuning point: nothing admissible beyond the
    # default survived the prior (dry or measured alike — a measured sweep
    # that could only confirm the default still says so)
    return 0 if len(survivors) > 1 else 1


def cmd_show(args) -> int:
    from implicitglobalgrid_tpu import tuning

    entries = _cache(args).entries()
    if args.json:
        print(json.dumps(
            [{"path": p, "entry": doc} for p, doc in entries],
            indent=2, sort_keys=True,
        ))
        return 0
    if not entries:
        print("igg_tune: no cache entries (primary "
              f"{_cache(args).primary} and seed layer are empty)")
        return 0
    for path, doc in entries:
        if doc is None:
            print(f"{os.path.basename(path)}: UNPARSEABLE")
            continue
        try:
            key, config = tuning.validate_entry(doc)
        except ValueError as e:
            print(f"{os.path.basename(path)}: INVALID ({e})")
            continue
        meas = doc.get("measured") or {}
        teff = meas.get("teff_gbs")
        print(f"{key['model']:22s} {'x'.join(str(s) for s in key['size']):>13s} "
              f"{key['dtype']:8s} {key['backend']:4s} "
              f"{json.dumps(config):32s} {doc['source']:18s}"
              + (f" {teff:.0f} GB/s" if teff else ""))
    return 0


def cmd_seed(args) -> int:
    from implicitglobalgrid_tpu import tuning

    entries = tuning.seed_from_bench(
        REPO, _cache(args), backend=args.backend, write=not args.dry_run,
    )
    if args.json:
        print(json.dumps(entries, indent=2, sort_keys=True))
    else:
        for e in entries:
            print(f"seeded {e['key']['model']} {e['key']['size']} "
                  f"{json.dumps(e['config'])} from {e['source']}"
                  + (" (dry run)" if args.dry_run else ""))
        if not entries:
            print("igg_tune seed: no seedable extras in the committed "
                  "BENCH rounds")
    return 0


def cmd_clear(args) -> int:
    n = _cache(args).clear()
    print(f"igg_tune: removed {n} entr{'y' if n == 1 else 'ies'} from "
          f"{_cache(args).primary}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="igg_tune", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    ps = sub.add_parser("sweep", help="search one tuning point")
    ps.add_argument("--model", required=True,
                    choices=["diffusion3d", "acoustic3d",
                             "porous_convection3d"])
    ps.add_argument("--n", type=int, required=True, help="local cube size")
    ps.add_argument("--nsteps", type=int, default=8,
                    help="steps per chunk the cadence is tuned for")
    ps.add_argument("--dtype", default="float32")
    ps.add_argument("--npt", type=int, default=12,
                    help="porous PT iterations (key component, not tuned)")
    ps.add_argument("--overlap", type=int, default=None)
    ps.add_argument("--period", default=None,
                    help="periodic dims, e.g. 'z' (1-chip self-neighbor)")
    ps.add_argument("--topk", type=int, default=None,
                    help="override IGG_TUNE_TOPK for this sweep")
    ps.add_argument("--dry-run", action="store_true",
                    help="print the pruned candidate table, measure nothing")
    ps.add_argument("--json", action="store_true")
    ps.add_argument("--cache", default=None, help="primary cache dir")
    ps.set_defaults(fn=cmd_sweep)

    for name, fn, hlp in (("show", cmd_show, "list cache entries"),
                          ("clear", cmd_clear, "delete primary entries")):
        px = sub.add_parser(name, help=hlp)
        px.add_argument("--json", action="store_true")
        px.add_argument("--cache", default=None)
        px.set_defaults(fn=fn)

    pd = sub.add_parser("seed", help="ingest BENCH_r*.json winners")
    pd.add_argument("--backend", default="tpu",
                    help="backend the bench rounds ran on (key component)")
    pd.add_argument("--dry-run", action="store_true")
    pd.add_argument("--json", action="store_true")
    pd.add_argument("--cache", default=None)
    pd.set_defaults(fn=cmd_seed)

    args = p.parse_args(argv)
    sys.path.insert(0, REPO)
    try:
        return args.fn(args)
    except (OSError, ValueError) as e:
        print(f"igg_tune: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
