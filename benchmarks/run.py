"""Benchmark harness: T_eff (GB/s/chip) and weak-scaling efficiency.

The reference publishes only narrative numbers (`/root/reference/README.md:159-164`)
— no benchmark code.  This harness ships the measurements as code so every
number in `BASELINE.md` is reproducible.  One JSON line per config on stdout.

Configs (BASELINE.json):

    diffusion        3-D heat diffusion (configs 1, 2, 5 via --n/--dtype/mesh)
    acoustic         3-D acoustic staggered FDTD, overlap on/off (config 3)
    porous           porous convection PT solver (config 4, HydroMech analogue)
    weak             weak-scaling efficiency over sub-meshes of the available
                     devices (same local size per device, t(1)/t(N))

T_eff convention (ParallelStencil/IGG papers): only arrays that *must* stream
once per iteration count — temperature in+out for diffusion (2 passes);
P,V in+out for acoustic (8); fluxes+pressure in+out per PT iteration for
porous (8) — times local cells per chip, divided by measured time.

Usage:
    python benchmarks/run.py [diffusion|acoustic|porous|weak|all]
        [--n 256] [--steps 100] [--chunk 25] [--dtype float32] [--hide-comm]
"""

from __future__ import annotations

import argparse
import json
import os
import time


def _sync(state):
    import jax

    jax.block_until_ready(state)
    leaf = state[0] if isinstance(state, (tuple, list)) else state
    # Fetch ONE element of the process-local shard.  This is the only sync
    # proven honest on the tunneled benchmark backend: `block_until_ready`
    # (plain or via a dependent scalar) returns before the compute chain
    # finishes there.  The fetch costs a full tunnel round trip (~50-90 ms
    # measured), which `_time_steps` cancels with two-point timing.
    shard = leaf.addressable_shards[0].data
    float(shard[(0,) * shard.ndim])


def _time_steps(step, state, chunk: int, reps: int):
    """Per-step time by two-point window timing, median over ``reps``.

    Each rep times a window of K chained ``step`` calls (K*chunk fused steps)
    and a window of 2K calls, both ending in the same `_sync`; their
    difference is K*chunk steps' worth of real device work — including those
    calls' own (pipelined) dispatch, which a production loop pays too — with
    the constant per-window sync round trip cancelled.

    Window sizing is the load-bearing detail on the tunneled benchmark
    backend: the sync round trip there is large and drifts (~0.05-0.3 s
    observed), and queued work executes *under* it, so windows must be sized
    by device work, not wall time of a synced call.  K targets ~1.5 s of
    estimated pure work per base window, making the residual RTT drift a
    few-percent effect on the difference.  The per-rep differences are
    combined by median (robust to a drift spike in either window of one rep)
    and clamped into the physically possible band derived from the fastest
    2K window (see the comment at the clamp).
    """
    import jax

    state = step(*state)  # compile + warmup
    _sync(state)
    # Virtual-CPU meshes (weak-scaling code-path validation) share one core:
    # a window of unsynced dispatches starves the device threads past the
    # XLA-CPU collective rendezvous timeout.  Sync every call there — CPU
    # timings are code-path checks, not performance numbers.
    leaf = state[0] if isinstance(state, (tuple, list)) else state
    sync_each = leaf.devices().pop().platform == "cpu"

    def run_window(state, ncalls):
        for _ in range(ncalls):
            state = step(*state)
            if sync_each:
                jax.block_until_ready(state)
        _sync(state)
        return state

    # Sync-only round trip: state is already materialized, so this times the
    # fetch RTT alone.  Min over a few samples — a single sample can catch a
    # drift spike and (over-subtracted below) inflate K enormously.
    rtt_est = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        _sync(state)
        rtt_est = min(rtt_est, time.perf_counter() - t0)
    # Work-only estimate from one ~20-call window (single sync at the end);
    # subtracting the measured RTT keeps K honest on fast configs, where one
    # RTT can otherwise inflate the estimate severalfold and shrink the
    # window below the work target.  The subtraction is capped at half the
    # elapsed time so a spiky RTT sample can never zero the estimate out.
    ncal = 20
    t0 = time.perf_counter()
    state = run_window(state, ncal)
    elapsed = time.perf_counter() - t0
    t_call_est = (elapsed - min(rtt_est, 0.5 * elapsed)) / ncal
    K = max(4, int(round(1.5 / t_call_est)))
    diffs = []
    b2_min = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        state = run_window(state, K)
        b1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        state = run_window(state, 2 * K)
        b2 = time.perf_counter() - t0
        b2_min = min(b2_min, b2)
        diffs.append((b2 - b1) / (K * chunk))
    diffs.sort()
    t_it = diffs[len(diffs) // 2]
    # Physical bounds from the fastest 2K window: it ran 2K*chunk steps plus
    # a sync RTT, so per-step time cannot exceed b2_min/(2K*chunk) — and
    # cannot be below (b2_min - rtt_bound)/(2K*chunk) either, which guards
    # against a drift pattern (slow K-windows, fast 2K-windows) driving the
    # median difference toward zero and inflating the reported speed without
    # bound.  rtt_bound is deliberately loose (>3x the worst observed RTT) so
    # the lower clamp only fires on pathological drift, not on honest
    # measurements; with ~3 s 2K windows it caps artifact inflation at ~1.5x.
    rtt_bound = 1.0
    lo = max((b2_min - rtt_bound) / (2 * K * chunk), 1e-9)
    t_it = min(max(t_it, lo), b2_min / (2 * K * chunk))
    # Per-rep spread (VERDICT r3 #7): the raw per-rep differences, pre-clamp,
    # so cross-round drift on the time-shared chip is interpretable from the
    # artifact alone (a tight spread + a >5% cross-round shift = real change;
    # a wide spread = tenancy noise).
    spread = {
        "reps": reps,
        "t_it_ms_min": round(diffs[0] * 1e3, 4),
        "t_it_ms_med": round(diffs[len(diffs) // 2] * 1e3, 4),
        "t_it_ms_max": round(diffs[-1] * 1e3, 4),
    }
    return t_it, state, spread


def _pipelined_provenance(pipelined, fused_k, model_mod, local_shape, itemsize,
                          fused_tile, support_kwargs=None):
    """(metric suffix, extra record) for a ``pipelined`` request.

    Same deterministic-provenance contract as `_fused_provenance`: the
    admissibility check is the model's own (`pipelined_support_error`), so
    a config whose split fell back to the serialized schedule is recorded
    as such instead of labeling a serialized number "pipelined" — and the
    AUTO default's decision is recorded too (``auto-on``/``auto-off``),
    since auto engages the pipelined schedule whenever admissible and an
    unmarked metric would make cross-round drift uninterpretable.  The
    metric-name suffix changes only for an explicit ``pipelined=True``
    (auto keeps prior rounds' names comparable)."""
    if not fused_k:
        return "", None
    bx, by = fused_tile if fused_tile is not None else (None, None)
    err = model_mod.pipelined_support_error(
        tuple(local_shape), fused_k, itemsize, bx, by, **(support_kwargs or {})
    )
    if pipelined is None:
        return "", {"pipelined": "auto-on" if err is None else f"auto-off: {err}"}
    if not pipelined:
        return "", {"pipelined": "off"}
    if err is None:
        return "_piped", {"pipelined": "on"}
    return "", {"pipelined": f"fallback: {err}"}


def _fused_provenance(fused_k, support_error, local_shape, itemsize, fused_tile,
                      z_active=False):
    """Metric suffix + path record for a ``fused_k`` request.

    Deterministic provenance (same envelope checks the model's fallback
    uses): a config the kernel envelope rejects ran the warn-once XLA
    cadence, and the emitted metric name must say so — otherwise an XLA
    number gets recorded under a fused-kernel label.  ``z_active`` mirrors
    the model's path selection: on z-communicating grids the z-patch
    envelope is consulted first (it admits full-y tiles the plain envelope
    does not, and vice versa at large volumes).
    """
    if not fused_k:
        return "", None
    bx, by = fused_tile if fused_tile is not None else (None, None)
    shape = tuple(local_shape)
    ok = support_error(shape, fused_k, itemsize, bx, by) is None
    if z_active and not ok:
        ok = support_error(shape, fused_k, itemsize, bx, by, zpatch=True) is None
    if ok:
        return f"_fused{fused_k}", "pallas-fused"
    return f"_fused{fused_k}fb", "xla-fallback"


def _grid_kwargs(overlap, period):
    """Shared setup kwargs for overlap/period CLI knobs (one definition for
    all three benchmarks).  Validates the period axis letters eagerly — a
    typo'd axis would otherwise surface as an opaque setup() TypeError."""
    kw = {} if overlap is None else dict(
        overlapx=overlap, overlapy=overlap, overlapz=overlap
    )
    for ax in period or "":
        if ax not in "xyz":
            raise ValueError(f"--period axes must be from 'xyz', got {period!r}")
        kw[f"period{ax}"] = 1
    return kw


def _emit(name, teff, t_it, extra=None, emit=True):
    rec = {
        "metric": name,
        "value": round(teff, 2),
        "unit": "GB/s/chip",
        "t_it_ms": round(t_it * 1e3, 4),
    }
    if extra:
        rec.update(extra)
    # Fold every emitted measurement into the process telemetry registry
    # (docs/observability.md): the driver's final snapshot then carries the
    # same numbers the JSON lines do — one source of truth for collectors.
    from implicitglobalgrid_tpu.utils import telemetry as _telemetry

    _telemetry.gauge(f"bench.{name}.teff_gbs").set(teff)
    _telemetry.histogram("bench.teff_gbs").record(teff)
    _telemetry.histogram("bench.t_it_s").record(t_it)
    if emit:
        print(json.dumps(rec), flush=True)
    return rec


def bench_diffusion(n=256, chunk=25, reps=4, dtype="float32", hide_comm=False,
                    devices=None, emit=True, fused_k=None, fused_tile=None,
                    exchange_every=1, overlap=None, force_spmd=False, period=None,
                    pipelined=None):
    """Benchmarks run with ``donate=False``: buffer donation costs ~3x on the
    tunneled single-chip backend used for the round measurements (measured:
    375 -> 119 GB/s at 256^3 f32; identical HLO, runtime-side penalty), and
    T_eff measures streaming, not allocation.

    ``fused_k``: use the temporally-blocked Pallas kernel (k steps per HBM
    pass) — the lever that takes T_eff past the raw streaming bound.
    """
    import jax

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    if igg.grid_is_initialized():
        igg.finalize_global_grid()
    okw = _grid_kwargs(overlap, period)
    state, params = diffusion3d.setup(
        n, n, n, dtype=jax.numpy.dtype(dtype), hide_comm=hide_comm, quiet=True,
        devices=devices, force_spmd=force_spmd, **okw,
    )
    step = diffusion3d.make_multi_step(
        params, chunk, donate=False, fused_k=fused_k, fused_tile=fused_tile,
        exchange_every=exchange_every, pipelined=pipelined,
    )
    from implicitglobalgrid_tpu.ops.pallas_stencil import fused_support_error

    from implicitglobalgrid_tpu.ops.halo import dim_has_halo_activity

    fsuf, fpath = _fused_provenance(
        fused_k, fused_support_error, igg.local_shape(state[0]),
        jax.numpy.dtype(dtype).itemsize, fused_tile,
        z_active=dim_has_halo_activity(igg.get_global_grid(), 2),
    )
    psuf, prec = _pipelined_provenance(
        pipelined, fused_k, diffusion3d, igg.local_shape(state[0]),
        jax.numpy.dtype(dtype).itemsize, fused_tile,
    )
    t_it, state, spread = _time_steps(step, state, chunk, reps)
    gg = igg.get_global_grid()
    igg.finalize_global_grid()
    nbytes = 2 * n**3 * jax.numpy.dtype(dtype).itemsize
    extra = {"dims": list(gg.dims), "nprocs": gg.nprocs, "spread": spread}
    if fpath:
        extra["path"] = fpath
    if prec:
        extra.update(prec)
    return _emit(
        f"diffusion3d_{n}_{dtype}"
        + (f"_period{period}" if period else "")
        + ("_overlap" if hide_comm else "")
        + fsuf
        + psuf
        + (f"_xch{exchange_every}" if exchange_every > 1 else ""),
        nbytes / t_it / 1e9,
        t_it,
        extra,
        emit=emit,
    )


def bench_acoustic(n=192, chunk=25, reps=4, dtype="float32", hide_comm=False, devices=None,
                   emit=True, exchange_every=1, overlap=None, fused_k=None,
                   fused_tile=None, period=None, pipelined=None):
    """``fused_k``: the temporally-blocked staggered Pallas kernel
    (`ops/pallas_leapfrog.py`, k leapfrog steps per HBM pass) — needs
    ``n % 128 == 0`` in the minor dimension (use ``--n 256``)."""
    import jax

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import acoustic3d

    if igg.grid_is_initialized():
        igg.finalize_global_grid()
    okw = _grid_kwargs(overlap, period)
    state, params = acoustic3d.setup(
        n, n, n, dtype=jax.numpy.dtype(dtype), hide_comm=hide_comm, quiet=True,
        devices=devices, **okw,
    )
    step = acoustic3d.make_multi_step(
        params, chunk, donate=False, exchange_every=exchange_every,
        fused_k=fused_k, fused_tile=fused_tile, pipelined=pipelined,
    )
    from implicitglobalgrid_tpu.ops.pallas_leapfrog import fused_support_error

    from implicitglobalgrid_tpu.ops.halo import dim_has_halo_activity

    fsuf, fpath = _fused_provenance(
        fused_k, fused_support_error, igg.local_shape(state[0]),
        jax.numpy.dtype(dtype).itemsize, fused_tile,
        z_active=dim_has_halo_activity(igg.get_global_grid(), 2),
    )
    psuf, prec = _pipelined_provenance(
        pipelined, fused_k, acoustic3d, igg.local_shape(state[0]),
        jax.numpy.dtype(dtype).itemsize, fused_tile,
    )
    t_it, state, spread = _time_steps(step, state, chunk, reps)
    gg = igg.get_global_grid()
    igg.finalize_global_grid()
    nbytes = 8 * n**3 * jax.numpy.dtype(dtype).itemsize  # P,Vx,Vy,Vz in+out
    extra = {"dims": list(gg.dims), "nprocs": gg.nprocs, "spread": spread}
    if fpath:
        extra["path"] = fpath
    if prec:
        extra.update(prec)
    return _emit(
        f"acoustic3d_{n}_{dtype}"
        + (f"_period{period}" if period else "")
        + ("_overlap" if hide_comm else "")
        + fsuf
        + psuf
        + (f"_xch{exchange_every}" if exchange_every > 1 else ""),
        nbytes / t_it / 1e9,
        t_it,
        extra,
        emit=emit,
    )


def bench_porous(n=128, chunk=4, reps=3, npt=10, dtype="float32", devices=None,
                 emit=True, exchange_every=1, overlap=None, fused_k=None,
                 fused_tile=None, period=None, pipelined=None):
    """``chunk`` whole time steps (= ``chunk*npt`` PT iterations) per call via
    `porous_convection3d.make_multi_step` — one XLA program, like the other
    models' production paths.  ``fused_k``: the temporally-blocked PT kernel
    (`ops/pallas_pt.py`; needs ``n % 128 == 0`` — use ``--n 256``)."""
    import jax

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import porous_convection3d as pc

    if igg.grid_is_initialized():
        igg.finalize_global_grid()
    okw = _grid_kwargs(overlap, period)
    state, params = pc.setup(
        n, n, n, dtype=jax.numpy.dtype(dtype), npt=npt, quiet=True, devices=devices,
        **okw,
    )
    step = pc.make_multi_step(
        params, chunk, donate=False, exchange_every=exchange_every,
        fused_k=fused_k, fused_tile=fused_tile, pipelined=pipelined,
    )
    from implicitglobalgrid_tpu.ops.pallas_pt import fused_support_error

    from implicitglobalgrid_tpu.ops.halo import dim_has_halo_activity

    fsuf, fpath = _fused_provenance(
        fused_k, fused_support_error, igg.local_shape(state[0]),
        jax.numpy.dtype(dtype).itemsize, fused_tile,
        z_active=dim_has_halo_activity(igg.get_global_grid(), 2),
    )
    psuf, prec = _pipelined_provenance(
        pipelined, fused_k, pc, igg.local_shape(state[0]),
        jax.numpy.dtype(dtype).itemsize, fused_tile,
        support_kwargs={"npt": npt},
    )
    t_step, state, spread = _time_steps(step, state, chunk, reps)
    gg = igg.get_global_grid()
    igg.finalize_global_grid()
    # Per PT iteration: qDx,qDy,qDz,Pf in+out = 8 array passes.
    t_pt = t_step / npt
    nbytes = 8 * n**3 * jax.numpy.dtype(dtype).itemsize
    extra = {"dims": list(gg.dims), "nprocs": gg.nprocs,
             "t_pt_ms": round(t_pt * 1e3, 4), "spread": spread}
    if fpath:
        extra["path"] = fpath
    if prec:
        extra.update(prec)
    return _emit(
        f"porous_convection3d_{n}_{dtype}_npt{npt}"
        + (f"_period{period}" if period else "")
        + fsuf
        + psuf
        + (f"_xch{exchange_every}" if exchange_every > 1 else ""),
        nbytes / t_pt / 1e9,
        t_step,
        extra,
        emit=emit,
    )


def bench_tuned_vs_default(model="diffusion", n=256, chunk=24, reps=3,
                           dtype="float32", npt=12, overlap=None, period=None,
                           emit=True):
    """ISSUE 13: the autotuner's closed loop — time the DEFAULT-config
    production chunk and the ``autotune=True`` chunk at the same point and
    record the ratio.  ``tuned_speedup = t_default / t_tuned`` is a gated
    perf key (`analysis.perf.GATED_KEYS`): a tuner that starts picking
    slower-than-default configs (or a regression erasing a tuned win)
    drops the ratio past the band and fails `scripts/check_perf.py`.

    Both runs share one grid and start from fresh `setup` states; the
    tuned build resolves through the winner cache (`IGG_TUNE_CACHE` — a
    prior `igg_tune.py seed`/sweep makes this a pure cache hit, a cold
    cache pays one short search, and the record says which happened).
    """
    import jax

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import (
        acoustic3d,
        diffusion3d,
        porous_convection3d,
    )
    from implicitglobalgrid_tpu.utils import telemetry as _tele

    mod, model_name, setup_kw = {
        "diffusion": (diffusion3d, "diffusion3d", {}),
        "acoustic": (acoustic3d, "acoustic3d", {}),
        "porous": (porous_convection3d, "porous_convection3d",
                   {"npt": npt}),
    }[model]
    if igg.grid_is_initialized():
        igg.finalize_global_grid()
    okw = _grid_kwargs(overlap, period)
    state, params = mod.setup(
        n, n, n, dtype=jax.numpy.dtype(dtype), quiet=True, **setup_kw, **okw
    )

    def _counters():
        snap = _tele.snapshot()
        return {k: v for k, v in snap.get("counters", {}).items()
                if k.startswith("tune.")}

    before = _counters()
    step_default = mod.make_multi_step(params, chunk, donate=False)
    t_def, _, spread_def = _time_steps(step_default, state, chunk, reps)
    step_tuned = mod.make_multi_step(params, chunk, donate=False,
                                     autotune=True)
    after = _counters()
    state2, _ = mod.setup(n, n, n, dtype=jax.numpy.dtype(dtype),
                          init_grid=False, **setup_kw)
    t_tun, _, spread_tun = _time_steps(step_tuned, state2, chunk, reps)

    from implicitglobalgrid_tpu import tuning

    gg = igg.get_global_grid()
    key = tuning.make_key(
        model_name, gg.nxyz, jax.numpy.dtype(dtype), gg=gg,
        extra={"npt": int(npt)} if model == "porous" else None,
        nsteps=chunk,
    )
    entry = tuning.TuneCache().lookup(key)
    igg.finalize_global_grid()
    hits = after.get("tune.cache_hit", 0) - before.get("tune.cache_hit", 0)
    rec = {
        "model": model_name,
        "n": n,
        "tuned_speedup": round(t_def / t_tun, 4),
        "t_default_ms": round(t_def * 1e3, 4),
        "t_tuned_ms": round(t_tun * 1e3, 4),
        "config": entry["config"] if entry else {},
        "source": entry["source"] if entry else None,
        "cache": "hit" if hits else "miss",
        "spread": {"default": spread_def, "tuned": spread_tun},
    }
    if emit:
        print(json.dumps({"metric": f"{model_name}_{n}_{dtype}_tuned_vs_default",
                          "value": rec["tuned_speedup"], "unit": "x", **rec}),
              flush=True)
    return rec


#: Standard member job length (steps) the members/s/chip figure normalizes
#: to: members_per_s = B / (t_step * BATCH_JOB_STEPS) / nchips — a
#: completed-standard-jobs-per-second rate, so the sweep is comparable
#: across rounds whatever chunk the timing used.
BATCH_JOB_STEPS = 100


def bench_batch(n=128, chunk=16, reps=3, dtype="float32", B_list=(1, 2, 4, 8),
                emit=True, fused_k=None, fused_tile=None, exchange_every=1,
                overlap=None, period=None):
    """Batched ensemble serving throughput (ISSUE 8): members/s/chip over a
    B sweep of the vmapped diffusion cadence (`make_multi_step(batch=True)`,
    the `serving.ServingLoop` round step).

    The claim under test: batching is a near-free throughput multiplier —
    B members cost ONE collective pair per exchanged dimension (see the
    ``batch_hlo`` A/B for the structural proof), so members/s/chip should
    scale ~×B until the batch saturates HBM.  ``extras.sweep`` records one
    row per B (each row's ``members_per_s`` is a gated perf metric,
    `analysis.perf.GATED_KEYS`); the headline value is the best B's rate,
    with ``throughput_multiplier`` = best/B1.
    """
    import jax

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import _batched, diffusion3d
    from implicitglobalgrid_tpu.utils import telemetry as _telemetry

    okw = _grid_kwargs(overlap, period)
    sweep = {}
    nprocs = 1
    for B in B_list:
        if igg.grid_is_initialized():
            igg.finalize_global_grid()
        bstate, params = _batched.batched_setup(
            diffusion3d, n, n, n, batch=B,
            dtype=jax.numpy.dtype(dtype), quiet=True, **okw,
        )
        step = diffusion3d.make_multi_step(
            params, chunk, donate=False, batch=True, fused_k=fused_k,
            fused_tile=fused_tile, exchange_every=exchange_every,
        )
        t_it, _state, spread = _time_steps(step, bstate, chunk, reps)
        gg = igg.get_global_grid()
        nprocs = gg.nprocs
        igg.finalize_global_grid()
        members_per_s = B / (t_it * BATCH_JOB_STEPS) / nprocs
        sweep[f"B{B}"] = {
            "members_per_s": round(members_per_s, 4),
            "member_steps_per_s": round(B / t_it / nprocs, 2),
            "t_step_ms": round(t_it * 1e3, 4),
            "spread": spread,
        }
        _telemetry.gauge(f"bench.batch.B{B}.members_per_s").set(
            members_per_s
        )
    b1 = sweep.get("B1", {}).get("members_per_s") or None
    best_key = max(sweep, key=lambda k: sweep[k]["members_per_s"])
    best = sweep[best_key]["members_per_s"]
    rec = {
        "metric": f"diffusion3d_batch_{n}_{dtype}",
        "value": best,
        "unit": "members/s/chip",
        "members_per_s": best,
        "best_B": int(best_key[1:]),
        "job_steps": BATCH_JOB_STEPS,
        "nprocs": nprocs,
        "sweep": sweep,
        "throughput_multiplier": round(best / b1, 3) if b1 else None,
    }
    if emit:
        print(json.dumps(rec), flush=True)
    return rec


def batch_hlo_ab(B=8, emit=True):
    """The batched exchange's compiled-HLO collective A/B (ISSUE 8
    acceptance): the B-member coalesced exchange must emit EXACTLY the
    unbatched program's collective-permute count, with payload bytes ×B.
    Structural (XLA:CPU 8-device mesh) — run it from any backend via the
    subprocess driver (`bench.py`'s `_cpu_mesh_json`)."""
    from implicitglobalgrid_tpu.analysis import ir
    from implicitglobalgrid_tpu.analysis.costmodel import text_census

    c1 = text_census(ir.compile_program(ir.EXCHANGE_HLO_PROGRAM).text)
    cB = text_census(ir._compile_batched_exchange_program(B=B).text)
    rec = {
        "metric": "batch_hlo_collectives_ab",
        "B": B,
        "b1_collective_permutes": c1["collective_permutes"],
        "bB_collective_permutes": cB["collective_permutes"],
        "collectives_equal": (
            c1["collective_permutes"] == cB["collective_permutes"]
        ),
        "b1_payload_bytes": c1["collective_payload_bytes"],
        "bB_payload_bytes": cB["collective_payload_bytes"],
        "payload_ratio": round(
            cB["collective_payload_bytes"]
            / max(c1["collective_payload_bytes"], 1),
            3,
        ),
    }
    if emit:
        print(json.dumps(rec), flush=True)
    return rec


def bench_halo_coalesce(n=32, width=2, reps=3, emit=True):
    """Coalesced-vs-per-field exchange A/B (ISSUE 5) on the porous 5-field
    shape set, with collective counts and per-hop payload bytes read from
    the OPTIMIZED HLO of each variant's exchange program.

    Runs on whatever mesh the backend offers (dims (2,2,2) + periodic z on
    the suite's 8-device layout — every dimension exchanges).  On a 1-chip
    backend all partners are self-copies and NO collectives exist either
    way, so `bench.py` drives this on the virtual 8-device CPU mesh in a
    subprocess (a CODE-PATH/structure record there — CPU wall times are
    not performance numbers; the structural counts are the point).
    """
    import jax
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.ops import halo as H
    from implicitglobalgrid_tpu.utils.hlo_analysis import collective_payloads

    if igg.grid_is_initialized():
        igg.finalize_global_grid()
    ndev = len(jax.devices())
    dims = dict(dimx=2, dimy=2, dimz=2) if ndev >= 8 else {}
    igg.init_global_grid(n, n, n, periodz=1, quiet=True,
                         overlapx=2 * width, overlapy=2 * width,
                         overlapz=2 * width, **dims)
    gg = igg.get_global_grid()
    rng = np.random.default_rng(0)
    shapes = [(n, n, n)] + [
        tuple(n + (1 if d == ax else 0) for d in range(3)) for ax in range(3)
    ] + [(n, n, n)]
    from jax.sharding import NamedSharding, PartitionSpec as P

    fields = tuple(
        jax.device_put(
            rng.random(tuple(gg.dims[d] * s[d] for d in range(3)))
            .astype(np.float32),
            NamedSharding(gg.mesh, P(*igg.AXIS_NAMES[:3])),
        )
        for s in shapes
    )
    sig = tuple((H.local_shape(A, gg), str(A.dtype)) for A in fields)
    rec = {"metric": f"halo_coalesce_ab_5field_{n}cube_w{width}",
           "nfields": len(fields), "dims": list(gg.dims)}
    for name, coalesce in (("per_field", False), ("coalesced", True)):
        fn = H._global_update_fn(gg, sig, width, False, coalesce)
        hlo = fn.lower(*fields).compile().as_text()
        hops = collective_payloads(hlo)
        t_call, _, spread = _time_steps(
            lambda *fs: fn(*fs), fields, 1, reps
        )
        rec[name] = {
            "n_collective_permutes": len(hops),
            "payload_bytes_total": sum(h["bytes"] for h in hops),
            "t_call_ms": round(t_call * 1e3, 4),
            "spread": spread,
        }
    igg.finalize_global_grid()
    rec["collectives_ratio"] = round(
        rec["per_field"]["n_collective_permutes"]
        / max(rec["coalesced"]["n_collective_permutes"], 1), 2
    )
    from implicitglobalgrid_tpu.utils import telemetry as _telemetry

    _telemetry.gauge("bench.halo_coalesce_ab.collectives_ratio").set(
        rec["collectives_ratio"]
    )
    if emit:
        print(json.dumps(rec), flush=True)
    return rec


def bench_diffusion_grad(n=256, chunk=8, reps=3, dtype="float32", fused_k=4,
                         overlap=None, period=None, emit=True):
    """Gradient-path throughput record (`fused_with_xla_grad`): time
    ``jax.grad`` through the fused cadence against the forward step, so an
    adjoint user can predict step cost (docs/performance.md's gradient-path
    row).  The backward pass recomputes + differentiates the XLA-cadence
    twin (rematerialization), so the expected cost is roughly one fused
    forward + two XLA-cadence-scale passes.
    """
    import jax
    import jax.numpy as jnp

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    if igg.grid_is_initialized():
        igg.finalize_global_grid()
    okw = _grid_kwargs(overlap, period)
    state, params = diffusion3d.setup(
        n, n, n, dtype=jax.numpy.dtype(dtype), quiet=True, **okw
    )
    step = diffusion3d.make_multi_step(
        params, chunk, donate=False, fused_k=fused_k
    )
    t_fwd, state, spread_f = _time_steps(step, state, chunk, reps)

    gfn = jax.jit(jax.grad(lambda T, Cp: jnp.sum(step(T, Cp)[0])))

    def gstep(T, Cp):
        # the gradient wrt T feeds back as the next "T": diffusion's VJP is
        # value-independent (linear model), so this is a pure timing loop
        return gfn(T, Cp), Cp

    t_grad, _, spread_g = _time_steps(gstep, state, chunk, reps)
    igg.finalize_global_grid()
    nbytes = 2 * n**3 * jax.numpy.dtype(dtype).itemsize
    rec = _emit(
        f"diffusion3d_grad_{n}_{dtype}_fused{fused_k}"
        + (f"_period{period}" if period else ""),
        nbytes / t_grad / 1e9,  # A_eff convention applied to the grad step
        t_grad,
        {
            "t_fwd_ms": round(t_fwd * 1e3, 4),
            "grad_over_fwd": round(t_grad / t_fwd, 3),
            "spread": spread_g,
            "fwd_spread": spread_f,
            "note": (
                "value = A_eff/t of ONE grad step (forward fused chunk + "
                "rematerialized XLA-cadence forward + backward)"
            ),
        },
        emit=emit,
    )
    return rec


def aot_weak_proxy(dims=(4, 4, 16), nloc=512, k=4, emit=True, pipelined=None):
    """North-star-topology AOT compile proxy (VERDICT r4 missing #2).

    Compile the production fused z-patch cadence for a 256-chip
    ``dims``-mesh at BASELINE config 5's per-chip volume (``nloc``^3 f32)
    and report the program's collective-permute hops with per-hop payload
    BYTES from the optimized HLO.  This is a STRUCTURAL record, not a
    measurement — multi-chip hardware is unavailable here; what it
    establishes is that (a) the north-star program compiles, (b) the z
    exchange moves packed thin slabs (not full arrays), and (c) the hop
    payloads feed the written efficiency budget in docs/performance.md.
    Uses the shared synthetic-GlobalGrid AOT scaffold
    (`implicitglobalgrid_tpu.utils.aot`), like scripts/verify_tpu.py's
    checks 9-11.

    ``pipelined``: forward the cadence knob and additionally report
    `pipelined_overlap_evidence` — the count of (collective, kernel
    launch) pairs the optimized HLO leaves mutually independent, i.e. the
    interior passes XLA may schedule across the in-flight
    `collective-permute`s.  A serialized compile of the same config is the
    differential control (`bench.py` records both).
    """
    import math as _math

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from implicitglobalgrid_tpu.utils.aot import synthetic_topology_grid
    from implicitglobalgrid_tpu.utils.hlo_analysis import (
        collective_payloads,
        pipelined_overlap_evidence,
    )

    nchips = _math.prod(dims)
    o = 2 * k
    with synthetic_topology_grid(dims, (nloc,) * 3, (o,) * 3) as (gg, mesh):
        from implicitglobalgrid_tpu.models import diffusion3d

        params = diffusion3d.Params(
            dx=0.1, dy=0.1, dz=0.1, dt=0.1 * 0.1 / 8.1,
            dtype=jax.numpy.float32,
        )
        step = diffusion3d.make_multi_step(
            params, k, donate=False, fused_k=k, pipelined=pipelined
        )
        shapes = tuple(
            jax.ShapeDtypeStruct(
                tuple(dims[d] * nloc for d in range(3)),
                jax.numpy.float32,
                sharding=NamedSharding(mesh, P("x", "y", "z")),
            )
            for _ in range(2)
        )
        fn = step._build(gg, shapes, jax.tree.flatten(shapes)[1])
        txt = fn.lower(*shapes).compile().as_text()
        psel = diffusion3d.pipelined_support_error((nloc,) * 3, k, 4, gg=gg)

    hops = collective_payloads(txt)
    by_shape: dict = {}
    for h in hops:
        r = by_shape.setdefault(h["shape"], {"count": 0, "bytes_per_hop": h["bytes"]})
        r["count"] += 1
    total = sum(h["bytes"] for h in hops)
    rec = {
        "metric": f"aot_weak_proxy_{nchips}chip_{nloc}cube"
        + ("_piped" if pipelined and psel is None else ""),
        "dims": list(dims),
        "n_collective_permutes": len(hops),
        "per_hop": by_shape,
        "total_exchange_bytes_per_chunk": total,
        "note": (
            "structural AOT compile record at the north-star topology — "
            "NOT a timing; see docs/performance.md's weak-scaling budget"
        ),
    }
    if pipelined is not None:
        rec["pipelined"] = (
            "on" if (pipelined and psel is None)
            else ("off" if not pipelined else f"fallback: {psel}")
        )
        rec["overlap_evidence"] = pipelined_overlap_evidence(txt)
    if emit:
        print(json.dumps(rec), flush=True)
    return rec


def bench_profile_attribution(n=16, steps=6, emit=True):
    """ISSUE 15: the measured device-timeline record — a windowed profiler
    capture around a short diffusion run on THIS backend's communicating
    mesh, parsed into per-scope device-time attribution and the measured
    comm/compute overlap fraction (`utils.profiling`, docs/observability.md
    "Device timeline").  On the virtual CPU mesh the numbers are code-path
    records (one core timeshares the devices), but the overlap fraction is
    still the real union-intersection of the capture's collective vs kernel
    intervals — the measured twin of `hlo_analysis.
    pipelined_overlap_evidence`'s structural count, and the number ROADMAP
    item 5(c) wants next to ``efficiency...achieved_fraction``.
    """
    import tempfile

    import jax

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.utils import profiling

    import shutil

    if igg.grid_is_initialized():
        igg.finalize_global_grid()
    igg.init_global_grid(n, n, n, quiet=True)
    logdir = tempfile.mkdtemp(prefix="igg_profile_attr_")
    try:
        state, params = diffusion3d.setup(n, n, n, init_grid=False)
        step = diffusion3d.make_step(params, donate=False)
        state = jax.block_until_ready(step(*state))  # compile OUTSIDE the window
        with profiling.profile_trace(logdir):
            for _ in range(steps):
                state = jax.block_until_ready(step(*state))
        rec = profiling.attribute_capture(logdir)
        profiling.publish_attribution(rec)
    finally:
        igg.finalize_global_grid()
        shutil.rmtree(logdir, ignore_errors=True)  # captures are MBs per run
    out = {
        "metric": "profile_attribution",
        "value": rec["overlap"]["fraction"],
        "unit": "overlap_fraction",
        "n": n,
        "steps": steps,
        # flat twin of overlap.fraction: the REPORTED perf-gate key
        # (analysis.perf.REPORTED_KEYS walks extras for this exact name)
        "overlap_fraction": rec["overlap"]["fraction"],
        "scope_seconds": rec["scope_seconds"],
        "overlap": rec["overlap"],
        "n_device_ops": rec["n_device_ops"],
        "device_seconds": rec["device_seconds"],
    }
    if emit:
        print(json.dumps(out), flush=True)
    return out


def bench_weak_scaling(n=128, chunk=25, reps=4, dtype="float32", hide_comm=False,
                       model="diffusion", npt=10):
    """Weak scaling: same local n^3 per device on growing sub-meshes.

    Parallel efficiency = t(1 device) / t(N devices); ~1.0 means the halo
    exchange is fully hidden or negligible.  All counts run ``force_spmd``
    so the 1-device baseline goes through the same shard_map/SPMD execution
    path as the multi-device runs — otherwise the 1-device fast path (see
    docs/performance.md) would make the ratio conflate SPMD dispatch
    overhead with communication cost.

    ``model="porous"`` runs the HydroMech analogue instead — BASELINE
    config 4 is *porous* weak scaling (npt PT iterations per step, the
    communication-heaviest pattern); the porous model has no force_spmd
    lever, so its 1-device point keeps the plain-jit fast path and the
    reported efficiency is conservative on 1-core virtual meshes.
    """
    import jax

    devs = jax.devices()
    counts = []
    c = 1
    while c <= len(devs):
        counts.append(c)
        c *= 2
    if counts[-1] != len(devs):  # non-power-of-two: still measure the full mesh
        counts.append(len(devs))
    results = {}
    for c in counts:
        if model == "porous":
            rec = bench_porous(
                n=n, chunk=max(chunk // npt, 1), reps=reps, npt=npt,
                dtype=dtype, devices=devs[:c],
            )
        else:
            rec = bench_diffusion(
                n=n, chunk=chunk, reps=reps, dtype=dtype, hide_comm=hide_comm,
                devices=devs[:c], force_spmd=True,
            )
        results[c] = rec["t_it_ms"]
    base = results[1]
    effs = {c: round(base / t, 4) for c, t in results.items()}
    print(
        json.dumps(
            {
                "metric": f"weak_scaling_{model}3d_{n}_{dtype}"
                + ("_overlap" if hide_comm else ""),
                "value": effs[counts[-1]],
                "unit": "parallel_efficiency",
                "per_count": effs,
            }
        ),
        flush=True,
    )


def main():
    p = argparse.ArgumentParser()
    p.add_argument("what", nargs="?", default="all",
                   choices=["diffusion", "acoustic", "porous", "weak",
                            "coalesce", "grad", "batch", "batch_hlo",
                            "reconcile", "tuned", "profile", "all"])
    p.add_argument("--model", default="diffusion",
                   choices=["diffusion", "acoustic", "porous"],
                   help="model for the tuned mode (tuned-vs-default A/B)")
    p.add_argument("--batch-sizes", default="1,2,4,8",
                   help="comma-separated B sweep for the batch mode")
    p.add_argument("--n", type=int, default=None)
    # None sentinel: per-mode defaults below (25 everywhere; 24 for the
    # tuned A/B, whose cadence candidates need a ladder-divisible chunk)
    p.add_argument("--chunk", type=int, default=None)
    p.add_argument("--reps", type=int, default=4)
    p.add_argument("--dtype", default="float32")
    p.add_argument("--hide-comm", action="store_true")
    p.add_argument("--npt", type=int, default=10)
    p.add_argument("--fused-k", type=int, default=None,
                   help="temporally-blocked Pallas kernel: k steps per HBM pass")
    p.add_argument("--exchange-every", type=int, default=1,
                   help="XLA slab cadence: w steps per width-w halo exchange "
                        "(needs a deep-halo grid: --overlap >= 2w)")
    p.add_argument("--overlap", type=int, default=None,
                   help="grid overlap in every dimension (deep halos for "
                        "--fused-k/--exchange-every on communicating grids)")
    p.add_argument("--period", default=None,
                   help="periodic dimensions, e.g. 'z' or 'xz' (the 1-chip "
                        "self-neighbor configs that exercise real exchanges)")
    p.add_argument("--pipelined", action="store_true", default=None,
                   help="boundary-first pipelined group schedule (fused_k "
                        "cadences); omit for the models' auto default")
    p.add_argument("--serialized", dest="pipelined", action="store_false",
                   help="force the serialized group schedule")
    p.add_argument("--weak-model", default="diffusion",
                   choices=["diffusion", "porous"],
                   help="model for the weak-scaling config (BASELINE config 4 "
                        "is porous weak scaling)")
    a = p.parse_args()
    tuned_chunk = 24 if a.chunk is None else a.chunk
    if a.chunk is None:
        a.chunk = 25  # the historical default of every other mode
    kw = dict(chunk=a.chunk, reps=a.reps, dtype=a.dtype)
    if a.what in ("diffusion", "all"):
        bench_diffusion(n=a.n or 256, hide_comm=a.hide_comm, fused_k=a.fused_k,
                        exchange_every=a.exchange_every, overlap=a.overlap,
                        period=a.period, pipelined=a.pipelined, **kw)
    if a.what in ("acoustic", "all"):
        bench_acoustic(n=a.n or (256 if a.fused_k else 192), hide_comm=a.hide_comm,
                       fused_k=a.fused_k, exchange_every=a.exchange_every,
                       overlap=a.overlap, period=a.period, pipelined=a.pipelined,
                       **kw)
    if a.what in ("porous", "all"):
        # porous steps contain npt inner iterations, so the outer chunk stays
        # small unless the user asked for porous explicitly
        porous_chunk = a.chunk if a.what == "porous" else 4
        # npt need not divide fused_k anymore: the ragged PT schedule
        # (round 4) chunks any npt into even kernel chunks.
        npt = a.npt
        bench_porous(n=a.n or (256 if a.fused_k else 128), chunk=porous_chunk,
                     reps=a.reps, npt=npt, dtype=a.dtype, fused_k=a.fused_k,
                     exchange_every=a.exchange_every, overlap=a.overlap,
                     period=a.period, pipelined=a.pipelined)
    if a.what in ("weak", "all"):
        bench_weak_scaling(n=a.n or 128, chunk=a.chunk, reps=a.reps,
                           dtype=a.dtype, hide_comm=a.hide_comm,
                           model=a.weak_model, npt=a.npt)
    if a.what == "coalesce":
        bench_halo_coalesce(n=a.n or 32, reps=a.reps)
    if a.what == "batch":
        bench_batch(
            n=a.n or 128, chunk=a.chunk, reps=a.reps, dtype=a.dtype,
            B_list=tuple(int(b) for b in a.batch_sizes.split(",")),
            fused_k=a.fused_k, exchange_every=a.exchange_every,
            overlap=a.overlap, period=a.period,
        )
    if a.what == "batch_hlo":
        batch_hlo_ab()
    if a.what == "profile":
        # Device-timeline attribution (ISSUE 15): windowed capture ->
        # per-scope device seconds + measured overlap fraction, one JSON
        # line (bench.py runs this on the virtual CPU mesh as
        # extras.profile_attribution).
        bench_profile_attribution(n=a.n or 16)
    if a.what == "reconcile":
        # Cost-model reconciliation (ISSUE 10): fresh XLA:CPU compiles of
        # the cadence matrix -> achieved_fraction per model, one JSON line
        # (bench.py runs this mode on the virtual CPU mesh and joins the
        # result with its measured teffs as extras.efficiency).
        from implicitglobalgrid_tpu.analysis.reconcile import reconcile_report

        print(json.dumps(reconcile_report(source="compiled")), flush=True)
    if a.what == "grad":
        bench_diffusion_grad(n=a.n or 256, chunk=a.chunk, reps=a.reps,
                             dtype=a.dtype, fused_k=a.fused_k or 4,
                             overlap=a.overlap, period=a.period)
    if a.what == "tuned":
        # the other modes' default chunk (25) divides NO fused_k rung — the
        # tuned A/B defaults to a cadence-friendly 24; an EXPLICIT --chunk
        # (25 included) is always honored
        bench_tuned_vs_default(
            model=a.model, n=a.n or 256, chunk=tuned_chunk, reps=a.reps,
            dtype=a.dtype, npt=a.npt, overlap=a.overlap, period=a.period,
        )


if __name__ == "__main__":
    # Direct invocation (`python benchmarks/run.py ...`) puts benchmarks/ on
    # sys.path, not the repo root where the package lives.
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    main()
