"""Telemetry subsystem tests (docs/observability.md).

Covers the registry semantics (counter/gauge/histogram, disabled-mode
no-op), the JSONL event-log schema round-trip, the per-step metrics every
model's ``run()`` emits (wall time, steps/s, T_eff), the named profiler
annotations landing in compiled-HLO op metadata (the toolchain-independent
stand-in for a live `jax.profiler` capture on this CPU-only environment),
and the Prometheus/JSON exposition of `igg.dump_metrics`.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.utils import telemetry as tele


@pytest.fixture(autouse=True)
def _fresh_registry():
    tele.reset()
    yield
    tele.reset()


# -- Registry semantics -------------------------------------------------------


def test_counter_gauge_histogram_semantics():
    c = tele.counter("t.count")
    c.inc()
    c.inc(4)
    assert tele.counter("t.count") is c  # one instance per name
    assert c.value == 5

    g = tele.gauge("t.gauge")
    g.set(2.5)
    g.set(7)
    assert tele.gauge("t.gauge").value == 7.0

    h = tele.histogram("t.hist")
    for v in range(1, 101):
        h.record(float(v))
    s = h.summary()
    assert s["count"] == 100 and s["min"] == 1.0 and s["max"] == 100.0
    assert s["sum"] == pytest.approx(5050.0)
    assert s["mean"] == pytest.approx(50.5)
    assert 40 <= s["p50"] <= 61 and s["p90"] >= s["p50"] and s["p99"] >= s["p90"]

    snap = tele.snapshot()
    assert snap["counters"]["t.count"] == 5
    assert snap["gauges"]["t.gauge"] == 7.0
    assert snap["histograms"]["t.hist"]["count"] == 100


def test_histogram_reservoir_bounded_and_deterministic():
    h = tele.histogram("t.res")
    for v in range(10_000):
        h.record(float(v))
    assert h.count == 10_000
    assert len(h._samples) == tele.RESERVOIR_SIZE
    # Seeded PRNG: the same record sequence yields the same reservoir.
    h2 = tele.Histogram("t.res2")
    for v in range(10_000):
        h2.record(float(v))
    assert h._samples == h2._samples


def test_disabled_mode_takes_zero_allocation_branch(monkeypatch, tmp_path):
    monkeypatch.setenv("IGG_TELEMETRY", "0")
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    assert not tele.enabled()
    # The acceptance contract: disabled accessors return the SHARED no-op
    # singleton (no per-call allocation) and the step loop is None, so the
    # models' loops reduce to one `is not None` check per step.
    assert tele.counter("t.x") is tele.NOOP
    assert tele.gauge("t.y") is tele.NOOP
    assert tele.histogram("t.z") is tele.NOOP
    tele.NOOP.inc()
    tele.NOOP.set(1.0)
    tele.NOOP.record(1.0)
    assert tele.step_loop("m", bytes_per_step=8) is None
    tele.event("t.never", foo=1)
    assert list(tmp_path.iterdir()) == []  # no event file, no registry entry
    snap = tele.snapshot()
    assert snap["counters"] == {} and snap["histograms"] == {}


def test_disabled_model_run_records_nothing(monkeypatch):
    monkeypatch.setenv("IGG_TELEMETRY", "0")
    from implicitglobalgrid_tpu.models import diffusion3d

    diffusion3d.run(1, 8, 8, 8, quiet=True)
    assert tele.snapshot()["counters"] == {}


# -- Event log ----------------------------------------------------------------


def test_event_jsonl_schema_roundtrip(monkeypatch, tmp_path):
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    import time

    t0 = time.time()
    tele.event("unit.test", step=3, detail="abc")
    tele.event("unit.test2", nested={"a": 1})
    path = tmp_path / "events.jsonl"  # single process = rank 0
    assert path.is_file()
    events = tele.read_events(path)
    assert [e["type"] for e in events] == ["unit.test", "unit.test2"]
    e = events[0]
    # Schema: absolute timestamp, rank/pid/coords tags, payload verbatim.
    assert {"ts", "type", "rank", "pid", "coords"} <= set(e)
    assert t0 <= e["ts"] <= time.time()
    assert e["rank"] == 0 and e["pid"] == os.getpid()
    assert e["step"] == 3 and e["detail"] == "abc"
    assert events[1]["nested"] == {"a": 1}
    # Append-only: a second emitter call extends, never truncates.
    tele.event("unit.test3")
    assert len(tele.read_events(path)) == 3


def test_event_coords_tagged_when_grid_up(monkeypatch, tmp_path):
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    tele.event("before.grid")
    igg.init_global_grid(8, 8, 8, quiet=True)
    tele.event("with.grid")
    events = tele.read_events(tmp_path / "events.jsonl")
    assert events[0]["coords"] is None
    assert events[1]["coords"] == list(igg.get_global_grid().coords)


def test_event_rank_hint_during_bringup(monkeypatch, tmp_path):
    """Bring-up events (before the runtime can answer process_index) must be
    tagged and FILED under the rank `init_distributed` staged via
    `set_rank_hint` — not misattributed to rank 0 (code-review finding)."""
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    tele.set_rank_hint(3)
    tele.event("bringup.retry")
    path = tmp_path / "events.p3.jsonl"
    assert path.is_file()
    (e,) = tele.read_events(path)
    assert e["rank"] == 3
    tele.reset()  # reset drops the hint with the registry
    tele.event("after.reset")
    (e2,) = tele.read_events(tmp_path / "events.jsonl")
    assert e2["rank"] == 0


def test_watchdog_deadline_exceeded_event(monkeypatch, tmp_path):
    """A watchdog scope outliving its deadline leaves the timeline marker
    (the observable proxy for the faulthandler dump)."""
    import time as _time

    from implicitglobalgrid_tpu.utils.resilience import watchdog

    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    with watchdog(0.05):
        _time.sleep(0.12)
    events = tele.read_events(tmp_path / "events.jsonl")
    (e,) = [x for x in events if x["type"] == "watchdog.deadline_exceeded"]
    assert e["elapsed_s"] > e["timeout_s"] == 0.05
    snap = tele.snapshot()
    assert snap["counters"]["resilience.watchdog_deadline_exceeded"] == 1


def test_event_non_serializable_payload_stringified(monkeypatch, tmp_path):
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    tele.event("odd.payload", obj=object())
    (e,) = tele.read_events(tmp_path / "events.jsonl")
    assert "object object" in e["obj"]


# -- Per-step metrics from the models' run loops ------------------------------


@pytest.mark.parametrize(
    "model_name,run_kwargs,nt",
    [
        ("diffusion3d", {}, 3),
        ("acoustic3d", {}, 2),
        ("porous_convection3d", {"npt": 2}, 1),
    ],
)
def test_model_run_emits_per_step_metrics(model_name, run_kwargs, nt):
    import importlib

    mod = importlib.import_module(
        f"implicitglobalgrid_tpu.models.{model_name}"
    )
    mod.run(nt, 8, 8, 8, quiet=True, **run_kwargs)
    snap = tele.snapshot()
    assert snap["counters"][f"{model_name}.steps"] == nt
    step_s = snap["histograms"][f"{model_name}.step_seconds"]
    assert step_s["count"] == nt and step_s["min"] > 0
    teff = snap["histograms"][f"{model_name}.t_eff_gbs"]
    assert teff["count"] == nt and teff["min"] > 0
    assert snap["gauges"][f"{model_name}.steps_per_s"] > 0


def test_heartbeat_line_and_event(monkeypatch, tmp_path, capfd):
    monkeypatch.setenv("IGG_HEARTBEAT_EVERY", "1")
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    from implicitglobalgrid_tpu.models import diffusion3d

    diffusion3d.run(2, 8, 8, 8, quiet=True)
    err = capfd.readouterr().err
    assert "[igg.telemetry] diffusion3d step" in err
    assert "T_eff" in err
    events = tele.read_events(tmp_path / "events.jsonl")
    types = [e["type"] for e in events]
    assert types.count("heartbeat") == 2
    # init emits the clock-sync anchor first (utils.tracing), then the run
    assert types[0] == "clock.sync"
    assert types[1] == "run.start" and types[-1] == "run.complete"
    hb = next(e for e in events if e["type"] == "heartbeat")
    assert hb["model"] == "diffusion3d" and hb["t_eff_gbs"] > 0
    # single-process run: no skew probe ran and no serving pool exists, so
    # the extended context attaches neither section (absence is explicit)
    assert "skew" not in hb and "serving" not in hb


def test_teff_bytes_model():
    igg.init_global_grid(8, 8, 8, quiet=True)
    T = igg.zeros((8, 8, 8), "float32")
    V = igg.zeros((9, 8, 8), "float32")
    # 2 * sum(global nbytes): each must-stream field once in + once out.
    assert tele.teff_bytes([T]) == 2 * T.nbytes
    assert tele.teff_bytes([T, V]) == 2 * (T.nbytes + V.nbytes)


# -- Instrumented hot paths ---------------------------------------------------


def test_update_halo_counters():
    igg.init_global_grid(
        8, 8, 8, periodx=1, overlapx=4, overlapy=4, overlapz=4, quiet=True
    )
    T = igg.zeros((8, 8, 8), "float64")
    T = igg.update_halo(T)
    T = igg.update_halo(T, width=2)
    snap = tele.snapshot()
    assert snap["counters"]["halo.exchanges"] == 2
    assert snap["counters"]["halo.fields"] == 2
    # Slab payload model: all three dims are active on the default 2x2x2
    # mesh (periodic x + interior neighbors), 2 slabs/dim of 8*8 f64 planes;
    # the width-2 call moves twice the width-1 call's bytes.
    per_plane = 8 * 8 * 8  # elements * itemsize
    w1 = 3 * 2 * per_plane
    assert snap["counters"]["halo.bytes"] == w1 + 2 * w1
    assert snap["histograms"]["halo.slab_bytes"]["count"] == 2


def test_gather_registry_fold():
    from implicitglobalgrid_tpu.ops import gather as gather_mod

    igg.init_global_grid(8, 8, 8, quiet=True)
    A = igg.zeros((8, 8, 8), "float32")
    got = igg.gather(A)
    assert got is not None
    snap = tele.snapshot()
    assert snap["counters"]["gather.calls"] == 1
    assert snap["counters"]["gather.calls.local"] == 1
    assert snap["counters"]["gather.host_bytes"] == got.nbytes
    # The compat alias mirrors the registry's last-call view.
    assert gather_mod.last_gather_stats["path"] == "local"


def test_checkpoint_events_and_counters(monkeypatch, tmp_path):
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path / "tele"))
    igg.init_global_grid(8, 8, 8, quiet=True)
    T = igg.zeros((8, 8, 8), "float32")
    ckdir = tmp_path / "ck"
    path = igg.save_checkpoint(ckdir, (T,), 2)
    igg.restore_checkpoint(path, like=(T,))
    igg.save_checkpoint(ckdir, (T,), 4)
    igg.prune_checkpoints(ckdir, keep=1)
    snap = tele.snapshot()
    assert snap["counters"]["checkpoint.saves"] == 2
    assert snap["counters"]["checkpoint.restores"] == 1
    assert snap["counters"]["checkpoint.prunes"] == 1
    events = tele.read_events(tmp_path / "tele" / "events.jsonl")
    types = [e["type"] for e in events]
    # the init-time clock-sync anchor leads, then the checkpoint sequence
    assert types == [
        "clock.sync",
        "checkpoint.saved",
        "checkpoint.restore",
        "checkpoint.saved",
        "checkpoint.prune",
    ]
    restore = next(e for e in events if e["type"] == "checkpoint.restore")
    assert restore["mode"] == "same_topology" and restore["step"] == 2


def test_corrupt_checkpoint_fallback_event(monkeypatch, tmp_path):
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path / "tele"))
    igg.init_global_grid(8, 8, 8, quiet=True)
    T = igg.zeros((8, 8, 8), "float32")
    ckdir = tmp_path / "ck"
    igg.save_checkpoint(ckdir, (T,), 2)
    newest = igg.save_checkpoint(ckdir, (T,), 4)
    shard = os.path.join(newest, "shards_p0.npz")
    with open(shard, "r+b") as f:
        f.seek(10)
        f.write(b"\xff")
    latest = igg.latest_checkpoint(ckdir)
    assert latest.endswith("step_00000002")
    events = tele.read_events(tmp_path / "tele" / "events.jsonl")
    fb = [e for e in events if e["type"] == "checkpoint.fallback"]
    assert fb and "corrupt" in fb[0]["problem"]
    assert tele.snapshot()["counters"]["checkpoint.fallbacks"] >= 1


# -- Named profiler annotations ----------------------------------------------
#
# This toolchain cannot run a TPU profiler capture; the toolchain-
# independent check (the ISSUE's jaxpr-level fallback) is that the
# `named_scope` names land in the compiled executable's op metadata — the
# exact strings a Perfetto trace groups ops under.


def test_pipelined_schedule_scopes_in_compiled_hlo():
    from implicitglobalgrid_tpu.models._fused import (
        run_pipelined_group_schedule,
    )

    def boundary(ki, c):
        return (c * 2.0,), ["pend"]

    def interior(ki, c, b_out, pend):
        return jnp.sin(b_out[0]) + c

    def f(x):
        return run_pipelined_group_schedule([1, 1], boundary, interior, x)

    txt = jax.jit(f).lower(jnp.ones((8,))).compile().as_text()
    assert "igg_ring_pass" in txt
    assert "igg_interior_pass" in txt


def test_slab_exchange_scopes_in_compiled_hlo():
    from jax.sharding import PartitionSpec as P

    from implicitglobalgrid_tpu.ops.halo import (
        begin_slab_exchange,
        finish_slab_exchange,
    )
    from implicitglobalgrid_tpu.utils.compat import shard_map

    igg.init_global_grid(8, 8, 8, periodx=1, quiet=True)
    gg = igg.get_global_grid()

    def local(T):
        pends = begin_slab_exchange((T,), (0, 1, 2), width=1)
        (T,) = finish_slab_exchange((T,), pends)
        return T

    mapped = shard_map(
        local,
        mesh=gg.mesh,
        in_specs=(P("x", "y", "z"),),
        out_specs=P("x", "y", "z"),
        check_vma=False,
    )
    T = igg.zeros((8, 8, 8), "float32")
    txt = jax.jit(mapped).lower(T).compile().as_text()
    assert "igg_slab_exchange_begin" in txt
    assert "igg_slab_exchange_finish" in txt
    # Trace-time counters: one begin/finish schedule was traced.
    snap = tele.snapshot()
    assert snap["counters"]["halo.begin_slab_traces"] == 1
    assert snap["counters"]["halo.finish_slab_traces"] == 1


def test_compat_shims_are_context_managers():
    from implicitglobalgrid_tpu.utils.compat import (
        named_scope,
        trace_annotation,
    )

    with named_scope("igg_test_scope"):
        pass
    with trace_annotation("igg_test_annotation"):
        pass


# -- Public surface: snapshot + dumps -----------------------------------------


def test_dump_metrics_json_and_prometheus(tmp_path):
    tele.counter("d.count").inc(3)
    tele.gauge("d.gauge").set(1.5)
    h = tele.histogram("d.hist")
    for v in (1.0, 2.0, 3.0):
        h.record(v)
    json_path, prom_path = igg.dump_metrics(tmp_path / "metrics")
    with open(json_path) as f:
        snap = json.load(f)
    assert snap["counters"]["d.count"] == 3
    assert snap["histograms"]["d.hist"]["count"] == 3
    prom = open(prom_path).read()
    assert "# TYPE igg_d_count_total counter" in prom
    assert "igg_d_count_total 3" in prom
    assert "# TYPE igg_d_gauge gauge" in prom
    assert "# TYPE igg_d_hist summary" in prom
    assert 'igg_d_hist{quantile="0.5"} 2.0' in prom
    assert "igg_d_hist_sum 6.0" in prom and "igg_d_hist_count 3" in prom
    # Every sample line is `name[{labels}] value` with a numeric value.
    for line in prom.splitlines():
        if not line or line.startswith("#"):
            continue
        name, value = line.rsplit(None, 1)
        assert name.startswith("igg_")
        float(value)


def test_snapshot_is_json_serializable():
    tele.counter("s.c").inc()
    tele.histogram("s.h").record(0.25)
    igg.init_global_grid(8, 8, 8, quiet=True)
    snap = igg.telemetry_snapshot()
    rt = json.loads(json.dumps(snap))
    assert rt["counters"]["s.c"] == 1
    assert rt["coords"] == list(igg.get_global_grid().coords)


# -- Batched serving metrics + event schema (ISSUE 8) -------------------------


def test_serving_metrics_and_event_schema(monkeypatch, tmp_path):
    """The serving loop's observability contract (docs/observability.md):
    ``serving.active_members`` tracks the pool live, the retire family of
    counters splits by outcome, per-member T_eff is recorded per round,
    per-tenant step counters accumulate, and every ``serving.*`` event is
    tagged with member/slot/tenant."""
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path / "tele"))
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.serving import Request, ServingLoop

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    _, params = diffusion3d.setup(8, 8, 8, init_grid=False)
    loop = ServingLoop(diffusion3d, params, capacity=2, steps_per_round=1)

    def req(scale, steps, tenant):
        s, _ = diffusion3d.setup(8, 8, 8, init_grid=False, ic_scale=scale)
        return Request(state=s, max_steps=steps, tenant=tenant)

    loop.submit(req(1.0, 2, "alice"))
    loop.submit(req(1.1, 1, "bob"))
    snap = tele.snapshot()
    assert snap["gauges"]["serving.active_members"] == 2
    loop.run(max_rounds=10)

    snap = tele.snapshot()
    c = snap["counters"]
    assert c["serving.admitted_total"] == 2
    assert c["serving.retired_total"] == 2
    assert c.get("serving.evicted_total", 0) == 0
    assert c["serving.tenant.alice.steps"] == 2
    assert c["serving.tenant.bob.steps"] == 1
    assert c["serving.rounds"] == loop.rounds
    assert snap["gauges"]["serving.active_members"] == 0
    # per-member T_eff tagging: one histogram sample per active member per
    # round (round 1: both members, round 2: alice alone)
    assert snap["histograms"]["serving.member_t_eff_gbs"]["count"] == 3

    events = tele.read_events(tmp_path / "tele" / "events.jsonl")
    serving = [e for e in events if e["type"].startswith("serving.")]
    assert {e["type"] for e in serving} == {"serving.admit",
                                           "serving.retire"}
    for e in serving:
        assert {"member", "slot", "tenant"} <= set(e), e
    retires = [e for e in serving if e["type"] == "serving.retire"]
    assert {e["tenant"] for e in retires} == {"alice", "bob"}
    assert all(e["status"] == "completed" for e in retires)


def test_serving_disabled_telemetry_is_noop(monkeypatch):
    """``IGG_TELEMETRY=0``: the loop still serves, nothing is recorded."""
    monkeypatch.setenv("IGG_TELEMETRY", "0")
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.serving import Request, ServingLoop

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    s, params = diffusion3d.setup(8, 8, 8, init_grid=False)
    loop = ServingLoop(diffusion3d, params, capacity=1, steps_per_round=1)
    m = loop.submit(Request(state=s, max_steps=1, tenant="x"))
    res = loop.run(max_rounds=5)
    assert res[m].status == "completed"
    monkeypatch.delenv("IGG_TELEMETRY")
    assert tele.snapshot()["counters"] == {}


def test_gather_member_counter_folds_into_gather_family(monkeypatch):
    from implicitglobalgrid_tpu.models import _batched

    igg.init_global_grid(8, 8, 8, quiet=True)
    A = igg.zeros((8, 8, 8), "float32")
    B = _batched.stack_fields(A, A)
    got = igg.gather(B, member=1)
    assert got is not None and got.shape == (16, 16, 16)  # dims (2,2,2)
    snap = tele.snapshot()
    assert snap["counters"]["gather.member_calls"] == 1
    assert snap["counters"]["gather.calls"] == 1  # the slice gather itself


# -- Tenant-series cardinality cap (ISSUE 10 satellite) -----------------------


def test_tenant_counter_caps_distinct_series(monkeypatch):
    """Tenant strings arrive from requests: the per-tenant counter family
    must stay bounded.  Past ``IGG_TELEMETRY_MAX_TENANTS`` distinct
    tenants, new ones fold into ``serving.tenant.__other__.steps`` while
    existing tenants keep their own series — and the family's TOTAL stays
    exact."""
    monkeypatch.setenv("IGG_TELEMETRY_MAX_TENANTS", "2")
    tele.tenant_counter("alice").inc(3)
    tele.tenant_counter("bob").inc(2)
    # cap reached: carol and dave fold into the overflow series
    tele.tenant_counter("carol").inc(5)
    tele.tenant_counter("dave").inc(7)
    # existing tenants keep attributing to their own series
    tele.tenant_counter("alice").inc(1)
    c = tele.snapshot()["counters"]
    tenant_keys = {k for k in c if k.startswith("serving.tenant.")}
    assert tenant_keys == {
        "serving.tenant.alice.steps",
        "serving.tenant.bob.steps",
        tele.TENANT_OVERFLOW,
    }
    assert c["serving.tenant.alice.steps"] == 4
    assert c["serving.tenant.bob.steps"] == 2
    assert c[tele.TENANT_OVERFLOW] == 12
    assert sum(c[k] for k in tenant_keys) == 18  # nothing lost to the cap


def test_tenant_counter_default_cap_and_disabled(monkeypatch):
    monkeypatch.delenv("IGG_TELEMETRY_MAX_TENANTS", raising=False)
    for i in range(tele.MAX_TENANTS_DEFAULT + 5):
        tele.tenant_counter(f"t{i}").inc()
    c = tele.snapshot()["counters"]
    distinct = [
        k for k in c
        if k.startswith("serving.tenant.") and k != tele.TENANT_OVERFLOW
    ]
    assert len(distinct) == tele.MAX_TENANTS_DEFAULT
    assert c[tele.TENANT_OVERFLOW] == 5
    monkeypatch.setenv("IGG_TELEMETRY", "0")
    assert tele.tenant_counter("x") is tele.NOOP


def test_serving_loop_tenant_flood_stays_bounded(monkeypatch):
    """Regression: the serving loop's per-tenant counters ride
    `tenant_counter`, so a flood of one-request tenants cannot grow the
    registry unboundedly."""
    monkeypatch.setenv("IGG_TELEMETRY_MAX_TENANTS", "3")
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.serving import Request, ServingLoop

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    s, params = diffusion3d.setup(8, 8, 8, init_grid=False)
    loop = ServingLoop(diffusion3d, params, capacity=2, steps_per_round=1)
    for i in range(6):
        si, _ = diffusion3d.setup(8, 8, 8, init_grid=False,
                                  ic_scale=1.0 + 0.01 * i)
        loop.submit(Request(state=si, max_steps=1, tenant=f"tenant{i}"))
    res = loop.run(max_rounds=20)
    assert len(res) == 6
    c = tele.snapshot()["counters"]
    tenant_keys = [k for k in c if k.startswith("serving.tenant.")]
    assert len(tenant_keys) <= 4  # 3 distinct + __other__
    assert sum(c[k] for k in tenant_keys) == 6  # every step attributed


# -- Prometheus exposition edge cases (ISSUE 10 satellite) --------------------


def _parse_prometheus(text: str) -> dict:
    """Minimal text-format (0.0.4) parser for the round-trip check:
    ``{metric name: {"type": ..., "samples": {sample name+labels: value}}}``.
    Samples attach to the preceding ``# TYPE`` block and must belong to it
    (name prefix match) — raises on anything a standard scraper would
    reject (sample before its header, duplicate headers, non-numeric
    value, malformed line)."""
    out: dict = {}
    current = None
    for line in text.splitlines():
        if not line.strip():
            raise ValueError("blank line in exposition")
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split()
            if name in out:
                raise ValueError(f"duplicate TYPE for {name}")
            out[name] = {"type": mtype, "samples": {}}
            current = name
            continue
        if line.startswith("#"):
            continue
        parts = line.rsplit(None, 1)
        if len(parts) != 2:
            raise ValueError(f"malformed sample line {line!r}")
        name_labels, value = parts
        if current is None or not name_labels.startswith(current):
            raise ValueError(f"sample outside its TYPE block: {line!r}")
        out[current]["samples"][name_labels] = float(value)
    return out


def test_prometheus_name_sanitization_edge_cases():
    # dots, hyphens and a LEADING DIGIT: all must sanitize to a valid
    # Prometheus name (the igg_ prefix also rescues the leading digit).
    tele.counter("9starts.with-digit").inc(2)
    tele.gauge("weird-gauge.name-x").set(1.0)
    text = tele.prometheus_text()
    parsed = _parse_prometheus(text)
    assert "igg_9starts_with_digit_total" in parsed
    assert parsed["igg_9starts_with_digit_total"]["type"] == "counter"
    assert "igg_weird_gauge_name_x" in parsed
    import re

    for name in parsed:
        assert re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", name), name


def test_prometheus_empty_histogram_exposition():
    tele.histogram("h.empty")  # created, never recorded
    text = tele.prometheus_text()
    parsed = _parse_prometheus(text)
    h = parsed["igg_h_empty"]
    assert h["type"] == "summary"
    # no quantile lines (the reservoir is empty), but sum/count present
    assert h["samples"] == {"igg_h_empty_sum": 0.0, "igg_h_empty_count": 0.0}
    assert "None" not in text


def test_prometheus_roundtrip_against_snapshot():
    tele.counter("rt.count").inc(7)
    tele.gauge("rt.gauge").set(-2.5)
    h = tele.histogram("rt.hist")
    for v in (1.0, 2.0, 4.0):
        h.record(v)
    snap = tele.snapshot()
    parsed = _parse_prometheus(tele.prometheus_text(snap))
    assert parsed["igg_rt_count_total"]["samples"]["igg_rt_count_total"] == 7.0
    assert parsed["igg_rt_gauge"]["samples"]["igg_rt_gauge"] == -2.5
    hs = parsed["igg_rt_hist"]["samples"]
    assert hs["igg_rt_hist_sum"] == 7.0
    assert hs["igg_rt_hist_count"] == 3.0
    assert hs['igg_rt_hist{quantile="0.5"}'] == snap["histograms"]["rt.hist"]["p50"]
    # every registry metric surfaced exactly once
    assert len(parsed) == 3


# -- Enriched heartbeat (ISSUE 10 satellite) ----------------------------------


def test_heartbeat_attaches_skew_and_serving_context(monkeypatch, tmp_path):
    """docs/observability.md heartbeat schema: when the skew gauges and
    the serving occupancy gauges exist, the rank-0 heartbeat event carries
    them; when they don't, the sections are absent (pinned by
    test_heartbeat_line_and_event)."""
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.setenv("IGG_HEARTBEAT_EVERY", "1")
    # a straggler probe and a serving pool published earlier this process
    tele.gauge("skew.step_seconds_max_over_min").set(3.5)
    tele.gauge("skew.slowest_rank").set(1)
    tele.gauge("serving.active_members").set(2)
    tele.gauge("serving.queue_depth").set(4)
    loop = tele.step_loop("m", bytes_per_step=8, total_steps=1)
    loop.on_step(1)
    events = tele.read_events(tmp_path / "events.jsonl")
    hb = next(e for e in events if e["type"] == "heartbeat")
    assert hb["skew"] == {
        "step_seconds_max_over_min": 3.5,
        "slowest_rank": 1.0,
    }
    assert hb["serving"] == {"active_members": 2.0, "queue_depth": 4.0}


# -- Rolling SLO windows (ISSUE 11) -------------------------------------------


def test_histogram_rolling_windows(monkeypatch):
    monkeypatch.setenv("IGG_SLO_WINDOW_S", "10")
    h = tele.histogram("w.hist")
    # window 1: [t=0, 10)
    for v in (1.0, 2.0, 3.0):
        h.record(v, now=0.0)
    w = h.window_summary(now=5.0)
    assert w["count"] == 3 and w["window_s"] == 10.0 and w["windows"] == 1
    assert w["p50"] == 2.0
    # window 2 opens at t=12: the old window slides into the ring
    h.record(100.0, now=12.0)
    w = h.window_summary(now=12.0)
    assert w["count"] == 4 and w["windows"] == 2
    assert w["p99"] == 100.0
    # beyond the horizon (SLO_WINDOWS * 10s) old windows fall out...
    w = h.window_summary(now=12.1 + tele.SLO_WINDOWS * 10)
    assert w is None
    # ...while the LIFETIME reservoir keeps everything
    s = h.summary()
    assert s["count"] == 4 and s["max"] == 100.0


def test_window_ring_is_bounded(monkeypatch):
    monkeypatch.setenv("IGG_SLO_WINDOW_S", "1")
    h = tele.histogram("w.ring")
    for i in range(20):  # 20 windows, ring keeps SLO_WINDOWS
        h.record(float(i), now=float(i))
    assert len(h._win_ring) == tele.SLO_WINDOWS - 1
    w = h.window_summary(now=19.0)
    # the live view spans only the last SLO_WINDOWS windows' samples
    assert w["count"] == tele.SLO_WINDOWS
    assert w["p50"] == float(19 - tele.SLO_WINDOWS // 2)


def test_windows_absent_until_first_record_and_when_disabled(monkeypatch):
    h = tele.histogram("w.lazy")
    assert h._win_cur is None and h._win_ring is None  # lazy allocation
    assert h.window_summary() is None
    assert "window" not in h.summary()
    monkeypatch.setenv("IGG_TELEMETRY", "0")
    # the disabled-mode singleton allocates nothing — no windows anywhere
    noop = tele.histogram("w.never")
    assert noop is tele.NOOP
    noop.record(1.0, now=0.0)
    assert "w.never" not in tele.snapshot()["histograms"]


def test_concurrent_scrape_hammer():
    """ISSUE 11 satellite: a reader thread snapshots/renders the exposition
    in a tight loop while the main thread records — the exact
    /metrics-during-step-loop interleaving.  Any exception on either side
    (or a torn histogram summary) fails the pin."""
    import threading

    errors = []
    stop = threading.Event()

    def reader():
        try:
            while not stop.is_set():
                snap = tele.snapshot()
                text = tele.prometheus_text(snap)
                for name, s in snap["histograms"].items():
                    # invariants a torn read would break
                    assert s["count"] >= 0
                    if s["count"]:
                        assert s["min"] <= s["max"]
                assert text.endswith("\n")
        except Exception as e:  # pragma: no cover - the failure path
            errors.append(e)

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    h = tele.histogram("hammer.hist")
    c = tele.counter("hammer.count")
    g = tele.gauge("hammer.gauge")
    for i in range(3000):
        h.record(float(i % 97))
        c.inc()
        g.set(float(i))
        if i % 500 == 0:
            tele.histogram(f"hammer.h{i}").record(1.0)  # registry growth
    stop.set()
    t.join(timeout=10)
    assert not t.is_alive()
    assert errors == []
    snap = tele.snapshot()
    assert snap["counters"]["hammer.count"] == 3000
    assert snap["histograms"]["hammer.hist"]["count"] == 3000


# -- proc RSS gauge (ISSUE 11 satellite) --------------------------------------


def test_proc_rss_bytes_reads_something():
    rss = tele.proc_rss_bytes()
    # Linux CI: /proc/self/statm must resolve; a python process with jax
    # loaded sits far above 10 MB
    assert rss is not None and rss > 10 * 1024 * 1024


def test_heartbeat_publishes_rss_gauge(monkeypatch, tmp_path):
    monkeypatch.setenv("IGG_HEARTBEAT_EVERY", "1")
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    loop = tele.step_loop("m", bytes_per_step=8, total_steps=1)
    loop.on_step(1)
    assert tele.snapshot()["gauges"]["proc.rss_bytes"] > 0


# -- progress record (the live plane's last-step-age source) ------------------


def test_note_progress_roundtrip():
    assert tele.last_progress() is None
    tele.note_progress("m", 0, init=True)
    p = tele.last_progress()
    assert p["init"] and not p["done"] and p["step"] == 0
    tele.note_progress("m", 3)
    p = tele.last_progress()
    assert not p["init"] and p["step"] == 3 and p["age_s"] >= 0
    tele.note_progress("m", 3, done=True)
    assert tele.last_progress()["done"]
    tele.reset()
    assert tele.last_progress() is None


def test_step_loop_progress_lifecycle(monkeypatch, tmp_path):
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    loop = tele.step_loop("m", total_steps=2)
    assert tele.last_progress()["init"]  # bring-up/compile phase marked
    loop.on_step(1)
    p = tele.last_progress()
    assert p["step"] == 1 and not p["init"] and not p["done"]
    loop.finish(2)
    assert tele.last_progress()["done"]
