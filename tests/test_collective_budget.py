"""Tier-1 collective-budget lint (`scripts/check_collectives.py`, ISSUE 5).

Each model's production exchange set must stay within <= 2 collective-
permutes per exchanged (dimension, dtype width group) on the virtual mesh —
the structural guarantee of the coalesced exchange.  A regression back to
per-field collectives (or extra hops) fails the suite, like an undocumented
knob fails the knob lint.
"""

import importlib.util
import os

_here = os.path.dirname(os.path.abspath(__file__))
_spec = importlib.util.spec_from_file_location(
    "igg_check_collectives",
    os.path.join(os.path.dirname(_here), "scripts", "check_collectives.py"),
)
check_collectives = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(check_collectives)


def test_models_within_collective_budget():
    probs = check_collectives.violations()
    assert not probs, "collective budget violations:\n" + "\n".join(
        f"  - {p}" for p in probs
    )


def test_budget_table_covers_all_models():
    assert set(check_collectives.BUDGET_PAIRS) == {
        "diffusion", "acoustic", "porous"
    }
