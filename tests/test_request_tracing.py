"""Request-scoped distributed tracing tests (ISSUE 19; docs/observability.md).

Covers the W3C ``traceparent`` codec and its adoption/minting matrix at
the door (inbound context wins, head sampling only gates MINTED traces),
the context-propagation API (``parent=`` chaining, ambient `use_context`,
the serving round's multi-request ``trace_ids`` form), the full loopback
round trip (submit with a ``traceparent`` header → every response echoes
the ledgered context → the round spans tag the request → `request_tree`
reconstructs the causal chain), the critical-path latency attribution on
a hand-computable fixture, the byte-stable OTLP/JSON export against a
checked-in golden, the per-epoch merge over a restart-shaped dump dir,
the span-ring overflow honesty chain (counter → ``dropped`` field →
``incomplete`` tree → the CLI's INCOMPLETE banner), the ``/spans``
liveplane filters + oldest-in-flight age, and the pinned zero-overhead
contracts (``IGG_TELEMETRY=0`` and ``IGG_TRACE_SAMPLE=0``).  The real
multi-pool / restart leg is the soak ``fleet`` drill
(`scripts/soak.py`), whose tree check replays all of this across
processes and generations.
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import diffusion3d
from implicitglobalgrid_tpu.serving import FrontDoor, Request, ServingLoop
from implicitglobalgrid_tpu.utils import liveplane as lp
from implicitglobalgrid_tpu.utils import telemetry as tele
from implicitglobalgrid_tpu.utils import tracing

_here = os.path.dirname(os.path.abspath(__file__))
_repo = os.path.dirname(_here)


@pytest.fixture(autouse=True)
def _fresh_state(monkeypatch):
    for knob in ("IGG_TRACE_SAMPLE", "IGG_TRACE_RING", "IGG_GENERATION",
                 "IGG_METRICS_PORT", "IGG_SERVE_PORT"):
        monkeypatch.delenv(knob, raising=False)
    tele.reset()
    tracing.reset()
    lp.reset()
    yield
    lp.reset()
    tele.reset()
    tracing.reset()


NX = 8
TID = "ab" * 16
SID = "cd" * 8


def _pool(capacity=2):
    igg.init_global_grid(NX, NX, NX, quiet=True)
    _, params = diffusion3d.setup(NX, NX, NX, init_grid=False)
    return ServingLoop(diffusion3d, params, capacity=capacity,
                      steps_per_round=1)


def _member(scale=1.0):
    state, _ = diffusion3d.setup(NX, NX, NX, init_grid=False, ic_scale=scale)
    return state


def _post(port, path, doc, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read().decode() or "{}"), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "{}"), dict(e.headers)


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.status, json.loads(r.read().decode() or "{}"), dict(r.headers)


# -- the traceparent codec ----------------------------------------------------


def test_parse_traceparent_matrix():
    hdr = f"00-{TID}-{SID}-01"
    assert tracing.parse_traceparent(hdr) == {"trace_id": TID, "span_id": SID}
    # tolerated variation: uppercase + surrounding whitespace, extra flags
    assert tracing.parse_traceparent(f"  00-{TID.upper()}-{SID}-00  ") == {
        "trace_id": TID, "span_id": SID,
    }
    # the W3C "restart the trace" shapes all map to None
    for bad in (
        None, "", "garbage", "00-short-" + SID + "-01",
        f"00-{TID}-{'0' * 16}-01",           # all-zero span id
        f"00-{'0' * 32}-{SID}-01",           # all-zero trace id
        f"ff-{TID}-{SID}-01",                # forbidden version
        f"zz-{TID}-{SID}-01",                # non-hex version
        f"00-{'x' * 32}-{SID}-01",           # non-hex trace id
    ):
        assert tracing.parse_traceparent(bad) is None, bad
    assert tracing.format_traceparent(
        {"trace_id": TID, "span_id": SID}
    ) == hdr


def test_trace_span_context_chaining_and_ambient():
    # explicit parent: the span mints its own id chained under the parent
    # and becomes the ambient parent of anything nested
    with tracing.trace_span("outer", parent={"trace_id": TID,
                                             "span_id": SID}):
        inner_ctx = tracing.current_context()
        assert inner_ctx["trace_id"] == TID
        assert inner_ctx["span_id"] != SID
        with tracing.trace_span("inner"):
            pass
    assert tracing.current_context() is None  # no leak
    inner, outer = tracing.span_records()
    assert outer["args"]["trace_id"] == TID
    assert outer["args"]["parent_id"] == SID
    assert inner["args"]["parent_id"] == outer["args"]["span_id"]
    # the multi-request (serving round) form tags ids without re-minting
    with tracing.use_context({"trace_ids": [TID, "ef" * 16]}):
        with tracing.trace_span("round"):
            pass
    rec = tracing.span_records()[-1]
    assert rec["args"]["trace_ids"] == [TID, "ef" * 16]
    assert "span_id" not in rec["args"]


# -- sampling + zero-overhead pins --------------------------------------------


def test_sample_zero_is_the_pinned_no_context_path(monkeypatch):
    monkeypatch.setenv("IGG_TRACE_SAMPLE", "0")
    assert tracing.should_sample() is False
    loop = _pool(capacity=1)
    fd = FrontDoor(loop, port=0)
    try:
        code, body, hdrs = fd.handle_submit({
            "tenant": "tA", "model": "diffusion3d",
            "params": {"max_steps": 1},
        })
        # no minted context: no header, no ledgered trace, no submit span
        assert code == 202 and hdrs == {}
        assert fd._requests[body["request_id"]]["trace"] is None
        assert fd.trace_header(body["request_id"]) is None
        assert not [s for s in tracing.span_records()
                    if s["name"].startswith("igg.frontdoor.")]
        # an INBOUND context is never re-sampled — upstream already decided
        code, body, hdrs = fd.handle_submit(
            {"tenant": "tA", "model": "diffusion3d",
             "params": {"max_steps": 1}},
            traceparent=f"00-{TID}-{SID}-01",
        )
        got = tracing.parse_traceparent(hdrs["traceparent"])
        assert got["trace_id"] == TID and got["span_id"] != SID
        rec = fd._requests[body["request_id"]]["trace"]
        assert rec["trace_id"] == TID and rec["parent_id"] == SID
    finally:
        fd.close()


def test_telemetry_off_is_pure_passthrough(monkeypatch):
    monkeypatch.setenv("IGG_TELEMETRY", "0")
    assert tracing.trace_span("x", parent={"trace_id": TID,
                                           "span_id": SID}) \
        is tracing.NOOP_SPAN
    assert tracing.record_span("y", t0=0.0, dur=1.0,
                               parent={"trace_id": TID}) is None
    loop = _pool(capacity=1)
    fd = FrontDoor(loop, port=0)
    try:
        hdr = f"00-{TID}-{SID}-01"
        code, body, hdrs = fd.handle_submit(
            {"tenant": "tA", "model": "diffusion3d",
             "params": {"max_steps": 1}},
            traceparent=hdr,
        )
        # the inbound header is echoed VERBATIM (no re-mint, no parse cost
        # beyond the dict lookup) and nothing lands in the ring
        assert code == 202 and hdrs == {"traceparent": hdr}
        assert fd._requests[body["request_id"]]["trace"] is None
        assert tracing.span_records() == []
    finally:
        fd.close()


# -- the loopback round trip --------------------------------------------------


def test_traceparent_roundtrip_through_loopback_frontdoor():
    loop = _pool(capacity=2)
    fd = FrontDoor(loop, port=0)
    try:
        code, body, hdrs = _post(
            fd.port, "/v1/submit",
            {"tenant": "tA", "model": "diffusion3d",
             "params": {"max_steps": 2, "ic_scale": 1.1}},
            headers={"traceparent": f"00-{TID}-{SID}-01"},
        )
        assert code == 202
        rid = body["request_id"]
        echo = tracing.parse_traceparent(hdrs["traceparent"])
        assert echo["trace_id"] == TID        # adopted, not re-minted
        assert echo["span_id"] != SID         # the door's own request span
        # the in-flight ledger drives the oldest-request-age gauge
        assert tele.snapshot()["gauges"][
            "frontdoor.oldest_submitted_ts"] > 0
        assert fd.serve_rounds(max_rounds=6) == "rounds"
        code, view, hdrs = _get(fd.port, f"/v1/result/{rid}")
        assert view["status"] == "done"
        # EVERY response for a traced request carries the same context back
        assert tracing.parse_traceparent(hdrs["traceparent"]) == echo
        assert tele.snapshot()["gauges"][
            "frontdoor.oldest_submitted_ts"] == 0  # nothing in flight
        # one causal tree from this process's ring: door hops chained
        # under the request span, rounds tagging the member context
        doc = {
            "schema": tracing.TRACE_SCHEMA, "rank": 0, "gen": None,
            "dropped": tracing.spans_dropped(),
            "clock_sync": tracing.clock_sync(),
            "spans": tracing.span_records(),
        }
        tree = tracing.request_tree([doc], TID)
        assert not tree["incomplete"]
        req = [r for r in tree["roots"]
               if r["name"] == "igg.frontdoor.request"]
        assert len(req) == 1, tree["roots"]
        assert req[0]["args"]["parent_id"] == SID  # chained to the caller
        assert req[0]["args"]["span_id"] == echo["span_id"]

        def _names(ns):
            out = set()
            for n in ns:
                out.add(n["name"])
                out |= _names(n["children"])
            return out

        names = _names(tree["roots"])
        assert {"igg.frontdoor.request", "igg.frontdoor.submit",
                "igg.frontdoor.admit", "igg.serving.round"} <= names
        cp = tracing.critical_path(tree)
        assert cp["total_s"] == pytest.approx(req[0]["dur_s"])
        assert sum(v["share"] for v in cp["segments"].values()) \
            == pytest.approx(1.0)
    finally:
        fd.close()


def test_round_spans_carry_member_context_single_process():
    loop = _pool(capacity=1)
    mem = loop.submit(Request(state=_member(), max_steps=1, tenant="tA",
                              trace={"trace_id": TID, "span_id": SID}))
    res = loop.run(max_rounds=3)
    assert res[mem].status == "completed"
    rounds = [s for s in tracing.span_records()
              if s["name"] == "igg.serving.round"
              and tracing._trace_match(s.get("args"), TID)[0]]
    assert rounds, "no round span tagged the traced member"
    args = rounds[0]["args"]
    assert TID in args["trace_ids"]
    # the embedded member context names the request-side parent directly
    assert tracing._trace_match(args, TID) == (True, SID)


# -- request_tree + critical_path on a hand-computable fixture ----------------

REQ, SUB, ADM, ADN = "aa" * 8, "bb" * 8, "cc" * 8, "dd" * 8


def _fixture_docs():
    """Two dumps, one request: the door on rank 0 (no generation), a pool
    rank 1 under a supervisor (gen 0).  Wall intervals, in seconds from
    t=1000: request [0,10], queue-wait [0,4] containing admission [0,1],
    round [4,9] containing a 2s exchange [6,8] — so the attribution is
    exactly queue_wait 3 / admission 1 / rounds 3 / exchange 2 / other 1.
    """
    door = {
        "schema": tracing.TRACE_SCHEMA, "rank": 0, "pid": 101, "gen": None,
        "dropped": 0,
        "clock_sync": {"wall": 1000.0, "perf": 100.0, "uncertainty_s": 0.0,
                       "epoch": 1, "barrier": False},
        "spans": [
            {"name": "igg.frontdoor.submit", "t0": 100.0, "dur": 0.5,
             "args": {"trace_id": TID, "span_id": SUB, "parent_id": REQ,
                      "request": "r000000"}},
            {"name": "igg.serving.admission", "t0": 100.0, "dur": 1.0,
             "args": {"trace_id": TID, "span_id": ADN, "parent_id": REQ,
                      "tenant": "tA"}},
            {"name": "igg.frontdoor.admit", "t0": 100.0, "dur": 4.0,
             "args": {"trace_id": TID, "span_id": ADM, "parent_id": REQ,
                      "request": "r000000"}},
            {"name": "igg.frontdoor.request", "t0": 100.0, "dur": 10.0,
             "args": {"trace_id": TID, "span_id": REQ, "request": "r000000",
                      "tenant": "tA", "result": "completed"}},
        ],
    }
    pool = {
        "schema": tracing.TRACE_SCHEMA, "rank": 1, "pid": 202, "gen": 0,
        "dropped": 0,
        "clock_sync": {"wall": 1000.0, "perf": 500.0, "uncertainty_s": 0.0,
                       "epoch": 1, "barrier": False},
        "spans": [
            {"name": "igg.serving.round", "t0": 504.0, "dur": 5.0,
             "args": {"round": 3, "trace_ids": [TID],
                      "members": [{"member": 0, "slot": 0, "tenant": "tA",
                                   "trace": {"trace_id": TID,
                                             "span_id": ADM}}]}},
            {"name": "igg_halo_exchange", "t0": 506.0, "dur": 2.0,
             "args": {"trace_ids": [TID]}},
        ],
    }
    return [door, pool]


def test_request_tree_parenting_across_dumps():
    tree = tracing.request_tree(_fixture_docs(), TID)
    assert tree["spans"] == 6
    assert tree["ranks"] == [0, 1] and tree["gens"] == [0]
    assert tree["dropped"] == 0 and tree["incomplete"] is False
    # ONE root: the pool round chains under the door's admit span through
    # its embedded member context — the edge that crosses the dumps
    assert [r["name"] for r in tree["roots"]] == ["igg.frontdoor.request"]
    req = tree["roots"][0]
    assert sorted(c["name"] for c in req["children"]) == [
        "igg.frontdoor.admit", "igg.frontdoor.submit",
        "igg.serving.admission",
    ]
    adm = next(c for c in req["children"]
               if c["name"] == "igg.frontdoor.admit")
    assert [c["name"] for c in adm["children"]] == ["igg.serving.round"]
    rnd = adm["children"][0]
    # the exchange has no explicit parent: it nests by time containment
    # under the smallest enclosing matching span of its OWN dump
    assert [c["name"] for c in rnd["children"]] == ["igg_halo_exchange"]
    assert rnd["t0_unix_s"] == pytest.approx(1004.0)
    # a trace id nothing matches reconstructs to an explicitly-empty tree
    empty = tracing.request_tree(_fixture_docs(), "99" * 16)
    assert empty["spans"] == 0 and empty["roots"] == []


def test_critical_path_segment_math():
    cp = tracing.critical_path(tracing.request_tree(_fixture_docs(), TID))
    assert cp["total_s"] == pytest.approx(10.0)
    seg = {k: v["s"] for k, v in cp["segments"].items()}
    # nested time charges the INNER segment exactly once: admission out of
    # queue-wait, exchange out of the round
    assert seg == {
        "queue_wait": pytest.approx(3.0),
        "admission": pytest.approx(1.0),
        "reroute": pytest.approx(0.0),
        "checkpoint": pytest.approx(0.0),
        "exchange": pytest.approx(2.0),
        "rounds": pytest.approx(3.0),
        "other": pytest.approx(1.0),
    }
    assert cp["segments"]["rounds"]["share"] == pytest.approx(0.3)
    assert sum(v["share"] for v in cp["segments"].values()) \
        == pytest.approx(1.0)


# -- OTLP export --------------------------------------------------------------


def _otlp_bytes(docs, **kw):
    out = tracing.otlp_trace(docs, **kw)
    assert tracing.validate_otlp(out) == []
    return json.dumps(out, sort_keys=True, separators=(",", ":"))


def test_otlp_export_golden_byte_stable():
    golden = os.path.join(_here, "data", "request_trace_otlp.golden.json")
    body = _otlp_bytes(_fixture_docs())
    assert body == _otlp_bytes(_fixture_docs())  # deterministic
    with open(golden, encoding="utf-8") as f:
        assert body == f.read().rstrip("\n"), (
            "OTLP export changed shape — if deliberate, regenerate the "
            "golden (see tests/data/request_trace_otlp.golden.json header "
            "comment in git history)"
        )
    doc = json.loads(body)
    spans = [s for rs in doc["resourceSpans"]
             for ss in rs["scopeSpans"] for s in ss["spans"]]
    assert len(spans) == 6
    by_name = {s["name"]: s for s in spans}
    assert by_name["igg.frontdoor.request"]["kind"] == 2  # SERVER
    assert by_name["igg.serving.round"]["kind"] == 1
    assert by_name["igg.frontdoor.admit"]["parentSpanId"] == REQ
    assert by_name["igg.frontdoor.request"]["startTimeUnixNano"] \
        == str(int(1000.0 * 1e9))


def test_otlp_request_slice_and_schema_rejections():
    # the single-request slice keeps only matching spans, and the round
    # span (matched through its member context) gains that parent edge
    doc = json.loads(_otlp_bytes(_fixture_docs(), trace_id=TID))
    spans = [s for rs in doc["resourceSpans"]
             for ss in rs["scopeSpans"] for s in ss["spans"]]
    assert all(s["traceId"] == TID for s in spans)
    rnd = next(s for s in spans if s["name"] == "igg.serving.round")
    assert rnd["parentSpanId"] == ADM
    # the validator actually rejects breakage
    bad = json.loads(_otlp_bytes(_fixture_docs()))
    sp = bad["resourceSpans"][0]["scopeSpans"][0]["spans"][0]
    sp["traceId"] = "nope"
    sp["endTimeUnixNano"] = "-"
    problems = tracing.validate_otlp(bad)
    assert any("bad traceId" in p for p in problems)
    assert any("timestamps" in p for p in problems)
    assert tracing.validate_otlp({}) == [
        "resourceSpans is missing or not a list"
    ]


# -- per-epoch merge over a restart-shaped dump dir ---------------------------


def test_per_epoch_merge_of_real_restart_dumps(monkeypatch, tmp_path):
    """Two generations dumped by the REAL dump path into one telemetry
    dir — the exact shape a supervised restart leaves.  The flat merge
    must refuse (different barriers cannot share an aligned clock); the
    per-epoch merge renders both generations as separate pid bands."""
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    paths = []
    for gen, epoch in ((0, 1), (1, 2)):
        monkeypatch.setenv("IGG_GENERATION", str(gen))
        tracing.reset()
        tracing.record_clock_sync(lambda: None, epoch=epoch)
        with tracing.trace_span("igg.serving.round", round=gen,
                                trace_ids=[TID]):
            pass
        p = igg.dump_trace()
        assert p is not None and p.endswith(f"trace.g{gen}.p0.json")
        paths.append(p)
    with pytest.raises(ValueError, match="--per-epoch"):
        tracing.merge_trace_files(paths)
    merged = tracing.merge_trace_files(paths, per_epoch=True)
    assert tracing.validate_chrome_trace(merged) == []
    xs = [e for e in merged["traceEvents"] if e["ph"] == "X"]
    assert sorted({e["pid"] for e in xs}) \
        == [0, tracing.EPOCH_PID_STRIDE]  # one band per generation
    names = {e["args"]["name"]
             for e in merged["traceEvents"] if e["ph"] == "M"}
    assert any(n.endswith("gen 0") for n in names)
    assert any(n.endswith("gen 1") for n in names)
    groups = merged["otherData"]["clock_alignment"]["groups"]
    assert [g["gen"] for g in groups] == ["0", "1"] or \
        [g["gen"] for g in groups] == [0, 1]
    # and the tree reconstructs ACROSS the generations from those dumps
    docs = [tracing._load_rank_trace(p) for p in paths]
    tree = tracing.request_tree(docs, TID)
    assert tree["spans"] == 2 and len(tree["gens"]) == 2


# -- ring overflow honesty ----------------------------------------------------


def test_ring_overflow_counts_and_marks_trees_incomplete(
    monkeypatch, tmp_path
):
    monkeypatch.setenv("IGG_TRACE_RING", "4")
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    for i in range(6):
        with tracing.trace_span("filler", i=i):
            pass
    with tracing.trace_span("igg.serving.round", trace_ids=[TID]):
        pass
    assert tracing.spans_dropped() == 3
    assert tele.snapshot()["counters"]["trace.spans_dropped_total"] == 3
    path = igg.dump_trace()
    doc = tracing._load_rank_trace(path)
    assert doc["dropped"] == 3
    tree = tracing.request_tree([doc], TID)
    assert tree["spans"] == 1
    assert tree["dropped"] == 3 and tree["incomplete"] is True


# -- the igg_trace.py CLI -----------------------------------------------------


def _cli(*argv, timeout=240):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (_repo, env.get("PYTHONPATH")) if p
    )
    script = os.path.join(_repo, "scripts", "igg_trace.py")
    return subprocess.run(
        [sys.executable, script, *argv],
        capture_output=True, text=True, env=env, timeout=timeout,
    )


def _write_fixture_dir(tmp_path, *, dropped=0):
    docs = _fixture_docs()
    docs[1]["dropped"] = dropped
    names = ["trace.p0.json", "trace.g0.p1.json"]
    for doc, name in zip(docs, names):
        (tmp_path / name).write_text(json.dumps(doc))
    return tmp_path


def test_igg_trace_cli_request_tree_and_views(tmp_path):
    d = _write_fixture_dir(tmp_path)
    view = tmp_path / "req.json"
    otlp = tmp_path / "req.otlp.json"
    r = _cli("request", TID, str(d), "-o", str(view), "--otlp", str(otlp))
    assert r.returncode == 0, r.stderr
    assert "INCOMPLETE" not in r.stderr
    assert f"trace {TID}: 6 span(s)" in r.stdout
    assert "- igg.frontdoor.request  [rank 0]  10000.000ms" in r.stdout
    assert "  - igg.serving.round  [rank 1 gen 0]" in r.stdout  # provenance
    assert "critical path: total 10000.000ms" in r.stdout
    assert "rounds" in r.stdout and "30.0%" in r.stdout
    # the request-highlighted Chrome view validates and bands by (gen, rank)
    vdoc = json.loads(view.read_text())
    assert tracing.validate_chrome_trace(vdoc) == []
    assert vdoc["otherData"]["request"]["trace_id"] == TID
    # the OTLP slice is the same byte-stable artifact the library emits
    assert otlp.read_text() == _otlp_bytes(_fixture_docs(), trace_id=TID)
    # unknown trace id: a structured refusal, not an empty tree
    r = _cli("request", "99" * 16, str(d))
    assert r.returncode == 2 and "no spans for trace" in r.stderr


def test_igg_trace_cli_incomplete_banner_and_export(tmp_path):
    d = _write_fixture_dir(tmp_path, dropped=7)
    r = _cli("request", TID, str(d))
    assert r.returncode == 0, r.stderr
    # the tree still prints, but NEVER as a silently-partial one
    assert "INCOMPLETE" in r.stderr and "7 span(s)" in r.stderr
    assert "IGG_TRACE_RING" in r.stderr
    out = tmp_path / "spans.otlp.json"
    r = _cli("export", str(d), "--otlp", "-o", str(out))
    assert r.returncode == 0, r.stderr
    assert "6 OTLP span(s) from 2 dump(s)" in r.stderr
    doc = json.loads(out.read_text())
    assert tracing.validate_otlp(doc) == []


# -- liveplane: /spans filters + oldest in-flight age -------------------------


def test_spans_endpoint_filters_by_name_and_request(monkeypatch):
    monkeypatch.setenv("IGG_METRICS_PORT", "0")
    with tracing.use_context({"trace_id": TID, "span_id": SID}):
        with tracing.trace_span("lp.traced", step=1):
            pass
    with tracing.trace_span("lp.other"):
        pass
    with tracing.trace_span("igg.serving.round", trace_ids=[TID]):
        pass
    port = lp.start_server().port
    _, s, _ = _get(port, "/spans")
    assert len(s["spans"]) == 3
    _, s, _ = _get(port, "/spans?name=lp.")
    assert sorted(x["name"] for x in s["spans"]) == ["lp.other", "lp.traced"]
    _, s, _ = _get(port, f"/spans?request={TID}")
    assert sorted(x["name"] for x in s["spans"]) \
        == ["igg.serving.round", "lp.traced"]
    _, s, _ = _get(port, f"/spans?name=round&request={TID}")
    assert [x["name"] for x in s["spans"]] == ["igg.serving.round"]
    _, s, _ = _get(port, "/spans?request=" + "99" * 16)
    assert s["spans"] == []


def test_healthz_reports_oldest_inflight_request_age(monkeypatch):
    monkeypatch.setenv("IGG_METRICS_PORT", "0")
    tele.gauge("serving.active_members").set(1)
    tele.gauge("frontdoor.oldest_submitted_ts").set(time.time() - 5.0)
    port = lp.start_server().port
    _, h, _ = _get(port, "/healthz")
    assert 4.0 <= h["serving"]["oldest_request_age_s"] <= 120.0
    # gauge at 0 = nothing in flight: the key stays absent, not "age now"
    tele.gauge("frontdoor.oldest_submitted_ts").set(0)
    _, h, _ = _get(port, "/healthz")
    assert "oldest_request_age_s" not in h["serving"]
