"""Live telemetry plane tests (ISSUE 11; docs/observability.md).

Covers the per-rank scrape server (endpoint catalog, the /metrics
byte-identity with `dump_metrics`, the IGG_TELEMETRY=0 never-starts
contract, ephemeral-port publication), the anomaly-rule engine (latching,
structured alert events, subscribers, every built-in rule), the
guard/serving escalation wiring, and the `scripts/igg_top.py` cluster
aggregation.  The real 2-process leg is the soak ``live_plane`` scenario
(`scripts/soak.py --quick`).
"""

import json
import os
import sys
import urllib.request

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.utils import liveplane as lp
from implicitglobalgrid_tpu.utils import telemetry as tele
from implicitglobalgrid_tpu.utils import tracing

_here = os.path.dirname(os.path.abspath(__file__))
_repo = os.path.dirname(_here)


@pytest.fixture(autouse=True)
def _fresh_state():
    tele.reset()
    tracing.reset()
    lp.reset()
    yield
    lp.reset()
    tele.reset()
    tracing.reset()


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as r:
        return r.read()


# -- server lifecycle ---------------------------------------------------------


def test_server_absent_without_port(monkeypatch):
    monkeypatch.delenv("IGG_METRICS_PORT", raising=False)
    assert not lp.enabled()
    assert lp.ensure_server() is None
    assert lp.server_port() is None


def test_server_never_starts_when_telemetry_disabled(monkeypatch):
    monkeypatch.setenv("IGG_METRICS_PORT", "0")
    monkeypatch.setenv("IGG_TELEMETRY", "0")
    assert not lp.enabled()
    assert lp.ensure_server() is None
    # heartbeat_tick is equally inert: no engine work, no gauges
    assert lp.heartbeat_tick() == []
    assert tele.snapshot()["gauges"] == {}


def test_ephemeral_port_published(monkeypatch, tmp_path):
    monkeypatch.setenv("IGG_METRICS_PORT", "0")
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    server = lp.ensure_server()
    assert server is not None and server.port > 0
    assert lp.ensure_server() is server  # idempotent
    # published: the gauge (rides the rank-0 heartbeat) + the endpoint file
    assert tele.snapshot()["gauges"]["liveplane.port"] == server.port
    doc = json.loads((tmp_path / lp.endpoint_filename(0)).read_text())
    assert doc["port"] == server.port and doc["rank"] == 0
    assert doc["host"] == "127.0.0.1"


def test_metrics_endpoint_byte_identical_to_dump(monkeypatch, tmp_path):
    monkeypatch.setenv("IGG_METRICS_PORT", "0")
    tele.counter("lp.test_total").inc(3)
    tele.gauge("lp.gauge").set(2.5)
    h = tele.histogram("lp.hist")
    for v in (1.0, 2.0, 3.0):
        h.record(v)
    port = lp.start_server().port
    body = _get(port, "/metrics").decode()
    _json_path, prom_path = tele.dump_metrics(str(tmp_path / "m"))
    assert body == open(prom_path).read()
    assert "igg_lp_test_total_total" in body


def test_healthz_and_spans_endpoints(monkeypatch):
    monkeypatch.setenv("IGG_METRICS_PORT", "0")
    tele.note_progress("diffusion3d", 7)
    with tracing.trace_span("lp.done", step=1):
        pass
    port = lp.start_server().port
    h = json.loads(_get(port, "/healthz"))
    assert h["ok"] is True and h["rank"] == 0
    assert h["uptime_s"] >= 0
    assert h["last_step"]["kind"] == "diffusion3d"
    assert h["last_step"]["step"] == 7 and h["last_step"]["age_s"] >= 0
    assert h["guard"]["trips"] == 0
    assert h["alerts"] == {"active": [], "recent": [], "fired_total": 0}
    assert "skew" not in h and "serving" not in h  # absence is meaningful
    s = json.loads(_get(port, "/spans"))
    assert [x["name"] for x in s["spans"]] == ["lp.done"]
    assert s["open"] == []


def test_unknown_endpoint_404(monkeypatch):
    monkeypatch.setenv("IGG_METRICS_PORT", "0")
    port = lp.start_server().port
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(port, "/nope")
    assert e.value.code == 404


# -- rolling SLO windows ------------------------------------------------------


def test_publish_slo_gauges(monkeypatch):
    h = tele.histogram("m.step_seconds")
    for v in (0.1, 0.2, 0.3):
        h.record(v)
    other = tele.histogram("m.unrelated")
    other.record(1.0)
    out = lp.publish_slo_gauges()
    assert set(out) == {"m.step_seconds"}
    g = tele.snapshot()["gauges"]
    assert g["slo.m.step_seconds.p50"] == pytest.approx(0.2)
    assert g["slo.m.step_seconds.p99"] == pytest.approx(0.3)
    assert not any(k.startswith("slo.m.unrelated") for k in g)


def test_publish_slo_gauges_disabled_is_noop(monkeypatch):
    monkeypatch.setenv("IGG_TELEMETRY", "0")
    assert lp.publish_slo_gauges() == {}


# -- rule engine --------------------------------------------------------------


class _FlagRule(lp.Rule):
    name = "flag"
    severity = "critical"

    def __init__(self):
        self.on = False

    def check(self, ctx):
        return {"why": "flag"} if self.on else None


def test_engine_latches_one_event_per_episode(monkeypatch, tmp_path):
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    rule = _FlagRule()
    eng = lp.RuleEngine(rules=[rule])
    assert eng.tick() == []
    rule.on = True
    fired = eng.tick()
    assert len(fired) == 1
    a = fired[0]
    assert a["rule"] == "flag" and a["severity"] == "critical"
    assert a["rank"] == 0 and a["evidence"] == {"why": "flag"}
    assert eng.tick() == []  # latched: same episode fires once
    assert [x["rule"] for x in eng.active_alerts()] == ["flag"]
    rule.on = False
    eng.tick()  # clears -> re-arms
    assert eng.active_alerts() == []
    rule.on = True
    assert len(eng.tick()) == 1  # a NEW episode fires again
    events = tele.read_events(tmp_path / "events.jsonl")
    alerts = [e for e in events if e["type"] == "alert.flag"]
    assert len(alerts) == 2
    assert alerts[0]["severity"] == "critical"
    assert alerts[0]["evidence"] == {"why": "flag"}
    assert alerts[0]["rank"] == 0
    assert tele.snapshot()["counters"]["alerts.fired_total"] == 2


def test_engine_subscribers_and_cursor():
    rule = _FlagRule()
    eng = lp.RuleEngine(rules=[rule])
    seen = []
    eng.subscribe(seen.append)
    rule.on = True
    eng.tick()
    assert len(seen) == 1 and seen[0]["rule"] == "flag"
    seq, fresh = eng.alerts_since(0)
    assert len(fresh) == 1 and seq == 1
    seq2, fresh2 = eng.alerts_since(seq)
    assert fresh2 == [] and seq2 == seq
    eng.unsubscribe(seen.append)
    rule.on = False
    eng.tick()
    rule.on = True
    eng.tick()
    assert len(seen) == 1  # unsubscribed: second episode not delivered


def test_broken_rule_never_breaks_the_tick():
    class Broken(lp.Rule):
        name = "broken"

        def check(self, ctx):
            raise RuntimeError("boom")

    rule = _FlagRule()
    rule.on = True
    eng = lp.RuleEngine(rules=[Broken(), rule])
    assert [a["rule"] for a in eng.tick()] == ["flag"]


def _ctx(histograms=None, gauges=None, progress=None, rss=None,
         source="heartbeat", rank=0):
    return {
        "now": 0.0,
        "source": source,
        "model": None,
        "snapshot": {
            "rank": rank,
            "histograms": histograms or {},
            "gauges": gauges or {},
            "counters": {},
        },
        "progress": progress,
        "rss": rss,
    }


def test_teff_drop_rule_self_prior_and_reconcile_prior():
    rule = lp.TeffDropRule(0.5)
    hist = {
        "diffusion3d.t_eff_gbs": {
            "count": 50,
            "p90": 100.0,
            "window": {"count": 10, "p50": 30.0},
        }
    }
    # window p50 30 vs self-prior p90 100: 30 < 50 -> fires, source lifetime
    ev = rule.check(_ctx(histograms=hist))
    assert ev and ev["expectation_source"] == "lifetime_p90"
    assert ev["expected_gbs"] == 100.0
    # an explicit reconcile-derived expectation wins over the self-prior
    lp.set_teff_expectation("diffusion3d", 40.0)
    ev = rule.check(_ctx(histograms=hist))
    assert ev is None  # 30 >= 0.5 * 40
    lp.set_teff_expectation("diffusion3d", 200.0)
    ev = rule.check(_ctx(histograms=hist))
    assert ev and ev["expectation_source"] == "reconcile"
    # warm-up guards: too few window or lifetime samples -> quiet
    hist["diffusion3d.t_eff_gbs"]["window"]["count"] = 2
    assert rule.check(_ctx(histograms=hist)) is None


def test_skew_sustained_rule_fires_on_slowest_rank_only():
    rule = lp.SkewSustainedRule(k=2)
    gauges = {"skew.step_seconds_max_over_min": 5.0, "skew.slowest_rank": 0}
    assert rule.check(_ctx(gauges=gauges)) is None  # streak 1 of 2
    # scrape ticks must not advance the streak (gauges move at heartbeats)
    assert rule.check(_ctx(gauges=gauges, source="scrape")) is None
    ev = rule.check(_ctx(gauges=gauges))  # streak 2 -> fires
    assert ev and ev["ratio"] == 5.0 and ev["windows"] == 2
    # this rank is NOT the slowest: resets, never fires here
    gauges["skew.slowest_rank"] = 1
    assert rule.check(_ctx(gauges=gauges)) is None
    assert rule.check(_ctx(gauges=gauges)) is None


def test_convergence_stall_rule():
    rule = lp.ConvergenceStallRule(k=2, gauge="serving.pt_residual_min")
    g = {"serving.pt_residual_min": 1.0}
    assert rule.check(_ctx(gauges=g)) is None  # first observation = best
    g["serving.pt_residual_min"] = 0.5  # improving: resets
    assert rule.check(_ctx(gauges=g)) is None
    assert rule.check(_ctx(gauges=g)) is None  # stagnant x1
    ev = rule.check(_ctx(gauges=g))  # stagnant x2 -> fires
    assert ev and ev["residual"] == 0.5 and ev["windows"] == 2
    g.clear()  # gauge gone (no tol members): resets quietly
    assert rule.check(_ctx(gauges=g)) is None


def test_convergence_stall_rule_population_changes():
    rule = lp.ConvergenceStallRule(k=2, gauge="serving.pt_residual_min")
    # a frozen residual with ZERO watched members is a retired member's
    # leftover, not a stall — the population gauge disarms the rule
    g = {"serving.pt_residual_min": 0.5, "serving.pt_residual_watched": 0}
    for _ in range(4):
        assert rule.check(_ctx(gauges=g)) is None
    # watched again: stagnation counts from a fresh episode
    g["serving.pt_residual_watched"] = 1
    assert rule.check(_ctx(gauges=g)) is None  # best = 0.5
    assert rule.check(_ctx(gauges=g)) is None  # stagnant x1
    assert rule.check(_ctx(gauges=g))  # stagnant x2 -> fires
    # a fresh member admitted at a much HIGHER residual resets the
    # episode (population change), it does not count as stagnation
    g["serving.pt_residual_min"] = 5.0
    assert rule.check(_ctx(gauges=g)) is None
    assert rule.check(_ctx(gauges=g)) is None  # stagnant x1 vs new best
    assert rule.check(_ctx(gauges=g))  # stagnant x2 -> fires again


def test_step_stall_rule_deadline_and_gates(monkeypatch):
    rule = lp.StepStallRule(floor_s=1.0, factor=20.0)
    prog = {"kind": "m", "step": 3, "age_s": 5.0, "init": False,
            "done": False}
    ev = rule.check(_ctx(progress=dict(prog), source="scrape"))
    assert ev and ev["age_s"] == 5.0 and ev["deadline_s"] == 1.0
    # the window p50 stretches the deadline (20 * 0.5 = 10 > age 5)
    hist = {"m.step_seconds": {"p50": 0.5, "count": 9,
                               "window": {"p50": 0.5, "count": 9}}}
    assert rule.check(_ctx(histograms=hist, progress=dict(prog))) is None
    # IGG_WATCHDOG_S pins the deadline outright
    monkeypatch.setenv("IGG_WATCHDOG_S", "2")
    ev = rule.check(_ctx(histograms=hist, progress=dict(prog)))
    assert ev and ev["deadline_s"] == 2.0
    monkeypatch.delenv("IGG_WATCHDOG_S")
    # bring-up and completed runs are not stalls
    assert rule.check(_ctx(progress={**prog, "init": True})) is None
    assert rule.check(_ctx(progress={**prog, "done": True})) is None
    assert rule.check(_ctx(progress=None)) is None


def test_rss_growth_rule():
    rule = lp.RssGrowthRule(factor=1.5, min_bytes=1000)
    base = 100_000
    assert rule.check(_ctx(rss=base)) is None  # first heartbeat = baseline
    assert rule.check(_ctx(rss=base + 500)) is None  # within bounds
    ev = rule.check(_ctx(rss=base * 2))
    assert ev and ev["baseline_bytes"] == base and ev["growth"] == 2.0
    # absolute floor: 1.5x growth of a tiny baseline stays quiet
    small = lp.RssGrowthRule(factor=1.5, min_bytes=10**9)
    assert small.check(_ctx(rss=base)) is None
    assert small.check(_ctx(rss=base * 10)) is None


# -- escalation wiring --------------------------------------------------------


def test_critical_alert_forces_guard_probe(monkeypatch, tmp_path):
    from implicitglobalgrid_tpu.utils.resilience import GuardError, RunGuard

    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    igg.init_global_grid(8, 8, 8, quiet=True)
    import jax.numpy as jnp

    Tg = igg.ones((8, 8, 8), "float64").at[2, 2, 2].set(jnp.nan)
    # guard_every=0: the cadence alone would NEVER probe this state
    guard = RunGuard(guard_every=0, policy="raise", names=("T",))
    state, _ = guard.start((Tg,))
    state, it = guard.on_step((Tg,), 1)  # no alert: passes through
    assert it == 1
    guard.on_alert({"severity": "warn", "rule": "x"})  # warn: no probe
    state, it = guard.on_step((Tg,), 2)
    assert it == 2
    guard.on_alert({"severity": "critical", "rule": "step_stall"})
    with pytest.raises(GuardError):
        guard.on_step((Tg,), 3)
    events = tele.read_events(tmp_path / "events.jsonl")
    probe = [e for e in events if e["type"] == "guard.alert_probe"]
    assert len(probe) == 1 and probe[0]["rule"] == "step_stall"
    snap = tele.snapshot()
    assert snap["counters"]["resilience.alert_probes"] == 1
    assert snap["counters"]["resilience.guard_trips"] == 1


def test_guarded_time_loop_subscribes_for_loop_lifetime(monkeypatch):
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.utils.resilience import RunGuard, \
        guarded_time_loop

    state, params = diffusion3d.setup(8, 8, 8, quiet=True)
    eng = lp.get_engine()
    seen_during = []

    class Probe(lp.Rule):
        name = "probe"
        severity = "warn"

        def check(self, ctx):
            seen_during.append(len(eng._subscribers))
            return None

    eng.register(Probe())
    monkeypatch.setenv("IGG_HEARTBEAT_EVERY", "1")
    guard = RunGuard(guard_every=1, names=("T", "Cp"))
    guarded_time_loop(
        diffusion3d.make_step(params), state, 2, guard=guard,
        sync_every_step=True, model="diffusion3d",
    )
    # the guard's on_alert was subscribed while the loop ran...
    assert seen_during and all(n == 1 for n in seen_during)
    # ...and unsubscribed afterwards
    assert eng._subscribers == []


def test_serving_escalation_evicts_on_single_process(monkeypatch, tmp_path):
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.serving import Request, ServingLoop

    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    igg.init_global_grid(8, 8, 8, quiet=True)
    _, params = diffusion3d.setup(8, 8, 8, init_grid=False)
    loop = ServingLoop(diffusion3d, params, capacity=1, guard_policy="off")

    state, _ = diffusion3d.setup(8, 8, 8, init_grid=False)
    bad_T = np.asarray(state[0]).copy()
    bad_T[(1,) * bad_T.ndim] = np.nan
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    gg = igg.get_global_grid()
    badt = jax.device_put(
        bad_T, NamedSharding(gg.mesh, P(*igg.AXIS_NAMES[: bad_T.ndim]))
    )
    m = loop.submit(Request(state=(badt,) + tuple(state[1:]), max_steps=99))
    loop.run_round()
    assert loop.active_members == 1  # guard off: the NaN member survives
    loop._escalate({"rule": "step_stall", "severity": "critical",
                    "evidence": {}})
    assert loop.active_members == 0
    assert loop.results[m].status == "evicted"
    events = tele.read_events(tmp_path / "events.jsonl")
    esc = [e for e in events if e["type"] == "serving.alert_escalation"]
    assert len(esc) == 1 and esc[0]["rule"] == "step_stall"
    assert tele.snapshot()["counters"]["serving.alert_escalations"] == 1


def test_serving_round_records_latency_and_residual_gauge():
    from implicitglobalgrid_tpu.models import porous_convection3d as porous
    from implicitglobalgrid_tpu.serving import Request, ServingLoop

    igg.init_global_grid(8, 8, 8, quiet=True)
    s, params = porous.setup(8, 8, 8, init_grid=False, npt=3)
    loop = ServingLoop(porous, params, capacity=1, steps_per_round=1)
    loop.submit(Request(state=s, max_steps=2, tol=1e-30, tenant="t"))
    loop.run(max_rounds=3)
    snap = tele.snapshot()
    assert snap["histograms"]["serving.round_seconds"]["count"] >= 2
    assert "window" in snap["histograms"]["serving.round_seconds"]
    assert snap["gauges"]["serving.pt_residual_min"] > 0
    # pool drained: the population gauge disarms the convergence rule
    assert snap["gauges"]["serving.pt_residual_watched"] == 0


# -- healthz with live context ------------------------------------------------


def test_healthz_reflects_alerts_and_slo(monkeypatch):
    monkeypatch.setenv("IGG_METRICS_PORT", "0")
    rule = _FlagRule()
    rule.severity = "critical"
    eng = lp.get_engine()
    eng.rules[:] = [rule]
    h = tele.histogram("m.step_seconds")
    for v in (0.1, 0.2):
        h.record(v)
    rule.on = True
    port = lp.start_server().port
    doc = json.loads(_get(port, "/healthz"))
    # the scrape itself ran the engine tick (scrape-time evaluation)
    assert doc["ok"] is False
    assert [a["rule"] for a in doc["alerts"]["active"]] == ["flag"]
    assert doc["alerts"]["fired_total"] == 1
    assert doc["slo"]["m.step_seconds"]["count"] == 2


# -- igg_top cluster aggregation ----------------------------------------------


def _igg_top():
    scripts = os.path.join(_repo, "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    import igg_top

    return igg_top


def test_igg_top_merges_expositions_with_rank_labels():
    igg_top = _igg_top()
    per_rank = {
        0: "# TYPE igg_m_steps_total counter\nigg_m_steps_total 4\n"
           '# TYPE igg_m_step_seconds summary\n'
           'igg_m_step_seconds{quantile="0.5"} 0.1\n',
        1: "# TYPE igg_m_steps_total counter\nigg_m_steps_total 7\n",
    }
    merged = igg_top.merge_expositions(per_rank)
    lines = merged.splitlines()
    assert 'igg_m_steps_total{rank="0"} 4' in lines
    assert 'igg_m_steps_total{rank="1"} 7' in lines
    # existing labels are preserved behind the rank label
    assert 'igg_m_step_seconds{rank="0",quantile="0.5"} 0.1' in lines
    # one TYPE header per metric, before its first sample
    assert lines.count("# TYPE igg_m_steps_total counter") == 1
    assert lines.index("# TYPE igg_m_steps_total counter") < lines.index(
        'igg_m_steps_total{rank="0"} 4'
    )


def test_igg_top_summary_rows_and_table():
    igg_top = _igg_top()
    healths = {
        1: {
            "ok": False,
            "coords": [1, 0, 0],
            "last_step": {"step": 40, "age_s": 9.3},
            "slo": {"diffusion3d.step_seconds": {"p50": 0.01, "p99": 0.02}},
            "skew": {"step_seconds_max_over_min": 3.2},
            "alerts": {"active": [
                {"rule": "step_stall", "severity": "critical"}
            ]},
        },
        0: {
            "ok": True,
            "coords": [0, 0, 0],
            "last_step": {"step": 42, "age_s": 0.1},
            "slo": {
                "diffusion3d.step_seconds": {"p50": 0.01, "p99": 0.015},
                "diffusion3d.t_eff_gbs": {"p50": 123.0},
                "serving.round_seconds": {"p50": 0.05, "p99": 0.2},
            },
            "serving": {"active_members": 3, "queue_depth": 5,
                        "capacity": 4},
            "frontdoor": {"admitted_total": 9, "rejected_total": 3,
                          "tenants": {"tA": {"admitted": 4, "rejected": 3},
                                      "tB": {"admitted": 5}}},
            "alerts": {"active": []},
        },
    }
    rows = igg_top.summary_rows(healths)
    assert [r["rank"] for r in rows] == [0, 1]  # sorted by rank
    assert rows[0]["teff_gbs"] == 123.0 and rows[0]["alerts"] == "-"
    assert rows[1]["alerts"] == "step_stall(critical)"
    assert rows[1]["skew"] == 3.2
    # the serving/frontdoor SLO columns (ISSUE 12): queue, occupancy,
    # round p50/p99, per-tenant reject rate — absent rows stay "-"
    assert rows[0]["queue"] == 5 and rows[0]["members"] == "3/4"
    assert rows[0]["rnd_p50_ms"] == pytest.approx(50.0)
    assert rows[0]["rnd_p99_ms"] == pytest.approx(200.0)
    assert rows[0]["reject"] == "25%(tA)"
    assert rows[1]["queue"] is None and rows[1]["reject"] is None
    table = igg_top.render_table(rows)
    assert "step_stall(critical)" in table and "ALRT" in table
    assert "25%(tA)" in table and "3/4" in table
    assert len(table.splitlines()) == 4  # header + rule + 2 ranks


def test_igg_top_scrapes_a_real_server(monkeypatch, tmp_path):
    igg_top = _igg_top()
    monkeypatch.setenv("IGG_METRICS_PORT", "0")
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    tele.counter("m.steps").inc(5)
    port = lp.start_server().port
    res = igg_top.scrape(f"127.0.0.1:{port}")
    assert res["health"]["rank"] == 0
    assert "igg_m_steps_total" in res["metrics"]
    # --dir discovery reads the endpoint file the server published
    eps = igg_top.discover_endpoints(
        type("A", (), {"endpoints": [], "endpoints_file": None,
                       "dir": str(tmp_path)})()
    )
    assert eps == [f"127.0.0.1:{port}"]


def test_igg_top_scrape_retries_with_backoff_then_succeeds(monkeypatch):
    """Satellite (ISSUE 16): a rank mid-GC answers on the second try —
    the scrape retries with exponential backoff instead of declaring a
    busy rank dead."""
    igg_top = _igg_top()
    sleeps = []
    monkeypatch.setattr(igg_top.time, "sleep", sleeps.append)
    calls = {"n": 0}

    class _Resp:
        def __init__(self, payload):
            self.payload = payload

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

        def read(self):
            return self.payload

    def flaky_urlopen(url, timeout=None):
        calls["n"] += 1
        if calls["n"] <= 1:  # the first attempt fails outright
            raise OSError("connection refused")
        if url.endswith("/healthz"):
            return _Resp(b'{"rank": 3}')
        return _Resp(b"igg_m_steps_total 4\n")

    monkeypatch.setattr(igg_top.urllib.request, "urlopen", flaky_urlopen)
    res = igg_top.scrape("h:1", retries=3, backoff_s=0.25)
    assert res["health"]["rank"] == 3 and "igg_m" in res["metrics"]
    assert sleeps == [0.25]  # one backoff step bought the answer

    # a truly dead endpoint exhausts the budget and re-raises
    calls["n"] = -10**9
    sleeps.clear()
    with pytest.raises(OSError):
        igg_top.scrape("h:1", retries=3, backoff_s=0.25)
    assert sleeps == [0.25, 0.5, 1.0]  # exponential, then give up


def test_igg_top_retries_default_reads_fleet_env(monkeypatch):
    igg_top = _igg_top()
    sleeps = []
    monkeypatch.setattr(igg_top.time, "sleep", sleeps.append)
    monkeypatch.setattr(
        igg_top.urllib.request, "urlopen",
        lambda url, timeout=None: (_ for _ in ()).throw(OSError("down")),
    )
    monkeypatch.setenv("IGG_FLEET_SCRAPE_RETRIES", "0")
    with pytest.raises(OSError):
        igg_top.scrape("h:1")
    assert sleeps == []  # 0 retries: one attempt, no backoff
    monkeypatch.delenv("IGG_FLEET_SCRAPE_RETRIES")
    with pytest.raises(OSError):
        igg_top.scrape("h:1", backoff_s=0.0)
    assert len(sleeps) == igg_top.DEFAULT_RETRIES


def test_igg_top_unreachable_rank_gets_an_explicit_row(capsys):
    """An unreachable rank is a DOWN row in the table, not a silently
    shorter table — and the exit code says so."""
    igg_top = _igg_top()
    args = type("A", (), {"retries": 0, "prom": None, "json": False})()
    rc = igg_top.one_view(args, ["127.0.0.1:1"])
    assert rc == 1
    out, err = capsys.readouterr()
    assert "0/1 rank(s)" in out
    row = [ln for ln in out.splitlines() if igg_top.UNREACHABLE in ln]
    assert row and "DOWN" in row[0] and "127.0.0.1:1" in row[0]
    assert igg_top.UNREACHABLE in err


def test_igg_top_main_parses_retries_flag():
    igg_top = _igg_top()
    # end to end through argparse: a dead endpoint with --retries 0 is
    # declared UNREACHABLE without a single backoff sleep
    assert igg_top.main(["127.0.0.1:1", "--retries", "0"]) == 1
