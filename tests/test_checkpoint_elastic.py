"""Topology-elastic checkpoint/restart with integrity verification.

The tentpole of the elastic-restart subsystem (docs/robustness.md): a
checkpoint is a portable snapshot of the IMPLICIT global grid, restorable
under any topology implying the same ``nxyz_g``; a damaged generation is
detected (per-shard CRC32 manifest) and skipped, falling back to the
newest valid one.  The cross-process legs live in `test_distributed.py`
(`test_elastic_restart_shrunk_topology`).
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import diffusion3d
from implicitglobalgrid_tpu.ops import gather as gather_mod
from implicitglobalgrid_tpu.parallel import grid as grid_mod
from implicitglobalgrid_tpu.parallel import topology
from implicitglobalgrid_tpu.utils import checkpoint as ckpt
from implicitglobalgrid_tpu.utils import resilience as res

NX = 8


@pytest.fixture
def clean_env(monkeypatch):
    for k in list(os.environ):
        if k.startswith("IGG_"):
            monkeypatch.delenv(k)
    res.reset_fault_injector()
    yield
    res.reset_fault_injector()


def _coord_state(tshape=(NX, NX, NX), vshape=(NX + 1, NX, NX)):
    """Globally-consistent fields (coordinate-derived: duplicated overlap
    cells agree by construction, like a post-exchange state)."""
    T0 = igg.zeros(tshape)
    X, Y, Z = igg.coord_fields(T0, (0.37, 0.11, 0.53))
    T = X * 1.3 + Y * 0.7 + Z * 0.11 + X * Y * 0.003
    V0 = igg.zeros(vshape)
    Xs, Ys, Zs = igg.coord_fields(V0, (0.37, 0.11, 0.53))
    Vx = Xs * 0.9 - Ys * 0.2 + Zs * 0.05
    return T, Vx


# -- topology admissibility ----------------------------------------------------


def test_implied_global_shape_is_inits_formula():
    assert topology.implied_global_shape((8, 8, 8), (2, 2, 2), (2, 2, 2), (0, 0, 0)) == (14, 14, 14)
    assert topology.implied_global_shape((8, 8, 8), (2, 2, 2), (2, 2, 2), (0, 0, 1)) == (14, 14, 12)
    igg.init_global_grid(NX, NX, NX, periodz=1, quiet=True)
    gg = igg.get_global_grid()
    assert gg.nxyz_g == topology.implied_global_shape(
        gg.nxyz, gg.dims, gg.overlaps, gg.periods
    )


def test_elastic_topology_error_names_the_mismatch():
    saved = dict(nxyz=[8, 8, 8], dims=[2, 2, 2], overlaps=[2, 2, 2],
                 periods=[0, 0, 0], nxyz_g=[14, 14, 14])
    ok = dict(nxyz=[5, 14, 8], dims=[4, 1, 2], overlaps=[2, 2, 2],
              periods=[0, 0, 0])
    assert grid_mod.elastic_topology_error(saved, ok) is None
    bad_size = dict(ok, nxyz=[6, 14, 8])
    err = grid_mod.elastic_topology_error(saved, bad_size)
    assert err is not None and "implied global size" in err
    bad_period = dict(ok, periods=[1, 0, 0])
    err = grid_mod.elastic_topology_error(saved, bad_period)
    assert err is not None and "periods" in err


# -- reshard-on-restore --------------------------------------------------------


def _save_222(tmp_path, periodz=0):
    igg.init_global_grid(NX, NX, NX, periodz=periodz, quiet=True)  # dims (2,2,2)
    T, Vx = _coord_state()
    dd = (igg.gather(T, dedup=True), igg.gather(Vx, dedup=True))
    path = igg.save_checkpoint(tmp_path, (T, Vx), 7, extra={"model": "t"})
    igg.finalize_global_grid()
    return path, dd


def test_restore_resharded_4x1x2_bit_exact(tmp_path):
    """The acceptance topology: dims (2,2,2) -> (4,1,2), local sizes
    adjusted so nxyz_g (14,14,14) is preserved."""
    path, (dd_T, dd_Vx) = _save_222(tmp_path)
    igg.init_global_grid(5, 14, 8, dimx=4, dimy=1, dimz=2, quiet=True)
    (T2, Vx2), step, extra = igg.restore_checkpoint(path)
    assert step == 7 and extra == {"model": "t"}
    assert T2.shape == (20, 14, 16) and Vx2.shape == (24, 14, 16)
    assert igg.gather(T2, dedup=True).tobytes() == dd_T.tobytes()
    assert igg.gather(Vx2, dedup=True).tobytes() == dd_Vx.tobytes()
    # the restored halos are consistent: an exchange is a bitwise no-op
    T2x = igg.update_halo(T2 + 0)
    np.testing.assert_array_equal(np.asarray(T2x), np.asarray(T2))


def test_restore_resharded_2x2x1_shrunk_device_set(tmp_path):
    """The surviving-slice topology: dims (2,2,2) on 8 devices -> (2,2,1)
    on a 4-device subset, z-local size grown to keep nxyz_g."""
    path, (dd_T, dd_Vx) = _save_222(tmp_path)
    igg.init_global_grid(
        NX, NX, 14, dimx=2, dimy=2, dimz=1, quiet=True,
        devices=jax.devices()[:4],
    )
    (T2, Vx2), step, _ = igg.restore_checkpoint(path)
    assert T2.shape == (16, 16, 14)
    assert igg.gather(T2, dedup=True).tobytes() == dd_T.tobytes()
    assert igg.gather(Vx2, dedup=True).tobytes() == dd_Vx.tobytes()


def test_restore_resharded_periodic_dim(tmp_path):
    """Periodic z: the de-dup identity wraps at the seam (nxyz_g_z = 12);
    staggered + periodic fields reshard bit-exactly too."""
    path, (dd_T, dd_Vx) = _save_222(tmp_path, periodz=1)
    igg.init_global_grid(5, 14, 8, dimx=4, dimy=1, dimz=2, periodz=1, quiet=True)
    (T2, Vx2), _, _ = igg.restore_checkpoint(path)
    assert igg.gather(T2, dedup=True).tobytes() == dd_T.tobytes()
    assert igg.gather(Vx2, dedup=True).tobytes() == dd_Vx.tobytes()
    T2x = igg.update_halo(T2 + 0)
    np.testing.assert_array_equal(np.asarray(T2x), np.asarray(T2))


def test_restore_resharded_batched_leading_axis(tmp_path):
    """A batched serving pool (leading ensemble axis B, replicated across
    the mesh — `models._batched`) reshards elastically member-for-member:
    the lead axis rides the reassembly as a degenerate grid dim (ISSUE 12,
    the `FrontDoor.elastic_resume` substrate)."""
    from implicitglobalgrid_tpu.models import _batched

    igg.init_global_grid(NX, NX, NX, quiet=True)  # dims (2,2,2)

    def member(s):
        T0 = igg.zeros((NX, NX, NX))
        X, Y, Z = igg.coord_fields(T0, (0.37, 0.11, 0.53))
        return (X * s + Y * 0.7 + Z * 0.11,)

    stack = _batched.stack_states([member(1.0), member(2.0)])
    dd = [
        np.asarray(igg.gather(_batched.member_field(stack[0], k), dedup=True))
        for k in (0, 1)
    ]
    path = igg.save_checkpoint(tmp_path, stack, 5)
    igg.finalize_global_grid()

    igg.init_global_grid(5, NX, 14, dimx=4, dimy=2, dimz=1, quiet=True)
    like = _batched.stack_states([(igg.zeros((5, NX, 14)),)] * 2)
    (B2,), step, _ = ckpt.restore_checkpoint(path, like=like, strict=False)
    assert step == 5 and B2.shape == (2, 20, 16, 14)
    for k in (0, 1):
        got = np.asarray(
            igg.gather(_batched.member_field(B2, k), dedup=True)
        )
        assert got.tobytes() == dd[k].tobytes(), f"member {k}"


def test_restore_scale_up_from_one_block_grid(tmp_path):
    """Scale-UP: a checkpoint written on a dims-(1,1,1) grid (one block ==
    the whole global array) must reshard onto a decomposed target — the
    one-block field is a GRID field headed for duplication of the new
    overlap regions, not a replicated scalar (the frontdoor drill's
    1-proc -> 2-proc resize shape)."""
    igg.init_global_grid(14, NX, NX, dimx=1, dimy=1, dimz=1, quiet=True,
                         devices=jax.devices()[:1])
    T, _ = _coord_state(tshape=(14, NX, NX), vshape=(15, NX, NX))
    dd = igg.gather(T, dedup=True)
    path = igg.save_checkpoint(tmp_path, (T,), 3)
    igg.finalize_global_grid()

    igg.init_global_grid(NX, NX, NX, dimx=2, dimy=1, dimz=1, quiet=True,
                         devices=jax.devices()[:2])
    like = (igg.zeros((NX, NX, NX)),)
    (T2,), step, _ = ckpt.restore_checkpoint(path, like=like, strict=False)
    assert step == 3 and T2.shape == (16, NX, NX)
    assert igg.gather(T2, dedup=True).tobytes() == dd.tobytes()
    # the duplicated overlap is consistent: an exchange is a bitwise no-op
    T2x = igg.update_halo(T2 + 0)
    np.testing.assert_array_equal(np.asarray(T2x), np.asarray(T2))


def test_restore_resharded_thin_slab_offset_coord_collision(tmp_path):
    """Regression: with more blocks than cells-per-block along a dim (dims
    (8,1,1), local nx=5), a block's byte OFFSET tuple (e.g. (5,0,0)) equals
    another block's COORDS tuple — the elastic reader's duplicate-block skip
    must compare in coordinate space, not offset space, or valid blocks are
    dropped as 'replicated' and the restore fails as incomplete.  The
    collision only fires when the high-coords block is SCANNED first (e.g.
    `shards_p10.npz` sorting before `shards_p2.npz` on a pod), so the shard
    file is rewritten with its keys reversed — block scan order is not part
    of the format and must not matter."""
    igg.init_global_grid(5, NX, NX, dimx=8, quiet=True)  # nxyz_g (26,8,8)
    T, _ = _coord_state(tshape=(5, NX, NX), vshape=(6, NX, NX))
    dd = igg.gather(T, dedup=True)
    path = igg.save_checkpoint(tmp_path, (T,), 1)
    igg.finalize_global_grid()
    shard = os.path.join(path, "shards_p0.npz")
    npz = np.load(shard)
    payload = {k: npz[k] for k in reversed(npz.files)}
    npz.close()
    with open(shard, "wb") as f:
        np.savez(f, **payload)
    meta_path = os.path.join(path, "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    meta["shards"]["shards_p0.npz"] = {
        "bytes": os.path.getsize(shard),
        "crc32": ckpt._crc32_file(shard),
    }
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    assert igg.verify_checkpoint(path) is None
    igg.init_global_grid(8, NX, NX, dimx=4, quiet=True,
                         devices=jax.devices()[:4])  # 4*(8-2)+2 = 26
    (T2,), _, _ = igg.restore_checkpoint(path)
    assert igg.gather(T2, dedup=True).tobytes() == dd.tobytes()


def test_restore_resharded_respects_like_shardings(tmp_path):
    path, (dd_T, dd_Vx) = _save_222(tmp_path)
    igg.init_global_grid(5, 14, 8, dimx=4, dimy=1, dimz=2, quiet=True)
    like = (igg.zeros((5, 14, 8)), igg.zeros((6, 14, 8)))
    (T2, Vx2), _, _ = igg.restore_checkpoint(path, like=like)
    assert T2.sharding.is_equivalent_to(like[0].sharding, T2.ndim)
    assert igg.gather(T2, dedup=True).tobytes() == dd_T.tobytes()
    with pytest.raises(ValueError, match="reshards to global shape"):
        igg.restore_checkpoint(path, like=(igg.zeros((5, 14, 8)),) * 2)


def test_restore_elastic_model_continuation_matches_oracle(tmp_path, clean_env):
    """Save a guarded diffusion run mid-flight at dims (2,2,2), resume it at
    dims (4,1,2) through the models' RunGuard path, and match the
    never-resharded oracle in de-dup space (decomposition invariance)."""
    # oracle: uninterrupted 6 steps at (2,2,2)
    T_full = diffusion3d.run(6, NX, NX, NX, quiet=True, finalize=False)
    oracle = igg.gather(T_full, dedup=True)
    igg.finalize_global_grid()
    # checkpointed partial run at (2,2,2)
    diffusion3d.run(4, NX, NX, NX, checkpoint_every=2, checkpoint_dir=tmp_path, quiet=True)
    # resume at (4,1,2): same nxyz_g (14,14,14) from local (5,14,8)
    T_res = diffusion3d.run(
        6, 5, 14, 8, dimx=4, dimy=1, dimz=2,
        checkpoint_every=2, checkpoint_dir=tmp_path, quiet=True, finalize=False,
    )
    got = igg.gather(T_res, dedup=True)
    igg.finalize_global_grid()
    np.testing.assert_allclose(got, oracle, rtol=1e-13, atol=1e-13)


def test_restore_strict_keeps_process_count_contract(tmp_path):
    path, _ = _save_222(tmp_path)
    igg.init_global_grid(5, 14, 8, dimx=4, dimy=1, dimz=2, quiet=True)
    # strict: topology differs -> the exact-topology error, not a reshard
    with pytest.raises(ValueError, match="different grid topology"):
        igg.restore_checkpoint(path, strict=True)


# -- integrity: manifest, verification, generation fallback -------------------


def _save_gens(tmp_path, steps=(2, 4)):
    igg.init_global_grid(NX, NX, NX, quiet=True)
    T, _ = _coord_state()
    return [igg.save_checkpoint(tmp_path, (T,), s) for s in steps]


def test_manifest_records_every_shard_crc(tmp_path):
    (path,) = _save_gens(tmp_path, steps=(3,))
    meta = ckpt.checkpoint_meta(path)
    assert meta["format"] == ckpt.FORMAT_VERSION
    assert set(meta["shards"]) == {"shards_p0.npz"}
    rec = meta["shards"]["shards_p0.npz"]
    shard = os.path.join(path, "shards_p0.npz")
    assert rec["bytes"] == os.path.getsize(shard)
    assert rec["crc32"] == ckpt._crc32_file(shard)
    assert igg.verify_checkpoint(path) is None
    # no staging remnants: the tmp dir was renamed away, sidecars removed
    assert [n for n in os.listdir(os.path.dirname(path)) if n.startswith(".")] == []
    assert not [n for n in os.listdir(path) if n.endswith(".crc.json")]


def test_verify_detects_corruption_and_truncation(tmp_path):
    (path,) = _save_gens(tmp_path, steps=(3,))
    shard = os.path.join(path, "shards_p0.npz")
    size = os.path.getsize(shard)
    with open(shard, "r+b") as f:  # flip one byte mid-file
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))
    assert "corrupt" in igg.verify_checkpoint(path)
    with pytest.raises(ValueError, match="integrity"):
        igg.restore_checkpoint(path)
    os.truncate(shard, size // 2)
    assert "truncated" in igg.verify_checkpoint(path)
    os.remove(shard)
    assert "missing" in igg.verify_checkpoint(path)


def test_latest_checkpoint_falls_back_to_newest_valid(tmp_path, capfd):
    p2, p4 = _save_gens(tmp_path)
    assert igg.latest_checkpoint(tmp_path) == p4
    shard = os.path.join(p4, "shards_p0.npz")
    os.truncate(shard, os.path.getsize(shard) // 2)
    # generation-by-generation fallback: newest is damaged -> previous wins
    assert igg.latest_checkpoint(tmp_path) == p2
    assert "skipping invalid checkpoint" in capfd.readouterr().err
    # unverified scan still reports the newest published generation
    assert igg.latest_checkpoint(tmp_path, verify=False) == p4
    # both generations damaged -> None
    shard2 = os.path.join(p2, "shards_p0.npz")
    os.truncate(shard2, os.path.getsize(shard2) // 2)
    assert igg.latest_checkpoint(tmp_path) is None


def test_legacy_format1_checkpoint_still_restores(tmp_path):
    """A pre-manifest (format 1) directory keeps its completion-marker
    semantics: verification passes on the marker alone and restore works."""
    (path,) = _save_gens(tmp_path, steps=(3,))
    meta_path = os.path.join(path, "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    meta["format"] = 1
    del meta["shards"]
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    assert igg.verify_checkpoint(path) is None
    (T,), step, _ = igg.restore_checkpoint(path)
    assert step == 3


def test_fault_injected_ckpt_corrupt_proves_fallback(tmp_path, clean_env, fault_injection):
    """The in-tree drill: ckpt_corrupt damages the step-4 generation right
    after it publishes; a resumed run must fall back to step 2 and still
    finish bit-identical to the fault-free oracle."""
    fault_injection("ckpt_corrupt:step4")
    diffusion3d.run(4, NX, NX, NX, checkpoint_every=2, checkpoint_dir=tmp_path, quiet=True)
    p4 = os.path.join(str(tmp_path), "step_00000004")
    assert "corrupt" in igg.verify_checkpoint(p4)
    assert igg.latest_checkpoint(tmp_path).endswith("step_00000002")
    res.reset_fault_injector()
    os.environ.pop("IGG_FAULT_INJECT", None)
    T_res = diffusion3d.run(6, NX, NX, NX, checkpoint_every=2, checkpoint_dir=tmp_path, quiet=True)
    T_full = diffusion3d.run(6, NX, NX, NX, quiet=True)
    np.testing.assert_array_equal(np.asarray(T_res), np.asarray(T_full))


def test_fault_injected_ckpt_truncate(tmp_path, clean_env, fault_injection):
    fault_injection("ckpt_truncate:step2")
    igg.init_global_grid(NX, NX, NX, quiet=True)
    T, _ = _coord_state()
    p2 = igg.save_checkpoint(tmp_path, (T,), 2)
    assert "truncated" in igg.verify_checkpoint(p2)
    # fires once: the next generation publishes intact
    p4 = igg.save_checkpoint(tmp_path, (T,), 4)
    assert igg.verify_checkpoint(p4) is None
    assert igg.latest_checkpoint(tmp_path) == p4


def test_fault_set_parses_comma_specs(clean_env):
    fs = res.FaultSet.from_spec("worker_crash:step4:proc1,ckpt_corrupt:step4")
    assert fs.active and len(fs.injectors) == 2
    assert {i.kind for i in fs.injectors} == {"worker_crash", "ckpt_corrupt"}
    assert not res.FaultSet.from_spec(None).active
    with pytest.raises(ValueError, match="shard"):
        res.FaultInjector.from_spec("ckpt_corrupt:step2:proc1")
    inj = res.FaultInjector.from_spec("ckpt_truncate:step7:shard1")
    assert (inj.kind, inj.step, inj.target) == ("ckpt_truncate", 7, 1)


# -- retention ----------------------------------------------------------------


def test_prune_refuses_to_delete_only_valid_generation(tmp_path):
    p2, p4 = _save_gens(tmp_path)
    shard = os.path.join(p4, "shards_p0.npz")
    os.truncate(shard, os.path.getsize(shard) // 2)
    # keep=1 would retain only the (damaged) newest: the only VALID
    # generation (step 2) must survive the prune
    removed = ckpt.prune_checkpoints(tmp_path, keep=1)
    assert removed == []
    assert igg.latest_checkpoint(tmp_path) == p2
    # with protection off, retention is blind (the documented escape hatch)
    removed = ckpt.prune_checkpoints(tmp_path, keep=1, protect_valid=False)
    assert removed == [p2]
    assert igg.latest_checkpoint(tmp_path) is None


def test_runguard_checkpoint_keep_env_and_kwarg(tmp_path, clean_env, monkeypatch):
    monkeypatch.setenv("IGG_CHECKPOINT_KEEP", "2")
    g = res.RunGuard(checkpoint_every=1, checkpoint_dir=str(tmp_path))
    assert g.checkpoint_keep == 2
    g = res.RunGuard(checkpoint_every=1, checkpoint_dir=str(tmp_path), checkpoint_keep=3)
    assert g.checkpoint_keep == 3
    with pytest.raises(ValueError, match="checkpoint_keep"):
        res.RunGuard(checkpoint_keep=-1)
    # end to end through a model loop: only the newest 2 generations remain
    monkeypatch.delenv("IGG_CHECKPOINT_KEEP")
    diffusion3d.run(
        6, NX, NX, NX, checkpoint_every=1, checkpoint_dir=tmp_path,
        checkpoint_keep=2, quiet=True,
    )
    steps = [s for s, _ in ckpt.checkpoint_steps(tmp_path)]
    assert steps == [5, 6]


# -- gather(dedup=True): the shared block-assembly path ------------------------


def test_gather_dedup_strips_overlaps():
    igg.init_global_grid(NX, NX, NX, quiet=True)  # dims (2,2,2)
    T, Vx = _coord_state()
    dd = igg.gather(T, dedup=True)
    assert dd.shape == (14, 14, 14)
    # the de-dup array IS the global grid: coordinate-derived values match
    # the analytic global coordinates at every cell
    x = np.arange(14) * 0.37
    y = np.arange(14) * 0.11
    z = np.arange(14) * 0.53
    X, Y, Z = np.meshgrid(x, y, z, indexing="ij")
    np.testing.assert_allclose(dd, X * 1.3 + Y * 0.7 + Z * 0.11 + X * Y * 0.003,
                               rtol=1e-13, atol=1e-13)
    assert igg.gather(Vx, dedup=True).shape == (15, 14, 14)


def test_gather_dedup_matches_chunked_path():
    igg.init_global_grid(NX, NX, NX, periodz=1, quiet=True)
    T, _ = _coord_state()
    local = igg.gather(T, dedup=True)
    assert local.shape == (14, 14, 12)
    chunked = igg.gather(T, dedup=True, _force_chunked=True)
    np.testing.assert_array_equal(local, chunked)
    # fill-in-place signature takes the de-dup-sized buffer
    buf = np.zeros_like(local)
    assert igg.gather(T, buf, dedup=True, _force_chunked=True) is None
    np.testing.assert_array_equal(buf, local)


def test_owned_range_partitions_exactly():
    # non-periodic: ranges tile [0, G) exactly once
    for nb, s, ol in [(2, 8, 2), (4, 5, 2), (3, 9, 3), (1, 8, 2)]:
        G = gather_mod.dedup_length(nb, s, ol, False)
        cover = []
        for c in range(nb):
            a, b = gather_mod.owned_range(c, nb, s, ol, False)
            cover += list(gather_mod.dedup_indices(c, a, b, s, ol, G))
        assert sorted(cover) == list(range(G))
    # periodic: same, with the wrap seam
    for nb, s, ol in [(2, 8, 2), (4, 5, 2), (1, 8, 2)]:
        G = gather_mod.dedup_length(nb, s, ol, True)
        cover = []
        for c in range(nb):
            a, b = gather_mod.owned_range(c, nb, s, ol, True)
            cover += list(gather_mod.dedup_indices(c, a, b, s, ol, G))
        assert sorted(cover) == list(range(G))
    with pytest.raises(ValueError, match="negative overlap"):
        gather_mod.owned_range(0, 2, 4, -1, False)
