"""Worker process for the multi-process `jax.distributed` tests (not pytest).

Spawned by `tests/test_distributed.py` as ``(pid, nprocs, port, out_path[,
mesh])``: ``nprocs`` coordinator-connected processes share one virtual CPU
mesh of shape ``mesh`` (``"DXxDYxDZ"``, default ``2x2x2`` = the suite's
8-device mesh; each process hosts ``prod(mesh)/nprocs`` virtual devices)
with real process boundaries through it — the TPU translation of the
reference running its suite under ``mpiexec -n N``
(`/root/reference/test/runtests.jl:8-31`).

The default 2-process shape runs the full battery below.  A non-default
``mesh`` (e.g. the 4-process ``2x2x1``: one device per process, TWO
simultaneous process boundaries) runs the compact scenario: fused-cadence
exchange + fill-in-place gather with corner carry-over across both
boundaries, plus coalesced-vs-per-field exchange bit-identity on real gloo
hops (ISSUE 5).

Covers the paths no single-process test can reach:
`parallel/distributed.py` (init via `init_global_grid(init_distributed=True)`),
multi-host ``me``/``coords`` derivation (`parallel/grid.py`), `gather`'s
`process_allgather` branch with a non-default root
(`/root/reference/test/test_gather.jl:126-137` analogue), and the
finalize-shuts-down-the-runtime lifecycle
(`/root/reference/src/finalize_global_grid.jl:19-23` analogue).
"""

import math
import sys

pid = int(sys.argv[1])
nproc = int(sys.argv[2])
port = sys.argv[3]
out_path = sys.argv[4]
mesh_arg = sys.argv[5] if len(sys.argv) > 5 else ""
MESH_DIMS = (
    tuple(int(x) for x in mesh_arg.split("x")) if mesh_arg else (2, 2, 2)
)
assert math.prod(MESH_DIMS) % nproc == 0, (MESH_DIMS, nproc)
LOCAL_DEVICES = math.prod(MESH_DIMS) // nproc

import faulthandler
import os

# Pre-import watchdog: jax import / backend plugin probing can itself stall;
# arm the raw timer BEFORE any heavy import (the library watchdog below
# replaces this timer once the package is importable).
faulthandler.dump_traceback_later(270, exit=True)

# Fresh process: stage the virtual-device count before jax import so older
# JAX versions (no jax_num_cpu_devices config option) honor it too.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={LOCAL_DEVICES}"
).strip()

# Telemetry armed for the whole worker run (docs/observability.md): both
# ranks log into one shared directory; the parent test asserts per-rank
# event files with consistent rank/coords tags.
os.environ["IGG_TELEMETRY"] = "1"
os.environ["IGG_TELEMETRY_DIR"] = out_path + ".telemetry"

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", LOCAL_DEVICES)
except AttributeError:
    pass
jax.config.update("jax_enable_x64", True)
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import diffusion3d
from implicitglobalgrid_tpu.parallel import distributed as dist
from implicitglobalgrid_tpu.utils.resilience import arm_watchdog

# Watchdog below BOTH the parent's 480 s kill AND the JAX coordination
# service's 5-minute shutdown barrier: a straggler that misses that barrier
# is killed by the coordination service with NO stacks, so the watchdog
# must fire first — a deadlock or stall then dumps both workers' stacks
# into the logs the parent shows on failure, instead of dying silently.
# Replaces (and restarts) the raw pre-import timer armed at the top.
arm_watchdog(270, exit=True)

NX = 8
NSTEPS = 3
ROOT = 1  # non-default root: reference test_gather.jl:126-137

me, dims, nprocs, coords, mesh = igg.init_global_grid(
    NX,
    NX,
    NX,
    quiet=(pid != 0),
    init_distributed=True,
    distributed_kwargs=dict(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    ),
    **(
        dict(dimx=MESH_DIMS[0], dimy=MESH_DIMS[1], dimz=MESH_DIMS[2])
        if mesh_arg
        else {}
    ),
)
assert dist.is_distributed_initialized()
assert jax.process_count() == nproc, jax.process_count()
assert nprocs == math.prod(MESH_DIMS), nprocs
assert tuple(dims) == MESH_DIMS, (dims, MESH_DIMS)
assert igg.get_global_grid().owns_distributed

# me/coords = the block of this process's FIRST local device; distinct
# processes must land on distinct blocks.
assert 0 <= me < nprocs
assert coords == tuple(
    int(c) for c in np.argwhere(mesh.devices == jax.local_devices()[0])[0]
)

if mesh_arg:
    # ------------------------------------------------------------------
    # Compact multi-boundary scenario (ISSUE 5 satellite): run on the
    # requested mesh (e.g. 4 processes x 1 device = a 2x2 process grid in
    # x/y) and exercise exactly the paths where TWO simultaneous process
    # boundaries matter: the fused production cadence's slab exchange with
    # sequential-dimension corner carry-over, the fill-in-place chunked
    # gather, and the coalesced exchange's bit-identity on real gloo hops.
    # ------------------------------------------------------------------
    import warnings

    import jax.numpy as jnp

    from implicitglobalgrid_tpu.ops import gather as gather_mod

    # Deep-halo grid for the fused cadence (keep the runtime up, like the
    # reference's finalize_MPI=false re-init cycle).
    igg.finalize_global_grid(finalize_distributed=False)
    igg.init_global_grid(
        NX, NX, NX,
        dimx=MESH_DIMS[0], dimy=MESH_DIMS[1], dimz=MESH_DIMS[2],
        overlapx=4, overlapy=4, overlapz=4, quiet=True,
    )

    # (1) Corner carry-over + coalesced bit-identity across both process
    # boundaries: on a coordinate-derived field set, duplicated cells are
    # consistent by construction, so a CORRECT width-2 multi-field slab
    # exchange is a bitwise no-op — any wrong plane, offset, partner or
    # corner strip breaks it.  Run it coalesced AND per-field: both must be
    # no-ops, hence bit-identical to each other over the real gloo hops.
    state, params = diffusion3d.setup(NX, NX, NX, init_grid=False)
    T0, Cp0 = state[0], state[1]
    fields = (T0, Cp0, T0.astype(jnp.float32), Cp0.astype(jnp.float32))
    maxdiff = jax.jit(lambda a, b: jnp.max(jnp.abs(a - b)))
    for coalesce in (True, False):
        outs = igg.update_halo(
            *[f + 0 for f in fields], width=2, coalesce=coalesce
        )
        for f, o in zip(fields, outs):
            d = float(maxdiff(o, f))
            assert d == 0.0, (
                f"width-2 slab exchange (coalesce={coalesce}) not a no-op "
                f"on a consistent field across the 2x2 process grid: {d}"
            )

    # (2) The fused production cadence (f64 grid: the documented warn-once
    # XLA fallback at the kernel path's exact exchange schedule) across
    # both boundaries; the parent compares against a single-process run of
    # the same global problem with the same decomposition.
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        stepc = diffusion3d.make_multi_step(params, 4, donate=False, fused_k=2)
        state = jax.block_until_ready(stepc(*state))
    Tf = igg.gather(diffusion3d.temperature(state), root=0)
    stats = gather_mod.last_gather_stats
    assert stats["path"] == "chunked", stats
    assert stats["blocks"] == nprocs, stats
    if jax.process_index() == 0:
        np.save(out_path, Tf)
    else:
        assert stats["host_bytes"] == 0, stats

    # (3) Fill-in-place gather rounds across the 2x2 block grid (the gloo
    # cross-match tripwire, here with FOUR processes contending).
    for round_ in range(2):
        buf = np.zeros_like(Tf) if jax.process_index() == 0 else None
        assert igg.gather(diffusion3d.temperature(state), buf, root=0) is None
        if jax.process_index() == 0 and not np.array_equal(buf, Tf):
            # supervisor-visible escalation (docs/robustness.md): the
            # tripwire leaves a classified flight bundle, not a generic
            # crash (`supervisor.classify` maps reason=gather_tripwire)
            igg.tracing.dump_flight_recorder(
                "gather_tripwire", round=round_, mesh=list(MESH_DIMS),
                nproc=nproc,
            )
            raise AssertionError(
                f"fill-in-place gather round {round_} mixed blocks on the "
                f"{nproc}-process mesh"
            )

    igg.finalize_global_grid()
    assert not igg.grid_is_initialized()
    assert not dist.is_distributed_initialized()
    print(f"WORKER {pid} OK", flush=True)
    sys.exit(0)

state, params = diffusion3d.setup(NX, NX, NX, init_grid=False)
step = diffusion3d.make_step(params)
for _ in range(NSTEPS):
    state = jax.block_until_ready(step(*state))

T = diffusion3d.temperature(state)
assert not T.is_fully_addressable  # the chunked multi-host branch, gather.py

from implicitglobalgrid_tpu.ops import gather as gather_mod

got = igg.gather(T, root=ROOT)
# Memory-scalable root-only assembly (reference gather.jl:33-46 bound): the
# multi-host path fetches block by block; non-roots must fetch NOTHING to
# host — they never hold (any part of) the assembled array.
stats = gather_mod.last_gather_stats
assert stats["path"] == "chunked", stats
assert stats["blocks"] == 8, stats
# Batched fetches (ADVICE r5 low #1): 8 blocks arrive in ceil(8/batch)
# collectives; the root-only memory bound is per BATCH now, the total host
# bytes still exactly one copy of every block.
assert stats["fetches"] == -(-stats["blocks"] // stats["batch"]), stats
if jax.process_index() == ROOT:
    assert stats["host_bytes"] == stats["blocks"] * stats["block_bytes"], stats
    assert got is not None
    np.save(out_path, got)
else:
    assert stats["host_bytes"] == 0, stats
    assert got is None

# Also exercise the fill-in-place signature.  gather is a collective: every
# process must make the call (root passes the output buffer, others None).
# THREE consecutive rounds: the jax-0.4.37 gloo transport used to cross-match
# in-flight per-block collectives (~50% of runs) when non-roots left fetches
# pending — the fix completes every fetch on every process
# (`_gather_chunked`); repeat rounds make any recurrence trip DETERMINISTICALLY
# in-worker instead of intermittently across suite runs (ROADMAP open item).
for round_ in range(3):
    buf = np.zeros_like(got) if jax.process_index() == ROOT else None
    assert igg.gather(T, buf, root=ROOT) is None
    if jax.process_index() == ROOT and not np.array_equal(buf, got):
        # The ROADMAP watch-item's supervisor-visible escalation path: a
        # tripped gather tripwire records a flight bundle whose
        # reason=gather_tripwire classifies as a TRANSPORT fault
        # (`igg.supervisor.classify`) instead of vanishing into a generic
        # worker crash — suspect the jax-0.4.37 gloo transport itself.
        igg.tracing.dump_flight_recorder(
            "gather_tripwire", round=round_, nproc=nproc,
        )
        raise AssertionError(
            f"fill-in-place gather round {round_} mixed blocks (gloo "
            f"transport cross-match recurrence? see ROADMAP open items)"
        )

# De-duplicated gather across the real process boundary: the owner-wise
# assembly (`gather(dedup=True)`, shared with the elastic checkpoint restore)
# must equal the concatenated result with overlaps stripped by ownership.
ddup = igg.gather(T, root=ROOT, dedup=True)
if jax.process_index() == ROOT:
    from implicitglobalgrid_tpu.ops.gather import dedup_shape

    assert ddup.shape == dedup_shape(T), (ddup.shape, dedup_shape(T))
    # dims (2,2,2) non-periodic, overlap 2: interior of the de-dup array
    # must match the concatenated blocks' owner regions
    assert np.array_equal(ddup[:7, :7, :7], got[:7, :7, :7])
    assert np.array_equal(ddup[-7:, -7:, -7:], got[-7:, -7:, -7:])
else:
    assert ddup is None

# Deep-halo slab exchange across the real process boundary: re-init with
# overlap=4 (keeping the runtime up — the reference's finalize_MPI=false
# cycle), then check width-2 exchange idempotence on a coordinate-derived
# field: duplicated cells are consistent by construction, so a correct slab
# exchange is a bitwise no-op, and any wrong plane/offset would break it.
igg.finalize_global_grid(finalize_distributed=False)
assert dist.is_distributed_initialized()
igg.init_global_grid(
    NX, NX, NX, overlapx=4, overlapy=4, overlapz=4, quiet=True
)
state2, params2 = diffusion3d.setup(NX, NX, NX, init_grid=False)
T2 = state2[0]
import jax.numpy as jnp

out2 = igg.update_halo(T2 + 0, width=2)  # +0: update_halo donates its input
d = float(jax.jit(lambda a, b: jnp.max(jnp.abs(a - b)))(out2, T2))
assert d == 0.0, f"width-2 slab exchange not idempotent on consistent field: {d}"

# --- Fused production cadence across the real process boundary (VERDICT r4
# #3).  The Pallas kernel itself CANNOT run in interpret mode across a
# process boundary: the TPU interpreter synchronizes every *global* device
# of the computation through one `threading.Barrier(num_devices)`
# (jax/_src/pallas/mosaic/interpret/interpret_pallas_call.py), but only the
# process-local devices run interpreter threads — any cross-process
# interpret-mode kernel deadlocks by construction (probed here; worker hung
# in `_barrier`).  What a process boundary actually changes is the cadence's
# COMMUNICATION, and that is fully exercised below:
# `make_multi_step(fused_k=2)` on this f64 grid takes the documented
# warn-once fallback to the XLA cadence at the SAME exchange schedule as
# the kernel path (one width-2 deep-halo slab exchange per 2 steps,
# sequential-dim corner carry-over) — the production exchange pattern on
# real gloo hops.  The kernel-vs-XLA-cadence arithmetic equivalence is
# pinned single-process (test_models_diffusion.py::
# test_fused_deep_halo_matches_xla_multiblock); transport cannot change
# per-block arithmetic.
import warnings

with warnings.catch_warnings():
    warnings.simplefilter("ignore", RuntimeWarning)
    stepc = diffusion3d.make_multi_step(params2, 4, donate=False, fused_k=2)
    state2 = jax.block_until_ready(stepc(*state2))
Tf = igg.gather(diffusion3d.temperature(state2), root=ROOT)
stats = gather_mod.last_gather_stats
assert stats["path"] == "chunked", stats
if jax.process_index() == ROOT:
    np.save(out_path + ".fused.npy", Tf)
else:
    assert stats["host_bytes"] == 0, stats

# --- Pipelined XLA-fallback cadence over the same real gloo hops (ISSUE 2):
# pipelined=True on this f64 grid runs the XLA cadence with the
# early-dispatch exchange shape (`begin_slab_exchange`/`finish`), whose
# ppermutes ride the gloo transport; by contract it is bit-identical to the
# serialized cadence — asserted here across a real process boundary.
state3p, params3p = diffusion3d.setup(NX, NX, NX, init_grid=False)
with warnings.catch_warnings():
    warnings.simplefilter("ignore", RuntimeWarning)
    stepp = diffusion3d.make_multi_step(
        params3p, 4, donate=False, fused_k=2, pipelined=True
    )
    state3p = jax.block_until_ready(stepp(*state3p))
Tfp = igg.gather(diffusion3d.temperature(state3p), root=ROOT)
if jax.process_index() == ROOT:
    assert np.array_equal(Tf, Tfp), (
        "pipelined XLA-fallback cadence diverged from the serialized "
        "cadence over gloo hops"
    )

# --- Batched ensemble across the real process boundary (ISSUE 8): the
# B=2 vmapped step's collectives ride the same gloo hops as everything
# above, and each member must advance bit-identically to its own B=1 run
# — the cross-process half of the B-for-the-price-of-1 contract (the
# collective-count invariance itself is pinned single-process by the
# budget census; transport cannot change per-member arithmetic, and THIS
# proves the batched transport delivers per-member bytes intact).
from implicitglobalgrid_tpu.models import _batched
from implicitglobalgrid_tpu.serving import Request, ServingLoop
from implicitglobalgrid_tpu.utils.resilience import arm_watchdog as _rearm_wd

_rearm_wd(240, exit=True)  # restart the one-shot deadline for this leg
sA, _pA = diffusion3d.setup(NX, NX, NX, init_grid=False, ic_scale=1.0)
sB, _pB = diffusion3d.setup(NX, NX, NX, init_grid=False, ic_scale=1.25)
bstate = _batched.stack_states([sA, sB])
stepb = diffusion3d.make_step(params2, donate=False, batch=True)
step1b = diffusion3d.make_step(params2, donate=False)
for _ in range(2):
    bstate = jax.block_until_ready(stepb(*bstate))
    sA = jax.block_until_ready(step1b(*sA))
    sB = jax.block_until_ready(step1b(*sB))
for b, oracle in ((0, sA), (1, sB)):
    got_b = igg.gather(bstate[0], member=b, root=ROOT)
    want_b = igg.gather(oracle[0], root=ROOT)
    if jax.process_index() == ROOT:
        assert np.array_equal(got_b, want_b), (
            f"batched member {b} diverged from its B=1 run across the "
            f"process boundary"
        )

# Mid-flight serving on the 2-process grid: 1 slot, 2 requests — the
# second member must be admitted into the slot the first one freed, with
# every rank taking the identical admit/retire decisions (the per-member
# finite probe is replicated by construction).  Both requests carry a
# request-scoped trace context (ISSUE 19): every rank's round spans must
# tag the active member's trace_id, so one causal tree reconstructs from
# EITHER rank's dump even though the request entered at a single door.
from implicitglobalgrid_tpu.utils import tracing as _trc

_tid0, _tid1 = "ab" * 16, "cd" * 16
_loop = ServingLoop(diffusion3d, params2, capacity=1, steps_per_round=1)
_m0 = _loop.submit(Request(state=sA, max_steps=1, tenant="r0",
                           trace={"trace_id": _tid0, "span_id": "0a" * 8}))
_m1 = _loop.submit(Request(state=sB, max_steps=1, tenant="r1",
                           trace={"trace_id": _tid1, "span_id": "0b" * 8}))
_res = _loop.run(max_rounds=6)
assert sorted(_res) == [_m0, _m1], _res
assert all(r.status == "completed" and r.steps == 1 for r in _res.values())
assert _loop.rounds == 2, _loop.rounds  # slot reuse = one round per member
_round_tids = set()
for _s in _trc.span_records():
    if _s["name"] == "igg.serving.round":
        for _t in (_tid0, _tid1):
            if _trc._trace_match(_s.get("args"), _t)[0]:
                _round_tids.add(_t)
assert _round_tids == {_tid0, _tid1}, (
    f"rank {pid} round spans lost request trace contexts: {_round_tids}"
)

# --- Autotuned config over the broadcast host transport (ISSUE 13): rank 0
# holds a seeded winner cache, rank 1 an EMPTY one — the deliberately
# rank-divergent disk state whose naive (rank-keyed) lookup is exactly the
# deadlock class the collective-consistency analyzer pins.  The resolve
# must let rank 0 alone decide and broadcast, so BOTH ranks build the
# identical tuned cadence; the tuned run must then be bit-identical to the
# default-config run over the same real gloo hops (tuning changes
# schedule, never results).
from implicitglobalgrid_tpu import tuning as _tuning

_tdir = out_path + f".tune.p{pid}"
_gg_now = igg.get_global_grid()
_tkey = _tuning.make_key("diffusion3d", _gg_now.nxyz, params2.dtype,
                         gg=_gg_now, nsteps=4)
if pid == 0:
    _tuning.TuneCache(primary=_tdir, fallbacks=()).store(
        _tkey, _tuning.new_entry(_tkey, {"exchange_every": 2},
                                 source="worker-seed"),
    )
os.environ["IGG_TUNE_CACHE"] = _tdir
try:
    from implicitglobalgrid_tpu.utils import telemetry as _tele

    sdef, _ = diffusion3d.setup(NX, NX, NX, init_grid=False)
    stun, _ = diffusion3d.setup(NX, NX, NX, init_grid=False)
    step_def = diffusion3d.make_multi_step(params2, 4, donate=False)
    _hits0 = _tele.snapshot()["counters"].get("tune.cache_hit", 0)
    step_tun = diffusion3d.make_multi_step(params2, 4, donate=False,
                                           autotune=True)
    _snap_t = _tele.snapshot()["counters"]
    # the broadcast decision was rank 0's HIT on every rank — rank 1's
    # empty disk must not have triggered a search (no candidate measured)
    assert _snap_t.get("tune.cache_hit", 0) - _hits0 == 1, _snap_t
    assert _snap_t.get("tune.candidates_measured", 0) == 0, _snap_t
    sdef = jax.block_until_ready(step_def(*sdef))
    stun = jax.block_until_ready(step_tun(*stun))
    Tdef = igg.gather(diffusion3d.temperature(sdef), root=ROOT)
    Ttun = igg.gather(diffusion3d.temperature(stun), root=ROOT)
    if jax.process_index() == ROOT:
        assert np.array_equal(Tdef, Ttun), (
            "broadcast-tuned cadence diverged from the default-config run "
            "across the process boundary"
        )
finally:
    del os.environ["IGG_TUNE_CACHE"]

# --- hide_communication across the real process boundary (VERDICT r4 #3):
# the overlap-scheduled exchange's ppermutes ride the same gloo hops.
igg.finalize_global_grid(finalize_distributed=False)
state4, params4 = diffusion3d.setup(NX, NX, NX, hide_comm=True, quiet=True)
step4 = diffusion3d.make_step(params4, donate=False)
for _ in range(NSTEPS):
    state4 = jax.block_until_ready(step4(*state4))
Th = igg.gather(diffusion3d.temperature(state4), root=ROOT)
if jax.process_index() == ROOT:
    np.save(out_path + ".hc.npy", Th)

# --- Telemetry across the real process boundary (docs/observability.md):
# every rank writes its OWN event file, tagged with its runtime rank and
# grid coords; the registry folded the gathers/exchanges above.
from implicitglobalgrid_tpu.utils import telemetry as tele

assert jax.process_index() == pid  # rank tag source below
tele.event("worker.check", nsteps=NSTEPS)
snap = tele.snapshot()
assert snap["rank"] == pid, snap
assert snap["counters"].get("gather.calls", 0) >= 5, snap["counters"]
assert snap["counters"].get("gather.calls.chunked", 0) >= 5, snap["counters"]
assert snap["counters"].get("halo.exchanges", 0) >= 1, snap["counters"]
_ev_file = os.path.join(
    os.environ["IGG_TELEMETRY_DIR"],
    "events.jsonl" if pid == 0 else f"events.p{pid}.jsonl",
)
_mine = [e for e in tele.read_events(_ev_file) if e["type"] == "worker.check"]
assert len(_mine) == 1 and _mine[0]["rank"] == pid, _mine
assert _mine[0]["coords"] == list(igg.get_global_grid().coords), _mine

# --- Cross-rank observability plane (ISSUE 10): run a short instrumented
# loop at heartbeat cadence so the all-ranks SKEW PROBE rides the real gloo
# transport (both ranks enter the replicated share at steps 2 and 4 — a
# cadence mismatch would deadlock right here, which is the point), then
# dump this rank's span file for the parent's merged-Chrome-trace check.
# ISSUE 15 rides the same loop: BOTH ranks arm a windowed device capture
# (IGG_PROFILE=steps:2-3) so the parent can join each rank's device track
# into the device-merged timeline (`igg_trace.py merge --device`).
from implicitglobalgrid_tpu.utils import tracing as _tracing
from implicitglobalgrid_tpu.utils.resilience import RunGuard, guarded_time_loop
from implicitglobalgrid_tpu.utils.telemetry import teff_bytes

assert _tracing.clock_sync()["barrier"], (
    "multi-process init_global_grid must record a barrier-anchored "
    "clock sync"
)
os.environ["IGG_HEARTBEAT_EVERY"] = "2"
os.environ["IGG_PROFILE"] = "steps:2-3"
try:
    state5, params5 = diffusion3d.setup(NX, NX, NX, init_grid=False)
    state5 = guarded_time_loop(
        diffusion3d.make_step(params5), state5, 4, guard=RunGuard(),
        sync_every_step=True, model="diffusion3d",
        bytes_per_step=teff_bytes(state5[:1]),
    )
finally:
    del os.environ["IGG_HEARTBEAT_EVERY"]
    del os.environ["IGG_PROFILE"]

# The windowed capture landed: per-rank meta with a parseable attribution
# over the real multi-process step program.
from implicitglobalgrid_tpu.utils import profiling as _profiling

_meta_path = os.path.join(
    os.environ["IGG_TELEMETRY_DIR"], _profiling.profile_meta_filename(pid)
)
assert os.path.isfile(_meta_path), f"no capture meta at {_meta_path}"
import json as _json

with open(_meta_path) as _f:
    _meta = _json.load(_f)
assert _meta["rank"] == pid and _meta["window"] == [2, 3], _meta
assert _meta["trace_path"] and os.path.isfile(_meta["trace_path"]), _meta
assert "error" not in _meta["attribution"], _meta["attribution"]
assert _meta["attribution"]["n_device_ops"] > 0, _meta["attribution"]
_snap = tele.snapshot()
assert _snap["gauges"].get("skew.step_seconds_max_over_min", 0.0) >= 1.0, (
    "skew probe did not publish its gauges over the gloo transport",
    _snap["gauges"],
)
assert _snap["gauges"].get("skew.slowest_rank") in (0.0, 1.0), _snap["gauges"]
_trace_path = igg.dump_trace(os.environ["IGG_TELEMETRY_DIR"])
assert _trace_path is not None and os.path.isfile(_trace_path), _trace_path
assert _trace_path.endswith(f"trace.p{pid}.json"), _trace_path

# --- Transport-checksum integrity plane over real gloo hops (ISSUE 18):
# with IGG_INTEGRITY=1 the coalesced packed exchange carries per-hop
# XOR-fold checksum words on the same ppermute payload.  Arm an in-flight
# payload-word flip on block rank 0 (process 0's x=0 corner block): its
# upper-x send lands on block 4, which lives on PROCESS 1 — so the
# RECEIVER (this worker's pid 1) must trip with an IntegrityError that
# implicates the SENDER (rank 0), and its reason=sdc flight bundle must
# carry that attribution for `supervisor.classify`.  Process 0 sends the
# lie and must see nothing locally.  The flip is consumed by one exchange:
# the next checksummed exchange must be clean again (no poisoned cache).
from implicitglobalgrid_tpu.integrity import IntegrityError
from implicitglobalgrid_tpu.ops import halo as _halo

os.environ["IGG_INTEGRITY"] = "1"
try:
    sI, _pI = diffusion3d.setup(NX, NX, NX, init_grid=False)
    TI, CpI = sI[0], sI[1]
    # clean checksummed exchange: zero false positives, and still the
    # bitwise no-op a consistent field demands
    oT, oCp = igg.update_halo(TI + 0, CpI + 0)
    _dmax = jax.jit(lambda a, b: jnp.max(jnp.abs(a - b)))
    assert float(_dmax(oT, TI)) == 0.0, "checksummed exchange not a no-op"
    assert float(_dmax(oCp, CpI)) == 0.0

    _halo.arm_transport_flip(0)
    _trip = None
    try:
        igg.update_halo(TI + 0, CpI + 0)
    except IntegrityError as e:
        _trip = e
    if pid == 1:
        assert _trip is not None, (
            "receiver did not trip on the flipped transport payload"
        )
        assert _trip.implicated_rank == 0, vars(_trip)
        assert _trip.detector == "transport_checksum", vars(_trip)
        _fl = os.path.join(
            os.environ["IGG_TELEMETRY_DIR"], f"flight_{pid}.json"
        )
        assert os.path.isfile(_fl), "no sdc flight bundle on the receiver"
        _sdc = [
            r for r in map(_json.loads, open(_fl))
            if r.get("reason") == "sdc"
        ]
        assert _sdc and _sdc[-1]["info"].get("implicated_rank") == 0, _sdc
        assert tele.snapshot()["counters"].get(
            "integrity.transport_mismatches", 0
        ) >= 1
    else:
        assert _trip is None, (
            f"sender tripped on its own clean receives: {_trip}"
        )

    # flip consumed: the clean cached program serves the next exchange
    oT2, _ = igg.update_halo(TI + 0, CpI + 0)
    assert float(_dmax(oT2, TI)) == 0.0, "post-flip exchange not clean"
finally:
    del os.environ["IGG_INTEGRITY"]

igg.finalize_global_grid()
assert not igg.grid_is_initialized()
assert not dist.is_distributed_initialized()  # finalize tore the runtime down
print(f"WORKER {pid} OK", flush=True)
