"""Tests for gather (ported from `/root/reference/test/test_gather.jl`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import implicitglobalgrid_tpu as igg


def test_gather_roundtrip_block_layout():
    me, dims, nprocs, *_ = igg.init_global_grid(4, 4, 4, quiet=True)
    # fill each block with its rank → gathered array must be block-constant
    def fill(coords):
        cx, cy, cz = coords
        r = (cx * dims[1] + cy) * dims[2] + cz
        return jnp.full((4, 4, 4), r, jnp.float32)

    A = igg.from_block_fn(fill, (4, 4, 4), jnp.float32)
    g = igg.gather(A)
    assert g.shape == tuple(d * 4 for d in dims)
    for cx in range(dims[0]):
        for cy in range(dims[1]):
            for cz in range(dims[2]):
                blk = g[cx * 4:(cx + 1) * 4, cy * 4:(cy + 1) * 4, cz * 4:(cz + 1) * 4]
                assert (blk == (cx * dims[1] + cy) * dims[2] + cz).all()


@pytest.mark.parametrize("dtype", ["float16", "float32", "float64", "int16", "complex64"])
def test_gather_dtypes(dtype):
    # reference dtype matrix: test_gather.jl:98-125
    igg.init_global_grid(4, 4, 4, quiet=True)
    A = igg.full((4, 4, 4), 3, dtype)
    g = igg.gather(A)
    assert g.dtype == np.dtype(dtype)
    assert (g == 3).all()


def test_gather_into_out_array():
    igg.init_global_grid(4, 4, 4, quiet=True)
    gg = igg.get_global_grid()
    A = igg.ones((4, 4, 4), "float64")
    out = np.zeros(tuple(d * 4 for d in gg.dims))
    ret = igg.gather(A, out)
    assert ret is None
    assert (out == 1).all()


def test_gather_size_mismatch_error():
    # reference: test_gather.jl:19-34
    igg.init_global_grid(4, 4, 4, quiet=True)
    A = igg.ones((4, 4, 4), "float64")
    with pytest.raises(ValueError, match="nprocs"):
        igg.gather(A, np.zeros((4, 4, 4)))


def test_gather_dtype_mismatch_error():
    igg.init_global_grid(4, 4, 4, quiet=True)
    gg = igg.get_global_grid()
    A = igg.ones((4, 4, 4), "float32")
    with pytest.raises(ValueError, match="dtype"):
        igg.gather(A, np.zeros(tuple(d * 4 for d in gg.dims), np.float64))


def test_gather_1d_2d():
    igg.init_global_grid(4, 4, 1, quiet=True)
    gg = igg.get_global_grid()
    A = igg.full((4, 4), 7, "float32")
    g = igg.gather(A)
    assert g.shape == (gg.dims[0] * 4, gg.dims[1] * 4)
    assert (g == 7).all()


def test_gather_after_block_slice():
    # the reference idiom: strip the halo locally, then gather
    igg.init_global_grid(4, 4, 4, quiet=True)
    dims = igg.get_global_grid().dims
    A = igg.from_block_fn(
        lambda c: jnp.arange(64, dtype=jnp.float32).reshape(4, 4, 4), (4, 4, 4)
    )
    inner = igg.block_slice(A, (slice(1, -1),) * 3)
    g = igg.gather(inner)
    assert g.shape == tuple(d * 2 for d in dims)
    expect = np.arange(64, dtype=np.float32).reshape(4, 4, 4)[1:-1, 1:-1, 1:-1]
    for cx in range(dims[0]):
        blk = g[cx * 2:(cx + 1) * 2, 0:2, 0:2]
        np.testing.assert_array_equal(blk, expect)
