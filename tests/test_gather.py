"""Tests for gather (ported from `/root/reference/test/test_gather.jl`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import implicitglobalgrid_tpu as igg


def test_gather_roundtrip_block_layout():
    me, dims, nprocs, *_ = igg.init_global_grid(4, 4, 4, quiet=True)
    # fill each block with its rank → gathered array must be block-constant
    def fill(coords):
        cx, cy, cz = coords
        r = (cx * dims[1] + cy) * dims[2] + cz
        return jnp.full((4, 4, 4), r, jnp.float32)

    A = igg.from_block_fn(fill, (4, 4, 4), jnp.float32)
    g = igg.gather(A)
    assert g.shape == tuple(d * 4 for d in dims)
    for cx in range(dims[0]):
        for cy in range(dims[1]):
            for cz in range(dims[2]):
                blk = g[cx * 4:(cx + 1) * 4, cy * 4:(cy + 1) * 4, cz * 4:(cz + 1) * 4]
                assert (blk == (cx * dims[1] + cy) * dims[2] + cz).all()


@pytest.mark.parametrize("dtype", ["float16", "float32", "float64", "int16", "complex64"])
def test_gather_dtypes(dtype):
    # reference dtype matrix: test_gather.jl:98-125
    igg.init_global_grid(4, 4, 4, quiet=True)
    A = igg.full((4, 4, 4), 3, dtype)
    g = igg.gather(A)
    assert g.dtype == np.dtype(dtype)
    assert (g == 3).all()


def test_gather_into_out_array():
    igg.init_global_grid(4, 4, 4, quiet=True)
    gg = igg.get_global_grid()
    A = igg.ones((4, 4, 4), "float64")
    out = np.zeros(tuple(d * 4 for d in gg.dims))
    ret = igg.gather(A, out)
    assert ret is None
    assert (out == 1).all()


def test_gather_size_mismatch_error():
    # reference: test_gather.jl:19-34
    igg.init_global_grid(4, 4, 4, quiet=True)
    A = igg.ones((4, 4, 4), "float64")
    with pytest.raises(ValueError, match="nprocs"):
        igg.gather(A, np.zeros((4, 4, 4)))


def test_gather_dtype_mismatch_error():
    igg.init_global_grid(4, 4, 4, quiet=True)
    gg = igg.get_global_grid()
    A = igg.ones((4, 4, 4), "float32")
    with pytest.raises(ValueError, match="dtype"):
        igg.gather(A, np.zeros(tuple(d * 4 for d in gg.dims), np.float64))


def test_gather_1d_2d():
    igg.init_global_grid(4, 4, 1, quiet=True)
    gg = igg.get_global_grid()
    A = igg.full((4, 4), 7, "float32")
    g = igg.gather(A)
    assert g.shape == (gg.dims[0] * 4, gg.dims[1] * 4)
    assert (g == 7).all()


@pytest.mark.parametrize("use_out", [False, True])
def test_gather_chunked_path_matches_local(use_out):
    """The multi-host block-by-block assembly (`_gather_chunked`) against the
    local path on the same field — pins the masked-psum fetch numerics and
    block placement without a process boundary (the real boundary is covered
    by tests/test_distributed.py)."""
    from implicitglobalgrid_tpu.ops import gather as gather_mod

    me, dims, nprocs, *_ = igg.init_global_grid(4, 4, 4, quiet=True)

    def fill(coords):
        cx, cy, cz = coords
        r = (cx * dims[1] + cy) * dims[2] + cz
        return (jnp.arange(64, dtype=jnp.float32).reshape(4, 4, 4) + 100.0 * r)

    A = igg.from_block_fn(fill, (4, 4, 4), jnp.float32)
    expect = igg.gather(A)
    assert gather_mod.last_gather_stats["path"] == "local"
    if use_out:
        out = np.zeros(expect.shape, np.float32)
        assert igg.gather(A, out, _force_chunked=True) is None
        got = out
    else:
        got = igg.gather(A, _force_chunked=True)
    stats = gather_mod.last_gather_stats
    assert stats["path"] == "chunked"
    assert stats["blocks"] == int(np.prod(dims))
    assert stats["fetches"] == -(-stats["blocks"] // stats["batch"])
    assert stats["block_bytes"] == 64 * 4
    # root (process 0 here) fetched exactly one batch of blocks per
    # collective — the per-process bound the reference's root-only design
    # guarantees (host transient <= batch blocks, total = every block once).
    assert stats["host_bytes"] == stats["blocks"] * stats["block_bytes"]
    np.testing.assert_array_equal(got, expect)


def test_gather_chunked_batching_matches_per_block(monkeypatch):
    """Batched fetches (several blocks per compiled dispatch, ADVICE r5
    low #1) assemble the same bytes as the one-block-per-collective path,
    and the fetch count shrinks by the batch factor."""
    from implicitglobalgrid_tpu.ops import gather as gather_mod

    igg.init_global_grid(4, 4, 4, quiet=True)
    gg = igg.get_global_grid()
    nblocks = int(np.prod(gg.dims))
    if nblocks < 2:
        pytest.skip("needs a multi-block mesh")
    A = igg.from_block_fn(
        lambda c: jnp.arange(64, dtype=jnp.float64).reshape(4, 4, 4)
        + 100.0 * (c[0] + 10 * c[1] + 100 * c[2]),
        (4, 4, 4),
        jnp.float64,
    )
    monkeypatch.setenv("IGG_GATHER_BATCH", "1")
    ref = igg.gather(A, _force_chunked=True)
    assert gather_mod.last_gather_stats["fetches"] == nblocks
    monkeypatch.setenv("IGG_GATHER_BATCH", "3")  # ragged tail batch too
    got = igg.gather(A, _force_chunked=True)
    stats = gather_mod.last_gather_stats
    assert stats["fetches"] == -(-nblocks // 3)
    assert stats["batch"] == 3
    assert stats["host_bytes"] == nblocks * stats["block_bytes"]
    np.testing.assert_array_equal(got, ref)


def test_gather_chunked_2d_field_on_3d_grid():
    """A 2-D field on a 3-D grid is replicated over z: the masked-psum fetch
    must psum over the field's OWN axes only ('x','y') — summing z too would
    multiply every block by dims[2]."""
    igg.init_global_grid(4, 4, 4, quiet=True)
    gg = igg.get_global_grid()
    if gg.dims[2] < 2:
        pytest.skip("needs a z-split mesh")
    A = igg.from_block_fn(
        lambda c: jnp.full((4, 4), 1.0, jnp.float64) * (1 + c[0] + 10 * c[1]),
        (4, 4),
        jnp.float64,
    )
    got = igg.gather(A, _force_chunked=True)
    np.testing.assert_array_equal(got, igg.gather(A))


def test_gather_chunked_complex_bitcast_roundtrip():
    """complex64 rides the chunked transport split into real/imag float32
    components (each bitcast to uint32 — `lax.bitcast_convert_type` cannot
    lower complex directly); the values, incl. signed zeros in BOTH
    components, must round-trip bit-exactly."""
    igg.init_global_grid(4, 4, 4, quiet=True)
    # NB: the Python literal ``-0.0 - 0.0j`` has a +0.0 imaginary part
    # ((-0.0) - complex(0,0) gives imag 0.0-0.0 = +0.0); construct explicitly.
    A = igg.full((4, 4, 4), complex(-0.0, -0.0), "complex64")
    g = igg.gather(A, _force_chunked=True)
    assert g.dtype == np.complex64
    assert np.signbit(g.real).all() and np.signbit(g.imag).all()
    B = igg.full((4, 4, 4), 1.5 + 2.5j, "complex64")
    np.testing.assert_array_equal(
        igg.gather(B, _force_chunked=True), igg.gather(B)
    )


def test_gather_chunked_bit_exact_negative_zero():
    """gather is a byte-copy in the reference (MPI); the chunked transport
    bitcasts to integers around the psum so -0.0 survives (a float psum
    would map -0.0 + 0.0 to +0.0)."""
    igg.init_global_grid(4, 4, 4, quiet=True)
    A = igg.full((4, 4, 4), -0.0, "float64")
    g = igg.gather(A, _force_chunked=True)
    assert np.signbit(g).all()


def test_gather_chunked_size_mismatch_raises_after_collectives():
    """An invalid A_global on the root must still raise — but only after the
    root has participated in every fetch (non-roots would otherwise hang in
    the first collective; single-process here pins the raise itself)."""
    from implicitglobalgrid_tpu.ops import gather as gather_mod

    igg.init_global_grid(4, 4, 4, quiet=True)
    A = igg.ones((4, 4, 4), "float64")
    with pytest.raises(ValueError, match="nprocs"):
        igg.gather(A, np.zeros((4, 4, 4)), _force_chunked=True)
    # the collectives all ran before the raise
    gg = igg.get_global_grid()
    assert gather_mod.last_gather_stats["blocks"] == int(np.prod(gg.dims))
    assert gather_mod.last_gather_stats["host_bytes"] == 0


def test_gather_chunked_2d_and_staggered():
    from implicitglobalgrid_tpu.ops import gather as gather_mod

    igg.init_global_grid(4, 4, 1, quiet=True)
    gg = igg.get_global_grid()
    A = igg.full((4, 4), 7, "float64")
    got = igg.gather(A, _force_chunked=True)
    assert gather_mod.last_gather_stats["path"] == "chunked"
    assert got.shape == (gg.dims[0] * 4, gg.dims[1] * 4)
    assert got.dtype == np.float64
    assert (got == 7).all()
    # staggered (nx+1) field: block shape from the shape-aware local_shape
    B = igg.from_block_fn(
        lambda c: jnp.full((5, 4), 1.0, jnp.float32) * c[0], (5, 4), jnp.float32
    )
    gotB = igg.gather(B, _force_chunked=True)
    np.testing.assert_array_equal(gotB, igg.gather(B))


def test_gather_after_block_slice():
    # the reference idiom: strip the halo locally, then gather
    igg.init_global_grid(4, 4, 4, quiet=True)
    dims = igg.get_global_grid().dims
    A = igg.from_block_fn(
        lambda c: jnp.arange(64, dtype=jnp.float32).reshape(4, 4, 4), (4, 4, 4)
    )
    inner = igg.block_slice(A, (slice(1, -1),) * 3)
    g = igg.gather(inner)
    assert g.shape == tuple(d * 2 for d in dims)
    expect = np.arange(64, dtype=np.float32).reshape(4, 4, 4)[1:-1, 1:-1, 1:-1]
    for cx in range(dims[0]):
        blk = g[cx * 2:(cx + 1) * 2, 0:2, 0:2]
        np.testing.assert_array_equal(blk, expect)


def test_gather_stats_reset_at_call_start():
    """PR-4 satellite: `last_gather_stats` is reset at the START of every
    gather, so a call that fails (here: before any collective) cannot leave
    the previous call's stats lying around as its own."""
    from implicitglobalgrid_tpu.ops import gather as gather_mod

    igg.init_global_grid(4, 4, 4, quiet=True)
    A = igg.from_block_fn(
        lambda c: jnp.ones((4, 4, 4), jnp.float32), (4, 4, 4), jnp.float32
    )
    assert igg.gather(A) is not None
    assert gather_mod.last_gather_stats is not None
    with pytest.raises(ValueError, match="root must be a valid process index"):
        igg.gather(A, root=99)
    assert gather_mod.last_gather_stats is None
