"""Env-var configuration tier (reference: src/init_global_grid.jl:51-68)."""

import os

import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.utils.config import env_config


@pytest.fixture
def clean_env():
    saved = {k: os.environ.pop(k) for k in list(os.environ) if k.startswith("IGG_")}
    yield
    for k in list(os.environ):
        if k.startswith("IGG_"):
            del os.environ[k]
    os.environ.update(saved)


def test_env_defaults_empty(clean_env):
    assert env_config() == {}


def test_env_values(clean_env):
    os.environ["IGG_QUIET"] = "1"
    os.environ["IGG_OVERLAP"] = "3"
    os.environ["IGG_REORDER"] = "0"
    os.environ["IGG_DEVICE_TYPE"] = "cpu"
    cfg = env_config()
    assert cfg == {"quiet": True, "overlap": 3, "reorder": 0, "device_type": "cpu"}


def test_env_invalid_int(clean_env):
    os.environ["IGG_OVERLAP"] = "two"
    with pytest.raises(ValueError, match="IGG_OVERLAP"):
        env_config()


def test_env_applied_at_init(clean_env):
    os.environ["IGG_OVERLAP"] = "3"
    os.environ["IGG_QUIET"] = "1"
    igg.init_global_grid(8, 8, 8)
    gg = igg.get_global_grid()
    assert gg.overlaps == (3, 3, 3)
    assert gg.quiet is True
    igg.finalize_global_grid()


def test_kwargs_override_env(clean_env):
    os.environ["IGG_OVERLAP"] = "3"
    igg.init_global_grid(8, 8, 8, overlapy=4, quiet=True)
    gg = igg.get_global_grid()
    assert gg.overlaps == (3, 4, 3)
    igg.finalize_global_grid()


def test_profile_trace(tmp_path):
    igg.init_global_grid(8, 8, 8, quiet=True)
    T = igg.zeros((8, 8, 8))
    with igg.profile_trace(tmp_path / "trace"):
        T = igg.update_halo(T)
    assert any((tmp_path / "trace").rglob("*"))  # trace files written
    igg.finalize_global_grid()
