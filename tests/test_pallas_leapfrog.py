"""Tests for the temporally-blocked staggered Pallas leapfrog kernel.

Same harness as `tests/test_pallas_stencil.py`: interpret-mode kernel on the
CPU suite (the interpreter implements the DMA/semaphore semantics the
double-buffering + padded-layout logic needs validated); compiled-mode
equivalence and numbers come from `bench.py` / `scripts/verify_tpu.py` on the
real chip.

Oracle: ``fused_leapfrog_steps(..., k)`` vs ``k`` applications of the
acoustic model's `_velocity_update` + `_pressure_update` — few-ULP interior
agreement (same constant folds, different FMA contraction), bit-exact frozen
velocity boundary faces, and P evolving at ALL cells including the global
boundary (the staggered model's boundary semantics, unlike the diffusion
kernel's frozen-cell ring).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from implicitglobalgrid_tpu.models.acoustic3d import (
    Params,
    _pressure_update,
    _velocity_update,
)
from implicitglobalgrid_tpu.ops.pallas_leapfrog import (
    default_tile,
    fused_leapfrog_steps,
    fused_support_error,
    pad_faces,
    unpad_faces,
)


def _setup(shape, seed=0, spacing=(0.1, 0.1, 0.1), K=1.0, rho=1.0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    n0, n1, n2 = shape
    P = jnp.asarray(rng.standard_normal(shape), dtype)
    Vx = jnp.asarray(0.1 * rng.standard_normal((n0 + 1, n1, n2)), dtype)
    Vy = jnp.asarray(0.1 * rng.standard_normal((n0, n1 + 1, n2)), dtype)
    Vz = jnp.asarray(0.1 * rng.standard_normal((n0, n1, n2 + 1)), dtype)
    dx, dy, dz = spacing
    dt = min(spacing) / (K / rho) ** 0.5 / 2.0
    params = Params(K=K, rho=rho, dx=dx, dy=dy, dz=dz, dt=dt, dtype=dtype)
    return (P, Vx, Vy, Vz), params


def _xla_steps(state, params, k):
    vu = _velocity_update(params)
    pu = _pressure_update(params)

    @jax.jit
    def step(P, Vx, Vy, Vz):
        Vx, Vy, Vz = vu(P, Vx, Vy, Vz)
        return pu(P, Vx, Vy, Vz), Vx, Vy, Vz

    for _ in range(k):
        state = step(*state)
    return state


def _fused_interpret(state, params, k, **kw):
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    P, Vx, Vy, Vz = state
    cax = params.dt / params.rho / params.dx
    cay = params.dt / params.rho / params.dy
    caz = params.dt / params.rho / params.dz
    b = params.dt * params.K
    Vxp, Vyp, Vzp = pad_faces(Vx, Vy, Vz)
    with pallas_force_interpret():
        Pg, Vxp, Vyp, Vzp = fused_leapfrog_steps(
            P, Vxp, Vyp, Vzp, k, cax, cay, caz, b,
            1.0 / params.dx, 1.0 / params.dy, 1.0 / params.dz, **kw,
        )
    return (Pg, *unpad_faces(Vxp, Vyp, Vzp))


@pytest.mark.parametrize(
    "k,shape,tile",
    [
        (2, (16, 32, 128), dict(bx=8, by=16)),
        (4, (16, 32, 128), dict(bx=8, by=16)),
        (6, (32, 32, 128), dict(bx=8, by=16)),
        # k=8: in the envelope since round 5 (H=16 y-halo margin)
        (8, (32, 64, 128), dict(bx=8, by=16)),
    ],
)
def test_fused_matches_k_single_steps(k, shape, tile):
    state, params = _setup(shape, spacing=(0.1, 0.15, 0.2), K=1.3, rho=0.8)
    ref = _xla_steps(state, params, k)
    got = _fused_interpret(state, params, k, **tile)
    names = ("P", "Vx", "Vy", "Vz")
    for name, g, r in zip(names, got, ref):
        g, r = np.asarray(g), np.asarray(r)
        np.testing.assert_allclose(g, r, rtol=2e-5, atol=2e-5, err_msg=name)
    # Frozen velocity boundary faces: bit-exact (never touched by either
    # path).
    for d, (g0, v0) in enumerate(zip(got[1:], state[1:])):
        g0, v0 = np.asarray(g0), np.asarray(v0)
        for ax in range(3):
            assert np.array_equal(np.take(g0, 0, axis=ax), np.take(v0, 0, axis=ax))
            last = g0.shape[ax] - 1
            assert np.array_equal(
                np.take(g0, last, axis=ax), np.take(v0, last, axis=ax)
            )
    # P must EVOLVE at the global boundary (all-cells update — the staggered
    # semantics the diffusion kernel's frozen ring does not have).
    P0, Pk = np.asarray(state[0]), np.asarray(got[0])
    for ax in range(3):
        assert not np.array_equal(np.take(Pk, 0, axis=ax), np.take(P0, 0, axis=ax))


def test_default_tile_shape():
    # The production default (32, 64) on a volume that admits it.
    state, params = _setup((64, 128, 128))
    assert default_tile((64, 128, 128), 2) == (32, 64)
    ref = _xla_steps(state, params, 2)
    got = _fused_interpret(state, params, 2)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=2e-5, atol=2e-5)


def test_pad_unpad_roundtrip():
    state, _ = _setup((16, 32, 128), seed=3)
    _, Vx, Vy, Vz = state
    back = unpad_faces(*pad_faces(Vx, Vy, Vz))
    for a, b in zip(back, (Vx, Vy, Vz)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bfloat16_structure():
    # Structural correctness at bf16 accuracy + bit-exact frozen faces.
    state, params = _setup((16, 32, 128), seed=5, dtype=jnp.bfloat16)
    ref = _xla_steps(state, params, 2)
    got = _fused_interpret(state, params, 2, bx=8, by=16)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(
            np.asarray(g.astype(jnp.float32)),
            np.asarray(r.astype(jnp.float32)),
            atol=0.05, rtol=0.05,
        )
    Vx0, Vxk = np.asarray(state[1].astype(jnp.float32)), np.asarray(
        got[1].astype(jnp.float32)
    )
    assert np.array_equal(Vxk[0], Vx0[0])
    assert np.array_equal(Vxk[-1], Vx0[-1])


def test_envelope_validation():
    state, params = _setup((16, 32, 128))
    P, Vx, Vy, Vz = state
    Vxp, Vyp, Vzp = pad_faces(Vx, Vy, Vz)
    args = (0.1, 0.1, 0.1, 0.1, 10.0, 10.0, 10.0)
    with pytest.raises(ValueError, match="k must be even"):
        fused_leapfrog_steps(P, Vxp, Vyp, Vzp, 3, *args)
    with pytest.raises(ValueError, match="does not divide"):
        fused_leapfrog_steps(P, Vxp, Vyp, Vzp, 2, *args, bx=7, by=16)
    with pytest.raises(ValueError, match="pad_faces layout"):
        fused_leapfrog_steps(P, Vx, Vy, Vz, 2, *args)
    # Minor-dim lane alignment (Mosaic HBM-slice requirement, probed on
    # hardware at n2=192 — also enforced for the diffusion kernel now).
    assert "multiple of 128" in fused_support_error((16, 32, 192), 2)
    assert "multiple of 128" in fused_support_error((64, 128, 192), 2)
    assert fused_support_error((16, 32, 2048), 2) is not None
    assert fused_support_error((16, 32, 128), 2, 4, 8, None) is not None
    # VMEM budget rejects oversize tiles before Mosaic stack OOM (probed:
    # (32,128) k=6 at n2=256).
    assert "VMEM" in fused_support_error((256, 256, 256), 6, 4, 32, 128)


def test_diffusion_envelope_minor_alignment():
    # The same probe closed a latent diffusion-kernel envelope gap.
    from implicitglobalgrid_tpu.ops.pallas_stencil import (
        fused_support_error as diff_err,
    )

    assert "multiple of 128" in diff_err((64, 128, 192), 2)
    assert diff_err((64, 128, 256), 2) is None


@pytest.mark.parametrize("seed", range(4))
def test_random_envelope_config_matches_xla(seed):
    """Property sweep: a random envelope-valid (shape, k, tile) config must
    match k XLA leapfrog steps (same oracle as the pinned cases)."""
    rng = np.random.default_rng(100 + seed)
    k = int(rng.choice([2, 4, 6]))
    bx = int(rng.choice([8, 16]))
    by = int(rng.choice([8, 16]))
    H = 8 * ((k + 7) // 8)
    n0 = bx * int(rng.integers((2 * k) // bx + 2, 5))
    n1 = by * max(int(rng.integers(2, 5)), (by + 2 * H) // by + 1)
    shape = (n0, n1, 128)
    err = fused_support_error(shape, k, 4, bx, by)
    if err is not None:
        pytest.skip(f"random config rejected by envelope: {err}")
    state, params = _setup(shape, seed=seed, spacing=(0.11, 0.13, 0.17), K=1.4, rho=0.7)
    ref = _xla_steps(state, params, k)
    got = _fused_interpret(state, params, k, bx=bx, by=by)
    for name, g, r in zip(("P", "Vx", "Vy", "Vz"), got, ref):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), rtol=3e-5, atol=3e-5, err_msg=name
        )


def test_envelope_rejects_x64():
    # f64/complex reaches the XLA fallback, not a Mosaic compile error
    # (TPU Pallas has no 8-byte element type) — all three kernels share
    # the check via ops/_fused_envelope.py.
    from implicitglobalgrid_tpu.ops.pallas_pt import fused_support_error as pt_err
    from implicitglobalgrid_tpu.ops.pallas_stencil import (
        fused_support_error as diff_err,
    )

    for err_fn in (fused_support_error, pt_err, diff_err):
        assert "not supported by TPU" in err_fn((64, 128, 128), 2, 8)
        assert err_fn((64, 128, 128), 2, 4) is None
