"""Resilience subsystem: guarded init, NaN/Inf guards, checkpoint/restart,
fault injection (docs/robustness.md).

Single-process coverage on the 8-device virtual mesh; the crash→restart
path across a REAL process boundary lives in `test_distributed.py`
(`test_worker_crash_restart_from_checkpoint`).
"""

import os
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import diffusion3d
from implicitglobalgrid_tpu.parallel import distributed as dist
from implicitglobalgrid_tpu.utils import checkpoint as ckpt
from implicitglobalgrid_tpu.utils import config as cfg
from implicitglobalgrid_tpu.utils import resilience as res

NX = 8


@pytest.fixture
def clean_env(monkeypatch):
    for k in list(os.environ):
        if k.startswith("IGG_"):
            monkeypatch.delenv(k)
    res.reset_fault_injector()
    yield
    res.reset_fault_injector()


# -- backoff / retry ----------------------------------------------------------


def test_backoff_schedule_deterministic_under_seeded_jitter():
    a = res.backoff_schedule(6, base_s=0.5, jitter=0.5, seed=123)
    b = res.backoff_schedule(6, base_s=0.5, jitter=0.5, seed=123)
    assert a == b and len(a) == 6
    c = res.backoff_schedule(6, base_s=0.5, jitter=0.5, seed=124)
    assert a != c  # the jitter really is seeded, not constant
    # exponential envelope: delay i in [base*2^i, base*2^i*(1+jitter)], capped
    for i, d in enumerate(a):
        lo = min(0.5 * 2**i, 30.0)
        assert lo <= d <= lo * 1.5


def test_backoff_schedule_no_jitter_exact():
    assert res.backoff_schedule(4, base_s=1.0, jitter=0.0) == [1.0, 2.0, 4.0, 8.0]
    assert res.backoff_schedule(0, base_s=1.0) == []


def test_backoff_schedule_validation():
    with pytest.raises(ValueError, match="retries"):
        res.backoff_schedule(-1)
    with pytest.raises(ValueError, match="base_s"):
        res.backoff_schedule(2, base_s=0)


def test_retry_call_recovers_and_sleeps_the_schedule():
    calls, slept = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("coordinator race")
        return "up"

    out = res.retry_call(
        flaky,
        retries=4,
        base_backoff_s=0.25,
        jitter=0.5,
        seed=9,
        sleep=slept.append,
        on_retry=lambda *a: None,
    )
    assert out == "up" and len(calls) == 3
    assert slept == res.backoff_schedule(4, base_s=0.25, jitter=0.5, seed=9)[:2]


def test_retry_call_exhaustion_names_the_knob():
    with pytest.raises(RuntimeError, match="IGG_INIT_RETRIES"):
        res.retry_call(
            lambda: (_ for _ in ()).throw(OSError("down")),
            retries=1,
            base_backoff_s=0.001,
            sleep=lambda s: None,
            on_retry=lambda *a: None,
        )


def test_retry_call_overall_deadline():
    t = [0.0]

    def clock():
        return t[0]

    def fail():
        t[0] += 10.0  # each attempt burns 10 virtual seconds
        raise OSError("down")

    with pytest.raises(RuntimeError, match="deadline"):
        res.retry_call(
            fail,
            retries=5,
            timeout_s=12.0,
            base_backoff_s=4.0,
            jitter=0.0,
            sleep=lambda s: None,
            clock=clock,
            on_retry=lambda *a: None,
        )


def test_init_distributed_retries_through_injected_flakes(
    clean_env, monkeypatch, fault_injection
):
    fault_injection("init_flake:2")
    attempts = []
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: attempts.append(kw)
    )
    try:
        dist.init_distributed(retries=3, timeout_s=60, backoff_s=0.001)
        # two injected coordinator races, then the real call went through
        assert len(attempts) == 1
        assert dist.owns_runtime()
    finally:
        dist._owns_runtime = False


def test_init_distributed_env_tier_precedence(
    clean_env, monkeypatch, fault_injection
):
    # env says no retries -> the injected flake is fatal...
    monkeypatch.setenv("IGG_INIT_RETRIES", "0")
    monkeypatch.setenv("IGG_INIT_BACKOFF_S", "0.001")
    fault_injection("init_flake:1")
    monkeypatch.setattr(jax.distributed, "initialize", lambda **kw: None)
    with pytest.raises(RuntimeError, match="IGG_INIT_RETRIES"):
        dist.init_distributed()
    # ...but an explicit kwarg overrides the env tier (reference precedence).
    fault_injection("init_flake:1")
    try:
        dist.init_distributed(retries=1)
        assert dist.owns_runtime()
    finally:
        dist._owns_runtime = False


def test_init_knob_env_validation(clean_env, monkeypatch):
    monkeypatch.setenv("IGG_INIT_RETRIES", "-3")
    with pytest.raises(ValueError, match="IGG_INIT_RETRIES.*>= 0"):
        cfg.init_retries_env()
    monkeypatch.setenv("IGG_INIT_TIMEOUT_S", "0")
    with pytest.raises(ValueError, match="IGG_INIT_TIMEOUT_S.*> 0"):
        cfg.init_timeout_env()
    monkeypatch.setenv("IGG_INIT_BACKOFF_S", "nope")
    with pytest.raises(ValueError, match="IGG_INIT_BACKOFF_S.*number"):
        cfg.init_backoff_env()
    monkeypatch.setenv("IGG_GUARD_POLICY", "explode")
    with pytest.raises(ValueError, match="IGG_GUARD_POLICY.*'raise'"):
        cfg.guard_policy_env()
    monkeypatch.setenv("IGG_GUARD_EVERY", "-1")
    with pytest.raises(ValueError, match="IGG_GUARD_EVERY.*>= 0"):
        cfg.guard_every_env()


def test_is_distributed_initialized_degrades_clearly(monkeypatch):
    # Simulate a JAX upgrade that removed the private module AND the public
    # introspection: the answer must be a clear RuntimeError, not an
    # AttributeError from deep inside jax internals.
    import jax._src.distributed as private

    monkeypatch.delattr(private, "global_state")
    if hasattr(jax.distributed, "is_initialized"):
        monkeypatch.delattr(jax.distributed, "is_initialized")
    with pytest.raises(RuntimeError, match="jax.distributed.is_initialized"):
        dist.is_distributed_initialized()


def test_watchdog_smoke():
    with res.watchdog(60):
        pass  # arms and cancels without firing
    with res.watchdog(None):
        pass  # disabled path


def test_watchdog_nesting_rearms_outer_strictest_wins(monkeypatch):
    # faulthandler keeps ONE timer: exiting an inner watchdog must re-arm
    # the enclosing one, and an inner watchdog with a LAXER deadline (the
    # init_distributed-600s-inside-a-270s-exit-watchdog pattern of
    # _resilience_worker.py) must not weaken the outer one.
    import faulthandler

    armed = []
    monkeypatch.setattr(
        faulthandler,
        "dump_traceback_later",
        lambda t, **kw: armed.append((t, kw.get("exit", False))),
    )
    monkeypatch.setattr(
        faulthandler, "cancel_dump_traceback_later", lambda: armed.append(None)
    )
    assert res._watchdog_stack == []
    with res.watchdog(120, exit=True):
        assert armed[-1] == (120.0, True)
        with res.watchdog(600):  # laxer inner: outer's 120/exit must hold
            assert armed[-1] == (120.0, True) and len(res._watchdog_stack) == 2
        with res.watchdog(60):  # tighter inner wins, exit flag ORs in
            assert armed[-1] == (60.0, True)
        assert armed[-1] == (120.0, True)  # inner exited: outer re-armed
    assert armed[-1] is None and res._watchdog_stack == []
    # linear-script arming survives garbage collection (no context object)
    res.arm_watchdog(90)
    assert armed[-1] == (90.0, False) and res._watchdog_stack[-1][0] == 90.0
    res.disarm_watchdog()
    assert armed[-1] is None and res._watchdog_stack == []


def test_checkpoint_step_is_guarded_between_probe_points(
    clean_env, fault_injection, tmp_path
):
    # guard_every=3, checkpoint_every=2, NaN at step 2: the step-2
    # checkpoint must be probed (and trip) — never persist un-probed state.
    fault_injection("halo_corrupt:step2")
    with pytest.raises(igg.GuardError) as ei:
        diffusion3d.run(
            6, NX, NX, NX, guard_every=3, guard_policy="raise",
            checkpoint_every=2, checkpoint_dir=tmp_path, quiet=True,
        )
    assert ei.value.step == 2
    assert igg.latest_checkpoint(tmp_path) is None  # nothing poisoned on disk


# -- numerical guards ---------------------------------------------------------


def test_check_fields_all_finite():
    igg.init_global_grid(NX, NX, NX, quiet=True)
    T = igg.ones((NX, NX, NX))
    report = igg.check_fields(T, names=("T",))
    assert report.ok
    assert "all finite" in report.summary()


def test_check_fields_reports_owning_block_coords():
    igg.init_global_grid(NX, NX, NX, quiet=True)  # dims (2,2,2)
    T = igg.zeros((NX, NX, NX))
    C = igg.ones((NX, NX, NX))
    # poison an interior cell of block (1, 0, 1) = global (8+1, 1, 8+1)
    T = T.at[(NX + 1, 1, NX + 1)].set(jnp.inf)
    report = igg.check_fields(T, C, names=("T", "C"))
    assert not report.ok
    assert report.bad_blocks == {"T": ((1, 0, 1),)}
    assert "T: block(s) (1, 0, 1)" in report.summary()


def test_check_fields_lower_rank_field_no_phantom_blocks():
    # A 2-D field on the 3-D mesh is replicated along z: its bad block must
    # be reported ONCE (coords clamped over the field's own dims), not once
    # per z-replica.
    igg.init_global_grid(NX, NX, NX, quiet=True)  # dims (2,2,2)
    F = igg.zeros((NX, NX))
    F = F.at[(1, NX + 1)].set(jnp.nan)  # block (0, 1)
    report = igg.check_fields(F, names=("F",))
    assert report.bad_blocks == {"F": ((0, 1, 0),)}, report


def test_check_fields_integer_fields_always_finite():
    igg.init_global_grid(NX, NX, NX, quiet=True)
    I = igg.full((NX, NX, NX), 3, jnp.int32)
    assert igg.check_fields(I).ok


def test_guard_trips_at_exact_step_with_block_coords(clean_env, fault_injection):
    fault_injection("halo_corrupt:step3:block5")
    with pytest.raises(igg.GuardError) as ei:
        diffusion3d.run(6, NX, NX, NX, guard_every=1, guard_policy="raise", quiet=True)
    assert ei.value.step == 3
    assert "(1, 0, 1)" in str(ei.value)  # rank 5 on dims (2,2,2)
    assert not igg.grid_is_initialized()  # failed run tore the grid down


def test_guard_trips_within_guard_every_steps(clean_env, fault_injection):
    fault_injection("halo_corrupt:step3")
    with pytest.raises(igg.GuardError) as ei:
        diffusion3d.run(8, NX, NX, NX, guard_every=2, guard_policy="raise", quiet=True)
    assert ei.value.step == 4  # injected at 3, first probe after is step 4
    assert "(0, 0, 0)" in str(ei.value)  # default target block 0


def test_guard_policy_warn_continues(clean_env, fault_injection):
    fault_injection("halo_corrupt:step2")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        T = diffusion3d.run(
            4, NX, NX, NX, guard_every=1, guard_policy="warn", quiet=True
        )
    assert any("guard tripped at step 2" in str(x.message) for x in w)
    assert not np.isfinite(np.asarray(T)).all()  # warn lets the NaN spread


def test_guard_policy_rollback_completes_finite(clean_env, fault_injection):
    fault_injection("halo_corrupt:step3:block2")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        T = diffusion3d.run(
            6, NX, NX, NX, guard_every=1, guard_policy="rollback", quiet=True
        )
    assert np.isfinite(np.asarray(T)).all()
    assert any("rolling back to step 2" in str(x.message) for x in w)
    # the rolled-back run reproduces the fault-free result bit-exactly
    # (the injector fires once; the rollback replays from the last good state)
    res.reset_fault_injector()
    os.environ.pop("IGG_FAULT_INJECT", None)
    T_ref = diffusion3d.run(6, NX, NX, NX, quiet=True)
    np.testing.assert_array_equal(np.asarray(T), np.asarray(T_ref))


def test_halo_hook_corruption_tripped_by_direct_check(clean_env, fault_injection):
    """The ops/halo.py hook point: corruption of a direct update_halo call
    is visible to check_fields (corruption→guard-trip, no model loop)."""
    fault_injection("halo_corrupt:step1:block3")
    igg.init_global_grid(NX, NX, NX, quiet=True)
    T = igg.ones((NX, NX, NX))
    T = igg.update_halo(T)
    report = igg.check_fields(T, names=("T",))
    assert not report.ok
    assert report.bad_blocks["T"] == ((0, 1, 1),)  # rank 3 on dims (2,2,2)


def test_fault_spec_validation(clean_env):
    with pytest.raises(ValueError, match="unknown fault kind"):
        res.FaultInjector.from_spec("cosmic_ray:step1")
    with pytest.raises(ValueError, match="init_flake:N"):
        res.FaultInjector.from_spec("init_flake:two")
    with pytest.raises(ValueError, match="stepN"):
        res.FaultInjector.from_spec("halo_corrupt:12")
    with pytest.raises(ValueError, match="block"):
        res.FaultInjector.from_spec("halo_corrupt:step2:proc1")
    inj = res.FaultInjector.from_spec("worker_crash:step7:proc1")
    assert (inj.kind, inj.step, inj.target) == ("worker_crash", 7, 1)
    assert not res.FaultInjector.from_spec(None).active


# -- checkpoint/restart -------------------------------------------------------


@pytest.mark.parametrize("dtype", ["float32", "float64", "bfloat16"])
def test_checkpoint_roundtrip_bit_exact(tmp_path, dtype):
    import ml_dtypes

    dt = np.dtype({"bfloat16": ml_dtypes.bfloat16}.get(dtype, dtype))
    igg.init_global_grid(NX, NX, NX, quiet=True)
    T = igg.zeros((NX, NX, NX), dt)
    X, Y, Z = igg.coord_fields(T, (0.37, 0.11, 0.53), dtype=dt)
    state = (X, (Y * 3 + Z).astype(dt))
    path = igg.save_checkpoint(tmp_path, state, 12, extra={"model": "t"})
    got, step, extra = igg.restore_checkpoint(path)
    assert step == 12 and extra == {"model": "t"}
    for a, b in zip(got, state):
        assert a.dtype == b.dtype and a.shape == b.shape
        an, bn = np.asarray(a), np.asarray(b)
        # bit-exact: compare the raw bytes, not values (covers -0.0 etc.)
        assert an.tobytes() == bn.tobytes()
        assert a.sharding.is_equivalent_to(b.sharding, a.ndim)


def test_checkpoint_staggered_fields_roundtrip(tmp_path):
    igg.init_global_grid(NX, NX, NX, quiet=True)
    P = igg.ones((NX, NX, NX))
    Vx = igg.full((NX + 1, NX, NX), 2.5)
    path = igg.save_checkpoint(tmp_path, (P, Vx), 1)
    (gP, gVx), step, _ = igg.restore_checkpoint(path, like=(P, Vx))
    np.testing.assert_array_equal(np.asarray(gVx), np.asarray(Vx))


def test_latest_checkpoint_ignores_incomplete(tmp_path):
    igg.init_global_grid(NX, NX, NX, quiet=True)
    T = igg.ones((NX, NX, NX))
    p2 = igg.save_checkpoint(tmp_path, (T,), 2)
    p5 = igg.save_checkpoint(tmp_path, (T,), 5)
    assert igg.latest_checkpoint(tmp_path) == p5
    # a crash mid-save leaves no meta.json -> the dir must be ignored
    os.remove(os.path.join(p5, "meta.json"))
    assert igg.latest_checkpoint(tmp_path) == p2
    assert igg.latest_checkpoint(tmp_path / "nowhere") is None


def test_checkpoint_prune_keeps_newest(tmp_path):
    igg.init_global_grid(NX, NX, NX, quiet=True)
    T = igg.ones((NX, NX, NX))
    for s in (1, 2, 3):
        igg.save_checkpoint(tmp_path, (T,), s)
    removed = ckpt.prune_checkpoints(tmp_path, keep=2)
    assert [os.path.basename(r) for r in removed] == ["step_00000001"]
    assert igg.latest_checkpoint(tmp_path).endswith("step_00000003")


def test_restore_rejects_topology_mismatch(tmp_path):
    # Same local sizes under different dims imply a DIFFERENT global grid —
    # inadmissible even elastically; strict=True keeps the exact-topology
    # contract and its error (the admissible-reshard cases live in
    # tests/test_checkpoint_elastic.py).
    igg.init_global_grid(NX, NX, NX, quiet=True)  # dims (2,2,2)
    T = igg.ones((NX, NX, NX))
    path = igg.save_checkpoint(tmp_path, (T,), 3)
    igg.finalize_global_grid()
    igg.init_global_grid(NX, NX, NX, dimx=4, dimy=2, dimz=1, quiet=True)
    with pytest.raises(ValueError, match="cannot be elastically restored"):
        igg.restore_checkpoint(path)
    with pytest.raises(ValueError, match="different grid topology"):
        igg.restore_checkpoint(path, strict=True)


def test_restore_rejects_wrong_overlap(tmp_path):
    igg.init_global_grid(NX, NX, NX, quiet=True)
    T = igg.ones((NX, NX, NX))
    path = igg.save_checkpoint(tmp_path, (T,), 3)
    igg.finalize_global_grid()
    igg.init_global_grid(NX, NX, NX, overlapx=4, overlapy=4, overlapz=4, quiet=True)
    with pytest.raises(ValueError, match="overlaps"):
        igg.restore_checkpoint(path)


def test_model_checkpoint_resume_bit_identical(tmp_path, clean_env):
    T_full = diffusion3d.run(6, NX, NX, NX, quiet=True)
    # partial run with checkpoints, then a fresh run resumes from step 4
    diffusion3d.run(4, NX, NX, NX, checkpoint_every=2, checkpoint_dir=tmp_path, quiet=True)
    assert igg.latest_checkpoint(tmp_path).endswith("step_00000004")
    T_res = diffusion3d.run(6, NX, NX, NX, checkpoint_every=2, checkpoint_dir=tmp_path, quiet=True)
    np.testing.assert_array_equal(np.asarray(T_res), np.asarray(T_full))


# -- env-tier precedence for the run-guard knobs ------------------------------


def test_runguard_env_tier_precedence(clean_env, monkeypatch, tmp_path):
    # defaults: everything off
    g = res.RunGuard()
    assert (g.guard_every, g.policy, g.checkpoint_every) == (0, "raise", 0)
    # env tier
    monkeypatch.setenv("IGG_GUARD_EVERY", "5")
    monkeypatch.setenv("IGG_GUARD_POLICY", "rollback")
    monkeypatch.setenv("IGG_CHECKPOINT_EVERY", "10")
    monkeypatch.setenv("IGG_CHECKPOINT_DIR", str(tmp_path))
    g = res.RunGuard()
    assert (g.guard_every, g.policy, g.checkpoint_every) == (5, "rollback", 10)
    assert g.checkpoint_dir == str(tmp_path)
    # kwargs beat env (the reference's precedence)
    g = res.RunGuard(guard_every=2, policy="warn", checkpoint_every=3,
                     checkpoint_dir=str(tmp_path / "x"))
    assert (g.guard_every, g.policy, g.checkpoint_every) == (2, "warn", 3)
    assert g.checkpoint_dir == str(tmp_path / "x")


def test_runguard_validation(clean_env):
    with pytest.raises(ValueError, match="policy"):
        res.RunGuard(policy="explode")
    with pytest.raises(ValueError, match="checkpoint_dir"):
        res.RunGuard(checkpoint_every=2)
    with pytest.raises(ValueError, match="guard_every"):
        res.RunGuard(guard_every=-1)
