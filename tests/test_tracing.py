"""Cross-rank observability plane tests (ISSUE 10; docs/observability.md).

Covers the span API (nesting, bounded ring, disabled-mode no-op identity),
the per-rank trace dump + merged Chrome-trace validity (the tier-1 pin:
valid JSON, one track per rank, per-track monotonic timestamps, alignment
metadata with its honesty bound), the straggler probe's single-process
skip, the crash flight recorder, the cost-model reconciliation report
(`analysis.reconcile`) and its reported — not yet gated — perf-gate keys.
The real 2-process gloo legs live in ``test_distributed.py`` /
``tests/_distributed_worker.py``.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.utils import telemetry as tele
from implicitglobalgrid_tpu.utils import tracing

_here = os.path.dirname(os.path.abspath(__file__))
_repo = os.path.dirname(_here)


@pytest.fixture(autouse=True)
def _fresh_state():
    tele.reset()
    tracing.reset()
    yield
    tele.reset()
    tracing.reset()


# -- span API -----------------------------------------------------------------


def test_trace_span_records_nested_spans():
    with tracing.trace_span("outer", kind="test"):
        with tracing.trace_span("inner", step=1):
            pass
    recs = tracing.span_records()
    names = [r["name"] for r in recs]
    # the inner span EXITS first, so it lands in the ring first
    assert names == ["inner", "outer"]
    inner, outer = recs
    assert inner["args"] == {"step": 1}
    assert outer["args"] == {"kind": "test"}
    assert inner["dur"] >= 0 and outer["dur"] >= inner["dur"]
    # containment: the inner span lies within the outer one
    assert outer["t0"] <= inner["t0"]
    assert inner["t0"] + inner["dur"] <= outer["t0"] + outer["dur"] + 1e-9

    summary = tracing.span_summary()
    assert summary["inner"]["count"] == 1
    assert summary["outer"]["total_s"] == pytest.approx(outer["dur"])


def test_trace_span_disabled_returns_shared_noop(monkeypatch):
    monkeypatch.setenv("IGG_TELEMETRY", "0")
    assert tracing.trace_span("x") is tracing.NOOP_SPAN
    with tracing.trace_span("x", a=1):
        pass
    monkeypatch.setenv("IGG_TELEMETRY", "1")
    monkeypatch.setenv("IGG_TRACE_RING", "0")
    assert tracing.trace_span("y") is tracing.NOOP_SPAN
    monkeypatch.delenv("IGG_TRACE_RING")
    assert tracing.span_records() == []


def test_trace_ring_is_bounded(monkeypatch):
    monkeypatch.setenv("IGG_TRACE_RING", "8")
    for i in range(50):
        with tracing.trace_span("s", i=i):
            pass
    recs = tracing.span_records()
    assert len(recs) == 8
    # oldest evicted, newest kept, order preserved
    assert [r["args"]["i"] for r in recs] == list(range(42, 50))


# -- dump + merge -------------------------------------------------------------


def test_dump_trace_requires_dir_and_enabled(monkeypatch, tmp_path):
    monkeypatch.delenv("IGG_TELEMETRY_DIR", raising=False)
    assert igg.dump_trace() is None
    monkeypatch.setenv("IGG_TELEMETRY", "0")
    assert igg.dump_trace(tmp_path) is None


def _synthetic_rank_file(tmp_path, rank, *, perf0, wall, spans,
                         barrier=True, uncertainty=1e-4):
    doc = {
        "schema": tracing.TRACE_SCHEMA,
        "rank": rank,
        "pid": 1000 + rank,
        "coords": [rank, 0, 0],
        "clock_sync": {
            "wall": wall,
            "perf": perf0,
            "uncertainty_s": uncertainty,
            "epoch": 1,
            "barrier": barrier,
        },
        "spans": spans,
    }
    path = tmp_path / tracing.trace_filename(rank)
    path.write_text(json.dumps(doc))
    return str(path)


def test_merge_aligns_ranks_on_the_barrier_instant(tmp_path):
    # Rank 0's perf clock reads 100.0 at the barrier; rank 1's reads 500.0
    # at the SAME instant.  A span 2s after the barrier on each rank must
    # land at the same merged timestamp despite the disjoint clock bases
    # (the in-tolerance NTP wall skew between the samples is ignored).
    f0 = _synthetic_rank_file(
        tmp_path, 0, perf0=100.0, wall=1_000_000.0,
        spans=[{"name": "igg.step", "t0": 102.0, "dur": 0.5,
                "args": {"step": 1}}],
    )
    f1 = _synthetic_rank_file(
        tmp_path, 1, perf0=500.0, wall=1_000_000.4,
        spans=[{"name": "igg.step", "t0": 502.0, "dur": 0.25}],
    )
    doc = tracing.merge_trace_files([f0, f1])
    assert tracing.validate_chrome_trace(doc) == []
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    by_rank = {e["pid"]: e for e in spans}
    assert by_rank[0]["ts"] == pytest.approx(by_rank[1]["ts"])
    align = doc["otherData"]["clock_alignment"]
    assert align["anchor_rank"] == 0
    assert align["per_rank"]["1"]["barrier_aligned"] is True
    assert align["per_rank"]["1"]["uncertainty_s"] == pytest.approx(1e-4)
    # one process_name metadata track per rank
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {e["pid"] for e in meta} == {0, 1}


def test_merge_falls_back_to_wall_clock_without_barrier(tmp_path):
    f0 = _synthetic_rank_file(
        tmp_path, 0, perf0=10.0, wall=50.0,
        spans=[{"name": "a", "t0": 11.0, "dur": 0.1}],
    )
    f1 = _synthetic_rank_file(
        tmp_path, 1, perf0=70.0, wall=53.0, barrier=False,
        spans=[{"name": "b", "t0": 71.0, "dur": 0.1}],
    )
    doc = tracing.merge_trace_files([f0, f1])
    align = doc["otherData"]["clock_alignment"]
    assert align["per_rank"]["1"]["barrier_aligned"] is False
    spans = {e["pid"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
    # rank 1's span sits 3s of wall time after rank 0's (1s past its sync
    # vs 1s past rank 0's sync + 3s wall offset)
    assert (spans[1]["ts"] - spans[0]["ts"]) / 1e6 == pytest.approx(3.0)


def test_merge_refuses_mismatched_barrier_anchors(tmp_path):
    """A stale per-rank dump from a PREVIOUS run in a reused telemetry dir
    must not merge into a fake 'barrier-aligned' timeline: barrier anchors
    from different barriers (wall samples far apart, or different grid
    epochs) are refused with a pointed error."""
    f0 = _synthetic_rank_file(
        tmp_path, 0, perf0=10.0, wall=1_000_000.0,
        spans=[{"name": "a", "t0": 11.0, "dur": 0.1}],
    )
    stale = _synthetic_rank_file(
        tmp_path, 1, perf0=70.0, wall=1_000_500.0,  # a run 500s earlier/later
        spans=[{"name": "b", "t0": 71.0, "dur": 0.1}],
    )
    with pytest.raises(ValueError, match="different runs/barriers"):
        tracing.merge_trace_files([f0, stale])
    # same wall instant but a different grid epoch is refused too
    doc = json.loads((tmp_path / tracing.trace_filename(1)).read_text())
    doc["clock_sync"]["wall"] = 1_000_000.1
    doc["clock_sync"]["epoch"] = 7
    (tmp_path / tracing.trace_filename(1)).write_text(json.dumps(doc))
    with pytest.raises(ValueError, match="different runs/barriers"):
        tracing.merge_trace_files([f0, stale])


def test_merge_rejects_duplicate_ranks_and_bad_schema(tmp_path):
    f0 = _synthetic_rank_file(tmp_path, 0, perf0=0.0, wall=0.0, spans=[])
    dup = tmp_path / "dup.json"
    dup.write_text((tmp_path / tracing.trace_filename(0)).read_text())
    with pytest.raises(ValueError, match="duplicate rank"):
        tracing.merge_trace_files([f0, str(dup)])
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"schema": 999, "rank": 1, "spans": [],
                               "clock_sync": {}}))
    with pytest.raises(ValueError, match="schema"):
        tracing.merge_trace_files([bad])


def test_validate_chrome_trace_catches_breakage():
    ok = {
        "traceEvents": [
            {"ph": "X", "name": "a", "pid": 0, "tid": 0, "ts": 0.0,
             "dur": 1.0},
            {"ph": "X", "name": "b", "pid": 0, "tid": 0, "ts": 2.0,
             "dur": 1.0},
        ],
        "otherData": {"clock_alignment": {}},
    }
    assert tracing.validate_chrome_trace(ok) == []
    nonmono = json.loads(json.dumps(ok))
    nonmono["traceEvents"].append(
        {"ph": "X", "name": "c", "pid": 0, "tid": 0, "ts": 1.0, "dur": 0.1}
    )
    assert any("monotonic" in p for p in tracing.validate_chrome_trace(nonmono))
    # NaN/inf timestamps must be rejected: Python's json writes them but
    # strict parsers and the trace viewers refuse the artifact (and a NaN
    # ts would silently pass the monotonicity comparison)
    for bad_ts in (float("nan"), float("inf")):
        doc = json.loads(json.dumps(ok))
        doc["traceEvents"][1]["ts"] = bad_ts  # json round-trip keeps them
        assert any(
            "non-finite" in p for p in tracing.validate_chrome_trace(doc)
        ), bad_ts
    bad_dur = json.loads(json.dumps(ok))
    bad_dur["traceEvents"][1]["dur"] = float("nan")
    assert any(
        "non-finite" in p for p in tracing.validate_chrome_trace(bad_dur)
    )
    assert tracing.validate_chrome_trace({}) == [
        "traceEvents is missing or not a list"
    ]
    no_meta = {"traceEvents": []}
    assert any(
        "clock_alignment" in p for p in tracing.validate_chrome_trace(no_meta)
    )


def test_real_run_dump_merges_into_valid_trace(monkeypatch, tmp_path):
    """Tier-1 pin of the end-to-end artifact on this process's mesh: an
    instrumented run's dumped spans merge into a valid Chrome trace whose
    ``igg.step`` spans carry their step tags in order."""
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.utils.resilience import RunGuard, \
        guarded_time_loop

    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    state, params = diffusion3d.setup(8, 8, 8, quiet=True)
    try:
        state = guarded_time_loop(
            diffusion3d.make_step(params), state, 3, guard=RunGuard(),
            sync_every_step=True, model="diffusion3d",
            bytes_per_step=tele.teff_bytes(state[:1]),
        )
        path = igg.dump_trace()
    finally:
        igg.finalize_global_grid()
    assert path == str(tmp_path / "trace.p0.json")
    doc = tracing.merge_trace_files([path])
    assert tracing.validate_chrome_trace(doc) == []
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    steps = [e["args"]["step"] for e in spans if e["name"] == "igg.step"]
    assert steps == [1, 2, 3]
    # single process: the sync is exact by construction (no barrier needed)
    sync = json.load(open(path))["clock_sync"]
    assert sync["barrier"] is False
    assert sync["uncertainty_s"] == 0.0


def test_igg_trace_cli_merge_and_validate(tmp_path):
    f0 = _synthetic_rank_file(
        tmp_path, 0, perf0=1.0, wall=10.0,
        spans=[{"name": "igg.step", "t0": 2.0, "dur": 0.5}],
    )
    _synthetic_rank_file(
        tmp_path, 1, perf0=3.0, wall=10.0,
        spans=[{"name": "igg.step", "t0": 4.0, "dur": 0.5}],
    )
    out = tmp_path / "merged.json"
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (_repo, env.get("PYTHONPATH")) if p
    )
    script = os.path.join(_repo, "scripts", "igg_trace.py")
    r = subprocess.run(
        [sys.executable, script, "merge", str(tmp_path), "-o", str(out)],
        capture_output=True, text=True, env=env, timeout=240,
    )
    assert r.returncode == 0, r.stderr
    doc = json.loads(out.read_text())
    assert tracing.validate_chrome_trace(doc) == []
    r = subprocess.run(
        [sys.executable, script, "validate", str(out)],
        capture_output=True, text=True, env=env, timeout=240,
    )
    assert r.returncode == 0, r.stderr
    del f0


# -- straggler probe ----------------------------------------------------------


def test_skew_probe_skips_single_process():
    igg.init_global_grid(8, 8, 8, quiet=True)
    try:
        assert tracing.skew_probe(0.5) is None
    finally:
        igg.finalize_global_grid()
    snap = tele.snapshot()
    assert "skew.step_seconds_max_over_min" not in snap["gauges"]
    assert "skew.slowest_rank" not in snap["gauges"]


def test_skew_probe_without_grid_is_none():
    assert tracing.skew_probe(0.1) is None


# -- flight recorder ----------------------------------------------------------


def test_guard_trip_dumps_flight_bundle(monkeypatch, tmp_path):
    from implicitglobalgrid_tpu.utils.resilience import GuardError, RunGuard

    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    igg.init_global_grid(8, 8, 8, quiet=True)
    try:
        import jax.numpy as jnp

        with tracing.trace_span("pre.trip", step=0):
            pass
        Tg = igg.ones((8, 8, 8), "float64").at[2, 2, 2].set(jnp.nan)
        guard = RunGuard(guard_every=1, policy="raise", names=("T",))
        state, _ = guard.start((Tg,))
        with pytest.raises(GuardError):
            guard.on_step((Tg,), 1)
    finally:
        igg.finalize_global_grid()
    path = tmp_path / tracing.flight_filename(0)
    assert path.is_file(), list(tmp_path.iterdir())
    bundles = tracing.read_flight_bundles(path)
    assert len(bundles) == 1
    b = bundles[0]
    assert b["reason"] == "guard.trip"
    assert b["info"]["step"] == 1 and b["info"]["policy"] == "raise"
    # the three sections: active config, metrics snapshot, span ring
    assert b["config"]["env"]["IGG_TELEMETRY_DIR"] == str(tmp_path)
    assert b["config"]["grid"]["nprocs"] == 8
    assert b["metrics"]["counters"]["resilience.guard_trips"] == 1
    assert any(s["name"] == "pre.trip" for s in b["spans"])


def test_flight_recorder_disabled_or_dirless_is_none(monkeypatch, tmp_path):
    monkeypatch.delenv("IGG_TELEMETRY_DIR", raising=False)
    assert tracing.dump_flight_recorder("test") is None
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.setenv("IGG_TELEMETRY", "0")
    assert tracing.dump_flight_recorder("test") is None
    assert list(tmp_path.iterdir()) == []


def test_flight_recorder_appends_complete_lines(monkeypatch, tmp_path):
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    p1 = tracing.dump_flight_recorder("first", detail=1)
    p2 = tracing.dump_flight_recorder("second", detail=2)
    assert p1 == p2
    bundles = tracing.read_flight_bundles(p1)
    assert [b["reason"] for b in bundles] == ["first", "second"]
    assert bundles[-1]["info"] == {"detail": 2}


# -- open-span tracking (ISSUE 11 satellite) ----------------------------------


def test_open_spans_tracks_the_executing_stack():
    assert tracing.open_spans() == []
    with tracing.trace_span("outer", step=4):
        with tracing.trace_span("inner"):
            open_ = tracing.open_spans()
            assert [s["name"] for s in open_] == ["outer", "inner"]
            assert all(s["open"] is True for s in open_)
            assert open_[0]["args"] == {"step": 4}
            assert all(s["dur"] >= 0 for s in open_)
        assert [s["name"] for s in tracing.open_spans()] == ["outer"]
    # everything closed: stack empty, no per-thread entry leaked
    assert tracing.open_spans() == []
    assert tracing._open_stacks == {}


def test_crash_inside_span_lands_in_flight_bundle(monkeypatch, tmp_path):
    """The ISSUE 11 satellite pin: the span you most want at crash time is
    the one CURRENTLY EXECUTING — the flight bundle must carry it with an
    ``open: true`` marker alongside the closed ring."""
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    with tracing.trace_span("before.crash"):
        pass
    with pytest.raises(RuntimeError, match="boom"):
        with tracing.trace_span("igg.step", model="m", step=9):
            tracing.dump_flight_recorder("test.crash", step=9)
            raise RuntimeError("boom")
    bundles = tracing.read_flight_bundles(
        tmp_path / tracing.flight_filename(0)
    )
    spans = bundles[-1]["spans"]
    closed = [s for s in spans if not s.get("open")]
    open_ = [s for s in spans if s.get("open")]
    assert [s["name"] for s in closed] == ["before.crash"]
    assert [s["name"] for s in open_] == ["igg.step"]
    assert open_[0]["args"] == {"model": "m", "step": 9}
    assert open_[0]["dur"] >= 0


def test_open_spans_disabled_mode_untouched(monkeypatch):
    monkeypatch.setenv("IGG_TELEMETRY", "0")
    with tracing.trace_span("x"):
        assert tracing.open_spans() == []  # NOOP_SPAN touches no stack


# -- span_stats + the summarize subcommand (ISSUE 11 satellite) ---------------


def test_span_stats_aggregates_across_ranks():
    lists = [
        [
            {"name": "igg.step", "t0": 0.0, "dur": 0.001},
            {"name": "igg.step", "t0": 1.0, "dur": 0.003},
            {"name": "igg.gather", "t0": 2.0, "dur": 0.010},
            {"name": "stuck", "t0": 3.0, "dur": 99.0, "open": True},
        ],
        [{"name": "igg.step", "t0": 0.0, "dur": 0.002}],
    ]
    stats = tracing.span_stats(lists)
    assert list(stats) == ["igg.gather", "igg.step"]  # sorted
    st = stats["igg.step"]
    assert st["count"] == 3
    assert st["total_s"] == pytest.approx(0.006)
    assert st["p50_s"] == pytest.approx(0.002)
    assert st["p99_s"] == pytest.approx(0.003)
    assert st["max_s"] == pytest.approx(0.003)
    assert "stuck" not in stats  # open spans carry ages, not durations


#: the golden summarize table for `_summarize_fixture` — change the CLI
#: format deliberately and update this pin with it
_SUMMARIZE_GOLDEN = """\
# 4 span(s) across rank(s) [0, 1]
span                               count   total_ms   mean_ms    p50_ms    p99_ms    max_ms
-------------------------------------------------------------------------------------------
igg.gather                             1     10.000    10.000    10.000    10.000    10.000
igg.step                               3      6.000     2.000     2.000     3.000     3.000"""


def _summarize_fixture(tmp_path):
    _synthetic_rank_file(
        tmp_path, 0, perf0=0.0, wall=100.0,
        spans=[
            {"name": "igg.step", "t0": 0.0, "dur": 0.001},
            {"name": "igg.step", "t0": 1.0, "dur": 0.003},
            {"name": "igg.gather", "t0": 2.0, "dur": 0.010},
        ],
    )
    _synthetic_rank_file(
        tmp_path, 1, perf0=0.0, wall=100.0,
        spans=[{"name": "igg.step", "t0": 0.0, "dur": 0.002}],
    )


def test_igg_trace_cli_summarize_golden(tmp_path):
    _summarize_fixture(tmp_path)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (_repo, env.get("PYTHONPATH")) if p
    )
    script = os.path.join(_repo, "scripts", "igg_trace.py")
    r = subprocess.run(
        [sys.executable, script, "summarize", str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=240,
    )
    assert r.returncode == 0, r.stderr
    assert r.stdout.rstrip("\n") == _SUMMARIZE_GOLDEN
    # --json mode: machine-readable, equals the library aggregation
    r = subprocess.run(
        [sys.executable, script, "summarize", "--json", str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=240,
    )
    assert r.returncode == 0, r.stderr
    stats = json.loads(r.stdout)
    assert stats["igg.step"]["count"] == 3
    assert stats["igg.gather"]["total_s"] == pytest.approx(0.010)


# -- cost-model reconciliation ------------------------------------------------


def test_reconcile_report_from_committed_baseline():
    from implicitglobalgrid_tpu.analysis import reconcile

    report = reconcile.reconcile_report(source="baseline")
    assert set(report["models"]) == {"diffusion", "acoustic", "porous"}
    for model, rec in report["models"].items():
        frac = rec["achieved_fraction"]
        assert frac is not None, (model, rec)
        assert 0.0 < frac <= 1.0, (model, frac)
        assert rec["stream_bytes"] > 0
        assert rec["iterations"] >= 1
        assert rec["modeled_bytes_per_iteration"] >= rec["stream_bytes"]
    # porous counts its inner PT iterations (nt * npt)
    assert report["models"]["porous"]["iterations"] > \
        report["models"]["diffusion"]["iterations"] // 4


def test_reconcile_join_measured_math():
    from implicitglobalgrid_tpu.analysis.reconcile import join_measured

    report = {
        "source": "baseline",
        "note": "n",
        "models": {
            "diffusion": {"achieved_fraction": 0.25},
            "acoustic": {"achieved_fraction": None},
        },
    }
    joined = join_measured(report, {"diffusion": 100.0, "acoustic": 50.0})
    d = joined["models"]["diffusion"]
    assert d["measured_teff_gbs"] == 100.0
    assert d["modeled_actual_gbs"] == pytest.approx(400.0)
    a = joined["models"]["acoustic"]
    assert a["measured_teff_gbs"] == 50.0
    assert "modeled_actual_gbs" not in a


def test_perf_gate_reports_achieved_fraction():
    from implicitglobalgrid_tpu.analysis.perf import (
        gate_metrics,
        gate_summary,
        reported_metrics,
    )

    record = {
        "value": 100.0,
        "extras": {
            "diffusion_xla": {"teff": 100.0},
            "efficiency": {
                "models": {
                    "diffusion": {"achieved_fraction": 0.33,
                                  "measured_teff_gbs": 100.0},
                },
            },
        },
    }
    rep = reported_metrics(record)
    assert rep == {
        "efficiency.models.diffusion.achieved_fraction": 0.33
    }
    # reported keys are NOT gated: they never appear in gate_metrics
    assert not any("achieved_fraction" in k for k in gate_metrics(record))
    verdict = gate_summary(record, _repo)
    assert verdict["reported"] == rep
