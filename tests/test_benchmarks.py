"""Smoke tests for the benchmark harness (`benchmarks/run.py`).

The harness is a shipped artifact (BASELINE.md promises every config as
code), so its code paths are tested like library code — on the virtual CPU
mesh, with tiny volumes.  Timings here are code-path validation only; the
real numbers come from `bench.py` on the TPU chip.  The weak-scaling stall
of round 2 (unsynced windows starving the single-core collective
rendezvous) is exactly the class of regression these tests pin.
"""

import importlib.util
import os

import numpy as np

_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "igg_bench_under_test", os.path.join(_root, "benchmarks", "run.py")
)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _assert_record(rec, metric_prefix):
    assert rec["metric"].startswith(metric_prefix)
    assert rec["unit"] == "GB/s/chip"
    assert np.isfinite(rec["value"]) and rec["value"] > 0
    assert rec["t_it_ms"] > 0


def test_bench_diffusion_smoke():
    rec = bench.bench_diffusion(n=16, chunk=2, reps=1, emit=False)
    _assert_record(rec, "diffusion3d_16")
    assert rec["nprocs"] == 8  # ran on the full virtual mesh


def test_bench_diffusion_multidevice_spmd():
    # The force_spmd path the weak-scaling bench uses (collectives in the
    # timed loop — the config that stalled when windows stopped syncing).
    import jax

    rec = bench.bench_diffusion(
        n=16, chunk=2, reps=1, emit=False, devices=jax.devices()[:2], force_spmd=True
    )
    _assert_record(rec, "diffusion3d_16")
    assert rec["nprocs"] == 2


def test_bench_acoustic_smoke():
    rec = bench.bench_acoustic(n=16, chunk=2, reps=1, emit=False)
    _assert_record(rec, "acoustic3d_16")


def test_bench_porous_smoke():
    rec = bench.bench_porous(n=16, chunk=1, reps=1, npt=2, emit=False)
    _assert_record(rec, "porous_convection3d_16")
    assert rec["t_pt_ms"] > 0


def test_bench_entrypoint_contract(monkeypatch, capsys):
    """bench.py must print exactly ONE valid JSON line with the driver's
    required keys, pick the faster production path as the headline, and
    isolate a failing extra without losing the rest."""
    import importlib.util
    import json

    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(_root, "bench.py")
    )
    bm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bm)

    calls = {}

    def fake_diffusion(**kw):
        calls.setdefault("diffusion", []).append(kw)
        if kw.get("fused_k") and kw.get("n") == 512:
            raise RuntimeError("no 512 on this backend")
        teff = 500.0 if kw.get("fused_k") else 350.0
        return {"metric": "diffusion3d_256_float32", "value": teff,
                "t_it_ms": 0.25, "unit": "GB/s/chip"}

    def fake_acoustic(**kw):
        return {"metric": "acoustic3d_192_float32", "value": 400.0,
                "t_it_ms": 0.5, "unit": "GB/s/chip"}

    def fake_porous(**kw):
        return {"metric": "porous_convection3d_160_float32_npt10", "value": 350.0,
                "t_it_ms": 3.7, "t_pt_ms": 0.37, "unit": "GB/s/chip"}

    monkeypatch.setattr(bm._bench, "bench_diffusion", lambda **kw: fake_diffusion(**kw))
    monkeypatch.setattr(bm._bench, "bench_acoustic", lambda **kw: fake_acoustic(**kw))
    monkeypatch.setattr(bm._bench, "bench_porous", lambda **kw: fake_porous(**kw))
    # The remaining extras do REAL work sized for a TPU chip (512^3 halo
    # timing windows, the weak-scaling subprocess, a 256-chip AOT lowering)
    # — minutes to hours on the test CPU; stub them so this stays the JSON
    # *contract* test.  Their code paths are covered by the bench smokes
    # above and the AOT/weak tests.
    monkeypatch.setattr(
        bm._bench, "_time_steps", lambda step, state, chunk, reps: (1e-3, state, 0.0)
    )
    monkeypatch.setattr(
        bm._bench, "aot_weak_proxy", lambda emit=False: {"stub": True}
    )
    # the front-door record drives a real serving pool + HTTP round trip
    # (covered by tests/test_frontdoor.py) — stub it for the contract test
    monkeypatch.setattr(
        bm, "_frontdoor_serving_record",
        lambda **kw: {"rounds_per_s": 1.0, "stub": True},
    )
    import subprocess
    import types

    monkeypatch.setattr(
        subprocess,
        "run",
        lambda *a, **kw: types.SimpleNamespace(
            returncode=0,
            stdout='{"metric": "weak_stub", "value": 1.0}\n',
            stderr="",
        ),
    )
    bm.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, f"expected ONE JSON line, got {len(out)}"
    rec = json.loads(out[0])
    assert set(rec) >= {"metric", "value", "unit", "vs_baseline", "extras"}
    assert rec["metric"] == "diffusion3d_256_float32_teff"
    assert rec["value"] == 500.0  # best of XLA (350) and fused (500)
    assert rec["vs_baseline"] == round(500.0 / bm.BASELINE_TEFF_GBS, 3)
    # the failing 512^3 extra is isolated as an error, others survive
    assert "error" in rec["extras"]["diffusion_512_pallas_fused4"]
    assert rec["extras"]["acoustic"]["teff"] == 400.0
    assert rec["extras"]["porous_pt"]["teff"] == 350.0


def test_fused_provenance_labels():
    """A fused_k request whose shape the envelope rejects must be labeled as
    the fallback in the emitted metric name and path record (an XLA number
    must never be recorded under a fused-kernel label)."""
    from benchmarks.run import _fused_provenance
    from implicitglobalgrid_tpu.ops.pallas_pt import fused_support_error as pt_err
    from implicitglobalgrid_tpu.ops.pallas_stencil import (
        fused_support_error as diff_err,
    )

    assert _fused_provenance(None, diff_err, (256, 256, 256), 4, None) == ("", None)
    assert _fused_provenance(4, diff_err, (256, 256, 256), 4, None) == (
        "_fused4", "pallas-fused"
    )
    # 192 minor dim: rejected by the lane-alignment envelope -> fallback label.
    assert _fused_provenance(4, diff_err, (192, 192, 192), 4, None) == (
        "_fused4fb", "xla-fallback"
    )
    assert _fused_provenance(2, pt_err, (160, 160, 160), 4, None) == (
        "_fused2fb", "xla-fallback"
    )


def test_collective_payloads_parser():
    """Unit pin of the HLO payload reader behind the weak-scaling AOT proxy:
    sync permutes count once, async starts halve their duplicated
    operand/result tuple (verified against a real compiled instruction),
    scalar context words are excluded, -done ops are not hops."""
    from implicitglobalgrid_tpu.utils.hlo_analysis import collective_payloads

    txt = """
ENTRY %main (p0: f32[4,8]) -> f32[4,8] {
  %a = f32[4,8]{1,0} collective-permute(%p0), source_target_pairs={{0,1}}
  %b = (f32[2,8]{1,0:T(8,128)S(1)}, f32[2,8]{1,0:T(8,128)S(1)}, u32[]{:S(2)}, u32[]{:S(2)}) collective-permute-start(%s), source_target_pairs={{0,1}}
  %c = f32[2,8]{1,0} collective-permute-done(%b)
  %d = (f32[4,8]{1,0}, f32[2,2]{1,0}) collective-permute(%x, %y), source_target_pairs={{1,0}}
}
"""
    hops = collective_payloads(txt)
    assert len(hops) == 3  # a, b, d — NOT the -done
    by_bytes = sorted(h["bytes"] for h in hops)
    # a: 4*8*4 = 128; b: (2*8*4)*2/2 = 64; d: 4*8*4 + 2*2*4 = 144
    assert by_bytes == [64, 128, 144]
