"""Smoke tests for the benchmark harness (`benchmarks/run.py`).

The harness is a shipped artifact (BASELINE.md promises every config as
code), so its code paths are tested like library code — on the virtual CPU
mesh, with tiny volumes.  Timings here are code-path validation only; the
real numbers come from `bench.py` on the TPU chip.  The weak-scaling stall
of round 2 (unsynced windows starving the single-core collective
rendezvous) is exactly the class of regression these tests pin.
"""

import importlib.util
import os

import numpy as np

_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "igg_bench_under_test", os.path.join(_root, "benchmarks", "run.py")
)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _assert_record(rec, metric_prefix):
    assert rec["metric"].startswith(metric_prefix)
    assert rec["unit"] == "GB/s/chip"
    assert np.isfinite(rec["value"]) and rec["value"] > 0
    assert rec["t_it_ms"] > 0


def test_bench_diffusion_smoke():
    rec = bench.bench_diffusion(n=16, chunk=2, reps=1, emit=False)
    _assert_record(rec, "diffusion3d_16")
    assert rec["nprocs"] == 8  # ran on the full virtual mesh


def test_bench_diffusion_multidevice_spmd():
    # The force_spmd path the weak-scaling bench uses (collectives in the
    # timed loop — the config that stalled when windows stopped syncing).
    import jax

    rec = bench.bench_diffusion(
        n=16, chunk=2, reps=1, emit=False, devices=jax.devices()[:2], force_spmd=True
    )
    _assert_record(rec, "diffusion3d_16")
    assert rec["nprocs"] == 2


def test_bench_acoustic_smoke():
    rec = bench.bench_acoustic(n=16, chunk=2, reps=1, emit=False)
    _assert_record(rec, "acoustic3d_16")


def test_bench_porous_smoke():
    rec = bench.bench_porous(n=16, chunk=1, reps=1, npt=2, emit=False)
    _assert_record(rec, "porous_convection3d_16")
    assert rec["t_pt_ms"] > 0
