"""Autotuned kernel & schedule configs (ISSUE 13, docs/performance.md).

The tuner's whole contract in one suite: the cache is a refusing,
atomically-published schema (never a crash, never a silently-applied stale
config), the static prior keeps over-budget candidates away from
measurement, the search is deterministic given deterministic measurements,
a cache hit measures NOTHING, and a tuned config is a pure schedule
substitution — bit-identical results on the oracle matrix for all three
models.  The SPMD half (rank-0-decides + broadcast over real gloo hops)
lives in `tests/_distributed_worker.py`; the rank-divergence POSITIVE
fixture here proves the `collective-consistency` analyzer catches a
rank-keyed cache lookup.
"""

import json
import os

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu import tuning
from implicitglobalgrid_tpu.models import (
    acoustic3d,
    diffusion3d,
    porous_convection3d,
)
from implicitglobalgrid_tpu.utils import telemetry as tele

_here = os.path.dirname(os.path.abspath(__file__))
_repo = os.path.dirname(_here)


def _tune_counters():
    snap = tele.snapshot()
    return {k: v for k, v in snap.get("counters", {}).items()
            if k.startswith("tune.")}


@pytest.fixture
def tune_cache(tmp_path, monkeypatch):
    """A fresh primary cache dir (env-wired) with NO seed fallback, so a
    test's lookups can never hit the committed chip entries."""
    d = str(tmp_path / "tunecache")
    monkeypatch.setenv("IGG_TUNE_CACHE", d)
    return tuning.TuneCache(primary=d, fallbacks=())


# -- keys + schema ------------------------------------------------------------


def test_keys_distinct_and_filenames_stable():
    base = dict(batch=0, backend="tpu", topology="t")
    k1 = tuning.make_key("diffusion3d", (256, 256, 256), "float32", **base)
    variants = [
        tuning.make_key("diffusion3d", (256, 256, 256), "float64", **base),
        tuning.make_key("diffusion3d", (128, 256, 256), "float32", **base),
        tuning.make_key("acoustic3d", (256, 256, 256), "float32", **base),
        tuning.make_key("diffusion3d", (256, 256, 256), "float32",
                        backend="tpu", topology="other", batch=0),
        tuning.make_key("diffusion3d", (256, 256, 256), "float32",
                        backend="tpu", topology="t", batch=1),
        tuning.make_key("porous_convection3d", (256, 256, 256), "float32",
                        extra={"npt": 12}, **base),
        tuning.make_key("porous_convection3d", (256, 256, 256), "float32",
                        extra={"npt": 10}, **base),
    ]
    names = {tuning.entry_filename(k) for k in variants}
    assert tuning.entry_filename(k1) not in names
    assert len(names) == len(variants)  # every key component keys
    # same inputs -> same digest (the lookup path depends on it)
    k1b = tuning.make_key("diffusion3d", (256, 256, 256), "float32", **base)
    assert tuning.entry_filename(k1) == tuning.entry_filename(k1b)
    with pytest.raises(ValueError, match="unknown model"):
        tuning.make_key("nope", (8, 8, 8), "float32", **base)


def test_validate_entry_contract():
    key = tuning.make_key("diffusion3d", (16, 16, 16), "float32",
                          backend="cpu", topology="t")
    good = tuning.new_entry(key, {"fused_k": 4, "fused_tile": [32, 64]})
    tuning.validate_entry(good)  # round-trips
    for mutate, match in (
        (lambda d: d.update(schema_version=99), "schema version"),
        (lambda d: d["config"].update(npt=12), "pure substitution"),
        (lambda d: d["config"].update(fused_k=3), r"\[2, 8\] ladder"),
        (lambda d: d["config"].update(fused_tile="big"), "2 positive ints"),
        (lambda d: d.update(source=""), "provenance"),
        (lambda d: d["key"].update(size=[0, 1, 2]), "3 positive ints"),
    ):
        doc = json.loads(json.dumps(good))
        mutate(doc)
        with pytest.raises(ValueError, match=match):
            tuning.validate_entry(doc)
    with pytest.raises(ValueError, match="without fused_k"):
        tuning.new_entry(key, {"fused_tile": [32, 64]})


def test_cache_roundtrip_refusals_and_atomicity(tune_cache):
    key = tuning.make_key("diffusion3d", (16, 16, 16), "float32",
                          backend="cpu", topology="t")
    entry = tuning.new_entry(key, {"exchange_every": 2}, source="test")
    path = tune_cache.store(key, entry)
    assert not os.path.exists(path + ".tmp")  # atomic publish, no debris
    got = tune_cache.lookup(key)
    assert got["config"] == {"exchange_every": 2}

    # version-mismatch refusal: a future schema must read as a MISS
    doc = json.load(open(path))
    doc["schema_version"] = tuning.SCHEMA_VERSION + 1
    json.dump(doc, open(path, "w"))
    assert tune_cache.lookup(key) is None
    assert "schema version" in tune_cache.last_refusal

    # corrupt-entry fallback to default: also a miss, reason recorded
    with open(path, "w") as f:
        f.write('{"schema_version": 1, "key": {tru')
    assert tune_cache.lookup(key) is None
    assert "corrupt" in tune_cache.last_refusal

    # key drift: a valid entry under the WRONG filename must not serve
    other = tuning.make_key("diffusion3d", (32, 32, 32), "float32",
                            backend="cpu", topology="t")
    tune_cache.store(key, entry)
    os.replace(path, tune_cache.path_for(other))
    assert tune_cache.lookup(other) is None
    assert "key drift" in tune_cache.last_refusal

    # layered lookup: the fallback serves what the primary lacks
    layered = tuning.TuneCache(primary=tune_cache.primary + ".empty",
                               fallbacks=(tune_cache.primary,))
    tune_cache.store(key, entry)
    assert layered.lookup(key)["config"] == {"exchange_every": 2}
    assert tune_cache.clear() >= 1
    assert tune_cache.lookup(key) is None


# -- candidate space + prior --------------------------------------------------


def test_candidate_space_ladders_and_rejections():
    cands, rejected = tuning.candidate_space(
        "diffusion3d", (256, 256, 256), 4, nsteps=24)
    cfgs = [c["config"] for c in cands]
    assert cfgs[0] == {}  # the default is always first (and always measured)
    ks = {c.get("fused_k") for c in cfgs if "fused_k" in c}
    assert ks == {2, 4, 6, 8}  # nsteps=24 admits the whole even ladder
    assert any("fused_tile" in c for c in cfgs)  # tile ladder enumerated
    # no grid -> nothing to exchange: no exchange_every, no coalesce twins
    assert not any("exchange_every" in c for c in cfgs)
    assert not any("coalesce" in c for c in cfgs)
    assert any("nothing to amortize" in r["error"] for r in rejected)
    # modeled prior: temporal blocking must model FEWER bytes than default
    default_b = cands[0]["modeled"]["bytes_per_step"]
    fused = next(c for c in cands if c["config"].get("fused_k") == 4)
    assert fused["modeled"]["bytes_per_step"] < default_b
    assert fused["modeled"]["vmem_bytes"] > 0

    # a non-128 minor dim rejects the whole kernel ladder with the reason
    cands8, rejected8 = tuning.candidate_space(
        "diffusion3d", (8, 8, 8), 4, nsteps=8)
    assert [c["config"] for c in cands8] == [{}]
    assert all("128" in r["error"] or "amortize" in r["error"]
               or "multiple" in r["error"] for r in rejected8)


def test_vmem_ladder_prunes_before_measurement(monkeypatch):
    # (a) the env ladder at enumeration: IGG_VMEM_MB shrinks every kernel
    # budget, so the fused candidates are rejected by the envelope itself
    monkeypatch.setenv("IGG_VMEM_MB", "4")
    cands, rejected = tuning.candidate_space(
        "diffusion3d", (256, 256, 256), 4, nsteps=24)
    assert [c["config"] for c in cands] == [{}]
    # the envelope's auto-tile flow reports a ladder with NO fitting rung
    # (every rung failed the scaled VMEM budget)
    assert any("no tuned tile candidate" in r["error"] for r in rejected)
    monkeypatch.delenv("IGG_VMEM_MB")

    # (b) the explicit prune budget: an over-budget candidate lands in the
    # cut with the reason and NEVER reaches the measure callable
    cands, _ = tuning.candidate_space(
        "diffusion3d", (256, 256, 256), 4, nsteps=24)
    big = [c for c in cands if c["modeled"]["vmem_bytes"] > 1024]
    assert big, "expected kernel candidates with a modeled working set"
    survivors, cut = tuning.prune(cands, topk=99, vmem_budget_bytes=1024)
    assert [c["config"] for c in survivors
            if c["modeled"]["vmem_bytes"] > 1024] == []
    assert all("VMEM" in c["error"] for c in cut
               if c["modeled"]["vmem_bytes"] > 1024)
    measured = [c["config"] for c in survivors]
    for c in big:
        assert c["config"] not in measured

    # (c) topk: the default always survives, the rest rank by the prior
    survivors, cut = tuning.prune(cands, topk=3)
    assert len(survivors) == 3 and survivors[0]["config"] == {}
    ranked = [tuning.modeled_seconds(c["modeled"]) for c in survivors[1:]]
    assert ranked == sorted(ranked)
    with pytest.raises(ValueError, match="topk"):
        tuning.prune(cands, topk=0)


# -- resolve: determinism, cache hit, telemetry -------------------------------


def _grid16():
    igg.init_global_grid(16, 16, 16, overlapx=4, overlapy=4, overlapz=4,
                         quiet=True)
    from implicitglobalgrid_tpu.parallel.grid import global_grid

    return global_grid()


def test_search_deterministic_and_second_call_hits(tune_cache):
    gg = _grid16()
    calls = []

    def measure(cfg):
        calls.append(json.dumps(cfg, sort_keys=True))
        return 0.25 if cfg.get("exchange_every") == 2 else 1.0

    before = _tune_counters()
    cfg1 = tuning.resolve_tuned_config(
        "diffusion3d", gg.nxyz, "float32", nsteps=4, gg=gg,
        cache=tune_cache, measure=measure)
    first_calls = list(calls)
    assert cfg1 == {"exchange_every": 2}
    assert len(first_calls) >= 2  # default + at least the winner

    # determinism: same inputs, fresh cache -> same winner, same order
    tune_cache.clear()
    calls.clear()
    cfg2 = tuning.resolve_tuned_config(
        "diffusion3d", gg.nxyz, "float32", nsteps=4, gg=gg,
        cache=tune_cache, measure=measure)
    assert cfg2 == cfg1 and calls == first_calls

    # cache hit: zero measurement, pinned via the counters
    calls.clear()
    cfg3 = tuning.resolve_tuned_config(
        "diffusion3d", gg.nxyz, "float32", nsteps=4, gg=gg,
        cache=tune_cache, measure=measure)
    after = _tune_counters()
    assert cfg3 == cfg1 and calls == []
    assert after.get("tune.cache_hit", 0) - before.get("tune.cache_hit", 0) == 1
    assert (after.get("tune.candidates_measured", 0)
            - before.get("tune.candidates_measured", 0)) == 2 * len(first_calls)
    assert after.get("tune.cache_miss", 0) - before.get("tune.cache_miss", 0) == 2
    assert (after.get("tune.candidates_pruned", 0)
            > before.get("tune.candidates_pruned", 0))

    # the persisted entry carries provenance + the tuner census
    entry = tune_cache.lookup(tuning.make_key(
        "diffusion3d", gg.nxyz, "float32", gg=gg, nsteps=4))
    assert entry["source"] == "search"
    assert entry["tuner"]["measured"] == len(first_calls)

    # the igg.tune span wrapped each resolve (rank-tagged winner events
    # ride the standard event log; the span is the timing surface)
    from implicitglobalgrid_tpu.utils.tracing import span_summary

    assert "igg.tune" in span_summary()


def test_degenerate_point_is_never_persisted(tune_cache):
    """nsteps=5 admits NO cadence candidate on this grid (odd, non-128
    minor): the resolve must return the default WITHOUT storing (or
    measuring) anything, and a cadence-admissible nsteps afterwards still
    finds its real win."""
    gg = _grid16()
    calls = []

    def measure(cfg):
        calls.append(cfg)
        return 0.25 if cfg.get("exchange_every") == 2 else 1.0

    cfg = tuning.resolve_tuned_config(
        "diffusion3d", gg.nxyz, "float32", nsteps=5, gg=gg,
        cache=tune_cache, measure=measure)
    assert cfg == {} and calls == []  # nothing measured either
    assert not os.path.isdir(tune_cache.primary) or \
        os.listdir(tune_cache.primary) == []
    # a cadence-admissible nsteps (its own schedule-class key) still
    # finds the real win afterwards
    cfg4 = tuning.resolve_tuned_config(
        "diffusion3d", gg.nxyz, "float32", nsteps=4, gg=gg,
        cache=tune_cache, measure=measure)
    assert cfg4 == {"exchange_every": 2} and calls


def test_schedule_class_keys_chunk_sizes_apart():
    """nsteps keys only through its admissibility class: 24 and 48 share a
    winner (same ladder), 16 tunes its own point, porous is class-exempt
    (its cadence chunks npt)."""
    base = dict(batch=0, backend="tpu", topology="t")
    k24 = tuning.make_key("diffusion3d", (256,) * 3, "float32", nsteps=24,
                          **base)
    k48 = tuning.make_key("diffusion3d", (256,) * 3, "float32", nsteps=48,
                          **base)
    k16 = tuning.make_key("diffusion3d", (256,) * 3, "float32", nsteps=16,
                          **base)
    assert k24 == k48 and k24["schedule"] == "w2.4.6.8"
    assert k16 != k24 and k16["schedule"] == "w2.4.8"
    assert tuning.schedule_class("porous_convection3d", 7) == "npt"
    assert tuning.schedule_class("diffusion3d", 5) == "none"


def test_incompatible_hit_researches_without_overwriting(tune_cache):
    """A HAND-SEEDED winner whose cadence cannot divide the live nsteps
    (a resolve-written one cannot — the key's schedule class forbids it)
    must not silently under-tune: the hit falls through to a fresh search
    for THIS nsteps — and the stored entry survives untouched."""
    gg = _grid16()
    key = tuning.make_key("diffusion3d", gg.nxyz, "float32", gg=gg,
                          nsteps=4)
    tune_cache.store(key, tuning.new_entry(
        key, {"fused_k": 6}, source="hand-seed"))
    calls = []

    def measure(cfg):
        calls.append(cfg)
        return 0.25 if cfg.get("exchange_every") == 2 else 1.0

    cfg = tuning.resolve_tuned_config(
        "diffusion3d", gg.nxyz, "float32", nsteps=4, gg=gg,
        cache=tune_cache, measure=measure)
    assert cfg == {"exchange_every": 2} and calls  # searched, not projected
    assert tune_cache.lookup(key)["config"] == {"fused_k": 6}  # no thrash
    # cache-only mode never applies the incompatible winner either
    assert tuning.resolve_tuned_config(
        "diffusion3d", gg.nxyz, "float32", nsteps=4, gg=gg,
        cache=tune_cache, allow_search=False) == {}


def test_unreadable_entry_degrades_to_a_miss(tune_cache):
    """The never-crash contract covers OSError too: a directory squatting
    on the entry's filename (or an unreadable file) must read as a miss,
    not abort make_multi_step."""
    key = tuning.make_key("diffusion3d", (16, 16, 16), "float32",
                          backend="cpu", topology="t")
    os.makedirs(tune_cache.path_for(key))  # IsADirectoryError on open()
    assert tune_cache.lookup(key) is None
    assert "unreadable" in tune_cache.last_refusal
    # the CLI listing survives it too: unreadable rows carry a None doc
    assert [doc for _p, doc in tune_cache.entries()] == [None]


def test_resolve_without_measure_needs_cache(tune_cache):
    gg = _grid16()
    with pytest.raises(ValueError, match="no measure callable"):
        tuning.resolve_tuned_config("diffusion3d", gg.nxyz, "float32",
                                    nsteps=4, gg=gg, cache=tune_cache)
    # allow_search=False is the no-surprise mode: a miss is the default
    assert tuning.resolve_tuned_config(
        "diffusion3d", gg.nxyz, "float32", nsteps=4, gg=gg,
        cache=tune_cache, allow_search=False) == {}


def test_telemetry_disabled_is_a_noop(tune_cache, monkeypatch):
    monkeypatch.setenv("IGG_TELEMETRY", "0")
    gg = _grid16()
    cfg = tuning.resolve_tuned_config(
        "diffusion3d", gg.nxyz, "float32", nsteps=4, gg=gg,
        cache=tune_cache, measure=lambda c: 1.0)
    assert isinstance(cfg, dict)  # no crash, no registry writes


def test_explicit_kwargs_win_and_skip_the_search(tune_cache, monkeypatch):
    gg = _grid16()
    key = tuning.make_key("diffusion3d", gg.nxyz, np.dtype("float64"), gg=gg)
    tune_cache.store(key, tuning.new_entry(key, {"exchange_every": 4},
                                           source="test"))
    state, params = diffusion3d.setup(16, 16, 16, init_grid=False)
    from implicitglobalgrid_tpu.tuning.search import apply_tuned_config

    kwargs = dict(fused_k=None, fused_tile=None, exchange_every=2,
                  pipelined=None, coalesce=None)
    out = apply_tuned_config("diffusion3d", diffusion3d, params, 4,
                             dict(kwargs))
    assert out == kwargs  # pinned kwarg -> untouched, no resolve
    # and the full entry point honors the pin too (the cached
    # exchange_every=4 would not even divide nsteps=6)
    step = diffusion3d.make_multi_step(params, 6, donate=False,
                                       exchange_every=2, autotune=True)
    assert callable(step)


def test_hide_comm_run_skips_the_search(tune_cache):
    """hide_comm schedules the per-step path; every cadence candidate
    conflicts with it (the builders raise on the combination), so
    autotune=True must SKIP cleanly — not crash mid-search on the first
    fused/exchange candidate build."""
    import jax

    state, params = diffusion3d.setup(
        16, 16, 16, hide_comm=True,
        overlapx=4, overlapy=4, overlapz=4, quiet=True,
    )
    step = diffusion3d.make_multi_step(params, 4, donate=False,
                                       autotune=True)
    out = jax.block_until_ready(step(*state))
    assert out[0].shape == state[0].shape
    # nothing searched, nothing persisted
    assert not os.path.isdir(tune_cache.primary) or \
        os.listdir(tune_cache.primary) == []


def test_project_config_drops_an_undividable_cadence():
    from implicitglobalgrid_tpu.tuning.search import project_config

    cfg = {"fused_k": 4, "fused_tile": [32, 64], "pipelined": True,
           "coalesce": False}
    assert project_config("diffusion3d", cfg, nsteps=6) == {"coalesce": False}
    assert project_config("diffusion3d", cfg, nsteps=8) == cfg
    # the porous cadence chunks npt, not nsteps: exempt
    assert project_config("porous_convection3d", {"fused_k": 6},
                          nsteps=7) == {"fused_k": 6}


# -- bit-exactness: tuning changes schedule, never results --------------------

#: (model module, model name, setup kwargs, tuned config, nsteps) — each on
#: the deep-halo DECOMPOSED oracle grid the repo's cadence-equivalence
#: tests pin bitwise (8-device (2,2,2) mesh, overlap 4, non-periodic: 12
#: real internal boundaries; a periodic wrap re-fuses the program and
#: trades bitwise for the documented fusion-rounding ULPs).  The cached
#: config is a nontrivial schedule change (slab cadence; the acoustic row
#: also flips the coalesce lever).
_ORACLE = (
    (diffusion3d, "diffusion3d", {}, {"exchange_every": 2}, 4),
    (acoustic3d, "acoustic3d", {}, {"exchange_every": 2, "coalesce": False},
     4),
    (porous_convection3d, "porous_convection3d", {"npt": 4},
     {"exchange_every": 2}, 2),
)


@pytest.mark.parametrize("module,name,setup_kw,config,nsteps", _ORACLE,
                         ids=[r[1] for r in _ORACLE])
def test_tuned_config_bit_identical_to_default(module, name, setup_kw,
                                               config, nsteps, tune_cache):
    import jax

    grid_kw = dict(overlapx=4, overlapy=4, overlapz=4, quiet=True)

    def run(**mk_kwargs):
        state, params = module.setup(16, 16, 16, **setup_kw, **grid_kw)
        step = module.make_multi_step(params, nsteps, donate=False,
                                      **mk_kwargs)
        out = jax.block_until_ready(step(*state))
        got = np.asarray(igg.gather(out[0]))
        key = tuning.make_key(
            name, (16, 16, 16), params.dtype,
            gg=igg.get_global_grid(), nsteps=nsteps,
            extra={"npt": setup_kw["npt"]} if "npt" in setup_kw else None,
        )
        igg.finalize_global_grid()
        return got, key

    ref, key = run()
    tune_cache.store(key, tuning.new_entry(key, config, source="test"))
    tuned, _ = run(autotune=True)
    # owned cells bit-identical: the tuned cadence changed the SCHEDULE
    # (slab exchanges, coalescing) and nothing else
    np.testing.assert_array_equal(tuned, ref)
    # and the resolve really served the seeded winner, not a fresh search
    assert tune_cache.lookup(key)["source"] == "test"


# -- seeding from the committed trajectory ------------------------------------


def test_seed_from_bench_ingests_the_recorded_winners(tune_cache):
    entries = tuning.seed_from_bench(_repo, tune_cache, backend="tpu")
    assert entries, "the committed BENCH rounds carry seedable extras"
    by_key = {(e["key"]["model"], tuple(e["key"]["size"]),
               e["key"]["extra"].get("npt")): e for e in entries}
    porous = by_key[("porous_convection3d", (256, 256, 256), 12)]
    assert porous["config"] == {"fused_k": 6}
    assert porous["source"] == "seed:bench_r04"  # provenance per entry
    assert porous["measured"]["teff_gbs"] == pytest.approx(989.35)
    # the npt=10 ragged win seeds its own key (npt keys, never tunes)
    assert ("porous_convection3d", (256, 256, 256), 10) in by_key
    assert by_key[("diffusion3d", (512, 512, 512), None)]["config"] == {
        "fused_k": 4, "fused_tile": [32, 128]}
    # what seed wrote is exactly what the committed layer ships
    committed = {os.path.basename(p) for p, _ in
                 tuning.TuneCache(primary=tuning.SEED_DIR,
                                  fallbacks=()).entries()}
    written = {os.path.basename(tune_cache.path_for(e["key"]))
               for e in entries}
    assert written == committed


# -- the tune-cache-valid analyzer --------------------------------------------


def test_tune_cache_valid_analyzer_fires_on_seeded_defects(tmp_path):
    from implicitglobalgrid_tpu.analysis.tunecache import cache_findings

    d = str(tmp_path)
    key = tuning.make_key("diffusion3d", (256, 256, 256), "float32",
                          backend="tpu", topology="t")
    good = tuning.new_entry(key, {"fused_k": 4}, source="test")

    # stale schema
    doc = json.loads(json.dumps(good))
    doc["schema_version"] = 0
    json.dump(doc, open(os.path.join(d, tuning.entry_filename(key)), "w"))
    # corrupt
    open(os.path.join(d, "broken.json"), "w").write("{nope")
    # inadmissible config: the tile does not divide the keyed volume
    # (schema-valid and correctly filed — only the admissibility gate fires)
    key512 = tuning.make_key("diffusion3d", (512, 512, 512), "float32",
                             backend="tpu", topology="t")
    bad = tuning.new_entry(key512, {"fused_k": 4, "fused_tile": [100, 100]},
                           source="test")
    json.dump(bad, open(os.path.join(d, tuning.entry_filename(key512)), "w"))
    # key drift: valid entry under a wrong filename
    json.dump(good, open(os.path.join(d, "drifted.json"), "w"))

    codes = sorted(f.code for f in cache_findings(d))
    assert codes == ["entry-corrupt", "inadmissible-config", "key-drift",
                     "stale-schema"]
    assert all(f.severity == "ERROR" for f in cache_findings(d))


def test_committed_seed_layer_is_clean_and_registered():
    from implicitglobalgrid_tpu.analysis import available_analyzers
    from implicitglobalgrid_tpu.analysis.core import Context
    from implicitglobalgrid_tpu.analysis.tunecache import run

    assert "tune-cache-valid" in available_analyzers()
    assert run(Context()) == []


# -- SPMD consistency: the rank-keyed-lookup fixture --------------------------


def test_control_plan_ignores_rank_identity():
    from implicitglobalgrid_tpu.tuning.search import control_plan

    for hit, n in ((True, 0), (False, 4)):
        plans = {control_plan(is_root=r, hit=hit, n_measured=n)
                 for r in (True, False)}
        assert len(plans) == 1  # rank identity must not shape the schedule
    assert control_plan(True, False, 2) == (
        ("broadcast_control", "cache-decision"),
        ("measure_candidate", 0), ("measure_candidate", 1),
        ("broadcast_control", "winner"),
    )


def test_analyzer_catches_a_rank_keyed_cache_lookup():
    """The POSITIVE fixture of the ISSUE-13 deadlock class: a tuner whose
    ranks each trust their own disk.  Rank 1's local hit skips the
    measurement collectives rank 0 enters — the exact
    `_gather_chunked`-style divergence the collective-consistency detector
    must pin as CRITICAL."""
    from implicitglobalgrid_tpu.analysis.collectives import (
        check_rank_consistency,
        tuning_plan_censuses,
    )
    from implicitglobalgrid_tpu.analysis.core import Context
    from implicitglobalgrid_tpu.analysis.ir import RankCensus
    from implicitglobalgrid_tpu.tuning.search import control_plan

    divergent = RankCensus(
        name="host/tune_resolve[rank-keyed-lookup]",
        sequences={
            0: control_plan(is_root=True, hit=False, n_measured=3),
            1: control_plan(is_root=False, hit=True, n_measured=0),
        },
    )
    findings = check_rank_consistency(divergent)
    assert len(findings) == 1
    f = findings[0]
    assert f.code == "rank-divergent-sequence" and f.severity == "CRITICAL"
    assert "hangs the fabric" in f.message

    # and the REAL resolve's censuses (registered providers) are clean
    for census in tuning_plan_censuses(Context()):
        assert check_rank_consistency(census) == []


# -- the perf-gate wiring -----------------------------------------------------


def test_tuned_speedup_is_gated_and_catches_a_doctored_record():
    from implicitglobalgrid_tpu.analysis import perf

    assert "tuned_speedup" in perf.GATED_KEYS
    ref = {"value": 100.0, "extras": {"tuned_vs_default": {
        "diffusion": {"tuned_speedup": 1.5, "t_default_ms": 3.0},
        "porous": {"tuned_speedup": 2.5},
    }}}
    got = perf.gate_metrics(ref)
    assert got["tuned_vs_default.diffusion.tuned_speedup"] == 1.5
    assert "tuned_vs_default.diffusion.t_default_ms" not in got  # wall time
    # a doctored slower-tuned candidate drops the ratio past the band
    doctored = json.loads(json.dumps(ref))
    doctored["extras"]["tuned_vs_default"]["diffusion"]["tuned_speedup"] = 1.0
    cmp = perf.compare_metrics(perf.gate_metrics(doctored),
                               perf.gate_metrics(ref), waivers=[])
    assert [r["metric"] for r in cmp["regressions"]] == [
        "tuned_vs_default.diffusion.tuned_speedup"]
    # within-band drift passes
    ok = json.loads(json.dumps(ref))
    ok["extras"]["tuned_vs_default"]["diffusion"]["tuned_speedup"] = 1.4
    assert perf.compare_metrics(perf.gate_metrics(ok),
                                perf.gate_metrics(ref),
                                waivers=[])["regressions"] == []


# -- the CLI ------------------------------------------------------------------


@pytest.fixture(scope="module")
def igg_tune_cli():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "igg_tune", os.path.join(_repo, "scripts", "igg_tune.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_cli_sweep_dry_run_prints_the_pruned_table(igg_tune_cli, tmp_path,
                                                   monkeypatch, capsys):
    monkeypatch.setenv("IGG_TUNE_CACHE", str(tmp_path))
    rc = igg_tune_cli.main([
        "sweep", "--model", "diffusion3d", "--n", "16", "--nsteps", "4",
        "--overlap", "4", "--dry-run", "--json", "--cache", str(tmp_path),
    ])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["dry_run"] is True and out["winner"] is None
    statuses = {r["status"] for r in out["rows"]}
    assert "survivor" in statuses  # the pruned candidate table, no timing
    assert out["rows"][0]["config"] == {}
    assert os.listdir(str(tmp_path)) == []  # dry run persists NOTHING
    assert not igg.grid_is_initialized()  # the sweep cleans up its grid


def test_cli_sweep_measures_and_persists(igg_tune_cli, tmp_path, monkeypatch,
                                         capsys):
    monkeypatch.setenv("IGG_TUNE_STEPS", "1")
    rc = igg_tune_cli.main([
        "sweep", "--model", "diffusion3d", "--n", "8", "--nsteps", "2",
        "--overlap", "4", "--topk", "2", "--json", "--cache", str(tmp_path),
    ])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["winner"] is not None
    assert any(r.get("t_chunk_s") for r in out["rows"]
               if r["status"] == "measured")
    files = os.listdir(str(tmp_path))
    assert len(files) == 1 and files[0].startswith("diffusion3d_8x8x8")
    # show lists it; clear removes exactly it
    assert igg_tune_cli.main(["show", "--cache", str(tmp_path)]) == 0
    assert "search" in capsys.readouterr().out
    assert igg_tune_cli.main(["clear", "--cache", str(tmp_path)]) == 0
    assert os.listdir(str(tmp_path)) == []


def test_cli_seed_dry_run_matches_committed(igg_tune_cli, tmp_path, capsys):
    rc = igg_tune_cli.main(["seed", "--dry-run", "--json",
                            "--cache", str(tmp_path)])
    entries = json.loads(capsys.readouterr().out)
    assert rc == 0 and len(entries) >= 4
    assert os.listdir(str(tmp_path)) == []  # dry run writes nothing
    assert all(e["source"].startswith("seed:bench_r") for e in entries)
