"""Model tests: 3-D heat diffusion (reference examples/diffusion3D_*.jl).

Correctness oracle: a multi-block run on the 8-device mesh must reproduce a
single-device run of the same *global* problem exactly (the implicit global
grid is an implementation detail — physics can't see the decomposition).
"""

import itertools

import jax
import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import diffusion3d


def dedup_global(gathered, dims, n, o):
    """Assemble the de-duplicated global array from side-by-side blocks.

    Block c's local cell i sits at global index c*(n-o)+i; overlapping cells
    are written repeatedly (they must agree after update_halo).
    """
    nd = len(n)
    out_shape = tuple(dims[d] * (n[d] - o[d]) + o[d] for d in range(nd))
    out = np.zeros(out_shape, gathered.dtype)
    for c in itertools.product(*(range(d) for d in dims)):
        src = tuple(slice(c[d] * n[d], (c[d] + 1) * n[d]) for d in range(nd))
        dst = tuple(
            slice(c[d] * (n[d] - o[d]), c[d] * (n[d] - o[d]) + n[d]) for d in range(nd)
        )
        out[dst] = gathered[src]
    return out


def run_multi(nt, nx, hide_comm=False):
    state, params = diffusion3d.setup(nx, nx, nx, hide_comm=hide_comm)
    gg = igg.get_global_grid()
    dims, o = gg.dims, gg.overlaps
    step = diffusion3d.make_step(params)
    for _ in range(nt):
        state = jax.block_until_ready(step(*state))
    T = np.asarray(igg.gather(diffusion3d.temperature(state)))
    igg.finalize_global_grid()
    return dedup_global(T, dims, (nx,) * 3, o)


def run_single(nt, nxg):
    state, params = diffusion3d.setup(
        nxg, nxg, nxg, devices=[jax.devices()[0]]
    )
    step = diffusion3d.make_step(params)
    for _ in range(nt):
        state = jax.block_until_ready(step(*state))
    T = np.asarray(igg.gather(diffusion3d.temperature(state)))
    igg.finalize_global_grid()
    return T


def test_multi_block_matches_single_device():
    nx = 10  # 2x2x2 blocks of 10^3, global deduped 18^3
    nt = 20
    T_multi = run_multi(nt, nx)
    assert T_multi.shape == (18, 18, 18)
    T_single = run_single(nt, 18)
    np.testing.assert_allclose(T_multi, T_single, rtol=1e-12, atol=1e-12)


def test_hide_comm_matches_plain():
    nx = 10
    nt = 10
    T_plain = run_multi(nt, nx)
    T_hide = run_multi(nt, nx, hide_comm=True)
    np.testing.assert_allclose(T_hide, T_plain, rtol=1e-12, atol=1e-12)


def test_run_end_to_end():
    T = diffusion3d.run(5, 8, 8, 8)
    assert not igg.grid_is_initialized()  # finalized
    assert np.isfinite(np.asarray(jax.device_get(T))).all()


def test_initial_conditions_decomposition_invariant():
    # ICs are computed from global coordinates: independent of the block layout.
    (T8, Cp8), _ = diffusion3d.setup(10, 10, 10)
    gg = igg.get_global_grid()
    dims, o = gg.dims, gg.overlaps
    T8 = dedup_global(np.asarray(igg.gather(T8)), dims, (10,) * 3, o)
    Cp8 = dedup_global(np.asarray(igg.gather(Cp8)), dims, (10,) * 3, o)
    igg.finalize_global_grid()

    (T1, Cp1), _ = diffusion3d.setup(18, 18, 18, devices=[jax.devices()[0]])
    T1 = np.asarray(igg.gather(T1))
    Cp1 = np.asarray(igg.gather(Cp1))
    igg.finalize_global_grid()

    np.testing.assert_allclose(T8, T1, rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(Cp8, Cp1, rtol=1e-12, atol=1e-12)


def test_anomaly_diffuses():
    # The peak must decay and heat must spread (sanity physics check).
    state, params = diffusion3d.setup(10, 10, 10)
    T0 = np.asarray(igg.gather(diffusion3d.temperature(state)))
    step = diffusion3d.make_step(params)
    for _ in range(50):
        state = jax.block_until_ready(step(*state))
    T1 = np.asarray(igg.gather(diffusion3d.temperature(state)))
    igg.finalize_global_grid()
    assert T1.max() < T0.max()
    assert T1.min() >= -1e-9


def test_multi_step_matches_single_steps():
    nx = 10
    state, params = diffusion3d.setup(nx, nx, nx)
    step = diffusion3d.make_step(params, donate=False)
    multi = diffusion3d.make_multi_step(params, 6, donate=False)
    s1 = state
    for _ in range(6):
        s1 = jax.block_until_ready(step(*s1))
    s6 = jax.block_until_ready(multi(*state))
    np.testing.assert_allclose(
        np.asarray(s1[0]), np.asarray(s6[0]), rtol=1e-12, atol=1e-13
    )
    igg.finalize_global_grid()


def test_fused_deep_halo_matches_xla_multiblock():
    """Temporal blocking on a communicating grid: k fused kernel steps + one
    width-k slab exchange must match the per-step XLA path on the same mesh
    (interpret-mode kernel; deep halo overlapx=4 licenses fused_k=2).

    2 devices deliberately: >2 concurrent interpret-mode Pallas kernels
    under shard_map deadlock inside the interpreter (no collective
    rendezvous involved — probed at 4 and 8 virtual devices; the compiled
    kernel + slab path is validated on hardware and the slab exchange alone
    on 8 devices in test_update_halo)."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    nt = 4
    kw = dict(
        devices=jax.devices()[:2], dimx=2, dimy=1, dimz=1, overlapx=4, quiet=True,
        dtype=jax.numpy.float32,  # pinned: f64 is outside the kernel envelope
    )
    state, params = diffusion3d.setup(16, 32, 128, **kw)
    step = diffusion3d.make_multi_step(params, nt, donate=False)
    state = jax.block_until_ready(step(*state))
    T_xla = np.asarray(igg.gather(state[0]))
    igg.finalize_global_grid()

    state, params = diffusion3d.setup(16, 32, 128, **kw)
    with pallas_force_interpret():
        stepf = diffusion3d.make_multi_step(params, nt, donate=False, fused_k=2)
        state = jax.block_until_ready(stepf(*state))
    T_fused = np.asarray(igg.gather(state[0]))
    igg.finalize_global_grid()
    np.testing.assert_allclose(T_fused, T_xla, rtol=1e-5, atol=1e-5)


def test_fused_fallback_warns_and_matches_xla():
    """A local block the kernel envelope rejects (y-size not a multiple of 8)
    must warn once and run the XLA path at the same exchange cadence —
    bit-identical to the per-step path at group boundaries."""
    # dtype pinned so the fallback fires for the documented y%8 shape
    # rejection, not the x64-itemsize check (the suite runs x64).
    kw = dict(overlapx=4, overlapy=4, overlapz=4, quiet=True,
              dtype=jax.numpy.float32)
    state, params = diffusion3d.setup(10, 10, 10, **kw)
    step = diffusion3d.make_multi_step(params, 4, donate=False)
    T_ref = np.asarray(igg.gather(jax.block_until_ready(step(*state))[0]))
    igg.finalize_global_grid()

    state, params = diffusion3d.setup(10, 10, 10, **kw)
    with pytest.warns(RuntimeWarning, match="falling back to the XLA path"):
        stepf = diffusion3d.make_multi_step(params, 4, donate=False, fused_k=2)
        state = jax.block_until_ready(stepf(*state))
    T_fb = np.asarray(igg.gather(state[0]))
    igg.finalize_global_grid()
    np.testing.assert_array_equal(T_fb, T_ref)


def test_fused_complex_falls_back_and_matches():
    """complex64 (itemsize 8) is outside the Mosaic envelope: fused_k must
    warn once and run the XLA cadence, bit-identical to the per-step path
    (the reference's dtype matrix includes complex; here the kernel lever
    simply declines them instead of miscompiling)."""
    kw = dict(overlapx=4, overlapy=4, overlapz=4, quiet=True,
              dtype=jax.numpy.complex64)
    state, params = diffusion3d.setup(16, 32, 128, **kw)
    step = diffusion3d.make_multi_step(params, 4, donate=False)
    T_ref = np.asarray(igg.gather(jax.block_until_ready(step(*state))[0]))
    igg.finalize_global_grid()

    state, params = diffusion3d.setup(16, 32, 128, **kw)
    with pytest.warns(RuntimeWarning, match="f64/complex"):
        stepf = diffusion3d.make_multi_step(params, 4, donate=False, fused_k=2)
        state = jax.block_until_ready(stepf(*state))
    T_fb = np.asarray(igg.gather(state[0]))
    igg.finalize_global_grid()
    np.testing.assert_array_equal(T_fb, T_ref)


def test_fused_requires_deep_halo():
    state, params = diffusion3d.setup(
        16, 32, 128, devices=jax.devices()[:2], dimx=2, dimy=1, dimz=1, quiet=True
    )
    with pytest.raises(ValueError, match="deep halo"):
        diffusion3d.make_multi_step(params, 4, fused_k=2)
    igg.finalize_global_grid()


def test_exchange_cadence_matches_per_step():
    """Deep-halo cadence on the XLA path: w steps + one width-w slab exchange
    must be bit-identical to per-step exchange at group boundaries."""
    kw = dict(overlapx=4, overlapy=4, overlapz=4, quiet=True)
    state, params = diffusion3d.setup(10, 10, 10, **kw)
    step = diffusion3d.make_multi_step(params, 4, donate=False)
    T_ref = np.asarray(igg.gather(jax.block_until_ready(step(*state))[0]))
    igg.finalize_global_grid()

    state, params = diffusion3d.setup(10, 10, 10, **kw)
    step2 = diffusion3d.make_multi_step(params, 4, donate=False, exchange_every=2)
    T_cad = np.asarray(igg.gather(jax.block_until_ready(step2(*state))[0]))
    igg.finalize_global_grid()
    np.testing.assert_array_equal(T_cad, T_ref)


def test_exchange_cadence_validation():
    state, params = diffusion3d.setup(10, 10, 10, quiet=True)  # overlap 2
    with pytest.raises(ValueError, match="deep halo"):
        diffusion3d.make_multi_step(params, 4, exchange_every=2)
    with pytest.raises(ValueError, match="multiple of exchange_every"):
        diffusion3d.make_multi_step(params, 5, exchange_every=2)
    igg.finalize_global_grid()


@pytest.mark.parametrize("seed", range(4))
def test_random_topology_decomposition_invariance(seed):
    """End-to-end oracle across random topologies: a multi-block run must
    reproduce the single-device run of the same global problem exactly,
    whatever dims/overlap are drawn.  Non-periodic only — on periodic dims
    the implicit global size drops the +overlap term and the duplicated
    cells wrap, so the single-device problem is not the simple dedup; the
    halo-level sweeps in test_update_halo carry the periodic coverage."""
    rng = np.random.default_rng(7000 + seed)
    o = int(rng.integers(2, 5))
    nx = int(rng.integers(2 * o + 2, 2 * o + 6))
    overlaps = {f"overlap{ax}": o for ax in "xyz"}
    nt = int(rng.integers(3, 8))

    state, params = diffusion3d.setup(nx, nx, nx, quiet=True, **overlaps)
    gg = igg.get_global_grid()
    dims = gg.dims
    step = diffusion3d.make_step(params)
    for _ in range(nt):
        state = jax.block_until_ready(step(*state))
    T_multi = dedup_global(
        np.asarray(igg.gather(state[0])), dims, (nx,) * 3, (o,) * 3
    )
    igg.finalize_global_grid()

    nxg = tuple(dims[d] * (nx - o) + o for d in range(3))
    state, params = diffusion3d.setup(
        *nxg, devices=[jax.devices()[0]], quiet=True
    )
    step = diffusion3d.make_step(params)
    for _ in range(nt):
        state = jax.block_until_ready(step(*state))
    T_single = np.asarray(igg.gather(state[0]))
    igg.finalize_global_grid()
    np.testing.assert_allclose(T_multi, T_single, rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("seed", range(3))
def test_fused_zpatch_random_topology_invariance(seed):
    """Decomposition-invariance oracle for the fused z-patch cadence
    (VERDICT r3 #1): a z-split fused_k run must reproduce the single-device
    per-step run of the same global problem.  The decomposition is fixed at
    dims=(1,1,2) — interpret-mode Pallas under shard_map deadlocks with >2
    concurrent kernel instances (see __graft_entry__.dryrun_multichip) —
    and the random draws cover local shape, tile, and step count instead;
    dims_z=2 keeps the in-kernel z-slab machinery on the exercised path in
    every draw."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    rng = np.random.default_rng(7100 + seed)
    dims = (1, 1, 2)
    k = 2
    o = 2 * k
    nt = int(rng.integers(1, 3)) * k
    n0 = int(rng.choice([16, 24, 32]))
    n1 = int(rng.choice([32, 64]))
    nloc = (n0, n1, 128)
    # (16,32) tiles need bx|n0 with the haloed window inside the block
    # (n0 >= 20) and by|n1 with SY=48 <= n1 — only the (32,64) draw.
    # A by=n1 draw exercises the TRANSPOSED full-y patch layout (round 5);
    # the others pin the packed 128-lane layout.
    big_ok = n0 == 32 and n1 == 64
    choice = int(rng.integers(3))
    if choice == 0:
        tile = (8, n1)  # full-y -> transposed layout
    elif big_ok and choice == 1:
        tile = (16, 32)
    else:
        tile = (8, 16)

    from implicitglobalgrid_tpu.ops.pallas_stencil import (
        fused_support_error,
        zpatch_transposed,
    )

    # The oracle is only meaningful if the z-patch kernel path is actually
    # selected (f32: the envelope rejects f64) — guard against a silent
    # fall-back to the XLA cadence.
    assert fused_support_error(nloc, k, 4, *tile, zpatch=True) is None
    assert zpatch_transposed(nloc, k, 4, *tile) == (tile[1] == n1)

    kw = dict(
        devices=jax.devices()[: dims[0] * dims[1] * dims[2]],
        dimx=dims[0], dimy=dims[1], dimz=dims[2],
        overlapx=o, overlapy=o, overlapz=o, quiet=True,
        dtype=jax.numpy.float32,
    )
    state, params = diffusion3d.setup(*nloc, **kw)
    with pallas_force_interpret():
        step = diffusion3d.make_multi_step(
            params, nt, donate=False, fused_k=k, fused_tile=tile
        )
        state = jax.block_until_ready(step(*state))
    T_multi = dedup_global(
        np.asarray(igg.gather(state[0])), dims, nloc, (o,) * 3
    )
    igg.finalize_global_grid()

    nxg = tuple(dims[d] * (nloc[d] - o) + o for d in range(3))
    state, params = diffusion3d.setup(
        *nxg, devices=[jax.devices()[0]], quiet=True, dtype=jax.numpy.float32
    )
    step = diffusion3d.make_step(params)
    for _ in range(nt):
        state = jax.block_until_ready(step(*state))
    T_single = np.asarray(igg.gather(state[0]))
    igg.finalize_global_grid()
    np.testing.assert_allclose(T_multi, T_single, rtol=2e-5, atol=2e-5)


def test_fused_zpatch_deep_halo_z_split_matches_xla():
    """The in-kernel z-slab diffusion cadence (z-dim decomposition) vs the
    per-step path (interpret-mode kernel, 2 devices split along z)."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    nt = 4
    kw = dict(
        devices=jax.devices()[:2], dimx=1, dimy=1, dimz=2, overlapz=4, quiet=True,
        dtype=jax.numpy.float32,
    )
    state, params = diffusion3d.setup(16, 32, 128, **kw)
    step = diffusion3d.make_multi_step(params, nt, donate=False)
    T_ref = np.asarray(igg.gather(jax.block_until_ready(step(*state))[0]))
    igg.finalize_global_grid()

    state, params = diffusion3d.setup(16, 32, 128, **kw)
    with pallas_force_interpret():
        stepf = diffusion3d.make_multi_step(params, nt, donate=False, fused_k=2)
        T_got = np.asarray(igg.gather(jax.block_until_ready(stepf(*state))[0]))
    igg.finalize_global_grid()
    np.testing.assert_allclose(T_got, T_ref, rtol=1e-5, atol=1e-5)


def test_fused_zpatch_periodic_z_multiblock_matches_xla():
    """Periodic z with dims_z=2: the packed exports communicate via the
    wrap ppermute (neither the self-neighbor fast path nor the PROC_NULL
    masking — the third topology of `z_patch_from_export`)."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    nt = 4
    kw = dict(
        devices=jax.devices()[:2], dimx=1, dimy=1, dimz=2, periodz=1,
        overlapz=4, quiet=True, dtype=jax.numpy.float32,
    )
    state, params = diffusion3d.setup(16, 32, 128, **kw)
    step = diffusion3d.make_multi_step(params, nt, donate=False)
    T_ref = np.asarray(igg.gather(jax.block_until_ready(step(*state))[0]))
    igg.finalize_global_grid()

    state, params = diffusion3d.setup(16, 32, 128, **kw)
    with pallas_force_interpret():
        stepf = diffusion3d.make_multi_step(params, nt, donate=False, fused_k=2)
        T_got = np.asarray(igg.gather(jax.block_until_ready(stepf(*state))[0]))
    igg.finalize_global_grid()
    np.testing.assert_allclose(T_got, T_ref, rtol=1e-5, atol=1e-5)


def test_fused_zpatch_periodic_z_bfloat16():
    """The z-patch/export cadence at bf16 (itemsize 2): packing, patch
    application, and export must be dtype-clean — compared against the XLA
    bf16 path at bf16 accuracy.  nt=4 = two fused groups, so the second
    group applies a REAL export-derived patch in-kernel (one group would
    only ever apply the trivial identity patch)."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    nt = 4
    kw = dict(
        devices=jax.devices()[:1], periodz=1, overlapz=4, quiet=True,
        dtype=jax.numpy.bfloat16,
    )
    state, params = diffusion3d.setup(16, 32, 128, **kw)
    step = diffusion3d.make_multi_step(params, nt, donate=False)
    T_ref = np.asarray(
        jax.block_until_ready(step(*state))[0].astype(jax.numpy.float32)
    )
    igg.finalize_global_grid()

    state, params = diffusion3d.setup(16, 32, 128, **kw)
    with pallas_force_interpret():
        stepf = diffusion3d.make_multi_step(params, nt, donate=False, fused_k=2)
        T_got = np.asarray(
            jax.block_until_ready(stepf(*state))[0].astype(jax.numpy.float32)
        )
    igg.finalize_global_grid()
    # bf16 has ~3 decimal digits; values are O(100).
    np.testing.assert_allclose(T_got, T_ref, rtol=0.05, atol=0.5)


def test_fused_zpatch_periodic_z_matches_xla():
    """Same cadence on the periodic self-neighbor z config (1 device)."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    nt = 4
    kw = dict(
        devices=jax.devices()[:1], periodz=1, overlapz=4, quiet=True,
        dtype=jax.numpy.float32,
    )
    state, params = diffusion3d.setup(16, 32, 128, **kw)
    step = diffusion3d.make_multi_step(params, nt, donate=False)
    T_ref = np.asarray(jax.block_until_ready(step(*state))[0])
    igg.finalize_global_grid()

    state, params = diffusion3d.setup(16, 32, 128, **kw)
    with pallas_force_interpret():
        stepf = diffusion3d.make_multi_step(params, nt, donate=False, fused_k=2)
        T_got = np.asarray(jax.block_until_ready(stepf(*state))[0])
    igg.finalize_global_grid()
    np.testing.assert_allclose(T_got, T_ref, rtol=1e-5, atol=1e-5)
