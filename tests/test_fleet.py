"""The fleet tier (ISSUE 16; docs/serving.md, docs/robustness.md).

Covers the four fleet pieces at the unit level, with fakes at every I/O
seam (spawn / transport / scrape hooks — no sockets, no subprocesses):

* `fleet.policy` — the pure pool-incident -> fleet-action verdict
  (respawn strikes -> quarantine, hot -> spill, idle spilled -> retire)
  and `fleet_plan`'s rank/fence uniformity;
* `fleet.router` — deterministic health-keyed routing, submit failover,
  sticky results, and the epoch zombie guard: a superseded pool's late
  answer is refused with ``fleet.zombie_result``;
* `fleet.canary` — the baking -> promoted / rolled_back state machine
  and the fence-gated `publish_canary_state` (a superseded controller's
  canary-verdict write is refused, ``fence.rejected``);
* `fleet.controller` — launch/discovery, the ordered death recovery
  (``fleet.detect`` -> ``fleet.reroute`` -> ``fleet.recovered`` with the
  generation fence moving FIRST), strike exhaustion -> device-subset
  quarantine, and the canary gate driving promote/rollback end to end.

Plus the acceptance fence contract one level down: a superseded POOL
incarnation's front-door endpoint-file write is refused.  The real
multi-process legs (chaos-killed pool, bit-identical digests vs an
oracle) are the soak ``fleet`` drill (``scripts/soak.py fleet --quick``).
"""

import itertools
import json
import os
import time

import pytest

from implicitglobalgrid_tpu import fleet
from implicitglobalgrid_tpu.fleet import canary as can_mod
from implicitglobalgrid_tpu.fleet import controller as ctl_mod
from implicitglobalgrid_tpu.fleet import policy as pol_mod
from implicitglobalgrid_tpu.fleet import router as rtr_mod
from implicitglobalgrid_tpu.supervisor import generation as gen_mod
from implicitglobalgrid_tpu.supervisor.classify import Incident
from implicitglobalgrid_tpu.utils import telemetry as tele


@pytest.fixture
def clean_env(monkeypatch):
    for k in list(os.environ):
        if k.startswith("IGG_"):
            monkeypatch.delenv(k)
    tele.reset()
    yield monkeypatch
    tele.reset()


def _events(path):
    return tele.read_events(path)


def _incident(kind, pool="a", **detail):
    return Incident(kind=kind, ranks=(), rcs=(),
                    detail={"pool": pool, **detail})


def _health(queue=0, members=1, cap=2, p99=0.01, ok=True, alerts=()):
    return {
        "ok": ok,
        "serving": {"queue_depth": queue, "active_members": members,
                    "capacity": cap},
        "slo": {"slo.serving.round_seconds": {"p99": p99, "count": 5}},
        "alerts": {"active": [
            {"rule": r, "severity": "critical"} for r in alerts
        ]},
    }


# -- policy: the pure verdict -------------------------------------------------


def test_fleet_policy_env_tier_and_validation(clean_env):
    assert pol_mod.FleetPolicy() == pol_mod.FleetPolicy.from_env()
    clean_env.setenv("IGG_FLEET_RESPAWN_LIMIT", "5")
    clean_env.setenv("IGG_FLEET_SPILL_QUEUE", "9")
    clean_env.setenv("IGG_FLEET_CANARY_P99_S", "0.75")
    pol = pol_mod.FleetPolicy.from_env(canary_streak=4)
    assert pol.respawn_limit == 5 and pol.spill_queue == 9
    assert pol.canary_p99_s == 0.75 and pol.canary_streak == 4
    assert pol.idle_retire is None
    for bad in (
        {"respawn_limit": -1}, {"spill_queue": 0}, {"idle_retire": 0},
        {"canary_streak": 0}, {"canary_p99_s": 0.0},
    ):
        with pytest.raises(ValueError):
            pol_mod.FleetPolicy(**bad)


def test_decide_pool_respawns_then_quarantines_the_device_subset():
    policy = pol_mod.FleetPolicy(respawn_limit=2)
    state = pol_mod.FleetState()
    for used in (1, 2):
        d = fleet.decide_pool(
            _incident("died", devices="devA"), state, policy
        )
        assert d.action == "respawn" and d.pool == "a"
        assert f"{used}/2" in d.reason
        state.apply(d)
    d = fleet.decide_pool(_incident("wedged", devices="devA"), state, policy)
    assert d.action == "quarantine" and d.quarantined == ("devA",)
    state.apply(d)
    assert "devA" in state.quarantined_devices
    # a healthy stretch resets the strike streak
    state.apply(pol_mod.FleetDecision(action="none", pool="a", reason="ok"))
    assert fleet.decide_pool(
        _incident("died"), state, policy
    ).action == "respawn"
    # the verdict is pure: no pool name -> explicit error, not a guess
    with pytest.raises(ValueError, match="pool"):
        fleet.decide_pool(
            Incident(kind="died", ranks=(), rcs=(), detail={}),
            state, policy,
        )


def test_decide_pool_hot_spills_and_idle_spilled_pool_retires():
    state = pol_mod.FleetState()
    spill = pol_mod.FleetPolicy(spill_queue=4, idle_retire=2)
    assert fleet.decide_pool(
        _incident("hot", queue_depth=6), state, spill
    ).action == "spill"
    # spill off -> hot is tolerated
    assert fleet.decide_pool(
        _incident("hot"), state, pol_mod.FleetPolicy()
    ).action == "none"
    # idle retires only SPILLED pools, only past the streak bar
    for _ in range(2):
        state.record_health("a", queue_depth=0, active_members=0)
    assert fleet.decide_pool(
        _incident("idle"), state, spill, spilled=False
    ).action == "none"
    assert fleet.decide_pool(
        _incident("idle"), state, spill, spilled=True
    ).action == "retire"
    # one busy observation resets the idle streak
    state.record_health("a", queue_depth=1, active_members=1)
    assert fleet.decide_pool(
        _incident("idle"), state, spill, spilled=True
    ).action == "none"


def test_fleet_plan_rank_and_fence_uniform():
    for action in pol_mod.FLEET_ACTIONS:
        assert fleet.fleet_plan(True, action, False) == fleet.fleet_plan(
            False, action, False
        )
        # a fenced incarnation refuses the directive on EVERY rank together
        assert fleet.fleet_plan(True, action, True) == ()
    assert fleet.fleet_plan(True, "respawn", False) == (
        ("broadcast_control", "adopt-replay"),
    )
    assert fleet.fleet_plan(False, "quarantine", False) == ()


# -- router: fakes at the transport/scrape seam -------------------------------


class _FakeDoor:
    """One pool front door behind the router's transport hook."""

    def __init__(self):
        self.next_rid = 0
        self.submits = []
        self.results = {}
        self.dead = False


def _fake_fleet(healths):
    """(router, doors): a serve=False router whose transport and scrape
    run against in-process fakes."""
    doors = {}

    def transport(endpoint, method, path, doc):
        door = doors.setdefault(endpoint, _FakeDoor())
        if door.dead:
            return 0, {}
        if method == "POST" and path == "/v1/submit":
            rid = f"r{door.next_rid:06d}"
            door.next_rid += 1
            door.submits.append((rid, dict(doc)))
            return 202, {"request_id": rid}
        if method == "GET" and path.startswith("/v1/result/"):
            rid = path.rsplit("/", 1)[1]
            if rid in door.results:
                return 200, {"status": "done", **door.results[rid]}
            return 200, {"request_id": rid, "status": "pending"}
        if method == "POST" and path == "/v1/shutdown":
            return 200, {}
        return 404, {}

    router = rtr_mod.FleetRouter(
        serve=False, transport=transport,
        scrape=lambda ep: healths.get(ep),
    )
    return router, doors


def test_choose_pool_is_deterministic_least_loaded_and_key_matched():
    def cand(name, *, q=0, m=0, p99=0.0, quarantined=False, key=None,
             unreachable=False):
        return {
            "name": name, "key": key or {}, "quarantined": quarantined,
            "health": rtr_mod.pool_health_view(
                None if unreachable else _health(queue=q, members=m, p99=p99)
            ),
        }

    doc = {"model": "diffusion3d", "tenant": "t"}
    cands = [
        cand("c", q=1), cand("b"), cand("a"),
        cand("quar", quarantined=True), cand("dark", unreachable=True),
        cand("other", key={"model": "acoustic3d"}),
    ]
    # least loaded first; name breaks ties; ineligible never chosen
    assert rtr_mod.choose_pool(doc, cands) == "a"
    assert rtr_mod.choose_pool(doc, cands) == "a"  # deterministic
    assert rtr_mod.choose_pool(
        doc, [cand("b", q=2, m=2), cand("c", q=2, m=1)]
    ) == "c"
    assert rtr_mod.choose_pool(doc, [cand("x", key={"model": "acoustic3d"})]) \
        is None
    # size is part of the routing contract when both sides state one
    sized = [cand("s", key={"model": "diffusion3d", "size": [8, 8, 8]})]
    assert rtr_mod.choose_pool(dict(doc, size=[8, 8, 8]), sized) == "s"
    assert rtr_mod.choose_pool(dict(doc, size=[16, 8, 8]), sized) is None


def test_router_submit_sticky_result_and_failover(clean_env, tmp_path):
    clean_env.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    healths = {"a:1": _health(queue=0), "b:2": _health(queue=3)}
    router, doors = _fake_fleet(healths)
    router.register_pool("a", "a:1", key={"model": "diffusion3d"})
    router.register_pool("b", "b:2", key={"model": "diffusion3d"})
    doc = {"tenant": "t", "model": "diffusion3d",
           "params": {"max_steps": 2}}
    code, body = router.submit(doc)
    assert code == 202 and body == {"request_id": "f000000", "pool": "a"}
    # sticky: the fetch proxies to the owning pool's own rid
    code, view = router.result("f000000")
    assert code == 200 and view["status"] == "pending"
    doors["a:1"].results["r000000"] = {"result": "completed", "steps": 2}
    code, view = router.result("f000000")
    assert view["status"] == "done" and view["pool"] == "a"
    # ...and the done answer is cached (the pool can die after)
    doors["a:1"].dead = True
    code, view = router.result("f000000")
    assert code == 200 and view["result"] == "completed"
    assert router.result("f999999")[0] == 404
    # failover: a dark pool costs one attempt, never a failed request
    code, body = router.submit(doc)
    assert code == 202 and body["pool"] == "b"
    events = _events(tmp_path / "events.jsonl")
    assert [e["pool"] for e in events if e["type"] == "fleet.route"] == \
        ["a", "b"]
    assert any(e["type"] == "fleet.pool_unreachable" and e["pool"] == "a"
               for e in events)
    counters = tele.snapshot()["counters"]
    assert counters["fleet.routed_total"] == 2
    # nobody left -> structured 503, counted
    doors["b:2"].dead = True
    code, body = router.submit(doc)
    assert code == 503 and "tried" in body
    assert tele.snapshot()["counters"]["fleet.unroutable_total"] == 1


def test_router_evacuate_rejects_zombie_pool_late_result(clean_env, tmp_path):
    """Satellite: a chaos-killed pool's process that outlives its SIGKILL
    and answers one last time must NOT land its result in the router."""
    clean_env.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    healths = {"a:1": _health(), "b:2": _health(queue=1)}
    router, doors = _fake_fleet(healths)
    router.register_pool("a", "a:1")
    router.register_pool("b", "b:2")
    code, body = router.submit({"tenant": "t", "params": {"max_steps": 2}})
    fid = body["request_id"]
    assert router.routes[fid]["pool"] == "a"
    moved = router.evacuate("a")
    assert moved == [fid]
    route = router.routes[fid]
    assert route["pool"] == "b" and route["epoch"] == 1
    # the re-submitted spec reached b verbatim (parameters, never arrays)
    assert doors["b:2"].submits[-1][1]["params"] == {"max_steps": 2}
    # the zombie's adoption quotes the OLD (pool, epoch): refused
    assert not router.adopt_result(fid, "a", 0, {"result": "completed"})
    assert router.routes[fid]["done"] is None
    # the CURRENT owner at the current epoch is adopted fine
    assert router.adopt_result(fid, "b", 1, {"result": "completed"})
    events = _events(tmp_path / "events.jsonl")
    reroutes = [e for e in events if e["type"] == "fleet.reroute"]
    assert reroutes and reroutes[0]["requests"] == [fid]
    zombies = [e for e in events if e["type"] == "fleet.zombie_result"]
    assert zombies and zombies[0]["pool"] == "a"
    assert zombies[0]["owner"] == "b" and zombies[0]["owner_epoch"] == 1
    counters = tele.snapshot()["counters"]
    assert counters["fleet.zombie_results_total"] == 1
    assert counters["fleet.rerouted_total"] == 1


# -- canary: the SLO-gated state machine --------------------------------------


def test_canary_promotes_after_healthy_streak(clean_env, tmp_path):
    clean_env.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    tr = can_mod.CanaryTracker(
        pool="c", candidate={"overlay": "v2"},
        policy=pol_mod.FleetPolicy(canary_streak=3, canary_p99_s=1.0),
    )
    assert tr.observe(_health(p99=0.2)) == "baking"
    assert tr.observe(_health(p99=0.2)) == "baking"
    assert tr.observe(_health(p99=0.2)) == "promoted"
    assert tr.observe(None) == "promoted"  # terminal states are sticky
    types = [e["type"] for e in _events(tmp_path / "events.jsonl")]
    assert types[0] == "fleet.canary.start"
    assert types.count("fleet.canary.observe") == 3
    assert types[-1] == "fleet.canary.promote"
    assert tele.snapshot()["counters"]["fleet.canary.promotions_total"] == 1


@pytest.mark.parametrize("health,kind", [
    (None, "unreachable"),
    (_health(p99=2.0), "slo"),
    (_health(ok=False, alerts=("step_stall",)), "alert"),
])
def test_canary_rolls_back_on_any_breach(clean_env, tmp_path, health, kind):
    clean_env.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    tr = can_mod.CanaryTracker(
        pool="c", candidate={"overlay": "v2"},
        policy=pol_mod.FleetPolicy(canary_streak=2, canary_p99_s=1.0),
    )
    assert tr.observe(_health(p99=0.2)) == "baking"
    assert tr.observe(health) == "rolled_back"
    assert tr.breach["kind"] == kind
    assert tr.observe(_health(p99=0.2)) == "rolled_back"  # sticky
    roll = [e for e in _events(tmp_path / "events.jsonl")
            if e["type"] == "fleet.canary.rollback"]
    assert roll and roll[0]["kind"] == kind and roll[0]["observations"] == 2
    assert tele.snapshot()["counters"]["fleet.canary.rollbacks_total"] == 1


def test_superseded_controller_canary_write_refused(clean_env, tmp_path):
    """Satellite: the zombie-controller half of the fence contract — a
    superseded incarnation must not flip a canary verdict on disk."""
    telem, fence, work = (
        tmp_path / "telem", tmp_path / "fence", tmp_path / "work"
    )
    work.mkdir()
    clean_env.setenv("IGG_TELEMETRY_DIR", str(telem))
    assert can_mod.publish_canary_state(str(work), {"state": "baking"})
    gen_mod.publish_generation(2, str(fence))
    clean_env.setenv("IGG_FENCE_DIR", str(fence))
    clean_env.setenv("IGG_GENERATION", "1")
    assert not can_mod.publish_canary_state(
        str(work), {"state": "rolled_back"}
    )
    # the live verdict is untouched
    doc = json.loads((work / can_mod.CANARY_STATE).read_text())
    assert doc == {"state": "baking"}
    rej = [e for e in _events(telem / "events.jsonl")
           if e["type"] == "fence.rejected"]
    assert rej and rej[0]["what"] == "fleet.canary"
    assert tele.snapshot()["counters"]["fence.rejected_total"] == 1
    # the current incarnation writes fine
    clean_env.setenv("IGG_GENERATION", "2")
    assert can_mod.publish_canary_state(str(work), {"state": "promoted"})


def test_superseded_pool_endpoint_file_refused(clean_env, tmp_path):
    """Satellite: the zombie-POOL half — a superseded pool incarnation's
    front door must not steal the discovery file the fleet controller's
    replacement pool publishes (`fence.rejected`, no file)."""
    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.serving import FrontDoor, ServingLoop
    from implicitglobalgrid_tpu.serving import frontdoor as fdm
    from implicitglobalgrid_tpu.utils import liveplane as lp

    telem, fence = tmp_path / "telem", tmp_path / "fence"
    clean_env.setenv("IGG_TELEMETRY_DIR", str(telem))
    gen_mod.publish_generation(2, str(fence))
    clean_env.setenv("IGG_FENCE_DIR", str(fence))
    clean_env.setenv("IGG_GENERATION", "1")
    igg.init_global_grid(8, 8, 8, quiet=True)
    _, params = diffusion3d.setup(8, 8, 8, init_grid=False)
    loop = ServingLoop(diffusion3d, params, capacity=1, steps_per_round=1)
    fd = FrontDoor(loop, port=0)
    try:
        assert not (telem / fdm.endpoint_filename(0)).exists()
        rej = [e for e in _events(telem / "events.jsonl")
               if e["type"] == "fence.rejected"]
        assert rej and rej[-1]["what"] == "frontdoor.endpoint"
    finally:
        fd.close()
        lp.reset()


# -- controller: fakes at the spawn seam --------------------------------------


class _FakeProc:
    def __init__(self):
        self.rc = None

    def poll(self):
        return self.rc

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        return self.rc


def _fleet_fixture(tmp_path, *, pools=("a", "b"), policy=None, healths=None):
    """A controller over fake processes: spawn writes the endpoint file a
    real pool's front door would, transport/scrape run in-process."""
    healths = healths if healths is not None else {}
    procs = {}
    ports = itertools.count(40001)

    def spawn(argv, env, log_path):
        tdir = env["IGG_TELEMETRY_DIR"]
        os.makedirs(tdir, exist_ok=True)
        port = next(ports)
        with open(os.path.join(tdir, "frontdoor.p0.json"), "w") as f:
            json.dump({"rank": 0, "pid": 1, "host": "127.0.0.1",
                       "port": port, "ts": time.time() + 5.0}, f)
        proc = _FakeProc()
        procs[env["IGG_TELEMETRY_DIR"]] = proc
        procs[f"127.0.0.1:{port}"] = proc
        return proc

    def scrape(endpoint):
        if procs.get(endpoint) is not None and procs[endpoint].rc is not None:
            return None
        return healths.get(endpoint, _health())

    router, doors = _fake_fleet({})
    router.scrape = scrape
    specs = [
        ctl_mod.PoolSpec(
            name=name,
            command_for=lambda spec, gen: ["pool", spec.name, str(gen)],
            workdir=str(tmp_path / name),
            telemetry_dir=str(tmp_path / name / "telemetry"),
            key={"model": "diffusion3d"},
            devices=f"dev-{name}",
        )
        for name in pools
    ]
    fc = ctl_mod.FleetController(
        specs, router=router,
        policy=policy or pol_mod.FleetPolicy(respawn_limit=2),
        poll_s=0.01, spawn=spawn, scrape=scrape,
    )
    return fc, router, doors, procs


def test_controller_launch_discovers_and_registers(clean_env, tmp_path):
    clean_env.setenv("IGG_TELEMETRY_DIR", str(tmp_path / "fleet-telem"))
    fc, router, _doors, _procs = _fleet_fixture(tmp_path)
    fc.launch(wait_s=5.0)
    assert sorted(router.pools) == ["a", "b"]
    assert fc.handles["a"].endpoint == "127.0.0.1:40001"
    # each pool is its own failure domain: its OWN fence dir and token
    for name in ("a", "b"):
        assert gen_mod.authoritative_generation(str(tmp_path / name)) == 0
    events = _events(tmp_path / "fleet-telem" / "events.jsonl")
    assert [e["type"] for e in events].count("fleet.pool_up") == 2


def test_controller_death_recovery_order_and_fence(clean_env, tmp_path):
    """The drill's event contract at the unit level: detect -> reroute ->
    recovered, with the authoritative generation bumped BEFORE the
    replacement spawns and the in-flight route re-homed with zero loss."""
    telem = tmp_path / "fleet-telem"
    clean_env.setenv("IGG_TELEMETRY_DIR", str(telem))
    fc, router, doors, procs = _fleet_fixture(tmp_path)
    fc.launch(wait_s=5.0)
    code, body = router.submit({"tenant": "t", "params": {"max_steps": 2}})
    assert code == 202
    fid, victim = body["request_id"], body["pool"]
    procs[fc.handles[victim].endpoint].rc = 9  # chaos kill
    decisions = fc.poll_once()
    assert [d.action for d in decisions] == ["respawn"]
    # the route survived onto the OTHER pool at a bumped epoch
    route = router.routes[fid]
    assert route["pool"] != victim and route["epoch"] == 1
    # fence moved first: the dead incarnation (gen 0) is now superseded
    assert gen_mod.authoritative_generation(str(tmp_path / victim)) == 1
    assert fc.handles[victim].generation == 1
    types = [e["type"] for e in _events(telem / "events.jsonl")]
    assert types.index("fleet.detect") < types.index("fleet.reroute") \
        < types.index("fleet.recovered")
    # healthy again -> the strike streak resets on the next sweep
    assert fc.poll_once() == []
    assert fc.state.respawns[victim] == 0


def test_controller_strike_exhaustion_quarantines_devices(
    clean_env, tmp_path
):
    telem = tmp_path / "fleet-telem"
    clean_env.setenv("IGG_TELEMETRY_DIR", str(telem))
    fc, router, _doors, procs = _fleet_fixture(
        tmp_path, policy=pol_mod.FleetPolicy(respawn_limit=0)
    )
    fc.launch(wait_s=5.0)
    procs[fc.handles["a"].endpoint].rc = 7
    decisions = fc.poll_once()
    assert [d.action for d in decisions] == ["quarantine"]
    assert fc.state.quarantined_devices == {"dev-a"}
    assert router.pools["a"]["quarantined"]
    # a quarantined pool never routes again
    code, body = router.submit({"tenant": "t", "params": {"max_steps": 1}})
    assert code == 202 and body["pool"] == "b"
    types = [e["type"] for e in _events(telem / "events.jsonl")]
    assert "fleet.quarantine" in types and "fleet.recovered" not in types


def test_controller_canary_promote_spreads_the_overlay(clean_env, tmp_path):
    telem = tmp_path / "fleet-telem"
    clean_env.setenv("IGG_TELEMETRY_DIR", str(telem))
    fc, _router, _doors, _procs = _fleet_fixture(
        tmp_path, pools=("a",),
        policy=pol_mod.FleetPolicy(canary_streak=2, canary_p99_s=1.0),
    )
    fc.launch(wait_s=5.0)
    spec = ctl_mod.PoolSpec(
        name="canary",
        command_for=lambda s, g: ["pool", s.name, str(g)],
        workdir=str(tmp_path / "canary"),
        telemetry_dir=str(tmp_path / "canary" / "telemetry"),
        env={"IGG_TUNE_CACHE": str(tmp_path / "overlay")},
    )
    fc.start_canary(spec, {"overlay": "v2"})
    with pytest.raises(RuntimeError, match="already baking"):
        fc.start_canary(spec, {"overlay": "v3"})
    assert fc.poll_once() == [] and fc.canary.state == "baking"
    assert fc.poll_once() == [] and fc.canary.state == "promoted"
    # the candidate is fleet-safe: the seed pool inherits the overlay for
    # its next (re)launch
    assert fc.specs["a"].env["IGG_TUNE_CACHE"] == str(tmp_path / "overlay")
    doc = json.loads((tmp_path / "canary" / can_mod.CANARY_STATE).read_text())
    assert doc["state"] == "promoted" and doc["streak"] == 2


def test_controller_canary_breach_rolls_back_through_strikes(
    clean_env, tmp_path
):
    telem = tmp_path / "fleet-telem"
    clean_env.setenv("IGG_TELEMETRY_DIR", str(telem))
    healths = {}
    fc, router, _doors, _procs = _fleet_fixture(
        tmp_path, pools=("a",), healths=healths,
        policy=pol_mod.FleetPolicy(canary_streak=3, canary_p99_s=0.5),
    )
    fc.launch(wait_s=5.0)
    spec = ctl_mod.PoolSpec(
        name="canary",
        command_for=lambda s, g: ["pool", s.name, str(g)],
        workdir=str(tmp_path / "canary"),
        telemetry_dir=str(tmp_path / "canary" / "telemetry"),
        env={"IGG_TUNE_CACHE": "doctored"},
    )
    fc.start_canary(spec, {"overlay": "doctored"})
    fc.poll_once()  # healthy observation: still baking
    # the doctored config shows up as a round-p99 SLO breach
    healths[fc.handles["canary"].endpoint] = _health(p99=2.0)
    fc.poll_once()
    assert fc.canary.state == "rolled_back"
    assert fc.canary.breach["kind"] == "slo"
    # the rollback IS the strike machinery: quarantined, never respawned
    assert router.pools["canary"]["quarantined"]
    assert "IGG_TUNE_CACHE" not in fc.specs["a"].env
    doc = json.loads((tmp_path / "canary" / can_mod.CANARY_STATE).read_text())
    assert doc["state"] == "rolled_back" and doc["breach"]["kind"] == "slo"
    types = [e["type"] for e in _events(telem / "events.jsonl")]
    assert "fleet.canary.rollback" in types and "fleet.quarantine" in types
    assert types.index("fleet.canary.start") \
        < types.index("fleet.canary.observe") \
        < types.index("fleet.canary.rollback")
    assert "fleet.canary.promote" not in types


def test_controller_spill_and_retire_lifecycle(clean_env, tmp_path):
    telem = tmp_path / "fleet-telem"
    clean_env.setenv("IGG_TELEMETRY_DIR", str(telem))
    healths = {}
    fc, router, _doors, _procs = _fleet_fixture(
        tmp_path, pools=("a",), healths=healths,
        policy=pol_mod.FleetPolicy(spill_queue=4, idle_retire=2),
    )
    fc.launch(wait_s=5.0)
    healths[fc.handles["a"].endpoint] = _health(queue=6, members=2)
    decisions = fc.poll_once()
    assert [d.action for d in decisions] == ["spill"]
    spill = next(iter(fc.spilled))
    assert spill.startswith("a-spill") and spill in fc.handles
    # the seed pool cools down; the spill pool sits idle past the bar
    healths[fc.handles["a"].endpoint] = _health(queue=1, members=1)
    healths[fc.discover_endpoint(spill)] = _health(queue=0, members=0)
    assert fc.poll_once() == []  # idle streak 1
    decisions = fc.poll_once()   # idle streak 2 -> retire
    assert [d.action for d in decisions] == ["retire"]
    assert spill not in router.pools
    # the seed pool NEVER retires, however idle
    healths[fc.handles["a"].endpoint] = _health(queue=0, members=0)
    for _ in range(4):
        assert fc.poll_once() == []
    types = [e["type"] for e in _events(telem / "events.jsonl")]
    assert "fleet.spill" in types and "fleet.retire" in types
