"""Test harness: force an 8-device virtual CPU mesh (SURVEY.md §4).

The reference runs each test file in a fresh process because MPI can only be
initialized once (`/root/reference/test/runtests.jl:24`); here the grid is
re-initializable, so ordinary pytest works.  Multi-device coverage without
hardware comes from 8 virtual CPU devices — the TPU translation of the
reference's single-process self-neighbor trick plus real multi-rank runs.
"""

import os

import pytest

# The axon sitecustomize may already have imported jax and registered the TPU
# plugin, so env vars are too late — use jax.config, which works post-import.
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_enable_x64", True)  # reference tests are Float64-heavy


@pytest.fixture(autouse=True)
def _finalize_grid_after_test():
    yield
    import implicitglobalgrid_tpu as igg

    if igg.grid_is_initialized():
        igg.finalize_global_grid()
