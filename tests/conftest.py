"""Test harness: force an 8-device virtual CPU mesh (SURVEY.md §4).

The reference runs each test file in a fresh process because MPI can only be
initialized once (`/root/reference/test/runtests.jl:24`); here the grid is
re-initializable, so ordinary pytest works.  Multi-device coverage without
hardware comes from 8 virtual CPU devices — the TPU translation of the
reference's single-process self-neighbor trick plus real multi-rank runs.
"""

import os

# XLA_FLAGS must be staged before the CPU backend initializes (first device
# use), which is later than import — so setting it here covers both import
# orders, including a sitecustomize that already imported jax.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import pytest

# The axon sitecustomize may already have imported jax and registered the TPU
# plugin, so env vars alone are too late for platform/x64 choices — use
# jax.config, which works post-import.
import jax

jax.config.update("jax_platforms", "cpu")
try:
    # The config option only exists on newer JAX; older ones take the
    # XLA_FLAGS staged above (read at backend init, after this module runs).
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass
jax.config.update("jax_enable_x64", True)  # reference tests are Float64-heavy


@pytest.fixture(autouse=True)
def _finalize_grid_after_test():
    yield
    import implicitglobalgrid_tpu as igg

    if igg.grid_is_initialized():
        igg.finalize_global_grid()


@pytest.fixture
def fault_injection(monkeypatch):
    """Arm ``IGG_FAULT_INJECT`` for one test and hand back the injector.

    Usage::

        def test_x(fault_injection):
            inj = fault_injection("halo_corrupt:step3:block5")
            ...

    Also wires the injector into `ops.halo`'s post-exchange hook point so
    direct `update_halo` calls see the fault.  Everything is torn down after
    the test (env var, injector cache, halo hook).
    """
    from implicitglobalgrid_tpu.ops import halo as _halo
    from implicitglobalgrid_tpu.utils import resilience

    def arm(spec: str):
        monkeypatch.setenv("IGG_FAULT_INJECT", spec)
        resilience.reset_fault_injector()
        resilience.install_halo_fault_hook()
        return resilience.get_fault_injector()

    yield arm
    _halo.set_post_exchange_hook(None)
    resilience.reset_fault_injector()
